#include "postoffice.h"

#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstring>

#include "logging.h"
#include "metrics.h"

namespace bps {

int64_t NowMs() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

static double EnvSeconds(const char* name, double dflt) {
  const char* v = getenv(name);
  return v && *v ? atof(v) : dflt;
}

static long EnvLong(const char* name, long dflt) {
  const char* v = getenv(name);
  return v && *v ? atol(v) : dflt;
}

// Transient-fault tolerance master switch: BYTEPS_RETRY_MAX > 0 (default
// on). 0 restores the pre-retry fail-fast behavior everywhere — any lost
// connection immediately fails that peer's in-flight requests.
bool RetryEnabled() {
  static const bool on = EnvLong("BYTEPS_RETRY_MAX", 4) > 0;
  return on;
}

int Postoffice::Start(Role role, const std::string& root_uri, int root_port,
                      int num_workers, int num_servers,
                      AppHandler app_handler) {
  role_ = role;
  num_workers_ = num_workers;
  num_servers_ = num_servers;
  app_handler_ = std::move(app_handler);
  van_ = std::make_unique<Van>(
      [this](Message&& m, int fd) { ControlHandler(std::move(m), fd); });
  van_->SetDisconnectHandler([this](int fd) {
    if (shutting_down_.load()) return;
    int node_id = -1;
    int stripe = -1;
    {
      std::lock_guard<std::mutex> lk(mu_);
      for (const auto& kv : node_fd_) {
        if (kv.second == fd) { node_id = kv.first; stripe = 0; break; }
      }
      if (node_id < 0) {
        // A lost STRIPE maps back to its peer too (one process owns
        // every stripe of a connection pair).
        for (const auto& kv : node_extra_fds_) {
          for (size_t s = 0; s < kv.second.size(); ++s) {
            if (kv.second[s] == fd) {
              node_id = kv.first;
              stripe = static_cast<int>(s) + 1;
              break;
            }
          }
          if (node_id >= 0) break;
        }
      }
    }
    if (node_id < 0) return;
    // Transient-vs-persistent fork (SURVEY.md §5, ISSUE 3): a worker's
    // lost server connection is first treated as TRANSIENT — re-dial
    // with capped backoff and let the KV retry layer drain its resend
    // queue over the fresh connection. Only when the re-dial exhausts
    // its attempts (peer process actually gone) does it escalate to
    // the pre-existing fail-fast path. Scheduler connections are never
    // reconnected: heartbeat state lives there, and losing it already
    // has its own failure-shutdown handling (HeartbeatLoop).
    if (role_ == ROLE_WORKER && node_id != kSchedulerId &&
        RetryEnabled() && TryReconnect(node_id, stripe)) {
      BPS_METRIC_COUNTER_ADD("bps_reconnects_total", 1);
      if (peer_reconnected_cb_) peer_reconnected_cb_(node_id);
      return;
    }
    if (peer_lost_cb_) peer_lost_cb_(node_id);
  });

  // Fleet-formation bound: until the topology completes no job can be
  // running, and the dead-node monitor has an empty heartbeat table
  // (nothing registered -> it can never fire). An indefinite wait here
  // would therefore leak the whole fleet — scheduler + servers + the
  // bound port — forever if one worker crashes before registering.
  // Fail loudly instead; post-formation lifetime is unbounded (the
  // heartbeat monitor is the failure exit from then on).
  // PS_TOPOLOGY_TIMEOUT <= 0 disables the bound (the file's <=0
  // convention, as with PS_HEARTBEAT_INTERVAL).
  double form_s = EnvSeconds("PS_TOPOLOGY_TIMEOUT", 600.0);
  auto wait_formed = [&](std::unique_lock<std::mutex>& lk,
                         const char* what) {
    if (form_s <= 0) {
      cv_.wait(lk, [this] { return addrbook_ready_; });
      return;
    }
    BPS_CHECK(cv_.wait_for(
        lk,
        std::chrono::milliseconds(static_cast<long>(form_s * 1000)),
        [this] { return addrbook_ready_; }))
        << what << " within PS_TOPOLOGY_TIMEOUT=" << form_s
        << "s (a node crashed before registering?)";
  };
  if (role == ROLE_SCHEDULER) {
    my_id_ = kSchedulerId;
    van_->Listen(root_port);
    // Wait for everyone to register; ControlHandler completes the handshake.
    std::unique_lock<std::mutex> lk(mu_);
    wait_formed(lk, "topology did not complete");
  } else {
    // Deployment port mapping (the DMLC_NODE_HOST analogue for ports):
    // BYTEPS_LISTEN_PORT pins the local bind (containers with published
    // ports), BYTEPS_ADVERTISED_PORT is what peers are told to dial
    // (NAT / port-forward / proxy in front of this node). Defaults:
    // ephemeral bind, advertise what we bound.
    int want_port = 0;
    if (const char* lp = getenv("BYTEPS_LISTEN_PORT")) want_port = atoi(lp);
    int listen_port = van_->Listen(want_port);
    int fd = van_->Connect(root_uri, root_port);
    BPS_CHECK_GE(fd, 0) << "cannot reach scheduler at " << root_uri << ":"
                        << root_port;
    {
      std::lock_guard<std::mutex> lk(mu_);
      node_fd_[kSchedulerId] = fd;
    }
    NodeInfo me{};
    me.id = -1;
    me.role = role;
    const char* host_env = getenv("DMLC_NODE_HOST");
    snprintf(me.host, sizeof(me.host), "%s",
             host_env && *host_env ? host_env : "127.0.0.1");
    me.port = listen_port;
    if (const char* ap = getenv("BYTEPS_ADVERTISED_PORT")) {
      me.port = atoi(ap);
    }
    MsgHeader h{};
    h.cmd = CMD_REGISTER;
    h.sender = -1;
    const char* wid = getenv("DMLC_WORKER_ID");
    h.arg0 = wid && *wid ? atol(wid) : -1;  // preferred rank (deterministic)
    h.arg1 = role;
    van_->Send(fd, h, &me, sizeof(me));
    // Wait for the address book (same formation bound as the scheduler).
    std::unique_lock<std::mutex> lk(mu_);
    wait_formed(lk, "no address book");
    lk.unlock();
    if (role == ROLE_WORKER) {
      // Dial every server; identify ourselves on each connection.
      // BYTEPS_VAN_STREAMS > 1 opens extra striped connections per server
      // (the RDMA-van role: one TCP stream's cwnd/ack clocking caps
      // per-peer goodput; partition-keyed striping multiplies it while
      // keeping each key's ordering on one stream).
      int streams = 1;
      if (const char* sv = getenv("BYTEPS_VAN_STREAMS")) {
        streams = atoi(sv);
        if (streams < 1) streams = 1;
      }
      for (const auto& n : nodes_) {
        if (n.role != ROLE_SERVER) continue;
        for (int s = 0; s < streams; ++s) {
          int sfd = van_->Connect(n.host, n.port);
          BPS_CHECK_GE(sfd, 0) << "cannot reach server " << n.id;
          MsgHeader hello{};
          hello.cmd = CMD_REGISTER;
          hello.sender = my_id_;
          hello.arg1 = ROLE_WORKER;
          van_->Send(sfd, hello);
          std::lock_guard<std::mutex> lk2(mu_);
          if (s == 0) {
            node_fd_[n.id] = sfd;
          } else {
            node_extra_fds_[n.id].push_back(sfd);
          }
        }
      }
    }
  }

  double interval = EnvSeconds("PS_HEARTBEAT_INTERVAL", 5.0);
  if (role != ROLE_SCHEDULER && interval > 0) {
    heartbeat_thread_ = std::thread([this] { HeartbeatLoop(); });
  }
  if (role == ROLE_SCHEDULER && interval > 0) {
    // Failure detection (reference: ps-lite heartbeat timeout, SURVEY.md
    // §5): a node missing heartbeats past PS_HEARTBEAT_TIMEOUT takes the
    // fleet down fail-stop — the cluster manager owns the restart.
    monitor_thread_ = std::thread([this, interval] {
      while (!shutting_down_.load()) {
        for (int i = 0; i < static_cast<int>(interval * 10) &&
                        !shutting_down_.load();
             ++i) {
          usleep(100 * 1000);
        }
        if (shutting_down_.load()) return;
        auto dead = DeadNodes();
        if (!dead.empty()) {
          std::string ids;
          for (int id : dead) ids += std::to_string(id) + " ";
          BPS_LOG(WARNING) << "scheduler: node(s) " << ids
                           << "missed heartbeats — broadcasting shutdown";
          MsgHeader h{};
          h.cmd = CMD_SHUTDOWN;
          h.sender = kSchedulerId;
          h.arg0 = 1;  // failure-triggered
          std::lock_guard<std::mutex> lk(mu_);
          for (const auto& n : nodes_) {
            if (n.id == kSchedulerId) continue;
            auto it = node_fd_.find(n.id);
            if (it != node_fd_.end()) van_->Send(it->second, h);
          }
          shutting_down_.store(true);
          cv_.notify_all();
          return;
        }
      }
    });
  }
  BPS_LOG(INFO) << "node started: role=" << role << " id=" << my_id_;
  return my_id_;
}

void Postoffice::ControlHandler(Message&& msg, int fd) {
  switch (msg.head.cmd) {
    case CMD_REGISTER: {
      if (role_ == ROLE_SCHEDULER) {
        std::unique_lock<std::mutex> lk(mu_);
        BPS_CHECK_EQ(msg.payload.size(), sizeof(NodeInfo));
        PendingReg pr;
        pr.fd = fd;
        memcpy(&pr.info, msg.payload.data(), sizeof(NodeInfo));
        pr.info.id = static_cast<int32_t>(msg.head.arg0);  // preferred rank
        pending_regs_.push_back(pr);
        if (static_cast<int>(pending_regs_.size()) ==
            num_workers_ + num_servers_) {
          // Assign ids: deterministic by (role, preferred rank, arrival).
          std::stable_sort(pending_regs_.begin(), pending_regs_.end(),
                           [](const PendingReg& a, const PendingReg& b) {
                             if (a.info.role != b.info.role)
                               return a.info.role < b.info.role;
                             return a.info.id < b.info.id;
                           });
          nodes_.clear();
          NodeInfo sched{};
          sched.id = kSchedulerId;
          sched.role = ROLE_SCHEDULER;
          nodes_.push_back(sched);
          int next_server = 0, next_worker = 0;
          for (auto& pr2 : pending_regs_) {
            int id = pr2.info.role == ROLE_SERVER
                         ? ServerId(next_server++)
                         : WorkerId(next_worker++);
            pr2.info.id = id;
            nodes_.push_back(pr2.info);
            node_fd_[id] = pr2.fd;
            last_heartbeat_ms_[id] = NowMs();
          }
          for (auto& pr2 : pending_regs_) {
            MsgHeader h{};
            h.cmd = CMD_ADDRBOOK;
            h.sender = kSchedulerId;
            h.arg0 = pr2.info.id;  // your assigned id
            van_->Send(pr2.fd, h, nodes_.data(),
                       nodes_.size() * sizeof(NodeInfo));
          }
          addrbook_ready_ = true;
          cv_.notify_all();
          BPS_LOG(INFO) << "scheduler: topology complete (" << num_workers_
                        << " workers, " << num_servers_ << " servers)";
        }
      } else {
        // Server side: a worker identifying itself on a fresh connection.
        // With BYTEPS_VAN_STREAMS > 1 the same worker registers each
        // stripe; only the FIRST (primary) fd is recorded so a later
        // stripe can't overwrite it. Invariant: server RESPONSES always
        // go out on the fd the request arrived on (kv.h keeps per-fd
        // reply routing), so node_fd_ here is only a fallback for any
        // future server-initiated send keyed by node id — which must use
        // the primary connection.
        std::lock_guard<std::mutex> lk(mu_);
        node_fd_.emplace(msg.head.sender, fd);  // no-op if already known
      }
      break;
    }
    case CMD_ADDRBOOK: {
      std::lock_guard<std::mutex> lk(mu_);
      my_id_ = static_cast<int>(msg.head.arg0);
      size_t n = msg.payload.size() / sizeof(NodeInfo);
      nodes_.resize(n);
      memcpy(nodes_.data(), msg.payload.data(), n * sizeof(NodeInfo));
      addrbook_ready_ = true;
      cv_.notify_all();
      break;
    }
    case CMD_BARRIER: {
      BPS_CHECK_EQ(role_, ROLE_SCHEDULER);
      int group = static_cast<int>(msg.head.arg0);
      std::lock_guard<std::mutex> lk(mu_);
      int need = ((group & GROUP_SERVERS) ? num_servers_ : 0) +
                 ((group & GROUP_WORKERS) ? num_workers_ : 0);
      if (++barrier_counts_[group] == need) {
        barrier_counts_[group] = 0;
        MsgHeader h{};
        h.cmd = CMD_BARRIER_ACK;
        h.sender = kSchedulerId;
        h.arg0 = group;
        for (const auto& n : nodes_) {
          bool in_group =
              (n.role == ROLE_SERVER && (group & GROUP_SERVERS)) ||
              (n.role == ROLE_WORKER && (group & GROUP_WORKERS));
          if (in_group) van_->Send(node_fd_[n.id], h);
        }
      }
      break;
    }
    case CMD_BARRIER_ACK: {
      std::lock_guard<std::mutex> lk(mu_);
      barrier_done_[static_cast<int>(msg.head.arg0)]++;
      cv_.notify_all();
      break;
    }
    case CMD_HEARTBEAT: {
      std::lock_guard<std::mutex> lk(mu_);
      // A cleanly-departed worker keeps heartbeating while it waits for
      // the fleet shutdown; re-inserting it would later read as a death.
      if (!departed_.count(msg.head.sender)) {
        last_heartbeat_ms_[msg.head.sender] = NowMs();
      }
      break;
    }
    case CMD_SHUTDOWN: {
      if (role_ == ROLE_SCHEDULER) {
        // A worker says goodbye; when all workers are done, stop the fleet.
        std::lock_guard<std::mutex> lk(mu_);
        // A cleanly-departing node is not a failure: stop tracking it.
        last_heartbeat_ms_.erase(msg.head.sender);
        departed_.insert(msg.head.sender);
        BPS_LOG(DEBUG) << "scheduler: goodbye from node " << msg.head.sender
                       << " (" << barrier_counts_[-1] + 1 << "/"
                       << num_workers_ << ")";
        if (++barrier_counts_[-1] == num_workers_) {
          MsgHeader h{};
          h.cmd = CMD_SHUTDOWN;
          h.sender = kSchedulerId;
          for (const auto& n : nodes_) {
            if (n.id != kSchedulerId) {
              bool ok = van_->Send(node_fd_[n.id], h);
              BPS_LOG(DEBUG) << "scheduler: SHUTDOWN -> node " << n.id
                             << (ok ? " ok" : " FAILED");
            }
          }
          shutting_down_.store(true);
          cv_.notify_all();
        }
      } else {
        BPS_LOG(DEBUG) << "node " << my_id_ << ": received fleet SHUTDOWN";
        // arg0 == 1 marks a FAILURE shutdown (dead-node broadcast from
        // the scheduler's heartbeat monitor) vs the clean teardown;
        // server entry points exit nonzero on it.
        if (msg.head.arg0 == 1) failure_shutdown_.store(true);
        shutting_down_.store(true);
        {
          std::lock_guard<std::mutex> lk(mu_);
          cv_.notify_all();
        }
        if (shutdown_cb_) shutdown_cb_();
      }
      break;
    }
    default:
      if (app_handler_) app_handler_(std::move(msg), fd);
  }
}

void Postoffice::Barrier(int group) {
  int target;
  {
    std::lock_guard<std::mutex> lk(mu_);
    target = barrier_done_[group] + 1;
  }
  MsgHeader h{};
  h.cmd = CMD_BARRIER;
  h.sender = my_id_;
  h.arg0 = group;
  van_->Send(FdOf(kSchedulerId), h);
  std::unique_lock<std::mutex> lk(mu_);
  cv_.wait(lk, [this, group, target] {
    return barrier_done_[group] >= target || shutting_down_.load();
  });
}

int Postoffice::FdOf(int node_id) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = node_fd_.find(node_id);
  BPS_CHECK(it != node_fd_.end()) << "no connection to node " << node_id;
  return it->second;
}

int Postoffice::FdOf(int node_id, int64_t key) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = node_fd_.find(node_id);
  BPS_CHECK(it != node_fd_.end()) << "no connection to node " << node_id;
  auto ex = node_extra_fds_.find(node_id);
  if (ex == node_extra_fds_.end() || ex->second.empty()) return it->second;
  size_t streams = ex->second.size() + 1;
  // Mix the key bits before reducing: keys are (tensor_id<<16)|part, so
  // a bare key % streams maps EVERY single-partition tensor to stripe 0
  // (low 16 bits all zero) and striping silently never engages —
  // exposed by the delay-proxy BDP sweep, where N stripes measured the
  // same goodput as one. splitmix64 finalizer; still deterministic per
  // key, so per-key ordering stays on one connection.
  uint64_t h = static_cast<uint64_t>(key);
  h ^= h >> 33; h *= 0xff51afd7ed558ccdULL;
  h ^= h >> 33; h *= 0xc4ceb9fe1a85ec53ULL;
  h ^= h >> 33;
  size_t s = static_cast<size_t>(h % streams);
  return s == 0 ? it->second : ex->second[s - 1];
}

bool Postoffice::TryReconnect(int node_id, int stripe) {
  NodeInfo target{};
  bool found = false;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (const auto& n : nodes_) {
      if (n.id == node_id) { target = n; found = true; break; }
    }
  }
  if (!found) return false;
  const int max_attempts =
      static_cast<int>(EnvLong("BYTEPS_RECONNECT_MAX", 3));
  long backoff_ms = EnvLong("BYTEPS_RECONNECT_BACKOFF_MS", 100);
  if (backoff_ms < 1) backoff_ms = 1;
  for (int attempt = 0; attempt < max_attempts; ++attempt) {
    if (attempt > 0) {
      // Capped exponential backoff between re-dials: a restarting peer
      // gets breathing room, a dead one costs at most the full ladder.
      long wait = backoff_ms << std::min(attempt - 1, 6);
      if (wait > 2000) wait = 2000;
      for (long slept = 0; slept < wait && !shutting_down_.load();
           slept += 50) {
        usleep(50 * 1000);
      }
    }
    if (shutting_down_.load() || van_->stopped()) return false;
    int fd = van_->Connect(target.host, target.port, 1);
    if (fd < 0) continue;
    // Re-identify on the fresh connection, exactly like the original
    // stripe dial: the server records/keeps the worker's primary fd and
    // answers requests on whichever fd they arrive on.
    MsgHeader hello{};
    hello.cmd = CMD_REGISTER;
    hello.sender = my_id_;
    hello.arg1 = role_;
    if (!van_->Send(fd, hello)) continue;
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (stripe == 0) {
        node_fd_[node_id] = fd;
      } else {
        auto& extra = node_extra_fds_[node_id];
        if (static_cast<size_t>(stripe - 1) < extra.size()) {
          extra[static_cast<size_t>(stripe - 1)] = fd;
        }
      }
    }
    BPS_LOG(WARNING) << "node " << my_id_ << ": reconnected to node "
                     << node_id << " (stripe " << stripe << ", attempt "
                     << attempt + 1 << ") — resuming in-flight requests";
    return true;
  }
  BPS_LOG(WARNING) << "node " << my_id_ << ": reconnect to node "
                   << node_id << " failed after " << max_attempts
                   << " attempt(s) — treating peer as dead";
  return false;
}

void Postoffice::HeartbeatLoop() {
  double interval = EnvSeconds("PS_HEARTBEAT_INTERVAL", 5.0);
  while (!shutting_down_.load() && !van_->stopped()) {
    MsgHeader h{};
    h.cmd = CMD_HEARTBEAT;
    h.sender = my_id_;
    int fd = -1;
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = node_fd_.find(kSchedulerId);
      if (it == node_fd_.end()) break;
      fd = it->second;
    }
    if (!van_->Send(fd, h)) {
      // The scheduler connection is gone. For a server this is the ONLY
      // exit signal once Finalize's indefinite wait has begun (the
      // SHUTDOWN broadcast can never arrive over a dead connection), and
      // for a worker it means the fleet is over: treat it as a
      // failure-triggered shutdown rather than spinning silently.
      if (!shutting_down_.load()) {
        BPS_LOG(WARNING) << "node " << my_id_
                         << ": scheduler connection lost — failure shutdown";
        failure_shutdown_.store(true);
        shutting_down_.store(true);
        {
          std::lock_guard<std::mutex> lk(mu_);
          cv_.notify_all();
        }
        if (shutdown_cb_) shutdown_cb_();
      }
      break;
    }
    for (int i = 0; i < static_cast<int>(interval * 10) &&
                    !shutting_down_.load();
         ++i) {
      usleep(100 * 1000);
    }
  }
}

std::vector<int> Postoffice::DeadNodes() {
  double timeout_ms = EnvSeconds("PS_HEARTBEAT_TIMEOUT", 30.0) * 1000.0;
  std::vector<int> dead;
  std::lock_guard<std::mutex> lk(mu_);
  int64_t now = NowMs();
  for (const auto& kv : last_heartbeat_ms_) {
    if (now - kv.second > timeout_ms) dead.push_back(kv.first);
  }
  std::sort(dead.begin(), dead.end());
  return dead;
}

std::vector<std::pair<int, int64_t>> Postoffice::HeartbeatAges() {
  std::vector<std::pair<int, int64_t>> ages;
  std::lock_guard<std::mutex> lk(mu_);
  int64_t now = NowMs();
  for (const auto& kv : last_heartbeat_ms_) {
    ages.emplace_back(kv.first, now - kv.second);
  }
  std::sort(ages.begin(), ages.end());
  return ages;
}

void Postoffice::Finalize() {
  if (!van_) return;
  if (shutting_down_.load()) {
    van_->Stop();
  } else if (role_ == ROLE_WORKER) {
    // Say goodbye, then wait for the scheduler's fleet-wide SHUTDOWN
    // (long grace period: other workers may still be training).
    MsgHeader h{};
    h.cmd = CMD_SHUTDOWN;
    h.sender = my_id_;
    bool ok = van_->Send(FdOf(kSchedulerId), h);
    BPS_LOG(DEBUG) << "worker " << my_id_ << ": goodbye sent ("
                   << (ok ? "ok" : "FAILED") << "), awaiting fleet SHUTDOWN";
    // If the goodbye could not be delivered the scheduler is already gone
    // and no SHUTDOWN reply can ever arrive — don't stall process exit for
    // the full grace period (other workers may still be training only in
    // the delivered case).
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait_for(lk, std::chrono::seconds(ok ? 300 : 2),
                 [this] { return shutting_down_.load(); });
    lk.unlock();
    van_->Stop();
  } else {
    // Scheduler: wait for all workers' goodbyes (handled in
    // ControlHandler) — for as long as the job runs. This wait IS the
    // scheduler's serving life (`python -m byteps_tpu.server` calls
    // shutdown() right after startup); a bounded wait here silently
    // killed any fleet whose job outlived the bound. The failure monitor
    // is the other exit: dead nodes trigger the fail-stop broadcast.
    // Server: same indefinite wait for the SHUTDOWN broadcast; if the
    // scheduler dies instead, the heartbeat loop notices the dead
    // connection and flips shutting_down_ (failure shutdown).
    // With heartbeats DISABLED (PS_HEARTBEAT_INTERVAL <= 0) neither
    // failure exit exists, so keep the old bounded grace as the only
    // defence against orphaned fleet processes.
    std::unique_lock<std::mutex> lk(mu_);
    if (EnvSeconds("PS_HEARTBEAT_INTERVAL", 5.0) > 0) {
      // Finalize is only reachable after Start() returned, i.e. after
      // the formation bound in Start (PS_TOPOLOGY_TIMEOUT) passed and
      // the topology completed — so from here the heartbeat monitor has
      // nodes to watch and IS the failure exit; the serving wait itself
      // is rightly unbounded (it is the fleet's lifetime).
      cv_.wait(lk, [this] { return shutting_down_.load(); });
    } else {
      cv_.wait_for(lk, std::chrono::seconds(30),
                   [this] { return shutting_down_.load(); });
    }
    lk.unlock();
    van_->Stop();
  }
  if (heartbeat_thread_.joinable()) heartbeat_thread_.join();
  if (monitor_thread_.joinable()) monitor_thread_.join();
  BPS_LOG(DEBUG) << "node " << my_id_ << ": finalize complete";
}

}  // namespace bps
