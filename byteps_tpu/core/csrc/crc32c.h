// CRC32C (Castagnoli, the iSCSI/ext4 polynomial) — the repo's single
// integrity primitive, shared by the checkpoint spill/scan path
// (ckpt.cc), the wire-frame trailer (van.cc, BYTEPS_WIRE_CRC), and the
// snapshot serving reply verification. Hoisted out of ckpt.cc (ISSUE 19)
// so the table exists exactly once.
//
// Hardware-accelerated where the build allows it (the SSE4.2 crc32
// instruction IS reflected-Castagnoli), with a table-driven software
// fallback — both produce identical checksums (the probe's known-vector
// test pins them). The paced wire-overhead gate lives in
// BENCH_integrity_r19.json.
#pragma once

#include <cstddef>
#include <cstdint>

namespace bps {

// `seed` chains calls: Crc32c(b, nb, Crc32c(a, na)) == Crc32c(a||b) —
// the property the van uses to checksum a gather-send's discontiguous
// iovec segments without flattening them.
uint32_t Crc32c(const void* data, size_t len, uint32_t seed = 0);

}  // namespace bps
