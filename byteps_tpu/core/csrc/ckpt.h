// Durable checkpoints (ISSUE 18, docs/checkpoint.md).
//
// Every failure class recovered so far is recovered from other
// processes' RAM; a correlated failure (power loss, OOM sweep, whole-box
// reboot) still loses every round ever trained. This layer persists the
// one artifact worth keeping — the SnapStore's committed, consistent,
// all-keys cut — to BYTEPS_CKPT_DIR so a relaunched fleet can resume
// from the last durable round instead of round zero.
//
// Durability argument (the whole design, in one paragraph): every file
// is written to a dot-tmp name, fsync'd, then atomically renamed into
// place; the per-version MANIFEST — carrying the key list, tenant ids,
// fleet shape, round watermark, per-chunk CRC32C and a sealing CRC over
// its own bytes — is written LAST. A crash at ANY byte therefore leaves
// either (a) a complete prior checkpoint, or (b) a candidate whose
// manifest is absent, torn (seal CRC mismatch) or pointing at chunks
// whose CRC32C no longer matches — all of which CkptScan detects and
// skips. A torn cut can never be installed, only rejected by name.
//
// Standalone by design (no topology; the writer owns its one thread) so
// the FFI probe (bps_ckpt_probe) can unit-test the spill / scan / load /
// torn-rejection matrix without a fleet.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "crc32c.h"  // Crc32c — hoisted to the shared utility (ISSUE 19)
#include "snapshot.h"

namespace bps {

// One key's restored value (CkptLoad output).
struct CkptItem {
  uint16_t tenant = 0;
  int64_t key = 0;
  int64_t version = -1;  // the entry's own version (== the cut version
                         // in lockstep training; <= it for idle keys)
  int32_t dtype = 0;
  std::vector<char> data;
};

// --- synchronous core (shared by the writer thread and the probe) -----------

// Persist one complete cut as checkpoint `version` for server shard
// `rank` under `dir`. `chaos` ("" / "truncate" / "bitflip" /
// "sealflip") is the torn-write injection the rejection tests drive
// (BYTEPS_CHAOS_CKPT): truncate/bitflip corrupt a seeded-random chunk
// AFTER its CRC was recorded and BEFORE the manifest seals the
// checkpoint; sealflip corrupts the sealed MANIFEST itself (intact
// chunks, broken seal). Returns false with a diagnostic in *why.
bool CkptSpillSync(const std::string& dir, int rank, int64_t version,
                   const std::vector<SnapDeltaEnt>& cut, int num_workers,
                   int num_servers, const std::string& chaos,
                   std::string* why);

// Newest FULLY-valid checkpoint version for `rank` under `dir` — the
// manifest must parse, its seal CRC must match, and every chunk must
// exist with its recorded length and CRC32C. -1 when none survive;
// every skipped candidate appends a named line to *why.
int64_t CkptScan(const std::string& dir, int rank, std::string* why);

// All fully-valid versions for `rank`, ascending (probe/introspection).
std::vector<int64_t> CkptList(const std::string& dir, int rank);

// Load exactly `version` (full CRC re-validation — scan-then-load is
// TOCTOU-proof by re-checking). False + diagnostic when the version is
// missing or any byte fails validation; the caller must treat that as
// fail-stop, never a silent cold start. *round gets the manifest's
// round watermark (== version).
bool CkptLoad(const std::string& dir, int rank, int64_t version,
              std::vector<CkptItem>* items, int64_t* round,
              std::string* why);

// Bounded retention mirroring the snapshot ring: keep the newest
// `retain` checkpoint directories for `rank`, delete the rest (and any
// stale dot-tmp debris from crashed spills).
void CkptRetain(const std::string& dir, int rank, int retain);

// --- async writer (server engine integration) --------------------------------

// Owns the spill thread, OFF the engine critical path: RoundReady only
// claims a due version (ShouldSpill), collects the cut's shared_ptr
// entries (no payload copy), and enqueues; fsyncs happen here.
class CkptWriter {
 public:
  ~CkptWriter() { Stop(); }

  // Idempotent; the server starts the writer lazily at the first due
  // spill (the shard rank is only known post-formation).
  void Start(const std::string& dir, int rank, int every, int retain,
             const std::string& chaos, int num_workers, int num_servers);
  void Stop();
  bool running() const { return running_.load(); }

  // Atomically claim `version` for spilling: true once per due version
  // (version % every == 0, newer than any prior claim). Engine threads
  // race this at round boundaries; CAS keeps exactly one winner.
  bool ShouldSpill(int64_t version);

  void Enqueue(int64_t version, std::vector<SnapDeltaEnt>&& cut);

  // Observability (bps_ckpt_* metrics + probe).
  int64_t last_spilled() const { return last_spilled_.load(); }
  int64_t spills() const { return spills_.load(); }
  int64_t failures() const { return failures_.load(); }
  int64_t last_spill_ms() const { return last_spill_ms_.load(); }

 private:
  void Loop();

  std::string dir_;
  std::string chaos_;
  int rank_ = 0;
  int every_ = 1;
  int retain_ = 2;
  int num_workers_ = 0;
  int num_servers_ = 0;

  std::atomic<bool> running_{false};
  std::atomic<bool> stop_{false};
  std::atomic<int64_t> claimed_{-1};       // highest version claimed
  std::atomic<int64_t> last_spilled_{-1};  // highest version sealed
  std::atomic<int64_t> spills_{0};
  std::atomic<int64_t> failures_{0};
  std::atomic<int64_t> last_spill_ms_{0};

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<std::pair<int64_t, std::vector<SnapDeltaEnt>>> queue_;
  std::thread thread_;
};

}  // namespace bps
