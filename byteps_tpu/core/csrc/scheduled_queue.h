// Priority queue with credit-based admission.
//
// Capability parity: reference byteps/common/scheduled_queue.{h,cc}
// (BytePSScheduledQueue): partitions are admitted to the DCN push stage
// highest-priority-first (priority = negative declaration order, so
// front-of-model gradients go first — the next forward pass needs them
// first), with a credit cap on in-flight BYTES
// (BYTEPS_SCHEDULING_CREDIT, the reference's in-flight byte budget) so
// one huge tensor cannot monopolise the fabric. With mixed partition
// sizes (the tail slice of every tensor) a partition-count cap would
// admit wildly different byte volumes; counting bytes keeps the
// admitted window constant. addTask/getTask/reportFinish →
// Push/Pop/ReleaseCredit.
#pragma once

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <functional>
#include <mutex>
#include <queue>
#include <vector>

namespace bps {

inline bool QueueDebug() {
  static const bool on = [] {
    const char* v = getenv("BYTEPS_QUEUE_DEBUG");
    return v && *v && *v != '0';
  }();
  return on;
}

// BYTEPS_SCHEDULING=fifo disables the priority order (pure enqueue
// order). Exists for A/B measurement of the scheduler's benefit
// (tools/bench_priority.py) and as an escape hatch; "priority" (default)
// is the reference behavior.
inline bool FifoScheduling() {
  static const bool fifo = [] {
    const char* v = getenv("BYTEPS_SCHEDULING");
    return v && strcmp(v, "fifo") == 0;
  }();
  return fifo;
}

struct Task {
  int priority = 0;       // higher = sooner
  int64_t seq = 0;        // FIFO tie-break within a priority level
  int64_t key = 0;
  int64_t bytes = 0;      // raw partition bytes charged against the budget
  // Small-tensor fusion (BYTEPS_FUSION_BYTES): tasks under the threshold
  // are fusible; the worker's PushLoop coalesces consecutive fusible
  // pops bound for the same server into one CMD_MULTI_PUSH frame.
  int server_id = -1;
  bool fusible = false;
  std::function<void()> run;
};

struct TaskOrder {
  bool operator()(const Task& a, const Task& b) const {
    if (!FifoScheduling() && a.priority != b.priority)
      return a.priority < b.priority;  // max-heap
    return a.seq > b.seq;  // earlier enqueue first
  }
};

class ScheduledQueue {
 public:
  explicit ScheduledQueue(int64_t budget_bytes) : budget_(budget_bytes) {}

  void Push(Task t) {
    std::lock_guard<std::mutex> lk(mu_);
    t.seq = seq_++;
    if (QueueDebug()) {
      fprintf(stderr, "[QDEBUG] push key=%lld bytes=%lld inflight=%lld "
              "pending=%zu\n", (long long)t.key, (long long)t.bytes,
              (long long)inflight_bytes_, heap_.size() + 1);
    }
    heap_.push(std::move(t));
    // notify_all: with BYTEPS_PUSH_THREADS > 1 several poppers wait on
    // cv_; a single notify can land on a popper whose predicate stays
    // false (budget exhausted) and be consumed without admitting work,
    // serialising the drain to one thread. Wakeups here are rare relative
    // to send work, so the spurious-wake cost is noise.
    cv_.notify_all();
  }

  // Blocks until the top task fits the byte budget (or Stop()). A task
  // larger than the whole budget is admitted alone — always-admit-one
  // keeps oversized partitions live instead of deadlocking.
  bool Pop(Task* out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [this] {
      return stopped_ ||
             (!heap_.empty() &&
              (inflight_bytes_ == 0 ||
               inflight_bytes_ + heap_.top().bytes <= budget_));
    });
    if (stopped_) return false;
    *out = heap_.top();
    heap_.pop();
    inflight_bytes_ += out->bytes;
    if (QueueDebug()) {
      fprintf(stderr, "[QDEBUG] pop key=%lld bytes=%lld inflight=%lld "
              "pending=%zu\n", (long long)out->key, (long long)out->bytes,
              (long long)inflight_bytes_, heap_.size());
    }
    return true;
  }

  // Bounded-wait companion to Pop for the fusion collector: pops the
  // top task when it is fusible (any server — the byte-balanced
  // partition->server assignment interleaves servers at the queue head,
  // so the collector accumulates one batch per server concurrently) and
  // fits the credit budget. When the queue is EMPTY it waits up to
  // `wait_us` microseconds for a matching task to arrive — the flush
  // linger that lets a batch form while the (slower) enqueuing thread
  // is still pumping tasks in; pass 0 for a pure non-blocking attempt.
  // A NON-fusible task at the top returns false immediately: the
  // collector must flush rather than delay a full partition, and
  // popping only the heap top keeps the priority order intact — fusion
  // changes how partitions share frames, never which goes first.
  bool TryPopFusible(int64_t wait_us, Task* out) {
    std::unique_lock<std::mutex> lk(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::microseconds(wait_us);
    for (;;) {
      if (stopped_) return false;
      if (!heap_.empty()) {
        const Task& top = heap_.top();
        if (!top.fusible) return false;
        if (inflight_bytes_ > 0 && inflight_bytes_ + top.bytes > budget_)
          return false;
        *out = heap_.top();
        heap_.pop();
        inflight_bytes_ += out->bytes;
        if (QueueDebug()) {
          fprintf(stderr, "[QDEBUG] pop(fuse) key=%lld bytes=%lld "
                  "inflight=%lld pending=%zu\n", (long long)out->key,
                  (long long)out->bytes, (long long)inflight_bytes_,
                  heap_.size());
        }
        return true;
      }
      if (wait_us <= 0 ||
          cv_.wait_until(lk, deadline) == std::cv_status::timeout) {
        if (heap_.empty()) return false;
      }
    }
  }

  // Called when a partition completes its pull (reference: reportFinish).
  void ReleaseCredit(int64_t bytes) {
    std::lock_guard<std::mutex> lk(mu_);
    inflight_bytes_ -= bytes;
    if (QueueDebug()) {
      fprintf(stderr, "[QDEBUG] release bytes=%lld inflight=%lld "
              "pending=%zu\n", (long long)bytes,
              (long long)inflight_bytes_, heap_.size());
    }
    // One release can free budget for MANY queued tasks; wake every
    // popper so they drain in parallel (see Push).
    cv_.notify_all();
  }

  void Stop() {
    std::lock_guard<std::mutex> lk(mu_);
    stopped_ = true;
    cv_.notify_all();
  }

  size_t pending() {
    std::lock_guard<std::mutex> lk(mu_);
    return heap_.size();
  }

  // Live occupancy for the monitor snapshot (bps_metrics_snapshot):
  // queue depth + credit window let an operator see whether the push
  // stage is admission-bound (inflight pinned at budget, deep queue) or
  // starved (both near zero).
  int64_t inflight_bytes() {
    std::lock_guard<std::mutex> lk(mu_);
    return inflight_bytes_;
  }
  int64_t budget_bytes() const { return budget_; }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::priority_queue<Task, std::vector<Task>, TaskOrder> heap_;
  int64_t budget_;
  int64_t inflight_bytes_ = 0;
  int64_t seq_ = 0;
  bool stopped_ = false;
};

}  // namespace bps
