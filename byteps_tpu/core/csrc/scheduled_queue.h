// Priority queue with credit-based admission.
//
// Capability parity: reference byteps/common/scheduled_queue.{h,cc}
// (BytePSScheduledQueue): partitions are admitted to the DCN push stage
// highest-priority-first (priority = negative declaration order, so
// front-of-model gradients go first — the next forward pass needs them
// first), with a credit cap on in-flight partitions
// (BYTEPS_SCHEDULING_CREDIT) so one huge tensor cannot monopolise the
// fabric. addTask/getTask/reportFinish → Push/Pop/ReleaseCredit.
#pragma once

#include <condition_variable>
#include <cstdint>
#include <functional>
#include <mutex>
#include <queue>
#include <vector>

namespace bps {

struct Task {
  int priority = 0;       // higher = sooner
  int64_t seq = 0;        // FIFO tie-break within a priority level
  int64_t key = 0;
  std::function<void()> run;
};

struct TaskOrder {
  bool operator()(const Task& a, const Task& b) const {
    if (a.priority != b.priority) return a.priority < b.priority;  // max-heap
    return a.seq > b.seq;  // earlier enqueue first
  }
};

class ScheduledQueue {
 public:
  explicit ScheduledQueue(int credit) : credits_(credit) {}

  void Push(Task t) {
    std::lock_guard<std::mutex> lk(mu_);
    t.seq = seq_++;
    heap_.push(std::move(t));
    cv_.notify_one();
  }

  // Blocks until a task is available AND a credit is free (or Stop()).
  bool Pop(Task* out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [this] {
      return stopped_ || (!heap_.empty() && credits_ > 0);
    });
    if (stopped_) return false;
    *out = heap_.top();
    heap_.pop();
    credits_--;
    return true;
  }

  // Called when a partition completes its pull (reference: reportFinish).
  void ReleaseCredit() {
    std::lock_guard<std::mutex> lk(mu_);
    credits_++;
    cv_.notify_one();
  }

  void Stop() {
    std::lock_guard<std::mutex> lk(mu_);
    stopped_ = true;
    cv_.notify_all();
  }

  size_t pending() {
    std::lock_guard<std::mutex> lk(mu_);
    return heap_.size();
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::priority_queue<Task, std::vector<Task>, TaskOrder> heap_;
  int credits_;
  int64_t seq_ = 0;
  bool stopped_ = false;
};

}  // namespace bps
