#include "compressor.h"

#include <algorithm>
#include <cfloat>
#include <cmath>
#include <cstring>
#include <random>

#include "logging.h"

namespace bps {

namespace {

// True iff every value is finite. `!(|v| <= FLT_MAX)` is NaN-proof:
// a NaN fails every comparison, while std::isfinite can be elided
// under -ffast-math and NaN never survives std::max.
bool AllFinite(const float* src, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    if (!(std::fabs(src[i]) <= FLT_MAX)) return false;
  }
  return true;
}

// A NaN/Inf gradient poisons every lossy encoding differently (onebit's
// mean scale goes NaN, sparse-k sorts it to the top, dithering divides
// by it) — all of them would silently encode garbage the server then
// sums into every worker's aggregate. Crash at the boundary with the
// key diagnosis instead; a non-finite gradient is a training bug, not
// a wire condition.
void CheckFiniteInput(const float* src, int64_t n, const char* who) {
  BPS_CHECK(AllFinite(src, n))
      << who << ": non-finite value in compressor input (" << n
      << " elements) — refusing to encode garbage";
}

}  // namespace

std::unordered_map<std::string, std::string> ParseCompressorConfig(
    const std::string& config) {
  std::unordered_map<std::string, std::string> kv;
  size_t pos = 0;
  while (pos < config.size()) {
    size_t end = config.find(';', pos);
    if (end == std::string::npos) end = config.size();
    std::string item = config.substr(pos, end - pos);
    size_t eq = item.find('=');
    if (eq != std::string::npos) {
      kv[item.substr(0, eq)] = item.substr(eq + 1);
    } else if (!item.empty()) {
      kv[item] = "";
    }
    pos = end + 1;
  }
  return kv;
}

namespace {

// --- onebit: sign bits + one mean-magnitude scale ---------------------------
// Wire: [f32 scale][ceil(n/8) sign bytes]; ~32x smaller than f32.
class OnebitCompressor : public Compressor {
 public:
  void Compress(const float* src, int64_t n, std::vector<char>* out) override {
    CheckFiniteInput(src, n, "onebit");
    int64_t nbytes = (n + 7) / 8;
    out->assign(sizeof(float) + nbytes, 0);
    double sum_abs = 0;
    for (int64_t i = 0; i < n; ++i) sum_abs += std::fabs(src[i]);
    float scale = n > 0 ? static_cast<float>(sum_abs / n) : 0.0f;
    memcpy(out->data(), &scale, sizeof(float));
    unsigned char* bits =
        reinterpret_cast<unsigned char*>(out->data() + sizeof(float));
    for (int64_t i = 0; i < n; ++i) {
      if (src[i] >= 0) bits[i >> 3] |= (1u << (i & 7));
    }
  }

  void Decompress(const char* src, int64_t src_bytes, float* dst,
                  int64_t n) override {
    BPS_CHECK_GE(src_bytes, static_cast<int64_t>(sizeof(float) + (n + 7) / 8));
    float scale;
    memcpy(&scale, src, sizeof(float));
    const unsigned char* bits =
        reinterpret_cast<const unsigned char*>(src + sizeof(float));
    for (int64_t i = 0; i < n; ++i) {
      dst[i] = (bits[i >> 3] >> (i & 7)) & 1 ? scale : -scale;
    }
  }
};

// --- topk / randomk: k (index, value) pairs ---------------------------------
// Wire: [i32 k][k * (i32 idx, f32 val)].
class SparseKCompressor : public Compressor {
 public:
  SparseKCompressor(int64_t k, bool random, uint64_t seed)
      : k_(k), random_(random), rng_(seed) {}

  void Compress(const float* src, int64_t n, std::vector<char>* out) override {
    CheckFiniteInput(src, n, random_ ? "randomk" : "topk");
    int64_t k = std::min<int64_t>(k_, n);
    std::vector<int64_t> idx;
    if (random_) {
      // sample k distinct indices
      idx.resize(n);
      for (int64_t i = 0; i < n; ++i) idx[i] = i;
      for (int64_t i = 0; i < k; ++i) {
        std::uniform_int_distribution<int64_t> d(i, n - 1);
        std::swap(idx[i], idx[d(rng_)]);
      }
      idx.resize(k);
    } else {
      idx.resize(n);
      for (int64_t i = 0; i < n; ++i) idx[i] = i;
      std::partial_sort(idx.begin(), idx.begin() + k, idx.end(),
                        [&](int64_t a, int64_t b) {
                          return std::fabs(src[a]) > std::fabs(src[b]);
                        });
      idx.resize(k);
    }
    out->resize(sizeof(int32_t) + k * (sizeof(int32_t) + sizeof(float)));
    char* p = out->data();
    int32_t k32 = static_cast<int32_t>(k);
    memcpy(p, &k32, sizeof(k32));
    p += sizeof(k32);
    for (int64_t i = 0; i < k; ++i) {
      int32_t j = static_cast<int32_t>(idx[i]);
      memcpy(p, &j, sizeof(j));
      p += sizeof(j);
      memcpy(p, &src[idx[i]], sizeof(float));
      p += sizeof(float);
    }
  }

  void Decompress(const char* src, int64_t src_bytes, float* dst,
                  int64_t n) override {
    memset(dst, 0, n * sizeof(float));
    BPS_CHECK_GE(src_bytes, static_cast<int64_t>(sizeof(int32_t)));
    int32_t k;
    memcpy(&k, src, sizeof(k));
    const char* p = src + sizeof(k);
    BPS_CHECK_GE(src_bytes,
                 static_cast<int64_t>(sizeof(int32_t)) +
                     k * static_cast<int64_t>(sizeof(int32_t) + sizeof(float)));
    for (int32_t i = 0; i < k; ++i) {
      int32_t j;
      float v;
      memcpy(&j, p, sizeof(j));
      p += sizeof(j);
      memcpy(&v, p, sizeof(v));
      p += sizeof(v);
      BPS_CHECK_GE(j, 0);
      BPS_CHECK(j < n) << "sparse index out of range";
      dst[j] = v;
    }
  }

 private:
  int64_t k_;
  bool random_;
  std::mt19937_64 rng_;
};

// --- dithering: stochastic uniform quantization -----------------------------
// Wire: [f32 max_abs][n int8]. Stochastic rounding keeps E[decode] == x
// (the reference's natural-dithering capability; uniform levels here).
class DitheringCompressor : public Compressor {
 public:
  explicit DitheringCompressor(uint64_t seed) : rng_(seed) {}

  void Compress(const float* src, int64_t n, std::vector<char>* out) override {
    CheckFiniteInput(src, n, "dithering");
    float maxabs = 0;
    for (int64_t i = 0; i < n; ++i)
      maxabs = std::max(maxabs, std::fabs(src[i]));
    out->resize(sizeof(float) + n);
    memcpy(out->data(), &maxabs, sizeof(float));
    int8_t* q = reinterpret_cast<int8_t*>(out->data() + sizeof(float));
    if (maxabs == 0) {
      memset(q, 0, n);
      return;
    }
    std::uniform_real_distribution<float> u(0.0f, 1.0f);
    for (int64_t i = 0; i < n; ++i) {
      float scaled = src[i] / maxabs * 127.0f;
      float low = std::floor(scaled);
      float frac = scaled - low;
      int v = static_cast<int>(low) + (u(rng_) < frac ? 1 : 0);
      q[i] = static_cast<int8_t>(std::max(-127, std::min(127, v)));
    }
  }

  void Decompress(const char* src, int64_t src_bytes, float* dst,
                  int64_t n) override {
    BPS_CHECK_GE(src_bytes, static_cast<int64_t>(sizeof(float)) + n);
    float maxabs;
    memcpy(&maxabs, src, sizeof(float));
    const int8_t* q = reinterpret_cast<const int8_t*>(src + sizeof(float));
    for (int64_t i = 0; i < n; ++i) dst[i] = q[i] / 127.0f * maxabs;
  }

 private:
  std::mt19937_64 rng_;
};

// --- error feedback decorator ----------------------------------------------
// e += g; send compress(e); e -= decompress(send)  — reference
// vanilla_error_feedback.cc capability.
class ErrorFeedback : public Compressor {
 public:
  ErrorFeedback(std::unique_ptr<Compressor> inner, int64_t n)
      : inner_(std::move(inner)), residual_(n, 0.0f), scratch_(n) {}

  void Compress(const float* src, int64_t n, std::vector<char>* out) override {
    BPS_CHECK_EQ(n, static_cast<int64_t>(residual_.size()));
    for (int64_t i = 0; i < n; ++i) residual_[i] += src[i];
    inner_->Compress(residual_.data(), n, out);
    inner_->Decompress(out->data(), out->size(), scratch_.data(), n);
    for (int64_t i = 0; i < n; ++i) residual_[i] -= scratch_[i];
  }

  void Decompress(const char* src, int64_t src_bytes, float* dst,
                  int64_t n) override {
    inner_->Decompress(src, src_bytes, dst, n);
  }

 private:
  std::unique_ptr<Compressor> inner_;
  std::vector<float> residual_;
  std::vector<float> scratch_;
};

// --- nesterov momentum decorator --------------------------------------------
// v = mu*v + g; send g + mu*v  — reference impl/nesterov_momentum.cc.
class NesterovMomentum : public Compressor {
 public:
  NesterovMomentum(std::unique_ptr<Compressor> inner, int64_t n, float mu)
      : inner_(std::move(inner)), vel_(n, 0.0f), send_(n), mu_(mu) {}

  void Compress(const float* src, int64_t n, std::vector<char>* out) override {
    BPS_CHECK_EQ(n, static_cast<int64_t>(vel_.size()));
    for (int64_t i = 0; i < n; ++i) {
      vel_[i] = mu_ * vel_[i] + src[i];
      send_[i] = src[i] + mu_ * vel_[i];
    }
    inner_->Compress(send_.data(), n, out);
  }

  void Decompress(const char* src, int64_t src_bytes, float* dst,
                  int64_t n) override {
    inner_->Decompress(src, src_bytes, dst, n);
  }

 private:
  std::unique_ptr<Compressor> inner_;
  std::vector<float> vel_;
  std::vector<float> send_;
  float mu_;
};

}  // namespace

std::unique_ptr<Compressor> CreateCompressor(const std::string& config,
                                             int64_t n) {
  auto kv = ParseCompressorConfig(config);
  auto type_it = kv.find("type");
  if (type_it == kv.end() || type_it->second.empty()) return nullptr;
  const std::string& type = type_it->second;

  auto get_i = [&](const char* key, int64_t dflt) {
    auto it = kv.find(key);
    return it != kv.end() ? atoll(it->second.c_str()) : dflt;
  };
  auto get_f = [&](const char* key, double dflt) {
    auto it = kv.find(key);
    return it != kv.end() ? atof(it->second.c_str()) : dflt;
  };

  std::unique_ptr<Compressor> c;
  if (type == "onebit") {
    c = std::make_unique<OnebitCompressor>();
  } else if (type == "topk") {
    c = std::make_unique<SparseKCompressor>(
        get_i("k", std::max<int64_t>(1, n / 100)), false, 0);
  } else if (type == "randomk") {
    c = std::make_unique<SparseKCompressor>(
        get_i("k", std::max<int64_t>(1, n / 100)), true,
        static_cast<uint64_t>(get_i("seed", 12345)));
  } else if (type == "dithering") {
    c = std::make_unique<DitheringCompressor>(
        static_cast<uint64_t>(get_i("seed", 12345)));
  } else {
    BPS_FATAL << "unknown compressor type: " << type;
  }

  // Decorators (order matches the reference: momentum inside error feedback
  // so the residual sees the momentum-folded gradient).
  auto mom = kv.find("momentum");
  if (mom != kv.end() && mom->second == "nesterov") {
    c = std::make_unique<NesterovMomentum>(
        std::move(c), n, static_cast<float>(get_f("mu", 0.9)));
  }
  auto ef = kv.find("ef");
  if (ef != kv.end() && ef->second == "vanilla") {
    c = std::make_unique<ErrorFeedback>(std::move(c), n);
  }
  return c;
}

// --- BlockQuant wire codec (ISSUE 6) ----------------------------------------

namespace {

constexpr uint16_t kBlockQuantMagic = 0xB10C;

#pragma pack(push, 1)
struct BlockQuantHeader {
  uint16_t magic;
  uint16_t block;
  int32_t nelem;
};
#pragma pack(pop)

// Shared encode body: when `residual` is non-null it IS the source and
// receives the EF update (residual -= decode(encoded)) in the same pass.
bool BlockQuantEncodeImpl(const float* src, float* residual, int64_t n,
                          int block, std::vector<char>* out) {
  if (!BlockQuant::ValidBlock(block) || n < 0) return false;
  const int64_t nblocks = (n + block - 1) / block;
  out->resize(static_cast<size_t>(BlockQuant::EncodedSize(n, block)));
  auto* hdr = reinterpret_cast<BlockQuantHeader*>(out->data());
  hdr->magic = kBlockQuantMagic;
  hdr->block = static_cast<uint16_t>(block);
  hdr->nelem = static_cast<int32_t>(n);
  float* scales =
      reinterpret_cast<float*>(out->data() + sizeof(BlockQuantHeader));
  int8_t* q = reinterpret_cast<int8_t*>(
      out->data() + sizeof(BlockQuantHeader) + nblocks * sizeof(float));
  for (int64_t b = 0; b < nblocks; ++b) {
    const int64_t lo = b * block;
    const int64_t hi = std::min<int64_t>(lo + block, n);
    float absmax = 0.0f;
    for (int64_t i = lo; i < hi; ++i) {
      const float a = std::fabs(src[i]);
      // NaN-proof finiteness gate (a NaN fails every comparison, so it
      // can neither become absmax nor pass this check).
      if (!(a <= FLT_MAX)) return false;
      if (a > absmax) absmax = a;
    }
    // All-zero block: scale 0 encodes — and decodes — exact zeros.
    const float scale = absmax / 127.0f;
    scales[b] = scale;
    const float inv = scale > 0.0f ? 1.0f / scale : 0.0f;
    for (int64_t i = lo; i < hi; ++i) {
      int v = static_cast<int>(std::lrintf(src[i] * inv));
      if (v > 127) v = 127;
      if (v < -127) v = -127;
      q[i] = static_cast<int8_t>(v);
      if (residual) residual[i] -= static_cast<float>(v) * scale;
    }
  }
  return true;
}

}  // namespace

bool BlockQuant::Encode(const float* src, int64_t n, int block,
                        std::vector<char>* out) {
  return BlockQuantEncodeImpl(src, nullptr, n, block, out);
}

bool BlockQuant::EncodeEF(float* residual, int64_t n, int block,
                          std::vector<char>* out) {
  return BlockQuantEncodeImpl(residual, residual, n, block, out);
}

bool BlockQuant::Decode(const char* src, int64_t src_bytes, float* dst,
                        int64_t n) {
  if (src_bytes < static_cast<int64_t>(sizeof(BlockQuantHeader))) {
    return false;
  }
  BlockQuantHeader hdr;
  memcpy(&hdr, src, sizeof(hdr));
  const int block = hdr.block;
  if (hdr.magic != kBlockQuantMagic || !ValidBlock(block) ||
      hdr.nelem != n || src_bytes != EncodedSize(n, block)) {
    return false;
  }
  const int64_t nblocks = (n + block - 1) / block;
  const float* scales =
      reinterpret_cast<const float*>(src + sizeof(BlockQuantHeader));
  const int8_t* q = reinterpret_cast<const int8_t*>(
      src + sizeof(BlockQuantHeader) + nblocks * sizeof(float));
  for (int64_t b = 0; b < nblocks; ++b) {
    const int64_t lo = b * block;
    const int64_t hi = std::min<int64_t>(lo + block, n);
    const float scale = scales[b];
    for (int64_t i = lo; i < hi; ++i) {
      dst[i] = static_cast<float>(q[i]) * scale;
    }
  }
  return true;
}

}  // namespace bps
