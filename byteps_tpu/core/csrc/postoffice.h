// Node registry, id assignment, barriers, heartbeats.
//
// Capability parity: reference ps-lite Postoffice (SURVEY.md §2.4):
// scheduler/server/worker role management, node registration handshake,
// group barriers, env-driven addressing (DMLC_PS_ROOT_URI/PORT,
// DMLC_NUM_WORKER, DMLC_NUM_SERVER), heartbeat-based failure detection
// (PS_HEARTBEAT_INTERVAL / PS_HEARTBEAT_TIMEOUT).
#pragma once

#include <atomic>
#include <condition_variable>
#include <deque>
#include <functional>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "common.h"
#include "schedrec.h"
#include "van.h"

namespace bps {

// Barrier groups (bitmask)
enum BarrierGroup : int {
  GROUP_SERVERS = 1,
  GROUP_WORKERS = 2,
  GROUP_ALL = 3,
};

class Postoffice {
 public:
  // App-level handler for data-plane messages (PUSH/PULL/...); control-plane
  // (register/barrier/heartbeat) is consumed internally.
  using AppHandler = std::function<void(Message&&, int fd)>;

  Postoffice() = default;
  ~Postoffice() { Finalize(); }

  // Start the node: scheduler binds the root port and waits for everyone;
  // servers/workers register with the scheduler and receive the address
  // book; workers additionally dial every server. Blocks until the topology
  // is fully connected. Returns this node's assigned id.
  int Start(Role role, const std::string& root_uri, int root_port,
            int num_workers, int num_servers, AppHandler app_handler);

  // Block until every member of `group` reached the barrier.
  void Barrier(int group);

  void Finalize();  // graceful: scheduler broadcasts SHUTDOWN

  // Invoked (on a van thread) when a fleet-wide SHUTDOWN arrives at a
  // non-scheduler node — lets the KV layer fail fast on in-flight work
  // instead of hanging when a peer died (failure detection, SURVEY.md §5).
  void SetShutdownCallback(std::function<void()> cb) {
    shutdown_cb_ = std::move(cb);
  }

  // Invoked (on a van thread) when the connection to a known peer node
  // drops while the fleet is running — the fast-fail signal for that
  // node's in-flight requests (heartbeat timeout is the slow fallback).
  // With the retry layer on (BYTEPS_RETRY_MAX > 0) this only fires after
  // reconnect-with-backoff exhausted its attempts: a transient reset is
  // absorbed in-band, only a persistent fault escalates.
  void SetPeerLostCallback(std::function<void(int node_id)> cb) {
    peer_lost_cb_ = std::move(cb);
  }

  // Invoked (on a van thread) after a lost worker->server connection was
  // re-established (transient fault absorbed): the KV layer resends that
  // node's in-flight requests over the fresh connection immediately
  // instead of waiting out their retry timeouts.
  void SetPeerReconnectedCallback(std::function<void(int node_id)> cb) {
    peer_reconnected_cb_ = std::move(cb);
  }

  // Hot server replacement (ISSUE 4). Paused: a server rank is presumed
  // dead and under scheduler-coordinated recovery — the KV layer freezes
  // that rank's retry clocks (requests park in the resend queue instead
  // of escalating). Recovered: a replacement adopted the rank and this
  // worker's connection was redialled — the worker re-seeds the shard
  // and drains the parked queue. Both run on van recv threads.
  void SetPeerPausedCallback(std::function<void(int node_id)> cb) {
    peer_paused_cb_ = std::move(cb);
  }
  void SetPeerRecoveredCallback(std::function<void(int node_id)> cb) {
    peer_recovered_cb_ = std::move(cb);
  }

  // Elastic worker membership (ISSUE 8). Pause: a JOIN-kind
  // CMD_FLEET_PAUSE arrived — the worker gates new rounds and answers
  // with its round counters (the KV layer's in-flight rounds complete
  // against the OLD roster, so no drain wait). Resume: the change is
  // committed — sync counters (join) and lift the gate. Resize (server
  // role): update the roster history; a removal additionally rolls the
  // in-flight rounds back. All run on van recv threads.
  void SetFleetPauseCallback(std::function<void(int kind)> cb) {
    fleet_pause_cb_ = std::move(cb);
  }
  void SetFleetResumeCallback(
      std::function<void(int kind, int affected, int64_t join_round,
                         int64_t join_bcast)> cb) {
    fleet_resume_cb_ = std::move(cb);
  }
  // Server-side resize additionally carries the affected node's TENANT
  // (ISSUE 9): rounds are per-tenant counters, so the roster epoch a
  // join/removal creates must land in that tenant's history only.
  void SetFleetResizeCallback(
      std::function<void(int kind, int affected, int64_t join_round,
                         int64_t join_bcast, int tenant)> cb) {
    fleet_resize_cb_ = std::move(cb);
  }

  // Scheduler fail-over (ISSUE 15). Invoked (on the heartbeat thread)
  // after a scheduler-lost park ended in a successful recovery — the
  // worker layer clears any stale round gate a pre-crash FLEET_PAUSE
  // left armed (its commit may have died with the old scheduler).
  void SetSchedRecoveredCallback(std::function<void()> cb) {
    sched_recovered_cb_ = std::move(cb);
  }
  // Provider for the rounds-completed watermark a CMD_REREGISTER
  // carries (workers: the KV layer's max issued round; others 0).
  void SetRoundWatermarkProvider(std::function<int64_t()> fn) {
    round_watermark_fn_ = std::move(fn);
  }
  // True while this node is parked on a lost scheduler connection
  // (fail-over armed): the KV retry layer defers its exhaustion
  // escalation — with the control plane down there is nobody to
  // coordinate a fail-stop, and the park owns the deadline.
  bool SchedLost() const { return sched_lost_.load(); }

  // Worker: gated-round counters -> scheduler (join drain-free ack).
  void SendFleetPauseAck(int64_t max_round, int64_t max_bcast);

  // Worker: graceful leave. Sends CMD_LEAVE_REQUEST (the caller must
  // have drained its handles first) and waits for the scheduler's
  // CMD_LEAVE_ACK. After a true return, Finalize skips the goodbye —
  // this rank no longer counts toward the fleet's shutdown quorum.
  bool RequestLeave();

  // Joiner: the round boundary this rank enters at (from the direct
  // ADDRBOOK's arg1; 0 on ordinary formation).
  int64_t join_round() const { return join_round_.load(); }
  int64_t join_bcast_round() const { return join_bcast_.load(); }

  // Durable checkpoints (ISSUE 18). A restore-armed server reports its
  // newest checksum-valid checkpoint version before Start (set by the
  // c_api glue from the server's scan; -1 = armed but nothing valid on
  // disk — the scheduler fail-stops on it by contract). The scheduler
  // commits a fleet-wide restore epoch at the minimum common version
  // across all server shards and broadcasts it in CMD_ADDRBOOK; every
  // node reads it back here (-1 = no restore this formation). Workers
  // jump their round counters to restore_round()+1; servers install
  // the checkpoint cut at exactly restore_round().
  void SetDurableCkpt(int64_t newest) {
    durable_armed_ = true;
    durable_ckpt_ = newest;
  }
  int64_t restore_round() const { return restore_round_.load(); }
  // Engine threads may race a fast worker's INIT_KEY against our own
  // ADDRBOOK receipt: block until the book (and with it the committed
  // restore epoch) arrived.
  int64_t WaitRestoreRound();

  // Current membership epoch (bumped by the scheduler per recovery) and
  // whether any rank is mid-recovery from this node's point of view.
  int64_t epoch() const { return epoch_.load(); }
  bool Recovering() const { return recovering_count_.load() > 0; }

  // True once this node received (or itself triggered) a FAILURE
  // shutdown — the scheduler's dead-node broadcast (CMD_SHUTDOWN
  // arg0=1) or a lost scheduler connection — as opposed to the clean
  // all-workers-said-goodbye teardown. Server/scheduler entry points
  // exit nonzero on it so a supervisor can tell crash from completion.
  bool FailureShutdown() const { return failure_shutdown_.load(); }

  // --- topology queries ---
  int my_id() const { return my_id_; }
  Role role() const { return role_; }
  // LIVE fleet size: elastic joins/leaves/shrinks update it mid-run
  // (CMD_FLEET_RESUME recounts it from the re-issued address book).
  int num_workers() const { return num_workers_.load(); }
  int num_servers() const { return num_servers_; }
  // node ids: scheduler 0, servers 1..S, workers S+1..S+W
  static int ServerId(int s) { return 1 + s; }
  int WorkerId(int w) const { return 1 + num_servers_ + w; }
  int my_worker_rank() const { return my_id_ - 1 - num_servers_; }
  // fd of the connection to a node (workers: scheduler + all servers).
  int FdOf(int node_id);
  // Striped variant (BYTEPS_VAN_STREAMS): the stream for `key`, chosen by
  // key hash so one key's traffic — and therefore its request ordering —
  // stays on one TCP connection. Falls back to the primary fd when no
  // extra stripes were dialed (control paths always use FdOf(node)).
  int FdOf(int node_id, int64_t key);

  Van& van() { return *van_; }
  bool ShuttingDown() const { return shutting_down_.load(); }
  // Clock alignment vs the scheduler (ISSUE 5 tracing): estimated from
  // the heartbeat echo (CMD_HEARTBEAT_ACK) with the minimum-RTT sample
  // kept — t_scheduler ~= t_local + ClockOffsetUs(). The scheduler's
  // own offset is 0; rtt -1 = no estimate yet (heartbeats disabled, or
  // none answered). Recorded in every trace dump's metadata so the
  // fleet merge (monitor.timeline) aligns per-rank clocks.
  int64_t ClockOffsetUs() const { return clock_offset_us_.load(); }
  int64_t ClockRttUs() const { return clock_rtt_us_.load(); }
  // --- multi-tenant roster (ISSUE 9), derived from the address book ---
  // Worker ids serving tenant `tenant`. Tenant registration rides
  // NodeInfo (CMD_REGISTER / CMD_JOIN_REQUEST payloads) and is
  // re-broadcast with every address book, so the roster is live across
  // elastic membership changes with no extra control traffic. Empty
  // when the book has not arrived yet (callers fall back to the
  // formation fleet size for tenant 0).
  std::set<int> TenantWorkers(uint16_t tenant);
  int TenantWorkerCount(uint16_t tenant);
  // The tenant's advertised BYTEPS_TENANT_WEIGHT share (max across its
  // workers; 0-weight legacy registrants read as 1).
  int TenantWeightOf(uint16_t tenant);
  // Tenant of a worker node id (-1 = unknown node).
  int TenantOfNode(int node_id);
  // Full roster: tenant -> (live worker count, weight).
  std::map<uint16_t, std::pair<int, int>> TenantRoster();

  // Address-book lookup by node id (ISSUE 16: a replica dials its
  // primary from the LIVE book, so a hot-replaced primary resolves to
  // the replacement's endpoint). False when the id is not in the book.
  bool NodeOf(int node_id, NodeInfo* out);

  // Worker/server ids the scheduler considers dead (missed heartbeats).
  std::vector<int> DeadNodes();
  // Scheduler-side heartbeat freshness: (node id, ms since last beat)
  // for every tracked node, sorted by id — the monitor snapshot's
  // health signal (a cleanly-departed node is not tracked).
  std::vector<std::pair<int, int64_t>> HeartbeatAges();

 private:
  void ControlHandler(Message&& msg, int fd);
  void HeartbeatLoop();
  // Elastic worker membership (scheduler; caller holds mu_). A queued
  // membership op starts when no other is active: bump the epoch,
  // broadcast CMD_FLEET_PAUSE, and — join only — wait for every
  // worker's gated-counter ack before committing. Leaves and death
  // shrinks commit immediately (no drain needed; the server rollback
  // owns in-flight rounds).
  struct MemberOp {
    int kind = 0;      // 0 join, 1 leave, 2 death shrink
    int fd = -1;       // joiner's scheduler connection
    NodeInfo info{};   // joiner's advertised address
    int node_id = -1;  // leaver / dead worker id
    // Tenant of the joining/departing worker (ISSUE 9): only THIS
    // tenant's workers gate (join) and only this tenant's rosters
    // move — another tenant's rounds are untouched by the change.
    int tenant = 0;
  };
  // Tenant of a node id from the current book; caller holds mu_.
  int TenantOfNodeLocked(int node_id) const;
  void StartMemberOpLocked(MemberOp&& op);
  void CompleteMemberOpLocked();
  void HandleJoinRequest(Message&& msg, int fd);
  void HandleLeaveRequest(const Message& msg, int fd);
  // Scheduler: enter RECOVERY for a dead server rank — bump the epoch,
  // broadcast CMD_EPOCH_PAUSE, and arm the replacement-wait deadline.
  // Caller holds mu_.
  void StartRecoveryLocked(int node_id);
  // Scheduler: a replacement registered for `rank` (CMD_REGISTER with
  // the recovery marker) — adopt it: assign the dead rank's id, update
  // the address book, reply ADDRBOOK, broadcast CMD_EPOCH_RESUME.
  void HandleRecoverRegister(int fd, const NodeInfo& info, int rank);
  // Scheduler: admit a read replica (ISSUE 16) — fresh elastic rank,
  // roster + heartbeat row, direct ADDRBOOK reply. Never a formation
  // participant and never counted into num_workers_/num_servers_.
  // Caller holds mu_.
  void AdmitReplicaLocked(int fd, const NodeInfo& info, int primary_rank);
  // Scheduler: the fail-stop broadcast (failure SHUTDOWN, arg0=1) —
  // shared by the heartbeat monitor and the recovery-timeout fallback.
  // Caller holds mu_.
  void BroadcastFailureLocked(const std::string& why);
  // Worker: dial the replacement server (all stripes), re-identify, and
  // swap the rank's fds. Returns false when the replacement is already
  // unreachable (escalates to peer-lost).
  bool DialReplacement(int node_id, const NodeInfo& info);
  // Re-dial a lost worker->server connection (stripe `stripe`; 0 =
  // primary) with capped exponential backoff (BYTEPS_RECONNECT_MAX /
  // BYTEPS_RECONNECT_BACKOFF_MS). On success the fresh fd replaces the
  // dead one in node_fd_/node_extra_fds_ and the worker re-identifies
  // itself (CMD_REGISTER hello, as at stripe dial time). Runs on the
  // dead connection's recv thread, before its CloseConn.
  bool TryReconnect(int node_id, int stripe);
  // Scheduler fail-over (ISSUE 15), node side: the scheduler
  // connection died with fail-over armed. Park — keep the data plane
  // draining against the last committed book — and re-dial the
  // scheduler endpoint with the capped backoff ladder, sending a
  // state-carrying CMD_REREGISTER on every fresh connection. Returns
  // true once CMD_SCHED_RESUME committed the recovery (heartbeats
  // resume); false when BYTEPS_SCHED_RECOVERY_TIMEOUT_MS expired (the
  // caller escalates to the original fail-stop). Runs on the
  // heartbeat thread.
  bool ParkOnSchedulerLost();
  // Scheduler fail-over, scheduler side: one node's CMD_REREGISTER.
  // Recover mode ingests it into sched_rec_ and commits at quorum; an
  // already-committed (or never-crashed) scheduler answers with an
  // idempotent direct ADDRBOOK + SCHED_RESUME.
  void HandleReregister(Message&& msg, int fd);
  // Quorum reached: rebuild the book / epoch / rank high-water mark /
  // tenant rosters from the fleet's reports, SEED the heartbeat table
  // (an empty table would declare every rank dead on the first tick),
  // broadcast re-issued ADDRBOOK + CMD_SCHED_RESUME, and release any
  // joins queued across the outage. Caller holds mu_.
  void CommitSchedRecoveryLocked();

  std::unique_ptr<Van> van_;
  AppHandler app_handler_;
  Role role_ = ROLE_WORKER;
  int my_id_ = -1;
  std::atomic<int> num_workers_{0};  // live (elastic membership)
  int num_servers_ = 0;
  std::atomic<bool> shutting_down_{false};
  std::atomic<bool> failure_shutdown_{false};

  std::mutex mu_;
  std::condition_variable cv_;
  std::vector<NodeInfo> nodes_;            // address book (set by ADDRBOOK)
  std::unordered_map<int, int> node_fd_;   // node id -> primary conn fd
  // node id -> extra striped data connections (BYTEPS_VAN_STREAMS > 1);
  // worker->server only. Stripe s of key k: s = k % streams, stripe 0 =
  // primary fd, stripe s>0 = extra[s-1].
  std::unordered_map<int, std::vector<int>> node_extra_fds_;
  bool addrbook_ready_ = false;

  // scheduler state
  // durable = the registrant's reported newest checkpoint version
  // (ISSUE 18): -2 = not restore-armed, -1 = armed with nothing valid
  // on disk, >= 0 = a checksum-valid checkpoint at that version.
  struct PendingReg { int fd; NodeInfo info; int64_t durable = -2; };
  std::vector<PendingReg> pending_regs_;
  // Read replicas that registered before fleet formation completed
  // (ISSUE 16): parked until there is an address book to answer with.
  struct BufferedReplica { NodeInfo info{}; int fd = -1; int primary = 0; };
  std::vector<BufferedReplica> buffered_replicas_;
  int replica_count_ = 0;  // live admitted replicas (guarded by mu_)
  std::map<int, int> barrier_counts_;      // group -> count
  std::unordered_map<int, int64_t> last_heartbeat_ms_;  // node id -> ts
  std::unordered_set<int> departed_;       // clean goodbyes: never "dead"
  int barrier_acks_needed_ = 0;

  // client-side barrier wait state
  std::map<int, int> barrier_done_;        // group -> generation

  std::thread heartbeat_thread_;
  std::thread monitor_thread_;  // scheduler: dead-node detection
  std::function<void()> shutdown_cb_;
  std::function<void(int)> peer_lost_cb_;
  std::function<void(int)> peer_reconnected_cb_;
  std::function<void(int)> peer_paused_cb_;
  std::function<void(int)> peer_recovered_cb_;
  std::function<void(int)> fleet_pause_cb_;
  std::function<void(int, int, int64_t, int64_t)> fleet_resume_cb_;
  std::function<void(int, int, int64_t, int64_t, int)> fleet_resize_cb_;

  // Hot-server-replacement state (guarded by mu_ unless atomic).
  std::atomic<int64_t> epoch_{0};          // fleet membership epoch
  std::atomic<int> recovering_count_{0};   // ranks currently mid-recovery
  std::unordered_set<int> recovering_peers_;  // node ids under recovery
  // Worker only: ranks parked by a LOCAL disconnect whose death the
  // scheduler has NOT yet confirmed (no CMD_EPOCH_PAUSE seen). The peer
  // may well be alive with only our connection broken (asymmetric loss,
  // chaos resets exhausting the reconnect ladder under load), and the
  // scheduler will then never start a recovery — so HeartbeatLoop keeps
  // re-dialing these (resume on success) and escalates to the
  // pre-recovery fail-fast once the deadline passes: by then a genuine
  // death would have produced either an EPOCH_RESUME or the scheduler's
  // no-replacement failure SHUTDOWN. stripes = dead stripes to re-dial.
  struct DiscPark {
    std::set<int> stripes;
    int64_t deadline_ms = 0;
  };
  std::unordered_map<int, DiscPark> disc_parked_;
  // Wire-CRC flaky-link quarantine attribution (ISSUE 19, guarded by
  // mu_): per-peer count of quarantine trips (the van force-closed a
  // connection over windowed CRC failures, BYTEPS_WIRE_CRC_QUARANTINE).
  // A peer whose trip count exceeds the reconnect budget
  // (BYTEPS_RECONNECT_MAX) is a persistently corrupting link: it joins
  // corrupt_failed_, and the disconnect handler then escalates straight
  // to the named fail-stop instead of re-dialing a poisoned path (a
  // fresh socket has already been tried budget-many times; the
  // corruption followed it every time).
  std::unordered_map<int, int> corrupt_quarantines_;
  std::unordered_set<int> corrupt_failed_;
  // scheduler only: the rank being replaced (-1 = none) and the
  // fall-back-to-fail-stop deadline for the replacement to arrive.
  int recovering_node_ = -1;
  int64_t recovery_deadline_ms_ = 0;

  // Elastic worker membership (scheduler state, guarded by mu_).
  // Worker ranks are allocated monotonically and NEVER reused: a joined
  // worker's rank (and therefore node id, trace identity, and monitor
  // endpoint port) can never collide with a departed one's.
  int next_worker_rank_ = -1;
  std::deque<MemberOp> member_queue_;
  bool member_active_ = false;
  MemberOp member_op_{};
  std::set<int> pause_acks_pending_;   // worker ids still to ack (join)
  int64_t member_round_max_ = 0;       // fleet max round counter (join)
  int64_t member_bcast_max_ = 0;
  int64_t member_start_ms_ = 0;
  int64_t member_deadline_ms_ = 0;     // fail-stop fallback

  // Worker: joiner's activation rounds (direct ADDRBOOK arg1) and the
  // graceful-leave handshake state.
  std::atomic<int64_t> join_round_{0};
  std::atomic<int64_t> join_bcast_{0};

  // Durable checkpoints (ISSUE 18): this node's own report (server,
  // set before Start) and the fleet's committed restore epoch (every
  // node, parsed from CMD_ADDRBOOK's key; -1 = none).
  bool durable_armed_ = false;
  int64_t durable_ckpt_ = -2;
  std::atomic<int64_t> restore_round_{-1};
  bool leave_acked_ = false;           // guarded by mu_
  std::atomic<bool> left_{false};      // leave committed: no goodbye owed

  // Heartbeat-echo clock estimate (see ClockOffsetUs).
  std::atomic<int64_t> clock_offset_us_{0};
  std::atomic<int64_t> clock_rtt_us_{-1};

  // --- scheduler fail-over (ISSUE 15) ---
  // Node side: the scheduler endpoint to re-dial (captured at Start —
  // the restarted scheduler binds the SAME root port, pinned by the
  // launcher), the park flag, and the per-park resume latch.
  std::string sched_host_;
  int sched_port_ = 0;
  std::atomic<bool> sched_lost_{false};
  bool sched_resumed_ = false;            // guarded by mu_
  std::function<void()> sched_recovered_cb_;
  std::function<int64_t()> round_watermark_fn_;
  // Scheduler side: recover mode (DMLC_SCHED_RECOVER), the fleet-state
  // reconstruction, a failure reason that turns Start's recovery wait
  // into the clean fail-stop (conflict / malformed quorum), and joins
  // that arrived mid-recovery (released at commit). All but the start
  // timestamp guarded by mu_.
  bool sched_recover_mode_ = false;
  SchedRecovery sched_rec_;
  std::string sched_rec_fail_;
  int64_t sched_rec_start_ms_ = 0;
  std::vector<std::pair<NodeInfo, int>> buffered_joins_;  // info, fd
};

int64_t NowMs();

// BYTEPS_RETRY_MAX > 0 (default 4): the transient-fault tolerance master
// switch shared by the van reconnect path (postoffice.cc) and the KV
// retry layer (kv.h). 0 = pre-retry fail-fast behavior.
bool RetryEnabled();

// Hot server replacement master switch: BYTEPS_RECOVERY_TIMEOUT_MS > 0
// (default 60000) AND the retry layer on (re-seed rides the resend
// queue). 0 restores the PR 3 behavior wholesale: a dead server is a
// fleet-wide failure SHUTDOWN.
bool RecoveryEnabled();
int64_t RecoveryTimeoutMs();

// Elastic worker membership master switch: BYTEPS_ELASTIC=1. Requires
// the retry layer (config.py validates; the C side reads the env
// directly). With it OFF, any worker death keeps the PR 3 fail-stop
// contract byte for byte.
bool ElasticEnabled();
// Fail-stop fallback window for a membership change that cannot commit
// (a worker never acks the join gate): BYTEPS_ELASTIC_TIMEOUT_MS.
int64_t ElasticTimeoutMs();

// Scheduler fail-over master switch (ISSUE 15):
// BYTEPS_SCHED_RECOVERY_TIMEOUT_MS > 0 (default 0 = off) AND the retry
// layer on AND heartbeats on (the heartbeat send failure IS the
// scheduler-lost detector, and the restarted scheduler's death
// verdicts come from the re-seeded heartbeat table). With it off, a
// lost scheduler connection keeps the fail-stop contract byte for
// byte. The window bounds BOTH sides: a parked node's re-dial ladder
// and the restarted scheduler's quorum wait.
bool SchedRecoveryEnabled();
int64_t SchedRecoveryTimeoutMs();

}  // namespace bps
