// Node registry, id assignment, barriers, heartbeats.
//
// Capability parity: reference ps-lite Postoffice (SURVEY.md §2.4):
// scheduler/server/worker role management, node registration handshake,
// group barriers, env-driven addressing (DMLC_PS_ROOT_URI/PORT,
// DMLC_NUM_WORKER, DMLC_NUM_SERVER), heartbeat-based failure detection
// (PS_HEARTBEAT_INTERVAL / PS_HEARTBEAT_TIMEOUT).
#pragma once

#include <atomic>
#include <condition_variable>
#include <functional>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "common.h"
#include "van.h"

namespace bps {

// Barrier groups (bitmask)
enum BarrierGroup : int {
  GROUP_SERVERS = 1,
  GROUP_WORKERS = 2,
  GROUP_ALL = 3,
};

class Postoffice {
 public:
  // App-level handler for data-plane messages (PUSH/PULL/...); control-plane
  // (register/barrier/heartbeat) is consumed internally.
  using AppHandler = std::function<void(Message&&, int fd)>;

  Postoffice() = default;
  ~Postoffice() { Finalize(); }

  // Start the node: scheduler binds the root port and waits for everyone;
  // servers/workers register with the scheduler and receive the address
  // book; workers additionally dial every server. Blocks until the topology
  // is fully connected. Returns this node's assigned id.
  int Start(Role role, const std::string& root_uri, int root_port,
            int num_workers, int num_servers, AppHandler app_handler);

  // Block until every member of `group` reached the barrier.
  void Barrier(int group);

  void Finalize();  // graceful: scheduler broadcasts SHUTDOWN

  // Invoked (on a van thread) when a fleet-wide SHUTDOWN arrives at a
  // non-scheduler node — lets the KV layer fail fast on in-flight work
  // instead of hanging when a peer died (failure detection, SURVEY.md §5).
  void SetShutdownCallback(std::function<void()> cb) {
    shutdown_cb_ = std::move(cb);
  }

  // Invoked (on a van thread) when the connection to a known peer node
  // drops while the fleet is running — the fast-fail signal for that
  // node's in-flight requests (heartbeat timeout is the slow fallback).
  // With the retry layer on (BYTEPS_RETRY_MAX > 0) this only fires after
  // reconnect-with-backoff exhausted its attempts: a transient reset is
  // absorbed in-band, only a persistent fault escalates.
  void SetPeerLostCallback(std::function<void(int node_id)> cb) {
    peer_lost_cb_ = std::move(cb);
  }

  // Invoked (on a van thread) after a lost worker->server connection was
  // re-established (transient fault absorbed): the KV layer resends that
  // node's in-flight requests over the fresh connection immediately
  // instead of waiting out their retry timeouts.
  void SetPeerReconnectedCallback(std::function<void(int node_id)> cb) {
    peer_reconnected_cb_ = std::move(cb);
  }

  // True once this node received (or itself triggered) a FAILURE
  // shutdown — the scheduler's dead-node broadcast (CMD_SHUTDOWN
  // arg0=1) or a lost scheduler connection — as opposed to the clean
  // all-workers-said-goodbye teardown. Server/scheduler entry points
  // exit nonzero on it so a supervisor can tell crash from completion.
  bool FailureShutdown() const { return failure_shutdown_.load(); }

  // --- topology queries ---
  int my_id() const { return my_id_; }
  Role role() const { return role_; }
  int num_workers() const { return num_workers_; }
  int num_servers() const { return num_servers_; }
  // node ids: scheduler 0, servers 1..S, workers S+1..S+W
  static int ServerId(int s) { return 1 + s; }
  int WorkerId(int w) const { return 1 + num_servers_ + w; }
  int my_worker_rank() const { return my_id_ - 1 - num_servers_; }
  // fd of the connection to a node (workers: scheduler + all servers).
  int FdOf(int node_id);
  // Striped variant (BYTEPS_VAN_STREAMS): the stream for `key`, chosen by
  // key hash so one key's traffic — and therefore its request ordering —
  // stays on one TCP connection. Falls back to the primary fd when no
  // extra stripes were dialed (control paths always use FdOf(node)).
  int FdOf(int node_id, int64_t key);

  Van& van() { return *van_; }
  bool ShuttingDown() const { return shutting_down_.load(); }
  // Worker/server ids the scheduler considers dead (missed heartbeats).
  std::vector<int> DeadNodes();
  // Scheduler-side heartbeat freshness: (node id, ms since last beat)
  // for every tracked node, sorted by id — the monitor snapshot's
  // health signal (a cleanly-departed node is not tracked).
  std::vector<std::pair<int, int64_t>> HeartbeatAges();

 private:
  void ControlHandler(Message&& msg, int fd);
  void HeartbeatLoop();
  // Re-dial a lost worker->server connection (stripe `stripe`; 0 =
  // primary) with capped exponential backoff (BYTEPS_RECONNECT_MAX /
  // BYTEPS_RECONNECT_BACKOFF_MS). On success the fresh fd replaces the
  // dead one in node_fd_/node_extra_fds_ and the worker re-identifies
  // itself (CMD_REGISTER hello, as at stripe dial time). Runs on the
  // dead connection's recv thread, before its CloseConn.
  bool TryReconnect(int node_id, int stripe);

  std::unique_ptr<Van> van_;
  AppHandler app_handler_;
  Role role_ = ROLE_WORKER;
  int my_id_ = -1;
  int num_workers_ = 0;
  int num_servers_ = 0;
  std::atomic<bool> shutting_down_{false};
  std::atomic<bool> failure_shutdown_{false};

  std::mutex mu_;
  std::condition_variable cv_;
  std::vector<NodeInfo> nodes_;            // address book (set by ADDRBOOK)
  std::unordered_map<int, int> node_fd_;   // node id -> primary conn fd
  // node id -> extra striped data connections (BYTEPS_VAN_STREAMS > 1);
  // worker->server only. Stripe s of key k: s = k % streams, stripe 0 =
  // primary fd, stripe s>0 = extra[s-1].
  std::unordered_map<int, std::vector<int>> node_extra_fds_;
  bool addrbook_ready_ = false;

  // scheduler state
  struct PendingReg { int fd; NodeInfo info; };
  std::vector<PendingReg> pending_regs_;
  std::map<int, int> barrier_counts_;      // group -> count
  std::unordered_map<int, int64_t> last_heartbeat_ms_;  // node id -> ts
  std::unordered_set<int> departed_;       // clean goodbyes: never "dead"
  int barrier_acks_needed_ = 0;

  // client-side barrier wait state
  std::map<int, int> barrier_done_;        // group -> generation

  std::thread heartbeat_thread_;
  std::thread monitor_thread_;  // scheduler: dead-node detection
  std::function<void()> shutdown_cb_;
  std::function<void(int)> peer_lost_cb_;
  std::function<void(int)> peer_reconnected_cb_;
};

int64_t NowMs();

// BYTEPS_RETRY_MAX > 0 (default 4): the transient-fault tolerance master
// switch shared by the van reconnect path (postoffice.cc) and the KV
// retry layer (kv.h). 0 = pre-retry fail-fast behavior.
bool RetryEnabled();

}  // namespace bps
