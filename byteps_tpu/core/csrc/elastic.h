// Elastic worker membership bookkeeping (ISSUE 8).
//
// Two small header-only pieces the server composes per key:
//
//  - RosterHistory: the fleet's per-epoch expected-contributor sets,
//    keyed by ACTIVATION ROUND. A join activates at `join_round` (the
//    max round counter any worker had issued when the fleet gated new
//    rounds), so rounds already in flight complete against the OLD
//    worker set while every round >= join_round expects the joiner too.
//    A removal (graceful leave or death shrink) applies to EVERY epoch:
//    a leaver drained before leaving (it is in no incomplete round) and
//    a dead worker's partial contributions are discarded by the rollback
//    — so after removal no incomplete round can legitimately expect the
//    departed id.
//
//  - ElasticSlot: one key-slot's contribution roster — which senders
//    pushed/pulled this round, and (until the round completes) a
//    retained copy of each sender's DECODED contribution so a death
//    shrink can discard the departed worker's partial sum and rebuild
//    the aggregate from the survivors' bytes exactly. Memory cost while
//    armed: up to (live workers) x key bytes per in-flight round per
//    key, freed the moment the round completes (SealPushes).
//
// Both are deliberately standalone (no server/postoffice dependency) so
// the epoch-roster and rollback arithmetic are unit-testable through
// the bps_elastic_probe FFI hook without standing up a fleet.
#pragma once

#include <algorithm>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <vector>

#include "cpu_reducer.h"

namespace bps {

// Per-epoch expected-contributor sets, looked up by round number.
// Thread-safe: the van thread mutates on membership changes, engine
// threads read per push/pull. Sets are shared_ptr-immutable so a read
// is one lock + one pointer copy.
class RosterHistory {
 public:
  using Roster = std::shared_ptr<const std::set<int>>;

  // Install the initial membership (activation round 0 for both the
  // push/pull round space and the broadcast round space).
  void Init(const std::set<int>& live) {
    std::lock_guard<std::mutex> lk(mu_);
    epochs_.clear();
    epochs_.push_back({0, 0, std::make_shared<const std::set<int>>(live)});
  }

  // A joiner enters at `join_round` / `bcast_round`: rounds at or past
  // the activation expect it, earlier in-flight rounds do not.
  void Join(int id, int64_t join_round, int64_t bcast_round) {
    std::lock_guard<std::mutex> lk(mu_);
    std::set<int> next(*Cur());
    next.insert(id);
    epochs_.push_back({join_round, bcast_round,
                       std::make_shared<const std::set<int>>(next)});
    // Bounded history: rounds older than the 8th-last activation are
    // long completed (the double-buffered slots retire rounds within
    // one parity cycle of the fleet's progress).
    while (epochs_.size() > 8) epochs_.erase(epochs_.begin());
  }

  // A removal applies to EVERY epoch (see the file comment): the
  // departed id is erased from all rosters, past and current.
  void Remove(int id) {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& e : epochs_) {
      if (!e.live->count(id)) continue;
      std::set<int> next(*e.live);
      next.erase(id);
      e.live = std::make_shared<const std::set<int>>(next);
    }
  }

  // Expected contributors for push/pull round `round`.
  Roster OfRound(int64_t round) const {
    std::lock_guard<std::mutex> lk(mu_);
    Roster out = epochs_.empty() ? EmptyRoster() : epochs_.front().live;
    for (const auto& e : epochs_) {
      if (e.act_round <= round) out = e.live;
    }
    return out;
  }

  // Expected participants for broadcast round `round` (broadcasts count
  // in their own round space; a join carries both activation points).
  Roster OfBcast(int64_t round) const {
    std::lock_guard<std::mutex> lk(mu_);
    Roster out = epochs_.empty() ? EmptyRoster() : epochs_.front().live;
    for (const auto& e : epochs_) {
      if (e.act_bcast <= round) out = e.live;
    }
    return out;
  }

  Roster Current() const {
    std::lock_guard<std::mutex> lk(mu_);
    return Cur();
  }

 private:
  struct Epoch {
    int64_t act_round;
    int64_t act_bcast;
    Roster live;
  };
  static Roster EmptyRoster() {
    static const Roster empty = std::make_shared<const std::set<int>>();
    return empty;
  }
  Roster Cur() const {
    return epochs_.empty() ? EmptyRoster() : epochs_.back().live;
  }
  mutable std::mutex mu_;
  std::vector<Epoch> epochs_;
};

// One key-slot's contribution roster. Touched only by the key's engine
// thread (the server's hash routing), so no internal locking.
class ElasticSlot {
 public:
  // Record an applied push: the sender joined the round's contributor
  // set, and its decoded bytes are retained until the round completes
  // (the rollback's rebuild source).
  void Push(int sender, const char* data, int64_t len) {
    pushers_.insert(sender);
    if (data) contribs_[sender].assign(data, data + len);
  }

  void Pull(int sender) { pullers_.insert(sender); }

  bool HasPusher(int sender) const { return pushers_.count(sender) > 0; }

  // The round is complete when its contributor set EQUALS the roster —
  // exact match, not superset: during a shrink the roster loses the
  // departed id before the rollback discards its contribution, and a
  // superset check would let a survivor's queued push complete the
  // round with the dead worker's bytes still in the sum.
  bool PushersMatch(const std::set<int>& roster) const {
    return pushers_ == roster;
  }

  // The round is fully served when every roster member pulled. COVER,
  // not match: a departed worker may legitimately have pulled before it
  // left, and its extra entry must not block the recycle.
  bool PullersCover(const std::set<int>& roster) const {
    return std::includes(pullers_.begin(), pullers_.end(),
                         roster.begin(), roster.end());
  }

  // Death shrink: discard the departed worker's partial contribution.
  // Returns true when it had one (the caller must then RebuildSum and
  // re-evaluate completion against the shrunk roster).
  bool Remove(int sender) {
    bool had = pushers_.erase(sender) > 0;
    contribs_.erase(sender);
    pullers_.erase(sender);
    return had;
  }

  // Re-sum the surviving contributions into `dst` (ascending sender id
  // — deterministic; exact for the integer-valued floats the elastic
  // acceptance pins, reorder-tolerant within float addition otherwise).
  // Returns false when there is nothing left (caller resets the slot).
  bool RebuildSum(char* dst, int64_t len, int32_t dtype) const {
    bool first = true;
    for (const auto& kv : contribs_) {
      if (static_cast<int64_t>(kv.second.size()) != len) continue;
      if (first) {
        memcpy(dst, kv.second.data(), len);
        first = false;
      } else {
        CpuReducer::Sum(dst, kv.second.data(), len, dtype);
      }
    }
    return !first;
  }

  // Round complete: drop the contribution copies (completed rounds are
  // never rolled back — they belong to the epoch they completed in).
  void SealPushes() { contribs_.clear(); }

  // Slot recycled for the next round of this parity.
  void Reset() {
    pushers_.clear();
    pullers_.clear();
    contribs_.clear();
  }

  int pusher_count() const { return static_cast<int>(pushers_.size()); }
  const std::set<int>& pushers() const { return pushers_; }
  const std::set<int>& pullers() const { return pullers_; }

 private:
  std::set<int> pushers_, pullers_;
  std::map<int, std::vector<char>> contribs_;  // sender -> decoded bytes
};

}  // namespace bps
