#include "ckpt.h"

#include <dirent.h>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <cstdio>
#include <cstring>

#include "events.h"
#include "logging.h"
#include "metrics.h"
#include "worker.h"  // NowUs

namespace bps {

// CRC32C lives in crc32c.cc (shared with the van's wire trailer and
// snapshot serving verification — ISSUE 19); ckpt.h re-exports it.

// --- filesystem helpers ------------------------------------------------------

namespace {

constexpr const char* kManifest = "MANIFEST";

std::string CkptDirName(int64_t version, int rank) {
  char buf[64];
  snprintf(buf, sizeof(buf), "ckpt_v%lld_s%d",
           static_cast<long long>(version), rank);
  return buf;
}

std::string ChunkName(size_t idx) {
  char buf[32];
  snprintf(buf, sizeof(buf), "chunk_%zu.bin", idx);
  return buf;
}

bool FsyncPath(const std::string& path, std::string* why) {
  int fd = open(path.c_str(), O_RDONLY);
  if (fd < 0) {
    if (why) *why += "fsync open(" + path + "): " + strerror(errno) + "; ";
    return false;
  }
  const bool ok = fsync(fd) == 0;
  if (!ok && why) *why += "fsync(" + path + "): " + strerror(errno) + "; ";
  close(fd);
  return ok;
}

// tmp -> write -> fsync -> atomic rename. The rename is the commit
// point: a crash before it leaves only a dot-tmp file that scan ignores
// and retention sweeps.
bool WriteFileAtomic(const std::string& dir, const std::string& name,
                     const char* data, size_t len, std::string* why) {
  const std::string tmp = dir + "/." + name + ".tmp";
  const std::string fin = dir + "/" + name;
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) {
    if (why) *why += "open(" + tmp + "): " + strerror(errno) + "; ";
    return false;
  }
  size_t off = 0;
  while (off < len) {
    ssize_t n = write(fd, data + off, len - off);
    if (n < 0) {
      if (errno == EINTR) continue;
      if (why) *why += "write(" + tmp + "): " + strerror(errno) + "; ";
      close(fd);
      unlink(tmp.c_str());
      return false;
    }
    off += static_cast<size_t>(n);
  }
  if (fsync(fd) != 0) {
    if (why) *why += "fsync(" + tmp + "): " + strerror(errno) + "; ";
    close(fd);
    unlink(tmp.c_str());
    return false;
  }
  close(fd);
  if (rename(tmp.c_str(), fin.c_str()) != 0) {
    if (why) *why += "rename(" + tmp + "): " + strerror(errno) + "; ";
    unlink(tmp.c_str());
    return false;
  }
  return true;
}

bool ReadFileAll(const std::string& path, std::vector<char>* out) {
  int fd = open(path.c_str(), O_RDONLY);
  if (fd < 0) return false;
  out->clear();
  char buf[1 << 16];
  ssize_t n;
  while ((n = read(fd, buf, sizeof(buf))) > 0) {
    out->insert(out->end(), buf, buf + n);
  }
  close(fd);
  return n == 0;
}

void RemoveDirRecursive(const std::string& path) {
  DIR* d = opendir(path.c_str());
  if (d) {
    struct dirent* e;
    while ((e = readdir(d)) != nullptr) {
      if (strcmp(e->d_name, ".") == 0 || strcmp(e->d_name, "..") == 0) {
        continue;
      }
      unlink((path + "/" + e->d_name).c_str());
    }
    closedir(d);
  }
  rmdir(path.c_str());
}

// Parsed manifest: header fields + per-chunk records.
struct ManifestItem {
  size_t idx = 0;
  long long tenant = 0, key = 0, version = -1;
  int dtype = 0;
  long long len = 0;
  uint32_t crc = 0;
};

struct Manifest {
  int64_t version = -1;
  int rank = -1;
  int num_workers = 0, num_servers = 0;
  size_t items = 0;
  std::vector<ManifestItem> entries;
  uint32_t digest = 0;
};

// Parse + verify the seal CRC. The seal line covers every byte that
// precedes it, so a truncated, appended-to, or bit-flipped manifest is
// detectably torn before any field is believed.
bool ParseManifest(const std::vector<char>& raw, Manifest* m,
                   std::string* why) {
  const std::string text(raw.begin(), raw.end());
  const size_t seal_pos = text.rfind("\nseal ");
  if (seal_pos == std::string::npos) {
    if (why) *why += "manifest has no seal line (torn write?); ";
    return false;
  }
  unsigned long long seal = 0;
  if (sscanf(text.c_str() + seal_pos + 1, "seal %llx", &seal) != 1) {
    if (why) *why += "manifest seal line unparseable; ";
    return false;
  }
  // The sealed region includes the newline before the seal line.
  const uint32_t got = Crc32c(text.data(), seal_pos + 1);
  if (got != static_cast<uint32_t>(seal)) {
    char b[96];
    snprintf(b, sizeof(b),
             "manifest seal CRC mismatch (recorded %08llx, computed "
             "%08x); ", seal, got);
    if (why) *why += b;
    return false;
  }
  // Line-by-line fields.
  size_t pos = 0;
  bool saw_magic = false;
  while (pos < seal_pos) {
    size_t end = text.find('\n', pos);
    if (end == std::string::npos || end > seal_pos) end = seal_pos;
    const std::string line = text.substr(pos, end - pos);
    pos = end + 1;
    if (line.empty()) continue;
    long long a = 0, b2 = 0, c = 0, d = 0;
    int e = 0;
    long long f = 0;
    unsigned long long g = 0;
    if (line.rfind("bpsckpt ", 0) == 0) {
      saw_magic = line == "bpsckpt 1";
    } else if (sscanf(line.c_str(), "version %lld", &a) == 1) {
      m->version = a;
    } else if (sscanf(line.c_str(), "rank %lld", &a) == 1) {
      m->rank = static_cast<int>(a);
    } else if (sscanf(line.c_str(), "fleet %lld %lld", &a, &b2) == 2) {
      m->num_workers = static_cast<int>(a);
      m->num_servers = static_cast<int>(b2);
    } else if (sscanf(line.c_str(), "items %lld", &a) == 1) {
      m->items = static_cast<size_t>(a);
    } else if (sscanf(line.c_str(),
                      "item %lld %lld %lld %lld %d %lld %llx", &a, &b2,
                      &c, &d, &e, &f, &g) == 7) {
      ManifestItem it;
      it.idx = static_cast<size_t>(a);
      it.tenant = b2;
      it.key = c;
      it.version = d;
      it.dtype = e;
      it.len = f;
      it.crc = static_cast<uint32_t>(g);
      m->entries.push_back(it);
    } else if (sscanf(line.c_str(), "digest %llx", &g) == 1) {
      m->digest = static_cast<uint32_t>(g);
    } else {
      if (why) *why += "manifest line unrecognized: '" + line + "'; ";
      return false;
    }
  }
  if (!saw_magic) {
    if (why) *why += "manifest magic missing/unknown; ";
    return false;
  }
  if (m->entries.size() != m->items) {
    if (why) *why += "manifest item count mismatch; ";
    return false;
  }
  return true;
}

// Full validation of one checkpoint directory: sealed manifest + every
// chunk present with its recorded length and CRC32C.
bool ValidateCkpt(const std::string& path, int rank, int64_t version,
                  Manifest* m, std::string* why) {
  std::vector<char> raw;
  if (!ReadFileAll(path + "/" + kManifest, &raw)) {
    if (why) *why += path + ": manifest missing/unreadable; ";
    return false;
  }
  if (!ParseManifest(raw, m, why)) {
    if (why) *why += path + ": manifest invalid; ";
    return false;
  }
  if (m->version != version || m->rank != rank) {
    if (why) {
      *why += path + ": manifest names version " +
              std::to_string(static_cast<long long>(m->version)) +
              " rank " + std::to_string(m->rank) +
              " (directory says otherwise); ";
    }
    return false;
  }
  uint32_t digest = 0;
  std::vector<char> data;
  for (const auto& it : m->entries) {
    const std::string cpath = path + "/" + ChunkName(it.idx);
    if (!ReadFileAll(cpath, &data)) {
      if (why) *why += cpath + ": chunk missing/unreadable; ";
      return false;
    }
    if (static_cast<long long>(data.size()) != it.len) {
      if (why) {
        *why += cpath + ": chunk length " +
                std::to_string(data.size()) + " != recorded " +
                std::to_string(it.len) + " (truncated?); ";
      }
      return false;
    }
    const uint32_t crc = Crc32c(data.data(), data.size());
    if (crc != it.crc) {
      char b[96];
      snprintf(b, sizeof(b),
               ": chunk CRC32C mismatch (recorded %08x, computed "
               "%08x); ", it.crc, crc);
      if (why) *why += cpath + b;
      return false;
    }
    digest = Crc32c(&crc, sizeof(crc), digest);
  }
  if (digest != m->digest) {
    if (why) *why += path + ": checkpoint digest mismatch; ";
    return false;
  }
  return true;
}

// All on-disk candidate versions for `rank` (no validation), ascending.
std::vector<int64_t> CandidateVersions(const std::string& dir, int rank) {
  std::vector<int64_t> out;
  DIR* d = opendir(dir.c_str());
  if (!d) return out;
  struct dirent* e;
  while ((e = readdir(d)) != nullptr) {
    long long v = -1;
    int r = -1;
    if (sscanf(e->d_name, "ckpt_v%lld_s%d", &v, &r) == 2 && r == rank &&
        v >= 0) {
      out.push_back(v);
    }
  }
  closedir(d);
  std::sort(out.begin(), out.end());
  return out;
}

}  // namespace

// --- synchronous core --------------------------------------------------------

bool CkptSpillSync(const std::string& dir, int rank, int64_t version,
                   const std::vector<SnapDeltaEnt>& cut, int num_workers,
                   int num_servers, const std::string& chaos,
                   std::string* why) {
  mkdir(dir.c_str(), 0755);  // single level; EEXIST is the common case
  const std::string path = dir + "/" + CkptDirName(version, rank);
  // A directory from a crashed prior attempt (no valid manifest) is
  // debris: wipe and rewrite. Overwriting a SEALED checkpoint is
  // idempotent (same cut, same bytes), so no special case.
  RemoveDirRecursive(path);
  if (mkdir(path.c_str(), 0755) != 0) {
    if (why) *why += "mkdir(" + path + "): " + strerror(errno) + "; ";
    return false;
  }
  std::string manifest = "bpsckpt 1\n";
  manifest += "version " + std::to_string(static_cast<long long>(version)) +
              "\n";
  manifest += "rank " + std::to_string(rank) + "\n";
  manifest += "fleet " + std::to_string(num_workers) + " " +
              std::to_string(num_servers) + "\n";
  manifest += "items " + std::to_string(cut.size()) + "\n";
  uint32_t digest = 0;
  for (size_t i = 0; i < cut.size(); ++i) {
    const auto& d = cut[i];
    const auto& raw = *d.entry.raw;
    if (!WriteFileAtomic(path, ChunkName(i), raw.data(), raw.size(),
                         why)) {
      return false;
    }
    const uint32_t crc = Crc32c(raw.data(), raw.size());
    digest = Crc32c(&crc, sizeof(crc), digest);
    char line[160];
    snprintf(line, sizeof(line), "item %zu %lld %lld %lld %d %lld %08x\n",
             i, static_cast<long long>(d.tenant),
             static_cast<long long>(d.key),
             static_cast<long long>(d.entry.version), d.entry.dtype,
             static_cast<long long>(raw.size()), crc);
    manifest += line;
  }
  char dl[32];
  snprintf(dl, sizeof(dl), "digest %08x\n", digest);
  manifest += dl;
  // Chaos injection (BYTEPS_CHAOS_CKPT): corrupt a seeded-random chunk
  // AFTER its CRC was recorded and BEFORE the manifest seals the
  // checkpoint — the exact torn-write window a crash mid-spill exposes
  // (chunk 0 alone, the pre-ISSUE-19 target, would never exercise the
  // scan's per-chunk verification past the first item). Deterministic
  // per (seed, version) so probe tests can name the victim. Scan/load
  // must reject this checkpoint by name, never install it.
  if (!chaos.empty() && !cut.empty() && chaos != "sealflip") {
    uint64_t z = static_cast<uint64_t>(version) * 0x9E3779B97F4A7C15ull;
    if (const char* sv = getenv("BYTEPS_CHAOS_SEED")) {
      z += static_cast<uint64_t>(atoll(sv));
    }
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    const size_t victim = static_cast<size_t>((z ^ (z >> 31)) % cut.size());
    const std::string cv = path + "/" + ChunkName(victim);
    if (chaos == "truncate") {
      const long long half =
          static_cast<long long>(cut[victim].entry.raw->size()) / 2;
      if (truncate(cv.c_str(), half) != 0 && why) {
        *why += "chaos truncate failed: " + std::string(strerror(errno)) +
                "; ";
      }
    } else if (chaos == "bitflip") {
      int fd = open(cv.c_str(), O_RDWR);
      if (fd >= 0) {
        char b = 0;
        if (pread(fd, &b, 1, 0) == 1) {
          b ^= 0x01;
          (void)!pwrite(fd, &b, 1, 0);
          fsync(fd);
        }
        close(fd);
      }
    }
    BPS_LOG(WARNING) << "ckpt: CHAOS corrupted chunk " << victim
                     << " of version " << version << " (" << chaos
                     << ") pre-seal";
  }
  // The seal covers every manifest byte BEFORE the seal line itself
  // (ParseManifest recomputes over exactly that region).
  char sl[24];
  snprintf(sl, sizeof(sl), "seal %08x\n",
           Crc32c(manifest.data(), manifest.size()));
  manifest += sl;
  if (!WriteFileAtomic(path, kManifest, manifest.data(), manifest.size(),
                       why)) {
    return false;
  }
  // Chaos "sealflip" (ISSUE 19): corrupt the sealed MANIFEST itself —
  // every chunk is intact, but the manifest's own integrity line no
  // longer matches its body. The restore scan must reject the version
  // on the seal check alone, before it ever reads a chunk.
  if (chaos == "sealflip") {
    const std::string mf = path + "/" + std::string(kManifest);
    int fd = open(mf.c_str(), O_RDWR);
    if (fd >= 0) {
      char b = 0;
      if (pread(fd, &b, 1, 0) == 1) {
        b ^= 0x01;
        (void)!pwrite(fd, &b, 1, 0);
        fsync(fd);
      }
      close(fd);
    }
    BPS_LOG(WARNING) << "ckpt: CHAOS corrupted the MANIFEST seal of "
                        "version " << version << " (sealflip)";
  }
  // Durability of the renames themselves: fsync the checkpoint dir and
  // its parent so the directory entries survive power loss too.
  FsyncPath(path, why);
  FsyncPath(dir, why);
  return true;
}

int64_t CkptScan(const std::string& dir, int rank, std::string* why) {
  const auto versions = CandidateVersions(dir, rank);
  for (auto it = versions.rbegin(); it != versions.rend(); ++it) {
    Manifest m;
    if (ValidateCkpt(dir + "/" + CkptDirName(*it, rank), rank, *it, &m,
                     why)) {
      return *it;
    }
    // Invalid candidate: the diagnostic is in *why; fall back to the
    // next-older version — a torn NEWEST checkpoint must never shadow
    // a complete prior one.
  }
  return -1;
}

std::vector<int64_t> CkptList(const std::string& dir, int rank) {
  std::vector<int64_t> out;
  for (int64_t v : CandidateVersions(dir, rank)) {
    Manifest m;
    std::string why;
    if (ValidateCkpt(dir + "/" + CkptDirName(v, rank), rank, v, &m,
                     &why)) {
      out.push_back(v);
    }
  }
  return out;
}

bool CkptLoad(const std::string& dir, int rank, int64_t version,
              std::vector<CkptItem>* items, int64_t* round,
              std::string* why) {
  const std::string path = dir + "/" + CkptDirName(version, rank);
  Manifest m;
  if (!ValidateCkpt(path, rank, version, &m, why)) return false;
  items->clear();
  items->reserve(m.entries.size());
  for (const auto& it : m.entries) {
    CkptItem out;
    out.tenant = static_cast<uint16_t>(it.tenant);
    out.key = it.key;
    out.version = it.version;
    out.dtype = it.dtype;
    if (!ReadFileAll(path + "/" + ChunkName(it.idx), &out.data) ||
        static_cast<long long>(out.data.size()) != it.len ||
        Crc32c(out.data.data(), out.data.size()) != it.crc) {
      // Validate-then-read raced a concurrent mutation (or the disk is
      // actively failing): same verdict as a torn checkpoint.
      if (why) {
        *why += path + "/" + ChunkName(it.idx) +
                ": re-read failed validation; ";
      }
      return false;
    }
    items->push_back(std::move(out));
  }
  if (round) *round = m.version;
  return true;
}

void CkptRetain(const std::string& dir, int rank, int retain) {
  if (retain < 1) retain = 1;
  const auto versions = CandidateVersions(dir, rank);
  if (static_cast<int>(versions.size()) > retain) {
    for (size_t i = 0; i + retain < versions.size(); ++i) {
      RemoveDirRecursive(dir + "/" + CkptDirName(versions[i], rank));
    }
  }
  // Dot-tmp debris from crashed spills (never referenced by any sealed
  // manifest) is swept alongside.
  DIR* d = opendir(dir.c_str());
  if (!d) return;
  struct dirent* e;
  while ((e = readdir(d)) != nullptr) {
    if (e->d_name[0] == '.' && strstr(e->d_name, ".tmp") != nullptr) {
      unlink((dir + "/" + e->d_name).c_str());
    }
  }
  closedir(d);
}

// --- async writer ------------------------------------------------------------

void CkptWriter::Start(const std::string& dir, int rank, int every,
                       int retain, const std::string& chaos,
                       int num_workers, int num_servers) {
  bool expect = false;
  if (!running_.compare_exchange_strong(expect, true)) return;
  dir_ = dir;
  rank_ = rank;
  every_ = every < 1 ? 1 : every;
  retain_ = retain < 1 ? 1 : retain;
  chaos_ = chaos;
  num_workers_ = num_workers;
  num_servers_ = num_servers;
  stop_.store(false);
  thread_ = std::thread([this] { Loop(); });
  BPS_LOG(INFO) << "ckpt: durable spill armed (dir " << dir_ << ", rank "
                << rank_ << ", every " << every_ << " version(s), retain "
                << retain_ << ")";
}

void CkptWriter::Stop() {
  if (!running_.load()) return;
  {
    std::lock_guard<std::mutex> lk(mu_);
    stop_.store(true);
  }
  cv_.notify_all();
  if (thread_.joinable()) thread_.join();
  running_.store(false);
}

bool CkptWriter::ShouldSpill(int64_t version) {
  if (!running_.load() || version < 0 || version % every_ != 0) {
    return false;
  }
  int64_t prev = claimed_.load();
  while (version > prev) {
    if (claimed_.compare_exchange_weak(prev, version)) return true;
  }
  return false;
}

void CkptWriter::Enqueue(int64_t version,
                         std::vector<SnapDeltaEnt>&& cut) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    queue_.emplace_back(version, std::move(cut));
  }
  cv_.notify_one();
}

void CkptWriter::Loop() {
  while (true) {
    std::pair<int64_t, std::vector<SnapDeltaEnt>> job;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [this] { return stop_.load() || !queue_.empty(); });
      // Drain what was enqueued before stop: a clean shutdown mid-queue
      // must not abandon a claimed version.
      if (queue_.empty()) return;
      job = std::move(queue_.front());
      queue_.pop_front();
    }
    const int64_t t0 = NowUs();
    Events::Get().Emit(EV_CKPT_SPILL, job.first,
                       static_cast<int64_t>(job.second.size()));
    std::string why;
    if (CkptSpillSync(dir_, rank_, job.first, job.second, num_workers_,
                      num_servers_, chaos_, &why)) {
      last_spilled_.store(job.first);
      spills_.fetch_add(1);
      const int64_t ms = (NowUs() - t0) / 1000;
      last_spill_ms_.store(ms);
      BPS_METRIC_GAUGE_SET("bps_ckpt_version", job.first);
      BPS_METRIC_COUNTER_ADD("bps_ckpt_spills_total", 1);
      BPS_METRIC_GAUGE_SET("bps_ckpt_spill_ms", ms);
      Events::Get().Emit(EV_CKPT_SEAL, job.first, ms, /*ok=*/1);
      CkptRetain(dir_, rank_, retain_);
    } else {
      failures_.fetch_add(1);
      BPS_METRIC_COUNTER_ADD("bps_ckpt_failures_total", 1);
      Events::Get().Emit(EV_CKPT_SEAL, job.first, 0, /*ok=*/0);
      BPS_LOG(WARNING) << "ckpt: spill of version " << job.first
                     << " FAILED: " << why;
    }
  }
}

}  // namespace bps
