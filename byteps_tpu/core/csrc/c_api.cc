// extern "C" surface loaded by byteps_tpu.core.ffi via ctypes.
//
// Capability parity: reference byteps/common/operations.{h,cc} public C
// entry points (byteps_init / byteps_declare_tensor / EnqueueTensor /
// byteps_rank / ...; SURVEY.md §2.1) — env-var configured exactly like the
// reference (DMLC_* / BYTEPS_* families, docs/ENV.md).
#include <chrono>
#include <cstdio>
#include <cstring>
#include <memory>
#include <string>
#include <vector>

#include "common.h"
#include "cpu_reducer.h"
#include "debug.h"
#include "kv.h"
#include "logging.h"
#include "postoffice.h"
#include "server.h"
#include "worker.h"

namespace {

using namespace bps;

struct Global {
  std::unique_ptr<Postoffice> po;
  std::unique_ptr<KVWorker> kv;
  std::unique_ptr<BytePSServer> server;
  std::unique_ptr<BytePSWorker> worker;
  Role role = ROLE_WORKER;
  bool inited = false;

  // Scripts that skip bps_finalize (no explicit shutdown) reach this
  // destructor with everything still live. Members are destroyed in
  // reverse declaration order, which would free the KVWorker BEFORE
  // ~Postoffice runs the goodbye protocol — whose SHUTDOWN handling
  // fires shutdown_cb_ -> kv->FailAllPending() on a van recv thread,
  // a use-after-free that wedges that thread on a garbage mutex and
  // deadlocks the van join (observed as workers hanging at exit).
  // Finalize in dependency order here instead; ~Postoffice's own
  // Finalize call is then an idempotent no-op.
  ~Global() {
    if (!inited) return;
    // Drain the callback executor FIRST: queued completions touch the
    // BytePSWorker (credit release, handle counts), which is destroyed
    // before the KVWorker in reverse member order.
    if (kv) kv->StopExec();
    if (worker) worker->Stop();
    if (po) po->Finalize();
    if (server) server->Stop();
    inited = false;
  }
};

Global* g() {
  static Global inst;
  return &inst;
}

int EnvInt(const char* name, int dflt) {
  const char* v = getenv(name);
  return v && *v ? atoi(v) : dflt;
}

int64_t EnvInt64(const char* name, int64_t dflt) {
  const char* v = getenv(name);
  return v && *v ? atoll(v) : dflt;
}

std::string EnvStr(const char* name, const char* dflt) {
  const char* v = getenv(name);
  return v && *v ? v : dflt;
}

bool EnvBool(const char* name) {
  const char* v = getenv(name);
  if (!v || !*v) return false;
  return strcmp(v, "0") != 0 && strcasecmp(v, "false") != 0;
}

// Build the default compressor config string from env (reference:
// byteps_compressor_type / _k / ef_type / momentum_type params).
std::string DefaultCompConfig() {
  std::string type = EnvStr("BYTEPS_COMPRESSOR", "");
  if (type.empty()) return "";
  if (type.find('=') != std::string::npos) {
    // Full config-string form ("type=onebit;ef=vanilla") — pass through
    // verbatim; the simple form below composes from the companion envs.
    return type;
  }
  std::string cfg = "type=" + type;
  int64_t k = EnvInt64("BYTEPS_COMPRESSOR_K", 0);
  if (k > 0) cfg += ";k=" + std::to_string(k);
  std::string ef = EnvStr("BYTEPS_ERROR_FEEDBACK", "");
  if (!ef.empty()) cfg += ";ef=" + ef;
  std::string mom = EnvStr("BYTEPS_MOMENTUM", "");
  if (!mom.empty()) {
    cfg += ";momentum=" + mom;
    cfg += ";mu=" + EnvStr("BYTEPS_MOMENTUM_MU", "0.9");
  }
  return cfg;
}

}  // namespace

extern "C" {

// role: 0 scheduler, 1 server, 2 worker (Role enum). Returns node id, <0 on
// error. All other configuration comes from the environment for parity with
// the reference (see byteps_tpu/config.py and docs/ENV.md).
int bps_init(int role) {
  InstallCrashHandler();
  Global* gl = g();
  BPS_CHECK(!gl->inited) << "bps_init called twice";
  // Fresh state per init so a process can re-init after finalize (tests).
  gl->worker.reset();
  gl->server.reset();
  gl->kv.reset();
  gl->po = std::make_unique<Postoffice>();
  gl->role = static_cast<Role>(role);
  std::string uri = EnvStr("DMLC_PS_ROOT_URI", "127.0.0.1");
  int port = EnvInt("DMLC_PS_ROOT_PORT", 9000);
  int nw = EnvInt("DMLC_NUM_WORKER", 1);
  int ns = EnvInt("DMLC_NUM_SERVER", 1);

  Postoffice::AppHandler handler;
  if (gl->role == ROLE_SERVER) {
    gl->server = std::make_unique<BytePSServer>();
    // Engine threads must exist BEFORE the postoffice starts accepting:
    // a fast worker can deliver INIT_KEY the moment the address book is
    // broadcast, racing a not-yet-started engine.
    gl->server->Start(gl->po.get(), EnvInt("BYTEPS_SERVER_ENGINE_THREAD", 4),
                      EnvBool("BYTEPS_ENABLE_ASYNC"));
    handler = [gl](Message&& m, int fd) {
      gl->server->Handle(std::move(m), fd);
    };
  } else if (gl->role == ROLE_WORKER) {
    gl->kv = std::make_unique<KVWorker>(
        gl->po.get(), EnvInt("BYTEPS_WORKER_CALLBACK_THREADS", 4));
    handler = [gl](Message&& m, int fd) {
      (void)fd;
      gl->kv->OnResponse(std::move(m));
    };
    gl->po->SetShutdownCallback([gl] { gl->kv->FailAllPending(); });
    gl->po->SetPeerLostCallback([gl](int node_id) {
      gl->kv->FailNode(node_id, "connection to node " +
                                    std::to_string(node_id) +
                                    " lost (peer died or was killed)");
    });
  }

  int id = gl->po->Start(gl->role, uri, port, nw, ns, std::move(handler));
  if (gl->role == ROLE_WORKER) {
    gl->worker = std::make_unique<BytePSWorker>();
    gl->worker->Start(gl->po.get(), gl->kv.get(),
                      EnvInt64("BYTEPS_PARTITION_BYTES", 4096000),
                      EnvInt64("BYTEPS_SCHEDULING_CREDIT", 0),
                      DefaultCompConfig(), EnvBool("BYTEPS_TRACE_ON"));
  }
  gl->inited = true;
  return id;
}

void bps_finalize() {
  Global* gl = g();
  if (!gl->inited) return;
  // Same drain-first order as ~Global (see its comment).
  if (gl->kv) gl->kv->StopExec();
  if (gl->worker) gl->worker->Stop();
  gl->po->Finalize();
  if (gl->server) gl->server->Stop();
  gl->inited = false;
}

int bps_my_id() { return g()->po->my_id(); }
int bps_worker_rank() { return g()->po->my_worker_rank(); }
int bps_num_workers() { return g()->po->num_workers(); }
int bps_num_servers() { return g()->po->num_servers(); }

void bps_barrier(int group) { g()->po->Barrier(group); }

long long bps_declare(const char* name, long long nelem, int dtype,
                      const char* comp_config) {
  return g()->worker->Declare(name, nelem, dtype,
                              comp_config ? comp_config : "__default__");
}

int bps_push_pull(long long tensor_id, void* ptr, long long nelem, int dtype,
                  int average, int async_mode) {
  return g()->worker->PushPull(tensor_id, ptr, nelem, dtype, average != 0,
                               async_mode != 0);
}

int bps_broadcast(long long tensor_id, void* ptr, long long nelem, int dtype,
                  int root) {
  return g()->worker->Broadcast(tensor_id, ptr, nelem, dtype, root);
}

// 0 = success; -1 = the handle failed fast (dead peer) — fetch the
// diagnostic with bps_last_error().
int bps_wait(int handle) { return g()->worker->Wait(handle); }
int bps_poll(int handle) { return g()->worker->Poll(handle); }

const char* bps_last_error() {
  static thread_local std::string err;
  err = g()->worker ? g()->worker->LastError() : "";
  return err.c_str();
}

// Dump accumulated trace events as Chrome trace-event JSON (reference:
// BYTEPS_TRACE_ON timeline, SURVEY.md §5). Returns number of events.
int bps_dump_trace(const char* path) {
  Global* gl = g();
  if (!gl->worker) return -1;
  auto events = gl->worker->DrainTrace();
  FILE* f = fopen(path, "w");
  if (!f) return -1;
  fprintf(f, "{\"traceEvents\":[\n");
  int rank = gl->po->my_worker_rank();
  for (size_t i = 0; i < events.size(); ++i) {
    const auto& e = events[i];
    fprintf(f,
            "{\"name\":\"%s\",\"ph\":\"X\",\"pid\":%d,\"tid\":%lld,"
            "\"ts\":%lld,\"dur\":%lld,\"args\":{\"key\":%lld}}%s\n",
            e.stage, rank, static_cast<long long>(e.key),
            static_cast<long long>(e.ts_us), static_cast<long long>(e.dur_us),
            static_cast<long long>(e.key), i + 1 < events.size() ? "," : "");
  }
  fprintf(f, "]}\n");
  fclose(f);
  return static_cast<int>(events.size());
}

// Standalone CpuReducer throughput probe: repeatedly sum a src buffer
// into dst (the server's hot loop) and return GB/s of summed INPUT
// bytes. Callable without any topology (SURVEY.md §7 hard part #5:
// server summation must not be the bottleneck — measure it).
double bps_reducer_bench(long long nbytes, int iters, int dtype) {
  if (nbytes <= 0 || iters <= 0 || DtypeSize(dtype) == 0) return -1.0;
  // 0x3C byte fill: normal-range values in every float format (fp16
  // 0x3C3C ~= 1.06, f32 0x3C3C3C3C ~= 0.011) — a 0x01 fill would make
  // fp16 lanes subnormal and measure the worst-case conversion branch
  // instead of typical gradient values.
  std::vector<char> dst(nbytes, 0x3C), src(nbytes, 0x3D);
  CpuReducer::Sum(dst.data(), src.data(), nbytes, dtype);  // warm
  auto t0 = std::chrono::steady_clock::now();
  for (int i = 0; i < iters; ++i) {
    CpuReducer::Sum(dst.data(), src.data(), nbytes, dtype);
  }
  double s = std::chrono::duration<double>(
                 std::chrono::steady_clock::now() - t0)
                 .count();
  return static_cast<double>(nbytes) * iters / s / 1e9;
}

// Cumulative DCN wire bytes through this node's van (frames + payloads).
// For bandwidth assertions (e.g. both push AND pull legs shrink under
// compression) and the timeline.
void bps_net_bytes(long long* sent, long long* recv) {
  Global* gl = g();
  *sent = gl->po ? gl->po->van().bytes_sent() : 0;
  *recv = gl->po ? gl->po->van().bytes_recv() : 0;
}

// Async-mode staleness stats (cumulative): per async pull, the number of
// fleet-wide pushes the server applied between this worker's push and
// its pull. samples==0 means no async pulls have completed.
void bps_async_staleness(double* mean, long long* max_, long long* n) {
  BytePSWorker* w = g()->worker.get();
  if (!w) {
    *mean = 0.0;
    *max_ = 0;
    *n = 0;
    return;
  }
  long long sum, cnt;
  w->StalenessStats(&sum, max_, &cnt);
  *n = cnt;
  *mean = cnt > 0 ? static_cast<double>(sum) / cnt : 0.0;
}

// Scheduler-side failure detection: ids of nodes with expired heartbeats.
int bps_dead_nodes(int* out, int max) {
  auto dead = g()->po->DeadNodes();
  int n = 0;
  for (int id : dead) {
    if (n >= max) break;
    out[n++] = id;
  }
  return n;
}

}  // extern "C"
