// extern "C" surface loaded by byteps_tpu.core.ffi via ctypes.
//
// Capability parity: reference byteps/common/operations.{h,cc} public C
// entry points (byteps_init / byteps_declare_tensor / EnqueueTensor /
// byteps_rank / ...; SURVEY.md §2.1) — env-var configured exactly like the
// reference (DMLC_* / BYTEPS_* families, docs/ENV.md).
#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <chrono>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <limits>
#include <memory>
#include <string>
#include <vector>

#include "ckpt.h"
#include "common.h"
#include "compressor.h"
#include "cpu_reducer.h"
#include "debug.h"
#include "elastic.h"
#include "events.h"
#include "kv.h"
#include "logging.h"
#include "metrics.h"
#include "postoffice.h"
#include "roundstats.h"
#include "server.h"
#include "snapshot.h"
#include "tenancy.h"
#include "trace.h"
#include "worker.h"

namespace {

using namespace bps;

struct Global {
  std::unique_ptr<Postoffice> po;
  std::unique_ptr<KVWorker> kv;
  std::unique_ptr<BytePSServer> server;
  std::unique_ptr<BytePSWorker> worker;
  Role role = ROLE_WORKER;
  bool inited = false;

  // Scripts that skip bps_finalize (no explicit shutdown) reach this
  // destructor with everything still live. Members are destroyed in
  // reverse declaration order, which would free the KVWorker BEFORE
  // ~Postoffice runs the goodbye protocol — whose SHUTDOWN handling
  // fires shutdown_cb_ -> kv->FailAllPending() on a van recv thread,
  // a use-after-free that wedges that thread on a garbage mutex and
  // deadlocks the van join (observed as workers hanging at exit).
  // Finalize in dependency order here instead; ~Postoffice's own
  // Finalize call is then an idempotent no-op.
  ~Global() {
    if (!inited) return;
    // Drain the callback executor FIRST: queued completions touch the
    // BytePSWorker (credit release, handle counts), which is destroyed
    // before the KVWorker in reverse member order.
    if (kv) kv->StopExec();
    if (worker) worker->Stop();
    if (po) po->Finalize();
    if (server) server->Stop();
    inited = false;
  }
};

Global* g() {
  static Global inst;
  return &inst;
}

int EnvInt(const char* name, int dflt) {
  const char* v = getenv(name);
  return v && *v ? atoi(v) : dflt;
}

int64_t EnvInt64(const char* name, int64_t dflt) {
  const char* v = getenv(name);
  return v && *v ? atoll(v) : dflt;
}

std::string EnvStr(const char* name, const char* dflt) {
  const char* v = getenv(name);
  return v && *v ? v : dflt;
}

bool EnvBool(const char* name) {
  const char* v = getenv(name);
  if (!v || !*v) return false;
  return strcmp(v, "0") != 0 && strcasecmp(v, "false") != 0;
}

// Build the default compressor config string from env (reference:
// byteps_compressor_type / _k / ef_type / momentum_type params).
std::string DefaultCompConfig() {
  std::string type = EnvStr("BYTEPS_COMPRESSOR", "");
  if (type.empty()) return "";
  if (type.find('=') != std::string::npos) {
    // Full config-string form ("type=onebit;ef=vanilla") — pass through
    // verbatim; the simple form below composes from the companion envs.
    return type;
  }
  std::string cfg = "type=" + type;
  int64_t k = EnvInt64("BYTEPS_COMPRESSOR_K", 0);
  if (k > 0) cfg += ";k=" + std::to_string(k);
  std::string ef = EnvStr("BYTEPS_ERROR_FEEDBACK", "");
  if (!ef.empty()) cfg += ";ef=" + ef;
  std::string mom = EnvStr("BYTEPS_MOMENTUM", "");
  if (!mom.empty()) {
    cfg += ";momentum=" + mom;
    cfg += ";mu=" + EnvStr("BYTEPS_MOMENTUM_MU", "0.9");
  }
  return cfg;
}

}  // namespace

extern "C" {

// role: 0 scheduler, 1 server, 2 worker, 3 read replica (Role enum).
// Returns node id, <0 on error. All other configuration comes from the
// environment for parity with the reference (see byteps_tpu/config.py
// and docs/ENV.md).
int bps_init(int role) {
  InstallCrashHandler();
  Global* gl = g();
  BPS_CHECK(!gl->inited) << "bps_init called twice";
  // Fresh state per init so a process can re-init after finalize (tests).
  gl->worker.reset();
  gl->server.reset();
  gl->kv.reset();
  gl->po = std::make_unique<Postoffice>();
  gl->role = static_cast<Role>(role);
  std::string uri = EnvStr("DMLC_PS_ROOT_URI", "127.0.0.1");
  int port = EnvInt("DMLC_PS_ROOT_PORT", 9000);
  int nw = EnvInt("DMLC_NUM_WORKER", 1);
  int ns = EnvInt("DMLC_NUM_SERVER", 1);

  Postoffice::AppHandler handler;
  if (gl->role == ROLE_SERVER) {
    gl->server = std::make_unique<BytePSServer>();
    // Engine threads must exist BEFORE the postoffice starts accepting:
    // a fast worker can deliver INIT_KEY the moment the address book is
    // broadcast, racing a not-yet-started engine.
    gl->server->Start(gl->po.get(), EnvInt("BYTEPS_SERVER_ENGINE_THREAD", 4),
                      EnvBool("BYTEPS_ENABLE_ASYNC"));
    handler = [gl](Message&& m, int fd) {
      gl->server->Handle(std::move(m), fd);
    };
    // Durable restore (ISSUE 18): the server scanned its checkpoint dir
    // in Start; arm the postoffice BEFORE registration so the durable
    // version rides this shard's CMD_REGISTER and the scheduler can
    // commit the fleet-wide restore epoch.
    if (gl->server->restore_armed()) {
      gl->po->SetDurableCkpt(gl->server->durable_ckpt_version());
    }
    // Elastic worker membership (ISSUE 8): membership epochs land here
    // — a join pushes a new contributor roster, a removal rolls the
    // in-flight rounds back onto the survivors.
    gl->po->SetFleetResizeCallback(
        [gl](int kind, int affected, int64_t jr, int64_t jb, int tenant) {
          gl->server->OnFleetResize(kind, affected, jr, jb, tenant);
        });
  } else if (gl->role == ROLE_REPLICA) {
    // Read replica (ISSUE 16): a server engine in replica mode — it
    // owns a SnapStore fed by primary deltas and the CMD_SNAP_* serve
    // path, but never aggregates (no worker ever dials it for pushes).
    // Same ordering rule as the server branch: engine threads before
    // the postoffice accepts.
    gl->server = std::make_unique<BytePSServer>();
    gl->server->Start(gl->po.get(),
                      EnvInt("BYTEPS_SERVER_ENGINE_THREAD", 4),
                      /*async_mode=*/false,
                      EnvInt("BYTEPS_REPLICA_OF", 0));
    handler = [gl](Message&& m, int fd) {
      gl->server->Handle(std::move(m), fd);
    };
  } else if (gl->role == ROLE_WORKER) {
    gl->kv = std::make_unique<KVWorker>(
        gl->po.get(), EnvInt("BYTEPS_WORKER_CALLBACK_THREADS", 4));
    handler = [gl](Message&& m, int fd) {
      (void)fd;
      gl->kv->OnResponse(std::move(m));
    };
    gl->po->SetShutdownCallback([gl] { gl->kv->FailAllPending(); });
    gl->po->SetPeerLostCallback([gl](int node_id) {
      gl->kv->FailNode(node_id, "connection to node " +
                                    std::to_string(node_id) +
                                    " lost (peer died or was killed)");
    });
    // Transient path: a reset server connection that re-dialled
    // successfully drains this node's resend queue over the fresh
    // socket immediately (ISSUE 3 reconnect-with-backoff).
    gl->po->SetPeerReconnectedCallback([gl](int node_id) {
      gl->kv->ResendNode(node_id);
    });
    // Hot server replacement (ISSUE 4): a dead server rank under
    // scheduler-coordinated recovery freezes its retry clocks; the
    // RESUME (replacement redialled) re-seeds the shard and drains the
    // parked resend queue.
    gl->po->SetPeerPausedCallback([gl](int node_id) {
      gl->kv->PauseNode(node_id);
    });
    gl->po->SetPeerRecoveredCallback([gl](int node_id) {
      gl->worker->OnServerRecovered(node_id);
    });
    // Elastic worker membership (ISSUE 8): a JOIN gates new rounds and
    // acks the scheduler with this worker's counters; the RESUME syncs
    // counters to the activation round and lifts the gate.
    gl->po->SetFleetPauseCallback([gl](int kind) {
      gl->worker->OnFleetPause(kind);
    });
    gl->po->SetFleetResumeCallback(
        [gl](int kind, int affected, int64_t jr, int64_t jb) {
          (void)affected;
          gl->worker->OnFleetResume(kind, jr, jb);
        });
    // Scheduler fail-over (ISSUE 15): the CMD_REREGISTER a parked
    // worker sends carries its rounds-completed watermark, and a
    // committed recovery lifts any round gate a pre-crash FLEET_PAUSE
    // left armed (its membership op died with the old scheduler).
    gl->po->SetRoundWatermarkProvider(
        [gl]() -> int64_t { return gl->worker->MaxIssuedRound(); });
    gl->po->SetSchedRecoveredCallback(
        [gl] { gl->worker->OnSchedRecovered(); });
    // The worker pipeline exists BEFORE the postoffice starts (same
    // reasoning as the server's engine threads above): recovery
    // callbacks fire on van threads and must always find a live
    // BytePSWorker.
    gl->worker = std::make_unique<BytePSWorker>();
    gl->worker->Start(gl->po.get(), gl->kv.get(),
                      EnvInt64("BYTEPS_PARTITION_BYTES", 4096000),
                      EnvInt64("BYTEPS_SCHEDULING_CREDIT", 0),
                      // Small-tensor fusion: partitions under this many
                      // raw bytes coalesce into CMD_MULTI_PUSH frames
                      // (0 = off -> pre-fusion wire protocol verbatim).
                      EnvInt64("BYTEPS_FUSION_BYTES", 65536),
                      EnvInt("BYTEPS_FUSION_KEYS", 128),
                      DefaultCompConfig(), EnvBool("BYTEPS_TRACE_ON"));
  }

  // Event-journal identity must exist BEFORE the postoffice starts: on
  // a crash-restarted scheduler the whole re-register -> recovery-
  // commit window runs INSIDE Start(), and only role-0 emits enter the
  // fleet timeline directly. The scheduler's id is fixed (0); other
  // roles learn theirs when Start returns — their pre-topology records
  // carry node -1 and the scheduler backfills identity from the wire
  // chunk's header at ingest.
  Events::Get().SetNode(role, gl->role == ROLE_SCHEDULER ? 0 : -1);

  int id = gl->po->Start(gl->role, uri, port, nw, ns, std::move(handler));
  // Elastic joiner (DMLC_JOIN): the scheduler's direct ADDRBOOK carried
  // the round boundary this rank enters at — every tensor declared from
  // here starts its counters there, so the first push lands exactly in
  // the first round the new roster expects this rank in.
  if (gl->role == ROLE_WORKER && EnvBool("DMLC_JOIN")) {
    gl->worker->SyncRounds(gl->po->join_round(),
                           gl->po->join_bcast_round());
  }
  // Durable restore epoch (ISSUE 18): the ADDRBOOK carried the round
  // the fleet resumes from. Workers jump their counters past it so the
  // first post-restore push is round R+1 — the PR 8 SyncRounds
  // machinery, driven by a disk-backed epoch instead of a join.
  if (gl->role == ROLE_WORKER && gl->po->restore_round() >= 0) {
    gl->worker->SyncRounds(gl->po->restore_round() + 1, 0);
    BPS_LOG(WARNING) << "worker: resuming from restored checkpoint "
                        "round " << gl->po->restore_round()
                     << " — counters jump to "
                     << gl->po->restore_round() + 1;
  }
  // Fleet tracing (ISSUE 5): identity for this rank's dump metadata,
  // plus the trace-health series pre-registered so every /metrics page
  // serves them from zero (monitor.top's TRACE-DROPPING flag).
  Trace::Get().SetNode(role, id,
                       gl->role == ROLE_WORKER ? gl->po->my_worker_rank()
                                               : -1);
  if (gl->role == ROLE_SCHEDULER) {
    Trace::Get().SetClock(0, 0);  // the scheduler IS the timebase
  }
  // Round-summary identity (ISSUE 7): stamps the heartbeat piggyback
  // so the scheduler's fleet table keys on real node ids.
  RoundStats::Get().SetNode(role, id);
  // Event-journal identity (ISSUE 20): same contract — wire chunks and
  // journal records carry the real node id from the first emit on.
  Events::Get().SetNode(role, id);
  Metrics::Get().Counter("bps_trace_events_total");
  Metrics::Get().Counter("bps_trace_dropped_total");
  Metrics::Get().Counter("bps_flight_dumps_total");
  Metrics::Get().Counter("bps_events_emitted_total");
  if (gl->role == ROLE_SCHEDULER) {
    Metrics::Get().Counter("bps_round_summaries_ingested_total");
    Metrics::Get().Counter("bps_events_ingested_total");
  }
  // Wire-CRC series pre-registration (ISSUE 20 satellite): where the
  // data-plane CRC is armed, its health counters must serve from zero
  // on every /metrics page — absent-until-first-corruption reads as
  // "CRC off" to dashboards, which is exactly backwards. Unarmed
  // builds keep the page byte-for-byte (same contract as the server
  // ctor's BYTEPS_CKPT_DIR-gated ckpt series).
  if (const char* crc = getenv("BYTEPS_WIRE_CRC");
      crc && *crc && *crc != '0') {
    Metrics::Get().Counter("bps_crc_fail_total");
    Metrics::Get().Counter("bps_crc_quarantine_total");
    Metrics::Get().Counter("bps_crc_quarantine_links_total");
    Metrics::Get().Gauge("bps_link_corrupting");
  }
  // Replica delta subscription starts only now: the poll loop dials the
  // primary out of the address book, which exists only after Start.
  if (gl->role == ROLE_REPLICA) {
    gl->server->StartReplicaPoll();
  }
  gl->inited = true;
  return id;
}

void bps_finalize() {
  Global* gl = g();
  if (!gl->inited) return;
  // Same drain-first order as ~Global (see its comment).
  if (gl->kv) gl->kv->StopExec();
  if (gl->worker) gl->worker->Stop();
  gl->po->Finalize();
  if (gl->server) gl->server->Stop();
  gl->inited = false;
}

// 1 when this node saw a FAILURE shutdown (scheduler dead-node
// broadcast, arg0=1, or a lost scheduler connection) rather than the
// clean all-goodbyes teardown. Valid after finalize — server/scheduler
// entry points use it to exit nonzero so supervisors can tell crash
// from completion.
int bps_failure_shutdown() {
  Global* gl = g();
  return gl->po && gl->po->FailureShutdown() ? 1 : 0;
}

int bps_my_id() { return g()->po->my_id(); }
int bps_worker_rank() { return g()->po->my_worker_rank(); }
int bps_num_workers() { return g()->po->num_workers(); }
int bps_num_servers() { return g()->po->num_servers(); }

// Fleet membership epoch (bumped per server recovery AND per worker
// join/leave/shrink — ISSUE 4 + ISSUE 8). Live: num_workers above also
// tracks elastic membership changes.
long long bps_epoch() {
  Global* gl = g();
  return gl->po ? gl->po->epoch() : 0;
}

// Graceful leave (ISSUE 8): drain this worker's in-flight requests,
// tell the scheduler, and wait for the removal ack. After a 0 return
// the process should call bps_finalize and exit — it is out of the
// fleet's shutdown quorum and owes no goodbye. -1 = not a worker, the
// scheduler never acked (elasticity off?), or requests still pending.
int bps_leave() {
  Global* gl = g();
  if (!gl->inited || gl->role != ROLE_WORKER || !gl->kv) return -1;
  // The caller should have waited its handles; this drains whatever
  // bookkeeping is left so the LEAVE provably follows the last settle.
  gl->kv->WaitAll();
  return gl->po->RequestLeave() ? 0 : -1;
}

void bps_barrier(int group) { g()->po->Barrier(group); }

long long bps_declare(const char* name, long long nelem, int dtype,
                      const char* comp_config) {
  return g()->worker->Declare(name, nelem, dtype,
                              comp_config ? comp_config : "__default__");
}

int bps_push_pull(long long tensor_id, void* ptr, long long nelem, int dtype,
                  int average, int async_mode) {
  return g()->worker->PushPull(tensor_id, ptr, nelem, dtype, average != 0,
                               async_mode != 0);
}

int bps_broadcast(long long tensor_id, void* ptr, long long nelem, int dtype,
                  int root) {
  return g()->worker->Broadcast(tensor_id, ptr, nelem, dtype, root);
}

// 0 = success; -1 = the handle failed fast (dead peer) — fetch the
// diagnostic with bps_last_error().
int bps_wait(int handle) { return g()->worker->Wait(handle); }
int bps_poll(int handle) { return g()->worker->Poll(handle); }

const char* bps_last_error() {
  static thread_local std::string err;
  err = g()->worker ? g()->worker->LastError() : "";
  return err.c_str();
}

// Dump accumulated trace events as Chrome trace-event JSON (reference:
// BYTEPS_TRACE_ON timeline, SURVEY.md §5). Returns number of events.
// ISSUE 5: works for EVERY role (the ring is process-wide, not
// worker-owned) and prepends a `meta` object — role, node id, and the
// heartbeat-derived clock offset vs the scheduler — that the fleet
// merge tool (python -m byteps_tpu.monitor.timeline) aligns ranks with.
// Drains the ring: dump-once timeline semantics, as before.
int bps_dump_trace(const char* path) {
  return static_cast<int>(Trace::Get().DumpMain(path));
}

// Snapshot the always-on flight recorder (BYTEPS_FLIGHT_RECORDER) to
// `path`, or to the default <BYTEPS_TRACE_DIR>/flight_r<role>_n<id>.json
// when path is NULL/empty. Non-draining: the recorder keeps recording.
// The same dump fires automatically on fatal CHECK, failure SHUTDOWN,
// and recovery EPOCH_PAUSE/RESUME.
int bps_dump_flight(const char* path) {
  if (path && *path) {
    return static_cast<int>(Trace::Get().DumpFlight(path));
  }
  return static_cast<int>(Trace::Get().FlightDumpAuto("manual"));
}

// Report the current training step for the BYTEPS_TRACE_START_STEP /
// _END_STEP window (utils.Timeline calls this once per step). Steps
// never reported leave the window open — raw-FFI users keep the old
// always-recording behavior; with steps reported, recording stops
// outside the window instead of accumulating without bound.
void bps_trace_step(int step) { Trace::Get().SetStep(step); }

// App-level annotation: record an instant into the main trace ring and
// the flight recorder (also the test hook for ring wraparound).
void bps_trace_note(const char* name, long long key) {
  if (name) Trace::Get().Note(name, key);
}

// Compressor roundtrip probe (no topology needed): encode `n` float32
// elements of `src` with the codec built from `config`, decode into
// `dst`, and return the encoded byte count. Errors are returned, not
// CHECK-crashed, so tests can assert on them: -1 = bad/empty config,
// -2 = non-finite input (the in-core push path CHECK-crashes on the
// same condition — "error loudly rather than encode garbage").
long long bps_compressor_roundtrip(const char* config, const void* src,
                                   long long n, void* dst) {
  if (!config || !src || !dst || n <= 0) return -1;
  const float* s = static_cast<const float*>(src);
  for (long long i = 0; i < n; ++i) {
    if (!(std::fabs(s[i]) <= std::numeric_limits<float>::max())) {
      return -2;
    }
  }
  // Pre-validate the type: CreateCompressor treats an unknown type as a
  // fatal misconfiguration (BPS_FATAL), which a probe must not be.
  auto kv = ParseCompressorConfig(config);
  auto type_it = kv.find("type");
  if (type_it == kv.end() ||
      (type_it->second != "onebit" && type_it->second != "topk" &&
       type_it->second != "randomk" && type_it->second != "dithering")) {
    return -1;
  }
  std::unique_ptr<Compressor> c = CreateCompressor(config, n);
  if (!c) return -1;
  std::vector<char> enc;
  c->Compress(s, n, &enc);
  c->Decompress(enc.data(), static_cast<int64_t>(enc.size()),
                static_cast<float*>(dst), n);
  return static_cast<long long>(enc.size());
}

// BlockQuant (ISSUE 6 wire codec) roundtrip probe: encode `src` with
// the given block, decode into `dst`, return encoded bytes. -1 = an
// invalid block (not a power of two in [16, 32768]) or bad args,
// -2 = non-finite input refused by the encoder.
long long bps_quant_roundtrip(const void* src, long long n, int block,
                              void* dst) {
  if (!src || !dst || n <= 0) return -1;
  if (!BlockQuant::ValidBlock(block)) return -1;
  std::vector<char> enc;
  if (!BlockQuant::Encode(static_cast<const float*>(src), n, block,
                          &enc)) {
    return -2;
  }
  if (!BlockQuant::Decode(enc.data(), static_cast<int64_t>(enc.size()),
                          static_cast<float*>(dst), n)) {
    return -1;
  }
  return static_cast<long long>(enc.size());
}

// Elastic epoch-roster / rollback probe (ISSUE 8; no topology needed):
// drives one RosterHistory + one key-slot contribution roster through a
// `;`-separated script and writes the final state as JSON into `buf`
// (same grow-the-buffer contract as bps_metrics_snapshot). Ops:
//   live:1,2,3   install the initial roster (ids)
//   join:5@8     id 5 joins, activating at round 8 (both round spaces)
//   remove:2     id 2 leaves/dies: erased from every roster AND its
//                retained slot contribution discarded (the rollback)
//   push:3       id 3 contributes 4 floats of value 3 to the slot
//   pull:3       id 3 pulled the slot's round
//   seal / reset round-ready / slot-recycle bookkeeping
//   round:8      the round number ready/served are evaluated against
// Output: {"roster":[...],"pushers":[...],"pullers":[...],
//          "ready":bool,"served":bool,"sum":[4 ints]} — `sum` is the
// slot rebuilt from the SURVIVING contributions (ascending sender id),
// i.e. exactly what the server's shrink rollback installs. Returns the
// JSON length, or -1 on a malformed script.
long long bps_elastic_probe(const char* script, char* buf,
                            long long maxlen) {
  if (!script) return -1;
  RosterHistory roster;
  ElasticSlot slot;
  long long round = 0;
  const std::string s(script);
  auto parse_ids = [](const std::string& v) {
    std::set<int> out;
    size_t p = 0;
    while (p < v.size()) {
      size_t c = v.find(',', p);
      if (c == std::string::npos) c = v.size();
      out.insert(atoi(v.substr(p, c - p).c_str()));
      p = c + 1;
    }
    return out;
  };
  size_t pos = 0;
  while (pos < s.size()) {
    size_t end = s.find(';', pos);
    if (end == std::string::npos) end = s.size();
    const std::string tok = s.substr(pos, end - pos);
    pos = end + 1;
    if (tok.empty()) continue;
    const size_t colon = tok.find(':');
    const std::string op = tok.substr(0, colon);
    const std::string val =
        colon == std::string::npos ? "" : tok.substr(colon + 1);
    if (op == "live") {
      roster.Init(parse_ids(val));
    } else if (op == "join") {
      const size_t at = val.find('@');
      const int id = atoi(val.substr(0, at).c_str());
      const long long r =
          at == std::string::npos ? 0 : atoll(val.substr(at + 1).c_str());
      roster.Join(id, r, r);
    } else if (op == "remove") {
      const int id = atoi(val.c_str());
      roster.Remove(id);
      slot.Remove(id);
    } else if (op == "push") {
      const int id = atoi(val.c_str());
      const float v[4] = {static_cast<float>(id), static_cast<float>(id),
                          static_cast<float>(id), static_cast<float>(id)};
      slot.Push(id, reinterpret_cast<const char*>(v), sizeof(v));
    } else if (op == "pull") {
      slot.Pull(atoi(val.c_str()));
    } else if (op == "seal") {
      slot.SealPushes();
    } else if (op == "reset") {
      slot.Reset();
    } else if (op == "round") {
      round = atoll(val.c_str());
    } else {
      return -1;
    }
  }
  auto ro = roster.OfRound(round);
  float sum[4] = {0, 0, 0, 0};
  const bool have_sum = slot.RebuildSum(reinterpret_cast<char*>(sum),
                                        sizeof(sum), BPS_FLOAT32);
  std::string out = "{";
  auto emit_set = [&out](const char* name, const std::set<int>& v) {
    out += std::string("\"") + name + "\":[";
    bool first = true;
    for (int id : v) {
      if (!first) out += ",";
      first = false;
      out += std::to_string(id);
    }
    out += "]";
  };
  emit_set("roster", *ro);
  out += ",";
  emit_set("pushers", slot.pushers());
  out += ",";
  emit_set("pullers", slot.pullers());
  out += ",\"ready\":";
  out += (!ro->empty() && slot.PushersMatch(*ro)) ? "true" : "false";
  out += ",\"served\":";
  out += (!ro->empty() && slot.PullersCover(*ro)) ? "true" : "false";
  out += ",\"sum\":[";
  if (have_sum) {
    for (int i = 0; i < 4; ++i) {
      if (i) out += ",";
      out += std::to_string(static_cast<long long>(sum[i]));
    }
  }
  out += "]}";
  const long long need = static_cast<long long>(out.size());
  if (buf && maxlen > 0) {
    long long n = need < maxlen - 1 ? need : maxlen - 1;
    memcpy(buf, out.data(), static_cast<size_t>(n));
    buf[n] = '\0';
  }
  return need;
}

// Scheduler fail-over reconstruction probe (ISSUE 15): drives the
// standalone SchedRecovery arithmetic — quorum counting, epoch
// max-adoption, split-brain conflict, rank high-water mark, tenant
// roster rebuild, heartbeat seeding, window expiry — with NO fleet.
// Script: `;`-separated ops:
//   servers:2         fleet has 2 server ranks (NextWorkerId base)
//   book:1,2,3,4      the TEMPLATE address book for later reports (ids;
//                     scheduler 0 auto-included; 1..servers = servers,
//                     the rest workers). Change it between reports to
//                     fabricate a same-epoch conflict.
//   tenant:5=2        template: worker id 5 belongs to tenant 2
//   report:3@7        node 3 re-registers at epoch 7 with the current
//                     template book. Optional `,hint,rounds` suffix:
//                     report:3@7,9,120
//   window:0,5000,4000  evaluate Expired(now=5000, start=0, win=4000)
//   seed:1000,2000    evaluate SeedHeartbeats(commit=1000) and
//                     EarliestDeathMs with timeout=2000
// Output: {"reregistered":N,"expected":[ids],"quorum":b,"conflict":b,
//          "epoch":E,"next_worker":id,"rosters":{"t":[ids],...},
//          "rounds":W,"book":[ids],"expired":b,"seeds":N,
//          "seed_min":ms,"earliest_death":ms}. Returns the JSON
// length (call again with a bigger buffer if it exceeds maxlen), or
// -1 on a malformed script.
long long bps_sched_probe(const char* script, char* buf,
                          long long maxlen) {
  if (!script) return -1;
  SchedRecovery rec;
  int num_servers = 1;
  std::vector<NodeInfo> tmpl;
  std::map<int, int> tenants;
  bool expired = false;
  int64_t seed_commit = -1, seed_timeout = 0;
  const std::string s(script);
  auto make_book = [&]() {
    std::vector<NodeInfo> out;
    NodeInfo sched{};
    sched.id = kSchedulerId;
    sched.role = ROLE_SCHEDULER;
    out.push_back(sched);
    for (const auto& n : tmpl) out.push_back(n);
    return out;
  };
  size_t pos = 0;
  while (pos < s.size()) {
    size_t end = s.find(';', pos);
    if (end == std::string::npos) end = s.size();
    const std::string tok = s.substr(pos, end - pos);
    pos = end + 1;
    if (tok.empty()) continue;
    const size_t colon = tok.find(':');
    const std::string op = tok.substr(0, colon);
    const std::string val =
        colon == std::string::npos ? "" : tok.substr(colon + 1);
    if (op == "servers") {
      num_servers = atoi(val.c_str());
    } else if (op == "book") {
      tmpl.clear();
      size_t p = 0;
      while (p < val.size()) {
        size_t c = val.find(',', p);
        if (c == std::string::npos) c = val.size();
        const int id = atoi(val.substr(p, c - p).c_str());
        p = c + 1;
        NodeInfo n{};
        n.id = id;
        n.role = (id >= 1 && id <= num_servers) ? ROLE_SERVER
                                                : ROLE_WORKER;
        n.tenant = static_cast<uint16_t>(tenants.count(id)
                                             ? tenants[id] : 0);
        snprintf(n.host, sizeof(n.host), "127.0.0.1");
        n.port = 9000 + id;
        tmpl.push_back(n);
      }
    } else if (op == "tenant") {
      const size_t eq = val.find('=');
      if (eq == std::string::npos) return -1;
      const int id = atoi(val.substr(0, eq).c_str());
      const int t = atoi(val.substr(eq + 1).c_str());
      tenants[id] = t;
      for (auto& n : tmpl) {
        if (n.id == id) n.tenant = static_cast<uint16_t>(t);
      }
    } else if (op == "report") {
      const size_t at = val.find('@');
      if (at == std::string::npos) return -1;
      const int id = atoi(val.substr(0, at).c_str());
      std::string rest = val.substr(at + 1);
      int64_t epoch = atoll(rest.c_str());
      int64_t hint = 0, rounds = 0;
      size_t c1 = rest.find(',');
      if (c1 != std::string::npos) {
        hint = atoll(rest.substr(c1 + 1).c_str());
        size_t c2 = rest.find(',', c1 + 1);
        if (c2 != std::string::npos) {
          rounds = atoll(rest.substr(c2 + 1).c_str());
        }
      }
      SchedRecovery::Report r;
      r.epoch = epoch;
      r.rank_hint = hint;
      r.rounds = rounds;
      r.book = make_book();
      r.self.id = id;
      for (const auto& n : r.book) {
        if (n.id == id) r.self = n;
      }
      rec.Ingest(id, std::move(r));
    } else if (op == "window") {
      long long a = 0, b = 0, w = 0;
      if (sscanf(val.c_str(), "%lld,%lld,%lld", &a, &b, &w) != 3) {
        return -1;
      }
      expired = SchedRecovery::Expired(b, a, w);
    } else if (op == "seed") {
      long long c = 0, t = 0;
      if (sscanf(val.c_str(), "%lld,%lld", &c, &t) != 2) return -1;
      seed_commit = c;
      seed_timeout = t;
    } else {
      return -1;
    }
  }
  std::string out = "{";
  out += "\"reregistered\":" + std::to_string(rec.Reregistered());
  out += ",\"expected\":[";
  {
    bool first = true;
    for (int id : rec.ExpectedIds()) {
      if (!first) out += ",";
      first = false;
      out += std::to_string(id);
    }
  }
  out += "],\"quorum\":";
  out += rec.QuorumMet() ? "true" : "false";
  out += ",\"conflict\":";
  out += rec.Conflict() ? "true" : "false";
  out += ",\"epoch\":" + std::to_string(rec.AdoptedEpoch());
  out += ",\"next_worker\":" +
         std::to_string(rec.NextWorkerId(num_servers));
  out += ",\"rosters\":{";
  {
    bool tfirst = true;
    for (const auto& kv : rec.TenantRosters()) {
      if (!tfirst) out += ",";
      tfirst = false;
      out += "\"" + std::to_string(kv.first) + "\":[";
      bool first = true;
      for (int id : kv.second) {
        if (!first) out += ",";
        first = false;
        out += std::to_string(id);
      }
      out += "]";
    }
  }
  out += "},\"rounds\":" + std::to_string(rec.RoundsWatermark());
  out += ",\"book\":[";
  {
    bool first = true;
    for (const auto& n : rec.RebuiltBook()) {
      if (!first) out += ",";
      first = false;
      out += std::to_string(n.id);
    }
  }
  out += "],\"expired\":";
  out += expired ? "true" : "false";
  {
    const auto seeds = rec.SeedHeartbeats(seed_commit < 0 ? 0
                                                          : seed_commit);
    int64_t seed_min = 0;
    for (const auto& kv : seeds) {
      if (seed_min == 0 || kv.second < seed_min) seed_min = kv.second;
    }
    out += ",\"seeds\":" + std::to_string(seeds.size());
    out += ",\"seed_min\":" + std::to_string(seed_min);
    out += ",\"earliest_death\":" +
           std::to_string(seed_commit < 0
                              ? 0
                              : SchedRecovery::EarliestDeathMs(
                                    seed_commit, seed_timeout));
  }
  out += "}";
  const long long need = static_cast<long long>(out.size());
  if (buf && maxlen > 0) {
    long long n = need < maxlen - 1 ? need : maxlen - 1;
    memcpy(buf, out.data(), static_cast<size_t>(n));
    buf[n] = '\0';
  }
  return need;
}

// Standalone CpuReducer throughput probe: repeatedly sum a src buffer
// into dst (the server's hot loop) and return GB/s of summed INPUT
// bytes. Callable without any topology (SURVEY.md §7 hard part #5:
// server summation must not be the bottleneck — measure it).
double bps_reducer_bench(long long nbytes, int iters, int dtype) {
  if (nbytes <= 0 || iters <= 0 || DtypeSize(dtype) == 0) return -1.0;
  // 0x3C byte fill: normal-range values in every float format (fp16
  // 0x3C3C ~= 1.06, f32 0x3C3C3C3C ~= 0.011) — a 0x01 fill would make
  // fp16 lanes subnormal and measure the worst-case conversion branch
  // instead of typical gradient values.
  std::vector<char> dst(nbytes, 0x3C), src(nbytes, 0x3D);
  CpuReducer::Sum(dst.data(), src.data(), nbytes, dtype);  // warm
  auto t0 = std::chrono::steady_clock::now();
  for (int i = 0; i < iters; ++i) {
    CpuReducer::Sum(dst.data(), src.data(), nbytes, dtype);
  }
  double s = std::chrono::duration<double>(
                 std::chrono::steady_clock::now() - t0)
                 .count();
  return static_cast<double>(nbytes) * iters / s / 1e9;
}

// One-call telemetry snapshot for the byteps_tpu.monitor subsystem:
// the whole metric registry (counters / gauges / latency histograms
// instrumented at every pipeline stage) plus the live node state that
// used to be three ad-hoc C APIs — van wire bytes, async staleness,
// scheduler dead nodes — and the scheduled-queue occupancy. Writes a
// JSON document into `buf` (NUL-terminated, truncated if needed) and
// returns the FULL length required excluding the NUL; callers retry
// with a bigger buffer when the return value >= maxlen. Callable in any
// state (before init, after finalize): sections without a live owner
// are emptied, the registry (process-cumulative) is always present.
long long bps_metrics_snapshot(char* buf, long long maxlen) {
  Global* gl = g();
  std::string out = "{";
  out += Metrics::Get().SnapshotJson();

  Postoffice* po = gl->inited ? gl->po.get() : nullptr;
  out += ",\"node\":{";
  out += "\"inited\":" + std::string(gl->inited ? "true" : "false");
  if (po) {
    out += ",\"role\":" + std::to_string(gl->role);
    out += ",\"id\":" + std::to_string(po->my_id());
    out += ",\"num_workers\":" + std::to_string(po->num_workers());
    out += ",\"num_servers\":" + std::to_string(po->num_servers());
    if (gl->role == ROLE_WORKER) {
      out += ",\"worker_rank\":" + std::to_string(po->my_worker_rank());
    }
  }
  out += "}";

  out += ",\"van\":{\"sent_bytes\":";
  out += std::to_string(po ? po->van().bytes_sent() : 0);
  out += ",\"recv_bytes\":";
  out += std::to_string(po ? po->van().bytes_recv() : 0);
  out += "}";

  BytePSWorker* w = gl->inited ? gl->worker.get() : nullptr;
  long long ssum = 0, smax = 0, scnt = 0;
  if (w) w->StalenessStats(&ssum, &smax, &scnt);
  char stale[128];
  snprintf(stale, sizeof(stale),
           ",\"staleness\":{\"mean\":%.3f,\"max\":%lld,\"samples\":%lld}",
           scnt > 0 ? static_cast<double>(ssum) / scnt : 0.0, smax, scnt);
  out += stale;

  int64_t qp = 0, qi = 0, qb = 0;
  if (w) w->QueueStats(&qp, &qi, &qb);
  out += ",\"queue\":{\"pending\":" + std::to_string(qp);
  out += ",\"inflight_bytes\":" + std::to_string(qi);
  out += ",\"credit_budget_bytes\":" + std::to_string(qb) + "}";

  // Multi-tenant section (ISSUE 9): this process's tenant identity,
  // the per-tenant accounting registry (servers: bytes / ops / queue
  // depth / sum time / DRR dispatch + starvation age), and — when the
  // address book is known — the tenant -> (workers, weight) roster.
  // monitor/metrics.py renders these as bps_tenant_*{tenant="N"}
  // labeled series; monitor/http.py serves them raw at /tenants.
  out += ",\"tenants\":{\"local\":{\"id\":" +
         std::to_string(TenantId());
  out += ",\"name\":\"" + TenantName() + "\"";
  out += ",\"weight\":" + std::to_string(TenantWeight()) + "}";
  out += ",\"stats\":" + Tenancy::Get().SnapshotJson(NowUs());
  out += ",\"roster\":{";
  if (po) {
    bool first = true;
    for (const auto& kv : po->TenantRoster()) {
      if (!first) out += ",";
      first = false;
      out += "\"" + std::to_string(kv.first) + "\":{\"workers\":" +
             std::to_string(kv.second.first) +
             ",\"weight\":" + std::to_string(kv.second.second) + "}";
    }
  }
  out += "}}";

  out += ",\"heartbeat_age_ms\":{";
  if (po && gl->role == ROLE_SCHEDULER) {
    bool first = true;
    for (const auto& kv : po->HeartbeatAges()) {
      if (!first) out += ",";
      first = false;
      out += "\"" + std::to_string(kv.first) +
             "\":" + std::to_string(kv.second);
    }
  }
  out += "},\"dead_nodes\":[";
  if (po) {
    bool first = true;
    for (int id : po->DeadNodes()) {
      if (!first) out += ",";
      first = false;
      out += std::to_string(id);
    }
  }
  out += "]}";

  long long need = static_cast<long long>(out.size());
  if (buf && maxlen > 0) {
    long long n = need < maxlen - 1 ? need : maxlen - 1;
    memcpy(buf, out.data(), static_cast<size_t>(n));
    buf[n] = '\0';
  }
  return need;
}

// Per-round introspection snapshot (ISSUE 7): this rank's round ring
// (oldest -> newest), the most recent completed round, and — on a rank
// that ingested heartbeat summaries, i.e. the scheduler — the fleet's
// per-rank EWMA baselines and bounded round table. Same buffer contract
// as bps_metrics_snapshot: returns the full length required; callers
// retry with a bigger buffer when the return value >= maxlen. Served
// live at the monitor endpoint's /rounds path and consumed by
// python -m byteps_tpu.monitor.insight.
long long bps_round_summary(char* buf, long long maxlen) {
  std::string out = RoundStats::Get().SnapshotJson();
  long long need = static_cast<long long>(out.size());
  if (buf && maxlen > 0) {
    long long n = need < maxlen - 1 ? need : maxlen - 1;
    memcpy(buf, out.data(), static_cast<size_t>(n));
    buf[n] = '\0';
  }
  return need;
}

// Feed one accumulation event into the round-summary layer from outside
// the C core (stage = RoundStage). This IS the production path — the
// ring/finalize unit tests drive wraparound and drop counters through
// it without a topology, and a Python-side training loop can report
// host-level stages into the same per-round records.
void bps_round_track(int stage, int round, long long us,
                     long long bytes) {
  RoundStats::Get().Track(stage, round, us, bytes);
}

// Ingest a serialized heartbeat round-summary sub-payload (the exact
// wire bytes a worker piggybacks). Returns 1 if accepted, 0 if the
// payload was not a recognized summary — the version-interop contract
// the tests pin down.
int bps_round_ingest(const void* data, long long len) {
  if (!data || len <= 0) return 0;
  return RoundStats::Get().Ingest(data, static_cast<size_t>(len)) ? 1
                                                                  : 0;
}

// This process's tenant id (BYTEPS_TENANT_ID; 0 = legacy/default).
int bps_tenant_id() { return TenantId(); }

// Multi-tenant snapshot (ISSUE 9): the same "tenants" section
// bps_metrics_snapshot embeds — local identity, per-tenant accounting,
// and the address-book roster — as a standalone JSON document for the
// /tenants monitor endpoint. Same buffer contract as the other
// snapshot probes.
long long bps_tenant_summary(char* buf, long long maxlen) {
  Global* gl = g();
  Postoffice* po = gl->inited ? gl->po.get() : nullptr;
  std::string out = "{\"local\":{\"id\":" + std::to_string(TenantId());
  out += ",\"name\":\"" + TenantName() + "\"";
  out += ",\"weight\":" + std::to_string(TenantWeight()) + "}";
  out += ",\"quantum_bytes\":" + std::to_string(TenantQuantum());
  out += ",\"stats\":" + Tenancy::Get().SnapshotJson(NowUs());
  out += ",\"roster\":{";
  if (po) {
    bool first = true;
    for (const auto& kv : po->TenantRoster()) {
      if (!first) out += ",";
      first = false;
      out += "\"" + std::to_string(kv.first) + "\":{\"workers\":" +
             std::to_string(kv.second.first) +
             ",\"weight\":" + std::to_string(kv.second.second) + "}";
    }
  }
  out += "}}";
  long long need = static_cast<long long>(out.size());
  if (buf && maxlen > 0) {
    long long n = need < maxlen - 1 ? need : maxlen - 1;
    memcpy(buf, out.data(), static_cast<size_t>(n));
    buf[n] = '\0';
  }
  return need;
}

// Weighted-DRR / namespacing probe (ISSUE 9; no topology needed):
// drives one WeightedDrr instance plus the TenantKey arithmetic
// through a `;`-separated script and writes the final state as JSON
// (same grow-the-buffer contract as bps_metrics_snapshot). Ops:
//   quantum:N     set the DRR base quantum (before the first enq)
//   weight:T=W    set tenant T's weight
//   enq:T@C       enqueue an item of cost C for tenant T
//   pop:N         dispatch N items (clamped to what is queued)
//   key:T@K       append TenantKey(T, K) to "keys"
//   route:T@K@Q   append TenantKey(T, K) % Q to "routes"
// Output: {"order":[[tenant,cost],...],"served":{"T":cost_total},
//          "keys":[...],"routes":[...],"remaining":N} — `order` is the
// exact dispatch sequence, the contract the fair-share and FIFO unit
// tests pin down. Returns the JSON length, or -1 on a bad script.
long long bps_tenant_probe(const char* script, char* buf,
                           long long maxlen) {
  if (!script) return -1;
  int64_t quantum = 0;
  std::map<uint16_t, int> weights;
  std::unique_ptr<WeightedDrr> drr;
  auto ensure = [&]() {
    if (!drr) {
      drr = std::make_unique<WeightedDrr>(
          quantum, [&weights](uint16_t t) {
            auto it = weights.find(t);
            return it == weights.end() ? 1 : it->second;
          });
    }
  };
  std::vector<std::pair<uint16_t, int64_t>> order;
  std::map<uint16_t, int64_t> served;
  std::vector<long long> keys, routes;
  const std::string s(script);
  size_t pos = 0;
  while (pos < s.size()) {
    size_t end = s.find(';', pos);
    if (end == std::string::npos) end = s.size();
    const std::string tok = s.substr(pos, end - pos);
    pos = end + 1;
    if (tok.empty()) continue;
    const size_t colon = tok.find(':');
    if (colon == std::string::npos) return -1;
    const std::string op = tok.substr(0, colon);
    const std::string val = tok.substr(colon + 1);
    if (op == "quantum") {
      quantum = atoll(val.c_str());
    } else if (op == "weight") {
      const size_t eq = val.find('=');
      if (eq == std::string::npos) return -1;
      weights[static_cast<uint16_t>(atoi(val.substr(0, eq).c_str()))] =
          atoi(val.substr(eq + 1).c_str());
    } else if (op == "enq") {
      const size_t at = val.find('@');
      if (at == std::string::npos) return -1;
      ensure();
      drr->Enqueue(
          static_cast<uint16_t>(atoi(val.substr(0, at).c_str())),
          atoll(val.substr(at + 1).c_str()));
    } else if (op == "pop") {
      ensure();
      long long n = atoll(val.c_str());
      while (n-- > 0 && !drr->Empty()) {
        int64_t cost = 0;
        const uint16_t t = drr->PickAndPop(&cost);
        order.emplace_back(t, cost);
        served[t] += cost;
      }
    } else if (op == "key") {
      const size_t at = val.find('@');
      if (at == std::string::npos) return -1;
      keys.push_back(TenantKey(
          static_cast<uint16_t>(atoi(val.substr(0, at).c_str())),
          atoll(val.substr(at + 1).c_str())));
    } else if (op == "route") {
      const size_t a1 = val.find('@');
      const size_t a2 = a1 == std::string::npos
                            ? std::string::npos
                            : val.find('@', a1 + 1);
      if (a2 == std::string::npos) return -1;
      const uint16_t t =
          static_cast<uint16_t>(atoi(val.substr(0, a1).c_str()));
      const long long k = atoll(val.substr(a1 + 1, a2 - a1 - 1).c_str());
      const long long q = atoll(val.substr(a2 + 1).c_str());
      if (q <= 0) return -1;
      routes.push_back(static_cast<long long>(
          static_cast<size_t>(TenantKey(t, k)) %
          static_cast<size_t>(q)));
    } else {
      return -1;
    }
  }
  std::string out = "{\"order\":[";
  for (size_t i = 0; i < order.size(); ++i) {
    if (i) out += ",";
    out += "[" + std::to_string(order[i].first) + "," +
           std::to_string(order[i].second) + "]";
  }
  out += "],\"served\":{";
  bool first = true;
  for (const auto& kv : served) {
    if (!first) out += ",";
    first = false;
    out += "\"" + std::to_string(kv.first) +
           "\":" + std::to_string(kv.second);
  }
  out += "},\"keys\":[";
  for (size_t i = 0; i < keys.size(); ++i) {
    if (i) out += ",";
    out += std::to_string(keys[i]);
  }
  out += "],\"routes\":[";
  for (size_t i = 0; i < routes.size(); ++i) {
    if (i) out += ",";
    out += std::to_string(routes[i]);
  }
  out += "],\"remaining\":" +
         std::to_string(drr ? static_cast<long long>(drr->Size()) : 0);
  out += "}";
  const long long need = static_cast<long long>(out.size());
  if (buf && maxlen > 0) {
    long long n = need < maxlen - 1 ? need : maxlen - 1;
    memcpy(buf, out.data(), static_cast<size_t>(n));
    buf[n] = '\0';
  }
  return need;
}

// Wire-layout pin for the A/B byte-identity test (ISSUE 9): serialize
// a MsgHeader with the given cmd/tenant/key/version into `buf` (which
// must hold sizeof(MsgHeader) = 64 bytes) and return its size. A
// tenant-0 header must be byte-for-byte the pre-tenant layout — the
// Python test asserts it against a struct.pack reference.
int bps_wire_header_probe(int cmd, int tenant, long long key,
                          int version, void* buf) {
  MsgHeader h{};
  h.cmd = static_cast<int16_t>(cmd);
  h.tenant = static_cast<uint16_t>(tenant);
  h.key = key;
  h.version = version;
  if (buf) memcpy(buf, &h, sizeof(h));
  return static_cast<int>(sizeof(h));
}

// Snapshot-store probe (ISSUE 16; no topology needed): drives one
// SnapStore — version monotonicity, complete-cut commit gating,
// retention-ring eviction, replica watermark adoption, delta
// collection — plus the CachedReplyValid stale-reply predicate through
// a `;`-separated script and writes the final state as JSON (same
// grow-the-buffer contract as the other probes). Ops:
//   retain:N        set the retention ring depth
//   publish:T,K,V   publish (tenant T, key K) at version V: 4 float32
//                   elements all equal to V (+ a fake quant sidecar
//                   when the op is `publishq`). Appends the Publish
//                   return (accepted/rejected) to "published".
//   publishq:T,K,V  as publish, with a quant sidecar attached
//   force:V         ForceLatest(V) — the replica adoption path
//   pull:T,K,V      Get (V = -1 means `latest`); appends
//                   [code, resolved, first_float, has_quant] to "pulls"
//   oldest:T,K      appends OldestOf to "oldest"
//   collect:S,B     CollectNewer(since=S, max_bytes=B); appends
//                   [entry_count, through] to "collects"
//   tag:C,S,N       appends CachedReplyValid(cached=C, serve=S,
//                   nonempty=N!=0) to "tags"
// Output: {"latest":L,"keys":N,"publishes":P,"evictions":E,
//          "published":[...],"pulls":[...],"oldest":[...],
//          "collects":[...],"tags":[...]}. Returns the JSON length, or
// -1 on a malformed script.
long long bps_snap_probe(const char* script, char* buf,
                         long long maxlen) {
  if (!script) return -1;
  SnapStore store;
  std::vector<int> published;
  std::vector<std::string> pulls, collects;
  std::vector<long long> oldest;
  std::vector<bool> tags;
  const std::string s(script);
  size_t pos = 0;
  while (pos < s.size()) {
    size_t end = s.find(';', pos);
    if (end == std::string::npos) end = s.size();
    const std::string tok = s.substr(pos, end - pos);
    pos = end + 1;
    if (tok.empty()) continue;
    const size_t colon = tok.find(':');
    if (colon == std::string::npos) return -1;
    const std::string op = tok.substr(0, colon);
    const std::string val = tok.substr(colon + 1);
    if (op == "retain") {
      store.SetRetain(atoi(val.c_str()));
    } else if (op == "selfcommit") {
      // 0 = replica mode: publishes install but never advance `latest`
      // (only ForceLatest, the adopted primary watermark, commits).
      store.SetSelfCommit(atoi(val.c_str()) != 0);
    } else if (op == "publish" || op == "publishq") {
      long long t = 0, k = 0, v = 0;
      if (sscanf(val.c_str(), "%lld,%lld,%lld", &t, &k, &v) != 3) {
        return -1;
      }
      const float f = static_cast<float>(v);
      const float raw[4] = {f, f, f, f};
      // A recognizable fake quant sidecar: the version byte-repeated
      // (the probe only asserts presence + fidelity, not the codec).
      char quant[8];
      memset(quant, static_cast<int>(v & 0x7f), sizeof(quant));
      published.push_back(
          store.Publish(static_cast<uint16_t>(t), k, v, BPS_FLOAT32,
                        reinterpret_cast<const char*>(raw), sizeof(raw),
                        op == "publishq" ? quant : nullptr,
                        op == "publishq" ? sizeof(quant) : 0)
              ? 1
              : 0);
    } else if (op == "force") {
      store.ForceLatest(atoll(val.c_str()));
    } else if (op == "pull") {
      long long t = 0, k = 0, v = 0;
      if (sscanf(val.c_str(), "%lld,%lld,%lld", &t, &k, &v) != 3) {
        return -1;
      }
      SnapEntry e;
      int64_t resolved = -1;
      const int code =
          store.Get(static_cast<uint16_t>(t), k, v, &e, &resolved);
      float first = 0;
      if (code == SnapStore::OK && e.raw && e.raw->size() >= 4) {
        memcpy(&first, e.raw->data(), sizeof(first));
      }
      pulls.push_back("[" + std::to_string(code) + "," +
                      std::to_string(resolved) + "," +
                      std::to_string(static_cast<long long>(first)) +
                      "," + (e.quant ? "true" : "false") + "]");
    } else if (op == "oldest") {
      long long t = 0, k = 0;
      if (sscanf(val.c_str(), "%lld,%lld", &t, &k) != 2) return -1;
      oldest.push_back(store.OldestOf(static_cast<uint16_t>(t), k));
    } else if (op == "collect") {
      long long since = 0, maxb = 0;
      if (sscanf(val.c_str(), "%lld,%lld", &since, &maxb) != 2) {
        return -1;
      }
      int64_t through = since;
      const auto got = store.CollectNewer(
          since, static_cast<size_t>(maxb), &through);
      collects.push_back("[" + std::to_string(got.size()) + "," +
                         std::to_string(through) + "]");
    } else if (op == "tag") {
      long long c = 0, sv = 0, ne = 0;
      if (sscanf(val.c_str(), "%lld,%lld,%lld", &c, &sv, &ne) != 3) {
        return -1;
      }
      tags.push_back(CachedReplyValid(c, sv, ne != 0));
    } else {
      return -1;
    }
  }
  std::string out = "{\"latest\":" + std::to_string(store.latest());
  out += ",\"keys\":" + std::to_string(store.key_count());
  out += ",\"publishes\":" + std::to_string(store.publishes());
  out += ",\"evictions\":" + std::to_string(store.evictions());
  auto emit_list = [&out](const char* name,
                          const std::vector<std::string>& items) {
    out += std::string(",\"") + name + "\":[";
    for (size_t i = 0; i < items.size(); ++i) {
      if (i) out += ",";
      out += items[i];
    }
    out += "]";
  };
  out += ",\"published\":[";
  for (size_t i = 0; i < published.size(); ++i) {
    if (i) out += ",";
    out += std::to_string(published[i]);
  }
  out += "]";
  emit_list("pulls", pulls);
  out += ",\"oldest\":[";
  for (size_t i = 0; i < oldest.size(); ++i) {
    if (i) out += ",";
    out += std::to_string(oldest[i]);
  }
  out += "]";
  emit_list("collects", collects);
  out += ",\"tags\":[";
  for (size_t i = 0; i < tags.size(); ++i) {
    if (i) out += ",";
    out += tags[i] ? "true" : "false";
  }
  out += "]}";
  const long long need = static_cast<long long>(out.size());
  if (buf && maxlen > 0) {
    long long n = need < maxlen - 1 ? need : maxlen - 1;
    memcpy(buf, out.data(), static_cast<size_t>(n));
    buf[n] = '\0';
  }
  return need;
}

// Fleet-free durable-checkpoint probe (ISSUE 18; modeled on
// bps_snap_probe): drives the spill / scan / load / torn-rejection
// matrix against a real directory, no topology. Script DSL
// (semicolon-separated op:args):
//   dir:<path>      checkpoint root for all later ops
//   rank:<r>        shard rank for all later ops
//   chaos:<mode>    none | truncate | bitflip | sealflip (applied by
//                   later spills; truncate/bitflip corrupt a
//                   seeded-random chunk, sealflip the sealed MANIFEST)
//   spill:V,K       spill a synthetic K-key cut as version V; item i is
//                   16 float32s of value V*1000+i under tenant i%2 —
//                   deterministic, so load can assert fidelity
//   retain:N        CkptRetain(dir, rank, N)
//   scan:0          newest fully-valid version (-1 none)
//   list:0          all fully-valid versions, ascending
//   load:V          [ok, round, items, first] — first = item 0's first
//                   float (0 when the load failed)
//   tear:V,M        corrupt an EXISTING checkpoint: M=0 truncate the
//                   manifest to half, 1 truncate chunk_0, 2 bit-flip
//                   chunk_0 byte 0, 3 delete the manifest
//   crc:<text>      CRC32C of the literal text (known-vector check)
// Output: {"spills":[...],"scans":[...],"lists":[[...]],"loads":[...],
//          "tears":[...],"crcs":[...]}. Returns the JSON length, or -1
// on a malformed script.
long long bps_ckpt_probe(const char* script, char* buf, long long maxlen) {
  if (!script) return -1;
  std::string dir = ".";
  int rank = 0;
  std::string chaos;
  std::vector<int> spills, tears;
  std::vector<long long> scans;
  std::vector<std::string> lists, loads, crcs;
  const std::string s(script);
  size_t pos = 0;
  while (pos < s.size()) {
    size_t end = s.find(';', pos);
    if (end == std::string::npos) end = s.size();
    const std::string tok = s.substr(pos, end - pos);
    pos = end + 1;
    if (tok.empty()) continue;
    const size_t colon = tok.find(':');
    if (colon == std::string::npos) return -1;
    const std::string op = tok.substr(0, colon);
    const std::string val = tok.substr(colon + 1);
    if (op == "dir") {
      dir = val;
    } else if (op == "rank") {
      rank = atoi(val.c_str());
    } else if (op == "chaos") {
      chaos = val == "none" ? "" : val;
    } else if (op == "spill") {
      long long v = 0, k = 0;
      if (sscanf(val.c_str(), "%lld,%lld", &v, &k) != 2) return -1;
      std::vector<SnapDeltaEnt> cut;
      for (long long i = 0; i < k; ++i) {
        SnapDeltaEnt d;
        d.tenant = static_cast<uint16_t>(i % 2);
        d.key = i;
        d.entry.version = v;
        d.entry.dtype = BPS_FLOAT32;
        std::vector<char> raw(16 * sizeof(float));
        float f = static_cast<float>(v * 1000 + i);
        for (int j = 0; j < 16; ++j) {
          memcpy(raw.data() + j * sizeof(float), &f, sizeof(float));
        }
        d.entry.raw =
            std::make_shared<const std::vector<char>>(std::move(raw));
        cut.push_back(std::move(d));
      }
      std::string why;
      spills.push_back(
          CkptSpillSync(dir, rank, v, cut, 1, 1, chaos, &why) ? 1 : 0);
    } else if (op == "retain") {
      CkptRetain(dir, rank, atoi(val.c_str()));
    } else if (op == "scan") {
      std::string why;
      scans.push_back(CkptScan(dir, rank, &why));
    } else if (op == "list") {
      const auto got = CkptList(dir, rank);
      std::string l = "[";
      for (size_t i = 0; i < got.size(); ++i) {
        if (i) l += ",";
        l += std::to_string(static_cast<long long>(got[i]));
      }
      lists.push_back(l + "]");
    } else if (op == "load") {
      std::vector<CkptItem> items;
      int64_t round = -1;
      std::string why;
      const bool ok =
          CkptLoad(dir, rank, atoll(val.c_str()), &items, &round, &why);
      float first = 0;
      if (ok && !items.empty() &&
          items[0].data.size() >= sizeof(float)) {
        memcpy(&first, items[0].data.data(), sizeof(float));
      }
      loads.push_back("[" + std::to_string(ok ? 1 : 0) + "," +
                      std::to_string(static_cast<long long>(round)) +
                      "," + std::to_string(items.size()) + "," +
                      std::to_string(static_cast<long long>(first)) +
                      "]");
    } else if (op == "tear") {
      long long v = 0, mode = 0;
      if (sscanf(val.c_str(), "%lld,%lld", &v, &mode) != 2) return -1;
      const std::string base = dir + "/ckpt_v" + std::to_string(v) +
                               "_s" + std::to_string(rank);
      const std::string manifest = base + "/MANIFEST";
      const std::string chunk0 = base + "/chunk_0.bin";
      const std::string target = mode == 0 || mode == 3 ? manifest
                                                        : chunk0;
      int rc = -1;
      struct stat st{};
      if (stat(target.c_str(), &st) == 0) {
        if (mode == 0 || mode == 1) {
          rc = truncate(target.c_str(), st.st_size / 2);
        } else if (mode == 2) {
          int fd = open(target.c_str(), O_RDWR);
          if (fd >= 0) {
            char b = 0;
            if (pread(fd, &b, 1, 0) == 1) {
              b ^= 0x01;
              rc = pwrite(fd, &b, 1, 0) == 1 ? 0 : -1;
            }
            close(fd);
          }
        } else if (mode == 3) {
          rc = unlink(target.c_str());
        }
      }
      tears.push_back(rc == 0 ? 1 : 0);
    } else if (op == "crc") {
      char hex[16];
      snprintf(hex, sizeof(hex), "%u",
               Crc32c(val.data(), val.size()));
      crcs.push_back(hex);
    } else {
      return -1;
    }
  }
  auto emit_list = [](std::string* out, const char* name,
                      const std::vector<std::string>& items) {
    *out += std::string(",\"") + name + "\":[";
    for (size_t i = 0; i < items.size(); ++i) {
      if (i) *out += ",";
      *out += items[i];
    }
    *out += "]";
  };
  std::string out = "{\"spills\":[";
  for (size_t i = 0; i < spills.size(); ++i) {
    if (i) out += ",";
    out += std::to_string(spills[i]);
  }
  out += "]";
  out += ",\"scans\":[";
  for (size_t i = 0; i < scans.size(); ++i) {
    if (i) out += ",";
    out += std::to_string(scans[i]);
  }
  out += "]";
  emit_list(&out, "lists", lists);
  emit_list(&out, "loads", loads);
  out += ",\"tears\":[";
  for (size_t i = 0; i < tears.size(); ++i) {
    if (i) out += ",";
    out += std::to_string(tears[i]);
  }
  out += "]";
  emit_list(&out, "crcs", crcs);
  out += "}";
  const long long need = static_cast<long long>(out.size());
  if (buf && maxlen > 0) {
    long long n = need < maxlen - 1 ? need : maxlen - 1;
    memcpy(buf, out.data(), static_cast<size_t>(n));
    buf[n] = '\0';
  }
  return need;
}

// The fleet-committed restore epoch this node learned from the address
// book (-1 = none). Workers use it to label results; tests assert the
// whole fleet agreed on one epoch.
long long bps_restore_round() {
  Global* gl = g();
  if (!gl->inited || !gl->po) return -1;
  return gl->po->restore_round();
}

// Record into the registry from outside the C core: kind is "counter"
// (add v), "gauge" (set v) or "histo" (observe v, microseconds). Used
// by the Python monitor layer (step-level metrics live in the same
// registry as the C++ pipeline stages) and by the metrics unit tests
// to exercise bucketing without a topology. Returns 0, or -1 on an
// unknown kind.
int bps_metrics_observe(const char* kind, const char* name, long long v) {
  if (!kind || !name) return -1;
  if (strcmp(kind, "counter") == 0) {
    Metrics::Get().Counter(name)->fetch_add(v, std::memory_order_relaxed);
    return 0;
  }
  if (strcmp(kind, "gauge") == 0) {
    Metrics::Get().Gauge(name)->store(v, std::memory_order_relaxed);
    return 0;
  }
  if (strcmp(kind, "histo") == 0) {
    Metrics::Get().Histogram(name)->Observe(v);
    return 0;
  }
  return -1;
}

// --- fleet event journal (ISSUE 20) -----------------------------------------

// Whole-journal JSON: local ring + (scheduler) fleet timeline + metric
// history rings. Same buffer contract as bps_metrics_snapshot: returns
// the byte length needed; copies + NUL-terminates only when it fits.
long long bps_events_summary(char* buf, long long maxlen) {
  std::string out = Events::Get().SnapshotJson();
  long long need = static_cast<long long>(out.size());
  if (buf && maxlen > need) {
    memcpy(buf, out.data(), static_cast<size_t>(need));
    buf[need] = '\0';
  }
  return need;
}

// Emit one event through the production path (ring, counters, and — on
// a scheduler — the fleet timeline). The FFI hook behind the Python
// monitor layer's journal writes (insight classifications, POST
// /events) and the reachability tests. Returns 0, or -1 on a type
// outside the catalog.
int bps_events_emit(int type, long long a0, long long a1, long long a2) {
  if (type <= EV_NONE || type >= EV_TYPE_COUNT) return -1;
  Events::Get().Emit(static_cast<EventType>(type), a0, a1, a2);
  return 0;
}

// Fill a heartbeat events sub-payload exactly as HeartbeatLoop would
// (new-since-last-beat, capped at kMaxWireEvents). Returns the bytes
// written, 0 when there is nothing new (or the journal is off), or
// the negated length needed when `maxlen` is too small — the chunk
// must ship whole or not at all (wire chunks are not resumable).
long long bps_events_fill_wire(char* buf, long long maxlen) {
  std::string out;
  if (!Events::Get().FillWire(&out)) return 0;
  long long need = static_cast<long long>(out.size());
  if (!buf || maxlen < need) return -need;
  memcpy(buf, out.data(), static_cast<size_t>(need));
  return need;
}

// Ingest one events wire chunk as the scheduler's heartbeat handler
// would. Returns 1 when ingested, 0 when rejected (foreign magic,
// version skew, short frame) — the interop contract the tests pin.
int bps_events_ingest(const void* data, long long len) {
  if (!data || len <= 0) return 0;
  return Events::Get().Ingest(data, static_cast<size_t>(len)) ? 1 : 0;
}

}  // extern "C"
