#include "trace.h"

#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <cstdlib>

#include "metrics.h"

namespace bps {

int64_t NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

namespace {

int64_t EnvLL(const char* name, int64_t dflt) {
  const char* v = getenv(name);
  return v && *v ? atoll(v) : dflt;
}

bool EnvOn(const char* name, bool dflt) {
  const char* v = getenv(name);
  if (!v || !*v) return dflt;
  return strcmp(v, "0") != 0 && strcasecmp(v, "false") != 0 &&
         strcasecmp(v, "off") != 0 && strcasecmp(v, "no") != 0;
}

const char* PhaseStr(int32_t ph) {
  switch (ph) {
    case TRACE_SPAN: return "X";
    case TRACE_FLOW_OUT: return "s";
    case TRACE_FLOW_STEP: return "t";
    case TRACE_FLOW_IN: return "f";
    default: return "i";
  }
}

}  // namespace

Trace::Trace()
    : main_(static_cast<size_t>(EnvLL("BYTEPS_TRACE_RING_EVENTS", 65536))),
      flight_(static_cast<size_t>(
          EnvLL("BYTEPS_FLIGHT_RECORDER_EVENTS", 256))) {
  trace_env_on_ = EnvOn("BYTEPS_TRACE_ON", false);
  flight_on_ = EnvOn("BYTEPS_FLIGHT_RECORDER", true);
  if (const char* s = getenv("BYTEPS_TRACE_START_STEP")) {
    if (*s) win_start_ = atoi(s);
  }
  if (const char* s = getenv("BYTEPS_TRACE_END_STEP")) {
    if (*s) win_end_ = atoi(s);
  }
  RecomputeArmed();
}

Trace& Trace::Get() {
  static Trace* inst = new Trace();
  return *inst;
}

void Trace::SetNode(int role, int node_id, int worker_rank) {
  role_.store(role, std::memory_order_relaxed);
  node_id_.store(node_id, std::memory_order_relaxed);
  worker_rank_.store(worker_rank, std::memory_order_relaxed);
  if (node_id < 0) return;
  // A flight dump written before the topology completed carries a pid
  // name nobody can attribute; now that this rank knows who it is,
  // give the file its canonical role/node name (best-effort — the
  // dump content, with its meta, is the source of truth either way).
  std::string old_path;
  {
    std::lock_guard<std::mutex> lk(reason_mu_);
    old_path.swap(pid_dump_path_);
  }
  if (old_path.empty()) return;
  std::string dir = old_path.substr(0, old_path.find_last_of('/'));
  // Same incarnation probing as FlightDumpAuto: the canonical name may
  // already belong to a dead predecessor's dump — renaming over it
  // would destroy the pre-crash half of the forensics.
  char new_path[512];
  snprintf(new_path, sizeof(new_path), "%s/flight_r%d_n%d.json",
           dir.c_str(), role, node_id);
  struct stat st {};
  for (int k = 1; ::stat(new_path, &st) == 0 && k < 1000; ++k) {
    snprintf(new_path, sizeof(new_path), "%s/flight_r%d_n%d_i%d.json",
             dir.c_str(), role, node_id, k);
  }
  if (::rename(old_path.c_str(), new_path) == 0) {
    std::lock_guard<std::mutex> lk(reason_mu_);
    if (auto_dump_path_.empty()) auto_dump_path_ = new_path;
  }
}

void Trace::SetClock(int64_t offset_us, int64_t rtt_us) {
  clock_offset_us_.store(offset_us, std::memory_order_relaxed);
  clock_rtt_us_.store(rtt_us, std::memory_order_relaxed);
}

void Trace::RecomputeArmed() {
  int s = step_.load(std::memory_order_relaxed);
  bool in_window = s < 0 || (s >= win_start_ && s <= win_end_);
  main_armed_.store(trace_env_on_ && in_window,
                    std::memory_order_relaxed);
}

void Trace::SetStep(int step) {
  step_.store(step, std::memory_order_relaxed);
  RecomputeArmed();
}

void Trace::Emit(const TraceRec& r, bool significant) {
  if (MainOn()) {
    main_.Emit(r);
    BPS_METRIC_COUNTER_ADD("bps_trace_events_total", 1);
    // Surface drop-oldest overwrites live: a climbing dropped counter
    // (TRACE-DROPPING in monitor.top) means the window outgrew the ring
    // — raise BYTEPS_TRACE_RING_EVENTS or narrow the step window.
    static int64_t last_dropped = 0;
    int64_t d = main_.dropped();
    if (d > last_dropped) {
      BPS_METRIC_COUNTER_ADD("bps_trace_dropped_total", d - last_dropped);
      last_dropped = d;
    }
  }
  if (significant && flight_on_) flight_.Emit(r);
}

void Trace::Span(const char* name, int64_t key, int64_t start_us,
                 int64_t end_us, int peer, int32_t req_id, int32_t round,
                 int64_t wire_bytes, int64_t raw_bytes) {
  if (!MainOn()) return;
  TraceRec r;
  snprintf(r.name, sizeof(r.name), "%s", name);
  r.phase = TRACE_SPAN;
  r.ts_us = start_us;
  r.dur_us = end_us - start_us;
  r.key = key;
  r.peer = peer;
  r.req_id = req_id;
  r.round = round;
  r.wire_bytes = wire_bytes;
  r.raw_bytes = raw_bytes;
  Emit(r, false);
}

void Trace::Instant(const char* name, int64_t key, int peer,
                    int32_t req_id, int32_t aux, int32_t round) {
  if (!MainOn()) return;
  TraceRec r;
  snprintf(r.name, sizeof(r.name), "%s", name);
  r.phase = TRACE_INSTANT;
  r.ts_us = NowUs();
  r.key = key;
  r.peer = peer;
  r.req_id = req_id;
  r.aux = aux;
  r.round = round;
  Emit(r, false);
}

void Trace::Flow(TracePhase ph, const char* name, int64_t key,
                 int64_t ts_us, int64_t flow_id) {
  if (!MainOn()) return;
  TraceRec r;
  snprintf(r.name, sizeof(r.name), "%s", name);
  r.phase = ph;
  r.ts_us = ts_us;
  r.key = key;
  r.flow = flow_id;
  Emit(r, false);
}

void Trace::Note(const char* name, int64_t key, int peer, int32_t req_id,
                 int32_t round) {
  if (!flight_on_ && !MainOn()) return;
  TraceRec r;
  snprintf(r.name, sizeof(r.name), "%s", name);
  r.phase = TRACE_INSTANT;
  r.ts_us = NowUs();
  r.key = key;
  r.peer = peer;
  r.req_id = req_id;
  r.round = round;
  Emit(r, true);
}

long long Trace::DumpRing(TraceRing* ring, const char* path, bool drain,
                          const char* ring_name, const char* reason) {
  int64_t dropped = ring->dropped();
  int64_t total = ring->total();
  if (drain) ring->FoldDropped();
  std::vector<TraceRec> evs = ring->Snapshot(drain);
  FILE* f = fopen(path, "w");
  if (!f) return -1;
  int nid = node_id_.load(std::memory_order_relaxed);
  int pid_field = nid >= 0 ? nid : 0;
  fprintf(f,
          "{\"meta\":{\"ring\":\"%s\",\"role\":%d,\"node_id\":%d,"
          "\"worker_rank\":%d,\"pid\":%d,\"clock_offset_us\":%lld,"
          "\"clock_rtt_us\":%lld,\"events_total\":%lld,"
          "\"dropped\":%lld,\"reason\":\"%s\"},\n",
          ring_name, role_.load(std::memory_order_relaxed), nid,
          worker_rank_.load(std::memory_order_relaxed),
          static_cast<int>(getpid()),
          static_cast<long long>(
              clock_offset_us_.load(std::memory_order_relaxed)),
          static_cast<long long>(
              clock_rtt_us_.load(std::memory_order_relaxed)),
          static_cast<long long>(total), static_cast<long long>(dropped),
          reason ? reason : "");
  fprintf(f, "\"traceEvents\":[\n");
  for (size_t i = 0; i < evs.size(); ++i) {
    const TraceRec& e = evs[i];
    const char* sep = i + 1 < evs.size() ? "," : "";
    if (e.phase == TRACE_SPAN) {
      // Byte labels only when present: unlabelled spans keep the
      // pre-ISSUE-7 args shape byte for byte.
      char bytes_args[96] = "";
      if (e.raw_bytes > 0) {
        snprintf(bytes_args, sizeof(bytes_args),
                 ",\"wire_bytes\":%lld,\"raw_bytes\":%lld",
                 static_cast<long long>(e.wire_bytes),
                 static_cast<long long>(e.raw_bytes));
      }
      fprintf(f,
              "{\"name\":\"%s\",\"ph\":\"X\",\"pid\":%d,\"tid\":%lld,"
              "\"ts\":%lld,\"dur\":%lld,\"args\":{\"key\":%lld,"
              "\"peer\":%d,\"req\":%d,\"round\":%d%s}}%s\n",
              e.name, pid_field, static_cast<long long>(e.key),
              static_cast<long long>(e.ts_us),
              static_cast<long long>(e.dur_us),
              static_cast<long long>(e.key), e.peer, e.req_id, e.round,
              bytes_args, sep);
    } else if (e.phase == TRACE_FLOW_OUT || e.phase == TRACE_FLOW_STEP ||
               e.phase == TRACE_FLOW_IN) {
      // Chrome flow-event triple: bound by (cat, name, id); "f" carries
      // bp:"e" so it binds to the enclosing slice like "s"/"t" do.
      fprintf(f,
              "{\"name\":\"%s\",\"cat\":\"bps\",\"ph\":\"%s\",%s"
              "\"id\":%lld,\"pid\":%d,\"tid\":%lld,\"ts\":%lld}%s\n",
              e.name, PhaseStr(e.phase),
              e.phase == TRACE_FLOW_IN ? "\"bp\":\"e\"," : "",
              static_cast<long long>(e.flow), pid_field,
              static_cast<long long>(e.key),
              static_cast<long long>(e.ts_us), sep);
    } else {
      fprintf(f,
              "{\"name\":\"%s\",\"ph\":\"i\",\"s\":\"t\",\"pid\":%d,"
              "\"tid\":%lld,\"ts\":%lld,\"args\":{\"key\":%lld,"
              "\"peer\":%d,\"req\":%d,\"round\":%d,\"aux\":%d}}%s\n",
              e.name, pid_field, static_cast<long long>(e.key),
              static_cast<long long>(e.ts_us),
              static_cast<long long>(e.key), e.peer, e.req_id, e.round,
              e.aux, sep);
    }
  }
  fprintf(f, "]}\n");
  fclose(f);
  return static_cast<long long>(evs.size());
}

long long Trace::DumpMain(const char* path) {
  return DumpRing(&main_, path, /*drain=*/true, "trace", "");
}

long long Trace::DumpFlight(const char* path) {
  std::string reason;
  {
    std::lock_guard<std::mutex> lk(reason_mu_);
    reason = last_reason_;
  }
  return DumpRing(&flight_, path, /*drain=*/false, "flight",
                  reason.c_str());
}

long long Trace::FlightDumpAuto(const char* reason) {
  if (!flight_on_) return 0;
  {
    std::lock_guard<std::mutex> lk(reason_mu_);
    last_reason_ = reason ? reason : "";
  }
  const char* dir = getenv("BYTEPS_TRACE_DIR");
  if (!dir || !*dir) dir = getenv("BPS_TRACE_OUT");
  if (!dir || !*dir) dir = "./traces";
  ::mkdir(dir, 0777);  // single level, best-effort (EEXIST is fine)
  char path[512];
  int nid = node_id_.load(std::memory_order_relaxed);
  if (nid >= 0) {
    // Probe for the first free incarnation name ONCE, then reuse it:
    // a relaunch of the same role/node must not overwrite its
    // predecessor's dump, but this process's own re-dumps should
    // overwrite in place (see auto_dump_path_ in trace.h).
    std::lock_guard<std::mutex> lk(reason_mu_);
    if (auto_dump_path_.empty()) {
      const int role = role_.load(std::memory_order_relaxed);
      snprintf(path, sizeof(path), "%s/flight_r%d_n%d.json", dir, role,
               nid);
      struct stat st {};
      for (int k = 1; ::stat(path, &st) == 0 && k < 1000; ++k) {
        snprintf(path, sizeof(path), "%s/flight_r%d_n%d_i%d.json", dir,
                 role, nid, k);
      }
      auto_dump_path_ = path;
    }
    snprintf(path, sizeof(path), "%s", auto_dump_path_.c_str());
  } else {
    // Pre-topology fatal: no node id yet; the pid keeps files distinct.
    // Remember the path — SetNode renames it to the role/node form if
    // this process survives long enough to learn its identity.
    snprintf(path, sizeof(path), "%s/flight_r%d_pid%d.json", dir,
             role_.load(std::memory_order_relaxed),
             static_cast<int>(getpid()));
    std::lock_guard<std::mutex> lk(reason_mu_);
    pid_dump_path_ = path;
  }
  long long n = DumpFlight(path);
  if (n >= 0) BPS_METRIC_COUNTER_ADD("bps_flight_dumps_total", 1);
  return n;
}

void FlightDumpOnFatal() {
  // One dump per process: a fatal inside the dump (or a second CHECK on
  // another thread racing the abort) must not recurse or interleave.
  static std::atomic<bool> dumped{false};
  bool expected = false;
  if (!dumped.compare_exchange_strong(expected, true)) return;
  Trace& t = Trace::Get();
  if (!t.FlightOn()) return;
  t.FlightDumpAuto("fatal_check");
}

}  // namespace bps
