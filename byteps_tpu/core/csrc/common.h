// Shared types and wire format for the byteps_tpu C++ core.
//
// Capability parity: reference byteps/common/common.h (TensorTableEntry,
// QueueType, DataType) + ps-lite Meta/SArray wire conventions — see
// SURVEY.md §2.1/§2.4. The wire format here is a fresh design: one fixed
// packed header per message followed by an opaque payload, framed over TCP.
#pragma once

#include <cstdint>
#include <cstring>
#include <memory>
#include <string>
#include <utility>
#include <vector>

namespace bps {

// --- data types -------------------------------------------------------------

enum DataType : int32_t {
  BPS_FLOAT32 = 0,
  BPS_FLOAT64 = 1,
  BPS_FLOAT16 = 2,
  BPS_BFLOAT16 = 3,
  BPS_INT32 = 4,
  BPS_INT64 = 5,
  BPS_UINT8 = 6,
  BPS_INT8 = 7,
};

inline int DtypeSize(int32_t dt) {
  switch (dt) {
    case BPS_FLOAT32: case BPS_INT32: return 4;
    case BPS_FLOAT64: case BPS_INT64: return 8;
    case BPS_FLOAT16: case BPS_BFLOAT16: return 2;
    case BPS_UINT8: case BPS_INT8: return 1;
    default: return 0;
  }
}

// --- node roles & ids -------------------------------------------------------

enum Role : int32_t {
  ROLE_SCHEDULER = 0,
  ROLE_SERVER = 1,
  ROLE_WORKER = 2,
  // Snapshot serving (ISSUE 16): a read-only replica of one primary
  // server's published snapshots. Rostered and heartbeat-monitored like
  // any node, but outside the training data plane entirely: it never
  // owns a key shard, never counts toward fleet formation, and its
  // death costs readers a failover, never the fleet anything.
  ROLE_REPLICA = 3,
};

constexpr int32_t kSchedulerId = 0;  // scheduler is always node 0

// --- message commands -------------------------------------------------------

enum Command : int32_t {
  CMD_REGISTER = 1,      // node -> scheduler: role + listen addr
  CMD_ADDRBOOK = 2,      // scheduler -> all: assigned id + address book
  CMD_BARRIER = 3,       // node -> scheduler
  CMD_BARRIER_ACK = 4,   // scheduler -> node
  CMD_PUSH = 5,          // worker -> server: gradient partition payload
  CMD_PUSH_ACK = 6,      // server -> worker
  CMD_PULL = 7,          // worker -> server: request aggregate
  CMD_PULL_RESP = 8,     // server -> worker: aggregate payload
  CMD_INIT_KEY = 9,      // worker -> server: declare key (len, dtype)
  CMD_INIT_ACK = 10,     // server -> worker
  CMD_HEARTBEAT = 11,    // node -> scheduler
  CMD_SHUTDOWN = 12,     // scheduler -> all (graceful teardown)
  CMD_BCAST_PUSH = 13,   // worker -> server: root pushes initial value
  CMD_BCAST_PULL = 14,   // worker -> server: non-root pulls initial value
  CMD_ERROR = 15,        // local synthetic: request failed (dead peer);
                         // payload = human-readable diagnostic
  CMD_SHM_HELLO = 16,    // van-internal: connector offers a shared-memory
                         // data path; payload = shm segment name, arg0 =
                         // per-direction ring bytes. Never reaches upper
                         // layers.
  // Small-tensor fusion (BYTEPS_FUSION_BYTES): many sub-partition-size
  // operations for ONE server coalesced into a single frame. Payload =
  // arg0 x SubHeader table + gathered sub-payloads (offset/len per
  // entry). One req_id covers the whole batch; replies are batched the
  // same way, so a conv net's hundreds of tiny tensors pay one framed
  // round trip per flush instead of one per key.
  CMD_MULTI_PUSH = 17,       // worker -> server: batched CMD_PUSH ops
  CMD_MULTI_ACK = 18,        // server -> worker: batched push acks
  CMD_MULTI_PULL = 19,       // worker -> server: batched CMD_PULL ops
  CMD_MULTI_PULL_RESP = 20,  // server -> worker: batched pull responses
  CMD_KEEPALIVE = 21,        // server -> worker: "your duplicate request
                             // is known and still being worked on" — the
                             // retry layer resets the request's attempt
                             // budget instead of escalating to fail-stop
                             // (a parked pull can legitimately wait out
                             // many retry timeouts behind a slow peer).
  // Hot server replacement (ISSUE 4): scheduler-coordinated recovery of
  // a dead SERVER rank instead of the fleet-wide failure SHUTDOWN.
  CMD_EPOCH_PAUSE = 22,      // scheduler -> all: a server rank died;
                             // membership epoch bumped (arg0 = epoch,
                             // arg1 = dead node id). Workers park that
                             // rank's in-flight requests in the resend
                             // queue and freeze their retry clocks.
  CMD_EPOCH_RESUME = 23,     // scheduler -> all: a replacement adopted
                             // the dead rank (arg0 = epoch, arg1 = node
                             // id, payload = the replacement's
                             // NodeInfo). Workers redial, re-seed the
                             // shard, and drain the parked queue.
  CMD_RESEED = 24,           // worker -> replacement server: re-seed one
                             // key's latest COMPLETED round (version =
                             // round, payload = the unscaled aggregate)
                             // so pulls parked mid-round can be served
                             // from the authoritative worker replica.
  // Elastic worker membership (ISSUE 8): the worker set is an
  // epoch-versioned quantity — joins, graceful leaves, and (with
  // BYTEPS_ELASTIC=1) unplanned worker deaths change the fleet size
  // without a restart. All of these are CONTROL-PLANE: never
  // chaos-injected, never retried — losing one would strand a
  // membership change exactly like a lost heartbeat fakes a death.
  CMD_JOIN_REQUEST = 26,     // new worker -> scheduler: join the running
                             // fleet (payload = NodeInfo; the scheduler
                             // answers with a direct CMD_ADDRBOOK whose
                             // arg0 = the allocated never-reused id and
                             // arg1 = (join_round << 32) | bcast_round —
                             // the round boundary the joiner enters at).
  CMD_LEAVE_REQUEST = 27,    // departing worker -> scheduler: graceful
                             // leave, sent after the worker drained its
                             // in-flight rounds (all handles settled).
  CMD_LEAVE_ACK = 28,        // scheduler -> leaver: removal recorded;
                             // the leaver may exit (no goodbye owed).
  CMD_FLEET_PAUSE = 29,      // scheduler -> all: worker membership is
                             // changing (arg0 = new epoch, version =
                             // kind 0 join / 1 leave / 2 death, key =
                             // affected node id, -1 for a join). For a
                             // JOIN, workers gate new rounds and answer
                             // CMD_FLEET_PAUSE_ACK with their round
                             // counters; leaves/shrinks need no gate
                             // (the departed rank is in no incomplete
                             // round once the server rolls it back).
  CMD_FLEET_PAUSE_ACK = 30,  // worker -> scheduler: rounds gated;
                             // arg0 = max tensor round counter, arg1 =
                             // max broadcast round counter (the
                             // scheduler's join_round is the fleet max).
  CMD_FLEET_RESUME = 31,     // scheduler -> all: the membership change
                             // is committed (arg0 = epoch, version =
                             // kind, key = affected node id, arg1 =
                             // (join_round << 32) | bcast_round for a
                             // join, payload = the full new NodeInfo
                             // address book). Servers re-roster; workers
                             // sync counters (join) and lift the gate.
  CMD_HEARTBEAT_ACK = 25,    // scheduler -> node: echo of a heartbeat
                             // (arg0 = the sender's original send
                             // timestamp in steady-clock us, arg1 = the
                             // scheduler's clock at receipt). The sender
                             // keeps its minimum-RTT sample and derives
                             // its clock offset vs the scheduler —
                             // recorded in every trace dump's metadata
                             // so the fleet timeline merge
                             // (monitor.timeline) can align per-rank
                             // clocks without NTP assumptions.
  // Scheduler fail-over (ISSUE 15): a crashed-and-restarted scheduler
  // rebuilds its entire state — address book, membership epoch, rank
  // allocator high-water mark, tenant rosters, heartbeat table — from
  // the surviving fleet's re-registrations. Control-plane by contract
  // (only BYTEPS_CHAOS_CTRL=1 may inject faults into them, and then
  // the park/re-dial machinery is the recovery path under test).
  CMD_REREGISTER = 32,       // parked node -> restarted scheduler: a
                             // state-carrying re-registration (sender =
                             // my committed node id, arg0 = my membership
                             // epoch, arg1 = the highest WORKER id in my
                             // committed book (rank-allocator high-water
                             // hint), key = my rounds-completed
                             // watermark; payload = my own NodeInfo
                             // followed by my full last-committed
                             // address book). The scheduler commits once
                             // a quorum — every non-scheduler id named
                             // by the highest-epoch book — has reported.
  CMD_SCHED_RESUME = 33,     // restarted scheduler -> re-registered
                             // node: recovery committed (arg0 = adopted
                             // epoch, arg1 = reregistered count); sent
                             // right after a re-issued CMD_ADDRBOOK,
                             // exactly like an elastic commit. Unparks
                             // the node's heartbeat loop.
  // Versioned snapshot serving (ISSUE 16, docs/serving.md): read traffic
  // against round-versioned immutable snapshots published by the server
  // engine at each round boundary. All four are DATA-PLANE (retried,
  // deduped, chaos-injectable) — a reader or replica losing a frame must
  // ride the same absorption machinery as a training pull.
  CMD_SNAP_PULL = 34,        // reader -> server/replica: request one
                             // key's snapshot (version = requested
                             // snapshot version, -1 for `latest`;
                             // FLAG_WIRE_QUANT requests the quantized
                             // serving encoding).
  CMD_SNAP_RESP = 35,        // server/replica -> reader: version = the
                             // served snapshot version (echoed so the
                             // client can assert its cut), arg0 = miss
                             // code (0 ok, 1 evicted/too old, 2 not yet
                             // committed, 3 unknown key), arg1 = raw
                             // float32 byte length when quantized.
  CMD_SNAP_SUB = 36,         // replica -> primary: delta poll (arg0 =
                             // highest snapshot version the replica
                             // holds; -1 = empty, full catch-up).
  CMD_SNAP_DELTA = 37,       // primary -> replica: batched snapshot
                             // entries newer than the subscription
                             // watermark (arg0 = entry count, payload =
                             // SubHeader table + gathered float32
                             // payloads, CMD_MULTI_* layout; version =
                             // the primary's latest snapshot version).
};

// Transient-fault tolerance: commands eligible for chaos injection,
// idempotent retry, and server-side dedup. Control-plane traffic
// (register/addrbook/barrier/heartbeat/shutdown) is NEVER injected or
// retried — dropping a heartbeat would fake a node death, and the
// topology handshake has its own retry (Van::Connect).
inline bool IsDataPlaneCmd(int32_t cmd) {
  switch (cmd) {
    case CMD_PUSH: case CMD_PUSH_ACK: case CMD_PULL: case CMD_PULL_RESP:
    case CMD_INIT_KEY: case CMD_INIT_ACK:
    case CMD_BCAST_PUSH: case CMD_BCAST_PULL:
    case CMD_MULTI_PUSH: case CMD_MULTI_ACK:
    case CMD_MULTI_PULL: case CMD_MULTI_PULL_RESP:
    case CMD_KEEPALIVE:
    // RESEED rides the same retry/dedup machinery as a push (it is one):
    // chaos may drop it, the retry layer re-delivers it, and re-applying
    // it is idempotent (assignment of an already-final aggregate).
    // EPOCH_PAUSE/RESUME are control-plane: losing one would strand the
    // recovery, exactly like a lost heartbeat would fake a death.
    case CMD_RESEED:
    // Snapshot serving (ISSUE 16): reads and replica delta traffic are
    // data plane by the same argument — a dropped SNAP_PULL retries
    // like a training pull, a replayed SNAP_DELTA re-installs an
    // identical immutable snapshot entry (idempotent assignment).
    case CMD_SNAP_PULL: case CMD_SNAP_RESP:
    case CMD_SNAP_SUB: case CMD_SNAP_DELTA:
      return true;
    default:
      return false;
  }
}

// --- message flags ----------------------------------------------------------

enum MsgFlags : int32_t {
  FLAG_COMPRESSED = 1 << 0,  // payload is compressor output
  FLAG_ASYNC = 1 << 1,       // async-mode operation
  FLAG_WIRE_QUANT = 1 << 2,  // payload is the block-quantized int8 wire
                             // encoding (BlockQuant, compressor.h): on a
                             // PUSH the sender encoded the raw float32
                             // partition; on a PULL it REQUESTS the
                             // quantized aggregate; on a PULL_RESP the
                             // server re-quantized the reply (arg0 =
                             // decoded byte length). Mutually exclusive
                             // with FLAG_COMPRESSED — quantization only
                             // applies to codec-less float32 keys.
  FLAG_CKPT_DURABLE = 1 << 3,  // CMD_REGISTER from a server launched
                             // with BYTEPS_CKPT_RESTORE=1 (ISSUE 18):
                             // the header's key field carries
                             // 1 + newest durable checkpoint version
                             // (0 = restore armed but no valid
                             // checkpoint on disk — the scheduler
                             // fail-stops rather than cold-start). The
                             // committed fleet restore epoch rides back
                             // the same way in CMD_ADDRBOOK's key.
  FLAG_WIRE_CRC = 1 << 4,    // BYTEPS_WIRE_CRC frame integrity (ISSUE
                             // 19): the payload carries a 4-byte
                             // little-endian CRC32C trailer computed
                             // over the MsgHeader (as stamped, flag set,
                             // payload_len INCLUDING the trailer, the
                             // trailer field itself excluded) followed
                             // by the payload bytes. payload_len counts
                             // the trailer, so framing is unchanged;
                             // receivers verify, then strip the trailer
                             // and clear this flag before dispatch. A
                             // CRC-off frame carries no trailer and no
                             // flag — byte-for-byte the pre-CRC wire.
};

// --- wire header ------------------------------------------------------------
// Every frame on the wire is: uint64 total_len | MsgHeader | payload bytes.
// total_len counts header + payload. Integers are host-endian (all nodes are
// little-endian x86/ARM Linux in scope).

#pragma pack(push, 1)
struct MsgHeader {
  // Carved out of the old i32 cmd (ISSUE 9, multi-tenant namespaces):
  // command values never exceeded 31, so the high two bytes were always
  // zero on the wire — they now carry the sender's tenant id. A frame
  // from a pre-tenant peer (or any BYTEPS_TENANT_ID-unset process)
  // reads back as tenant 0, and a tenant-0 frame is byte-for-byte the
  // pre-tenant header: cmd's little-endian bytes [lo, hi] followed by
  // tenant [0, 0] reproduce the old 4-byte cmd exactly.
  int16_t cmd = 0;
  uint16_t tenant = 0;     // sender's tenant id (0 = legacy/default)
  int32_t sender = -1;     // node id (-1 before registration)
  int64_t key = 0;         // partition key
  int32_t req_id = -1;     // request id for matching responses
  int32_t dtype = 0;
  int64_t payload_len = 0;  // bytes following the header
  int32_t flags = 0;
  int32_t version = 0;     // round parity slot (sync double-buffering)
  int64_t arg0 = 0;        // cmd-specific (e.g. decompressed len for PUSH,
                           // listen port for REGISTER, count for BARRIER)
  int64_t arg1 = 0;        // cmd-specific (e.g. role for REGISTER)
  int64_t seq = 0;         // per-connection monotone frame sequence,
                           // stamped by the van under the per-fd send
                           // lock. A receiver-side gap (seq jumps) means
                           // frames were lost on this connection (chaos
                           // drop, or a reset mid-stream); a repeat means
                           // duplicate delivery. Pure observability
                           // (bps_seq_gaps_total / bps_seq_dups_total);
                           // end-to-end retry dedup keys on (sender,
                           // req_id), which is worker-monotone.
};
#pragma pack(pop)

// Per-operation entry in a CMD_MULTI_* frame. The frame header's arg0
// holds the entry count; the payload is the packed table followed by the
// gathered sub-payload bytes, each entry's slice at [offset, offset+len).
// `cmd` names the sub-operation (CMD_PUSH / CMD_PULL on requests,
// CMD_PUSH_ACK / CMD_PULL_RESP on replies) so one table layout serves
// all four multi commands; arg0/arg1 mirror the cmd-specific fields of
// the equivalent single-frame MsgHeader (raw len, async apply count).
#pragma pack(push, 1)
struct SubHeader {
  int64_t key = 0;
  int16_t cmd = 0;        // sub-operation command (values are tiny)
  // Wire encoding of this entry's sub-payload (ISSUE 6, quantized fused
  // wire): BPS_FLOAT32 (0, the default — the payload is the raw `dtype`
  // bytes, exactly the pre-quant wire) or BPS_INT8 (the BlockQuant
  // int8 encoding; FLAG_WIRE_QUANT is set in `flags` alongside it).
  // Carved out of the old int32 `cmd` (whose values never exceeded 25),
  // so a quant-off frame is byte-for-byte identical to the pre-quant
  // table layout: cmd's little-endian bytes [lo, 0] followed by
  // wire_dtype [0, 0] reproduce the old 4-byte cmd exactly.
  int16_t wire_dtype = 0;
  int32_t version = 0;
  // Carved out of the old i32 dtype exactly like the frame header's cmd
  // (ISSUE 9): dtype values never exceed 7, so the high bytes were
  // always zero — they now carry the sub-operation's tenant id (every
  // sub-op of one frame shares the frame's tenant; the field makes each
  // table entry self-describing for the engine fan-out). Tenant-0
  // tables stay byte-for-byte the pre-tenant layout.
  int16_t dtype = 0;
  uint16_t tenant = 0;
  int32_t flags = 0;
  int64_t arg0 = 0;
  int64_t arg1 = 0;
  int64_t offset = 0;  // byte offset into the gathered payload region
  int64_t len = 0;     // sub-payload bytes (0 for pulls / bare acks)
};
#pragma pack(pop)

// Owned byte buffer whose resize does NOT zero-fill. The receive path
// resizes to the frame length and immediately overwrites every byte from
// the socket; std::vector's value-initialising resize would write each
// 4 MB partition twice (memset + recv), a measurable slice of DCN-leg
// bandwidth. Move-only, minimal surface.
class Bytes {
 public:
  Bytes() = default;
  // Explicit moves: a defaulted move would copy len_/cap_, leaving the
  // moved-from object claiming nonzero size with null data_ — a later
  // resize_uninit(n <= cap_) on it would hand out data()==nullptr with
  // size()>0. Messages move through parked_pushes and back; keep the
  // moved-from state honest (empty).
  Bytes(Bytes&& other) noexcept
      : data_(std::move(other.data_)),
        len_(std::exchange(other.len_, 0)),
        cap_(std::exchange(other.cap_, 0)) {}
  Bytes& operator=(Bytes&& other) noexcept {
    if (this != &other) {
      data_ = std::move(other.data_);
      len_ = std::exchange(other.len_, 0);
      cap_ = std::exchange(other.cap_, 0);
    }
    return *this;
  }

  void resize_uninit(size_t n) {
    if (n > cap_) {
      data_.reset(new char[n]);
      cap_ = n;
    }
    len_ = n;
  }
  void assign(const char* b, const char* e) {
    resize_uninit(static_cast<size_t>(e - b));
    if (len_) memcpy(data_.get(), b, len_);
  }
  char* data() { return data_.get(); }
  const char* data() const { return data_.get(); }
  size_t size() const { return len_; }
  bool empty() const { return len_ == 0; }
  const char* begin() const { return data_.get(); }
  const char* end() const { return data_.get() + len_; }

 private:
  std::unique_ptr<char[]> data_;
  size_t len_ = 0;
  size_t cap_ = 0;
};

struct Message {
  MsgHeader head;
  Bytes payload;  // owned receive buffer
};

// --- node descriptor (address book entry) -----------------------------------

#pragma pack(push, 1)
struct NodeInfo {
  int32_t id;
  int32_t role;
  char host[64];
  int32_t port;
  // Multi-tenant roster (ISSUE 9): the tenant this node serves traffic
  // for (workers; servers/scheduler are shared infrastructure, 0) and
  // its job's BYTEPS_TENANT_WEIGHT share, registered at CMD_REGISTER /
  // CMD_JOIN_REQUEST time and broadcast to every rank in the address
  // book — servers derive per-tenant expected-contributor counts and
  // DRR weights from the book alone, with no extra control messages.
  // Zero-initialised by every pre-existing construction site, so a
  // tenant-less fleet's book carries (0, 0) = the legacy pool.
  int32_t tenant = 0;
  int32_t weight = 0;  // 0 reads as weight 1 (legacy registrants)
};
#pragma pack(pop)

// Wire-layout pins (ISSUE 9 A/B contract): the tenant fields are carved
// from bytes that were provably always zero, so the header/sub-header
// sizes — and therefore every data-plane frame with tenant 0 — are
// byte-for-byte the pre-tenant wire. NodeInfo (control-plane address
// book, same-binary fleet) is the one struct that legitimately grew.
static_assert(sizeof(MsgHeader) == 64, "MsgHeader wire size changed");
static_assert(sizeof(SubHeader) == 56, "SubHeader wire size changed");
static_assert(sizeof(NodeInfo) == 84, "NodeInfo wire size changed");

}  // namespace bps
