// Round-versioned immutable snapshot store (ISSUE 16, docs/serving.md).
//
// At each round boundary the server engine publishes a consistent cut of
// every (tenant, key) aggregate under a monotone snapshot version. The
// store is the single source of truth for the read-serving path:
//
//  - Publication is copy-on-publish: the engine hands in the finished
//    float32 aggregate (plus the eagerly re-encoded BlockQuant serving
//    bytes for quant-eligible keys) and the store takes an immutable,
//    shared_ptr-owned copy. Engine-side KeyStore buffers are never
//    exposed to readers, so serving can never observe a torn mid-round
//    mix no matter how the engine recycles its slots.
//  - Versions map 1:1 to committed rounds. A version becomes `latest`
//    (committed) only once EVERY known key has published it — readers
//    asking for `latest` therefore always get a complete cut.
//  - Retention is a bounded per-key ring (BYTEPS_SNAPSHOT_RETAIN):
//    readers pinned to an evicted version get a clean EVICTED miss and
//    restart at the new latest, never stale bytes.
//
// Standalone by design (no topology, no threads of its own) so the FFI
// probe (bps_snap_probe) can unit-test version monotonicity, commit
// gating, and ring eviction without a fleet.
#pragma once

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <utility>
#include <vector>

#include "events.h"

namespace bps {

// Stale-reply guard for the server's per-slot cached re-encodes
// (comp_reply / qreply; ISSUE 16 satellite). A cached encode may be
// served ONLY when it is non-empty and its round tag matches the round
// the request is being answered for — a dedup-replayed or
// replica-forwarded pull must never ship a newer round's bytes under an
// older round's header. Centralised (and probe-tested via
// bps_snap_probe) so every serve site asserts the identical predicate.
inline bool CachedReplyValid(int64_t cached_round, int64_t serve_round,
                             bool nonempty) {
  return nonempty && cached_round >= 0 && cached_round == serve_round;
}

// One immutable published value. `raw` is always the float32 aggregate;
// `quant` is the BlockQuant serving encoding, null for quant-ineligible
// keys (tiny / non-float32) — the serve path falls back to raw then.
struct SnapEntry {
  int64_t version = -1;
  int32_t dtype = 0;
  std::shared_ptr<const std::vector<char>> raw;
  std::shared_ptr<const std::vector<char>> quant;
};

// One (tenant, key, entry) item of a replica delta batch.
struct SnapDeltaEnt {
  uint16_t tenant = 0;
  int64_t key = 0;
  SnapEntry entry;
};

class SnapStore {
 public:
  // CMD_SNAP_RESP arg0 miss codes (wire contract, docs/serving.md).
  enum Code : int {
    OK = 0,
    EVICTED = 1,        // version older than the retention ring holds
    NOT_COMMITTED = 2,  // version newer than the latest committed cut
    UNKNOWN_KEY = 3,
  };

  explicit SnapStore(int retain = 4) : retain_(std::max(1, retain)) {}

  void SetRetain(int retain) {
    std::lock_guard<std::mutex> lk(mu_);
    retain_ = std::max(1, retain);
    for (auto& kv : keys_) Trim(&kv.second);
  }

  int retain() const {
    std::lock_guard<std::mutex> lk(mu_);
    return retain_;
  }

  // Replica stores never self-commit: `latest` must advance ONLY via
  // ForceLatest (the primary's committed watermark, adopted after a
  // whole delta batch is installed). With per-publish commit counting a
  // replica's first batch would commit `latest` after its first key and
  // a concurrent reader could resolve a cut whose remaining keys are
  // still uninstalled — a spurious UNKNOWN_KEY on a fully-committed cut.
  void SetSelfCommit(bool on) {
    std::lock_guard<std::mutex> lk(mu_);
    self_commit_ = on;
  }

  // Install one (tenant, key) value under `version`. Re-publishing a
  // version the key already holds (a replayed replica delta, a deduped
  // re-seed) is an idempotent no-op; an OLDER version than the newest
  // held is rejected outright — snapshot history is append-only.
  // Returns true when the entry was installed.
  bool Publish(uint16_t tenant, int64_t key, int64_t version,
               int32_t dtype, const char* raw, size_t raw_len,
               const char* quant = nullptr, size_t quant_len = 0) {
    if (version < 0 || raw == nullptr) return false;
    SnapEntry e;
    e.version = version;
    e.dtype = dtype;
    e.raw = std::make_shared<const std::vector<char>>(raw, raw + raw_len);
    if (quant != nullptr && quant_len > 0) {
      e.quant = std::make_shared<const std::vector<char>>(
          quant, quant + quant_len);
    }
    std::lock_guard<std::mutex> lk(mu_);
    auto& ring = keys_[{tenant, key}];
    if (!ring.empty() && version <= ring.back().version) return false;
    ring.push_back(std::move(e));
    Trim(&ring);
    publishes_++;
    if (!self_commit_) return true;  // replica: ForceLatest only
    // Commit gating: `latest` advances to v only once every known key
    // has published v — the cut is complete by construction. A key set
    // that grows mid-round can stall one version's count; the next
    // full round supersedes it (latest is a running max).
    const int64_t pre_commit = latest_;
    size_t n = ++pub_count_[version];
    if (n >= keys_.size() && version > latest_) latest_ = version;
    // Lockstep commit: the sync engine publishes a key's round v only
    // after the workers waited every key's round v-1 (push_pull handles
    // are all waited each step), so the arrival of ANY publish at
    // version v proves every older pending version is complete. Without
    // this, a key that goes permanently idle after one round (a one-shot
    // broadcast) would stall the all-keys count above forever.
    for (const auto& pc : pub_count_) {
      if (pc.first < version && pc.first > latest_) latest_ = pc.first;
    }
    for (auto it = pub_count_.begin(); it != pub_count_.end();) {
      it = (it->first <= latest_) ? pub_count_.erase(it) : ++it;
    }
    if (latest_ > pre_commit) {
      // Journal the version-commit edge, not the per-key publishes: one
      // EV_SNAP_COMMIT per serving-visible version advance (ISSUE 20).
      Events::Get().Emit(EV_SNAP_COMMIT, latest_,
                         static_cast<int64_t>(keys_.size()));
    }
    return true;
  }

  // Replica path: adopt the primary's committed watermark directly (the
  // delta batch carries everything up to it). Monotone.
  void ForceLatest(int64_t version) {
    std::lock_guard<std::mutex> lk(mu_);
    if (version > latest_) {
      latest_ = version;
      Events::Get().Emit(EV_SNAP_COMMIT, latest_,
                         static_cast<int64_t>(keys_.size()),
                         /*adopted=*/1);
    }
  }

  int64_t latest() const {
    std::lock_guard<std::mutex> lk(mu_);
    return latest_;
  }

  // Resolve one read. version < 0 means `latest`. On OK, *out holds
  // shared ownership of the immutable entry and *resolved names the
  // exact version served (echoed in every CMD_SNAP_RESP header).
  Code Get(uint16_t tenant, int64_t key, int64_t version,
           SnapEntry* out, int64_t* resolved) const {
    std::lock_guard<std::mutex> lk(mu_);
    int64_t want = version < 0 ? latest_ : version;
    if (resolved) *resolved = want;
    if (want < 0 || want > latest_) return NOT_COMMITTED;
    auto it = keys_.find({tenant, key});
    if (it == keys_.end()) return UNKNOWN_KEY;
    const auto& ring = it->second;
    // Newest entry at-or-below the cut: in lockstep training every key
    // publishes every version, but a key idle for round v is still
    // consistently represented by its last value before v.
    for (auto rit = ring.rbegin(); rit != ring.rend(); ++rit) {
      if (rit->version <= want) {
        if (out) *out = *rit;
        return OK;
      }
    }
    return EVICTED;
  }

  // Replica delta support: every entry newer than `since`, whole
  // versions at a time in ascending order, until max_bytes of raw
  // payload is exceeded (always at least one version when any is
  // pending). *through = the highest version fully included, so the
  // caller can hand the replica an exact new watermark; capped at the
  // committed latest — uncommitted (partially published) versions
  // never leave the primary.
  std::vector<SnapDeltaEnt> CollectNewer(int64_t since, size_t max_bytes,
                                         int64_t* through) const {
    std::lock_guard<std::mutex> lk(mu_);
    std::map<int64_t, std::vector<SnapDeltaEnt>> by_version;
    for (const auto& kv : keys_) {
      for (const auto& e : kv.second) {
        if (e.version > since && e.version <= latest_) {
          by_version[e.version].push_back(
              {kv.first.first, kv.first.second, e});
        }
      }
    }
    std::vector<SnapDeltaEnt> out;
    int64_t thru = since;
    size_t bytes = 0;
    for (auto& vv : by_version) {
      size_t vbytes = 0;
      for (const auto& d : vv.second) vbytes += d.entry.raw->size();
      if (!out.empty() && bytes + vbytes > max_bytes) break;
      for (auto& d : vv.second) out.push_back(std::move(d));
      bytes += vbytes;
      thru = vv.first;
    }
    if (through) *through = thru;
    return out;
  }

  // Durable-checkpoint spill support (ISSUE 18): materialize the cut
  // Get(version) serves — the newest entry at-or-below `version` for
  // every known key — as one list for the checkpoint writer. Entries
  // share the store's immutable payload (shared_ptr, no copy), so
  // collecting on an engine thread costs pointer work only. *complete
  // reports whether EVERY known key contributed an entry: a key whose
  // ring no longer reaches back to `version` would make the cut torn,
  // and the writer must skip the spill rather than persist a partial
  // checkpoint. Called with a COMMITTED version, completeness holds by
  // the commit-gating construction.
  std::vector<SnapDeltaEnt> CollectCut(int64_t version,
                                       bool* complete) const {
    std::lock_guard<std::mutex> lk(mu_);
    std::vector<SnapDeltaEnt> out;
    bool all = true;
    for (const auto& kv : keys_) {
      const auto& ring = kv.second;
      bool found = false;
      for (auto rit = ring.rbegin(); rit != ring.rend(); ++rit) {
        if (rit->version <= version) {
          out.push_back({kv.first.first, kv.first.second, *rit});
          found = true;
          break;
        }
      }
      if (!found) all = false;
    }
    if (complete) *complete = all;
    return out;
  }

  size_t key_count() const {
    std::lock_guard<std::mutex> lk(mu_);
    return keys_.size();
  }

  int64_t publishes() const {
    std::lock_guard<std::mutex> lk(mu_);
    return publishes_;
  }

  int64_t evictions() const {
    std::lock_guard<std::mutex> lk(mu_);
    return evictions_;
  }

  // Oldest version still held for (tenant, key); -1 when unknown.
  int64_t OldestOf(uint16_t tenant, int64_t key) const {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = keys_.find({tenant, key});
    if (it == keys_.end() || it->second.empty()) return -1;
    return it->second.front().version;
  }

  void Clear() {
    std::lock_guard<std::mutex> lk(mu_);
    keys_.clear();
    pub_count_.clear();
    latest_ = -1;
    publishes_ = evictions_ = 0;
  }

 private:
  void Trim(std::deque<SnapEntry>* ring) {
    while (ring->size() > static_cast<size_t>(retain_)) {
      const int64_t ev = ring->front().version;
      ring->pop_front();
      evictions_++;
      // One journal entry per version falling out of the retain window
      // — NOT per (key, version): with K keys a round boundary evicts K
      // entries of the same version and would flood the event ring.
      if (ev > evict_emit_ver_) {
        evict_emit_ver_ = ev;
        Events::Get().Emit(EV_SNAP_EVICT, ev, evictions_);
      }
    }
  }

  mutable std::mutex mu_;
  int retain_;
  bool self_commit_ = true;  // false on replicas: ForceLatest only
  int64_t latest_ = -1;  // highest committed (complete-cut) version
  int64_t publishes_ = 0;
  int64_t evictions_ = 0;
  int64_t evict_emit_ver_ = -1;  // highest version already journaled
  std::map<std::pair<uint16_t, int64_t>, std::deque<SnapEntry>> keys_;
  std::map<int64_t, size_t> pub_count_;  // uncommitted versions only
};

}  // namespace bps
