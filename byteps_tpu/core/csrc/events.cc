#include "events.h"

#include <algorithm>
#include <cstdlib>
#include <cstring>

#include "metrics.h"
#include "trace.h"

namespace bps {

namespace {

int64_t EnvLL(const char* name, int64_t dflt) {
  const char* v = getenv(name);
  return v && *v ? atoll(v) : dflt;
}

bool EnvOn(const char* name, bool dflt) {
  const char* v = getenv(name);
  if (!v || !*v) return dflt;
  return strcmp(v, "0") != 0 && strcasecmp(v, "false") != 0 &&
         strcasecmp(v, "off") != 0 && strcasecmp(v, "no") != 0;
}

// Gauge sampling cadence for the scheduler-side history rings. Fixed
// (not a knob): one sample per second is plenty for incident curves
// and bounds the sampling cost at one registry walk per second.
constexpr int64_t kHistorySampleUs = 1000000;

// Cap on how many DISTINCT metric series the history tracks: the gauge
// registry grows with features, and an unbounded map would too.
constexpr size_t kHistoryMaxSeries = 128;

void AppendEvent(std::string* out, const FleetEvent& e, int64_t ts_us) {
  char buf[256];
  snprintf(buf, sizeof(buf),
           "{\"type\":%d,\"name\":\"%s\",\"node\":%d,\"role\":%d,"
           "\"ts_us\":%lld,\"a0\":%lld,\"a1\":%lld,\"a2\":%lld}",
           e.type, EventTypeName(e.type), e.node_id, e.role,
           static_cast<long long>(ts_us), static_cast<long long>(e.a0),
           static_cast<long long>(e.a1), static_cast<long long>(e.a2));
  *out += buf;
}

}  // namespace

const char* EventTypeName(int32_t type) {
  switch (type) {
    case EV_NONE: return "none";
    case EV_EPOCH_PAUSE: return "epoch_pause";
    case EV_EPOCH_RESUME: return "epoch_resume";
    case EV_FLEET_PAUSE: return "fleet_pause";
    case EV_FLEET_RESUME: return "fleet_resume";
    case EV_JOIN: return "join";
    case EV_LEAVE: return "leave";
    case EV_DEATH: return "death";
    case EV_SERVER_RECOVER: return "server_recover";
    case EV_RESEED: return "reseed";
    case EV_SCHED_PARK: return "sched_park";
    case EV_SCHED_REREGISTER: return "sched_reregister";
    case EV_SCHED_RECOVERY_COMMIT: return "sched_recovery_commit";
    case EV_CKPT_SPILL: return "ckpt_spill";
    case EV_CKPT_SEAL: return "ckpt_seal";
    case EV_CKPT_RESTORE: return "ckpt_restore";
    case EV_SNAP_COMMIT: return "snap_commit";
    case EV_SNAP_EVICT: return "snap_evict";
    case EV_REPLICA_LAG: return "replica_lag";
    case EV_CRC_QUARANTINE: return "crc_quarantine";
    case EV_CRC_FAILSTOP: return "crc_failstop";
    case EV_TENANT_STARVED: return "tenant_starved";
    case EV_CHAOS: return "chaos";
    case EV_INSIGHT: return "insight";
    case EV_SHUTDOWN: return "shutdown";
    default: return "unknown";
  }
}

Events::Events()
    : ring_cap_(static_cast<size_t>(EnvLL("BYTEPS_EVENTS_RING", 512))),
      timeline_cap_(0),
      history_depth_(
          static_cast<size_t>(EnvLL("BYTEPS_EVENTS_HISTORY", 128))) {
  if (ring_cap_ < 16) ring_cap_ = 16;
  if (history_depth_ < 8) history_depth_ = 8;
  // The scheduler's timeline holds the whole fleet's journal; size it
  // a few rings deep so one chatty rank cannot evict the others.
  timeline_cap_ = ring_cap_ * 4;
  ring_.resize(ring_cap_);
  armed_.store(EnvOn("BYTEPS_EVENTS_ON", true), std::memory_order_relaxed);
}

Events& Events::Get() {
  static Events* inst = new Events();
  return *inst;
}

void Events::SetNode(int role, int node_id) {
  role_.store(role, std::memory_order_relaxed);
  node_id_.store(node_id, std::memory_order_relaxed);
}

void Events::SetClock(int64_t offset_us) {
  clock_offset_us_.store(offset_us, std::memory_order_relaxed);
}

void Events::Emit(int32_t type, int64_t a0, int64_t a1, int64_t a2) {
  if (!On()) return;
  FleetEvent e;
  e.type = type;
  e.node_id = node_id_.load(std::memory_order_relaxed);
  e.role = role_.load(std::memory_order_relaxed);
  e.ts_us = NowUs();
  e.a0 = a0;
  e.a1 = a1;
  e.a2 = a2;
  std::lock_guard<std::mutex> lk(mu_);
  ring_[ring_head_] = e;
  ring_head_ = (ring_head_ + 1) % ring_cap_;
  ++ring_total_;
  BPS_METRIC_COUNTER_ADD("bps_events_emitted_total", 1);
  // The scheduler is its own ingest path: its clock IS the timebase,
  // so its events enter the timeline directly with offset 0.
  if (e.role == 0 /* ROLE_SCHEDULER */) {
    IngestOneLocked(e, 0);
  }
}

bool Events::FillWire(std::string* out) {
  if (!On()) return false;
  std::lock_guard<std::mutex> lk(mu_);
  if (ring_total_ <= wire_sent_total_) return false;
  int64_t backlog = ring_total_ - wire_sent_total_;
  // Events that rotated out of the ring before a heartbeat could ship
  // them are lost to the timeline (counted in `dropped`).
  if (backlog > static_cast<int64_t>(ring_cap_)) {
    wire_sent_total_ = ring_total_ - static_cast<int64_t>(ring_cap_);
    backlog = static_cast<int64_t>(ring_cap_);
  }
  int count = backlog > kMaxWireEvents ? kMaxWireEvents
                                       : static_cast<int>(backlog);
  EventWireHdr hdr;
  hdr.magic = kEventWireMagic;
  hdr.version = kEventWireVersion;
  hdr.node_id = node_id_.load(std::memory_order_relaxed);
  hdr.role = role_.load(std::memory_order_relaxed);
  hdr.count = count;
  hdr.emitted_total = ring_total_;
  int64_t over = ring_total_ - static_cast<int64_t>(ring_cap_);
  int64_t unsent_over = wire_sent_total_ < over ? over - wire_sent_total_ : 0;
  hdr.dropped = unsent_over;
  hdr.clock_offset_us = clock_offset_us_.load(std::memory_order_relaxed);
  out->append(reinterpret_cast<const char*>(&hdr), sizeof(hdr));
  for (int64_t i = wire_sent_total_; i < wire_sent_total_ + count; ++i) {
    const FleetEvent& e = ring_[static_cast<size_t>(i % ring_cap_)];
    out->append(reinterpret_cast<const char*>(&e), sizeof(e));
  }
  wire_sent_total_ += count;
  return true;
}

size_t Events::PeekWireSize(const void* data, size_t len) {
  if (!data || len < sizeof(EventWireHdr)) return 0;
  EventWireHdr hdr;
  memcpy(&hdr, data, sizeof(hdr));
  if (hdr.magic != kEventWireMagic || hdr.version != kEventWireVersion) {
    return 0;
  }
  if (hdr.count < 0 || hdr.count > kMaxWireEvents) return 0;
  size_t need = sizeof(hdr) +
                static_cast<size_t>(hdr.count) * sizeof(FleetEvent);
  return len >= need ? need : 0;
}

bool Events::Ingest(const void* data, size_t len) {
  size_t need = PeekWireSize(data, len);
  if (need == 0) return false;
  EventWireHdr hdr;
  memcpy(&hdr, data, sizeof(hdr));
  const char* p = static_cast<const char*>(data) + sizeof(hdr);
  std::lock_guard<std::mutex> lk(mu_);
  for (int i = 0; i < hdr.count; ++i) {
    FleetEvent e;
    memcpy(&e, p + static_cast<size_t>(i) * sizeof(FleetEvent),
           sizeof(e));
    // Trust the header's identity over the record's: a record emitted
    // before SetNode (pre-topology) carries -1/-1.
    if (e.node_id < 0) e.node_id = hdr.node_id;
    if (e.role < 0) e.role = hdr.role;
    IngestOneLocked(e, hdr.clock_offset_us);
  }
  BPS_METRIC_COUNTER_ADD("bps_events_ingested_total", hdr.count);
  return true;
}

void Events::IngestOneLocked(const FleetEvent& ev, int64_t offset_us) {
  TimelineEvent t;
  t.ev = ev;
  // PR 5 offset convention: t_scheduler ~= t_local + offset.
  t.aligned_ts_us = ev.ts_us + offset_us;
  timeline_.push_back(t);
  ++ingested_total_;
  while (timeline_.size() > timeline_cap_) {
    timeline_.pop_front();
    ++timeline_dropped_;
  }
}

void Events::SampleHistory(int64_t now_us) {
  if (!On()) return;
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (now_us - last_sample_us_ < kHistorySampleUs) return;
    last_sample_us_ = now_us;
  }
  // Walk the gauge registry OUTSIDE our lock (Metrics has its own),
  // then fold the batch in under ours.
  std::vector<std::pair<std::string, int64_t>> batch;
  Metrics::Get().ForEachGauge([&batch](const std::string& name,
                                       int64_t v) {
    batch.emplace_back(name, v);
  });
  std::lock_guard<std::mutex> lk(mu_);
  for (const auto& kv : batch) {
    auto it = history_.find(kv.first);
    if (it == history_.end()) {
      if (history_.size() >= kHistoryMaxSeries) continue;
      it = history_.emplace(kv.first, History{}).first;
    }
    it->second.samples.emplace_back(now_us, kv.second);
    while (it->second.samples.size() > history_depth_) {
      it->second.samples.pop_front();
    }
  }
}

int64_t Events::emitted_total() {
  std::lock_guard<std::mutex> lk(mu_);
  return ring_total_;
}

int64_t Events::dropped() {
  std::lock_guard<std::mutex> lk(mu_);
  int64_t over = ring_total_ - static_cast<int64_t>(ring_cap_);
  return over > 0 ? over : 0;
}

std::string Events::SnapshotJson() {
  std::lock_guard<std::mutex> lk(mu_);
  std::string out = "{";
  out += "\"on\":" + std::string(On() ? "true" : "false");
  out += ",\"role\":" +
         std::to_string(role_.load(std::memory_order_relaxed));
  out += ",\"node_id\":" +
         std::to_string(node_id_.load(std::memory_order_relaxed));
  out += ",\"ring_capacity\":" + std::to_string(ring_cap_);
  out += ",\"emitted_total\":" + std::to_string(ring_total_);
  int64_t over = ring_total_ - static_cast<int64_t>(ring_cap_);
  out += ",\"dropped\":" + std::to_string(over > 0 ? over : 0);
  out += ",\"clock_offset_us\":" +
         std::to_string(clock_offset_us_.load(std::memory_order_relaxed));
  // Local ring, oldest -> newest (raw local timestamps).
  size_t n = ring_total_ < static_cast<int64_t>(ring_cap_)
                 ? static_cast<size_t>(ring_total_)
                 : ring_cap_;
  size_t start = (ring_head_ + ring_cap_ - n) % ring_cap_;
  out += ",\"events\":[";
  for (size_t i = 0; i < n; ++i) {
    if (i) out += ",";
    const FleetEvent& e = ring_[(start + i) % ring_cap_];
    AppendEvent(&out, e, e.ts_us);
  }
  out += "]";
  // Fleet timeline (scheduler), sorted by ALIGNED timestamp — the
  // clock-skew-corrected fleet order an incident report renders.
  std::vector<const TimelineEvent*> sorted;
  sorted.reserve(timeline_.size());
  for (const auto& t : timeline_) sorted.push_back(&t);
  std::stable_sort(sorted.begin(), sorted.end(),
                   [](const TimelineEvent* a, const TimelineEvent* b) {
                     return a->aligned_ts_us < b->aligned_ts_us;
                   });
  out += ",\"timeline_dropped\":" + std::to_string(timeline_dropped_);
  out += ",\"ingested_total\":" + std::to_string(ingested_total_);
  out += ",\"timeline\":[";
  for (size_t i = 0; i < sorted.size(); ++i) {
    if (i) out += ",";
    AppendEvent(&out, sorted[i]->ev, sorted[i]->aligned_ts_us);
  }
  out += "]";
  out += ",\"history\":{";
  bool first = true;
  for (const auto& kv : history_) {
    if (!first) out += ",";
    first = false;
    out += "\"" + kv.first + "\":[";
    bool f2 = true;
    for (const auto& s : kv.second.samples) {
      if (!f2) out += ",";
      f2 = false;
      out += "[" + std::to_string(s.first) + "," +
             std::to_string(s.second) + "]";
    }
    out += "]";
  }
  out += "}}";
  return out;
}

}  // namespace bps
