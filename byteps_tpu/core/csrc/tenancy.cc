#include "tenancy.h"

#include <cstdlib>
#include <memory>

namespace bps {

namespace {

long EnvLongT(const char* name, long dflt) {
  const char* v = getenv(name);
  return v && *v ? atol(v) : dflt;
}

}  // namespace

uint16_t TenantId() {
  static const uint16_t id = [] {
    long v = EnvLongT("BYTEPS_TENANT_ID", 0);
    if (v < 0) v = 0;
    if (v > 0xffff) v = 0xffff;
    return static_cast<uint16_t>(v);
  }();
  return id;
}

const std::string& TenantName() {
  static const std::string name = [] {
    const char* v = getenv("BYTEPS_TENANT_NAME");
    if (v && *v) return std::string(v);
    if (TenantId() == 0) return std::string("default");
    return "tenant" + std::to_string(TenantId());
  }();
  return name;
}

int TenantWeight() {
  static const int w = [] {
    long v = EnvLongT("BYTEPS_TENANT_WEIGHT", 1);
    if (v < 1) v = 1;
    if (v > (1 << 20)) v = 1 << 20;
    return static_cast<int>(v);
  }();
  return w;
}

int64_t TenantQuantum() {
  static const int64_t q = [] {
    long v = EnvLongT("BYTEPS_TENANT_QUANTUM_BYTES", 64 * 1024);
    if (v < 1024) v = 1024;
    return static_cast<int64_t>(v);
  }();
  return q;
}

Tenancy& Tenancy::Get() {
  static Tenancy* inst = new Tenancy();
  return *inst;
}

TenantStat* Tenancy::OfSlow(uint16_t tenant) {
  std::lock_guard<std::mutex> lk(mu_);
  auto& p = stats_[tenant];
  if (!p) p = std::make_unique<TenantStat>();
  if (tenant < kFastTenants) {
    fast_[tenant].store(p.get(), std::memory_order_release);
  }
  return p.get();
}

std::vector<uint16_t> Tenancy::Known() {
  std::lock_guard<std::mutex> lk(mu_);
  std::vector<uint16_t> out;
  out.reserve(stats_.size());
  for (const auto& kv : stats_) out.push_back(kv.first);
  return out;
}

std::string Tenancy::SnapshotJson(int64_t now_us) {
  std::lock_guard<std::mutex> lk(mu_);
  std::string out = "{";
  bool first = true;
  for (const auto& kv : stats_) {
    const TenantStat& s = *kv.second;
    if (!first) out += ",";
    first = false;
    const int64_t depth = s.queue_depth.load(std::memory_order_relaxed);
    const int64_t last = s.last_serve_us.load(std::memory_order_relaxed);
    // Starvation age: how long the tenant has had work queued without
    // being served. 0 when its lanes are empty (nothing owed) or it
    // was never served but also never queued.
    int64_t starve_us = 0;
    if (depth > 0) {
      starve_us = last > 0 ? now_us - last : now_us;
      if (starve_us < 0) starve_us = 0;
    }
    out += "\"" + std::to_string(kv.first) + "\":{";
    out += "\"push_bytes\":" +
           std::to_string(s.push_bytes.load(std::memory_order_relaxed));
    out += ",\"reply_bytes\":" +
           std::to_string(s.reply_bytes.load(std::memory_order_relaxed));
    out += ",\"ops\":" +
           std::to_string(s.ops.load(std::memory_order_relaxed));
    out += ",\"sum_us\":" +
           std::to_string(s.sum_us.load(std::memory_order_relaxed));
    out += ",\"queue_depth\":" + std::to_string(depth);
    out += ",\"dispatched\":" +
           std::to_string(s.dispatched.load(std::memory_order_relaxed));
    out += ",\"starve_us\":" + std::to_string(starve_us);
    out += "}";
  }
  out += "}";
  return out;
}

}  // namespace bps
