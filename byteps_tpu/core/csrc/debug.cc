#include "debug.h"

#include <execinfo.h>
#include <signal.h>
#include <unistd.h>

#include <cstdio>

namespace bps {

namespace {

void CrashHandler(int sig) {
  void* frames[64];
  int n = backtrace(frames, 64);
  dprintf(STDERR_FILENO, "[byteps-tpu crash] signal %d, backtrace:\n", sig);
  backtrace_symbols_fd(frames, n, STDERR_FILENO);
  signal(sig, SIG_DFL);
  raise(sig);
}

}  // namespace

void InstallCrashHandler() {
  static bool done = false;
  if (done) return;
  done = true;
  // Prime backtrace's lazy libgcc load now — calling it first inside a
  // SIGABRT handler can deadlock in malloc when the heap is corrupted.
  void* frames[4];
  backtrace(frames, 4);
  signal(SIGABRT, CrashHandler);
  signal(SIGSEGV, CrashHandler);
  signal(SIGBUS, CrashHandler);
  signal(SIGFPE, CrashHandler);
}

}  // namespace bps
