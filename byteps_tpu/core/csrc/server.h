// The CPU-summation parameter server.
//
// Capability parity: reference byteps/server/server.{h,cc} (SURVEY.md
// §2.3): a KV request handler plus an engine thread pool
// (BYTEPS_SERVER_ENGINE_THREAD, default 4) so summation never blocks the
// network threads; per-key aggregation buffers; sync mode releases pulls
// once all num_worker pushes for a key arrived; async mode
// (BYTEPS_ENABLE_ASYNC) keeps server-resident parameters, applies pushes
// immediately and replies immediately. Summation via CpuReducer.
//
// Fresh design notes: keys are routed to engine threads by hash, which
// serialises all work for one key on one thread — per-key ordering without
// per-key locks. Sync-mode rounds are double-buffered by version parity
// (head.version), tolerating the legal one-round skew between workers.
//
// Small-tensor fusion (CMD_MULTI_PUSH / CMD_MULTI_PULL): a fused frame is
// unpacked on the van thread into one EngineTask per sub-operation, each
// routed to its key's engine thread exactly like a single frame — per-key
// total ordering and the KeyStore single-writer invariant hold unchanged.
// The sub-tasks share a MultiReply accumulator; each sub-op's reply (ack
// or pull response) lands in its slot, and the LAST one to settle sends a
// single batched CMD_MULTI_ACK / CMD_MULTI_PULL_RESP frame back. A
// sub-push that would PARK records its ack at park time instead of
// withholding the batch (ack-on-park, see Process): the batched ack gates
// the worker's fused pull for every key in the frame, and those pulls are
// what recycle the slot a parked push waits on — gating acks on slot
// recycling would let two workers' frames deadlock through each other
// (ack -> slot-recycle -> pull -> ack).
#pragma once

#include <atomic>
#include <condition_variable>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include "ckpt.h"
#include "common.h"
#include "compressor.h"
#include "elastic.h"
#include "postoffice.h"
#include "snapshot.h"
#include "tenancy.h"

namespace bps {

class BytePSServer {
 public:
  // replica_of >= 0 starts the engine in READ-REPLICA mode (ISSUE 16):
  // no training data plane — the process serves CMD_SNAP_PULL from a
  // snapshot store fed by per-round deltas polled off primary server
  // rank `replica_of` (StartReplicaPoll, called once the postoffice
  // joined the fleet and holds the address book).
  void Start(Postoffice* po, int engine_threads, bool async_mode,
             int replica_of = -1);
  // Replica only: spawn the delta-poll thread. Separate from Start
  // because Start runs BEFORE the postoffice forms (engine threads must
  // exist first) and the poll needs the primary's book entry.
  void StartReplicaPoll();
  void Handle(Message&& msg, int fd);  // van-thread entry; enqueues to engine
  void Stop();
  ~BytePSServer() { Stop(); }

  // Elastic worker membership (ISSUE 8; van thread, from the
  // postoffice's fleet-resize callback). A JOIN pushes a new roster
  // epoch activating at `join_round`/`join_bcast` — rounds already in
  // flight keep completing against the old contributor set. A removal
  // (graceful leave kind 1, death shrink kind 2) erases the id from
  // every roster and, for a death, enqueues a rollback task per engine
  // thread: the dead rank's partial contributions are discarded, the
  // survivors' retained bytes re-summed, and every slot's readiness /
  // recycle re-evaluated against the shrunk roster.
  // `tenant` scopes the change (ISSUE 9): rounds are per-tenant
  // counters, so the roster epoch lands in that tenant's history only
  // and the re-eval/rollback tasks visit only that tenant's keys.
  void OnFleetResize(int kind, int affected, int64_t join_round,
                     int64_t join_bcast, int tenant);

  // Durable restore (ISSUE 18): newest checksum-valid checkpoint
  // version found on disk at Start, -1 when armed but nothing valid,
  // -2 when BYTEPS_CKPT_RESTORE is not armed. The c_api glue forwards
  // this to the postoffice BEFORE registration so the report rides the
  // CMD_REGISTER frame.
  int64_t durable_ckpt_version() const { return durable_version_; }
  bool restore_armed() const { return restore_armed_; }

 private:
  // Accumulator for one fused frame's batched reply. subs/data are
  // indexed by the request table position, so the reply table preserves
  // the worker's sub-operation order; each slot is written by exactly one
  // engine thread (the key's owner) and `remaining`'s final decrement
  // publishes them to the flusher.
  struct MultiReply {
    int fd = -1;
    int32_t req_id = -1;
    int32_t reply_cmd = 0;  // CMD_MULTI_ACK or CMD_MULTI_PULL_RESP
    uint16_t tenant = 0;    // the frame's tenant (one frame, one tenant)
    int64_t first_key = 0;
    std::atomic<int> remaining{0};
    std::vector<SubHeader> subs;
    std::vector<std::vector<char>> data;  // owned reply payload copies
  };

  struct KeyStore;
  struct EngineQueue;

  // One unit of engine work: a single frame, or one sub-operation of a
  // fused frame (batch != nullptr; sub_idx = its reply slot).
  struct EngineTask {
    Message msg;
    int fd = -1;
    std::shared_ptr<MultiReply> batch;
    int sub_idx = -1;
    // Set when a fused sub-push records its ack at park time
    // (ack-on-park, see Process CMD_PUSH): the parked replay must not
    // reply a second time.
    bool replied = false;
    // Set by ReplayParked: a parked task re-entering Process is the
    // ORIGINAL request being completed, not a wire duplicate — it must
    // bypass the dedup window its own first arrival recorded.
    bool from_park = false;
  };

  struct KeyStore {
    // Owning tenant (ISSUE 9): set at INIT_KEY from the declaring
    // frame. The store map keys on TenantKey(tenant, key), so two
    // tenants' colliding tids can never alias; this field is the
    // back-reference for completion counts, rosters, and accounting.
    uint16_t tenant = 0;
    // Bare wire key, set at INIT_KEY: the snapshot publication hook
    // (RoundReady) needs the full (tenant, key) identity and only has
    // the KeyStore in hand.
    int64_t key = -1;
    // Idempotent-retry dedup window (ISSUE 3): per sender, the last
    // data-plane request seen for this key. Per key per sender at most
    // ONE request chain is outstanding (the worker's per-key ordering
    // invariant), so a single record per sender is a complete window:
    // a request whose req_id matches the record is a wire duplicate
    // (chaos dup, or a retry resend) — it is acked/served again from
    // recorded state but NEVER re-applied, which is what keeps chaos
    // runs bit-identical to fault-free runs. An unreplied match (the
    // original is parked) answers CMD_KEEPALIVE so the worker's retry
    // budget never expires on a legitimately slow round. Header-only
    // state: pull replays re-serve from the slot/param buffers (see
    // last_round below), so the window costs no payload copies.
    // Touched only by this key's engine thread (hash routing).
    struct SenderRec {
      int32_t req_id = -1;
      bool replied = false;
      MsgHeader reply_head{};
    };
    std::unordered_map<int, SenderRec> seen;
    // Round a recycled slot LAST served, and its data retained: a
    // replayed sync pull whose PULL_RESP was lost can be re-served
    // from slot[s]/comp_reply[s] until the slot is reassigned — which
    // per-key chaining guarantees cannot happen before every worker
    // completed that round's pull (round r+2's first push needs all
    // r+1 pushes, which need all r pulls delivered). The one corner
    // that CAN outrun this window — deep pipelining parking r+2's
    // push before our round-r reply was delivered — is detected and
    // fail-stopped with a wire CMD_ERROR instead of serving stale
    // bytes (see Process CMD_PULL).
    int last_round[2] = {-1, -1};
    // Latest broadcast round pushed (bcast replay fallback: param
    // still holds exactly that round's bytes).
    int last_bcast_round = -1;

    int64_t len = 0;  // decompressed payload bytes
    int32_t dtype = BPS_FLOAT32;
    std::string comp_config;
    std::unique_ptr<Compressor> compressor;  // for decompressing pushes
    std::vector<float> scratch;              // decompression target
    // Pull-leg compression (reference §2.2 server symmetry: decompress
    // pushes, sum, RE-COMPRESS pull responses so the DCN pays compressed
    // freight in both directions). Separate instance: momentum is a
    // push-direction decorator and must not be re-applied to aggregates;
    // error feedback is kept — the server accumulates its own re-encode
    // residual into the next round (DoubleSqueeze-style two-way EF).
    std::unique_ptr<Compressor> reply_comp;
    std::vector<char> comp_reply[2];  // cached encode, one per live round
    // Stale-reply guard (ISSUE 16 satellite): the ROUND each cached
    // re-encode was produced for, stamped at encode time and asserted
    // at every serve site (ReplyPull / ServeRetainedPull /
    // AnswerDuplicate via CachedReplyValid). Before the tag, the
    // cached bytes were guarded only by round checks on the SLOT — a
    // dedup-replayed pull racing a slot re-encode could ship a newer
    // round's bytes under an older round's header. -1 = no valid cache.
    int comp_reply_round[2] = {-1, -1};
    // Quantized wire (ISSUE 6): true when this key's pushes may arrive
    // block-quantized and its pull replies are re-quantized — quant
    // armed fleet-wide, codec-less, float32, at least the minimum raw
    // size (the worker computes the same predicate, so the two sides
    // agree without negotiation). qreply mirrors comp_reply: the
    // aggregate is encoded ONCE per round at round-ready and every
    // flagged pull (and replay) serves the same cached bytes.
    // Deliberately NO server-side EF residual on this leg: a hot
    // replacement starts residual-less, so any server-resident carry
    // would make post-recovery replies diverge from the fault-free
    // run — breaking the recovery bit-identity contract. The reply
    // rounding error is ~|aggregate|/254 per element, round-to-nearest
    // (near-unbiased); the convergence A/B (BENCH_compression_r06)
    // shows the worker-side push EF alone tracks dense (docs/rationale).
    bool quant_ok = false;
    std::vector<char> qreply[2];  // cached quantized encode per slot
    int qreply_round[2] = {-1, -1};  // round tag (see comp_reply_round)
    // sync mode: double-buffered rounds. round[s] is the full round
    // number (head.version) the slot currently accumulates/serves;
    // pushes/pulls for a LATER round that maps to a busy slot are parked
    // and replayed when the slot recycles — deep pipelining (3+ rounds
    // of one tensor in flight) backpressures instead of crashing.
    std::vector<char> slot[2];
    int push_count[2] = {0, 0};
    int pull_count[2] = {0, 0};
    bool ready[2] = {false, false};
    int round[2] = {-1, -1};
    // Elastic membership (ISSUE 8; maintained only when BYTEPS_ELASTIC):
    // per-slot contributor roster + retained decoded contributions (the
    // death-shrink rollback's rebuild source — freed at round ready).
    ElasticSlot er[2];
    // Contributor count of the round a slot serves / last served: the
    // worker-side mean divisor, carried on every sync PULL_RESP's arg1
    // so a pull issued before a membership change still divides by the
    // round's ACTUAL roster size. Mirrors round[]/last_round[].
    int contrib_n[2] = {0, 0};
    int last_contrib_n[2] = {0, 0};
    std::vector<EngineTask> pending_pulls[2];
    std::vector<EngineTask> parked_pushes[2];
    // async mode: server-resident value
    std::vector<char> param;
    bool param_init = false;
    // Total async pushes applied to this key (any worker). Returned on
    // async acks/pull responses (arg1) so workers can compute pull
    // staleness; single-writer per key via the hash-routed engine.
    int64_t async_pushes = 0;
    // Broadcast: per-round buffers keyed by the root's round counter
    // (head.version). A round-r BCAST_PULL is served exactly round r's
    // bytes — never a previous or FUTURE round's, even when the root
    // races ahead — and a round's buffer is freed once all num_workers-1
    // non-root pulls for it were served.
    struct BcastRound {
      std::vector<char> data;
      int served = 0;
      // Expected non-root pulls, FROZEN at push time from the round's
      // roster: a bcast pushed before a join must not wait for the
      // joiner, and one pushed after expects it (ISSUE 8).
      int waiters = 0;
    };
    std::unordered_map<int, BcastRound> bcast_rounds;
    std::vector<std::pair<int, MsgHeader>> pending_bcast_pulls;
  };

  void EngineLoop(int tid);
  void Process(EngineTask&& task);
  // Dedup-window hit: answer a wire duplicate from recorded state
  // (re-ack / re-serve / keepalive) without touching key state.
  void AnswerDuplicate(KeyStore* ks, KeyStore::SenderRec& rec,
                       EngineTask& task);
  // Server -> worker control frames outside the reply tables.
  void SendKeepalive(const EngineTask& t);
  void SendWireError(int fd, const MsgHeader& req, const std::string& why);
  // Close the dedup-window entry for (sender, req_id) with the reply
  // header just sent, so a later wire duplicate replays it.
  void MarkReplied(KeyStore* ks, int32_t sender, int32_t req_id,
                   const MsgHeader& reply_head);
  // Fused-frame entry (van thread): unpack, account, fan sub-operations
  // out to their keys' engine threads under a shared MultiReply.
  void HandleMulti(Message&& msg, int fd);
  // Reply path shared by single and fused tasks: direct van send when the
  // task is a lone frame, reply-slot capture (and batch flush when it was
  // the last outstanding sub-op) when it belongs to a fused frame.
  void SendReply(const EngineTask& t, MsgHeader& head,
                 const void* data = nullptr, int64_t len = 0);
  void FlushMulti(const std::shared_ptr<MultiReply>& batch);
  // Store lookup is (tenant, key)-namespaced (ISSUE 9); tenant 0
  // composes to the bare key, so a legacy fleet's store map — and its
  // `key % threads` engine routing — is bit-for-bit the pre-tenant one.
  KeyStore* GetStore(uint16_t tenant, int64_t key);
  // Route an engine task to its key's thread through the per-tenant
  // DRR lanes (the one enqueue point: depth/cost accounting lives
  // here). `lane` overrides the DRR lane the task is queued under
  // (default: the frame's tenant) — the serving path enqueues reader
  // traffic under kServingLane without touching the header's tenant,
  // which the snapshot lookup and the reply stamping still need.
  void EnqueueTask(EngineTask&& task, int lane = -1);
  // Zero-cost control marker into a specific queue's tenant lane
  // (roster re-eval / rollback tasks).
  void EnqueueTaskTo(EngineQueue& eq, EngineTask&& task);
  // Returns true when this pull completed the round and recycled the
  // slot (caller must then ReplayParked).
  bool ReplyPull(KeyStore* ks, int slot, const EngineTask& t);
  // Serve a pull for an already-COMPLETED round from the retained slot
  // data (the replay window / a re-seeded aggregate) without advancing
  // pull_count — the round's accounting is final; this is re-delivery.
  void ServeRetainedPull(KeyStore* ks, int slot, const EngineTask& t);
  // Recovery incarnation only: a data-plane op for a key that has not
  // been re-declared yet parks here (keepalive keeps the worker's retry
  // budget fresh) and replays when its INIT_KEY arrives. Returns true
  // when the task was parked.
  bool ParkUndeclared(EngineTask&& task);
  // End of the re-seed grace window: exit recover mode (restoring the
  // unknown-key fatal) and fail any ops still parked without their
  // re-declare — they would otherwise hang forever, their keepalives
  // keeping the sender's retry budget fresh. Idempotent; safe to race
  // from multiple engine threads.
  void EndReseedGrace();
  void ReplayParked(KeyStore* ks, int slot);
  void ReplyBcastPull(KeyStore* ks, int fd, const MsgHeader& req);
  void ServeBcastRound(KeyStore* ks, int round, int fd,
                       const MsgHeader& req);

  // Encode one round's aggregate into qreply[slot] (quant-eligible keys
  // only; called at round-ready, exactly like the comp_reply encode).
  void EncodeQuantReply(KeyStore* ks, int slot);

  // --- snapshot serving (ISSUE 16) ---
  // CMD_SNAP_PULL: one reader's request for one key's snapshot —
  // resolve against the store, echo the served version, reply on the
  // arrival fd (readers are raw TCP clients, never registered nodes).
  void ProcessSnapPull(EngineTask& task);
  // CMD_SNAP_SUB (primary): a replica's delta poll — gather every
  // committed entry past its watermark (bounded per frame) into one
  // CMD_SNAP_DELTA (SubHeader table + payloads, the CMD_MULTI layout).
  void ProcessSnapSub(EngineTask& task);
  // CMD_SNAP_DELTA (replica): install the batch (idempotent) and adopt
  // the primary's committed watermark.
  void ProcessSnapDelta(EngineTask& task);
  // Replica delta-poll loop: dial the primary, send CMD_SNAP_SUB with
  // our highest held version every poll interval (a lost SUB or DELTA
  // is repaired by the next poll — retry semantics without a retry
  // layer), re-dial on failure from the live address book (so a
  // hot-replaced primary is picked up).
  void ReplicaPollLoop();

  // --- durable checkpoints (ISSUE 18) ---
  // Install a finished aggregate for round `ver` into the KeyStore's
  // parity slot: the shared re-seed/restore machinery (slot bytes,
  // last_round / last_contrib_n, cached-encode invalidation, partial
  // supersede, parked-pull release). Factored from CMD_RESEED so the
  // checkpoint restore path installs through the identical invariants.
  // `why` names the installer in the skip diagnostics. Engine thread
  // (the key's owner) only.
  void InstallAggregate(KeyStore* ks, int64_t ver, const char* data,
                        size_t len, const char* why);
  // Restore hook (CMD_INIT_KEY): on the first declared key, load the
  // fleet-committed restore epoch's checkpoint from disk (fail-stop on
  // any mismatch — never a silent cold start); then install this key's
  // restored aggregate and publish it into the snapshot store at the
  // restore round.
  void MaybeInstallRestored(KeyStore* ks);
  // Spill trigger (RoundReady, after snapshot Publish): when the
  // committed snapshot version advanced to a spill boundary, collect
  // the cut (shared_ptr, no copy) and hand it to the async writer.
  void MaybeSpillCkpt();

  // The round is complete (every expected contributor summed): seal the
  // contribution roster, encode the cached replies, release this
  // round's pending pulls, and replay parked pushes when a pull
  // recycled the slot. Shared by the push path and the shrink rollback.
  void RoundReady(KeyStore* ks, int slot);
  // Expected contributor count for round `version` of a sync key: the
  // key's TENANT roster size when elastic, the tenant's live worker
  // count otherwise (tenant 0 falls back to the fleet size until the
  // address book arrives — the pre-tenant behavior).
  int ExpectedContributors(const KeyStore* ks, int64_t version);
  // The tenant's worker count from the address book, with the legacy
  // tenant-0 fallback above.
  int TenantWorkerCount(uint16_t tenant);
  // True when round `version`'s contributor set is complete. The
  // elastic check is EXACT set equality against the round's roster —
  // see ElasticSlot::PushersMatch for why superset would be unsound
  // during a shrink.
  bool RoundComplete(KeyStore* ks, int slot, int64_t version);
  // True when every roster member pulled round `version` (recycle).
  bool RoundServed(KeyStore* ks, int slot, int64_t version);
  // Death-shrink rollback for this engine thread's keys (tid-owned),
  // scoped to the departed worker's TENANT (other tenants' slots never
  // held its contributions): discard `dead`'s partial contributions,
  // rebuild sums from the survivors' retained bytes, drop its
  // parked/pending ops, and re-evaluate every slot against the shrunk
  // roster.
  void ShrinkWorker(int tid, int dead, uint16_t tenant);

  // Elastic state: armed flag + per-TENANT epoch roster histories
  // (activation-round keyed in that tenant's round space; see
  // elastic.h). Tenant 0 is pre-seeded from the formation env at
  // Start (the PR 8 behavior, byte for byte); other tenants
  // initialise lazily from the address book.
  bool elastic_ = false;
  RosterHistory* RosterOf(uint16_t tenant);
  std::mutex roster_mu_;  // guards the map shape, not the histories
  std::map<uint16_t, std::unique_ptr<RosterHistory>> rosters_;

  Postoffice* po_ = nullptr;
  bool async_ = false;
  // Engine service-rate cap per engine thread (ISSUE 9;
  // BYTEPS_SERVER_ENGINE_PACE_MBPS, 0 = off): after each dispatched
  // data task the engine sleeps cost/rate. Ops knob for capping a
  // shared server's CPU burn — and the calibration lever the
  // weighted-split QoS tests/bench use to create honest engine
  // contention on a loopback fleet (an unloaded engine never
  // backlogs, and fair-share is only observable under backlog).
  int64_t engine_pace_bps_ = 0;
  // Quantized wire knobs (ISSUE 6), read from the same env the worker
  // reads so both sides compute identical eligibility.
  bool wire_quant_ = false;          // BYTEPS_WIRE_QUANT
  int quant_block_ = 64;             // BYTEPS_WIRE_QUANT_BLOCK
  int64_t quant_min_bytes_ = 1024;   // BYTEPS_WIRE_QUANT_MIN_BYTES
  // Replacement incarnation (DMLC_RECOVER_RANK set): data-plane ops may
  // legally arrive before their keys are re-declared — park them
  // instead of treating an unknown key as a protocol violation. The
  // state is bounded: once the grace deadline passes, EndReseedGrace
  // clears the flag and the fatal is back — a genuinely undeclared key
  // (a real protocol bug, not a re-seed race) crashes loudly instead
  // of hanging silently. Atomic: engine threads race the lazy expiry.
  std::atomic<bool> recover_mode_{false};
  int64_t recover_grace_end_us_ = 0;  // written once in Start
  std::mutex store_mu_;  // guards store_ map shape + pre_declare_parked_
  std::unordered_map<int64_t, std::unique_ptr<KeyStore>> store_;
  std::unordered_map<int64_t, std::vector<EngineTask>> pre_declare_parked_;

  // Per-tenant FIFO lanes dispatched by weighted deficit round robin
  // (ISSUE 9, tenancy.h): whenever two tenants' lanes are both
  // backlogged, the engine serves their bytes in the ratio of their
  // BYTEPS_TENANT_WEIGHT shares — a heavy tenant cannot starve a light
  // one. `drr` mirrors the lanes cost-for-cost (enqueue/pop pairs run
  // under `mu`); with a single active tenant the picker short-circuits
  // to plain FIFO, keeping single-tenant dispatch byte-for-byte PR 8's.
  struct EngineQueue {
    EngineQueue(int64_t quantum, WeightedDrr::WeightFn wf)
        : drr(quantum, std::move(wf)) {}
    std::mutex mu;
    std::condition_variable cv;
    std::map<uint16_t, std::deque<EngineTask>> lanes;
    WeightedDrr drr;
  };
  std::vector<std::unique_ptr<EngineQueue>> queues_;
  std::vector<std::thread> threads_;
  std::atomic<bool> stopped_{false};

  // --- snapshot serving (ISSUE 16) ---
  // The DRR lane reader traffic rides: a reserved lane id no tenant can
  // collide with (tenants are worker-advertised and the fleet never
  // registers 0xFFFF), weighted by BYTEPS_SERVING_WEIGHT — so a reader
  // swarm shares the engine at a capped ratio and provably cannot move
  // the training digest.
  static constexpr uint16_t kServingLane = 0xFFFF;
  SnapStore snaps_;
  // BYTEPS_SNAPSHOT_RETAIN: per-key retention ring depth; 0 disables
  // snapshot publication (and with it the whole serving path) on this
  // node.
  int snapshot_retain_ = 4;
  int64_t serving_weight_ = 1;  // BYTEPS_SERVING_WEIGHT
  // Bound one CMD_SNAP_DELTA frame's raw payload; a lagging replica
  // catches up over successive polls instead of one giant frame.
  int64_t snap_delta_max_bytes_ = 16 << 20;
  // Replica mode: the primary server RANK this process mirrors
  // (BYTEPS_REPLICA_OF); -1 = a normal training-plane server.
  int replica_of_ = -1;
  std::thread replica_thread_;
  // Replica poll thread only: edge-triggers the EV_REPLICA_LAG journal
  // entry on the crossing into REPLICA-LAGGING (ISSUE 20).
  bool replica_lagging_ = false;

  // --- durable checkpoints (ISSUE 18) ---
  // BYTEPS_CKPT_DIR: spill root; empty = checkpointing off entirely
  // (the server is then byte-for-byte the pre-checkpoint build: no
  // writer thread, no metrics, no restore scan).
  std::string ckpt_dir_;
  int ckpt_every_ = 1;   // BYTEPS_CKPT_EVERY: spill every Nth version
  int ckpt_retain_ = 2;  // BYTEPS_CKPT_RETAIN: on-disk dirs kept
  std::string ckpt_chaos_;  // BYTEPS_CHAOS_CKPT: "" / truncate / bitflip
  bool restore_armed_ = false;        // BYTEPS_CKPT_RESTORE
  int64_t durable_version_ = -2;      // newest valid on disk (Start)
  CkptWriter ckpt_writer_;
  // Restore install state: the checkpoint is loaded from disk ONCE (on
  // the first CMD_INIT_KEY, after the restore epoch arrived with the
  // address book) into restored_, then drained key-by-key as the
  // worker re-declares; restore_round_ is the fleet-committed epoch.
  std::once_flag restore_once_;
  std::mutex restore_mu_;
  std::map<std::pair<uint16_t, int64_t>, CkptItem> restored_;
  int64_t ckpt_restore_round_ = -1;
};

}  // namespace bps
