// The CPU-summation parameter server.
//
// Capability parity: reference byteps/server/server.{h,cc} (SURVEY.md
// §2.3): a KV request handler plus an engine thread pool
// (BYTEPS_SERVER_ENGINE_THREAD, default 4) so summation never blocks the
// network threads; per-key aggregation buffers; sync mode releases pulls
// once all num_worker pushes for a key arrived; async mode
// (BYTEPS_ENABLE_ASYNC) keeps server-resident parameters, applies pushes
// immediately and replies immediately. Summation via CpuReducer.
//
// Fresh design notes: keys are routed to engine threads by hash, which
// serialises all work for one key on one thread — per-key ordering without
// per-key locks. Sync-mode rounds are double-buffered by version parity
// (head.version), tolerating the legal one-round skew between workers.
#pragma once

#include <atomic>
#include <condition_variable>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "compressor.h"
#include "postoffice.h"

namespace bps {

class BytePSServer {
 public:
  void Start(Postoffice* po, int engine_threads, bool async_mode);
  void Handle(Message&& msg, int fd);  // van-thread entry; enqueues to engine
  void Stop();
  ~BytePSServer() { Stop(); }

 private:
  struct KeyStore {
    int64_t len = 0;  // decompressed payload bytes
    int32_t dtype = BPS_FLOAT32;
    std::string comp_config;
    std::unique_ptr<Compressor> compressor;  // for decompressing pushes
    std::vector<float> scratch;              // decompression target
    // Pull-leg compression (reference §2.2 server symmetry: decompress
    // pushes, sum, RE-COMPRESS pull responses so the DCN pays compressed
    // freight in both directions). Separate instance: momentum is a
    // push-direction decorator and must not be re-applied to aggregates;
    // error feedback is kept — the server accumulates its own re-encode
    // residual into the next round (DoubleSqueeze-style two-way EF).
    std::unique_ptr<Compressor> reply_comp;
    std::vector<char> comp_reply[2];  // cached encode, one per live round
    // sync mode: double-buffered rounds. round[s] is the full round
    // number (head.version) the slot currently accumulates/serves;
    // pushes/pulls for a LATER round that maps to a busy slot are parked
    // and replayed when the slot recycles — deep pipelining (3+ rounds
    // of one tensor in flight) backpressures instead of crashing.
    std::vector<char> slot[2];
    int push_count[2] = {0, 0};
    int pull_count[2] = {0, 0};
    bool ready[2] = {false, false};
    int round[2] = {-1, -1};
    std::vector<std::pair<int, MsgHeader>> pending_pulls[2];
    std::vector<std::pair<Message, int>> parked_pushes[2];
    // async mode: server-resident value
    std::vector<char> param;
    bool param_init = false;
    // Total async pushes applied to this key (any worker). Returned on
    // async acks/pull responses (arg1) so workers can compute pull
    // staleness; single-writer per key via the hash-routed engine.
    int64_t async_pushes = 0;
    // Broadcast: per-round buffers keyed by the root's round counter
    // (head.version). A round-r BCAST_PULL is served exactly round r's
    // bytes — never a previous or FUTURE round's, even when the root
    // races ahead — and a round's buffer is freed once all num_workers-1
    // non-root pulls for it were served.
    struct BcastRound {
      std::vector<char> data;
      int served = 0;
    };
    std::unordered_map<int, BcastRound> bcast_rounds;
    std::vector<std::pair<int, MsgHeader>> pending_bcast_pulls;
  };

  struct EngineTask {
    Message msg;
    int fd;
  };

  void EngineLoop(int tid);
  void Process(Message&& msg, int fd);
  KeyStore* GetStore(int64_t key);
  // Returns true when this pull completed the round and recycled the
  // slot (caller must then ReplayParked).
  bool ReplyPull(KeyStore* ks, int slot, int fd, const MsgHeader& req);
  void ReplayParked(KeyStore* ks, int slot);
  void ReplyBcastPull(KeyStore* ks, int fd, const MsgHeader& req);
  void ServeBcastRound(KeyStore* ks, int round, int fd,
                       const MsgHeader& req);

  Postoffice* po_ = nullptr;
  bool async_ = false;
  std::mutex store_mu_;  // guards store_ map shape only
  std::unordered_map<int64_t, std::unique_ptr<KeyStore>> store_;

  struct EngineQueue {
    std::mutex mu;
    std::condition_variable cv;
    std::deque<EngineTask> q;
  };
  std::vector<std::unique_ptr<EngineQueue>> queues_;
  std::vector<std::thread> threads_;
  std::atomic<bool> stopped_{false};
};

}  // namespace bps
