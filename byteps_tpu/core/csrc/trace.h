// Fleet-wide distributed tracing (ISSUE 5).
//
// Two fixed-capacity, drop-oldest event rings shared by every role:
//
// - the MAIN ring (BYTEPS_TRACE_ON, capacity BYTEPS_TRACE_RING_EVENTS):
//   the Chrome-trace timeline — worker compress/push/pull spans, server
//   recv/park/sum/reply spans, van wire instants, scheduler membership
//   events, plus Chrome flow events ("s"/"t"/"f") whose ids are derived
//   from (sender node id, req_id) — both already cross the wire — so a
//   worker's push span visually stitches to its server's sum span and
//   back to the ack in the merged fleet view
//   (python -m byteps_tpu.monitor.timeline).
// - the FLIGHT RECORDER (BYTEPS_FLIGHT_RECORDER, default ON, capacity
//   BYTEPS_FLIGHT_RECORDER_EVENTS): a small always-on ring of
//   SIGNIFICANT events only (epoch pause/resume, reseeds, resends,
//   keepalives, chaos injections, reconnects, failures) that is
//   auto-dumped to BYTEPS_TRACE_DIR on fatal CHECK, failure SHUTDOWN,
//   and recovery EPOCH_PAUSE/RESUME — so every failure ships with the
//   last N events from every rank, with zero configuration.
//
// The replaced design was worker-only (TraceEvent lived in worker.h): a
// fat pull span could not distinguish "server summation slow" from "a
// peer worker is late" from "the wire is congested" — exactly the
// attribution the BytePS paper needed for its CPU-summation PS design.
//
// Concurrency: rings are mutex-guarded (emit sites are either cold-path
// or already serialised per connection/key); the armed checks are one
// relaxed atomic load, so a disabled ring costs one branch per site.
// Like the Metrics registry, the singleton is intentionally leaked so
// teardown paths (goodbye frames, fatal dumps) can always record.
#pragma once

#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace bps {

int64_t NowUs();  // CLOCK_MONOTONIC microseconds (defined in trace.cc)

enum TracePhase : int32_t {
  TRACE_SPAN = 0,       // Chrome ph "X" (ts + dur)
  TRACE_INSTANT = 1,    // ph "i"
  TRACE_FLOW_OUT = 2,   // ph "s" — flow starts here
  TRACE_FLOW_STEP = 3,  // ph "t" — flow passes through here
  TRACE_FLOW_IN = 4,    // ph "f" bp "e" — flow ends here
};

struct TraceRec {
  char name[24] = {0};
  int64_t ts_us = 0;
  int64_t dur_us = 0;   // spans only
  int64_t key = 0;
  int64_t flow = 0;     // flow events: the stitch id; 0 = none
  int32_t phase = TRACE_INSTANT;
  int32_t peer = -1;    // peer node id (-1 = n/a)
  int32_t req_id = -1;
  int32_t round = -1;   // head.version where known
  int32_t aux = 0;      // cmd for wire instants; free-form otherwise
  // Byte labels for data-carrying spans (ISSUE 7 satellite): what
  // actually crossed the wire vs the decoded length — the quantized
  // wire's push/qdecode spans dump these so the timeline report can
  // show per-span quantized-vs-raw freight. 0/0 = unlabelled.
  int64_t wire_bytes = 0;
  int64_t raw_bytes = 0;
};

// Flow id for the (sender, req_id) pair: req ids are monotone per
// worker and the node id is fleet-unique, so the pair — which the wire
// already carries on every frame — names one request chain fleet-wide.
inline int64_t TraceFlowId(int node_id, int32_t req_id) {
  return (static_cast<int64_t>(node_id) << 40) |
         static_cast<int64_t>(static_cast<uint32_t>(req_id));
}

// Fixed-capacity drop-oldest ring. total()/dropped() are cumulative.
class TraceRing {
 public:
  explicit TraceRing(size_t cap) : cap_(cap < 8 ? 8 : cap) {
    buf_.resize(cap_);
  }
  void Emit(const TraceRec& r) {
    std::lock_guard<std::mutex> lk(mu_);
    buf_[head_] = r;
    head_ = (head_ + 1) % cap_;
    ++total_;
  }
  // Oldest -> newest. `drain` empties the ring (the main timeline is
  // dump-once; the flight recorder keeps recording across dumps).
  std::vector<TraceRec> Snapshot(bool drain) {
    std::lock_guard<std::mutex> lk(mu_);
    std::vector<TraceRec> out;
    size_t n = total_ < static_cast<int64_t>(cap_)
                   ? static_cast<size_t>(total_)
                   : cap_;
    out.reserve(n);
    size_t start = (head_ + cap_ - n) % cap_;
    for (size_t i = 0; i < n; ++i) {
      out.push_back(buf_[(start + i) % cap_]);
    }
    if (drain) {
      head_ = 0;
      total_ = 0;
      // dropped_ stays: it is the cumulative health counter.
    }
    return out;
  }
  int64_t total() const {
    std::lock_guard<std::mutex> lk(mu_);
    return total_;
  }
  int64_t dropped() const {
    std::lock_guard<std::mutex> lk(mu_);
    int64_t over = total_ - static_cast<int64_t>(cap_);
    return dropped_ + (over > 0 ? over : 0);
  }
  // Fold the current overflow into the cumulative count (drain time).
  void FoldDropped() {
    std::lock_guard<std::mutex> lk(mu_);
    int64_t over = total_ - static_cast<int64_t>(cap_);
    if (over > 0) dropped_ += over;
  }
  size_t capacity() const { return cap_; }

 private:
  mutable std::mutex mu_;
  size_t cap_;
  size_t head_ = 0;
  int64_t total_ = 0;    // events ever emitted (this fill)
  int64_t dropped_ = 0;  // folded from previous fills
  std::vector<TraceRec> buf_;
};

class Trace {
 public:
  // Leaked heap singleton (same rationale as Metrics::Get): fatal-path
  // dumps and goodbye-frame instants run during static teardown.
  static Trace& Get();

  // Node identity for dump metadata; re-invoked per bps_init.
  void SetNode(int role, int node_id, int worker_rank);
  // Per-rank clock alignment vs the scheduler, estimated from the
  // heartbeat RTT exchange (postoffice.cc): offset such that
  // t_scheduler ~= t_local + offset. rtt < 0 = no estimate yet.
  void SetClock(int64_t offset_us, int64_t rtt_us);
  // Step-window enforcement (BYTEPS_TRACE_START_STEP/_END_STEP): the
  // Python Timeline reports training steps; outside the window the main
  // ring stops recording so a core-only user tracing a long run no
  // longer accumulates events without bound. Steps never reported
  // (step < 0) leave the window open — raw FFI users keep the old
  // always-recording behavior.
  void SetStep(int step);

  bool MainOn() const { return main_armed_.load(std::memory_order_relaxed); }
  bool FlightOn() const { return flight_on_; }

  // Main-ring emitters (no-ops unless MainOn()).
  void Span(const char* name, int64_t key, int64_t start_us, int64_t end_us,
            int peer = -1, int32_t req_id = -1, int32_t round = -1,
            int64_t wire_bytes = 0, int64_t raw_bytes = 0);
  void Instant(const char* name, int64_t key, int peer = -1,
               int32_t req_id = -1, int32_t aux = 0, int32_t round = -1);
  void Flow(TracePhase ph, const char* name, int64_t key, int64_t ts_us,
            int64_t flow_id);

  // Significant event: always into the flight recorder (when on), and
  // into the main ring when armed. The only emitter failure paths use.
  void Note(const char* name, int64_t key = 0, int peer = -1,
            int32_t req_id = -1, int32_t round = -1);

  // Chrome-trace JSON dumps; return event count, or -1 on I/O error.
  // DumpMain drains the ring (dump-once timeline semantics); DumpFlight
  // snapshots without draining (the recorder keeps recording).
  long long DumpMain(const char* path);
  long long DumpFlight(const char* path);
  // Flight dump to the default location:
  //   <BYTEPS_TRACE_DIR | BPS_TRACE_OUT | ./traces>/flight_r<role>_n<id>.json
  // `reason` lands in the dump metadata. Used by the auto-dump triggers
  // (fatal CHECK, failure SHUTDOWN, EPOCH_PAUSE/RESUME, recovery done).
  long long FlightDumpAuto(const char* reason);

  int64_t MainEventsTotal() const { return main_.total(); }
  int64_t MainDropped() const { return main_.dropped(); }

 private:
  Trace();
  void Emit(const TraceRec& r, bool significant);
  void RecomputeArmed();
  long long DumpRing(TraceRing* ring, const char* path, bool drain,
                     const char* ring_name, const char* reason);

  TraceRing main_;
  TraceRing flight_;
  bool trace_env_on_ = false;
  bool flight_on_ = true;
  int win_start_ = 1;
  int win_end_ = 1 << 30;
  std::atomic<bool> main_armed_{false};
  std::atomic<int> step_{-1};
  std::atomic<int> role_{-1};
  std::atomic<int> node_id_{-1};
  std::atomic<int> worker_rank_{-1};
  std::atomic<int64_t> clock_offset_us_{0};
  std::atomic<int64_t> clock_rtt_us_{-1};
  std::string last_reason_;  // guarded by reason_mu_
  // Pre-topology auto-dump path (flight_r<role>_pid<pid>.json): a dump
  // written before this rank learned its node id is unattributable to
  // humans and to timeline.py's role/node globs. SetNode renames it to
  // the canonical flight_r<role>_n<id>.json once topology is known
  // (ISSUE 7 satellite); a process that dies pre-topology keeps the
  // pid name — the merge tool tolerates both. Guarded by reason_mu_.
  std::string pid_dump_path_;
  // Incarnation-stable auto-dump path (ISSUE 18 satellite): a
  // relaunched process of the SAME role/node-id must not overwrite its
  // predecessor's dump — restart forensics need both sides of a crash.
  // The first auto dump probes flight_r<role>_n<id>.json, then
  // _i1/_i2/... for the first free name, and the choice is pinned here
  // so this process's own re-dumps still overwrite in place.
  // timeline.py labels the incarnations at merge. Guarded by reason_mu_.
  std::string auto_dump_path_;
  std::mutex reason_mu_;
};

// Fatal-CHECK hook (called from logging.h's LogMessage destructor just
// before abort): dump the flight recorder so every CHECK failure ships
// with the last N events. Reentrancy-guarded; never throws.
void FlightDumpOnFatal();

}  // namespace bps
