// Online per-round performance introspection (ISSUE 7).
//
// The tracing subsystem (trace.h, PR 5) can answer "which stage bound
// round 412" — but only OFFLINE: stop the fleet, gather per-rank dumps,
// merge. The live /metrics counters (PR 1) are cumulative totals that
// cannot attribute one round. This layer is the missing middle: a
// fixed-capacity drop-oldest ring of per-round stage summaries,
// accumulated at the SAME instrumentation sites PR 1/PR 5 already
// touch, cheap enough to stay on by default (BYTEPS_ROUNDSTATS_ON,
// armed = one relaxed atomic load per site; overhead gated like
// BENCH_trace_r06 — see BENCH_insight_r07.json).
//
// A "round" is the push_pull round number (MsgHeader.version): in the
// synchronous step pattern every tensor advances it in lockstep, so one
// round == one training step's DCN leg. Workers accumulate the
// worker-observed stages (queue wait, compress/qencode, push wire,
// server_sum — reported back on every CMD_PUSH_ACK's arg0 — pull wait,
// decode); servers accumulate their own view (sum spans, parked ops,
// recv bytes). A round finalizes into the ring when its operations all
// completed AND a later round has started (deep pipelining keeps up to
// ~4 rounds legally open at once; see TryFinalizeLocked).
//
// Fleet aggregation: every non-scheduler rank piggybacks its completed-
// since-last-beat summaries on CMD_HEARTBEAT (a versioned sub-payload —
// old schedulers ignore heartbeat payloads, new schedulers ignore
// unrecognized magic/version, so mixed fleets interop). The scheduler
// ingests them into per-rank EWMA baselines and a bounded fleet round
// table, which monitor/insight.py reads live through the new
// bps_round_summary probe (served at /rounds by the monitor endpoint).
//
// Concurrency: one mutex guards the open-round table + ring + fleet
// table (every emit site is per-partition or per-heartbeat — the same
// cost class as the trace ring's mutex, measured within noise). The
// singleton is intentionally leaked, like Metrics and Trace, so
// teardown paths can still record and dump.
#pragma once

#include <atomic>
#include <cstdint>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace bps {

// Accumulation sites. One entry point (Track) serves every stage so the
// FFI test hook (bps_round_track) and any Python-side reporter can
// drive the exact production path.
enum RoundStage : int32_t {
  RS_ENQ = 0,    // a partition entered the scheduled queue (starts a round)
  RS_QUEUE = 1,  // us = scheduled-queue wait (enqueue -> pop)
  RS_COMP = 2,   // us = compress or qencode time
  RS_PUSH = 3,   // us = push issue -> server ack; bytes = wire payload
  RS_SUM = 4,    // us = server-side decode+sum (ack-reported on workers)
  RS_PULL = 5,   // us = pull issue -> response; bytes = reply payload
  RS_DEC = 6,    // us = decompress or qdecode time
  RS_RETRY = 7,  // a resend fired for this round
  RS_PARK = 8,   // an op parked (server slot busy / undeclared key)
  RS_FRAME = 9,  // one wire frame sent; bytes != 0 marks it fused
  RS_DONE = 10,  // a partition's pull landed (ends a round when balanced)
};

// One round's summary. Packed: this struct IS the heartbeat wire
// sub-payload element, so its layout is part of the versioned wire
// contract (bump kRoundSummaryVersion on any change).
#pragma pack(push, 1)
struct RoundRec {
  int32_t round = -1;
  int32_t parts = 0;         // operations completed (RS_DONE count)
  int64_t queue_us = 0;
  int64_t comp_us = 0;       // compress + qencode
  int64_t push_us = 0;       // wire + server, per sub-op
  int64_t sum_us = 0;        // server summation inside push_us
  int64_t pull_us = 0;       // includes waiting for peers' pushes
  int64_t dec_us = 0;        // decompress + qdecode
  int64_t wire_bytes = 0;    // payload bytes, both legs
  int32_t wire_msgs = 0;     // request frames sent (fused frame = 1)
  int32_t fused_frames = 0;
  int32_t retries = 0;
  int32_t parked = 0;
};

// Heartbeat sub-payload: header + `count` RoundRecs (the rounds
// completed since the last beat, oldest first, capped — see
// kMaxWireRecs). Versioned so old/new nodes interop: a reader accepts
// only its known magic+version and at least the advertised length;
// anything else is silently ignored (the heartbeat itself is already
// handled from the header alone).
struct RoundSummaryHdr {
  uint16_t magic = 0;
  uint16_t version = 0;
  int32_t node_id = -1;
  int32_t role = -1;
  int32_t count = 0;
  int64_t completed_total = 0;
  int64_t dropped = 0;
};
#pragma pack(pop)

constexpr uint16_t kRoundSummaryMagic = 0xB57A;
constexpr uint16_t kRoundSummaryVersion = 1;
constexpr int kMaxWireRecs = 64;  // per heartbeat; the rest ride the next

class RoundStats {
 public:
  // Leaked heap singleton (same rationale as Metrics/Trace): heartbeat
  // piggybacks and dump probes run during teardown paths.
  static RoundStats& Get();

  bool On() const { return armed_.load(std::memory_order_relaxed); }
  void SetNode(int role, int node_id);

  // Tenant tag for a fleet rank (ISSUE 9): the scheduler feeds its
  // address-book node->tenant mapping here so fleet round summaries —
  // and therefore insight's classifier — can name the noisy neighbor
  // by tenant. Local snapshots tag with the process's own TenantId().
  void SetNodeTenant(int node_id, int tenant);

  // The one accumulation entry point (no-op unless On()). `round` < 0
  // is ignored — broadcast traffic and pre-round ops carry no round.
  void Track(int32_t stage, int round, int64_t us = 0, int64_t bytes = 0);

  // Fill the heartbeat sub-payload with rounds completed since the
  // last call (at most kMaxWireRecs). Returns false when there is
  // nothing new to report (the heartbeat then ships headerless, as
  // before this layer existed).
  bool FillWire(std::string* out);

  // Scheduler side: ingest one heartbeat sub-payload. Returns false —
  // and changes nothing — when the payload is not a recognized
  // summary (old sender, foreign magic, short frame). Trailing bytes
  // past the advertised count are tolerated — that slack is what lets
  // the events journal (ISSUE 20) append a second sub-payload behind
  // this one without breaking older receivers.
  bool Ingest(const void* data, size_t len);

  // Bytes a recognized round-summary sub-payload at `data` occupies
  // (0 when not ours) — the heartbeat payload multiplexes magic-tagged
  // chunks (ISSUE 20) and the scheduler walks them with this.
  static size_t WireSize(const void* data, size_t len);

  // Most recent finalized round (false when none yet).
  bool LastCompleted(RoundRec* out);

  int64_t completed_total();
  int64_t dropped();

  // Whole-state JSON for bps_round_summary: {"on","role","node_id",
  // "completed_total","dropped","last","rounds":[...]} plus, on ranks
  // that ingested fleet summaries (the scheduler), "fleet" (per-rank
  // latest + EWMA baseline) and "fleet_rounds" (round -> node -> rec).
  std::string SnapshotJson();

 private:
  RoundStats();

  struct OpenRound {
    RoundRec rec;
    int32_t enqueued = 0;  // RS_ENQ count (0 on roles with no enqueue)
    int32_t done = 0;      // RS_DONE count
  };

  struct RankState {
    int32_t role = -1;
    RoundRec last{};
    int64_t completed_total = 0;
    int64_t updates = 0;
    // EWMA of the rank's round wall time (sum of worker-observed
    // stages) — the regression baseline insight.py compares against.
    double ewma_wall_us = 0.0;
  };

  void TryFinalizeLocked();
  void FinalizeLocked(int round);
  void PublishGaugesLocked(const RoundRec& r);

  std::atomic<bool> armed_{false};
  std::atomic<int> role_{-1};
  std::atomic<int> node_id_{-1};

  std::mutex mu_;
  std::map<int, OpenRound> open_;   // ordered: finalize oldest-first
  int max_round_ = -1;
  size_t ring_cap_;
  size_t ring_head_ = 0;
  int64_t ring_total_ = 0;          // rounds ever finalized
  int64_t forced_ = 0;              // rounds force-finalized (table cap)
  std::vector<RoundRec> ring_;
  int64_t wire_sent_total_ = 0;     // rounds already shipped via FillWire

  // Fleet aggregation (scheduler; populated by Ingest).
  bool heartbeat_summary_on_ = true;
  std::map<int, RankState> fleet_;
  std::map<int, std::map<int, RoundRec>> fleet_rounds_;
  // node id -> tenant (scheduler, fed from the address book). The
  // heartbeat wire stays byte-identical — tenant identity is control-
  // plane state the scheduler already holds.
  std::map<int, int> node_tenant_;

 public:
  bool HeartbeatSummaryOn() const { return heartbeat_summary_on_; }
};

// EWMA smoothing for the per-rank baselines (shared with insight.py's
// documentation; see docs/monitoring.md "Round insight").
constexpr double kRoundEwmaAlpha = 0.2;

// Sum of the worker-observed stage times — the round's "wall" cost on
// one rank (pull_us overlaps push_us across partitions, so this is an
// attribution weight, not literal wall-clock; shares of it are what
// insight.py classifies on).
inline int64_t RoundWallUs(const RoundRec& r) {
  return r.queue_us + r.comp_us + r.push_us + r.pull_us + r.dec_us;
}

}  // namespace bps
