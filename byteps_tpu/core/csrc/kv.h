// Awaitable request/response bookkeeping over the van.
//
// Capability parity: reference ps-lite Customer + KVWorker<char>::ZPush/
// ZPull (SURVEY.md §2.4): zero-copy request issue (payload bytes go from
// the caller's buffer straight to writev), request-id matching of
// responses, callback-or-wait completion. KVServer-side dispatch lives in
// server.h; this class is the worker-side half.
#pragma once

#include <sys/uio.h>

#include <condition_variable>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "common.h"
#include "logging.h"
#include "metrics.h"
#include "postoffice.h"
#include "roundstats.h"
#include "tenancy.h"
#include "trace.h"

namespace bps {

class KVWorker {
 public:
  using Callback = std::function<void(Message&&)>;

  // Response callbacks run on a small key-hashed executor pool, NEVER on
  // the van receive threads. A callback may send (the push→pull chain
  // issues CMD_PULL from the push-ack callback); a send can block on a
  // full socket, and a recv thread blocked in a send stops reading — the
  // classic bidirectional-TCP deadlock (worker blocked sending to a
  // server whose sends to the worker have filled both kernel buffers,
  // each side's reader wedged behind its writer). Key-hashing keeps one
  // key's chain (push ack → pull → pull resp) totally ordered, matching
  // the server's per-key engine queues (server.cc:24-33).
  explicit KVWorker(Postoffice* po, int exec_threads = 4) : po_(po) {
    exec_queues_.resize(exec_threads < 1 ? 1 : exec_threads);
    for (auto& q : exec_queues_) q = std::make_unique<ExecQueue>();
    for (size_t i = 0; i < exec_queues_.size(); ++i) {
      exec_threads_.emplace_back([this, i] { ExecLoop(i); });
    }
    // Idempotent-retry layer (ISSUE 3 transient-fault tolerance): every
    // request keeps its header + payload segment list until it settles;
    // a timer thread resends requests whose response is overdue with
    // capped exponential backoff. The server dedups replays by (sender,
    // req_id) — ack-without-reapply — so a resend is always safe.
    // BYTEPS_RETRY_MAX=0 disables the layer entirely (no snapshot
    // bookkeeping, no timer thread: the pre-retry hot path).
    if (const char* v = getenv("BYTEPS_RETRY_MAX")) retry_max_ = atoi(v);
    if (const char* v = getenv("BYTEPS_RETRY_TIMEOUT_MS")) {
      retry_timeout_ms_ = atol(v);
      if (retry_timeout_ms_ < 10) retry_timeout_ms_ = 10;
    }
    if (retry_max_ > 0) {
      Metrics::Get().Counter("bps_retries_total");
      retry_thread_ = std::thread([this] { RetryLoop(); });
    }
  }

  ~KVWorker() { StopExec(); }

  // Drain queued callbacks, then stop the executor + retry threads.
  // Idempotent.
  void StopExec() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      retry_stop_ = true;
    }
    cv_.notify_all();
    if (retry_thread_.joinable()) retry_thread_.join();
    for (auto& q : exec_queues_) {
      std::lock_guard<std::mutex> lk(q->mu);
      q->stop = true;
      q->cv.notify_all();
    }
    for (auto& t : exec_threads_) {
      if (t.joinable()) t.join();
    }
    exec_threads_.clear();
  }

  // Issue a request to `node_id`; `cb` fires on an executor thread when
  // the matching response (same req_id) arrives, or with a synthetic
  // CMD_ERROR message if the peer's connection is already/later found
  // dead. Returns the req id, or -1 if the send failed outright with the
  // retry layer off (the callback then fires with CMD_ERROR before
  // Request returns). `hold` optionally pins transient payload storage
  // (e.g. a fused frame's sub-header table) for the request's lifetime;
  // all other payload segments must stay valid until `cb` fires — the
  // contract every call site already honours for the zero-copy send, and
  // what makes resends copy-free.
  int Request(int node_id, MsgHeader head, const void* payload,
              int64_t payload_len, Callback cb,
              std::shared_ptr<void> hold = nullptr) {
    struct iovec one;
    one.iov_base = const_cast<void*>(payload);
    one.iov_len = static_cast<size_t>(payload_len > 0 ? payload_len : 0);
    return RequestV(node_id, head, &one, payload_len > 0 ? 1 : 0,
                    std::move(cb), std::move(hold));
  }

  // Gather variant (fusion layer): the request payload is the
  // concatenation of `nsegs` segments, sent via the van's writev path
  // with no staging copy. ONE req_id covers the whole frame — the server
  // answers a CMD_MULTI_* batch with a single batched reply, so `cb`
  // fires once for the entire sub-operation set.
  int RequestV(int node_id, MsgHeader head, const struct iovec* segs,
               int nsegs, Callback cb,
               std::shared_ptr<void> hold = nullptr) {
    int rid;
    bool dead;
    const bool retry_on = retry_max_ > 0;
    head.sender = po_->my_id();
    // Tenant stamp (ISSUE 9): every request this process sends carries
    // its BYTEPS_TENANT_ID — the server's (tenant, key) namespace and
    // per-tenant QoS key on it. Unset/legacy processes stamp 0, which
    // is byte-for-byte the pre-tenant header.
    head.tenant = TenantId();
    {
      std::lock_guard<std::mutex> lk(mu_);
      rid = next_req_id_++;
      head.req_id = rid;
      // A peer already known dead: without this check a chained request
      // issued during the peer-lost window could still write() into the
      // half-closed socket "successfully" and then sit in pending_
      // forever (no second disconnect event fires for that fd). The dead
      // mark and the FailNode pending-scan share mu_, so every request
      // either lands in pending_ before the scan or sees the mark here.
      dead = dead_nodes_.count(node_id) > 0;
      if (!dead) {
        PendingReq pr;
        pr.cb = std::move(cb);
        pr.node = node_id;
        if (retry_on) {
          // Resend snapshot: header + the caller-stable segment list.
          pr.head = head;
          pr.segs.assign(segs, segs + nsegs);
          pr.hold = std::move(hold);
          pr.deadline_ms = NowMs() + retry_timeout_ms_;
        }
        pending_[rid] = std::move(pr);
      }
    }
    if (dead) {
      if (cb) {
        Message err;
        err.head.cmd = CMD_ERROR;
        err.head.req_id = rid;
        std::string why = "node " + std::to_string(node_id) +
                          " is known dead (connection lost)";
        err.payload.assign(why.data(), why.data() + why.size());
        cb(std::move(err));
      }
      return -1;
    }
    // Striped by key (BYTEPS_VAN_STREAMS): one key's chain stays on one
    // connection, so per-key ordering survives striping. Multi frames
    // stripe by head.key = their first sub-key; that is only sound
    // because the fusion collector batches per (server, stripe fd)
    // (worker.cc PushLoop), so EVERY sub-key of a fused frame hashes to
    // the lead key's connection — each key's chain stays on its own
    // stripe whether it travels fused or as a singleton.
    if (!po_->van().SendV(po_->FdOf(node_id, head.key), head, segs,
                          nsegs)) {
      if (retry_on) {
        // Transient stance: the frame is lost but the request stays
        // pending — the van's disconnect handler is already driving a
        // reconnect (the failed send and the recv-side EOF have the
        // same cause), after which ResendNode or the retry timer
        // re-issues it. Only exhausted reconnects/retries escalate.
        return rid;
      }
      // Retry layer off: dead connection means the response can never
      // come. Mark the node and fail THIS request immediately (VERDICT
      // r2 weak #7 — a push into a dead connection used to block its
      // handle until the heartbeat detector fired).
      {
        std::lock_guard<std::mutex> lk(mu_);
        dead_nodes_.insert(node_id);
      }
      FailRequests({rid},
                   "send to node " + std::to_string(node_id) +
                   " failed (connection dead)");
      return -1;
    }
    return rid;
  }

  // Fail every in-flight request addressed to `node_id` (peer-lost event
  // from the van). Each callback fires once with CMD_ERROR + diagnostic.
  void FailNode(int node_id, const std::string& why) {
    std::vector<int> rids;
    {
      std::lock_guard<std::mutex> lk(mu_);
      dead_nodes_.insert(node_id);  // before the scan, same lock: no gap
      paused_nodes_.erase(node_id);  // escalation ends any recovery park
      for (const auto& kv : pending_) {
        if (kv.second.node == node_id) rids.push_back(kv.first);
      }
    }
    if (!rids.empty()) {
      BPS_LOG(WARNING) << "failing " << rids.size()
                       << " in-flight request(s): " << why;
      FailRequests(rids, why);
    }
  }

  // Route a response message (PUSH_ACK / PULL_RESP / INIT_ACK / ...).
  // Runs on the van receive thread: must not block and must not send —
  // just settle the request table and hand the callback to the executor.
  void OnResponse(Message&& msg) {
    if (msg.head.cmd == CMD_KEEPALIVE) {
      // The server saw our duplicate and is still working on the
      // original (e.g. a pull parked behind a slow peer's push): reset
      // the attempt budget so a legitimately slow round never exhausts
      // retries — only true silence escalates to fail-stop.
      Trace::Get().Note("KEEPALIVE", msg.head.key, msg.head.sender,
                        msg.head.req_id);
      std::lock_guard<std::mutex> lk(mu_);
      auto it = pending_.find(msg.head.req_id);
      if (it != pending_.end() && retry_max_ > 0) {
        it->second.attempts = 0;
        it->second.deadline_ms = NowMs() + retry_timeout_ms_;
      }
      return;
    }
    Callback cb;
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = pending_.find(msg.head.req_id);
      if (it == pending_.end()) return;  // late/duplicate response
      cb = std::move(it->second.cb);
      pending_.erase(it);
      done_count_++;
    }
    cv_.notify_all();
    if (!cb) return;
    auto& q = *exec_queues_[static_cast<size_t>(msg.head.key) %
                            exec_queues_.size()];
    {
      std::lock_guard<std::mutex> lk(q.mu);
      q.items.emplace_back(std::move(cb), std::move(msg));
    }
    q.cv.notify_one();
  }

  // Block until there are no outstanding requests.
  void WaitAll() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [this] { return pending_.empty(); });
  }

  // Block until the given request ids have all completed (does NOT wait on
  // unrelated in-flight requests — a late Declare must not serialize
  // against the previous round's pushes).
  void WaitRequests(const std::vector<int>& ids) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [this, &ids] {
      for (int id : ids) {
        if (pending_.count(id)) return false;
      }
      return true;
    });
  }

  // Fail-stop on fleet shutdown with work in flight (a peer died and the
  // scheduler broadcast failure shutdown): crashing with a clear message
  // beats hanging forever on responses that will never come.
  void FailAllPending() {
    size_t n;
    {
      std::lock_guard<std::mutex> lk(mu_);
      // Latch the shutdown for the retry loop: a request PARKED right
      // now (paused node mid-recovery) or issued after this hook fires
      // has no escalation owner left — the heartbeat thread that owned
      // the park deadline exits with the fleet — so the retry loop
      // must fail it instead of deferring forever (ISSUE 15: the
      // scheduler-death fail-stop found this wedge).
      fleet_failed_ = true;
      n = pending_.size();
    }
    if (n > 0) {
      BPS_FATAL << "fleet shutdown with " << n
                << " request(s) in flight — a server or worker died "
                   "(see scheduler log); restart the job";
    }
  }

  // Hot server replacement (ISSUE 4): freeze the retry clock for every
  // request addressed to `node_id` — they stay parked in the resend
  // queue, neither resent nor escalated, until ResendNode (recovery
  // complete) drains them, or the fleet's failure SHUTDOWN fail-stops
  // them. Idempotent; invoked from the peer-paused callback.
  void PauseNode(int node_id) {
    if (retry_max_ <= 0) return;
    std::lock_guard<std::mutex> lk(mu_);
    paused_nodes_.insert(node_id);
  }

  // True while request `rid` is still pending (unsettled). Used by the
  // worker's recovery hook to tell "push settled but its callback has
  // not run yet" (re-push needed) from "push still in the resend queue"
  // (ResendNode re-delivers it).
  bool HasPending(int rid) {
    std::lock_guard<std::mutex> lk(mu_);
    return pending_.count(rid) > 0;
  }

  // Immediately re-issue every in-flight request addressed to `node_id`
  // over its (freshly reconnected) connection, instead of waiting out
  // each request's retry timeout. Invoked from the postoffice's
  // peer-reconnected callback on a van thread, and by the recovery hook
  // after the replacement server was re-seeded (also lifts PauseNode).
  void ResendNode(int node_id) {
    if (retry_max_ <= 0) return;
    std::vector<Resend> work;
    {
      std::lock_guard<std::mutex> lk(mu_);
      paused_nodes_.erase(node_id);
      for (auto& kv : pending_) {
        if (kv.second.node != node_id) continue;
        work.push_back(SnapshotForResend(kv.first, kv.second));
        kv.second.deadline_ms = NowMs() + retry_timeout_ms_;
        kv.second.attempts = 0;  // fresh budget against the fresh peer
      }
    }
    if (!work.empty()) {
      BPS_LOG(WARNING) << "resending " << work.size()
                       << " in-flight request(s) to reconnected node "
                       << node_id;
    }
    DoResends(work);
  }

 private:
  struct PendingReq {
    Callback cb;
    int node = -1;
    // Retry snapshot (retry layer on): the header exactly as first
    // sent, the caller-stable payload segments, and an optional
    // lifetime pin for transient storage (fused-frame tables).
    MsgHeader head{};
    std::vector<struct iovec> segs;
    std::shared_ptr<void> hold;
    int64_t deadline_ms = 0;
    int attempts = 0;
  };

  struct Resend {
    int rid;
    int node;
    MsgHeader head;
    std::string payload;  // owned flat copy of the request payload
  };

  // Flatten a pending request's payload into an OWNED copy, under mu_.
  // Must be called while the entry is alive: an unsettled request's
  // segments are guaranteed valid (the callback has not fired, so the
  // caller has not reclaimed its buffers, and `hold` pins any transient
  // table). The copy is what makes the actual send safe to run OUTSIDE
  // mu_ — without it, a request settling between snapshot and send
  // frees the buffers under the resend and ships a garbage frame.
  Resend SnapshotForResend(int rid, const PendingReq& pr) {
    Resend r;
    r.rid = rid;
    r.node = pr.node;
    r.head = pr.head;
    size_t total = 0;
    for (const auto& s : pr.segs) total += s.iov_len;
    r.payload.reserve(total);
    for (const auto& s : pr.segs) {
      r.payload.append(static_cast<const char*>(s.iov_base), s.iov_len);
    }
    return r;
  }

  // Re-issue the given snapshots (outside mu_ — sends can block). A
  // resend that races its response is harmless: the server's dedup
  // window acks-without-reapplying, and OnResponse drops the duplicate
  // reply. A failed resend is NOT counted as an attempt — the
  // reconnect/peer-lost machinery owns escalation for dead connections;
  // attempts only measure delivered-but-unanswered sends.
  void DoResends(const std::vector<Resend>& work) {
    for (const auto& r : work) {
      struct iovec one;
      one.iov_base = const_cast<char*>(r.payload.data());
      one.iov_len = r.payload.size();
      bool ok = po_->van().SendV(po_->FdOf(r.node, r.head.key), r.head,
                                 &one, r.payload.empty() ? 0 : 1);
      if (!ok) continue;
      BPS_METRIC_COUNTER_ADD("bps_retries_total", 1);
      Trace::Get().Note("RESEND", r.head.key, r.node, r.rid,
                        r.head.version);
      // Round attribution (ISSUE 7): resends are the retry-degraded
      // classifier's per-round signal. Data-plane heads carry the
      // round in version; control-plane resends (version 0 overloads)
      // land on round 0, which the classifier reads as fleet noise.
      if (IsDataPlaneCmd(r.head.cmd)) {
        RoundStats::Get().Track(RS_RETRY, r.head.version);
      }
      std::lock_guard<std::mutex> lk(mu_);
      auto it = pending_.find(r.rid);
      if (it == pending_.end()) continue;  // settled while resending
      ++it->second.attempts;
    }
  }

  // Timer thread: resend overdue requests with capped exponential
  // backoff; escalate to CMD_ERROR only when a request has been resent
  // BYTEPS_RETRY_MAX times with neither a response nor a server
  // keepalive — the in-band signal is then that the server is not
  // processing us at all (its van would dedup-and-keepalive a live but
  // slow request), which is exactly the persistent fault that should
  // fail-stop.
  void RetryLoop() {
    const int64_t tick_ms =
        retry_timeout_ms_ / 4 > 20 ? retry_timeout_ms_ / 4 : 20;
    for (;;) {
      std::vector<Resend> work;
      std::vector<int> exhausted;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait_for(lk, std::chrono::milliseconds(tick_ms),
                     [this] { return retry_stop_; });
        if (retry_stop_) return;
        int64_t now = NowMs();
        // Scheduler fail-over park (ISSUE 15): with the control plane
        // down there is nobody to coordinate a fail-stop, and a
        // transiently wedged server cannot enter hot replacement until
        // the scheduler is back — so exhaustion escalation DEFERS
        // while parked (resends keep flowing; the park's own window is
        // the escalation deadline, and its expiry restores fail-stop).
        const bool sched_parked = po_ && po_->SchedLost();
        if (fleet_failed_) {
          // Fleet is down (FailAllPending latched it): every pending
          // request — parked ones included — fails now; nobody is
          // left to resend to or to end a park.
          for (auto& kv : pending_) exhausted.push_back(kv.first);
          lk.unlock();
          if (!exhausted.empty()) {
            FailRequests(exhausted,
                         "fleet shutdown with the request in flight — "
                         "a server, worker or the scheduler died (see "
                         "scheduler log); restart the job");
          }
          continue;
        }
        for (auto& kv : pending_) {
          PendingReq& pr = kv.second;
          // A paused node's requests are parked, not overdue: their
          // rank is mid-recovery and the scheduler owns escalation
          // (replacement, or the failure-SHUTDOWN fallback).
          if (paused_nodes_.count(pr.node)) continue;
          if (pr.deadline_ms <= 0 || now < pr.deadline_ms) continue;
          if (pr.attempts >= retry_max_) {
            if (sched_parked) {
              pr.deadline_ms = now + retry_timeout_ms_;
              continue;
            }
            exhausted.push_back(kv.first);
            continue;
          }
          // Next deadline: base doubled per attempt, capped at 8x.
          int shift = pr.attempts < 3 ? pr.attempts + 1 : 3;
          pr.deadline_ms = now + (retry_timeout_ms_ << shift);
          work.push_back(SnapshotForResend(kv.first, pr));
        }
      }
      DoResends(work);
      if (!exhausted.empty()) {
        FailRequests(exhausted,
                     "request unanswered after " +
                         std::to_string(retry_max_) +
                         " retries (no response, no keepalive) — "
                         "persistent fault, failing fast");
      }
    }
  }

  // Settle `rids` as failed: each callback fires (on the caller's thread)
  // with a synthetic CMD_ERROR message carrying the diagnostic.
  void FailRequests(const std::vector<int>& rids, const std::string& why) {
    for (int rid : rids) {
      Callback cb;
      {
        std::lock_guard<std::mutex> lk(mu_);
        auto it = pending_.find(rid);
        if (it == pending_.end()) continue;
        Trace::Get().Note("REQ_FAILED", it->second.head.key,
                          it->second.node, rid);
        cb = std::move(it->second.cb);
        pending_.erase(it);
        done_count_++;
      }
      cv_.notify_all();
      if (!cb) continue;
      Message err;
      err.head.cmd = CMD_ERROR;
      err.head.req_id = rid;
      err.payload.assign(why.data(), why.data() + why.size());
      cb(std::move(err));
    }
  }

  struct ExecQueue {
    std::mutex mu;
    std::condition_variable cv;
    std::deque<std::pair<Callback, Message>> items;
    bool stop = false;
  };

  void ExecLoop(size_t idx) {
    auto& q = *exec_queues_[idx];
    for (;;) {
      std::pair<Callback, Message> item;
      {
        std::unique_lock<std::mutex> lk(q.mu);
        q.cv.wait(lk, [&q] { return q.stop || !q.items.empty(); });
        if (q.items.empty()) return;  // stop requested and fully drained
        item = std::move(q.items.front());
        q.items.pop_front();
      }
      item.first(std::move(item.second));
    }
  }

  Postoffice* po_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::unordered_map<int, PendingReq> pending_;
  std::unordered_set<int> dead_nodes_;  // peers with lost connections
  std::unordered_set<int> paused_nodes_;  // ranks mid-recovery (frozen)
  int next_req_id_ = 0;
  int64_t done_count_ = 0;
  std::vector<std::unique_ptr<ExecQueue>> exec_queues_;
  std::vector<std::thread> exec_threads_;
  // Retry layer (BYTEPS_RETRY_MAX / BYTEPS_RETRY_TIMEOUT_MS).
  int retry_max_ = 4;
  int64_t retry_timeout_ms_ = 1000;
  bool retry_stop_ = false;  // guarded by mu_
  bool fleet_failed_ = false;  // guarded by mu_; latched on shutdown
  std::thread retry_thread_;
};

}  // namespace bps
