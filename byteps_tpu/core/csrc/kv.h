// Awaitable request/response bookkeeping over the van.
//
// Capability parity: reference ps-lite Customer + KVWorker<char>::ZPush/
// ZPull (SURVEY.md §2.4): zero-copy request issue (payload bytes go from
// the caller's buffer straight to writev), request-id matching of
// responses, callback-or-wait completion. KVServer-side dispatch lives in
// server.h; this class is the worker-side half.
#pragma once

#include <condition_variable>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "logging.h"
#include "postoffice.h"

namespace bps {

class KVWorker {
 public:
  using Callback = std::function<void(Message&&)>;

  // Response callbacks run on a small key-hashed executor pool, NEVER on
  // the van receive threads. A callback may send (the push→pull chain
  // issues CMD_PULL from the push-ack callback); a send can block on a
  // full socket, and a recv thread blocked in a send stops reading — the
  // classic bidirectional-TCP deadlock (worker blocked sending to a
  // server whose sends to the worker have filled both kernel buffers,
  // each side's reader wedged behind its writer). Key-hashing keeps one
  // key's chain (push ack → pull → pull resp) totally ordered, matching
  // the server's per-key engine queues (server.cc:24-33).
  explicit KVWorker(Postoffice* po, int exec_threads = 4) : po_(po) {
    exec_queues_.resize(exec_threads < 1 ? 1 : exec_threads);
    for (auto& q : exec_queues_) q = std::make_unique<ExecQueue>();
    for (size_t i = 0; i < exec_queues_.size(); ++i) {
      exec_threads_.emplace_back([this, i] { ExecLoop(i); });
    }
  }

  ~KVWorker() { StopExec(); }

  // Drain queued callbacks, then stop the executor threads. Idempotent.
  void StopExec() {
    for (auto& q : exec_queues_) {
      std::lock_guard<std::mutex> lk(q->mu);
      q->stop = true;
      q->cv.notify_all();
    }
    for (auto& t : exec_threads_) {
      if (t.joinable()) t.join();
    }
    exec_threads_.clear();
  }

  // Issue a request to `node_id`; `cb` fires on an executor thread when
  // the matching response (same req_id) arrives. Returns the req id.
  int Request(int node_id, MsgHeader head, const void* payload,
              int64_t payload_len, Callback cb) {
    int rid;
    {
      std::lock_guard<std::mutex> lk(mu_);
      rid = next_req_id_++;
      pending_[rid] = std::move(cb);
    }
    head.sender = po_->my_id();
    head.req_id = rid;
    po_->van().Send(po_->FdOf(node_id), head, payload, payload_len);
    return rid;
  }

  // Route a response message (PUSH_ACK / PULL_RESP / INIT_ACK / ...).
  // Runs on the van receive thread: must not block and must not send —
  // just settle the request table and hand the callback to the executor.
  void OnResponse(Message&& msg) {
    Callback cb;
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = pending_.find(msg.head.req_id);
      if (it == pending_.end()) return;  // late/duplicate response
      cb = std::move(it->second);
      pending_.erase(it);
      done_count_++;
    }
    cv_.notify_all();
    if (!cb) return;
    auto& q = *exec_queues_[static_cast<size_t>(msg.head.key) %
                            exec_queues_.size()];
    {
      std::lock_guard<std::mutex> lk(q.mu);
      q.items.emplace_back(std::move(cb), std::move(msg));
    }
    q.cv.notify_one();
  }

  // Block until there are no outstanding requests.
  void WaitAll() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [this] { return pending_.empty(); });
  }

  // Block until the given request ids have all completed (does NOT wait on
  // unrelated in-flight requests — a late Declare must not serialize
  // against the previous round's pushes).
  void WaitRequests(const std::vector<int>& ids) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [this, &ids] {
      for (int id : ids) {
        if (pending_.count(id)) return false;
      }
      return true;
    });
  }

  // Fail-stop on fleet shutdown with work in flight (a peer died and the
  // scheduler broadcast failure shutdown): crashing with a clear message
  // beats hanging forever on responses that will never come.
  void FailAllPending() {
    size_t n;
    {
      std::lock_guard<std::mutex> lk(mu_);
      n = pending_.size();
    }
    if (n > 0) {
      BPS_FATAL << "fleet shutdown with " << n
                << " request(s) in flight — a server or worker died "
                   "(see scheduler log); restart the job";
    }
  }

 private:
  struct ExecQueue {
    std::mutex mu;
    std::condition_variable cv;
    std::deque<std::pair<Callback, Message>> items;
    bool stop = false;
  };

  void ExecLoop(size_t idx) {
    auto& q = *exec_queues_[idx];
    for (;;) {
      std::pair<Callback, Message> item;
      {
        std::unique_lock<std::mutex> lk(q.mu);
        q.cv.wait(lk, [&q] { return q.stop || !q.items.empty(); });
        if (q.items.empty()) return;  // stop requested and fully drained
        item = std::move(q.items.front());
        q.items.pop_front();
      }
      item.first(std::move(item.second));
    }
  }

  Postoffice* po_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::unordered_map<int, Callback> pending_;
  int next_req_id_ = 0;
  int64_t done_count_ = 0;
  std::vector<std::unique_ptr<ExecQueue>> exec_queues_;
  std::vector<std::thread> exec_threads_;
};

}  // namespace bps
