// Awaitable request/response bookkeeping over the van.
//
// Capability parity: reference ps-lite Customer + KVWorker<char>::ZPush/
// ZPull (SURVEY.md §2.4): zero-copy request issue (payload bytes go from
// the caller's buffer straight to writev), request-id matching of
// responses, callback-or-wait completion. KVServer-side dispatch lives in
// server.h; this class is the worker-side half.
#pragma once

#include <condition_variable>
#include <functional>
#include <mutex>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "logging.h"
#include "postoffice.h"

namespace bps {

class KVWorker {
 public:
  using Callback = std::function<void(Message&&)>;

  explicit KVWorker(Postoffice* po) : po_(po) {}

  // Issue a request to `node_id`; `cb` fires on the van receive thread when
  // the matching response (same req_id) arrives. Returns the req id.
  int Request(int node_id, MsgHeader head, const void* payload,
              int64_t payload_len, Callback cb) {
    int rid;
    {
      std::lock_guard<std::mutex> lk(mu_);
      rid = next_req_id_++;
      pending_[rid] = std::move(cb);
    }
    head.sender = po_->my_id();
    head.req_id = rid;
    po_->van().Send(po_->FdOf(node_id), head, payload, payload_len);
    return rid;
  }

  // Route a response message (PUSH_ACK / PULL_RESP / INIT_ACK / ...).
  void OnResponse(Message&& msg) {
    Callback cb;
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = pending_.find(msg.head.req_id);
      if (it == pending_.end()) return;  // late/duplicate response
      cb = std::move(it->second);
      pending_.erase(it);
      done_count_++;
    }
    if (cb) cb(std::move(msg));
    cv_.notify_all();
  }

  // Block until there are no outstanding requests.
  void WaitAll() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [this] { return pending_.empty(); });
  }

  // Block until the given request ids have all completed (does NOT wait on
  // unrelated in-flight requests — a late Declare must not serialize
  // against the previous round's pushes).
  void WaitRequests(const std::vector<int>& ids) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [this, &ids] {
      for (int id : ids) {
        if (pending_.count(id)) return false;
      }
      return true;
    });
  }

  // Fail-stop on fleet shutdown with work in flight (a peer died and the
  // scheduler broadcast failure shutdown): crashing with a clear message
  // beats hanging forever on responses that will never come.
  void FailAllPending() {
    size_t n;
    {
      std::lock_guard<std::mutex> lk(mu_);
      n = pending_.size();
    }
    if (n > 0) {
      BPS_FATAL << "fleet shutdown with " << n
                << " request(s) in flight — a server or worker died "
                   "(see scheduler log); restart the job";
    }
  }

 private:
  Postoffice* po_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::unordered_map<int, Callback> pending_;
  int next_req_id_ = 0;
  int64_t done_count_ = 0;
};

}  // namespace bps
