// Awaitable request/response bookkeeping over the van.
//
// Capability parity: reference ps-lite Customer + KVWorker<char>::ZPush/
// ZPull (SURVEY.md §2.4): zero-copy request issue (payload bytes go from
// the caller's buffer straight to writev), request-id matching of
// responses, callback-or-wait completion. KVServer-side dispatch lives in
// server.h; this class is the worker-side half.
#pragma once

#include <sys/uio.h>

#include <condition_variable>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "common.h"
#include "logging.h"
#include "postoffice.h"

namespace bps {

class KVWorker {
 public:
  using Callback = std::function<void(Message&&)>;

  // Response callbacks run on a small key-hashed executor pool, NEVER on
  // the van receive threads. A callback may send (the push→pull chain
  // issues CMD_PULL from the push-ack callback); a send can block on a
  // full socket, and a recv thread blocked in a send stops reading — the
  // classic bidirectional-TCP deadlock (worker blocked sending to a
  // server whose sends to the worker have filled both kernel buffers,
  // each side's reader wedged behind its writer). Key-hashing keeps one
  // key's chain (push ack → pull → pull resp) totally ordered, matching
  // the server's per-key engine queues (server.cc:24-33).
  explicit KVWorker(Postoffice* po, int exec_threads = 4) : po_(po) {
    exec_queues_.resize(exec_threads < 1 ? 1 : exec_threads);
    for (auto& q : exec_queues_) q = std::make_unique<ExecQueue>();
    for (size_t i = 0; i < exec_queues_.size(); ++i) {
      exec_threads_.emplace_back([this, i] { ExecLoop(i); });
    }
  }

  ~KVWorker() { StopExec(); }

  // Drain queued callbacks, then stop the executor threads. Idempotent.
  void StopExec() {
    for (auto& q : exec_queues_) {
      std::lock_guard<std::mutex> lk(q->mu);
      q->stop = true;
      q->cv.notify_all();
    }
    for (auto& t : exec_threads_) {
      if (t.joinable()) t.join();
    }
    exec_threads_.clear();
  }

  // Issue a request to `node_id`; `cb` fires on an executor thread when
  // the matching response (same req_id) arrives, or with a synthetic
  // CMD_ERROR message if the peer's connection is already/later found
  // dead. Returns the req id, or -1 if the send failed outright (the
  // callback then fires with CMD_ERROR before Request returns).
  int Request(int node_id, MsgHeader head, const void* payload,
              int64_t payload_len, Callback cb) {
    struct iovec one;
    one.iov_base = const_cast<void*>(payload);
    one.iov_len = static_cast<size_t>(payload_len > 0 ? payload_len : 0);
    return RequestV(node_id, head, &one, payload_len > 0 ? 1 : 0,
                    std::move(cb));
  }

  // Gather variant (fusion layer): the request payload is the
  // concatenation of `nsegs` segments, sent via the van's writev path
  // with no staging copy. ONE req_id covers the whole frame — the server
  // answers a CMD_MULTI_* batch with a single batched reply, so `cb`
  // fires once for the entire sub-operation set.
  int RequestV(int node_id, MsgHeader head, const struct iovec* segs,
               int nsegs, Callback cb) {
    int rid;
    bool dead;
    {
      std::lock_guard<std::mutex> lk(mu_);
      rid = next_req_id_++;
      // A peer already known dead: without this check a chained request
      // issued during the peer-lost window could still write() into the
      // half-closed socket "successfully" and then sit in pending_
      // forever (no second disconnect event fires for that fd). The dead
      // mark and the FailNode pending-scan share mu_, so every request
      // either lands in pending_ before the scan or sees the mark here.
      dead = dead_nodes_.count(node_id) > 0;
      if (!dead) pending_[rid] = PendingReq{std::move(cb), node_id};
    }
    if (dead) {
      if (cb) {
        Message err;
        err.head.cmd = CMD_ERROR;
        err.head.req_id = rid;
        std::string why = "node " + std::to_string(node_id) +
                          " is known dead (connection lost)";
        err.payload.assign(why.data(), why.data() + why.size());
        cb(std::move(err));
      }
      return -1;
    }
    head.sender = po_->my_id();
    head.req_id = rid;
    // Striped by key (BYTEPS_VAN_STREAMS): one key's chain stays on one
    // connection, so per-key ordering survives striping. Multi frames
    // stripe by head.key = their first sub-key; that is only sound
    // because the fusion collector batches per (server, stripe fd)
    // (worker.cc PushLoop), so EVERY sub-key of a fused frame hashes to
    // the lead key's connection — each key's chain stays on its own
    // stripe whether it travels fused or as a singleton.
    if (!po_->van().SendV(po_->FdOf(node_id, head.key), head, segs,
                          nsegs)) {
      // Dead connection: the response can never come. Mark the node and
      // fail THIS request immediately (VERDICT r2 weak #7 — a push into
      // a dead connection used to block its handle until the heartbeat
      // detector fired).
      {
        std::lock_guard<std::mutex> lk(mu_);
        dead_nodes_.insert(node_id);
      }
      FailRequests({rid},
                   "send to node " + std::to_string(node_id) +
                   " failed (connection dead)");
      return -1;
    }
    return rid;
  }

  // Fail every in-flight request addressed to `node_id` (peer-lost event
  // from the van). Each callback fires once with CMD_ERROR + diagnostic.
  void FailNode(int node_id, const std::string& why) {
    std::vector<int> rids;
    {
      std::lock_guard<std::mutex> lk(mu_);
      dead_nodes_.insert(node_id);  // before the scan, same lock: no gap
      for (const auto& kv : pending_) {
        if (kv.second.node == node_id) rids.push_back(kv.first);
      }
    }
    if (!rids.empty()) {
      BPS_LOG(WARNING) << "failing " << rids.size()
                       << " in-flight request(s): " << why;
      FailRequests(rids, why);
    }
  }

  // Route a response message (PUSH_ACK / PULL_RESP / INIT_ACK / ...).
  // Runs on the van receive thread: must not block and must not send —
  // just settle the request table and hand the callback to the executor.
  void OnResponse(Message&& msg) {
    Callback cb;
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = pending_.find(msg.head.req_id);
      if (it == pending_.end()) return;  // late/duplicate response
      cb = std::move(it->second.cb);
      pending_.erase(it);
      done_count_++;
    }
    cv_.notify_all();
    if (!cb) return;
    auto& q = *exec_queues_[static_cast<size_t>(msg.head.key) %
                            exec_queues_.size()];
    {
      std::lock_guard<std::mutex> lk(q.mu);
      q.items.emplace_back(std::move(cb), std::move(msg));
    }
    q.cv.notify_one();
  }

  // Block until there are no outstanding requests.
  void WaitAll() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [this] { return pending_.empty(); });
  }

  // Block until the given request ids have all completed (does NOT wait on
  // unrelated in-flight requests — a late Declare must not serialize
  // against the previous round's pushes).
  void WaitRequests(const std::vector<int>& ids) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_.wait(lk, [this, &ids] {
      for (int id : ids) {
        if (pending_.count(id)) return false;
      }
      return true;
    });
  }

  // Fail-stop on fleet shutdown with work in flight (a peer died and the
  // scheduler broadcast failure shutdown): crashing with a clear message
  // beats hanging forever on responses that will never come.
  void FailAllPending() {
    size_t n;
    {
      std::lock_guard<std::mutex> lk(mu_);
      n = pending_.size();
    }
    if (n > 0) {
      BPS_FATAL << "fleet shutdown with " << n
                << " request(s) in flight — a server or worker died "
                   "(see scheduler log); restart the job";
    }
  }

 private:
  struct PendingReq {
    Callback cb;
    int node = -1;
  };

  // Settle `rids` as failed: each callback fires (on the caller's thread)
  // with a synthetic CMD_ERROR message carrying the diagnostic.
  void FailRequests(const std::vector<int>& rids, const std::string& why) {
    for (int rid : rids) {
      Callback cb;
      {
        std::lock_guard<std::mutex> lk(mu_);
        auto it = pending_.find(rid);
        if (it == pending_.end()) continue;
        cb = std::move(it->second.cb);
        pending_.erase(it);
        done_count_++;
      }
      cv_.notify_all();
      if (!cb) continue;
      Message err;
      err.head.cmd = CMD_ERROR;
      err.head.req_id = rid;
      err.payload.assign(why.data(), why.data() + why.size());
      cb(std::move(err));
    }
  }

  struct ExecQueue {
    std::mutex mu;
    std::condition_variable cv;
    std::deque<std::pair<Callback, Message>> items;
    bool stop = false;
  };

  void ExecLoop(size_t idx) {
    auto& q = *exec_queues_[idx];
    for (;;) {
      std::pair<Callback, Message> item;
      {
        std::unique_lock<std::mutex> lk(q.mu);
        q.cv.wait(lk, [&q] { return q.stop || !q.items.empty(); });
        if (q.items.empty()) return;  // stop requested and fully drained
        item = std::move(q.items.front());
        q.items.pop_front();
      }
      item.first(std::move(item.second));
    }
  }

  Postoffice* po_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::unordered_map<int, PendingReq> pending_;
  std::unordered_set<int> dead_nodes_;  // peers with lost connections
  int next_req_id_ = 0;
  int64_t done_count_ = 0;
  std::vector<std::unique_ptr<ExecQueue>> exec_queues_;
  std::vector<std::thread> exec_threads_;
};

}  // namespace bps
