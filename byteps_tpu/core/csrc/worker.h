// Worker-side partitioned push/pull pipeline.
//
// Capability parity: reference byteps/common/operations.cc (InitTensor /
// EnqueueTensor) + the PUSH→PULL stages of core_loops.cc (SURVEY.md §2.1,
// §3.3): tensors are split into BYTEPS_PARTITION_BYTES slices at declare
// time; each push_pull enqueues every partition into the priority-credit
// scheduled queue; a push thread drains it (compress → ZPush), push-acks
// chain into ZPulls, and pull responses land back in the caller's buffer.
// Completion is tracked per-handle (reference: handle_manager.cc).
//
// The D2H/H2D and NCCL stages of the reference pipeline do not exist here:
// on TPU those are XLA's job (ICI reduce-scatter inside jit); this class
// only runs the DCN leg, on host buffers handed over via dlpack/numpy.
#pragma once

#include <atomic>
#include <condition_variable>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "compressor.h"
#include "kv.h"
#include "postoffice.h"
#include "scheduled_queue.h"
#include "trace.h"

namespace bps {

// Trace spans (compress/push/pull + flow stitching) are recorded into
// the process-wide rings in trace.h (ISSUE 5) — the worker is one of
// four instrumented roles, no longer the sole owner of the timeline.

class BytePSWorker {
 public:
  // fusion_bytes: partitions with raw size under this are eligible for
  // small-tensor fusion (coalesced CMD_MULTI_PUSH frames); 0 disables —
  // the wire protocol is then byte-for-byte the unfused one.
  // fusion_keys: max sub-operations per fused frame.
  void Start(Postoffice* po, KVWorker* kv, int64_t partition_bytes,
             int64_t credit_bytes, int64_t fusion_bytes, int fusion_keys,
             std::string default_comp, bool trace_on);
  void Stop();
  // Cumulative async-pull staleness stats (see stale_* members).
  void StalenessStats(long long* sum, long long* max_out,
                      long long* count) const {
    *sum = stale_sum_.load(std::memory_order_relaxed);
    *max_out = stale_max_.load(std::memory_order_relaxed);
    *count = stale_n_.load(std::memory_order_relaxed);
  }
  ~BytePSWorker() { Stop(); }

  // Partition + register a tensor with its owning servers (blocking).
  // Returns the tensor id. Priority = negative declaration order.
  int64_t Declare(const std::string& name, int64_t nelem, int dtype,
                  const std::string& comp_config);

  // Enqueue all partitions; returns a completion handle immediately.
  // The aggregate (sum over workers; divided by num_workers when `average`)
  // is written back into `ptr` in place.
  int PushPull(int64_t tensor_id, void* ptr, int64_t nelem, int dtype,
               bool average, bool async_mode);

  // Init-time weight sync: root's buffer becomes everyone's (in place).
  int Broadcast(int64_t tensor_id, void* ptr, int64_t nelem, int dtype,
                int root_rank);

  // Returns 0 on success, -1 if the handle failed (dead peer) — the
  // diagnostic is then available via LastError().
  int Wait(int handle);
  // 1 = complete (reaped), 0 = pending, -1 = settled-but-failed (not
  // reaped; a follow-up Wait surfaces the error and reaps).
  int Poll(int handle);

  // Diagnostic for the most recent failed Wait on this worker.
  std::string LastError();

  // Scheduled-queue occupancy for the monitor snapshot: pending tasks,
  // in-flight bytes, and the credit budget they are admitted against.
  void QueueStats(int64_t* pending, int64_t* inflight,
                  int64_t* budget) const {
    if (!queue_) {
      *pending = *inflight = *budget = 0;
      return;
    }
    *pending = static_cast<int64_t>(queue_->pending());
    *inflight = queue_->inflight_bytes();
    *budget = queue_->budget_bytes();
  }

 private:
  struct Part;
  struct TensorCtx;

  struct Handle {
    std::atomic<int> remaining;
    std::atomic<bool> failed{false};
    std::string error;  // guarded by the worker mutex
    explicit Handle(int n) : remaining(n) {}
  };

  // One wire-ready push staged by a scheduled-queue task: everything the
  // send path needs after compression ran. `payload` points into the
  // caller's buffer or the partition's comp_buf — both stay alive until
  // the handle settles, so fused sends may gather them without copies.
  struct PushOp {
    Part* p = nullptr;
    TensorCtx* ctx = nullptr;
    char* base = nullptr;  // caller buffer slice (pull destination)
    int64_t raw_len = 0;
    const void* payload = nullptr;
    int64_t payload_len = 0;
    int flags = 0;
    int version = 0;
    double scale = 1.0;
    // Mean requested: the divisor is the ROUND's contributor count
    // reported on the pull response (arg1), not the fleet size captured
    // at issue time — an elastic membership change between issue and
    // completion would otherwise divide by the wrong N (ISSUE 8).
    bool average = false;
    std::shared_ptr<Handle> handle;
  };

  struct Part {
    int64_t key;
    int server_id;  // postoffice node id
    int64_t offset;  // elements
    int64_t len;     // elements
    std::unique_ptr<Compressor> comp;
    std::vector<char> comp_buf;
    // Hot-replacement recovery state (ISSUE 4; guarded by rec_mu_,
    // maintained only when recovery is armed). The sync step keeps at
    // most ONE op per key in flight, so one slot is a complete record:
    //   rec_stage 0: idle — reseed_data holds round reseed_round's
    //     unscaled aggregate (the authoritative re-seed payload);
    //   rec_stage 1: push issued (rec_push_rid = its request id; while
    //     the request is pending, the resend queue re-delivers it);
    //   rec_stage 2: push ACKED, pull in flight — the dead server's
    //     partial sum held our contribution, so recovery must RE-PUSH
    //     it (rec_op's payload pointers stay valid: the handle has not
    //     settled, so the caller buffer / comp_buf are alive and the
    //     pull has not overwritten them).
    int rec_stage = 0;
    int rec_push_rid = -1;
    PushOp rec_op;
    // Quantized wire state (ISSUE 6, BYTEPS_WIRE_QUANT). qresidual is
    // the per-key push-leg error-feedback carry: residual += grad,
    // encode(residual), residual -= decode(encoded) — so the int8
    // rounding error of round r rides into round r+1's encode and the
    // EF trajectory tracks dense. It lives HERE (worker-resident, one
    // float per element whenever quant is armed — the same memory
    // class as reseed_data) precisely so it survives a server death:
    // recovery re-pushes ship the already-encoded snapshot and the
    // residual stream stays bit-identical to the fault-free run.
    // qbuf is the encoded payload; like comp_buf it is pinned until
    // the handle settles (fused frames gather from it zero-copy).
    std::vector<float> qresidual;
    std::vector<char> qbuf;
    // Last completed round's unscaled aggregate — the re-seed payload.
    // Costs ~one gradient-sized buffer per worker whenever recovery is
    // armed (documented under BYTEPS_RECOVERY_TIMEOUT_MS in
    // docs/env.md). EVERY worker retains it, not a designated rank:
    // the server can die after serving some ranks' round-r pulls but
    // not others', and only a rank whose pull COMPLETED holds round
    // r's bytes — which ranks those are is unknowable in advance.
    std::vector<char> reseed_data;
    int reseed_round = -1;
  };

  struct TensorCtx {
    int64_t id;
    std::string name;
    int64_t nelem;
    int dtype;
    int priority;
    int64_t round = 0;
    int64_t bcast_round = 0;  // broadcast round (head.version on BCAST_*)
    std::string comp_config;  // resolved codec config (recovery re-declare)
    std::vector<Part> parts;
  };

  void PushLoop();
  // True when a partition ships the block-quantized wire encoding:
  // quant armed, float32, and at least the minimum raw size (below it
  // the per-block scale overhead isn't worth the framing). Callers
  // additionally require the key to be codec-less (p->comp == nullptr)
  // — a compressed payload is already encoded freight.
  bool QuantEligible(const TensorCtx* ctx, int64_t raw_len) const {
    return wire_quant_ && ctx->dtype == BPS_FLOAT32 &&
           raw_len >= quant_min_bytes_;
  }
  // Span into the shared main trace ring (trace.h); `round`/`peer`/`req`
  // feed the merge tool's stage attribution and flow stitching.
  // `wire_bytes`/`raw_bytes` label data-carrying spans (push/qdecode)
  // with their on-wire vs decoded sizes, so the timeline report can
  // show quantized-vs-raw freight per span (ISSUE 7 satellite).
  void Record(int64_t key, const char* stage, int64_t start_us,
              int peer = -1, int32_t req_id = -1, int32_t round = -1,
              int64_t wire_bytes = 0, int64_t raw_bytes = 0);
  // Mark a handle failed with the CMD_ERROR diagnostic and complete it.
  void FailHandle(const std::shared_ptr<Handle>& handle, int64_t key,
                  Message&& err);
  // Single-frame send: CMD_PUSH, chained CMD_PULL from the ack callback
  // (the pre-fusion hot path, unchanged semantics).
  void SendPush(PushOp op);
  // Collector flush: singletons keep the single-frame wire format,
  // anything larger goes out as one fused frame.
  void FlushBatch(int server_id, std::vector<PushOp> ops);
  // Fused send: one CMD_MULTI_PUSH frame for the whole batch, one
  // batched ack, one CMD_MULTI_PULL, one batched response.
  void SendFusedPush(int server_id, std::vector<PushOp> ops);
  void OnFusedAck(int server_id,
                  const std::shared_ptr<std::vector<PushOp>>& batch,
                  int64_t t_push, Message&& ack);
  void OnFusedPullResp(const std::shared_ptr<std::vector<PushOp>>& batch,
                       const std::shared_ptr<std::vector<int64_t>>& at_push,
                       int64_t t_pull, Message&& resp);
  // Fail every handle in the batch with the CMD_ERROR diagnostic and
  // release its credits.
  void FailBatch(const std::shared_ptr<std::vector<PushOp>>& batch,
                 Message&& err);

 public:
  // Elastic worker membership (ISSUE 8; van recv threads). Pause (join
  // kind): gate new rounds and ack the scheduler with this worker's
  // round counters — DRAIN-FREE: rounds already issued complete
  // against the old roster, so the ack only has to freeze the
  // counters. Resume: sync counters up to the join activation round
  // (so every member's next round is the first the joiner is expected
  // in) and lift the gate.
  void OnFleetPause(int kind);
  void OnFleetResume(int kind, int64_t join_round, int64_t join_bcast);
  // Joiner: counters this rank's tensors start at (from the
  // scheduler's direct ADDRBOOK); applies to future Declares too.
  void SyncRounds(int64_t round, int64_t bcast_round);

  // Scheduler fail-over (ISSUE 15). MaxIssuedRound: the
  // rounds-completed watermark a CMD_REREGISTER carries (max round any
  // tensor has issued — same arithmetic as OnFleetPause's gated-counter
  // ack). OnSchedRecovered: a scheduler recovery committed — any round
  // gate a pre-crash FLEET_PAUSE armed is stale (its commit died with
  // the old scheduler; the rebuilt one has no such op in flight), so
  // lift it rather than deadlock the next round.
  int64_t MaxIssuedRound();
  void OnSchedRecovered();

  // Hot server replacement (ISSUE 4): the postoffice's peer-recovered
  // callback lands here (van recv thread). Spawns a background thread
  // that re-declares the dead rank's key shard on the replacement,
  // re-pushes settled in-flight contributions, RESEEDs completed rounds
  // from this worker's retained aggregates, then drains the parked
  // resend queue (KVWorker::ResendNode).
  void OnServerRecovered(int node_id);

 private:
  void RecoverServer(int node_id);
  // Recovery bookkeeping around a push send (stage 1 + request id).
  void RecTrackPush(Part* p, const PushOp& op);
  void RecTrackPushRid(Part* p, int rid);
  // Push acked: the dead-server recovery must re-push from rec_op.
  void RecTrackAck(Part* p);
  // Pull landed: retain the round's unscaled aggregate for RESEED.
  void RecTrackDone(Part* p, int version, const char* base,
                    int64_t raw_len);
  void RecClear(Part* p);

  Postoffice* po_ = nullptr;
  KVWorker* kv_ = nullptr;
  int64_t partition_bytes_ = 4096000;
  int64_t fusion_bytes_ = 0;  // 0 = fusion off
  int fusion_keys_ = 128;
  int64_t fusion_linger_us_ = 200;  // BYTEPS_FUSION_LINGER_US
  // Block-quantized wire (ISSUE 6): BYTEPS_WIRE_QUANT arms int8
  // encoding (+ worker-side EF residuals) for codec-less float32
  // partitions of at least quant_min_bytes_ raw bytes; the pull leg
  // requests the server's re-quantized aggregate for the same keys.
  bool wire_quant_ = false;          // BYTEPS_WIRE_QUANT
  int quant_block_ = 64;             // BYTEPS_WIRE_QUANT_BLOCK
  int64_t quant_min_bytes_ = 1024;   // BYTEPS_WIRE_QUANT_MIN_BYTES
  std::string default_comp_;
  bool trace_on_ = false;

  // Fusion collector: while a PushLoop thread assembles a batch, its
  // tasks stage PushOps here instead of sending (thread-local — each
  // push thread batches independently).
  static thread_local std::vector<PushOp>* fusion_sink_;

  std::mutex mu_;
  std::condition_variable cv_;
  // Elastic membership gate + counter sync (guarded by mu_): while a
  // JOIN commits, new PushPull/Broadcast rounds wait at the gate;
  // sync_round_/sync_bcast_round_ are the counters new declares (and,
  // on a join's RESUME, existing tensors) start from.
  bool fleet_paused_ = false;
  int64_t sync_round_ = 0;
  int64_t sync_bcast_round_ = 0;
  std::unordered_map<std::string, int64_t> by_name_;
  std::vector<std::unique_ptr<TensorCtx>> tensors_;
  // Cumulative bytes assigned per server (guarded by mu_): drives the
  // byte-balanced partition->server mapping in Declare.
  std::vector<int64_t> server_bytes_;
  // Async staleness accounting (SURVEY §2.7 DP-async): per async pull,
  // how many fleet-wide pushes the server applied between this worker's
  // push and its pull (from the ack/resp arg1 counters). Cumulative over
  // the worker's lifetime; read via byteps_async_staleness.
  std::atomic<int64_t> stale_sum_{0};
  std::atomic<int64_t> stale_max_{0};
  std::atomic<int64_t> stale_n_{0};
  std::unordered_map<int, std::shared_ptr<Handle>> handles_;
  int next_handle_ = 0;
  std::string last_error_;  // guarded by mu_

  std::unique_ptr<ScheduledQueue> queue_;
  std::vector<std::thread> push_threads_;

  // Recovery (ISSUE 4): armed when RecoveryEnabled(); rec_mu_ guards
  // every Part's rec_*/reseed_* fields (writers are the per-key
  // executor callbacks; the reader is a RecoverServer thread).
  bool recovery_on_ = false;
  std::mutex rec_mu_;
  std::mutex rec_threads_mu_;
  std::vector<std::thread> rec_threads_;
};

}  // namespace bps
