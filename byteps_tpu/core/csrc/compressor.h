// Gradient compression plugin framework.
//
// Capability parity: reference byteps/common/compressor/ (SURVEY.md §2.2):
// Compressor base + registry keyed by per-tensor param strings, algorithms
// onebit / topk / randomk / dithering, decorators error-feedback (residual
// accumulation) and momentum (nesterov), applied on host buffers at the
// push boundary; the server decompresses, sums, and serves raw aggregates.
//
// Config string grammar (passed through declare_tensor, parity with the
// reference's byteps_compressor_* params):
//   "type=onebit" | "type=topk;k=32" | "type=randomk;k=32;seed=7" |
//   "type=dithering;bits=8"  — optionally with ";ef=vanilla" and/or
//   ";momentum=nesterov;mu=0.9" decorators.
#pragma once

#include <cstdint>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

namespace bps {

class Compressor {
 public:
  virtual ~Compressor() = default;
  // Encode `n` float32 elements of src into out (resized to compressed size).
  virtual void Compress(const float* src, int64_t n,
                        std::vector<char>* out) = 0;
  // Decode into dst (n float32 elements, overwritten).
  virtual void Decompress(const char* src, int64_t src_bytes, float* dst,
                          int64_t n) = 0;
};

// Parse a config string and build the (possibly decorated) compressor for a
// partition of `n` elements. Returns nullptr for empty/absent type.
std::unique_ptr<Compressor> CreateCompressor(const std::string& config,
                                             int64_t n);

// Parsed key=value view of a config string (exposed for tests).
std::unordered_map<std::string, std::string> ParseCompressorConfig(
    const std::string& config);

// --- block-quantized wire codec (ISSUE 6) -----------------------------------
// EQuARX-style per-block int8 encoding for the fused data plane
// (BYTEPS_WIRE_QUANT): each block of `block` float32 values ships as one
// f32 absmax-derived scale plus `block` int8 codes. Unlike the Compressor
// plugins above — per-key stateful objects selected per tensor — this is
// a stateless, self-describing WIRE format: any rank can decode any
// frame from the payload alone, resends ship snapshot bytes untouched,
// and the server dequant-sums into its float32 accumulator.
//
// Wire layout: [u16 magic 0xB10C][u16 block][i32 nelem]
//              [ceil(n/block) f32 scales][n int8 codes]
// ~3.8x smaller than raw float32 at block=64. Error feedback is the
// CALLER's job (the worker keeps per-key residuals; EncodeEF folds the
// residual update into the encode pass).
struct BlockQuant {
  // Blocks must be a power of two in [16, 32768] (config.py validates
  // the env knob; this is the wire-level contract Decode enforces too).
  static bool ValidBlock(int block) {
    return block >= 16 && block <= 32768 && (block & (block - 1)) == 0;
  }
  static int64_t EncodedSize(int64_t n, int block) {
    int64_t nblocks = (n + block - 1) / block;
    return 8 + nblocks * static_cast<int64_t>(sizeof(float)) + n;
  }
  // Encode n floats. Returns false — without producing output — on a
  // NaN/Inf input or an invalid block: a non-finite gradient must error
  // loudly at the encode boundary, never ship as garbage codes.
  static bool Encode(const float* src, int64_t n, int block,
                     std::vector<char>* out);
  // Error-feedback variant: `residual` already holds gradient + carried
  // residual; encodes it and subtracts the decoded value in place, so
  // the quantization error of THIS round rides into the next one.
  static bool EncodeEF(float* residual, int64_t n, int block,
                       std::vector<char>* out);
  // Decode into dst (n floats). Returns false on a malformed payload
  // (bad magic/block/element count/length) instead of reading garbage.
  static bool Decode(const char* src, int64_t src_bytes, float* dst,
                     int64_t n);
};

}  // namespace bps
