// Gradient compression plugin framework.
//
// Capability parity: reference byteps/common/compressor/ (SURVEY.md §2.2):
// Compressor base + registry keyed by per-tensor param strings, algorithms
// onebit / topk / randomk / dithering, decorators error-feedback (residual
// accumulation) and momentum (nesterov), applied on host buffers at the
// push boundary; the server decompresses, sums, and serves raw aggregates.
//
// Config string grammar (passed through declare_tensor, parity with the
// reference's byteps_compressor_* params):
//   "type=onebit" | "type=topk;k=32" | "type=randomk;k=32;seed=7" |
//   "type=dithering;bits=8"  — optionally with ";ef=vanilla" and/or
//   ";momentum=nesterov;mu=0.9" decorators.
#pragma once

#include <cstdint>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

namespace bps {

class Compressor {
 public:
  virtual ~Compressor() = default;
  // Encode `n` float32 elements of src into out (resized to compressed size).
  virtual void Compress(const float* src, int64_t n,
                        std::vector<char>* out) = 0;
  // Decode into dst (n float32 elements, overwritten).
  virtual void Decompress(const char* src, int64_t src_bytes, float* dst,
                          int64_t n) = 0;
};

// Parse a config string and build the (possibly decorated) compressor for a
// partition of `n` elements. Returns nullptr for empty/absent type.
std::unique_ptr<Compressor> CreateCompressor(const std::string& config,
                                             int64_t n);

// Parsed key=value view of a config string (exposed for tests).
std::unordered_map<std::string, std::string> ParseCompressorConfig(
    const std::string& config);

}  // namespace bps
