// Leveled logging + check macros.
// Capability parity: reference byteps/common/logging.{h,cc} (BPS_LOG /
// BPS_CHECK gated by BYTEPS_LOG_LEVEL) — see SURVEY.md §2.1.
#pragma once

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <sstream>
#include <string>

namespace bps {

// Defined in trace.cc: dump the always-on flight recorder before a
// fatal CHECK aborts, so the crash ships with the last N events
// (docs/troubleshooting.md "read the flight recorder first").
// Reentrancy-guarded, dumps at most once per process.
void FlightDumpOnFatal();

enum class LogLevel : int { DEBUG = 0, INFO = 1, WARNING = 2, FATAL = 3 };

inline LogLevel MinLogLevel() {
  static LogLevel lvl = [] {
    const char* env = getenv("BYTEPS_LOG_LEVEL");
    if (!env) return LogLevel::WARNING;
    std::string s(env);
    for (auto& c : s) c = toupper(c);
    if (s == "DEBUG" || s == "TRACE") return LogLevel::DEBUG;
    if (s == "INFO") return LogLevel::INFO;
    if (s == "WARNING" || s == "WARN") return LogLevel::WARNING;
    return LogLevel::WARNING;
  }();
  return lvl;
}

class LogMessage {
 public:
  LogMessage(const char* file, int line, LogLevel level, bool fatal)
      : level_(level), fatal_(fatal) {
    stream_ << "[byteps-tpu " << Name(level) << " " << Basename(file) << ":"
            << line << "] ";
  }
  ~LogMessage() {
    if (level_ >= MinLogLevel() || fatal_) {
      fprintf(stderr, "%s\n", stream_.str().c_str());
      fflush(stderr);
    }
    if (fatal_) {
      // Still in normal (non-signal) context here: safe to take the
      // ring mutex and write the flight-recorder dump before aborting.
      FlightDumpOnFatal();
      abort();
    }
  }
  std::ostringstream& stream() { return stream_; }

 private:
  static const char* Name(LogLevel l) {
    switch (l) {
      case LogLevel::DEBUG: return "DEBUG";
      case LogLevel::INFO: return "INFO";
      case LogLevel::WARNING: return "WARN";
      default: return "FATAL";
    }
  }
  static const char* Basename(const char* f) {
    const char* s = strrchr(f, '/');
    return s ? s + 1 : f;
  }
  std::ostringstream stream_;
  LogLevel level_;
  bool fatal_;
};

#define BPS_LOG(lvl) \
  ::bps::LogMessage(__FILE__, __LINE__, ::bps::LogLevel::lvl, false).stream()

#define BPS_FATAL \
  ::bps::LogMessage(__FILE__, __LINE__, ::bps::LogLevel::FATAL, true).stream()

#define BPS_CHECK(cond) \
  if (!(cond)) BPS_FATAL << "Check failed: " #cond " "

#define BPS_CHECK_EQ(a, b) \
  BPS_CHECK((a) == (b)) << "(" << (a) << " vs " << (b) << ") "
#define BPS_CHECK_NE(a, b) \
  BPS_CHECK((a) != (b)) << "(" << (a) << " vs " << (b) << ") "
#define BPS_CHECK_GE(a, b) \
  BPS_CHECK((a) >= (b)) << "(" << (a) << " vs " << (b) << ") "
#define BPS_CHECK_GT(a, b) \
  BPS_CHECK((a) > (b)) << "(" << (a) << " vs " << (b) << ") "
#define BPS_CHECK_LE(a, b) \
  BPS_CHECK((a) <= (b)) << "(" << (a) << " vs " << (b) << ") "

}  // namespace bps
