#include "cpu_reducer.h"

#include <cstring>
#if defined(__F16C__) && defined(__AVX__)
#include <immintrin.h>
#endif

#include "common.h"
#include "logging.h"

namespace bps {

float Fp16ToF32(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1F;
  uint32_t mant = h & 0x3FF;
  union { uint32_t u; float f; } x;
  if (exp == 0) {
    if (mant == 0) {
      x.u = sign;
    } else {  // subnormal
      exp = 127 - 15 + 1;
      while ((mant & 0x400) == 0) {
        mant <<= 1;
        exp--;
      }
      mant &= 0x3FF;
      x.u = sign | (exp << 23) | (mant << 13);
    }
  } else if (exp == 31) {
    x.u = sign | 0x7F800000u | (mant << 13);
  } else {
    x.u = sign | ((exp + 127 - 15) << 23) | (mant << 13);
  }
  return x.f;
}

uint16_t F32ToFp16(float f) {
  union { uint32_t u; float f32; } x;
  x.f32 = f;
  uint32_t sign = (x.u >> 16) & 0x8000u;
  int32_t exp = static_cast<int32_t>((x.u >> 23) & 0xFF) - 127 + 15;
  uint32_t mant = x.u & 0x7FFFFF;
  if (exp >= 31) return static_cast<uint16_t>(sign | 0x7C00u);  // inf/overflow
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);
    mant |= 0x800000;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint32_t half = 1u << (shift - 1);
    return static_cast<uint16_t>(sign | ((mant + half) >> shift));
  }
  // round to nearest even on the 13 dropped bits
  uint32_t rounded = mant + 0xFFF + ((mant >> 13) & 1);
  if (rounded & 0x800000) {
    rounded = 0;
    exp++;
    if (exp >= 31) return static_cast<uint16_t>(sign | 0x7C00u);
  }
  return static_cast<uint16_t>(sign | (exp << 10) | (rounded >> 13));
}

namespace {

template <typename T>
void SumT(T* dst, const T* a, const T* b, int64_t n) {
#pragma omp parallel for simd
  for (int64_t i = 0; i < n; ++i) dst[i] = a[i] + b[i];
}

void SumBf16(uint16_t* dst, const uint16_t* a, const uint16_t* b, int64_t n) {
#pragma omp parallel for simd
  for (int64_t i = 0; i < n; ++i)
    dst[i] = F32ToBf16(Bf16ToF32(a[i]) + Bf16ToF32(b[i]));
}

void SumFp16(uint16_t* dst, const uint16_t* a, const uint16_t* b, int64_t n) {
#if defined(__F16C__) && defined(__AVX__)
  // Hardware half<->float converts, 8 lanes at a time: the scalar
  // conversion is branch-heavy (subnormals, round-to-nearest-even) and
  // runs ~30x slower — slow enough to make fp16-wire summation the
  // server bottleneck.
  int64_t vec_end = n & ~int64_t(7);
#pragma omp parallel for
  for (int64_t i = 0; i < vec_end; i += 8) {
    __m256 va = _mm256_cvtph_ps(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(a + i)));
    __m256 vb = _mm256_cvtph_ps(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(b + i)));
    _mm_storeu_si128(
        reinterpret_cast<__m128i*>(dst + i),
        _mm256_cvtps_ph(_mm256_add_ps(va, vb),
                        _MM_FROUND_TO_NEAREST_INT | _MM_FROUND_NO_EXC));
  }
  for (int64_t i = vec_end; i < n; ++i)
    dst[i] = F32ToFp16(Fp16ToF32(a[i]) + Fp16ToF32(b[i]));
#else
#pragma omp parallel for simd
  for (int64_t i = 0; i < n; ++i)
    dst[i] = F32ToFp16(Fp16ToF32(a[i]) + Fp16ToF32(b[i]));
#endif
}

template <typename T>
void ScaleT(T* dst, double s, int64_t n) {
#pragma omp parallel for simd
  for (int64_t i = 0; i < n; ++i)
    dst[i] = static_cast<T>(dst[i] * s);
}

}  // namespace

void CpuReducer::Sum(void* dst, const void* a, const void* b,
                     int64_t len_bytes, int dtype) {
  int esz = DtypeSize(dtype);
  BPS_CHECK_GT(esz, 0) << "bad dtype " << dtype;
  int64_t n = len_bytes / esz;
  switch (dtype) {
    case BPS_FLOAT32:
      SumT(static_cast<float*>(dst), static_cast<const float*>(a),
           static_cast<const float*>(b), n);
      break;
    case BPS_FLOAT64:
      SumT(static_cast<double*>(dst), static_cast<const double*>(a),
           static_cast<const double*>(b), n);
      break;
    case BPS_BFLOAT16:
      SumBf16(static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(a),
              static_cast<const uint16_t*>(b), n);
      break;
    case BPS_FLOAT16:
      SumFp16(static_cast<uint16_t*>(dst), static_cast<const uint16_t*>(a),
              static_cast<const uint16_t*>(b), n);
      break;
    case BPS_INT32:
      SumT(static_cast<int32_t*>(dst), static_cast<const int32_t*>(a),
           static_cast<const int32_t*>(b), n);
      break;
    case BPS_INT64:
      SumT(static_cast<int64_t*>(dst), static_cast<const int64_t*>(a),
           static_cast<const int64_t*>(b), n);
      break;
    case BPS_INT8:
      SumT(static_cast<int8_t*>(dst), static_cast<const int8_t*>(a),
           static_cast<const int8_t*>(b), n);
      break;
    case BPS_UINT8:
      SumT(static_cast<uint8_t*>(dst), static_cast<const uint8_t*>(a),
           static_cast<const uint8_t*>(b), n);
      break;
    default:
      BPS_FATAL << "unsupported dtype " << dtype;
  }
}

void CpuReducer::Sum(void* dst, const void* src, int64_t len_bytes,
                     int dtype) {
  Sum(dst, dst, src, len_bytes, dtype);
}

void CpuReducer::Copy(void* dst, const void* src, int64_t len_bytes) {
  memcpy(dst, src, static_cast<size_t>(len_bytes));
}

void CpuReducer::Scale(void* dst, double s, int64_t len_bytes, int dtype) {
  int esz = DtypeSize(dtype);
  BPS_CHECK_GT(esz, 0);
  int64_t n = len_bytes / esz;
  switch (dtype) {
    case BPS_FLOAT32:
      ScaleT(static_cast<float*>(dst), s, n);
      break;
    case BPS_FLOAT64:
      ScaleT(static_cast<double*>(dst), s, n);
      break;
    case BPS_BFLOAT16: {
      auto* p = static_cast<uint16_t*>(dst);
#pragma omp parallel for simd
      for (int64_t i = 0; i < n; ++i)
        p[i] = F32ToBf16(static_cast<float>(Bf16ToF32(p[i]) * s));
      break;
    }
    case BPS_FLOAT16: {
      auto* p = static_cast<uint16_t*>(dst);
#pragma omp parallel for simd
      for (int64_t i = 0; i < n; ++i)
        p[i] = F32ToFp16(static_cast<float>(Fp16ToF32(p[i]) * s));
      break;
    }
    // Integer scaling truncates toward zero (averaging an int tensor is
    // inherently lossy; supported so a stray int leaf in a gradient tree
    // degrades gracefully instead of killing the worker).
    case BPS_INT32:
      ScaleT(static_cast<int32_t*>(dst), s, n);
      break;
    case BPS_INT64:
      ScaleT(static_cast<int64_t*>(dst), s, n);
      break;
    case BPS_INT8:
      ScaleT(static_cast<int8_t*>(dst), s, n);
      break;
    case BPS_UINT8:
      ScaleT(static_cast<uint8_t*>(dst), s, n);
      break;
    default:
      BPS_FATAL << "Scale: unsupported dtype " << dtype;
  }
}

}  // namespace bps
