// Structured fleet event journal (ISSUE 20).
//
// The stack already records WHAT the fleet is doing numerically (the
// metric registry, PR 1), WHERE time goes (trace rings, PR 5) and HOW
// each round broke down (roundstats, PR 7) — but the lifecycle
// transitions themselves (epoch pause/resume, membership changes,
// scheduler fail-over, checkpoint spills, snapshot commits, CRC
// quarantines, chaos injections) only exist as log lines and trace-ring
// notes scattered across ranks. This layer is the missing journal: a
// bounded drop-oldest ring of TYPED, versioned FleetEvent records,
// emitted at the exact sites where those transitions already happen,
// cheap enough to stay on by default (BYTEPS_EVENTS_ON, armed = one
// relaxed atomic load per site; overhead gated like BENCH_insight_r07 —
// see BENCH_events_r20.json).
//
// Fleet aggregation mirrors the roundstats sensor path: every
// non-scheduler rank piggybacks its new-since-last-beat events on
// CMD_HEARTBEAT as a SECOND versioned sub-payload after the 0xB57A
// round-summary one. Each sub-payload is self-describing (magic +
// version + count), so the scheduler walks the heartbeat payload chunk
// by chunk and old receivers — whose RoundStats::Ingest tolerates
// trailing bytes — simply never see the new chunk. With events off the
// heartbeat payload is byte-for-byte the PR 19 wire.
//
// The scheduler ingests events into a fleet-ordered TIMELINE: each
// event's local CLOCK_MONOTONIC timestamp is shifted by the sender's
// heartbeat-derived clock offset (PR 5 min-RTT estimate, carried in the
// sub-payload header) onto the scheduler's timebase. Alongside, the
// scheduler samples every registered gauge into bounded per-metric
// HISTORY rings (one sample per second), so an incident report can show
// the metric curves around any event window. Both are served by the
// bps_events_summary FFI probe, the /events monitor endpoint, and
// `python -m byteps_tpu.monitor.incident`.
//
// Concurrency: one mutex guards ring + timeline + history (emit sites
// are per-transition, far off any hot path; the armed check is a
// relaxed atomic load). The singleton is intentionally leaked, like
// Metrics/Trace/RoundStats, so teardown paths can still journal.
#pragma once

#include <atomic>
#include <cstdint>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace bps {

// Event types. Values are part of the versioned wire contract (bump
// kEventWireVersion on any renumbering); names via EventTypeName.
// Argument meanings are catalogued in docs/monitoring.md.
enum EventType : int32_t {
  EV_NONE = 0,
  EV_EPOCH_PAUSE = 1,        // a0=epoch a1=node being replaced
  EV_EPOCH_RESUME = 2,       // a0=epoch a1=replacement node
  EV_FLEET_PAUSE = 3,        // a0=epoch a1=kind (0 join,1 leave,2 shrink)
  EV_FLEET_RESUME = 4,       // a0=epoch a1=kind — the membership commit
  EV_JOIN = 5,               // a0=node a1=role
  EV_LEAVE = 6,              // a0=node a1=1 when a death-shrink
  EV_DEATH = 7,              // a0=node a1=role (heartbeat-timeout death)
  EV_SERVER_RECOVER = 8,     // a0=node a1=epoch (replacement registered
                             //   on the scheduler; re-seed done on workers)
  EV_RESEED = 9,             // a0=key a1=node a2=round (worker offer /
                             //   server adoption)
  EV_SCHED_PARK = 10,        // a0=deadline_ms (node parked on lost sched)
  EV_SCHED_REREGISTER = 11,  // a0=node (re-registration accepted)
  EV_SCHED_RECOVERY_COMMIT = 12,  // a0=epoch a1=nodes re-registered
  EV_CKPT_SPILL = 13,        // a0=version a1=items (spill started)
  EV_CKPT_SEAL = 14,         // a0=version a1=spill_ms (manifest sealed;
                             //   a2=1 marks a FAILED spill)
  EV_CKPT_RESTORE = 15,      // a0=restore round (fleet restore epoch)
  EV_SNAP_COMMIT = 16,       // a0=committed version
  EV_SNAP_EVICT = 17,        // a0=newest evicted version
  EV_REPLICA_LAG = 18,       // a0=lag rounds a1=primary version
  EV_CRC_QUARANTINE = 19,    // a0=node a1=failures in window
  EV_CRC_FAILSTOP = 20,      // a0=node (persistently corrupting link)
  EV_TENANT_STARVED = 21,    // a0=tenant a1=starved_ms
  EV_CHAOS = 22,             // a0=kind (0 reset,1 drop,2 dup,3 corrupt)
                             //   a1=key
  EV_INSIGHT = 23,           // a0=state code a1=round (insight.py
                             //   classification change, journaled via
                             //   POST /events)
  EV_SHUTDOWN = 24,          // a0=1 failure-triggered, 0 clean
  EV_TYPE_COUNT = 25,
};

const char* EventTypeName(int32_t type);

#pragma pack(push, 1)
// One journal record. Packed: this struct IS the heartbeat wire
// sub-payload element (part of the versioned wire contract).
struct FleetEvent {
  int32_t type = EV_NONE;
  int32_t node_id = -1;
  int32_t role = -1;
  int32_t pad = 0;       // explicit, so the packed layout is stable
  int64_t ts_us = 0;     // local CLOCK_MONOTONIC at emit (us); the
                         // scheduler aligns via the sender's offset
  int64_t a0 = 0;
  int64_t a1 = 0;
  int64_t a2 = 0;
};

// Heartbeat sub-payload header: `count` FleetEvents follow, oldest
// first. clock_offset_us is the sender's CURRENT heartbeat-derived
// offset vs the scheduler clock (t_sched ~= t_local + offset), so the
// receiver can place even pre-outage backlog events on its timebase.
struct EventWireHdr {
  uint16_t magic = 0;
  uint16_t version = 0;
  int32_t node_id = -1;
  int32_t role = -1;
  int32_t count = 0;
  int64_t emitted_total = 0;
  int64_t dropped = 0;
  int64_t clock_offset_us = 0;
};
#pragma pack(pop)

constexpr uint16_t kEventWireMagic = 0xE7B5;  // != 0xB57A (roundstats)
constexpr uint16_t kEventWireVersion = 1;
constexpr int kMaxWireEvents = 64;  // per heartbeat; rest ride the next

class Events {
 public:
  // Leaked heap singleton (same rationale as Metrics/Trace/RoundStats):
  // shutdown and failure paths are exactly when journaling matters.
  static Events& Get();

  bool On() const { return armed_.load(std::memory_order_relaxed); }
  void SetNode(int role, int node_id);

  // Heartbeat-derived clock offset vs the scheduler (PR 5 min-RTT
  // estimate); fed next to Trace::SetClock. The scheduler itself is
  // the timebase (offset 0).
  void SetClock(int64_t offset_us);
  int64_t clock_offset_us() const {
    return clock_offset_us_.load(std::memory_order_relaxed);
  }

  // The one emit entry point (no-op unless On()). Timestamps with
  // NowUs() and appends to the local drop-oldest ring; on the
  // scheduler the event also enters the fleet timeline directly.
  void Emit(int32_t type, int64_t a0 = 0, int64_t a1 = 0, int64_t a2 = 0);

  // APPEND the events newer than the last call to `out` as one
  // magic-tagged sub-payload (at most kMaxWireEvents; the backlog
  // rides later beats). Returns false — appending nothing — when off
  // or nothing is new, keeping the events-off heartbeat byte-for-byte
  // the pre-journal wire.
  bool FillWire(std::string* out);

  // Scheduler side: ingest one events sub-payload into the fleet
  // timeline, aligning each record's timestamp by the header's clock
  // offset. Returns false — and changes nothing — when the bytes are
  // not a recognized events chunk (old sender, foreign magic, short
  // frame).
  bool Ingest(const void* data, size_t len);

  // Bytes a recognized events sub-payload at `data` occupies (0 when
  // not ours) — heartbeat payloads multiplex magic-tagged chunks and
  // the scheduler walks them with this.
  static size_t PeekWireSize(const void* data, size_t len);

  // Scheduler side: sample every registered gauge into the bounded
  // per-metric history rings, rate-limited internally to one sample
  // per second — called from the heartbeat handler, so history
  // advances exactly while the fleet is alive.
  void SampleHistory(int64_t now_us);

  // Whole-state JSON for bps_events_summary: {"on","role","node_id",
  // "ring_capacity","emitted_total","dropped","clock_offset_us",
  // "events":[...]} plus, on ranks that ingested fleet events (the
  // scheduler), "timeline":[...] (clock-aligned, fleet-ordered) and
  // "history":{name:[[ts_us,value],...]}.
  std::string SnapshotJson();

  int64_t emitted_total();
  int64_t dropped();

 private:
  Events();

  struct TimelineEvent {
    FleetEvent ev;
    int64_t aligned_ts_us = 0;
  };

  void IngestOneLocked(const FleetEvent& ev, int64_t offset_us);

  std::atomic<bool> armed_{false};
  std::atomic<int> role_{-1};
  std::atomic<int> node_id_{-1};
  std::atomic<int64_t> clock_offset_us_{0};

  std::mutex mu_;
  size_t ring_cap_;
  size_t ring_head_ = 0;
  int64_t ring_total_ = 0;   // events ever emitted locally
  std::vector<FleetEvent> ring_;
  int64_t wire_sent_total_ = 0;  // events already shipped via FillWire

  // Fleet timeline (scheduler; bounded drop-oldest by arrival — reads
  // sort by aligned timestamp).
  std::deque<TimelineEvent> timeline_;
  size_t timeline_cap_;
  int64_t timeline_dropped_ = 0;
  int64_t ingested_total_ = 0;

  // Per-metric history rings (scheduler): name -> (ts, value) samples.
  struct History {
    std::deque<std::pair<int64_t, int64_t>> samples;
  };
  std::map<std::string, History> history_;
  size_t history_depth_;
  int64_t last_sample_us_ = 0;
};

}  // namespace bps
