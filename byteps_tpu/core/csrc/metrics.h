// Lock-free metric registry: counters, gauges, fixed-bucket latency
// histograms, exported through bps_metrics_snapshot (c_api.cc) and the
// byteps_tpu.monitor Python package.
//
// New scope (no reference equivalent): the reference's only runtime
// observability is the post-hoc Chrome-trace timeline (BYTEPS_TRACE_*);
// a production fleet needs live per-stage counters you can scrape while
// the job runs (ROADMAP north star; docs/monitoring.md).
//
// Concurrency model: every metric is a named set of std::atomic<int64_t>
// words. Registration (first lookup of a name) takes a mutex; hot paths
// cache the returned pointer in a function-local static, so the steady
// state is one relaxed atomic add per event. Entries are never removed,
// so cached pointers stay valid for the process lifetime — including
// across bps_finalize/bps_init cycles (metrics are cumulative per
// process, like the van byte counters they absorb).
#pragma once

#include <atomic>
#include <cstdint>
#include <map>
#include <memory>
#include <mutex>
#include <string>

namespace bps {

// Fixed bucket upper bounds in MICROSECONDS, spanning sub-RTT loopback
// sends (~50 us) to multi-second straggler pulls. Cumulative ("le")
// conversion for Prometheus exposition happens Python-side
// (monitor/metrics.py); the C side stores per-bucket counts.
constexpr int64_t kHistoBoundsUs[] = {
    50,     100,     250,     500,     1000,    2500,    5000,    10000,
    25000,  50000,   100000,  250000,  500000,  1000000, 2500000, 5000000,
};
constexpr int kHistoBuckets =
    static_cast<int>(sizeof(kHistoBoundsUs) / sizeof(kHistoBoundsUs[0])) + 1;

struct MetricHistogram {
  std::atomic<int64_t> buckets[kHistoBuckets] = {};
  std::atomic<int64_t> sum{0};
  std::atomic<int64_t> count{0};

  void Observe(int64_t v) {
    int i = 0;
    while (i < kHistoBuckets - 1 && v > kHistoBoundsUs[i]) ++i;
    buckets[i].fetch_add(1, std::memory_order_relaxed);
    sum.fetch_add(v, std::memory_order_relaxed);
    count.fetch_add(1, std::memory_order_relaxed);
  }
};

class Metrics {
 public:
  // Intentionally leaked: the registry is constructed AFTER the c_api
  // Global (first metric registration happens inside bps_init), so a
  // function-local static would be destroyed BEFORE ~Global — whose
  // goodbye protocol still sends frames through Van::Send, which counts
  // them here. A heap singleton outlives every teardown path, and the
  // pointers hot paths cache stay valid for the process lifetime.
  static Metrics& Get() {
    static Metrics* inst = new Metrics();
    return *inst;
  }

  std::atomic<int64_t>* Counter(const std::string& name) {
    return Slot(&counters_, name);
  }
  std::atomic<int64_t>* Gauge(const std::string& name) {
    return Slot(&gauges_, name);
  }
  MetricHistogram* Histogram(const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    auto& h = histos_[name];
    if (!h) h = std::make_unique<MetricHistogram>();
    return h.get();
  }

  // Registry contents as JSON object members ("counters":{...},
  // "gauges":{...},"histograms":{...}) WITHOUT the enclosing braces —
  // bps_metrics_snapshot appends topology/role state around it.
  std::string SnapshotJson() {
    std::lock_guard<std::mutex> lk(mu_);
    std::string out = "\"counters\":{";
    AppendScalars(&out, counters_);
    out += "},\"gauges\":{";
    AppendScalars(&out, gauges_);
    out += "},\"histograms\":{";
    bool first = true;
    for (const auto& kv : histos_) {
      if (!first) out += ",";
      first = false;
      out += "\"" + kv.first + "\":{\"bounds_us\":[";
      for (int i = 0; i < kHistoBuckets - 1; ++i) {
        if (i) out += ",";
        out += std::to_string(kHistoBoundsUs[i]);
      }
      out += "],\"buckets\":[";
      for (int i = 0; i < kHistoBuckets; ++i) {
        if (i) out += ",";
        out += std::to_string(
            kv.second->buckets[i].load(std::memory_order_relaxed));
      }
      out += "],\"sum\":" +
             std::to_string(kv.second->sum.load(std::memory_order_relaxed));
      out += ",\"count\":" +
             std::to_string(kv.second->count.load(std::memory_order_relaxed));
      out += "}";
    }
    out += "}";
    return out;
  }

  // Enumerate the registered gauges under the registry lock (ISSUE 20:
  // the scheduler samples every gauge into its event-journal history
  // rings). Registration-ordered; fn(name, current_value).
  template <typename Fn>
  void ForEachGauge(Fn fn) {
    std::lock_guard<std::mutex> lk(mu_);
    for (const auto& kv : gauges_) {
      fn(kv.first, kv.second->load(std::memory_order_relaxed));
    }
  }

 private:
  using ScalarMap =
      std::map<std::string, std::unique_ptr<std::atomic<int64_t>>>;

  std::atomic<int64_t>* Slot(ScalarMap* m, const std::string& name) {
    std::lock_guard<std::mutex> lk(mu_);
    auto& p = (*m)[name];
    if (!p) p = std::make_unique<std::atomic<int64_t>>(0);
    return p.get();
  }

  static void AppendScalars(std::string* out, const ScalarMap& m) {
    bool first = true;
    for (const auto& kv : m) {
      if (!first) *out += ",";
      first = false;
      *out += "\"" + kv.first +
              "\":" + std::to_string(kv.second->load(std::memory_order_relaxed));
    }
  }

  std::mutex mu_;  // registration + snapshot only; never on the add path
  ScalarMap counters_;
  ScalarMap gauges_;
  std::map<std::string, std::unique_ptr<MetricHistogram>> histos_;
};

// Hot-path helpers: resolve the name once per call site.
#define BPS_METRIC_COUNTER_ADD(name, delta)                                \
  do {                                                                     \
    static std::atomic<int64_t>* c = ::bps::Metrics::Get().Counter(name);  \
    c->fetch_add((delta), std::memory_order_relaxed);                      \
  } while (0)

#define BPS_METRIC_GAUGE_SET(name, value)                                  \
  do {                                                                     \
    static std::atomic<int64_t>* g = ::bps::Metrics::Get().Gauge(name);    \
    g->store((value), std::memory_order_relaxed);                          \
  } while (0)

#define BPS_METRIC_HISTO_OBSERVE(name, value)                              \
  do {                                                                     \
    static ::bps::MetricHistogram* h =                                     \
        ::bps::Metrics::Get().Histogram(name);                             \
    h->Observe(value);                                                     \
  } while (0)

}  // namespace bps
