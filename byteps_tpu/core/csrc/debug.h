// Crash diagnostics: print a native backtrace on SIGABRT/SIGSEGV.
// (The reference relies on bare CHECK aborts; symbolised backtraces make
// multi-process topology failures debuggable from captured stderr.)
#pragma once

namespace bps {
// Idempotent; installed at bps_init.
void InstallCrashHandler();
}  // namespace bps
