// Multi-tenant parameter server (ISSUE 9): first-class tenant
// namespaces, per-tenant accounting, and weighted-fair QoS.
//
// A production PS fleet serves many concurrent training jobs. Before
// this layer, two jobs could only share a fleet by accident of the
// `{prefix}_{crc32}_{i}` tid hashing — colliding keys silently aliased
// one job's gradients into the other's, and a heavy job's pushes could
// starve a light job's engine queues. Now:
//
//  - every process carries a tenant id (BYTEPS_TENANT_ID, u16, 0 =
//    legacy/default) stamped into every MsgHeader/SubHeader it sends
//    (common.h carves the field out of bytes that were always zero, so
//    a tenant-0 frame is byte-for-byte the pre-tenant wire);
//  - the server's KeyStore map keys on TenantKey(tenant, key), so two
//    jobs with colliding tids can never alias;
//  - each server engine thread dispatches its queue through WeightedDrr
//    (classic deficit round robin, quantum scaled by the tenant's
//    BYTEPS_TENANT_WEIGHT) so a heavy tenant cannot starve a light one
//    — with a SINGLE active tenant the picker short-circuits to plain
//    FIFO, keeping single-tenant dispatch order byte-for-byte PR 8's;
//  - Tenancy (leaked singleton, like Metrics) accounts bytes / ops /
//    queue depth / sum time per tenant, surfaced as bps_tenant_*
//    series on /metrics, the /tenants monitor endpoint, and
//    monitor.top's tenant rows + starvation flag.
//
// WeightedDrr and TenantKey are deliberately standalone (no server /
// postoffice dependency) so the fair-share arithmetic and the (tenant,
// key) namespacing are unit-testable through the bps_tenant_probe FFI
// hook without standing up a fleet (modeled on bps_elastic_probe).
#pragma once

#include <atomic>
#include <cstdint>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

namespace bps {

// --- process-wide tenant identity (env, static-cached) ----------------------

// BYTEPS_TENANT_ID, clamped to [0, 65535]. 0 = the legacy/default
// tenant: frames carry all-zero tenant bytes and every pre-tenant peer
// interops unchanged.
uint16_t TenantId();

// BYTEPS_TENANT_NAME; defaults to "default" for tenant 0 and
// "tenant<ID>" otherwise. Display-only — names never cross the wire.
const std::string& TenantName();

// BYTEPS_TENANT_WEIGHT, clamped to [1, 1 << 20]. The DRR quantum grant
// is weight x TenantQuantum(), so a weight-3 tenant drains 3x the bytes
// of a weight-1 tenant whenever both lanes are backlogged.
int TenantWeight();

// BYTEPS_TENANT_QUANTUM_BYTES (default 64 KiB): the base DRR quantum.
// Must comfortably exceed the largest single task cost divided by the
// smallest weight only for latency, not correctness — a lane's deficit
// accumulates across visits until its head fits.
int64_t TenantQuantum();

// --- (tenant, key) namespacing ----------------------------------------------

// Composite KeyStore key: the tenant id in bits 47..62 above the data
// key's 47 usable bits (worker keys are (tensor_id << 16) | part, far
// below 2^47; the sign bit stays clear). Tenant 0 composes to the key
// itself, so a legacy fleet's store keys — and therefore its engine
// thread routing `key % threads` — are bit-for-bit unchanged.
inline int64_t TenantKey(uint16_t tenant, int64_t key) {
  return key | (static_cast<int64_t>(tenant) << 47);
}

inline uint16_t TenantOfKey(int64_t tkey) {
  return static_cast<uint16_t>((tkey >> 47) & 0xffff);
}

inline int64_t BareKey(int64_t tkey) {
  return tkey & ((int64_t{1} << 47) - 1);
}

// --- weighted deficit-round-robin dispatch ----------------------------------

// Cost model: payload bytes plus a flat per-operation charge, so a
// tenant spamming byte-less pulls still pays its share of engine time.
constexpr int64_t kDrrOpCost = 1024;

inline int64_t DrrCost(int64_t payload_bytes) {
  return (payload_bytes > 0 ? payload_bytes : 0) + kDrrOpCost;
}

// Per-tenant FIFO lanes of item costs + the classic DRR picker. The
// server's EngineQueue mirrors it with a lane of EngineTasks per
// tenant: Enqueue/PickAndPop pairs run under the queue's mutex, so the
// two structures stay in lockstep by construction. Not internally
// locked (the caller owns the lock); the probe drives it single-
// threaded.
//
// Fairness: whenever two or more lanes stay backlogged, the bytes
// served per tenant converge to the ratio of their weights (each fresh
// visit grants weight x quantum deficit; serving costs the item's
// cost; an emptied lane forfeits its residue). FIFO within a lane, so
// per-(tenant, key) ordering is exactly the pre-tenant per-key
// ordering.
class WeightedDrr {
 public:
  using WeightFn = std::function<int(uint16_t)>;

  // weight_fn resolves a tenant's share at grant time (the server
  // passes an address-book lookup; the probe passes a local map).
  // Null = every tenant weight 1.
  explicit WeightedDrr(int64_t quantum = 0, WeightFn weight_fn = nullptr)
      : quantum_(quantum > 0 ? quantum : 64 * 1024),
        weight_fn_(std::move(weight_fn)) {}

  void Enqueue(uint16_t tenant, int64_t cost) {
    Lane& l = lanes_[tenant];
    if (l.costs.empty()) active_.push_back(tenant);
    l.costs.push_back(cost < 0 ? 0 : cost);
    ++total_;
  }

  bool Empty() const { return total_ == 0; }
  size_t Size() const { return total_; }
  size_t ActiveTenants() const { return active_.size(); }

  // The tenant whose head item is dispatched next; pops its cost.
  // Single active tenant = plain FIFO pop with no deficit bookkeeping:
  // a single-tenant fleet's dispatch order is byte-for-byte the
  // pre-tenant queue's.
  uint16_t PickAndPop(int64_t* cost_out = nullptr) {
    if (active_.size() == 1) {
      const uint16_t t = active_[0];
      Lane& l = lanes_[t];
      const int64_t c = l.costs.front();
      l.costs.pop_front();
      --total_;
      l.deficit = 0;
      if (l.costs.empty()) {
        active_.clear();
        rr_ = 0;
        grant_ = true;
      }
      if (cost_out) *cost_out = c;
      return t;
    }
    for (;;) {
      if (rr_ >= active_.size()) rr_ = 0;
      const uint16_t t = active_[rr_];
      Lane& l = lanes_[t];
      if (grant_) {
        l.deficit += quantum_ * WeightOf(t);
        grant_ = false;
      }
      const int64_t c = l.costs.front();
      if (c <= l.deficit) {
        l.deficit -= c;
        l.costs.pop_front();
        --total_;
        if (l.costs.empty()) {
          // Forfeit the residue (standard DRR: an idle lane must not
          // bank credit) and give the next lane a fresh grant.
          l.deficit = 0;
          active_.erase(active_.begin() + static_cast<long>(rr_));
          if (rr_ >= active_.size()) rr_ = 0;
          grant_ = true;
        }
        if (cost_out) *cost_out = c;
        return t;
      }
      // Head does not fit this visit: the deficit carries over and the
      // next lane gets its grant. Progress is guaranteed — each lap
      // adds weight x quantum >= quantum to this lane's deficit.
      rr_ = (rr_ + 1) % active_.size();
      grant_ = true;
    }
  }

 private:
  struct Lane {
    std::deque<int64_t> costs;
    int64_t deficit = 0;
  };

  int WeightOf(uint16_t t) const {
    if (!weight_fn_) return 1;
    const int w = weight_fn_(t);
    return w > 0 ? w : 1;
  }

  std::map<uint16_t, Lane> lanes_;
  std::vector<uint16_t> active_;  // round-robin order (arrival)
  size_t rr_ = 0;
  bool grant_ = true;  // the lane at rr_ is owed its visit grant
  int64_t quantum_;
  WeightFn weight_fn_;
  size_t total_ = 0;
};

// --- per-tenant accounting registry -----------------------------------------

// One tenant's cumulative accounting. Atomics: engine threads and van
// threads update concurrently; the snapshot reads relaxed.
struct TenantStat {
  std::atomic<int64_t> push_bytes{0};   // decoded-or-wire push payload in
  std::atomic<int64_t> reply_bytes{0};  // reply payload out
  std::atomic<int64_t> ops{0};          // data-plane operations seen
  std::atomic<int64_t> sum_us{0};       // engine decode+sum time
  std::atomic<int64_t> queue_depth{0};  // tasks waiting in engine lanes
  std::atomic<int64_t> dispatched{0};   // DRR cost served (bytes + op
                                        // charge — the fair-share meter)
  std::atomic<int64_t> last_serve_us{0};  // NowUs of the last dispatch
};

// Leaked singleton (the same lifetime rationale as Metrics): teardown
// paths still account, and snapshot pointers stay valid for the
// process lifetime.
class Tenancy {
 public:
  static Tenancy& Get();

  // Hot path (several calls per data frame, van + engine threads):
  // lock-free for tenants below 256 once registered — one relaxed
  // pointer load. Entries are never removed, so cached pointers stay
  // valid for the process lifetime (the Metrics registry contract).
  TenantStat* Of(uint16_t tenant) {
    if (tenant < kFastTenants) {
      TenantStat* s = fast_[tenant].load(std::memory_order_acquire);
      if (s) return s;
    }
    return OfSlow(tenant);
  }

  // Snapshot as a JSON object body: {"0":{...},"3":{...}} — the
  // /metrics tenants section and the /tenants endpoint both render it.
  // now_us timestamps the starvation age (now - last_serve_us while
  // queue_depth > 0; 0 otherwise).
  std::string SnapshotJson(int64_t now_us);

  // Tenants ever seen by this process (ids, ascending).
  std::vector<uint16_t> Known();

 private:
  static constexpr int kFastTenants = 256;

  TenantStat* OfSlow(uint16_t tenant);

  std::mutex mu_;  // registration + snapshot only
  std::map<uint16_t, std::unique_ptr<TenantStat>> stats_;
  std::atomic<TenantStat*> fast_[kFastTenants] = {};
};

}  // namespace bps
