#include "crc32c.h"

#include <cstring>

namespace bps {

#ifndef __SSE4_2__
namespace {

const uint32_t* Crc32cTable() {
  static uint32_t table[256];
  static bool init = [] {
    // Castagnoli polynomial, reflected: 0x82F63B78.
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) {
        c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
      }
      table[i] = c;
    }
    return true;
  }();
  (void)init;
  return table;
}

}  // namespace
#endif

uint32_t Crc32c(const void* data, size_t len, uint32_t seed) {
  uint32_t c = seed ^ 0xFFFFFFFFu;
  const unsigned char* p = static_cast<const unsigned char*>(data);
#ifdef __SSE4_2__
  // Hardware CRC32C (the SSE4.2 crc32 instruction implements exactly
  // this reflected-Castagnoli update): ~10+ GB/s vs ~0.4 GB/s for the
  // byte-at-a-time table, which is what keeps the per-frame wire
  // trailer inside BENCH_integrity_r19.json's <5% paced-goodput gate.
  uint64_t c64 = c;
  while (len >= 8) {
    uint64_t w;
    memcpy(&w, p, sizeof(w));
    c64 = __builtin_ia32_crc32di(c64, w);
    p += 8;
    len -= 8;
  }
  c = static_cast<uint32_t>(c64);
  while (len--) {
    c = __builtin_ia32_crc32qi(c, *p++);
  }
#else
  const uint32_t* table = Crc32cTable();
  for (size_t i = 0; i < len; ++i) {
    c = table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  }
#endif
  return c ^ 0xFFFFFFFFu;
}

}  // namespace bps
