#include "van.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <ifaddrs.h>
#include <linux/errqueue.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/uio.h>
#include <unistd.h>

#ifndef IP_RECVERR
#define IP_RECVERR 11
#endif

#include <cerrno>
#include <chrono>
#include <climits>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <new>
#include <vector>

#ifndef IOV_MAX
#define IOV_MAX 1024
#endif

#include "crc32c.h"
#include "events.h"
#include "logging.h"
#include "metrics.h"
#include "shm_ring.h"
#include "trace.h"

namespace bps {

// ps-lite parity: PS_VERBOSE=2 logs every message on the wire (1 is
// reserved for connection-level events, matching the reference's split).
static int VerboseLevel() {
  static const int v = [] {
    const char* e = getenv("PS_VERBOSE");
    return e ? atoi(e) : 0;
  }();
  return v;
}

static void LogMsg(const char* dir, int fd, const MsgHeader& h,
                   int64_t payload_len) {
  if (VerboseLevel() >= 2) {
    // Direct stderr: PS_VERBOSE must work standalone, independent of the
    // BYTEPS_LOG_LEVEL gate (ps-lite behaves the same way).
    fprintf(stderr, "[PS_VERBOSE] van %s fd=%d cmd=%d key=%lld ver=%d "
            "req=%d len=%lld\n", dir, fd, h.cmd,
            static_cast<long long>(h.key), h.version, h.req_id,
            static_cast<long long>(payload_len));
  }
}

// --- chaos injection (BYTEPS_CHAOS_*) ---------------------------------------
// Deterministic transient-fault injection on the send path, for the
// fault-tolerance test harness (docs/troubleshooting.md "failure
// model"). Applies ONLY to data-plane frames (IsDataPlaneCmd) by
// default: dropping control traffic would fake node deaths instead of
// exercising the in-band retry/reconnect machinery. BYTEPS_CHAOS_CTRL=1
// (ISSUE 15) opts control-plane frames in too — there "faking" a
// scheduler-link loss is the point, and the park/re-register fail-over
// machinery is the recovery path under test (config.py refuses the
// knob unless scheduler recovery is armed). Zero overhead when off: one
// branch on a cached flag per send. All faults are injected under the
// per-fd send lock from a seeded per-connection PRNG, so a fixed seed
// gives a reproducible fault pattern per connection.
struct ChaosCfg {
  bool on = false;
  bool ctrl = false;       // also inject into control-plane frames
  uint64_t seed = 0;
  double drop = 0.0;       // P(frame silently not written)
  double dup = 0.0;        // P(frame written twice back-to-back)
  double corrupt = 0.0;    // P(one on-wire payload byte flipped AFTER the
                           // wire CRC was stamped — ISSUE 19's bitflip
                           // window; config.py requires BYTEPS_WIRE_CRC
                           // so the flip is detected, not summed in)
  int64_t delay_us = 0;    // fixed extra latency per data frame
  int64_t reset_every = 0; // force a connection reset every N data frames
};

static const ChaosCfg& Chaos() {
  static const ChaosCfg cfg = [] {
    ChaosCfg c;
    auto envf = [](const char* n) {
      const char* v = getenv(n);
      return v && *v ? atof(v) : 0.0;
    };
    auto envll = [](const char* n) {
      const char* v = getenv(n);
      return v && *v ? atoll(v) : 0ll;
    };
    c.drop = envf("BYTEPS_CHAOS_DROP");
    c.dup = envf("BYTEPS_CHAOS_DUP");
    c.corrupt = envf("BYTEPS_CHAOS_CORRUPT");
    c.delay_us = envll("BYTEPS_CHAOS_DELAY_US");
    c.reset_every = envll("BYTEPS_CHAOS_RESET_EVERY");
    c.seed = static_cast<uint64_t>(envll("BYTEPS_CHAOS_SEED"));
    c.ctrl = envll("BYTEPS_CHAOS_CTRL") != 0;
    c.on = c.drop > 0 || c.dup > 0 || c.corrupt > 0 || c.delay_us > 0 ||
           c.reset_every > 0;
    return c;
  }();
  return cfg;
}

// splitmix64 step: uniform in [0,1). Good enough for fault dice; cheap
// and dependency-free.
static double ChaosRand(uint64_t* state) {
  uint64_t z = (*state += 0x9E3779B97F4A7C15ull);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  z ^= z >> 31;
  return static_cast<double>(z >> 11) * (1.0 / 9007199254740992.0);
}

// --- wire-CRC frame integrity (BYTEPS_WIRE_CRC, ISSUE 19) -------------------
// When armed, every data-plane frame carries a 4-byte little-endian
// CRC32C trailer (see FLAG_WIRE_CRC in common.h for the exact layout
// contract). Off by default and byte-for-byte the pre-CRC wire when off:
// no trailer, no flag, zero per-send cost beyond one cached-bool branch.
static bool WireCrcEnabled() {
  static const bool on = [] {
    const char* v = getenv("BYTEPS_WIRE_CRC");
    return v && *v && *v != '0';
  }();
  return on;
}

// Quarantine threshold: CRC failures tolerated per window per connection
// before the van force-closes it so the reconnect ladder re-dials a
// fresh socket (flaky-link quarantine). 0 = count/trace only.
static int64_t WireCrcQuarantine() {
  static const int64_t n = [] {
    const char* v = getenv("BYTEPS_WIRE_CRC_QUARANTINE");
    return v && *v ? atoll(v) : 0ll;
  }();
  return n;
}

static int64_t WireCrcWindowUs() {
  static const int64_t us = [] {
    const char* v = getenv("BYTEPS_WIRE_CRC_WINDOW_MS");
    return (v && *v ? atoll(v) : 10000ll) * 1000;
  }();
  return us;
}

static int64_t RxNowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Size data-connection socket buffers for high-bandwidth-delay links
// (DCN between TPU pods and PS racks): the kernel default (~200 KB) caps
// a 100 Gbit/s x 1 ms path at ~1.6 Gbit/s per connection. Tunable via
// BYTEPS_SOCKET_BUF bytes; 0 keeps the kernel default.
//
// BYTEPS_PACING_RATE (bytes/sec per connection, 0 = off) engages the
// kernel's TCP internal pacing (SO_MAX_PACING_RATE) on every data
// connection. Production use: keep a many-stripe van from bursting past
// a shared NIC's fair share. Benchmark use: emulate a DCN-shaped link on
// loopback with ZERO userspace relay cost — the scaling/overlap benches
// set it so fleet goodput is link-bound, not host-bound (verified: a
// 12.5 MB/s cap measures 12.6 MB/s on this kernel's loopback).
static void SizeSocketBuffers(int fd) {
  static const int kBuf = [] {
    const char* v = getenv("BYTEPS_SOCKET_BUF");
    return v ? atoi(v) : 8 << 20;
  }();
  if (kBuf > 0) {
    setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &kBuf, sizeof(kBuf));
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &kBuf, sizeof(kBuf));
  }
  static const uint64_t kPace = [] {
    const char* v = getenv("BYTEPS_PACING_RATE");
    return v ? static_cast<uint64_t>(atoll(v)) : 0ull;
  }();
  if (kPace > 0) {
#ifdef SO_MAX_PACING_RATE
    // The kernel reads an unsigned 32-bit (or 64-bit on newer kernels)
    // rate; pass 32-bit for widest compatibility, saturating at 4 GB/s
    // (far above any rate worth pacing to).
    uint32_t rate = kPace > 0xFFFFFFFFull
                        ? 0xFFFFFFFFu
                        : static_cast<uint32_t>(kPace);
    setsockopt(fd, SOL_SOCKET, SO_MAX_PACING_RATE, &rate, sizeof(rate));
#endif
  }
}

// --- MSG_ZEROCOPY send path (BYTEPS_VAN_ZEROCOPY=1) -------------------------
// The RDMA-parity experiment (SURVEY §2.4 rdma_van.h: kernel-bypass
// zero-copy sends). Linux MSG_ZEROCOPY pins the payload pages instead of
// copying them into kernel memory; completion arrives asynchronously on
// the socket error queue. This implementation is SYNCHRONOUS: Send()
// reaps the completion before returning, so caller buffer-lifetime
// semantics are identical to the copying path (the payload may be reused
// the moment Send returns). That costs one errqueue round trip per large
// send — acceptable for an A/B experiment, and the per-fd send lock
// already serialises same-connection sends. Measured verdict lives in
// BENCH_zerocopy_r05.json / docs/best-practice.md: on loopback the
// kernel COPIES anyway (SO_EE_CODE_ZEROCOPY_COPIED) and the notification
// machinery is pure overhead; the path where it pays is a real NIC at
// >=10 Gbit/s with >=1 MB partitions.
#ifndef SO_ZEROCOPY
#define SO_ZEROCOPY 60
#endif
#ifndef SO_EE_ORIGIN_ZEROCOPY
#define SO_EE_ORIGIN_ZEROCOPY 5
#endif
#ifndef MSG_ZEROCOPY
#define MSG_ZEROCOPY 0x4000000
#endif

static bool ZerocopyEnabled() {
  static const bool on = [] {
    const char* v = getenv("BYTEPS_VAN_ZEROCOPY");
    return v && *v && *v != '0';
  }();
  return on;
}

// Minimum payload for the zerocopy path: page pinning has fixed cost, so
// small sends always copy (the kernel's own guidance is ~10 KB; we gate
// far above it since only partition payloads matter here).
static constexpr int64_t kZerocopyMin = 1 << 20;

// Reap errqueue notifications until the zerocopy send numbered `seq` on
// this fd is acknowledged. Sends are serialised per fd, so completions
// arrive in order; `reaped` tracks the highest acked sequence. TCP
// completions arrive only once the peer ACKs the pinned pages, so on a
// slow (e.g. paced) link a completion can legitimately take arbitrarily
// long: there is NO fixed deadline here — the loop polls in short ticks
// and exits on van stop or connection death (shutdown/close surfaces as
// POLLERR/POLLHUP -> recvmsg error below).
static bool ReapZerocopy(int fd, uint32_t seq, uint32_t* reaped,
                         const std::atomic<bool>& stop) {
  while (static_cast<int32_t>(*reaped - seq) < 0) {
    pollfd pfd{fd, 0, 0};  // errqueue events surface as POLLERR
    int pr = ::poll(&pfd, 1, 500);
    if (pr < 0 && errno != EINTR) return false;
    if (pr <= 0) {
      if (stop.load()) return false;
      continue;  // completion still in flight (slow link) — keep waiting
    }
    char ctrl[128];
    msghdr mh{};
    mh.msg_control = ctrl;
    mh.msg_controllen = sizeof(ctrl);
    ssize_t r = ::recvmsg(fd, &mh, MSG_ERRQUEUE);
    if (r < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN) {
        // POLLERR with an EMPTY errqueue: the error is on the socket
        // itself (peer reset), not a zerocopy completion. Without these
        // checks the loop spins at 100% CPU — poll returns instantly on
        // the standing POLLERR, recvmsg keeps yielding EAGAIN — while
        // holding the per-fd send lock, so even Van::Stop can't break in.
        if (stop.load()) return false;
        int soerr = 0;
        socklen_t slen = sizeof(soerr);
        if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &slen) == 0 &&
            soerr != 0) {
          return false;  // dead connection; completion will never come
        }
        continue;
      }
      return false;
    }
    for (cmsghdr* c = CMSG_FIRSTHDR(&mh); c; c = CMSG_NXTHDR(&mh, c)) {
      // The van dials AF_INET only, so completions arrive as
      // SOL_IP/IP_RECVERR; an IPv6 van would need SOL_IPV6/IPV6_RECVERR
      // (25) handling here.
      if (c->cmsg_level == SOL_IP && c->cmsg_type == IP_RECVERR) {
        auto* ee = reinterpret_cast<sock_extended_err*>(CMSG_DATA(c));
        if (ee->ee_origin == SO_EE_ORIGIN_ZEROCOPY) {
          *reaped = ee->ee_data;  // range [ee_info, ee_data] completed
        }
      }
    }
  }
  return true;
}

// --- shared-memory data path (BYTEPS_VAN_TYPE=shm) --------------------------

struct Van::ShmConn {
  ShmHeader* hdr = nullptr;
  size_t map_len = 0;
  ShmDir* out = nullptr;  // direction this process produces into
  ShmDir* in = nullptr;   // direction this process consumes from
  char* out_ring = nullptr;
  char* in_ring = nullptr;
  uint32_t cap = 0;
  // Connector side keeps the segment name: normally the acceptor
  // shm_unlinks right after mapping, but if it dies (or its attach
  // fails) before that, the named segment would outlive both processes
  // — tmpfs memory leaked host-wide. A second unlink is ENOENT, so the
  // connector unlinking again at teardown is always safe.
  std::string name;
  // The fd number has TWO standing user threads on an shm connection —
  // the idle TCP recv thread (EOF watch) and the shm recv thread (which
  // passes fd to handlers that may reply on it) — plus, on the connector
  // side only, a third transient user: the OfferShm thread while its
  // hello send is in flight (set at registration). ::close only when the
  // LAST user is done — closing while any user still touches the fd
  // would let the kernel reuse the number for a fresh accept and route
  // stale writes to an unrelated peer (the fd-reuse race CloseConn's
  // contract exists to prevent).
  std::atomic<int> fd_users{2};

  ~ShmConn() {
    if (hdr) munmap(hdr, map_len);
    if (!name.empty()) shm_unlink(name.c_str());
  }
};

static bool ShmEnabled() {
  static const bool on = [] {
    const char* v = getenv("BYTEPS_VAN_TYPE");
    return v && strcmp(v, "shm") == 0;
  }();
  return on;
}

static uint32_t ShmRingBytes() {
  static const uint32_t n = [] {
    const char* v = getenv("BYTEPS_SHM_RING_BYTES");
    long b = v ? atol(v) : 4 << 20;  // one 4 MB partition per direction;
                                     // larger frames stream through
    if (b < 1 << 16) b = 1 << 16;
    if (b > 1 << 30) b = 1 << 30;
    // Round up to a power of two: the ring's free-running uint32 indices
    // are correct across counter wraparound only when the capacity
    // divides 2^32 (offset = index mod cap must stay continuous as the
    // index wraps).
    uint32_t cap = 1u << 16;
    while (cap < static_cast<uint32_t>(b)) cap <<= 1;
    return cap;
  }();
  return n;
}

// The shm path only makes sense when the peer shares this host's memory.
// Decided on the RESOLVED dial address: anything in 127/8 plus any
// address bound to a local interface — so a co-located worker/server
// pair that advertises its reachable address (DMLC_NODE_HOST=10.0.0.5
// in a mixed fleet) still gets the ring, and remote peers keep TCP with
// no configuration.
static bool IsLocalAddr(const sockaddr* sa) {
  static const std::vector<uint32_t> locals = [] {
    std::vector<uint32_t> v;
    ifaddrs* ifa = nullptr;
    if (getifaddrs(&ifa) == 0) {
      for (ifaddrs* p = ifa; p; p = p->ifa_next) {
        if (p->ifa_addr && p->ifa_addr->sa_family == AF_INET)
          v.push_back(reinterpret_cast<sockaddr_in*>(p->ifa_addr)
                          ->sin_addr.s_addr);
      }
      freeifaddrs(ifa);
    }
    return v;
  }();
  if (sa->sa_family != AF_INET) return false;
  uint32_t a = reinterpret_cast<const sockaddr_in*>(sa)->sin_addr.s_addr;
  if ((ntohl(a) >> 24) == 127) return true;  // whole loopback block
  for (uint32_t l : locals) {
    if (l == a) return true;
  }
  return false;
}

static bool SendAll(int fd, const void* buf, size_t len) {
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= n;
  }
  return true;
}

static bool RecvAll(int fd, void* buf, size_t len) {
  char* p = static_cast<char*>(buf);
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n == 0) return false;  // peer closed
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= n;
  }
  return true;
}

int Van::Listen(int port) {
  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  BPS_CHECK_GE(lfd, 0) << "socket() failed: " << strerror(errno);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  BPS_CHECK_EQ(::bind(lfd, reinterpret_cast<sockaddr*>(&addr),
                      sizeof(addr)), 0)
      << "bind(" << port << ") failed: " << strerror(errno);
  BPS_CHECK_EQ(::listen(lfd, 128), 0)
      << "listen failed: " << strerror(errno);
  socklen_t alen = sizeof(addr);
  getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen);
  listen_fd_.store(lfd);
  int bound = ntohs(addr.sin_port);
  {
    std::lock_guard<std::mutex> lk(mu_);
    threads_.emplace_back([this] { AcceptLoop(); });
  }
  BPS_LOG(DEBUG) << "van listening on port " << bound;
  return bound;
}

int Van::Connect(const std::string& host, int port, int max_attempts) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  std::string port_s = std::to_string(port);
  // Retry: the peer may not have bound its listener yet (startup races are
  // normal — the reference's ps-lite retries its scheduler dial the same way).
  for (int attempt = 0; attempt < max_attempts; ++attempt) {
    if (attempt > 0) usleep(100 * 1000);
    if (stop_.load()) break;
    if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0) {
      continue;
    }
    int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd >= 0 && ::connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
      bool same_host = ShmEnabled() && IsLocalAddr(res->ai_addr);
      freeaddrinfo(res);
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      SizeSocketBuffers(fd);
      // send_mu_ entry + TCP recv thread first: the shm recv loop may
      // dispatch a handler that replies on this fd immediately.
      auto smu = StartRecvThread(fd);
      if (same_host) OfferShm(fd, smu);
      return fd;
    }
    if (fd >= 0) ::close(fd);
    freeaddrinfo(res);
    res = nullptr;
  }
  BPS_LOG(WARNING) << "van connect to " << host << ":" << port
                   << " failed after " << max_attempts << " attempt(s)";
  return -1;
}

bool Van::Send(int fd, const MsgHeader& head, const void* payload,
               int64_t payload_len) {
  iovec one;
  one.iov_base = const_cast<void*>(payload);
  one.iov_len = static_cast<size_t>(payload_len > 0 ? payload_len : 0);
  return SendV(fd, head, &one, payload_len > 0 ? 1 : 0);
}

bool Van::SendV(int fd, const MsgHeader& head, const struct iovec* segs,
                int nsegs) {
  int64_t payload_len = 0;
  for (int i = 0; i < nsegs; ++i) {
    payload_len += static_cast<int64_t>(segs[i].iov_len);
  }
  MsgHeader h = head;
  h.payload_len = payload_len;
  uint64_t total = sizeof(MsgHeader) + static_cast<uint64_t>(payload_len);
  std::shared_ptr<std::mutex> smu;
  std::shared_ptr<ShmConn> shm;
  std::shared_ptr<ZcState> zcs;
  std::shared_ptr<TxState> tx;
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = send_mu_.find(fd);
    if (it == send_mu_.end()) return false;
    smu = it->second;
    auto sit = shm_conns_.find(fd);
    if (sit != shm_conns_.end()) shm = sit->second;
    auto zit = zc_.find(fd);
    if (zit != zc_.end()) zcs = zit->second;
    auto tit = tx_.find(fd);
    if (tit != tx_.end()) tx = tit->second;
  }
  std::lock_guard<std::mutex> lk(*smu);
  // Per-connection monotone frame sequence, stamped under the per-fd
  // send lock (so seq order == wire order). A chaos-duplicated frame
  // carries the SAME seq — it is the same frame delivered twice.
  if (tx) h.seq = ++tx->seq;
  // Wire-CRC trailer (data-plane frames only; control traffic keeps the
  // bare wire so CRC-on fleets interoperate frame-layout-wise with the
  // handshake path). Stamped AFTER the seq so the CRC covers the final
  // header exactly as it hits the wire. The trailer rides as one extra
  // iovec segment: payload bytes stay zero-copy.
  uint32_t crc_trailer = 0;
  std::vector<iovec> crc_segs;
  if (WireCrcEnabled() && IsDataPlaneCmd(h.cmd)) {
    h.flags |= FLAG_WIRE_CRC;
    h.payload_len = payload_len + 4;
    total += 4;
    uint32_t c = Crc32c(&h, sizeof(h));
    for (int i = 0; i < nsegs; ++i) {
      if (segs[i].iov_len) c = Crc32c(segs[i].iov_base, segs[i].iov_len, c);
    }
    crc_trailer = c;
    crc_segs.assign(segs, segs + nsegs);
    iovec t;
    t.iov_base = &crc_trailer;
    t.iov_len = sizeof(crc_trailer);
    crc_segs.push_back(t);
    segs = crc_segs.data();
    nsegs = static_cast<int>(crc_segs.size());
    payload_len += 4;
  }
  // Chaos injection point (data-plane frames, plus control-plane with
  // BYTEPS_CHAOS_CTRL=1; see Chaos()).
  int sends = 1;
  std::vector<char> corrupt_scratch;
  iovec corrupt_seg;
  if (tx && Chaos().on && (IsDataPlaneCmd(h.cmd) || Chaos().ctrl)) {
    const ChaosCfg& c = Chaos();
    ++tx->data_frames;
    if (c.reset_every > 0 && tx->data_frames % c.reset_every == 0) {
      // Forced connection reset: kill the socket mid-protocol. The
      // local recv thread wakes with EOF -> disconnect handler ->
      // reconnect-with-backoff; this send reports failure like any
      // send into a dead connection (the retry layer re-issues it).
      BPS_METRIC_COUNTER_ADD("bps_chaos_injected_total", 1);
      BPS_METRIC_COUNTER_ADD("bps_chaos_reset_total", 1);
      Trace::Get().Note("CHAOS_RESET", h.key, -1, h.req_id);
      Events::Get().Emit(EV_CHAOS, /*kind=*/0, h.key);
      if (VerboseLevel() >= 2) {
        fprintf(stderr, "[PS_VERBOSE] van CHAOS reset fd=%d\n", fd);
      }
      ::shutdown(fd, SHUT_RDWR);
      return false;
    }
    if (c.delay_us > 0) {
      BPS_METRIC_COUNTER_ADD("bps_chaos_injected_total", 1);
      BPS_METRIC_COUNTER_ADD("bps_chaos_delay_total", 1);
      usleep(static_cast<useconds_t>(c.delay_us));
    }
    if (c.drop > 0 && ChaosRand(&tx->rng) < c.drop) {
      // Silent loss: report success, write nothing. Only the retry
      // layer's timeout can recover the frame — exactly the contract
      // under test.
      BPS_METRIC_COUNTER_ADD("bps_chaos_injected_total", 1);
      BPS_METRIC_COUNTER_ADD("bps_chaos_drop_total", 1);
      Trace::Get().Note("CHAOS_DROP", h.key, -1, h.req_id);
      Events::Get().Emit(EV_CHAOS, /*kind=*/1, h.key);
      if (VerboseLevel() >= 2) {
        fprintf(stderr, "[PS_VERBOSE] van CHAOS drop fd=%d cmd=%d "
                "seq=%lld\n", fd, h.cmd, (long long)h.seq);
      }
      return true;
    }
    if (c.dup > 0 && ChaosRand(&tx->rng) < c.dup) {
      BPS_METRIC_COUNTER_ADD("bps_chaos_injected_total", 1);
      BPS_METRIC_COUNTER_ADD("bps_chaos_dup_total", 1);
      Trace::Get().Note("CHAOS_DUP", h.key, -1, h.req_id);
      Events::Get().Emit(EV_CHAOS, /*kind=*/2, h.key);
      sends = 2;  // duplicate delivery, back-to-back, same seq
    }
    if (c.corrupt > 0 && payload_len > 0 &&
        ChaosRand(&tx->rng) < c.corrupt) {
      // On-wire bit corruption: flip one payload byte AFTER the CRC was
      // stamped, so the receiver's verify catches it and the retry layer
      // must resend. The flip happens on a flattened scratch copy — the
      // caller's iovec buffers are zero-copy views of live engine/fusion
      // state and the eventual RETRY must ship the uncorrupted bytes.
      BPS_METRIC_COUNTER_ADD("bps_chaos_injected_total", 1);
      BPS_METRIC_COUNTER_ADD("bps_chaos_corrupt_total", 1);
      Trace::Get().Note("CHAOS_CORRUPT", h.key, -1, h.req_id);
      Events::Get().Emit(EV_CHAOS, /*kind=*/3, h.key);
      corrupt_scratch.resize(static_cast<size_t>(payload_len));
      size_t off = 0;
      for (int i = 0; i < nsegs; ++i) {
        if (segs[i].iov_len) {
          memcpy(corrupt_scratch.data() + off, segs[i].iov_base,
                 segs[i].iov_len);
          off += segs[i].iov_len;
        }
      }
      size_t idx = static_cast<size_t>(
          ChaosRand(&tx->rng) * static_cast<double>(payload_len));
      if (idx >= static_cast<size_t>(payload_len)) {
        idx = static_cast<size_t>(payload_len) - 1;
      }
      corrupt_scratch[idx] ^= 0x20;
      if (VerboseLevel() >= 2) {
        fprintf(stderr, "[PS_VERBOSE] van CHAOS corrupt fd=%d cmd=%d "
                "seq=%lld byte=%zu\n", fd, h.cmd, (long long)h.seq, idx);
      }
      corrupt_seg.iov_base = corrupt_scratch.data();
      corrupt_seg.iov_len = corrupt_scratch.size();
      segs = &corrupt_seg;
      nsegs = 1;
    }
  }
  // Wire instant (main ring only; one per logical send, not per chaos
  // duplicate — the receiver's wire_recv shows the double delivery).
  if (Trace::Get().MainOn()) {
    Trace::Get().Instant("wire_send", h.key, -1, h.req_id, h.cmd);
  }
  bool ok = true;
  for (int send_i = 0; send_i < sends && ok; ++send_i) {
    ok = WriteFrame(fd, h, segs, nsegs, total, payload_len, shm.get(),
                    zcs.get());
  }
  return ok;
}

// One framed write on the already-locked connection: transport selection
// (shm ring / zerocopy / gather writev) exactly as before the chaos
// layer; factored out so a chaos-duplicated frame can be written twice.
bool Van::WriteFrame(int fd, MsgHeader& h, const struct iovec* segs,
                     int nsegs, uint64_t total, int64_t payload_len,
                     ShmConn* shm, ZcState* zcs) {
  // Under the per-fd send lock so the PS_VERBOSE trace order matches the
  // actual wire order (the whole point of a message trace).
  LogMsg("send", fd, h, payload_len);
  BPS_METRIC_COUNTER_ADD("bps_van_sent_frames_total", 1);
  if (shm) {
    // Ring data path: same frame layout, memcpy instead of syscalls. The
    // per-fd send lock makes this the ring's single producer.
    bytes_sent_.fetch_add(
        static_cast<int64_t>(sizeof(total) + total),
        std::memory_order_relaxed);
    if (!ShmStreamWrite(shm->out, shm->out_ring, shm->cap, &total,
                        sizeof(total)) ||
        !ShmStreamWrite(shm->out, shm->out_ring, shm->cap, &h, sizeof(h)))
      return false;
    for (int i = 0; i < nsegs; ++i) {
      if (segs[i].iov_len == 0) continue;
      if (!ShmStreamWrite(shm->out, shm->out_ring, shm->cap,
                          segs[i].iov_base, segs[i].iov_len))
        return false;
    }
    return true;
  }
  if (zcs && nsegs == 1 && payload_len >= kZerocopyMin) {
    const void* payload = segs[0].iov_base;
    // Zerocopy experiment path: copy the tiny framing, pin the payload
    // pages. Completion is reaped before returning (synchronous — see
    // the block comment above ZerocopyEnabled).
    bytes_sent_.fetch_add(
        static_cast<int64_t>(sizeof(total) + sizeof(h) + payload_len),
        std::memory_order_relaxed);
    if (!SendAll(fd, &total, sizeof(total)) ||
        !SendAll(fd, &h, sizeof(h)))
      return false;
    const char* p = static_cast<const char*>(payload);
    size_t left = static_cast<size_t>(payload_len);
    while (left > 0) {
      ssize_t n = ::send(fd, p, left, MSG_NOSIGNAL | MSG_ZEROCOPY);
      if (n < 0) {
        if (errno == EINTR) continue;
        if (errno == ENOBUFS) {
          // Usually optmem_max exhausted by unreaped notifications:
          // drain, retry. If everything is already reaped (ENOBUFS from
          // general memory pressure instead), the reap is a no-op — back
          // off briefly so the retry is not a busy-spin holding the
          // per-fd send lock.
          bool nothing_pending =
              zcs->next == 0 ||
              static_cast<int32_t>(zcs->reaped - (zcs->next - 1)) >= 0;
          if (nothing_pending) {
            // Sustained ENOBUFS (general memory pressure) must not stall
            // Van::Stop: bail out of the backoff loop once stop is
            // requested instead of retrying forever under the per-fd
            // send lock.
            if (stop_.load()) return false;
            usleep(1000);
          } else if (!ReapZerocopy(fd, zcs->next - 1, &zcs->reaped,
                                   stop_)) {
            return false;
          }
          continue;
        }
        return false;
      }
      ++zcs->next;  // each MSG_ZEROCOPY send gets one completion number
      p += n;
      left -= static_cast<size_t>(n);
    }
    // left started >= kZerocopyMin, so at least one send incremented next.
    return ReapZerocopy(fd, zcs->next - 1, &zcs->reaped, stop_);
  }
  // Gather write: framing words + every payload segment in one writev.
  // Segments beyond IOV_MAX (or past a partial write) finish through the
  // SendAll fallback loop below.
  std::vector<iovec> iov(2 + static_cast<size_t>(nsegs));
  iov[0].iov_base = &total;
  iov[0].iov_len = sizeof(total);
  iov[1].iov_base = &h;
  iov[1].iov_len = sizeof(h);
  int iovcnt = 2;
  for (int i = 0; i < nsegs; ++i) {
    if (segs[i].iov_len == 0) continue;
    iov[iovcnt++] = segs[i];
  }
  size_t want = sizeof(total) + sizeof(h) + static_cast<size_t>(payload_len);
  bytes_sent_.fetch_add(static_cast<int64_t>(want),
                        std::memory_order_relaxed);
  int first_cnt = iovcnt > IOV_MAX ? IOV_MAX : iovcnt;
  ssize_t n = ::writev(fd, iov.data(), first_cnt);
  if (n == static_cast<ssize_t>(want)) return true;
  if (n < 0) return false;
  // Partial write (or clipped iov list): finish from where writev stopped.
  size_t done = static_cast<size_t>(n);
  for (int i = 0; i < iovcnt; ++i) {
    if (done >= iov[i].iov_len) {
      done -= iov[i].iov_len;
      continue;
    }
    if (!SendAll(fd, static_cast<const char*>(iov[i].iov_base) + done,
                 iov[i].iov_len - done))
      return false;
    done = 0;
  }
  return true;
}

std::shared_ptr<std::mutex> Van::StartRecvThread(int fd) {
  auto smu = std::make_shared<std::mutex>();
  std::shared_ptr<ZcState> zcs;
  if (ZerocopyEnabled()) {
    int one = 1;
    // Only arm the zerocopy path if the kernel accepts SO_ZEROCOPY —
    // otherwise MSG_ZEROCOPY sends would fail with EINVAL.
    if (setsockopt(fd, SOL_SOCKET, SO_ZEROCOPY, &one, sizeof(one)) == 0) {
      zcs = std::make_shared<ZcState>();
    } else {
      BPS_LOG(WARNING) << "BYTEPS_VAN_ZEROCOPY=1 but SO_ZEROCOPY "
                          "unsupported; staying on copying sends";
    }
  }
  auto tx = std::make_shared<TxState>();
  {
    // Seed the chaos PRNG per connection: deterministic for a fixed
    // BYTEPS_CHAOS_SEED, decorrelated across connections.
    static std::atomic<uint64_t> conn_idx{0};
    tx->rng = (Chaos().seed + 1) * 0x9E3779B97F4A7C15ull +
              conn_idx.fetch_add(1);
  }
  std::lock_guard<std::mutex> lk(mu_);
  send_mu_[fd] = smu;
  tx_[fd] = tx;
  if (zcs) zc_[fd] = zcs;
  threads_.emplace_back([this, fd] { RecvLoop(fd); });
  return smu;
}

void Van::AcceptLoop() {
  while (!stop_.load()) {
    sockaddr_in peer{};
    socklen_t plen = sizeof(peer);
    int lfd = listen_fd_.load();
    if (lfd < 0) return;
    int fd = ::accept(lfd, reinterpret_cast<sockaddr*>(&peer), &plen);
    if (fd < 0) {
      if (stop_.load()) break;
      if (errno == EINTR) continue;
      break;  // listener shut down
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    SizeSocketBuffers(fd);
    StartRecvThread(fd);
  }
  // The accept thread owns the listening fd's close (Stop only shuts it
  // down, so no other thread can race this close with a blocked accept).
  int lfd = listen_fd_.exchange(-1);
  if (lfd >= 0) ::close(lfd);
}

// Parse one framed message through any blocking byte-stream reader
// (RecvAll over a socket, ShmStreamRead over a ring). Returns false on
// EOF / connection close.
template <typename ReadFn>
static bool ReadFrame(ReadFn&& rd, Message* msg) {
  uint64_t total = 0;
  if (!rd(&total, sizeof(total))) return false;
  BPS_CHECK_GE(total, sizeof(MsgHeader)) << "malformed frame";
  if (!rd(&msg->head, sizeof(MsgHeader))) return false;
  uint64_t plen = total - sizeof(MsgHeader);
  BPS_CHECK_EQ(plen, static_cast<uint64_t>(msg->head.payload_len))
      << "frame length mismatch";
  if (plen > 0) {
    msg->payload.resize_uninit(plen);  // reader overwrites every byte
    if (!rd(msg->payload.data(), plen)) return false;
  }
  return true;
}

void Van::DispatchFrame(Message&& msg, int fd, RxState* rx) {
  int64_t plen = msg.head.payload_len;
  bytes_recv_.fetch_add(
      static_cast<int64_t>(sizeof(uint64_t) + sizeof(MsgHeader) + plen),
      std::memory_order_relaxed);
  BPS_METRIC_COUNTER_ADD("bps_van_recv_frames_total", 1);
  // Wire-CRC verification (FLAG_WIRE_CRC, ISSUE 19) — BEFORE the seq
  // cursor and BEFORE any upper layer sees the frame, so a corrupted
  // frame cannot advance dedup/engine/accumulator state. The CRC covers
  // the header verbatim as received (the sender stamped it over the
  // final header, flag set, payload_len including the trailer) chained
  // over the payload minus the 4-byte trailer. A mismatch is dropped
  // exactly like a chaos drop: the retry layer's timeout resends.
  if (msg.head.flags & FLAG_WIRE_CRC) {
    uint32_t want = 0;
    bool ok = plen >= 4;
    if (ok) {
      memcpy(&want, msg.payload.data() + plen - 4, sizeof(want));
      uint32_t got = Crc32c(&msg.head, sizeof(MsgHeader));
      if (plen > 4) {
        got = Crc32c(msg.payload.data(), static_cast<size_t>(plen) - 4,
                     got);
      }
      ok = got == want;
    }
    if (!ok) {
      BPS_METRIC_COUNTER_ADD("bps_crc_fail_total", 1);
      Trace::Get().Note("CRC_FAIL", msg.head.key, msg.head.sender,
                        msg.head.req_id);
      if (VerboseLevel() >= 1) {
        fprintf(stderr, "[PS_VERBOSE] van CRC FAIL fd=%d cmd=%d "
                "sender=%d seq=%lld len=%lld (frame dropped)\n",
                fd, msg.head.cmd, msg.head.sender, (long long)msg.head.seq,
                (long long)plen);
      }
      // Flaky-link quarantine: too many failures inside one window and
      // the connection itself is suspect — force-close it so the
      // reconnect ladder re-dials a fresh socket (postoffice is told
      // first, via corrupt_cb_, so it can attribute the link to a peer
      // and escalate persistent corruption to a named fail-stop).
      if (rx && WireCrcQuarantine() > 0) {
        int64_t now = RxNowUs();
        if (rx->win_start_us == 0 ||
            now - rx->win_start_us > WireCrcWindowUs()) {
          rx->win_start_us = now;
          rx->win_fails = 0;
        }
        if (++rx->win_fails >= WireCrcQuarantine()) {
          rx->win_fails = 0;
          rx->win_start_us = 0;
          BPS_METRIC_COUNTER_ADD("bps_crc_quarantine_total", 1);
          Trace::Get().Note("CRC_QUARANTINE", msg.head.key,
                            msg.head.sender, msg.head.req_id);
          if (corrupt_cb_ && !stop_.load()) corrupt_cb_(fd);
          ::shutdown(fd, SHUT_RDWR);
        }
      }
      return;  // dropped: no cursor advance, no dispatch
    }
    // Verified: strip the trailer and the flag so upper layers (and the
    // dedup/fusion parsers) see exactly the pre-CRC frame.
    plen -= 4;
    msg.head.payload_len = plen;
    msg.head.flags &= ~FLAG_WIRE_CRC;
    msg.payload.resize_uninit(static_cast<size_t>(plen));
  }
  // Frame-loss observability from the per-connection seq: a jump means
  // frames vanished between sender stamping and this reader (chaos
  // drop); a repeat is a duplicate delivery. Cursor is the single recv
  // thread's local, so no locking.
  if (msg.head.seq > 0 && rx) {
    if (msg.head.seq == rx->last_seq) {
      BPS_METRIC_COUNTER_ADD("bps_seq_dups_total", 1);
    } else if (rx->last_seq > 0 && msg.head.seq > rx->last_seq + 1) {
      BPS_METRIC_COUNTER_ADD("bps_seq_gaps_total",
                             msg.head.seq - rx->last_seq - 1);
    }
    if (msg.head.seq > rx->last_seq) rx->last_seq = msg.head.seq;
  }
  LogMsg("recv", fd, msg.head, plen);
  if (Trace::Get().MainOn()) {
    Trace::Get().Instant("wire_recv", msg.head.key, msg.head.sender,
                         msg.head.req_id, msg.head.cmd);
  }
  if (msg.head.cmd == CMD_SHM_HELLO) {
    // Van-internal: the peer created a shm segment for this connection.
    // From here on the socket carries no frames; it stays open purely
    // as the peer-death signal (EOF in RecvLoop).
    AttachShm(fd, msg);
    return;
  }
  handler_(std::move(msg), fd);
}

void Van::RecvLoop(int fd) {
  RxState rx;
  while (!stop_.load()) {
    Message msg;
    if (!ReadFrame([fd](void* b, size_t n) { return RecvAll(fd, b, n); },
                   &msg))
      break;
    DispatchFrame(std::move(msg), fd, &rx);
  }
  // A live-van exit means the PEER went away (EOF / reset), not Stop():
  // let the upper layer fail that peer's outstanding requests now.
  if (!stop_.load() && disconnect_cb_) disconnect_cb_(fd);
  CloseConn(fd);
}

// Connector side: create the segment, announce it over the socket, start
// consuming the inbound ring. Any failure leaves the connection on plain
// TCP (no hello sent, peer never knows).
bool Van::OfferShm(int fd, const std::shared_ptr<std::mutex>& smu) {
  static std::atomic<uint32_t> seq{0};
  char name[64];
  snprintf(name, sizeof(name), "/bpsvan_%d_%d_%u", getpid(), fd,
           seq.fetch_add(1));
  uint32_t cap = ShmRingBytes();
  size_t map_len = sizeof(ShmHeader) + 2 * static_cast<size_t>(cap);
  int sfd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (sfd < 0) {
    BPS_LOG(WARNING) << "shm_open(" << name << ") failed: "
                     << strerror(errno) << "; staying on TCP";
    return false;
  }
  // posix_fallocate, not ftruncate: tmpfs enforces its size limit at
  // page-fault time, so a merely-truncated segment on a small /dev/shm
  // (Docker default: 64 MB) would SIGBUS mid-memcpy after the hello had
  // already committed the peer to the ring. Reserving the pages up
  // front turns overcommit into a clean stay-on-TCP fallback here.
  int ferr = posix_fallocate(sfd, 0, static_cast<off_t>(map_len));
  if (ferr != 0) {
    ::close(sfd);
    shm_unlink(name);
    BPS_LOG(WARNING) << "shm reserve (" << map_len << " B) failed: "
                     << strerror(ferr) << "; staying on TCP";
    return false;
  }
  void* mm = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED,
                  sfd, 0);
  ::close(sfd);
  if (mm == MAP_FAILED) {
    shm_unlink(name);
    BPS_LOG(WARNING) << "mmap shm failed: " << strerror(errno)
                     << "; staying on TCP";
    return false;
  }
  auto conn = std::make_shared<ShmConn>();
  conn->name = name;
  conn->hdr = new (mm) ShmHeader{};
  conn->hdr->magic = kShmMagic;
  conn->hdr->ring_bytes = cap;
  conn->map_len = map_len;
  conn->cap = cap;
  conn->out = &conn->hdr->dir[0];  // connector produces dir 0
  conn->in = &conn->hdr->dir[1];
  conn->out_ring = ShmRingData(conn->hdr, 0);
  conn->in_ring = ShmRingData(conn->hdr, 1);

  // Register BEFORE sending the hello, under an identity check on the
  // send mutex: if the peer died during shm setup above, the TCP recv
  // thread's CloseConn already erased this fd and closed it — the number
  // may already belong to a NEW connection (whose StartRecvThread
  // re-inserted the same key with a FRESH mutex, which is why key
  // presence alone is not enough). Writing the hello, or registering the
  // ring, against a reused fd would corrupt an unrelated connection;
  // bail and let the conn dtor unmap + unlink instead. Once registered,
  // this thread holds a third fd_users reference, so the fd cannot be
  // closed (hence not reused) while the hello send below is in flight.
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = send_mu_.find(fd);
    if (stop_.load() || it == send_mu_.end() || it->second != smu)
      return false;  // conn dtor unmaps + unlinks
    conn->fd_users.store(3);  // TCP recv + shm recv + this hello send
    shm_conns_[fd] = conn;
    threads_.emplace_back([this, fd, conn] { ShmRecvLoop(fd, conn); });
  }
  MsgHeader h{};
  h.cmd = CMD_SHM_HELLO;
  int64_t plen = static_cast<int64_t>(strlen(name));
  h.payload_len = plen;
  h.arg0 = cap;
  uint64_t total = sizeof(MsgHeader) + static_cast<uint64_t>(plen);
  // Raw socket send: the ONLY frame this socket will ever carry —
  // Connect has not returned the fd to callers yet, and any concurrent
  // internal Send already routes through the just-registered ring, so
  // the TCP byte stream stays exclusively ours. A dead peer surfaces as
  // a send failure; the TCP recv thread's EOF handling then tears the
  // ring down through the normal path.
  bool sent = SendAll(fd, &total, sizeof(total)) &&
              SendAll(fd, &h, sizeof(h)) &&
              SendAll(fd, name, static_cast<size_t>(plen));
  if (conn->fd_users.fetch_sub(1) == 1) ::close(fd);
  if (!sent) {
    BPS_LOG(WARNING) << "shm hello send failed on fd=" << fd
                     << "; peer-loss teardown will reap the ring";
    return false;
  }
  BPS_LOG(DEBUG) << "van fd=" << fd << " data path -> shm ring " << name
                 << " (" << cap << " B/dir)";
  return true;
}

// Acceptor side, invoked from the connection's TCP recv thread.
void Van::AttachShm(int fd, const Message& hello) {
  std::string name(hello.payload.data(), hello.payload.size());
  uint32_t cap = static_cast<uint32_t>(hello.head.arg0);
  // Wrap-correctness invariant (power of two) plus the same 1<<30 upper
  // clamp the connector's ShmRingBytes enforces — a hello above it cannot
  // have come from a healthy peer.
  if (cap == 0 || (cap & (cap - 1)) != 0 || cap > (1u << 30)) {
    BPS_LOG(WARNING) << "shm hello with invalid ring capacity " << cap
                     << "; dropping connection";
    ::shutdown(fd, SHUT_RDWR);
    return;
  }
  size_t map_len = sizeof(ShmHeader) + 2 * static_cast<size_t>(cap);
  int sfd = shm_open(name.c_str(), O_RDWR, 0600);
  if (sfd < 0) {
    // Peer committed to the ring; without it this connection is dead.
    // Close the socket — the peer's EOF handling fails it fast.
    BPS_LOG(WARNING) << "shm_open(" << name << ") failed: "
                   << strerror(errno) << "; dropping connection";
    ::shutdown(fd, SHUT_RDWR);
    return;
  }
  // The connector fallocated map_len before sending the hello, so a
  // smaller object means truncation/mismatch — mapping it would SIGBUS on
  // first access past EOF instead of failing cleanly here.
  struct stat st {};
  if (fstat(sfd, &st) != 0 ||
      static_cast<size_t>(st.st_size) < map_len) {
    BPS_LOG(WARNING) << "shm segment " << name << " size " << st.st_size
                     << " < expected " << map_len
                     << "; dropping connection";
    ::close(sfd);
    ::shutdown(fd, SHUT_RDWR);
    return;
  }
  void* mm = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED,
                  sfd, 0);
  ::close(sfd);
  shm_unlink(name.c_str());  // both sides mapped or dying; name done
  if (mm == MAP_FAILED ||
      reinterpret_cast<ShmHeader*>(mm)->magic != kShmMagic ||
      reinterpret_cast<ShmHeader*>(mm)->ring_bytes != cap) {
    BPS_LOG(WARNING) << "shm map/validate failed for " << name
                   << "; dropping connection";
    if (mm != MAP_FAILED) munmap(mm, map_len);
    ::shutdown(fd, SHUT_RDWR);
    return;
  }
  auto conn = std::make_shared<ShmConn>();
  conn->hdr = reinterpret_cast<ShmHeader*>(mm);
  conn->map_len = map_len;
  conn->cap = cap;
  conn->out = &conn->hdr->dir[1];  // acceptor produces dir 1
  conn->in = &conn->hdr->dir[0];
  conn->out_ring = ShmRingData(conn->hdr, 1);
  conn->in_ring = ShmRingData(conn->hdr, 0);
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (stop_.load()) return;
    shm_conns_[fd] = conn;
    threads_.emplace_back([this, fd, conn] { ShmRecvLoop(fd, conn); });
  }
  BPS_LOG(DEBUG) << "van fd=" << fd << " accepted shm ring " << name;
}

// Frame consumer for one shm connection. Mirrors RecvLoop; the TCP recv
// thread (still blocked on the idle socket) owns disconnect
// notification, and the fd itself closes when its last user thread
// (this loop or the TCP recv thread via CloseConn) releases it.
void Van::ShmRecvLoop(int fd, std::shared_ptr<ShmConn> conn) {
  RxState rx;
  while (!stop_.load()) {
    Message msg;
    if (!ReadFrame(
            [&conn](void* b, size_t n) {
              return ShmStreamRead(conn->in, conn->in_ring, conn->cap, b,
                                   n);
            },
            &msg))
      break;
    DispatchFrame(std::move(msg), fd, &rx);
  }
  if (conn->fd_users.fetch_sub(1) == 1) ::close(fd);
}

// Connection fds are CLOSED only by their owning recv thread (via
// CloseConn at RecvLoop exit) — for shm connections, by whichever of
// the TCP and shm recv threads finishes LAST. Other threads may only
// shutdown() them. This avoids the close-vs-blocked-recv (and
// dispatch-after-close) fd-reuse races.
void Van::CloseConn(int fd) {
  std::shared_ptr<ShmConn> shm;
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = shm_conns_.find(fd);
    if (it != shm_conns_.end()) {
      shm = it->second;
      shm_conns_.erase(it);
    }
    zc_.erase(fd);
    tx_.erase(fd);
    if (send_mu_.erase(fd) && !shm) ::close(fd);
  }
  // Outside mu_: wakes the shm recv thread (and any blocked producer in
  // the peer process); the mapping lives until the last shared_ptr drops.
  if (shm) {
    ShmCloseBoth(shm->hdr);
    if (shm->fd_users.fetch_sub(1) == 1) ::close(fd);
  }
}

void Van::Stop() {
  bool expected = false;
  if (!stop_.compare_exchange_strong(expected, true)) return;
  int lfd = listen_fd_.load();
  if (lfd >= 0) ::shutdown(lfd, SHUT_RDWR);  // wakes accept; thread closes
  std::vector<std::thread> ts;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : send_mu_) ::shutdown(kv.first, SHUT_RDWR);
    for (auto& kv : shm_conns_) ShmCloseBoth(kv.second->hdr);
    ts.swap(threads_);
  }
  for (auto& t : ts) {
    if (t.get_id() == std::this_thread::get_id()) t.detach();
    else if (t.joinable()) t.join();
  }
}

}  // namespace bps
