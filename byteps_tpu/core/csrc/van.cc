#include "van.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "logging.h"

namespace bps {

// ps-lite parity: PS_VERBOSE=2 logs every message on the wire (1 is
// reserved for connection-level events, matching the reference's split).
static int VerboseLevel() {
  static const int v = [] {
    const char* e = getenv("PS_VERBOSE");
    return e ? atoi(e) : 0;
  }();
  return v;
}

static void LogMsg(const char* dir, int fd, const MsgHeader& h,
                   int64_t payload_len) {
  if (VerboseLevel() >= 2) {
    // Direct stderr: PS_VERBOSE must work standalone, independent of the
    // BYTEPS_LOG_LEVEL gate (ps-lite behaves the same way).
    fprintf(stderr, "[PS_VERBOSE] van %s fd=%d cmd=%d key=%lld ver=%d "
            "req=%d len=%lld\n", dir, fd, h.cmd,
            static_cast<long long>(h.key), h.version, h.req_id,
            static_cast<long long>(payload_len));
  }
}

// Size data-connection socket buffers for high-bandwidth-delay links
// (DCN between TPU pods and PS racks): the kernel default (~200 KB) caps
// a 100 Gbit/s x 1 ms path at ~1.6 Gbit/s per connection. Tunable via
// BYTEPS_SOCKET_BUF bytes; 0 keeps the kernel default.
static void SizeSocketBuffers(int fd) {
  static const int kBuf = [] {
    const char* v = getenv("BYTEPS_SOCKET_BUF");
    return v ? atoi(v) : 8 << 20;
  }();
  if (kBuf > 0) {
    setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &kBuf, sizeof(kBuf));
    setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &kBuf, sizeof(kBuf));
  }
}

static bool SendAll(int fd, const void* buf, size_t len) {
  const char* p = static_cast<const char*>(buf);
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= n;
  }
  return true;
}

static bool RecvAll(int fd, void* buf, size_t len) {
  char* p = static_cast<char*>(buf);
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n == 0) return false;  // peer closed
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += n;
    len -= n;
  }
  return true;
}

int Van::Listen(int port) {
  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  BPS_CHECK_GE(lfd, 0) << "socket() failed: " << strerror(errno);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  BPS_CHECK_EQ(::bind(lfd, reinterpret_cast<sockaddr*>(&addr),
                      sizeof(addr)), 0)
      << "bind(" << port << ") failed: " << strerror(errno);
  BPS_CHECK_EQ(::listen(lfd, 128), 0)
      << "listen failed: " << strerror(errno);
  socklen_t alen = sizeof(addr);
  getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen);
  listen_fd_.store(lfd);
  int bound = ntohs(addr.sin_port);
  {
    std::lock_guard<std::mutex> lk(mu_);
    threads_.emplace_back([this] { AcceptLoop(); });
  }
  BPS_LOG(DEBUG) << "van listening on port " << bound;
  return bound;
}

int Van::Connect(const std::string& host, int port) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  std::string port_s = std::to_string(port);
  // Retry: the peer may not have bound its listener yet (startup races are
  // normal — the reference's ps-lite retries its scheduler dial the same way).
  for (int attempt = 0; attempt < 300; ++attempt) {
    if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0) {
      usleep(100 * 1000);
      continue;
    }
    int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd >= 0 && ::connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
      freeaddrinfo(res);
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      SizeSocketBuffers(fd);
      StartRecvThread(fd);
      return fd;
    }
    if (fd >= 0) ::close(fd);
    freeaddrinfo(res);
    res = nullptr;
    usleep(100 * 1000);
  }
  BPS_LOG(WARNING) << "van connect to " << host << ":" << port
                   << " failed after retries";
  return -1;
}

bool Van::Send(int fd, const MsgHeader& head, const void* payload,
               int64_t payload_len) {
  MsgHeader h = head;
  h.payload_len = payload_len;
  uint64_t total = sizeof(MsgHeader) + static_cast<uint64_t>(payload_len);
  std::shared_ptr<std::mutex> smu;
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = send_mu_.find(fd);
    if (it == send_mu_.end()) return false;
    smu = it->second;
  }
  std::lock_guard<std::mutex> lk(*smu);
  // Under the per-fd send lock so the PS_VERBOSE trace order matches the
  // actual wire order (the whole point of a message trace).
  LogMsg("send", fd, h, payload_len);
  iovec iov[3];
  iov[0].iov_base = &total;
  iov[0].iov_len = sizeof(total);
  iov[1].iov_base = &h;
  iov[1].iov_len = sizeof(h);
  iov[2].iov_base = const_cast<void*>(payload);
  iov[2].iov_len = static_cast<size_t>(payload_len);
  int iovcnt = payload_len > 0 ? 3 : 2;
  // writev for the common case; fall back to SendAll on partial writes.
  size_t want = sizeof(total) + sizeof(h) + (payload_len > 0 ? payload_len : 0);
  bytes_sent_.fetch_add(static_cast<int64_t>(want),
                        std::memory_order_relaxed);
  ssize_t n = ::writev(fd, iov, iovcnt);
  if (n == static_cast<ssize_t>(want)) return true;
  if (n < 0) return false;
  // Partial write: finish byte-by-byte from where writev stopped.
  size_t done = static_cast<size_t>(n);
  const char* bufs[3] = {reinterpret_cast<const char*>(&total),
                         reinterpret_cast<const char*>(&h),
                         static_cast<const char*>(payload)};
  size_t lens[3] = {sizeof(total), sizeof(h),
                    static_cast<size_t>(payload_len > 0 ? payload_len : 0)};
  for (int i = 0; i < 3; ++i) {
    if (done >= lens[i]) {
      done -= lens[i];
      continue;
    }
    if (!SendAll(fd, bufs[i] + done, lens[i] - done)) return false;
    done = 0;
  }
  return true;
}

void Van::StartRecvThread(int fd) {
  std::lock_guard<std::mutex> lk(mu_);
  send_mu_.emplace(fd, std::make_shared<std::mutex>());
  threads_.emplace_back([this, fd] { RecvLoop(fd); });
}

void Van::AcceptLoop() {
  while (!stop_.load()) {
    sockaddr_in peer{};
    socklen_t plen = sizeof(peer);
    int lfd = listen_fd_.load();
    if (lfd < 0) return;
    int fd = ::accept(lfd, reinterpret_cast<sockaddr*>(&peer), &plen);
    if (fd < 0) {
      if (stop_.load()) break;
      if (errno == EINTR) continue;
      break;  // listener shut down
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    SizeSocketBuffers(fd);
    StartRecvThread(fd);
  }
  // The accept thread owns the listening fd's close (Stop only shuts it
  // down, so no other thread can race this close with a blocked accept).
  int lfd = listen_fd_.exchange(-1);
  if (lfd >= 0) ::close(lfd);
}

void Van::RecvLoop(int fd) {
  while (!stop_.load()) {
    uint64_t total = 0;
    if (!RecvAll(fd, &total, sizeof(total))) break;
    BPS_CHECK_GE(total, sizeof(MsgHeader)) << "malformed frame";
    Message msg;
    if (!RecvAll(fd, &msg.head, sizeof(MsgHeader))) break;
    uint64_t plen = total - sizeof(MsgHeader);
    BPS_CHECK_EQ(plen, static_cast<uint64_t>(msg.head.payload_len))
        << "frame length mismatch";
    if (plen > 0) {
      msg.payload.resize_uninit(plen);  // recv overwrites every byte
      if (!RecvAll(fd, msg.payload.data(), plen)) break;
    }
    bytes_recv_.fetch_add(static_cast<int64_t>(sizeof(total) + total),
                          std::memory_order_relaxed);
    LogMsg("recv", fd, msg.head, static_cast<int64_t>(plen));
    handler_(std::move(msg), fd);
  }
  // A live-van exit means the PEER went away (EOF / reset), not Stop():
  // let the upper layer fail that peer's outstanding requests now.
  if (!stop_.load() && disconnect_cb_) disconnect_cb_(fd);
  CloseConn(fd);
}

// Connection fds are CLOSED only by their owning recv thread (via
// CloseConn at RecvLoop exit); other threads may only shutdown() them.
// This avoids the close-vs-blocked-recv fd-reuse race.
void Van::CloseConn(int fd) {
  std::lock_guard<std::mutex> lk(mu_);
  if (send_mu_.erase(fd)) ::close(fd);
}

void Van::Stop() {
  bool expected = false;
  if (!stop_.compare_exchange_strong(expected, true)) return;
  int lfd = listen_fd_.load();
  if (lfd >= 0) ::shutdown(lfd, SHUT_RDWR);  // wakes accept; thread closes
  std::vector<std::thread> ts;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : send_mu_) ::shutdown(kv.first, SHUT_RDWR);
    ts.swap(threads_);
  }
  for (auto& t : ts) {
    if (t.get_id() == std::this_thread::get_id()) t.detach();
    else if (t.joinable()) t.join();
  }
}

}  // namespace bps
