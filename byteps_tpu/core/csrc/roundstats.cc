#include "roundstats.h"

#include <cstdlib>
#include <cstring>

#include "metrics.h"
#include "tenancy.h"

namespace bps {

namespace {

int64_t EnvLL(const char* name, int64_t dflt) {
  const char* v = getenv(name);
  return v && *v ? atoll(v) : dflt;
}

bool EnvOn(const char* name, bool dflt) {
  const char* v = getenv(name);
  if (!v || !*v) return dflt;
  return strcmp(v, "0") != 0 && strcasecmp(v, "false") != 0 &&
         strcasecmp(v, "off") != 0 && strcasecmp(v, "no") != 0;
}

// Rounds legally overlap: double buffering keeps r and r+1 live, and a
// deep-pipelining caller keeps up to ~4 in flight. An open round this
// far behind the newest with its ENQ/DONE ledger still unbalanced is
// wedged or abandoned (a failed handle) — force-finalize so the table
// stays bounded and the ring keeps moving.
constexpr int kOpenRounds = 8;

void AppendRec(std::string* out, const RoundRec& r) {
  char buf[512];
  snprintf(buf, sizeof(buf),
           "{\"round\":%d,\"parts\":%d,\"queue_us\":%lld,"
           "\"comp_us\":%lld,\"push_us\":%lld,\"sum_us\":%lld,"
           "\"wire_ack_us\":%lld,\"pull_us\":%lld,\"dec_us\":%lld,"
           "\"wire_bytes\":%lld,\"wire_msgs\":%d,\"fused_frames\":%d,"
           "\"retries\":%d,\"parked\":%d,\"wall_us\":%lld}",
           r.round, r.parts, static_cast<long long>(r.queue_us),
           static_cast<long long>(r.comp_us),
           static_cast<long long>(r.push_us),
           static_cast<long long>(r.sum_us),
           static_cast<long long>(
               r.push_us > r.sum_us ? r.push_us - r.sum_us : 0),
           static_cast<long long>(r.pull_us),
           static_cast<long long>(r.dec_us),
           static_cast<long long>(r.wire_bytes), r.wire_msgs,
           r.fused_frames, r.retries, r.parked,
           static_cast<long long>(RoundWallUs(r)));
  *out += buf;
}

}  // namespace

RoundStats::RoundStats()
    : ring_cap_(static_cast<size_t>(EnvLL("BYTEPS_ROUNDSTATS_RING", 256))) {
  if (ring_cap_ < 8) ring_cap_ = 8;
  ring_.resize(ring_cap_);
  armed_.store(EnvOn("BYTEPS_ROUNDSTATS_ON", true),
               std::memory_order_relaxed);
  heartbeat_summary_on_ = EnvOn("BYTEPS_ROUNDSTATS_HEARTBEAT_SUMMARY", true);
}

RoundStats& RoundStats::Get() {
  static RoundStats* inst = new RoundStats();
  return *inst;
}

void RoundStats::SetNode(int role, int node_id) {
  role_.store(role, std::memory_order_relaxed);
  node_id_.store(node_id, std::memory_order_relaxed);
}

void RoundStats::SetNodeTenant(int node_id, int tenant) {
  std::lock_guard<std::mutex> lk(mu_);
  node_tenant_[node_id] = tenant;
}

void RoundStats::Track(int32_t stage, int round, int64_t us,
                       int64_t bytes) {
  if (!On() || round < 0) return;
  std::lock_guard<std::mutex> lk(mu_);
  OpenRound& o = open_[round];
  o.rec.round = round;
  switch (stage) {
    case RS_ENQ:   ++o.enqueued; break;
    case RS_QUEUE: o.rec.queue_us += us; break;
    case RS_COMP:  o.rec.comp_us += us; break;
    case RS_PUSH:
      o.rec.push_us += us;
      o.rec.wire_bytes += bytes;
      break;
    case RS_SUM:   o.rec.sum_us += us; break;
    case RS_PULL:
      o.rec.pull_us += us;
      o.rec.wire_bytes += bytes;
      break;
    case RS_DEC:   o.rec.dec_us += us; break;
    case RS_RETRY: ++o.rec.retries; break;
    case RS_PARK:  ++o.rec.parked; break;
    case RS_FRAME:
      ++o.rec.wire_msgs;
      if (bytes) ++o.rec.fused_frames;
      break;
    case RS_DONE:
      ++o.done;
      ++o.rec.parts;
      break;
    default: return;
  }
  if (round > max_round_) max_round_ = round;
  TryFinalizeLocked();
}

void RoundStats::TryFinalizeLocked() {
  // Oldest-first so the ring preserves round order. Two rules:
  //  - ledger-balanced rounds (workers: every enqueued partition's pull
  //    landed) finalize once a NEWER round exists — "done for now" can
  //    be mid-step (tensor A's round r completes before tensor B's
  //    round-r push is even enqueued), so a later round starting is the
  //    step boundary signal;
  //  - ledger-less rounds (servers never see RS_ENQ/RS_DONE) finalize
  //    two rounds behind the newest — one round of slack for the legal
  //    double-buffer skew between slot parities.
  for (auto it = open_.begin(); it != open_.end();) {
    const bool balanced =
        it->second.enqueued > 0 && it->second.done >= it->second.enqueued;
    const bool ledgerless = it->second.enqueued == 0;
    if ((balanced && it->first < max_round_) ||
        (ledgerless && it->first <= max_round_ - 2)) {
      FinalizeLocked(it->first);
      it = open_.erase(it);
    } else {
      ++it;
    }
  }
  // Bounded open table: force out the oldest wedged rounds.
  while (open_.size() > kOpenRounds) {
    auto it = open_.begin();
    FinalizeLocked(it->first);
    ++forced_;
    open_.erase(it);
  }
}

void RoundStats::FinalizeLocked(int round) {
  const RoundRec& r = open_[round].rec;
  ring_[ring_head_] = r;
  ring_head_ = (ring_head_ + 1) % ring_cap_;
  ++ring_total_;
  PublishGaugesLocked(r);
}

void RoundStats::PublishGaugesLocked(const RoundRec& r) {
  // Per-round series on /metrics: monitor.top reads these for its
  // BOTTLENECK column without needing the /rounds endpoint. Gauges hold
  // the LAST completed round; the histogram keeps the distribution.
  BPS_METRIC_COUNTER_ADD("bps_rounds_completed_total", 1);
  BPS_METRIC_GAUGE_SET("bps_round_last", r.round);
  BPS_METRIC_GAUGE_SET("bps_round_parts", r.parts);
  BPS_METRIC_GAUGE_SET("bps_round_queue_us", r.queue_us);
  BPS_METRIC_GAUGE_SET("bps_round_comp_us", r.comp_us);
  BPS_METRIC_GAUGE_SET("bps_round_push_us", r.push_us);
  BPS_METRIC_GAUGE_SET("bps_round_sum_us", r.sum_us);
  BPS_METRIC_GAUGE_SET("bps_round_wire_ack_us",
                       r.push_us > r.sum_us ? r.push_us - r.sum_us : 0);
  BPS_METRIC_GAUGE_SET("bps_round_pull_us", r.pull_us);
  BPS_METRIC_GAUGE_SET("bps_round_dec_us", r.dec_us);
  BPS_METRIC_GAUGE_SET("bps_round_wire_bytes", r.wire_bytes);
  BPS_METRIC_GAUGE_SET("bps_round_wire_msgs", r.wire_msgs);
  BPS_METRIC_GAUGE_SET("bps_round_retries", r.retries);
  BPS_METRIC_GAUGE_SET("bps_round_parked", r.parked);
  BPS_METRIC_HISTO_OBSERVE("bps_round_wall_us", RoundWallUs(r));
}

bool RoundStats::FillWire(std::string* out) {
  if (!On() || !heartbeat_summary_on_) return false;
  std::lock_guard<std::mutex> lk(mu_);
  if (ring_total_ <= wire_sent_total_) return false;
  int64_t backlog = ring_total_ - wire_sent_total_;
  // Rounds that rotated out of the ring before a heartbeat could ship
  // them are lost to the fleet table (counted in `dropped`).
  if (backlog > static_cast<int64_t>(ring_cap_)) {
    wire_sent_total_ = ring_total_ - static_cast<int64_t>(ring_cap_);
    backlog = static_cast<int64_t>(ring_cap_);
  }
  int count = backlog > kMaxWireRecs ? kMaxWireRecs
                                     : static_cast<int>(backlog);
  RoundSummaryHdr hdr;
  hdr.magic = kRoundSummaryMagic;
  hdr.version = kRoundSummaryVersion;
  hdr.node_id = node_id_.load(std::memory_order_relaxed);
  hdr.role = role_.load(std::memory_order_relaxed);
  hdr.count = count;
  hdr.completed_total = ring_total_;
  int64_t over = ring_total_ - static_cast<int64_t>(ring_cap_);
  hdr.dropped = forced_ + (over > 0 ? over : 0);
  out->assign(reinterpret_cast<const char*>(&hdr), sizeof(hdr));
  // Oldest unsent first. ring slot of the i-th record ever finalized:
  // i % cap (head_ advanced past it).
  for (int64_t i = wire_sent_total_; i < wire_sent_total_ + count; ++i) {
    const RoundRec& r = ring_[static_cast<size_t>(i % ring_cap_)];
    out->append(reinterpret_cast<const char*>(&r), sizeof(r));
  }
  wire_sent_total_ += count;
  return true;
}

size_t RoundStats::WireSize(const void* data, size_t len) {
  if (!data || len < sizeof(RoundSummaryHdr)) return 0;
  RoundSummaryHdr hdr;
  memcpy(&hdr, data, sizeof(hdr));
  if (hdr.magic != kRoundSummaryMagic ||
      hdr.version != kRoundSummaryVersion) {
    return 0;
  }
  if (hdr.count < 0 || hdr.count > kMaxWireRecs) return 0;
  size_t need =
      sizeof(hdr) + static_cast<size_t>(hdr.count) * sizeof(RoundRec);
  return len >= need ? need : 0;
}

bool RoundStats::Ingest(const void* data, size_t len) {
  if (len < sizeof(RoundSummaryHdr)) return false;
  RoundSummaryHdr hdr;
  memcpy(&hdr, data, sizeof(hdr));
  if (hdr.magic != kRoundSummaryMagic ||
      hdr.version != kRoundSummaryVersion) {
    return false;  // unknown sender generation — interop: ignore
  }
  if (hdr.count < 0 || hdr.count > kMaxWireRecs ||
      len < sizeof(hdr) + static_cast<size_t>(hdr.count) * sizeof(RoundRec)) {
    return false;
  }
  const char* p = static_cast<const char*>(data) + sizeof(hdr);
  std::lock_guard<std::mutex> lk(mu_);
  RankState& st = fleet_[hdr.node_id];
  st.role = hdr.role;
  st.completed_total = hdr.completed_total;
  for (int i = 0; i < hdr.count; ++i) {
    RoundRec r;
    memcpy(&r, p + static_cast<size_t>(i) * sizeof(RoundRec), sizeof(r));
    st.last = r;
    ++st.updates;
    double wall = static_cast<double>(RoundWallUs(r));
    st.ewma_wall_us = st.updates == 1
                          ? wall
                          : (1.0 - kRoundEwmaAlpha) * st.ewma_wall_us +
                                kRoundEwmaAlpha * wall;
    fleet_rounds_[r.round][hdr.node_id] = r;
  }
  // Bounded fleet table: keep the last 128 rounds.
  while (fleet_rounds_.size() > 128) {
    fleet_rounds_.erase(fleet_rounds_.begin());
  }
  BPS_METRIC_COUNTER_ADD("bps_round_summaries_ingested_total", hdr.count);
  return true;
}

bool RoundStats::LastCompleted(RoundRec* out) {
  std::lock_guard<std::mutex> lk(mu_);
  if (ring_total_ == 0) return false;
  *out = ring_[(ring_head_ + ring_cap_ - 1) % ring_cap_];
  return true;
}

int64_t RoundStats::completed_total() {
  std::lock_guard<std::mutex> lk(mu_);
  return ring_total_;
}

int64_t RoundStats::dropped() {
  std::lock_guard<std::mutex> lk(mu_);
  int64_t over = ring_total_ - static_cast<int64_t>(ring_cap_);
  return forced_ + (over > 0 ? over : 0);
}

std::string RoundStats::SnapshotJson() {
  std::lock_guard<std::mutex> lk(mu_);
  std::string out = "{";
  out += "\"on\":" + std::string(On() ? "true" : "false");
  out += ",\"role\":" +
         std::to_string(role_.load(std::memory_order_relaxed));
  out += ",\"node_id\":" +
         std::to_string(node_id_.load(std::memory_order_relaxed));
  out += ",\"tenant\":" + std::to_string(TenantId());
  out += ",\"ring_capacity\":" + std::to_string(ring_cap_);
  out += ",\"completed_total\":" + std::to_string(ring_total_);
  int64_t over = ring_total_ - static_cast<int64_t>(ring_cap_);
  out += ",\"dropped\":" +
         std::to_string(forced_ + (over > 0 ? over : 0));
  out += ",\"last\":";
  if (ring_total_ > 0) {
    AppendRec(&out, ring_[(ring_head_ + ring_cap_ - 1) % ring_cap_]);
  } else {
    out += "null";
  }
  size_t n = ring_total_ < static_cast<int64_t>(ring_cap_)
                 ? static_cast<size_t>(ring_total_)
                 : ring_cap_;
  size_t start = (ring_head_ + ring_cap_ - n) % ring_cap_;
  out += ",\"rounds\":[";
  for (size_t i = 0; i < n; ++i) {
    if (i) out += ",";
    AppendRec(&out, ring_[(start + i) % ring_cap_]);
  }
  out += "]";
  out += ",\"fleet\":{";
  bool first = true;
  for (const auto& kv : fleet_) {
    if (!first) out += ",";
    first = false;
    out += "\"" + std::to_string(kv.first) + "\":{";
    out += "\"role\":" + std::to_string(kv.second.role);
    auto tit = node_tenant_.find(kv.first);
    out += ",\"tenant\":" +
           std::to_string(tit == node_tenant_.end() ? 0 : tit->second);
    out += ",\"completed_total\":" +
           std::to_string(kv.second.completed_total);
    out += ",\"updates\":" + std::to_string(kv.second.updates);
    char e[48];
    snprintf(e, sizeof(e), ",\"ewma_wall_us\":%.1f",
             kv.second.ewma_wall_us);
    out += e;
    out += ",\"last\":";
    AppendRec(&out, kv.second.last);
    out += "}";
  }
  out += "},\"fleet_rounds\":{";
  first = true;
  for (const auto& rkv : fleet_rounds_) {
    if (!first) out += ",";
    first = false;
    out += "\"" + std::to_string(rkv.first) + "\":{";
    bool f2 = true;
    for (const auto& nkv : rkv.second) {
      if (!f2) out += ",";
      f2 = false;
      out += "\"" + std::to_string(nkv.first) + "\":";
      AppendRec(&out, nkv.second);
    }
    out += "}";
  }
  out += "}}";
  return out;
}

}  // namespace bps
