// Message transport over TCP sockets, with an optional shared-memory data
// path for co-located peers.
//
// Capability parity: reference ps-lite Van/ZMQVan (SURVEY.md §2.4) — node
// handshake, framed message send/recv, zero-copy sends. Fresh design: no
// ZMQ dependency; plain POSIX sockets with one receive thread per
// connection (TPU-host fleets are Linux; thread-per-conn is simple and at
// PS-scale [O(100) conns] well within epoll-free territory), writev-based
// gather sends so payload bytes are never copied into a staging buffer.
//
// Second transport (BYTEPS_VAN_TYPE=shm): the role the reference's non-TCP
// vans play (ZMQVan ipc:// and rdma_van.h — SURVEY.md §2.4) is "don't pay
// the network stack when you don't have to". For loopback peers the
// connector negotiates a per-connection POSIX shm segment over the freshly
// dialled TCP socket (CMD_SHM_HELLO) and both sides move all subsequent
// frames through lock-free SPSC byte rings (shm_ring.h). The TCP socket
// stays open but idle: peer death still surfaces as an EOF on it, so
// heartbeat-free fast-fail (SetDisconnectHandler) works identically on
// both transports. Remote peers keep TCP — mixed fleets need no config.
#pragma once

#include <sys/uio.h>

#include <atomic>
#include <functional>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include "common.h"

namespace bps {

class Van {
 public:
  // Handler is invoked on the connection's receive thread. fd identifies the
  // connection so upper layers can reply on the same socket.
  using Handler = std::function<void(Message&&, int fd)>;

  explicit Van(Handler handler) : handler_(std::move(handler)) {}
  ~Van() { Stop(); }

  // Bind + listen on port (0 = ephemeral). Returns the bound port.
  int Listen(int port);

  // Connect to a remote listener. Returns the connection fd (or -1).
  // max_attempts bounds the dial loop (100 ms between tries): the
  // default rides out fleet-formation races like the reference; the
  // RECONNECT path (postoffice) passes 1 per try and owns its own
  // backoff, so a dead peer is detected in milliseconds, not 30 s.
  int Connect(const std::string& host, int port, int max_attempts = 300);

  // Send one framed message; thread-safe per connection. Payload bytes are
  // written straight from `payload` (zero-copy gather write).
  bool Send(int fd, const MsgHeader& head, const void* payload = nullptr,
            int64_t payload_len = 0);

  // Gather-send: one framed message whose payload is the concatenation of
  // `nsegs` discontiguous segments (the fusion layer's sub-header table +
  // sub-payloads), written via a single writev without staging copies.
  // head.payload_len is set to the segment total. Same per-fd locking and
  // transport selection as Send.
  bool SendV(int fd, const MsgHeader& head, const struct iovec* segs,
             int nsegs);

  void CloseConn(int fd);
  void Stop();
  bool stopped() const { return stop_.load(); }

  // Invoked (on the dying connection's receive thread) when a connection
  // closes while the van is still running — peer crash/EOF, not Stop().
  // Upper layers use it to fail outstanding requests to that peer fast
  // instead of waiting out the heartbeat detector.
  void SetDisconnectHandler(std::function<void(int fd)> cb) {
    disconnect_cb_ = std::move(cb);
  }

  // Invoked (on the connection's receive thread) when the wire-CRC
  // quarantine threshold trips on a connection (ISSUE 19,
  // BYTEPS_WIRE_CRC_QUARANTINE: too many CRC failures inside one
  // window) — immediately BEFORE the van force-closes the connection so
  // the reconnect ladder re-dials a fresh socket. Upper layers use it
  // to attribute the corrupting link to a peer node and escalate a
  // persistently-corrupting link to a named fail-stop.
  void SetCorruptionHandler(std::function<void(int fd)> cb) {
    corrupt_cb_ = std::move(cb);
  }

  // Cumulative wire bytes (frames + payloads), for bandwidth assertions
  // and the timeline. Monotonic over the van's lifetime.
  int64_t bytes_sent() const { return bytes_sent_.load(); }
  int64_t bytes_recv() const { return bytes_recv_.load(); }

 private:
  struct ShmConn;  // mapped segment + role (van.cc)
  // MSG_ZEROCOPY per-fd completion bookkeeping (BYTEPS_VAN_ZEROCOPY=1;
  // van.cc zerocopy block). Touched only under the per-fd send lock.
  struct ZcState {
    uint32_t next = 0;              // zerocopy sends issued on this fd
    uint32_t reaped = 0xFFFFFFFFu;  // highest completed (-1 = none yet)
  };
  // Per-connection transmit state, mutated only under the per-fd send
  // lock: the monotone frame sequence (MsgHeader::seq) plus the chaos
  // layer's deterministic PRNG and data-frame counter
  // (BYTEPS_CHAOS_SEED/_DROP/_DELAY_US/_DUP/_RESET_EVERY; van.cc).
  struct TxState {
    int64_t seq = 0;
    uint64_t rng = 0;
    int64_t data_frames = 0;
  };
  // Per-connection receive state, owned by the connection's single frame
  // consumer thread per transport (no locking): the seq gap/dup cursor
  // plus the wire-CRC quarantine window (BYTEPS_WIRE_CRC_QUARANTINE,
  // ISSUE 19; van.cc).
  struct RxState {
    int64_t last_seq = 0;
    int64_t win_fails = 0;     // CRC failures inside the current window
    int64_t win_start_us = 0;  // window open time (0 = none open yet)
  };

  // One framed write on an already-locked connection (transport
  // selection: shm ring / zerocopy / gather writev). Factored out of
  // SendV so the chaos layer can write a duplicated frame twice.
  bool WriteFrame(int fd, MsgHeader& h, const struct iovec* segs,
                  int nsegs, uint64_t total, int64_t payload_len,
                  ShmConn* shm, ZcState* zcs);
  void AcceptLoop();
  void RecvLoop(int fd);
  // Returns the per-fd send mutex it registered — an identity token for
  // THIS incarnation of the fd (a closed-and-reaccepted fd gets a fresh
  // mutex), which OfferShm uses to detect fd reuse.
  std::shared_ptr<std::mutex> StartRecvThread(int fd);
  void ShmRecvLoop(int fd, std::shared_ptr<ShmConn> conn);
  // Shared tail of both recv loops: wire accounting, PS_VERBOSE trace,
  // wire-CRC verification (BYTEPS_WIRE_CRC — a mismatching frame is
  // dropped here, before it can touch seq cursors or upper-layer
  // state), seq gap/dup detection, van-internal command handling,
  // handler dispatch — ONE copy so the transports cannot drift. `rx` is
  // the caller recv loop's per-connection state (each connection has
  // exactly one frame consumer thread per transport).
  void DispatchFrame(Message&& msg, int fd, RxState* rx);
  // Connector side; returns false -> stay on TCP. `smu` is the send-mutex
  // identity StartRecvThread returned for this connection.
  bool OfferShm(int fd, const std::shared_ptr<std::mutex>& smu);
  void AttachShm(int fd, const Message& hello);  // acceptor side

  Handler handler_;
  std::function<void(int fd)> disconnect_cb_;
  std::function<void(int fd)> corrupt_cb_;
  std::atomic<int> listen_fd_{-1};
  std::atomic<bool> stop_{false};
  std::atomic<int64_t> bytes_sent_{0};
  std::atomic<int64_t> bytes_recv_{0};
  std::mutex mu_;  // guards send_mu_ / threads_ / shm_conns_
  // shared_ptr: Send() keeps the per-fd mutex alive across its write even
  // if CloseConn erases the entry concurrently (connection teardown race).
  std::unordered_map<int, std::shared_ptr<std::mutex>> send_mu_;
  // Connections whose data path moved to a shm ring, keyed by the (still
  // open) TCP fd. Send() consults this under the per-fd send lock, so a
  // connection's frames never interleave across transports.
  std::unordered_map<int, std::shared_ptr<ShmConn>> shm_conns_;
  // fds armed for MSG_ZEROCOPY sends (SO_ZEROCOPY accepted at setup).
  std::unordered_map<int, std::shared_ptr<ZcState>> zc_;
  // Per-fd transmit state (seq stamping + chaos); created with the
  // connection, looked up in SendV under the same mu_ acquisition as
  // send_mu_, mutated only under the per-fd send lock.
  std::unordered_map<int, std::shared_ptr<TxState>> tx_;
  std::vector<std::thread> threads_;
};

}  // namespace bps
