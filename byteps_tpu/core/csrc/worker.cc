#include "worker.h"

#include <sys/uio.h>

#include <algorithm>
#include <chrono>
#include <cstring>
#include <map>

#include "cpu_reducer.h"
#include "events.h"
#include "logging.h"
#include "metrics.h"
#include "roundstats.h"
#include "tenancy.h"

namespace bps {

thread_local std::vector<BytePSWorker::PushOp>* BytePSWorker::fusion_sink_ =
    nullptr;

void BytePSWorker::Start(Postoffice* po, KVWorker* kv, int64_t partition_bytes,
                         int64_t credit_bytes, int64_t fusion_bytes,
                         int fusion_keys, std::string default_comp,
                         bool trace_on) {
  po_ = po;
  kv_ = kv;
  partition_bytes_ = partition_bytes;
  fusion_bytes_ = fusion_bytes < 0 ? 0 : fusion_bytes;
  // Backstop for direct FFI users (the Python config layer rejects this
  // combination when fusion is on, and ignores fusion_keys when it is
  // off): clamp to the minimum batch of 2, loudly when it matters.
  if (fusion_keys < 2 && fusion_bytes_ > 0) {
    BPS_LOG(WARNING) << "fusion_keys=" << fusion_keys
                     << " below the minimum fused batch of 2; clamping to 2";
  }
  fusion_keys_ = fusion_keys < 2 ? 2 : fusion_keys;
  // Flush linger: how long the collector waits for the enqueuing thread
  // to deliver the next fusible task before flushing a partial batch.
  // Bounded per batch; small vs a framed round trip but long vs the
  // enqueuer's per-task cadence, so batches actually form.
  if (const char* lv = getenv("BYTEPS_FUSION_LINGER_US")) {
    fusion_linger_us_ = atoll(lv);
    if (fusion_linger_us_ < 0) fusion_linger_us_ = 0;
  }
  // Block-quantized wire (ISSUE 6): the Python config layer validates
  // these; the clamp here is a backstop for direct FFI users so a bad
  // block can never reach the codec (Encode refuses invalid blocks).
  if (const char* qv = getenv("BYTEPS_WIRE_QUANT")) {
    wire_quant_ = atoi(qv) != 0;
  }
  if (const char* qb = getenv("BYTEPS_WIRE_QUANT_BLOCK")) {
    quant_block_ = atoi(qb);
  }
  if (!BlockQuant::ValidBlock(quant_block_)) {
    if (wire_quant_) {
      BPS_LOG(WARNING) << "BYTEPS_WIRE_QUANT_BLOCK=" << quant_block_
                       << " is not a power of two in [16, 32768]; "
                          "using 64";
    }
    quant_block_ = 64;
  }
  if (const char* qm = getenv("BYTEPS_WIRE_QUANT_MIN_BYTES")) {
    quant_min_bytes_ = atoll(qm);
    if (quant_min_bytes_ < 0) quant_min_bytes_ = 0;
  }
  default_comp_ = std::move(default_comp);
  trace_on_ = trace_on;
  // Pre-register the worker-side metric catalog: every stage's series
  // exists from zero on the /metrics page (an idle or compression-less
  // worker omits nothing — scrapers sum and ratio these fleet-wide).
  Metrics::Get().Counter("bps_partitions_enqueued_total");
  Metrics::Get().Counter("bps_enqueued_bytes_total");
  Metrics::Get().Counter("bps_push_bytes_total");
  Metrics::Get().Counter("bps_push_partitions_total");
  Metrics::Get().Counter("bps_pull_bytes_total");
  Metrics::Get().Counter("bps_fused_msgs_total");
  Metrics::Get().Histogram("bps_fusion_batch_keys");
  // Quantized-wire accounting (docs/monitoring.md): encoded bytes that
  // actually crossed the wire and the raw-minus-encoded savings, both
  // legs (push encode here, pull decode below). Present-from-zero so
  // monitor.top's compression-ratio column reads 1.0x, not a hole.
  Metrics::Get().Counter("bps_quant_bytes_on_wire_total");
  Metrics::Get().Counter("bps_quant_bytes_saved_total");
  Metrics::Get().Histogram("bps_push_us");
  Metrics::Get().Histogram("bps_pull_us");
  // Transient-fault telemetry: present-from-zero so monitor.top and
  // /healthz can watch a climbing retry rate BEFORE a node goes dead
  // (docs/monitoring.md). bps_chaos_injected_total stays lazily
  // registered — nonzero only when fault injection is armed.
  Metrics::Get().Counter("bps_retries_total");
  Metrics::Get().Counter("bps_reconnects_total");
  Metrics::Get().Counter("bps_seq_gaps_total");
  Metrics::Get().Counter("bps_seq_dups_total");
  // Hot-replacement telemetry (docs/monitoring.md "Recovery"):
  // recoveries this worker completed, the fleet membership epoch, and
  // whether a rank is mid-recovery right now.
  Metrics::Get().Counter("bps_recoveries_total");
  Metrics::Get().Gauge("bps_membership_epoch");
  Metrics::Get().Gauge("bps_recovering");
  // Per-round introspection series (ISSUE 7): present-from-zero so
  // monitor.top's BOTTLENECK column reads zeros, not holes, on an idle
  // worker. The gauges hold the LAST completed round's stage breakdown
  // (published by RoundStats at round finalize).
  Metrics::Get().Counter("bps_rounds_completed_total");
  for (const char* g :
       {"bps_round_last", "bps_round_parts", "bps_round_queue_us",
        "bps_round_comp_us", "bps_round_push_us", "bps_round_sum_us",
        "bps_round_wire_ack_us", "bps_round_pull_us", "bps_round_dec_us",
        "bps_round_wire_bytes", "bps_round_wire_msgs",
        "bps_round_retries", "bps_round_parked"}) {
    Metrics::Get().Gauge(g);
  }
  Metrics::Get().Histogram("bps_round_wall_us");
  recovery_on_ = RecoveryEnabled();
  // Reference semantics: BYTEPS_SCHEDULING_CREDIT is an in-flight BYTE
  // budget. 0 = auto: four full partitions' worth. A value under 1024
  // can only be a legacy partition count (the reference default was 4;
  // no real byte budget is smaller than 1 KiB, and no in-flight count
  // reaches 1024) — honouring it as bytes would serialise every push,
  // so interpret it AS a partition count (credit × partition_bytes) so
  // legacy env users keep their intended overlap. Values >= 1024 are
  // honoured as bytes, so small genuine budgets stay expressible.
  // This is the SINGLE conversion point: the Python config layer warns
  // about sub-1024 values but passes them through unchanged.
  if (credit_bytes > 0 && credit_bytes < 1024) {
    BPS_LOG(WARNING) << "BYTEPS_SCHEDULING_CREDIT=" << credit_bytes
                     << " looks like a legacy in-flight partition count; "
                     << "interpreting as " << credit_bytes << " x "
                     << partition_bytes << " bytes";
    credit_bytes = credit_bytes * partition_bytes;
  }
  if (credit_bytes <= 0) credit_bytes = 4 * partition_bytes;
  queue_ = std::make_unique<ScheduledQueue>(credit_bytes);
  // Sender parallelism: the van's writev blocks once a connection's
  // SNDBUF fills, and with ONE push thread a full stripe head-of-line
  // blocks sends to every OTHER stripe/server (exposed by the BDP
  // sweep: N stripes measured one stripe's goodput). Concurrent pops
  // are order-safe under the synchronous step pattern every in-tree
  // caller uses (jax/training.py waits all handles each step): a key's
  // next-round push_pull is only issued after the previous round's
  // pull completed, so two tasks for one key never coexist in the
  // queue, and the van's per-fd lock serialises same-connection
  // writes. A caller that DEEP-PIPELINES one tensor (3+ push_pull
  // handles in flight — see the version comment in PushPull) can have
  // rounds r and r+2 of a key queued at once; per-key wire order then
  // requires a single push thread (set BYTEPS_PUSH_THREADS=1 when
  // striping is on), and the fusion collector's duplicate-key flush in
  // PushLoop handles exactly that case. Default: match the stripe
  // count (capped), 1 when unstriped (the single-thread wire order
  // PS_VERBOSE users expect).
  int push_threads = 0;
  if (const char* pt = getenv("BYTEPS_PUSH_THREADS")) {
    push_threads = atoi(pt);
  }
  if (push_threads <= 0) {
    int streams = 1;
    if (const char* sv = getenv("BYTEPS_VAN_STREAMS")) {
      streams = atoi(sv);
    }
    push_threads = streams > 1 ? std::min(streams, 8) : 1;
  }
  for (int i = 0; i < push_threads; ++i) {
    push_threads_.emplace_back([this] { PushLoop(); });
  }
}

void BytePSWorker::Stop() {
  if (queue_) queue_->Stop();
  for (auto& t : push_threads_) {
    if (t.joinable()) t.join();
  }
  push_threads_.clear();
  std::vector<std::thread> rec;
  {
    std::lock_guard<std::mutex> lk(rec_threads_mu_);
    rec.swap(rec_threads_);
  }
  for (auto& t : rec) {
    if (t.joinable()) t.join();
  }
}

// --- elastic worker membership (ISSUE 8) ------------------------------------

void BytePSWorker::OnFleetPause(int kind) {
  if (kind != 0) return;  // only a JOIN gates new rounds
  int64_t rmax, bmax;
  {
    std::lock_guard<std::mutex> lk(mu_);
    fleet_paused_ = true;
    rmax = sync_round_;
    bmax = sync_bcast_round_;
    for (auto& ctx : tensors_) {
      rmax = std::max(rmax, ctx->round);
      bmax = std::max(bmax, ctx->bcast_round);
    }
  }
  // Drain-free ack: every round this worker has ISSUED is < the
  // counters reported here, and those rounds complete against the OLD
  // roster (the server's per-epoch contributor sets) — so the gate
  // alone makes the counters final; nothing has to settle first.
  BPS_LOG(WARNING) << "worker: fleet join in progress — new rounds "
                      "gated at round " << rmax;
  po_->SendFleetPauseAck(rmax, bmax);
}

void BytePSWorker::OnFleetResume(int kind, int64_t join_round,
                                 int64_t join_bcast) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (kind == 0) {
      // Jump every tensor's counters to the join activation round:
      // each member's NEXT round is the first one the new roster —
      // joiner included — is expected in. Counters only move forward.
      sync_round_ = std::max(sync_round_, join_round);
      sync_bcast_round_ = std::max(sync_bcast_round_, join_bcast);
      for (auto& ctx : tensors_) {
        if (ctx->round < sync_round_) ctx->round = sync_round_;
        if (ctx->bcast_round < sync_bcast_round_) {
          ctx->bcast_round = sync_bcast_round_;
        }
      }
    }
    fleet_paused_ = false;
  }
  cv_.notify_all();
}

int64_t BytePSWorker::MaxIssuedRound() {
  std::lock_guard<std::mutex> lk(mu_);
  int64_t rmax = sync_round_;
  for (auto& ctx : tensors_) rmax = std::max(rmax, ctx->round);
  return rmax;
}

void BytePSWorker::OnSchedRecovered() {
  bool was_gated;
  {
    std::lock_guard<std::mutex> lk(mu_);
    was_gated = fleet_paused_;
    fleet_paused_ = false;
  }
  if (was_gated) {
    BPS_LOG(WARNING) << "worker: lifting a stale fleet-pause gate — "
                        "its membership change died with the old "
                        "scheduler (re-request the join)";
  }
  cv_.notify_all();
}

void BytePSWorker::SyncRounds(int64_t round, int64_t bcast_round) {
  std::lock_guard<std::mutex> lk(mu_);
  // Monotone: a later join's RESUME may already have advanced the
  // counters past this rank's own activation point (two joins racing a
  // joiner's startup) — counters only ever move forward.
  sync_round_ = std::max(sync_round_, round);
  sync_bcast_round_ = std::max(sync_bcast_round_, bcast_round);
  for (auto& ctx : tensors_) {
    if (ctx->round < sync_round_) ctx->round = sync_round_;
    if (ctx->bcast_round < sync_bcast_round_) {
      ctx->bcast_round = sync_bcast_round_;
    }
  }
}

// --- hot-replacement recovery bookkeeping (ISSUE 4) -------------------------

void BytePSWorker::RecTrackPush(Part* p, const PushOp& op) {
  if (!recovery_on_) return;
  std::lock_guard<std::mutex> lk(rec_mu_);
  p->rec_op = op;
  p->rec_stage = 1;
  p->rec_push_rid = -1;
}

void BytePSWorker::RecTrackPushRid(Part* p, int rid) {
  if (!recovery_on_) return;
  std::lock_guard<std::mutex> lk(rec_mu_);
  // Only while still in push stage: a fast ack may have advanced (or a
  // fast chain completed) the state before Request returned.
  if (p->rec_stage == 1) p->rec_push_rid = rid;
}

void BytePSWorker::RecTrackAck(Part* p) {
  if (!recovery_on_) return;
  std::lock_guard<std::mutex> lk(rec_mu_);
  p->rec_stage = 2;
}

void BytePSWorker::RecTrackDone(Part* p, int version, const char* base,
                                int64_t raw_len) {
  if (!recovery_on_) return;
  std::lock_guard<std::mutex> lk(rec_mu_);
  // Retain the round's UNSCALED aggregate (exactly the server's slot
  // bytes): the authoritative re-seed payload should the owning server
  // die while a peer's pull for this round is still outstanding.
  p->reseed_data.assign(base, base + raw_len);
  p->reseed_round = version;
  p->rec_stage = 0;
  p->rec_push_rid = -1;
}

void BytePSWorker::RecClear(Part* p) {
  if (!recovery_on_) return;
  std::lock_guard<std::mutex> lk(rec_mu_);
  p->rec_stage = 0;
  p->rec_push_rid = -1;
}

void BytePSWorker::OnServerRecovered(int node_id) {
  // Off the van recv thread: the re-seed BLOCKS on INIT_KEY acks, and
  // the scheduler connection's recv thread must stay free to deliver a
  // failure SHUTDOWN should the replacement die mid-recovery.
  std::lock_guard<std::mutex> lk(rec_threads_mu_);
  rec_threads_.emplace_back([this, node_id] { RecoverServer(node_id); });
}

void BytePSWorker::RecoverServer(int node_id) {
  // Snapshot this rank's shard (tensors_ only grows and Part addresses
  // are stable after declare).
  std::vector<std::pair<TensorCtx*, Part*>> mine;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& ctx : tensors_) {
      for (auto& part : ctx->parts) {
        if (part.server_id == node_id) {
          mine.emplace_back(ctx.get(), &part);
        }
      }
    }
  }
  BPS_LOG(WARNING) << "worker: re-seeding replacement server " << node_id
                   << " (" << mine.size() << " partition(s))";
  // 1. Decide each partition's recovery action BEFORE anything is
  //    resent. Once the parked resend queue drains (step 2), a resent
  //    push can settle against the replacement at any moment and
  //    advance rec_stage to 2 — deciding from the live state after
  //    that would RE-PUSH a contribution the replacement has already
  //    applied under a different req_id, which the dedup window cannot
  //    link: a double-applied push, silent corruption. The snapshot is
  //    stable: the rank's requests are still paused, and only the dead
  //    predecessor could settle them.
  struct Action {
    TensorCtx* ctx;
    Part* p;
    bool repush;  // false = reseed
  };
  std::vector<Action> actions;
  for (auto& it : mine) {
    Part* p = it.second;
    std::lock_guard<std::mutex> lk(rec_mu_);
    const bool push_settled =
        p->rec_stage == 2 ||
        (p->rec_stage == 1 && p->rec_push_rid >= 0 &&
         !kv_->HasPending(p->rec_push_rid));
    if (push_settled) {
      // The dead server's partial sum held this contribution; the
      // resend queue does not (the request settled). Re-push it.
      actions.push_back({it.first, p, true});
    } else if (p->rec_stage == 0 && p->reseed_round >= 0) {
      // Idle key with a completed round retained: offer the aggregate
      // so a peer's parked pull for that round can be served.
      actions.push_back({it.first, p, false});
    }
    // rec_stage 1 with the push still pending: the resend queue owns
    // re-delivery; nothing extra to do.
  }
  // 2. Lift the pause and drain the parked resend queue: the blocking
  //    INIT_KEY wait below relies on the retry clock to re-deliver
  //    declares the chaos layer (or a flaky link) eats, and a paused
  //    rank's clock is frozen. Draining before the declares is safe —
  //    the replacement PARKS data ops for not-yet-redeclared keys and
  //    keepalives their senders (re-seed state, server.cc).
  kv_->ResendNode(node_id);
  // 3. Re-declare the shard — the replacement's store is empty, and a
  //    payload for an undeclared key is a protocol violation once its
  //    re-seed grace ends. Blocking, but only on our own INIT_KEYs.
  std::vector<int> reqs;
  for (auto& it : mine) {
    TensorCtx* ctx = it.first;
    Part* p = it.second;
    MsgHeader h{};
    h.cmd = CMD_INIT_KEY;
    h.key = p->key;
    h.dtype = ctx->dtype;
    h.arg0 = p->len * DtypeSize(ctx->dtype);
    reqs.push_back(kv_->Request(
        node_id, h, ctx->comp_config.data(),
        static_cast<int64_t>(ctx->comp_config.size()), nullptr));
  }
  kv_->WaitRequests(reqs);
  // 4. Issue the snapshotted re-pushes and reseeds. Payload lifetimes
  //    hold: a re-pushed op's handle has not settled (its pull cannot
  //    complete before our contribution lands), so the caller buffer /
  //    comp_buf are alive; reseed_data is worker-owned and only
  //    overwritten after the key's NEXT round completes, which this
  //    recovery gates. Ordinary retried requests from here — the timer
  //    re-drives any the wire eats, the dedup window absorbs replays.
  int repushed = 0, reseeded = 0;
  for (const Action& a : actions) {
    std::lock_guard<std::mutex> lk(rec_mu_);
    MsgHeader h{};
    h.key = a.p->key;
    h.dtype = a.ctx->dtype;
    if (a.repush) {
      h.cmd = CMD_PUSH;
      h.version = a.p->rec_op.version;
      h.flags = a.p->rec_op.flags;
      h.arg0 = a.p->rec_op.raw_len;
      kv_->Request(node_id, h, a.p->rec_op.payload,
                   a.p->rec_op.payload_len, nullptr);
      Trace::Get().Note("REPUSH", a.p->key, node_id, -1, h.version);
      ++repushed;
    } else {
      h.cmd = CMD_RESEED;
      h.version = a.p->reseed_round;
      kv_->Request(node_id, h, a.p->reseed_data.data(),
                   static_cast<int64_t>(a.p->reseed_data.size()),
                   nullptr);
      Trace::Get().Note("RESEED_OFFER", a.p->key, node_id, -1,
                        a.p->reseed_round);
      Events::Get().Emit(EV_RESEED, a.p->key, node_id, a.p->reseed_round);
      ++reseeded;
    }
  }
  BPS_METRIC_COUNTER_ADD("bps_recoveries_total", 1);
  BPS_METRIC_GAUGE_SET("bps_recovering", 0);
  BPS_LOG(WARNING) << "worker: server " << node_id << " re-seeded ("
                   << repushed << " re-pushed, " << reseeded
                   << " re-seeded round(s)) — resuming";
  // The recovery's closing flight dump: the EPOCH_PAUSE dump predates
  // the re-seed, so refresh the file with the RESUME + reseed trail.
  Trace::Get().Note("RECOVER_DONE", repushed + reseeded, node_id);
  Events::Get().Emit(EV_SERVER_RECOVER, node_id, repushed + reseeded,
                     /*done=*/1);
  Trace::Get().FlightDumpAuto("recovery_complete");
}

void BytePSWorker::PushLoop() {
  Task t;
  while (queue_->Pop(&t)) {
    if (fusion_bytes_ <= 0 || !t.fusible) {
      t.run();
      continue;
    }
    // Fusion collector: this (priority-ordered) pop opens a collect
    // session. Fusible tasks keep popping — in priority order, for ANY
    // server (the byte-balanced assignment interleaves servers at the
    // queue head) — and accumulate into one batch per destination
    // (server, stripe). Batches are keyed by the striped connection fd,
    // NOT the server alone: a fused frame is routed by its lead key
    // (SendFusedPush sets h.key = table[0].key), so every key sharing a
    // frame must hash to the same BYTEPS_VAN_STREAMS connection.
    // Batching per server would let one key's pushes ride a different
    // stripe from round to round (fused under a varying lead key, or
    // singleton under its own stripe), breaking the one-connection-per-
    // key ordering invariant striping relies on — a later round could
    // overtake an earlier one on another stripe and wedge the server's
    // slot. A batch flushes the moment it reaches the byte threshold
    // (BYTEPS_FUSION_BYTES) or key cap (BYTEPS_FUSION_KEYS); the
    // session ends — flushing every partial batch — when a non-fusible
    // task reaches the queue head or the queue stays empty past the
    // linger deadline (the enqueuing thread pumps tasks in slower than
    // this thread drains them; without a short wait every batch
    // degenerates to a singleton).
    std::map<std::pair<int, int>,
             std::pair<std::vector<PushOp>, int64_t>> acc;
    const int64_t deadline_us = NowUs() + fusion_linger_us_;
    auto stage = [this, &acc](Task& task) {
      const std::pair<int, int> dst{
          task.server_id, po_->FdOf(task.server_id, task.key)};
      auto& a = acc[dst];
      // One operation per key per frame: a deep-pipelining caller
      // (single push thread — see the thread-count comment in Start)
      // can enqueue rounds r and r+2 of one tensor back-to-back, and
      // the server PARKS an r+2 sub-push until round r's pulls recycle
      // its slot. Two rounds of one key in one frame would also break
      // the worker-side ack/pull-resp table matching (one slot per
      // key); flush the batch and let the next frame carry the later
      // round, exactly like the unfused wire.
      for (const PushOp& prev : a.first) {
        if (prev.p->key == task.key) {
          FlushBatch(task.server_id, std::move(a.first));
          a = {};
          break;
        }
      }
      fusion_sink_ = &a.first;
      task.run();  // stages its PushOp via fusion_sink_
      fusion_sink_ = nullptr;
      a.second += task.bytes;
      if (a.second >= fusion_bytes_ ||
          static_cast<int>(a.first.size()) >= fusion_keys_) {
        FlushBatch(task.server_id, std::move(a.first));
        acc.erase(dst);
      }
    };
    stage(t);
    Task more;
    while (queue_->TryPopFusible(
        std::max<int64_t>(0, deadline_us - NowUs()), &more)) {
      stage(more);
    }
    for (auto& kv : acc) {
      FlushBatch(kv.first.first, std::move(kv.second.first));
    }
  }
}

void BytePSWorker::FlushBatch(int server_id, std::vector<PushOp> ops) {
  if (ops.empty()) return;
  if (ops.size() == 1) {
    // A batch of one gains nothing from the multi framing; keep the
    // single-frame wire format (and its lower parse cost).
    SendPush(std::move(ops[0]));
    return;
  }
  SendFusedPush(server_id, std::move(ops));
}

void BytePSWorker::Record(int64_t key, const char* stage, int64_t start_us,
                          int peer, int32_t req_id, int32_t round,
                          int64_t wire_bytes, int64_t raw_bytes) {
  if (!trace_on_) return;
  Trace::Get().Span(stage, key, start_us, NowUs(), peer, req_id, round,
                    wire_bytes, raw_bytes);
}

int64_t BytePSWorker::Declare(const std::string& name, int64_t nelem,
                              int dtype, const std::string& comp_config) {
  std::unique_lock<std::mutex> lk(mu_);
  auto it = by_name_.find(name);
  if (it != by_name_.end()) {
    auto& t = *tensors_[it->second];
    BPS_CHECK_EQ(t.nelem, nelem) << "tensor " << name << " re-declared";
    BPS_CHECK_EQ(t.dtype, dtype) << "tensor " << name << " re-declared";
    return t.id;
  }
  auto ctx = std::make_unique<TensorCtx>();
  ctx->id = static_cast<int64_t>(tensors_.size());
  ctx->name = name;
  ctx->nelem = nelem;
  ctx->dtype = dtype;
  ctx->priority = -static_cast<int>(ctx->id);  // declaration-order priority
  // Elastic join (ISSUE 8): a joiner's tensors start at the fleet's
  // activation round, not 0 — its first push lands exactly in the
  // first round the new roster expects it in. 0 on ordinary workers.
  ctx->round = sync_round_;
  ctx->bcast_round = sync_bcast_round_;

  const std::string& comp =
      comp_config == "__default__" ? default_comp_ : comp_config;
  if (!comp.empty()) {
    BPS_CHECK_EQ(dtype, BPS_FLOAT32)
        << "lossy compressors operate on float32 gradients";
  }
  // Retained for the hot-replacement re-declare (RecoverServer): the
  // replacement server must rebuild each key's codec exactly.
  ctx->comp_config = comp;

  int esz = DtypeSize(dtype);
  int64_t per_part = std::max<int64_t>(1, partition_bytes_ / esz);
  int64_t nparts = (nelem + per_part - 1) / per_part;
  int ns = po_->num_servers();
  // Byte-balanced server assignment: each partition goes to the server
  // with the least bytes assigned so far (ties -> lowest index, so the
  // choice is deterministic). Every worker declares the same tensors in
  // the same order, so all workers compute the same mapping without any
  // coordination. Round-robin by (tid + i) was measured 22% hot at 8
  // servers on the ResNet-50 leaf distribution (tools/bench_scaling.py)
  // — and the hottest server's links gate the whole sync round.
  if (server_bytes_.size() != static_cast<size_t>(ns)) {
    server_bytes_.assign(ns, 0);
  }
  for (int64_t i = 0; i < nparts; ++i) {
    Part p;
    p.key = (ctx->id << 16) | i;
    int best = 0;
    for (int s = 1; s < ns; ++s) {
      if (server_bytes_[s] < server_bytes_[best]) best = s;
    }
    p.server_id = Postoffice::ServerId(best);
    p.offset = i * per_part;
    p.len = std::min(per_part, nelem - p.offset);
    server_bytes_[best] += p.len * esz;
    if (!comp.empty()) {
      p.comp = CreateCompressor(comp, p.len);
    }
    ctx->parts.push_back(std::move(p));
  }

  // Register every partition with its owning server (blocking, but only
  // on our own INIT_KEY requests — not on unrelated in-flight traffic).
  std::vector<int> reqs;
  for (auto& p : ctx->parts) {
    MsgHeader h{};
    h.cmd = CMD_INIT_KEY;
    h.key = p.key;
    h.dtype = dtype;
    h.arg0 = p.len * esz;
    reqs.push_back(kv_->Request(p.server_id, h, comp.data(),
                                static_cast<int64_t>(comp.size()), nullptr));
  }
  int64_t id = ctx->id;
  by_name_[name] = id;
  tensors_.push_back(std::move(ctx));
  lk.unlock();
  for (int rid : reqs) {
    BPS_CHECK_GE(rid, 0) << "declare of '" << name
                         << "' failed: a server connection is dead";
  }
  kv_->WaitRequests(reqs);
  return id;
}

int BytePSWorker::PushPull(int64_t tensor_id, void* ptr, int64_t nelem,
                           int dtype, bool average, bool async_mode) {
  std::unique_lock<std::mutex> lk(mu_);
  // Elastic membership gate (ISSUE 8): while a JOIN commits, new
  // rounds wait here so the acked counters stay final. Rounds already
  // issued are unaffected (they complete against the old roster). The
  // periodic wake lets a fleet fail-stop (no RESUME will ever come)
  // fall through instead of wedging at the gate.
  while (fleet_paused_ && !po_->ShuttingDown()) {
    cv_.wait_for(lk, std::chrono::milliseconds(100));
  }
  BPS_CHECK_GE(tensor_id, 0);
  BPS_CHECK(tensor_id < static_cast<int64_t>(tensors_.size()))
      << "undeclared tensor id " << tensor_id;
  TensorCtx* ctx = tensors_[tensor_id].get();
  BPS_CHECK_EQ(ctx->nelem, nelem) << "shape changed for " << ctx->name;
  BPS_CHECK_EQ(ctx->dtype, dtype) << "dtype changed for " << ctx->name;
  // Full round number on the wire (server: slot = version & 1). Parity
  // alone cannot tell round r from r+2, which matters once users keep
  // 3+ push_pull handles of one tensor in flight (deep pipelining).
  int version = static_cast<int>(ctx->round++);
  int handle_id = next_handle_++;
  auto handle = std::make_shared<Handle>(static_cast<int>(ctx->parts.size()));
  handles_[handle_id] = handle;
  lk.unlock();

  int esz = DtypeSize(dtype);
  double scale = average ? 1.0 / po_->num_workers() : 1.0;
  for (auto& part : ctx->parts) {
    Part* p = &part;
    Task task;
    task.priority = ctx->priority;
    task.key = p->key;
    task.bytes = p->len * esz;  // raw bytes charged against the credit
    task.server_id = p->server_id;
    // Fusible iff under the fusion threshold: a conv net's hundreds of
    // sub-partition-size tensors coalesce; full partitions keep their
    // own frames.
    task.fusible = fusion_bytes_ > 0 && task.bytes < fusion_bytes_;
    const int64_t t_enq = NowUs();
    task.run = [this, ctx, p, ptr, esz, version, scale, average,
                async_mode, handle, t_enq] {
      // Scheduled-queue wait (credit admission + priority) — the first
      // stage of the per-round breakdown (ISSUE 7).
      RoundStats::Get().Track(RS_QUEUE, version, NowUs() - t_enq);
      char* base = static_cast<char*>(ptr) + p->offset * esz;
      int64_t raw_len = p->len * esz;
      PushOp op;
      op.p = p;
      op.ctx = ctx;
      op.base = base;
      op.raw_len = raw_len;
      op.payload = base;
      op.payload_len = raw_len;
      op.flags = async_mode ? FLAG_ASYNC : 0;
      op.version = version;
      op.scale = scale;
      op.average = average;
      op.handle = handle;
      int64_t t0 = NowUs();
      if (p->comp) {
        p->comp->Compress(reinterpret_cast<const float*>(base), p->len,
                          &p->comp_buf);
        op.payload = p->comp_buf.data();
        op.payload_len = static_cast<int64_t>(p->comp_buf.size());
        op.flags |= FLAG_COMPRESSED;
        Record(p->key, "compress", t0);
        RoundStats::Get().Track(RS_COMP, version, NowUs() - t0);
        BPS_METRIC_HISTO_OBSERVE("bps_compress_us", NowUs() - t0);
        BPS_METRIC_COUNTER_ADD("bps_compress_in_bytes_total", raw_len);
        BPS_METRIC_COUNTER_ADD("bps_compress_out_bytes_total",
                               op.payload_len);
      } else if (QuantEligible(ctx, raw_len)) {
        // Block-quantized wire (ISSUE 6): fold the gradient into the
        // per-key EF residual, encode the residual as per-block int8,
        // and carry the rounding error into the next round. The encoded
        // qbuf is the wire payload — fused frames gather it, resend
        // snapshots copy it, and a recovery RE-PUSH ships the identical
        // bytes, which is what keeps the residual stream (and therefore
        // every later round) bit-identical across fault and fault-free
        // runs.
        if (p->qresidual.empty()) p->qresidual.assign(p->len, 0.0f);
        const float* g = reinterpret_cast<const float*>(base);
        for (int64_t i = 0; i < p->len; ++i) p->qresidual[i] += g[i];
        BPS_CHECK(BlockQuant::EncodeEF(p->qresidual.data(), p->len,
                                       quant_block_, &p->qbuf))
            << "non-finite gradient for key " << p->key
            << " — refusing to quantize garbage onto the wire";
        op.payload = p->qbuf.data();
        op.payload_len = static_cast<int64_t>(p->qbuf.size());
        op.flags |= FLAG_WIRE_QUANT;
        // Distinct span (ISSUE 7 satellite): quant encode time was
        // invisible under the shared "compress" label — the critical-
        // path report now attributes it as its own stage.
        Record(p->key, "qencode", t0);
        RoundStats::Get().Track(RS_COMP, version, NowUs() - t0);
        BPS_METRIC_COUNTER_ADD("bps_quant_bytes_on_wire_total",
                               op.payload_len);
        BPS_METRIC_COUNTER_ADD("bps_quant_bytes_saved_total",
                               raw_len - op.payload_len);
      }
      if (fusion_sink_ != nullptr) {
        // PushLoop is assembling a fused frame: stage, don't send.
        fusion_sink_->push_back(std::move(op));
        return;
      }
      SendPush(std::move(op));
    };
    BPS_METRIC_COUNTER_ADD("bps_partitions_enqueued_total", 1);
    BPS_METRIC_COUNTER_ADD("bps_enqueued_bytes_total", task.bytes);
    // Enqueue instant: the gap to this key's push span is scheduled-
    // queue wait (credit/priority), the first stage of the merge tool's
    // critical-path breakdown.
    if (trace_on_) {
      Trace::Get().Instant("enqueue", p->key, p->server_id, -1, 0,
                           version);
    }
    RoundStats::Get().Track(RS_ENQ, version);
    queue_->Push(std::move(task));
  }
  return handle_id;
}

void BytePSWorker::SendPush(PushOp op) {
  Part* p = op.p;
  TensorCtx* ctx = op.ctx;
  char* base = op.base;
  int64_t raw_len = op.raw_len;
  int flags = op.flags;
  int version = op.version;
  double scale = op.scale;
  bool average = op.average;
  std::shared_ptr<Handle> handle = op.handle;
  MsgHeader h{};
  h.cmd = CMD_PUSH;
  h.key = p->key;
  h.dtype = ctx->dtype;
  h.version = version;
  h.flags = flags;
  h.arg0 = raw_len;
  int64_t t_push = NowUs();
  // Wire-byte parity contract with the server's bps_recv_bytes_total
  // (docs/monitoring.md): both sides count CMD_PUSH payload bytes —
  // compressed size when a codec is on — so worker-side push totals
  // and server-side recv totals sum to the same number fleet-wide.
  BPS_METRIC_COUNTER_ADD("bps_push_bytes_total", op.payload_len);
  BPS_METRIC_COUNTER_ADD("bps_push_partitions_total", 1);
  RoundStats::Get().Track(RS_FRAME, version);
  const int64_t plen = op.payload_len;
  RecTrackPush(p, op);
  int push_rid = kv_->Request(
      p->server_id, h, op.payload, op.payload_len,
      [this, ctx, p, base, raw_len, version, scale, average, flags,
       handle, t_push, plen](Message&& ack) {
        if (ack.head.cmd == CMD_ERROR) {
          // Dead server: fail the handle now with the diagnostic
          // instead of blocking Wait until the heartbeat detector.
          RecClear(p);
          RoundStats::Get().Track(RS_DONE, version);
          FailHandle(handle, p->key, std::move(ack));
          queue_->ReleaseCredit(raw_len);
          return;
        }
        if (QueueDebug())
          fprintf(stderr, "[QDEBUG] push_ack key=%lld\n",
                  (long long)p->key);
        if (trace_on_) {
          // Close the push flow at the ack, inside the push span (the
          // span's end is recorded just after, so ts stays inside it):
          // the merged view stitches push span -> server sum -> ack.
          Trace::Get().Flow(TRACE_FLOW_IN, "req", p->key, NowUs(),
                            TraceFlowId(po_->my_id(), ack.head.req_id));
        }
        Record(p->key, "push", t_push, p->server_id, ack.head.req_id,
               version, plen, raw_len);
        BPS_METRIC_HISTO_OBSERVE("bps_push_us", NowUs() - t_push);
        // Per-round breakdown: push wall, and the server's own
        // decode+sum time reported back on the ack (arg0 — a field
        // CMD_PUSH_ACK never used; old servers leave it 0, which
        // degrades gracefully to "all wire"). wire_ack = push - sum.
        RoundStats::Get().Track(RS_PUSH, version, NowUs() - t_push,
                                plen);
        RoundStats::Get().Track(RS_SUM, version, ack.head.arg0);
        RecTrackAck(p);
        // Async: the ack carries the server's fleet-wide apply count
        // for this key as of OUR push; the pull resp carries it as
        // of the pull. Their difference is this pull's staleness.
        int64_t at_push = ack.head.arg1;
        // Push acknowledged -> issue the pull for the aggregate.
        MsgHeader ph{};
        ph.cmd = CMD_PULL;
        ph.key = p->key;
        ph.dtype = ctx->dtype;
        ph.version = version;
        // FLAG_WIRE_QUANT on a pull REQUESTS the server's re-quantized
        // aggregate (the reply leg of the quantized wire); the response
        // declares its own encoding, so a raw reply (reseeded slot,
        // async param) is still handled below.
        ph.flags = flags & (FLAG_ASYNC | FLAG_WIRE_QUANT);
        int64_t t_pull = NowUs();
        RoundStats::Get().Track(RS_FRAME, version);
        int pull_rid = kv_->Request(
            p->server_id, ph, nullptr, 0,
            [this, ctx, p, base, raw_len, version, scale, average,
             handle, t_pull, flags, at_push](Message&& resp) {
              if (resp.head.cmd == CMD_ERROR) {
                RecClear(p);
                RoundStats::Get().Track(RS_DONE, version);
                FailHandle(handle, p->key, std::move(resp));
                queue_->ReleaseCredit(raw_len);
                return;
              }
              if (QueueDebug())
                fprintf(stderr, "[QDEBUG] pull_resp key=%lld\n",
                        (long long)p->key);
              if (trace_on_) {
                Trace::Get().Flow(
                    TRACE_FLOW_IN, "reply", p->key, NowUs(),
                    TraceFlowId(po_->my_id(), resp.head.req_id));
              }
              Record(p->key, "pull", t_pull, p->server_id,
                     resp.head.req_id, version);
              BPS_METRIC_HISTO_OBSERVE("bps_pull_us", NowUs() - t_pull);
              RoundStats::Get().Track(
                  RS_PULL, version, NowUs() - t_pull,
                  static_cast<int64_t>(resp.payload.size()));
              BPS_METRIC_COUNTER_ADD(
                  "bps_pull_bytes_total",
                  static_cast<int64_t>(resp.payload.size()));
              if (flags & FLAG_ASYNC) {
                int64_t stale = resp.head.arg1 - at_push;
                if (stale >= 0) {  // peers' pushes applied between
                  stale_sum_.fetch_add(stale,
                                       std::memory_order_relaxed);
                  stale_n_.fetch_add(1, std::memory_order_relaxed);
                  int64_t cur =
                      stale_max_.load(std::memory_order_relaxed);
                  while (stale > cur &&
                         !stale_max_.compare_exchange_weak(
                             cur, stale, std::memory_order_relaxed)) {
                  }
                }
              }
              if (resp.head.flags & FLAG_COMPRESSED) {
                // Pull-leg compression: the server re-encoded the
                // aggregate with this key's codec (SURVEY.md §2.2
                // server symmetry); decode straight into the
                // caller's buffer.
                BPS_CHECK(p->comp)
                    << "compressed pull but no codec, key " << p->key;
                BPS_CHECK_EQ(resp.head.arg0, raw_len)
                    << "pull length mismatch for key " << p->key;
                int64_t t_dec = NowUs();
                p->comp->Decompress(
                    resp.payload.data(),
                    static_cast<int64_t>(resp.payload.size()),
                    reinterpret_cast<float*>(base), p->len);
                BPS_METRIC_HISTO_OBSERVE("bps_decompress_us",
                                         NowUs() - t_dec);
                RoundStats::Get().Track(RS_DEC, version,
                                        NowUs() - t_dec);
              } else if (resp.head.flags & FLAG_WIRE_QUANT) {
                // Quantized reply: dequantize the aggregate straight
                // into the caller's buffer.
                BPS_CHECK_EQ(resp.head.arg0, raw_len)
                    << "quant pull length mismatch for key " << p->key;
                int64_t t_dec = NowUs();
                BPS_CHECK(BlockQuant::Decode(
                    resp.payload.data(),
                    static_cast<int64_t>(resp.payload.size()),
                    reinterpret_cast<float*>(base), p->len))
                    << "malformed quantized pull reply for key "
                    << p->key;
                // qdecode span (ISSUE 7 satellite): the reply-leg
                // dequant was invisible in critical paths before.
                Record(p->key, "qdecode", t_dec, p->server_id,
                       resp.head.req_id, version,
                       static_cast<int64_t>(resp.payload.size()),
                       raw_len);
                RoundStats::Get().Track(RS_DEC, version,
                                        NowUs() - t_dec);
                BPS_METRIC_COUNTER_ADD(
                    "bps_quant_bytes_on_wire_total",
                    static_cast<int64_t>(resp.payload.size()));
                BPS_METRIC_COUNTER_ADD(
                    "bps_quant_bytes_saved_total",
                    raw_len - static_cast<int64_t>(resp.payload.size()));
              } else {
                BPS_CHECK_EQ(
                    static_cast<int64_t>(resp.payload.size()), raw_len)
                    << "pull length mismatch for key " << p->key;
                memcpy(base, resp.payload.data(), raw_len);
              }
              // Before Scale: the retained re-seed payload must be the
              // server's slot bytes (the unscaled sum).
              RecTrackDone(p, version, base, raw_len);
              RoundStats::Get().Track(RS_DONE, version);
              // Mean divisor: the ROUND's contributor count reported by
              // the server (arg1) — an elastic membership change
              // between issue and completion makes the captured fleet
              // size stale. Same-N fleets produce the identical double
              // (1/arg1 == the captured 1/num_workers); old servers
              // send 0 and keep the captured scale.
              double eff = scale;
              if (average && !(flags & FLAG_ASYNC) &&
                  resp.head.arg1 > 0) {
                eff = 1.0 / static_cast<double>(resp.head.arg1);
              }
              if (eff != 1.0) {
                CpuReducer::Scale(base, eff, raw_len, ctx->dtype);
              }
              queue_->ReleaseCredit(raw_len);
              if (handle->remaining.fetch_sub(1) == 1) {
                std::lock_guard<std::mutex> lk2(mu_);
                cv_.notify_all();
              }
            });
        if (trace_on_ && pull_rid >= 0) {
          // Open the pull's flow at its issue time (inside the pull
          // span); the server's s_reply span carries the "t" step.
          Trace::Get().Flow(TRACE_FLOW_OUT, "reply", p->key, t_pull,
                            TraceFlowId(po_->my_id(), pull_rid));
        }
      });
  RecTrackPushRid(p, push_rid);
  if (trace_on_ && push_rid >= 0) {
    // Open the push's flow at its issue time, inside the push span.
    Trace::Get().Flow(TRACE_FLOW_OUT, "req", p->key, t_push,
                      TraceFlowId(po_->my_id(), push_rid));
  }
}

// Validate a CMD_MULTI_* reply frame and return its sub-header table;
// *gathered points at the payload region behind the table.
static const SubHeader* ParseMultiReply(const Message& m, int expect_cmd,
                                        int expect_n,
                                        const char** gathered) {
  BPS_CHECK_EQ(m.head.cmd, expect_cmd)
      << "unexpected reply cmd for fused frame";
  BPS_CHECK_EQ(static_cast<int>(m.head.arg0), expect_n)
      << "fused reply count mismatch";
  int64_t table_bytes =
      static_cast<int64_t>(expect_n) * static_cast<int64_t>(sizeof(SubHeader));
  BPS_CHECK_GE(static_cast<int64_t>(m.payload.size()), table_bytes)
      << "fused reply shorter than its table";
  *gathered = m.payload.data() + table_bytes;
  return reinterpret_cast<const SubHeader*>(m.payload.data());
}

void BytePSWorker::SendFusedPush(int server_id, std::vector<PushOp> ops) {
  const int n = static_cast<int>(ops.size());
  auto batch = std::make_shared<std::vector<PushOp>>(std::move(ops));
  // shared_ptr table: the retry layer may resend this frame after
  // SendFusedPush returned, so the sub-header table must live until the
  // request settles (passed to RequestV as the lifetime hold). The
  // sub-payload segments already do — they point into caller buffers /
  // comp_bufs pinned until the handles complete.
  auto table_hold = std::make_shared<std::vector<SubHeader>>(
      static_cast<size_t>(n));
  std::vector<SubHeader>& table = *table_hold;
  std::vector<iovec> segs;
  segs.reserve(static_cast<size_t>(n) + 1);
  segs.push_back({table.data(),
                  static_cast<size_t>(n) * sizeof(SubHeader)});
  int64_t off = 0, wire_bytes = 0;
  for (int i = 0; i < n; ++i) {
    PushOp& op = (*batch)[i];
    SubHeader& s = table[i];
    s.key = op.p->key;
    s.cmd = CMD_PUSH;
    s.tenant = TenantId();  // one frame = one tenant (ISSUE 9)
    // Wire-dtype of the sub-payload: BPS_INT8 marks the block-quantized
    // encoding (FLAG_WIRE_QUANT rides in flags too — the engine-side
    // dequant keys on the flag, the table field is the wire contract
    // HandleMulti validates). Default 0 = raw float32/`dtype` bytes, so
    // a quant-off frame is byte-for-byte the pre-quant wire.
    s.wire_dtype = (op.flags & FLAG_WIRE_QUANT)
                       ? static_cast<int16_t>(BPS_INT8)
                       : static_cast<int16_t>(0);
    s.version = op.version;
    s.dtype = static_cast<int16_t>(op.ctx->dtype);
    s.flags = op.flags;
    s.arg0 = op.raw_len;
    s.offset = off;
    s.len = op.payload_len;
    off += op.payload_len;
    wire_bytes += op.payload_len;
    if (op.payload_len > 0) {
      segs.push_back({const_cast<void*>(op.payload),
                      static_cast<size_t>(op.payload_len)});
    }
  }
  MsgHeader h{};
  h.cmd = CMD_MULTI_PUSH;
  h.key = table[0].key;  // stripes/routes the batch like its lead key
  h.arg0 = n;
  // Parity contract unchanged under fusion: both sides count the SUB
  // payload bytes (the table is framing, like headers).
  BPS_METRIC_COUNTER_ADD("bps_push_bytes_total", wire_bytes);
  BPS_METRIC_COUNTER_ADD("bps_push_partitions_total", n);
  BPS_METRIC_COUNTER_ADD("bps_fused_msgs_total", 1);
  BPS_METRIC_HISTO_OBSERVE("bps_fusion_batch_keys", n);
  // One wire frame for the whole batch, charged to the lead sub-op's
  // round (frames may legally mix rounds across the duplicate-key
  // flush; the lead round is where the frame-count signal belongs).
  RoundStats::Get().Track(RS_FRAME, table[0].version, 0, /*fused=*/1);
  int64_t t_push = NowUs();
  if (recovery_on_) {
    std::lock_guard<std::mutex> lk(rec_mu_);
    for (PushOp& op : *batch) {
      op.p->rec_op = op;
      op.p->rec_stage = 1;
      op.p->rec_push_rid = -1;
    }
  }
  // The iovec list lives only until RequestV returns (it snapshots the
  // segments when retry is on); the table is pinned via the hold, the
  // payload segments via caller buffers / comp_bufs until the handles
  // settle.
  int push_rid = kv_->RequestV(
      server_id, h, segs.data(), static_cast<int>(segs.size()),
      [this, server_id, batch, t_push](Message&& ack) {
        OnFusedAck(server_id, batch, t_push, std::move(ack));
      },
      table_hold);
  if (trace_on_ && push_rid >= 0) {
    // One flow per fused frame, opened on the lead key's track; every
    // sub-key's s_sum span on the server steps the same flow (they all
    // share the frame's req_id).
    Trace::Get().Flow(TRACE_FLOW_OUT, "req", h.key, t_push,
                      TraceFlowId(po_->my_id(), push_rid));
  }
  if (recovery_on_) {
    // One req id covers the whole frame; each sub-op records it so the
    // recovery hook can tell "frame still in the resend queue" from
    // "frame settled, contributions live only in the dead server".
    std::lock_guard<std::mutex> lk(rec_mu_);
    for (PushOp& op : *batch) {
      if (op.p->rec_stage == 1) op.p->rec_push_rid = push_rid;
    }
  }
}

void BytePSWorker::OnFusedAck(
    int server_id, const std::shared_ptr<std::vector<PushOp>>& batch,
    int64_t t_push, Message&& ack) {
  if (ack.head.cmd == CMD_ERROR) {
    FailBatch(batch, std::move(ack));
    return;
  }
  const int n = static_cast<int>(batch->size());
  if (recovery_on_) {
    std::lock_guard<std::mutex> lk(rec_mu_);
    for (PushOp& op : *batch) op.p->rec_stage = 2;
  }
  const char* gathered = nullptr;
  const SubHeader* subs = ParseMultiReply(ack, CMD_MULTI_ACK, n, &gathered);
  if (trace_on_) {
    Trace::Get().Flow(TRACE_FLOW_IN, "req", (*batch)[0].p->key, NowUs(),
                      TraceFlowId(po_->my_id(), ack.head.req_id));
  }
  auto at_push = std::make_shared<std::vector<int64_t>>(
      static_cast<size_t>(n), 0);
  // shared_ptr table: pinned past this callback for the retry layer's
  // resends (same contract as SendFusedPush).
  auto table_hold = std::make_shared<std::vector<SubHeader>>(
      static_cast<size_t>(n));
  std::vector<SubHeader>& table = *table_hold;
  for (int i = 0; i < n; ++i) {
    PushOp& op = (*batch)[i];
    BPS_CHECK_EQ(subs[i].key, op.p->key) << "fused ack table out of order";
    if (QueueDebug())
      fprintf(stderr, "[QDEBUG] push_ack key=%lld\n",
              (long long)op.p->key);
    Record(op.p->key, "push", t_push, server_id, ack.head.req_id,
           op.version, op.payload_len, op.raw_len);
    BPS_METRIC_HISTO_OBSERVE("bps_push_us", NowUs() - t_push);
    // Per-round breakdown per sub-op: the batched ack carries each
    // sub-push's server decode+sum time in its sub-header arg0 (the
    // same contract as the single-frame ack).
    RoundStats::Get().Track(RS_PUSH, op.version, NowUs() - t_push,
                            op.payload_len);
    RoundStats::Get().Track(RS_SUM, op.version, subs[i].arg0);
    (*at_push)[i] = subs[i].arg1;  // async apply count as of our push
    SubHeader& s = table[i];
    s.key = op.p->key;
    s.cmd = CMD_PULL;
    s.tenant = TenantId();
    s.version = op.version;
    s.dtype = static_cast<int16_t>(op.ctx->dtype);
    // FLAG_WIRE_QUANT requests the re-quantized aggregate for keys this
    // worker pushed quantized (see the single-frame pull's comment);
    // wire_dtype mirrors it (the REQUESTED reply encoding — a pull has
    // no payload of its own).
    s.flags = op.flags & (FLAG_ASYNC | FLAG_WIRE_QUANT);
    s.wire_dtype = (s.flags & FLAG_WIRE_QUANT)
                       ? static_cast<int16_t>(BPS_INT8)
                       : static_cast<int16_t>(0);
  }
  // Whole batch acknowledged -> one fused pull for the aggregates.
  MsgHeader h{};
  h.cmd = CMD_MULTI_PULL;
  h.key = table[0].key;
  h.arg0 = n;
  iovec seg{table.data(), static_cast<size_t>(n) * sizeof(SubHeader)};
  int64_t t_pull = NowUs();
  RoundStats::Get().Track(RS_FRAME, table[0].version, 0, /*fused=*/1);
  int pull_rid = kv_->RequestV(
      server_id, h, &seg, 1,
      [this, batch, at_push, t_pull](Message&& resp) {
        OnFusedPullResp(batch, at_push, t_pull, std::move(resp));
      },
      table_hold);
  if (trace_on_ && pull_rid >= 0) {
    Trace::Get().Flow(TRACE_FLOW_OUT, "reply", h.key, t_pull,
                      TraceFlowId(po_->my_id(), pull_rid));
  }
}

void BytePSWorker::OnFusedPullResp(
    const std::shared_ptr<std::vector<PushOp>>& batch,
    const std::shared_ptr<std::vector<int64_t>>& at_push, int64_t t_pull,
    Message&& resp) {
  if (resp.head.cmd == CMD_ERROR) {
    FailBatch(batch, std::move(resp));
    return;
  }
  const int n = static_cast<int>(batch->size());
  const char* gathered = nullptr;
  const SubHeader* subs =
      ParseMultiReply(resp, CMD_MULTI_PULL_RESP, n, &gathered);
  if (trace_on_) {
    Trace::Get().Flow(TRACE_FLOW_IN, "reply", (*batch)[0].p->key,
                      NowUs(),
                      TraceFlowId(po_->my_id(), resp.head.req_id));
  }
  int64_t gathered_len = static_cast<int64_t>(resp.payload.size()) -
                         static_cast<int64_t>(n) *
                             static_cast<int64_t>(sizeof(SubHeader));
  for (int i = 0; i < n; ++i) {
    PushOp& op = (*batch)[i];
    const SubHeader& s = subs[i];
    BPS_CHECK_EQ(s.key, op.p->key) << "fused pull table out of order";
    BPS_CHECK(s.offset >= 0 && s.len >= 0 &&
              s.offset + s.len <= gathered_len)
        << "fused pull sub-payload out of range, key " << s.key;
    if (QueueDebug())
      fprintf(stderr, "[QDEBUG] pull_resp key=%lld\n",
              (long long)op.p->key);
    Record(op.p->key, "pull", t_pull, op.p->server_id,
           resp.head.req_id, op.version);
    BPS_METRIC_HISTO_OBSERVE("bps_pull_us", NowUs() - t_pull);
    RoundStats::Get().Track(RS_PULL, op.version, NowUs() - t_pull,
                            s.len);
    BPS_METRIC_COUNTER_ADD("bps_pull_bytes_total", s.len);
    if (op.flags & FLAG_ASYNC) {
      int64_t stale = s.arg1 - (*at_push)[i];
      if (stale >= 0) {  // peers' pushes applied between
        stale_sum_.fetch_add(stale, std::memory_order_relaxed);
        stale_n_.fetch_add(1, std::memory_order_relaxed);
        int64_t cur = stale_max_.load(std::memory_order_relaxed);
        while (stale > cur &&
               !stale_max_.compare_exchange_weak(
                   cur, stale, std::memory_order_relaxed)) {
        }
      }
    }
    const char* data = gathered + s.offset;
    if (s.flags & FLAG_COMPRESSED) {
      // Pull-leg compression, per sub-entry (server symmetry as in the
      // single-frame path).
      BPS_CHECK(op.p->comp)
          << "compressed pull but no codec, key " << op.p->key;
      BPS_CHECK_EQ(s.arg0, op.raw_len)
          << "pull length mismatch for key " << op.p->key;
      int64_t t_dec = NowUs();
      op.p->comp->Decompress(data, s.len,
                             reinterpret_cast<float*>(op.base), op.p->len);
      BPS_METRIC_HISTO_OBSERVE("bps_decompress_us", NowUs() - t_dec);
      RoundStats::Get().Track(RS_DEC, op.version, NowUs() - t_dec);
    } else if (s.flags & FLAG_WIRE_QUANT) {
      BPS_CHECK_EQ(s.arg0, op.raw_len)
          << "quant pull length mismatch for key " << op.p->key;
      int64_t t_dec = NowUs();
      BPS_CHECK(BlockQuant::Decode(data, s.len,
                                   reinterpret_cast<float*>(op.base),
                                   op.p->len))
          << "malformed quantized pull reply for key " << op.p->key;
      Record(op.p->key, "qdecode", t_dec, op.p->server_id,
             resp.head.req_id, op.version, s.len, op.raw_len);
      RoundStats::Get().Track(RS_DEC, op.version, NowUs() - t_dec);
      BPS_METRIC_COUNTER_ADD("bps_quant_bytes_on_wire_total", s.len);
      BPS_METRIC_COUNTER_ADD("bps_quant_bytes_saved_total",
                             op.raw_len - s.len);
    } else {
      BPS_CHECK_EQ(s.len, op.raw_len)
          << "pull length mismatch for key " << op.p->key;
      memcpy(op.base, data, static_cast<size_t>(op.raw_len));
    }
    RecTrackDone(op.p, op.version, op.base, op.raw_len);
    RoundStats::Get().Track(RS_DONE, op.version);
    // Same round-roster mean divisor as the single-frame path: the
    // batched reply carries each sub-entry's contributor count in its
    // sub-header arg1.
    double eff = op.scale;
    if (op.average && !(op.flags & FLAG_ASYNC) && s.arg1 > 0) {
      eff = 1.0 / static_cast<double>(s.arg1);
    }
    if (eff != 1.0) {
      CpuReducer::Scale(op.base, eff, op.raw_len, op.ctx->dtype);
    }
    queue_->ReleaseCredit(op.raw_len);
    if (op.handle->remaining.fetch_sub(1) == 1) {
      std::lock_guard<std::mutex> lk2(mu_);
      cv_.notify_all();
    }
  }
}

void BytePSWorker::FailBatch(
    const std::shared_ptr<std::vector<PushOp>>& batch, Message&& err) {
  for (PushOp& op : *batch) {
    Message e;
    e.head = err.head;
    e.payload.assign(err.payload.begin(), err.payload.end());
    RecClear(op.p);
    RoundStats::Get().Track(RS_DONE, op.version);
    FailHandle(op.handle, op.p->key, std::move(e));
    queue_->ReleaseCredit(op.raw_len);
  }
}

int BytePSWorker::Broadcast(int64_t tensor_id, void* ptr, int64_t nelem,
                            int dtype, int root_rank) {
  std::unique_lock<std::mutex> lk(mu_);
  // Same elastic membership gate as PushPull (ISSUE 8).
  while (fleet_paused_ && !po_->ShuttingDown()) {
    cv_.wait_for(lk, std::chrono::milliseconds(100));
  }
  BPS_CHECK(tensor_id >= 0 &&
            tensor_id < static_cast<int64_t>(tensors_.size()));
  TensorCtx* ctx = tensors_[tensor_id].get();
  BPS_CHECK_EQ(ctx->nelem, nelem);
  // All workers advance the round in lockstep (same call sequence), so a
  // non-root's pull for round r waits for the root's r-th push even when
  // the same tensor is re-broadcast later (weight re-sync).
  int bcast_version = static_cast<int>(ctx->bcast_round++);
  int handle_id = next_handle_++;
  auto handle = std::make_shared<Handle>(static_cast<int>(ctx->parts.size()));
  handles_[handle_id] = handle;
  lk.unlock();

  bool is_root = po_->my_worker_rank() == root_rank;
  int esz = DtypeSize(dtype);
  for (auto& part : ctx->parts) {
    Part* p = &part;
    char* base = static_cast<char*>(ptr) + p->offset * esz;
    int64_t raw_len = p->len * esz;
    MsgHeader h{};
    h.cmd = is_root ? CMD_BCAST_PUSH : CMD_BCAST_PULL;
    h.key = p->key;
    h.dtype = dtype;
    h.version = bcast_version;
    auto done = [this, p, base, raw_len, is_root, handle](Message&& resp) {
      if (resp.head.cmd == CMD_ERROR) {
        FailHandle(handle, p->key, std::move(resp));
        return;
      }
      if (!is_root) {
        BPS_CHECK_EQ(static_cast<int64_t>(resp.payload.size()), raw_len);
        memcpy(base, resp.payload.data(), raw_len);
      }
      if (handle->remaining.fetch_sub(1) == 1) {
        std::lock_guard<std::mutex> lk2(mu_);
        cv_.notify_all();
      }
    };
    if (is_root) {
      kv_->Request(p->server_id, h, base, raw_len, done);
    } else {
      kv_->Request(p->server_id, h, nullptr, 0, done);
    }
  }
  return handle_id;
}

void BytePSWorker::FailHandle(const std::shared_ptr<Handle>& handle,
                              int64_t key, Message&& err) {
  std::string why(err.payload.data(),
                  err.payload.data() + err.payload.size());
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (!handle->failed.load()) {
      handle->error = "key " + std::to_string(key) + ": " + why;
      handle->failed.store(true);
    }
  }
  // Same order as the completion paths: decrement FIRST, then notify —
  // notifying before the decrement is a lost wakeup (the waiter's
  // predicate still sees the old count and sleeps forever).
  if (handle->remaining.fetch_sub(1) == 1) {
    std::lock_guard<std::mutex> lk(mu_);
    cv_.notify_all();
  }
  BPS_LOG(WARNING) << "request failed for key " << key << ": " << why;
}

int BytePSWorker::Wait(int handle_id) {
  std::shared_ptr<Handle> h;
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = handles_.find(handle_id);
    if (it == handles_.end()) return 0;  // already reaped
    h = it->second;
  }
  std::unique_lock<std::mutex> lk(mu_);
  // Even when the handle has FAILED, wait for every partition to settle
  // (complete or fail): returning early would let still-in-flight
  // callbacks memcpy into — and queued push tasks read from — the
  // caller's buffer after the caller saw the error and freed it. Every
  // partition settles promptly: live-server partitions complete, dead-
  // server partitions get CMD_ERROR from the peer-lost scan or their
  // send failure (each path decrements `remaining`).
  cv_.wait(lk, [&] { return h->remaining.load() == 0; });
  handles_.erase(handle_id);
  if (h->failed.load()) {
    last_error_ = h->error;
    return -1;
  }
  return 0;
}

std::string BytePSWorker::LastError() {
  std::lock_guard<std::mutex> lk(mu_);
  return last_error_;
}

int BytePSWorker::Poll(int handle_id) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = handles_.find(handle_id);
  if (it == handles_.end()) return 1;
  // Failed or not, a handle is complete only when every partition has
  // settled — reporting completion earlier would tell a poll-driven
  // caller the buffer is theirs while in-flight callbacks still write
  // into it (same invariant as Wait).
  if (it->second->remaining.load() != 0) return 0;
  if (it->second->failed.load()) {
    // Tri-state: -1 = settled but FAILED. NOT reaped — the follow-up
    // Wait must still find the handle to surface the error string; the
    // FFI poll wrapper maps -1 to that Wait so poll-only consumers
    // neither leak the entry nor mistake a dead-peer failure for
    // success.
    return -1;
  }
  // Reap on completion so poll-only consumers don't leak handle entries.
  handles_.erase(it);
  return 1;
}

}  // namespace bps
