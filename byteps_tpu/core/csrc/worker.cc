#include "worker.h"

#include <chrono>
#include <cstring>

#include "cpu_reducer.h"
#include "logging.h"
#include "metrics.h"

namespace bps {

int64_t NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void BytePSWorker::Start(Postoffice* po, KVWorker* kv, int64_t partition_bytes,
                         int64_t credit_bytes, std::string default_comp,
                         bool trace_on) {
  po_ = po;
  kv_ = kv;
  partition_bytes_ = partition_bytes;
  default_comp_ = std::move(default_comp);
  trace_on_ = trace_on;
  // Pre-register the worker-side metric catalog: every stage's series
  // exists from zero on the /metrics page (an idle or compression-less
  // worker omits nothing — scrapers sum and ratio these fleet-wide).
  Metrics::Get().Counter("bps_partitions_enqueued_total");
  Metrics::Get().Counter("bps_enqueued_bytes_total");
  Metrics::Get().Counter("bps_push_bytes_total");
  Metrics::Get().Counter("bps_push_partitions_total");
  Metrics::Get().Counter("bps_pull_bytes_total");
  Metrics::Get().Histogram("bps_push_us");
  Metrics::Get().Histogram("bps_pull_us");
  // Reference semantics: BYTEPS_SCHEDULING_CREDIT is an in-flight BYTE
  // budget. 0 = auto: four full partitions' worth. A value under 1024
  // can only be a legacy partition count (the reference default was 4;
  // no real byte budget is smaller than 1 KiB, and no in-flight count
  // reaches 1024) — honouring it as bytes would serialise every push,
  // so interpret it AS a partition count (credit × partition_bytes) so
  // legacy env users keep their intended overlap. Values >= 1024 are
  // honoured as bytes, so small genuine budgets stay expressible.
  // This is the SINGLE conversion point: the Python config layer warns
  // about sub-1024 values but passes them through unchanged.
  if (credit_bytes > 0 && credit_bytes < 1024) {
    BPS_LOG(WARNING) << "BYTEPS_SCHEDULING_CREDIT=" << credit_bytes
                     << " looks like a legacy in-flight partition count; "
                     << "interpreting as " << credit_bytes << " x "
                     << partition_bytes << " bytes";
    credit_bytes = credit_bytes * partition_bytes;
  }
  if (credit_bytes <= 0) credit_bytes = 4 * partition_bytes;
  queue_ = std::make_unique<ScheduledQueue>(credit_bytes);
  // Sender parallelism: the van's writev blocks once a connection's
  // SNDBUF fills, and with ONE push thread a full stripe head-of-line
  // blocks sends to every OTHER stripe/server (exposed by the BDP
  // sweep: N stripes measured one stripe's goodput). Concurrent pops
  // are order-safe: a key's next-round push cannot be enqueued before
  // its previous pull completed, so two tasks for the same key never
  // coexist, and the van's per-fd lock serialises same-connection
  // writes. Default: match the stripe count (capped), 1 when unstriped
  // (the single-thread wire order PS_VERBOSE users expect).
  int push_threads = 0;
  if (const char* pt = getenv("BYTEPS_PUSH_THREADS")) {
    push_threads = atoi(pt);
  }
  if (push_threads <= 0) {
    int streams = 1;
    if (const char* sv = getenv("BYTEPS_VAN_STREAMS")) {
      streams = atoi(sv);
    }
    push_threads = streams > 1 ? std::min(streams, 8) : 1;
  }
  for (int i = 0; i < push_threads; ++i) {
    push_threads_.emplace_back([this] { PushLoop(); });
  }
}

void BytePSWorker::Stop() {
  if (queue_) queue_->Stop();
  for (auto& t : push_threads_) {
    if (t.joinable()) t.join();
  }
  push_threads_.clear();
}

void BytePSWorker::PushLoop() {
  Task t;
  while (queue_->Pop(&t)) t.run();
}

void BytePSWorker::Record(int64_t key, const char* stage, int64_t start_us) {
  if (!trace_on_) return;
  TraceEvent ev{};
  ev.key = key;
  snprintf(ev.stage, sizeof(ev.stage), "%s", stage);
  ev.ts_us = start_us;
  ev.dur_us = NowUs() - start_us;
  std::lock_guard<std::mutex> lk(trace_mu_);
  trace_.push_back(ev);
}

std::vector<TraceEvent> BytePSWorker::DrainTrace() {
  std::lock_guard<std::mutex> lk(trace_mu_);
  std::vector<TraceEvent> out;
  out.swap(trace_);
  return out;
}

int64_t BytePSWorker::Declare(const std::string& name, int64_t nelem,
                              int dtype, const std::string& comp_config) {
  std::unique_lock<std::mutex> lk(mu_);
  auto it = by_name_.find(name);
  if (it != by_name_.end()) {
    auto& t = *tensors_[it->second];
    BPS_CHECK_EQ(t.nelem, nelem) << "tensor " << name << " re-declared";
    BPS_CHECK_EQ(t.dtype, dtype) << "tensor " << name << " re-declared";
    return t.id;
  }
  auto ctx = std::make_unique<TensorCtx>();
  ctx->id = static_cast<int64_t>(tensors_.size());
  ctx->name = name;
  ctx->nelem = nelem;
  ctx->dtype = dtype;
  ctx->priority = -static_cast<int>(ctx->id);  // declaration-order priority

  const std::string& comp =
      comp_config == "__default__" ? default_comp_ : comp_config;
  if (!comp.empty()) {
    BPS_CHECK_EQ(dtype, BPS_FLOAT32)
        << "lossy compressors operate on float32 gradients";
  }

  int esz = DtypeSize(dtype);
  int64_t per_part = std::max<int64_t>(1, partition_bytes_ / esz);
  int64_t nparts = (nelem + per_part - 1) / per_part;
  int ns = po_->num_servers();
  // Byte-balanced server assignment: each partition goes to the server
  // with the least bytes assigned so far (ties -> lowest index, so the
  // choice is deterministic). Every worker declares the same tensors in
  // the same order, so all workers compute the same mapping without any
  // coordination. Round-robin by (tid + i) was measured 22% hot at 8
  // servers on the ResNet-50 leaf distribution (tools/bench_scaling.py)
  // — and the hottest server's links gate the whole sync round.
  if (server_bytes_.size() != static_cast<size_t>(ns)) {
    server_bytes_.assign(ns, 0);
  }
  for (int64_t i = 0; i < nparts; ++i) {
    Part p;
    p.key = (ctx->id << 16) | i;
    int best = 0;
    for (int s = 1; s < ns; ++s) {
      if (server_bytes_[s] < server_bytes_[best]) best = s;
    }
    p.server_id = Postoffice::ServerId(best);
    p.offset = i * per_part;
    p.len = std::min(per_part, nelem - p.offset);
    server_bytes_[best] += p.len * esz;
    if (!comp.empty()) {
      p.comp = CreateCompressor(comp, p.len);
    }
    ctx->parts.push_back(std::move(p));
  }

  // Register every partition with its owning server (blocking, but only
  // on our own INIT_KEY requests — not on unrelated in-flight traffic).
  std::vector<int> reqs;
  for (auto& p : ctx->parts) {
    MsgHeader h{};
    h.cmd = CMD_INIT_KEY;
    h.key = p.key;
    h.dtype = dtype;
    h.arg0 = p.len * esz;
    reqs.push_back(kv_->Request(p.server_id, h, comp.data(),
                                static_cast<int64_t>(comp.size()), nullptr));
  }
  int64_t id = ctx->id;
  by_name_[name] = id;
  tensors_.push_back(std::move(ctx));
  lk.unlock();
  for (int rid : reqs) {
    BPS_CHECK_GE(rid, 0) << "declare of '" << name
                         << "' failed: a server connection is dead";
  }
  kv_->WaitRequests(reqs);
  return id;
}

int BytePSWorker::PushPull(int64_t tensor_id, void* ptr, int64_t nelem,
                           int dtype, bool average, bool async_mode) {
  std::unique_lock<std::mutex> lk(mu_);
  BPS_CHECK_GE(tensor_id, 0);
  BPS_CHECK(tensor_id < static_cast<int64_t>(tensors_.size()))
      << "undeclared tensor id " << tensor_id;
  TensorCtx* ctx = tensors_[tensor_id].get();
  BPS_CHECK_EQ(ctx->nelem, nelem) << "shape changed for " << ctx->name;
  BPS_CHECK_EQ(ctx->dtype, dtype) << "dtype changed for " << ctx->name;
  // Full round number on the wire (server: slot = version & 1). Parity
  // alone cannot tell round r from r+2, which matters once users keep
  // 3+ push_pull handles of one tensor in flight (deep pipelining).
  int version = static_cast<int>(ctx->round++);
  int handle_id = next_handle_++;
  auto handle = std::make_shared<Handle>(static_cast<int>(ctx->parts.size()));
  handles_[handle_id] = handle;
  lk.unlock();

  int esz = DtypeSize(dtype);
  double scale = average ? 1.0 / po_->num_workers() : 1.0;
  for (auto& part : ctx->parts) {
    Part* p = &part;
    Task task;
    task.priority = ctx->priority;
    task.key = p->key;
    task.bytes = p->len * esz;  // raw bytes charged against the credit
    task.run = [this, ctx, p, ptr, esz, version, scale, async_mode, handle] {
      char* base = static_cast<char*>(ptr) + p->offset * esz;
      int64_t raw_len = p->len * esz;
      const void* payload = base;
      int64_t payload_len = raw_len;
      int flags = async_mode ? FLAG_ASYNC : 0;
      int64_t t0 = NowUs();
      if (p->comp) {
        p->comp->Compress(reinterpret_cast<const float*>(base), p->len,
                          &p->comp_buf);
        payload = p->comp_buf.data();
        payload_len = static_cast<int64_t>(p->comp_buf.size());
        flags |= FLAG_COMPRESSED;
        Record(p->key, "compress", t0);
        BPS_METRIC_HISTO_OBSERVE("bps_compress_us", NowUs() - t0);
        BPS_METRIC_COUNTER_ADD("bps_compress_in_bytes_total", raw_len);
        BPS_METRIC_COUNTER_ADD("bps_compress_out_bytes_total", payload_len);
      }
      MsgHeader h{};
      h.cmd = CMD_PUSH;
      h.key = p->key;
      h.dtype = ctx->dtype;
      h.version = version;
      h.flags = flags;
      h.arg0 = raw_len;
      int64_t t_push = NowUs();
      // Wire-byte parity contract with the server's bps_recv_bytes_total
      // (docs/monitoring.md): both sides count CMD_PUSH payload bytes —
      // compressed size when a codec is on — so worker-side push totals
      // and server-side recv totals sum to the same number fleet-wide.
      BPS_METRIC_COUNTER_ADD("bps_push_bytes_total", payload_len);
      BPS_METRIC_COUNTER_ADD("bps_push_partitions_total", 1);
      kv_->Request(
          p->server_id, h, payload, payload_len,
          [this, ctx, p, base, raw_len, version, scale, flags, handle,
           t_push](Message&& ack) {
            if (ack.head.cmd == CMD_ERROR) {
              // Dead server: fail the handle now with the diagnostic
              // instead of blocking Wait until the heartbeat detector.
              FailHandle(handle, p->key, std::move(ack));
              queue_->ReleaseCredit(raw_len);
              return;
            }
            if (QueueDebug())
              fprintf(stderr, "[QDEBUG] push_ack key=%lld\n",
                      (long long)p->key);
            Record(p->key, "push", t_push);
            BPS_METRIC_HISTO_OBSERVE("bps_push_us", NowUs() - t_push);
            // Async: the ack carries the server's fleet-wide apply count
            // for this key as of OUR push; the pull resp carries it as
            // of the pull. Their difference is this pull's staleness.
            int64_t at_push = ack.head.arg1;
            // Push acknowledged -> issue the pull for the aggregate.
            MsgHeader ph{};
            ph.cmd = CMD_PULL;
            ph.key = p->key;
            ph.dtype = ctx->dtype;
            ph.version = version;
            ph.flags = flags & FLAG_ASYNC;
            int64_t t_pull = NowUs();
            kv_->Request(
                p->server_id, ph, nullptr, 0,
                [this, ctx, p, base, raw_len, scale, handle, t_pull,
                 flags, at_push](Message&& resp) {
                  if (resp.head.cmd == CMD_ERROR) {
                    FailHandle(handle, p->key, std::move(resp));
                    queue_->ReleaseCredit(raw_len);
                    return;
                  }
                  if (QueueDebug())
                    fprintf(stderr, "[QDEBUG] pull_resp key=%lld\n",
                            (long long)p->key);
                  Record(p->key, "pull", t_pull);
                  BPS_METRIC_HISTO_OBSERVE("bps_pull_us", NowUs() - t_pull);
                  BPS_METRIC_COUNTER_ADD(
                      "bps_pull_bytes_total",
                      static_cast<int64_t>(resp.payload.size()));
                  if (flags & FLAG_ASYNC) {
                    int64_t stale = resp.head.arg1 - at_push;
                    if (stale >= 0) {  // peers' pushes applied between
                      stale_sum_.fetch_add(stale,
                                           std::memory_order_relaxed);
                      stale_n_.fetch_add(1, std::memory_order_relaxed);
                      int64_t cur =
                          stale_max_.load(std::memory_order_relaxed);
                      while (stale > cur &&
                             !stale_max_.compare_exchange_weak(
                                 cur, stale, std::memory_order_relaxed)) {
                      }
                    }
                  }
                  if (resp.head.flags & FLAG_COMPRESSED) {
                    // Pull-leg compression: the server re-encoded the
                    // aggregate with this key's codec (SURVEY.md §2.2
                    // server symmetry); decode straight into the
                    // caller's buffer.
                    BPS_CHECK(p->comp)
                        << "compressed pull but no codec, key " << p->key;
                    BPS_CHECK_EQ(resp.head.arg0, raw_len)
                        << "pull length mismatch for key " << p->key;
                    int64_t t_dec = NowUs();
                    p->comp->Decompress(
                        resp.payload.data(),
                        static_cast<int64_t>(resp.payload.size()),
                        reinterpret_cast<float*>(base), p->len);
                    BPS_METRIC_HISTO_OBSERVE("bps_decompress_us",
                                             NowUs() - t_dec);
                  } else {
                    BPS_CHECK_EQ(
                        static_cast<int64_t>(resp.payload.size()), raw_len)
                        << "pull length mismatch for key " << p->key;
                    memcpy(base, resp.payload.data(), raw_len);
                  }
                  if (scale != 1.0) {
                    CpuReducer::Scale(base, scale, raw_len, ctx->dtype);
                  }
                  queue_->ReleaseCredit(raw_len);
                  if (handle->remaining.fetch_sub(1) == 1) {
                    std::lock_guard<std::mutex> lk2(mu_);
                    cv_.notify_all();
                  }
                });
          });
    };
    BPS_METRIC_COUNTER_ADD("bps_partitions_enqueued_total", 1);
    BPS_METRIC_COUNTER_ADD("bps_enqueued_bytes_total", task.bytes);
    queue_->Push(std::move(task));
  }
  return handle_id;
}

int BytePSWorker::Broadcast(int64_t tensor_id, void* ptr, int64_t nelem,
                            int dtype, int root_rank) {
  std::unique_lock<std::mutex> lk(mu_);
  BPS_CHECK(tensor_id >= 0 &&
            tensor_id < static_cast<int64_t>(tensors_.size()));
  TensorCtx* ctx = tensors_[tensor_id].get();
  BPS_CHECK_EQ(ctx->nelem, nelem);
  // All workers advance the round in lockstep (same call sequence), so a
  // non-root's pull for round r waits for the root's r-th push even when
  // the same tensor is re-broadcast later (weight re-sync).
  int bcast_version = static_cast<int>(ctx->bcast_round++);
  int handle_id = next_handle_++;
  auto handle = std::make_shared<Handle>(static_cast<int>(ctx->parts.size()));
  handles_[handle_id] = handle;
  lk.unlock();

  bool is_root = po_->my_worker_rank() == root_rank;
  int esz = DtypeSize(dtype);
  for (auto& part : ctx->parts) {
    Part* p = &part;
    char* base = static_cast<char*>(ptr) + p->offset * esz;
    int64_t raw_len = p->len * esz;
    MsgHeader h{};
    h.cmd = is_root ? CMD_BCAST_PUSH : CMD_BCAST_PULL;
    h.key = p->key;
    h.dtype = dtype;
    h.version = bcast_version;
    auto done = [this, p, base, raw_len, is_root, handle](Message&& resp) {
      if (resp.head.cmd == CMD_ERROR) {
        FailHandle(handle, p->key, std::move(resp));
        return;
      }
      if (!is_root) {
        BPS_CHECK_EQ(static_cast<int64_t>(resp.payload.size()), raw_len);
        memcpy(base, resp.payload.data(), raw_len);
      }
      if (handle->remaining.fetch_sub(1) == 1) {
        std::lock_guard<std::mutex> lk2(mu_);
        cv_.notify_all();
      }
    };
    if (is_root) {
      kv_->Request(p->server_id, h, base, raw_len, done);
    } else {
      kv_->Request(p->server_id, h, nullptr, 0, done);
    }
  }
  return handle_id;
}

void BytePSWorker::FailHandle(const std::shared_ptr<Handle>& handle,
                              int64_t key, Message&& err) {
  std::string why(err.payload.data(),
                  err.payload.data() + err.payload.size());
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (!handle->failed.load()) {
      handle->error = "key " + std::to_string(key) + ": " + why;
      handle->failed.store(true);
    }
  }
  // Same order as the completion paths: decrement FIRST, then notify —
  // notifying before the decrement is a lost wakeup (the waiter's
  // predicate still sees the old count and sleeps forever).
  if (handle->remaining.fetch_sub(1) == 1) {
    std::lock_guard<std::mutex> lk(mu_);
    cv_.notify_all();
  }
  BPS_LOG(WARNING) << "request failed for key " << key << ": " << why;
}

int BytePSWorker::Wait(int handle_id) {
  std::shared_ptr<Handle> h;
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = handles_.find(handle_id);
    if (it == handles_.end()) return 0;  // already reaped
    h = it->second;
  }
  std::unique_lock<std::mutex> lk(mu_);
  // Even when the handle has FAILED, wait for every partition to settle
  // (complete or fail): returning early would let still-in-flight
  // callbacks memcpy into — and queued push tasks read from — the
  // caller's buffer after the caller saw the error and freed it. Every
  // partition settles promptly: live-server partitions complete, dead-
  // server partitions get CMD_ERROR from the peer-lost scan or their
  // send failure (each path decrements `remaining`).
  cv_.wait(lk, [&] { return h->remaining.load() == 0; });
  handles_.erase(handle_id);
  if (h->failed.load()) {
    last_error_ = h->error;
    return -1;
  }
  return 0;
}

std::string BytePSWorker::LastError() {
  std::lock_guard<std::mutex> lk(mu_);
  return last_error_;
}

int BytePSWorker::Poll(int handle_id) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = handles_.find(handle_id);
  if (it == handles_.end()) return 1;
  // Failed or not, a handle is complete only when every partition has
  // settled — reporting completion earlier would tell a poll-driven
  // caller the buffer is theirs while in-flight callbacks still write
  // into it (same invariant as Wait).
  if (it->second->remaining.load() != 0) return 0;
  if (it->second->failed.load()) {
    // Tri-state: -1 = settled but FAILED. NOT reaped — the follow-up
    // Wait must still find the handle to surface the error string; the
    // FFI poll wrapper maps -1 to that Wait so poll-only consumers
    // neither leak the entry nor mistake a dead-peer failure for
    // success.
    return -1;
  }
  // Reap on completion so poll-only consumers don't leak handle entries.
  handles_.erase(it);
  return 1;
}

}  // namespace bps
