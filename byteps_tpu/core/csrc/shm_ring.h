// Shared-memory byte-ring transport segment for the van's intra-host data
// path.
//
// Capability parity: this is byteps_tpu's second van transport, playing the
// role the reference's non-TCP vans play (ps-lite ZMQVan's ipc:// transport
// and rdma_van.h's zero-copy path — SURVEY.md §2.4): co-located
// worker/server pairs should not pay the kernel TCP stack for every
// gradient byte. Fresh design, no ZMQ/verbs: one POSIX shm segment per
// connection holding two single-producer/single-consumer byte rings (one
// per direction), lock-free indices, Linux futex wakeups shared across
// processes. The existing framed-message format flows through unchanged —
// a frame is simply written into the ring instead of a socket — so
// PS_VERBOSE tracing, wire counters, and every upper layer are transport
// agnostic.
//
// Concurrency contract: exactly one producer thread per direction (the
// van's per-fd send mutex already serialises senders) and one consumer
// (the connection's shm recv thread). Indices are free-running uint32
// byte counts (ring capacity < 4 GB); `tail - head` is the unread span,
// valid across wraparound by unsigned arithmetic.
#pragma once

#include <linux/futex.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstring>

namespace bps {

inline void FutexWait(std::atomic<uint32_t>* addr, uint32_t expected) {
  // Bounded: re-checks closed/progress on expiry. The Dekker waiter
  // flags make wakes reliable, so this is pure insurance — short enough
  // that even a pathological missed wake costs single-digit ms, long
  // enough that an idle connection burns ~200 wakeups/s of pure kernel
  // time at most.
  timespec ts{0, 5 * 1000 * 1000};
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAIT,
          expected, &ts, nullptr, 0);
}

inline void FutexWake(std::atomic<uint32_t>* addr) {
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAKE,
          INT32_MAX, nullptr, nullptr, 0);
}

// One direction of the duplex connection. Cache-line separation keeps the
// producer's tail store from false-sharing the consumer's head.
struct alignas(64) ShmDir {
  std::atomic<uint32_t> tail{0};    // bytes published by the producer
  std::atomic<uint32_t> c_wait{0};  // consumer is in (or entering) FutexWait
  char pad0[56];
  std::atomic<uint32_t> head{0};    // bytes consumed by the consumer
  std::atomic<uint32_t> p_wait{0};  // producer is in (or entering) FutexWait
  char pad1[56];
  std::atomic<uint32_t> closed{0};  // either side tearing the conn down
  char pad2[60];
};

constexpr uint32_t kShmMagic = 0x62707331;  // "bps1"

struct ShmHeader {
  uint32_t magic;
  uint32_t ring_bytes;  // per-direction data capacity
  ShmDir dir[2];        // [0] connector->acceptor, [1] acceptor->connector
  // Data follows: dir[0]'s ring, then dir[1]'s ring.
};

inline char* ShmRingData(ShmHeader* h, int dir) {
  return reinterpret_cast<char*>(h + 1) +
         static_cast<size_t>(dir) * h->ring_bytes;
}

// Blocking stream write: copies `len` bytes into the ring, chunking at the
// wrap point and whenever the ring fills (so messages larger than the ring
// stream through it, like a socket buffer). Returns false if the
// connection closed mid-write.
inline bool ShmStreamWrite(ShmDir* d, char* ring, uint32_t cap,
                           const void* src, size_t len) {
  const char* p = static_cast<const char*>(src);
  uint32_t tail = d->tail.load(std::memory_order_relaxed);
  while (len > 0) {
    uint32_t head = d->head.load(std::memory_order_acquire);
    uint32_t free_b = cap - (tail - head);
    if (free_b == 0) {
      if (d->closed.load(std::memory_order_relaxed)) return false;
      // Brief spin (common case: consumer is actively draining), then a
      // bounded futex sleep on head. The p_wait flag publishes the
      // sleep intent with seq_cst so the consumer's wake check cannot
      // reorder past its head store (Dekker pattern).
      for (int i = 0; i < 4096 && d->head.load(std::memory_order_acquire)
                                      == head; ++i) {
      }
      if (d->head.load(std::memory_order_acquire) == head) {
        d->p_wait.store(1, std::memory_order_seq_cst);
        if (d->head.load(std::memory_order_seq_cst) == head)
          FutexWait(&d->head, head);
        d->p_wait.store(0, std::memory_order_relaxed);
      }
      continue;
    }
    uint32_t off = tail % cap;
    uint32_t chunk = free_b;
    if (chunk > cap - off) chunk = cap - off;  // contiguous to wrap point
    if (chunk > len) chunk = static_cast<uint32_t>(len);
    memcpy(ring + off, p, chunk);
    p += chunk;
    len -= chunk;
    // Wake only when the consumer could be waiting (it saw an empty
    // ring, or its c_wait flag is up): an unconditional wake per chunk
    // would put syscalls back on the hot path this transport removes.
    // seq_cst on the tail store vs the c_wait load pairs with the
    // consumer's Dekker sequence; the bounded FutexWait backstops it.
    bool was_empty = (tail == head);
    tail += chunk;
    d->tail.store(tail, std::memory_order_seq_cst);
    if (was_empty || d->c_wait.load(std::memory_order_seq_cst))
      FutexWake(&d->tail);
  }
  return true;
}

// Blocking stream read: fills `dst` with exactly `len` bytes. Returns
// false once the connection is closed AND the requested bytes are not
// fully available (a torn trailing frame at teardown is dropped — the
// connection is dying and the upper layer fails outstanding requests via
// the disconnect handler, same as a mid-frame TCP EOF).
inline bool ShmStreamRead(ShmDir* d, char* ring, uint32_t cap, void* dst,
                          size_t len) {
  char* p = static_cast<char*>(dst);
  uint32_t head = d->head.load(std::memory_order_relaxed);
  while (len > 0) {
    uint32_t tail = d->tail.load(std::memory_order_acquire);
    uint32_t avail = tail - head;
    if (avail == 0) {
      if (d->closed.load(std::memory_order_relaxed)) return false;
      for (int i = 0; i < 4096 && d->tail.load(std::memory_order_acquire)
                                      == tail; ++i) {
      }
      if (d->tail.load(std::memory_order_acquire) == tail) {
        d->c_wait.store(1, std::memory_order_seq_cst);
        if (d->tail.load(std::memory_order_seq_cst) == tail)
          FutexWait(&d->tail, tail);
        d->c_wait.store(0, std::memory_order_relaxed);
      }
      continue;
    }
    uint32_t off = head % cap;
    uint32_t chunk = avail;
    if (chunk > cap - off) chunk = cap - off;
    if (chunk > len) chunk = static_cast<uint32_t>(len);
    memcpy(p, ring + off, chunk);
    p += chunk;
    len -= chunk;
    // Mirror of the producer's conditional wake: the producer can only
    // be waiting when it observed a FULL ring or has p_wait up.
    bool was_full = (tail - head == cap);
    head += chunk;
    d->head.store(head, std::memory_order_seq_cst);
    if (was_full || d->p_wait.load(std::memory_order_seq_cst))
      FutexWake(&d->head);
  }
  return true;
}

// Mark both directions closed and wake any waiter (producer blocked on a
// full ring, consumer on an empty one). Idempotent; callable from either
// process.
inline void ShmCloseBoth(ShmHeader* h) {
  for (int i = 0; i < 2; ++i) {
    h->dir[i].closed.store(1, std::memory_order_release);
    FutexWake(&h->dir[i].tail);
    FutexWake(&h->dir[i].head);
  }
}

}  // namespace bps
