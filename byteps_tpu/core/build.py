"""Build the byteps_tpu C++ core into libbyteps_core.so.

Run as ``python -m byteps_tpu.core.build`` (reference analogue: the
setup.py c_lib extension build, SURVEY.md §2.6). No external deps — plain
g++; OpenMP is enabled when available (the PS summation hot loop,
cpu_reducer.cc, parallelises across the server's spare cores).
"""

from __future__ import annotations

import os
import subprocess
import sys

CORE_DIR = os.path.dirname(os.path.abspath(__file__))
CSRC = os.path.join(CORE_DIR, "csrc")
LIB_PATH = os.path.join(CORE_DIR, "libbyteps_core.so")

SOURCES = [
    "debug.cc",
    "crc32c.cc",
    "trace.cc",
    "tenancy.cc",
    "roundstats.cc",
    "events.cc",
    "van.cc",
    "postoffice.cc",
    "cpu_reducer.cc",
    "compressor.cc",
    "ckpt.cc",
    "server.cc",
    "worker.cc",
    "c_api.cc",
]


def _supports_flag(cxx: str, flag: str) -> bool:
    probe = subprocess.run(
        [cxx, flag, "-x", "c++", "-", "-fsyntax-only"],
        input="int main(){return 0;}", text=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    return probe.returncode == 0


def build(force: bool = False, verbose: bool = True,
          sanitize: str = "") -> str:
    """Compile if sources are newer than the library. Returns the lib path.

    ``sanitize``: "address" or "thread" builds an instrumented variant
    (libbyteps_core.asan.so / .tsan.so). The reference relies on CHECK
    macros alone (SURVEY.md §5 "no TSAN/ASAN CI"); these builds are how
    byteps_tpu races/UAFs get caught — an exit-order use-after-free in the
    shutdown path was found exactly this way. Run with:

        BPS_CORE_LIB=.../libbyteps_core.asan.so \
        LD_PRELOAD=$(g++ -print-file-name=libasan.so) python ...
    """
    lib_path = LIB_PATH
    if sanitize:
        assert sanitize in ("address", "thread"), sanitize
        suffix = {"address": ".asan.so", "thread": ".tsan.so"}[sanitize]
        lib_path = LIB_PATH[:-3] + suffix
    srcs = [os.path.join(CSRC, s) for s in SOURCES]
    hdrs = [os.path.join(CSRC, h) for h in os.listdir(CSRC)
            if h.endswith(".h")]
    if not force and os.path.exists(lib_path):
        lib_mtime = os.path.getmtime(lib_path)
        if all(os.path.getmtime(f) < lib_mtime for f in srcs + hdrs):
            return lib_path

    cxx = os.environ.get("CXX", "g++")
    if sanitize:
        flags = ["-O1", "-g", "-std=c++17", "-fPIC", "-shared", "-pthread",
                 "-Wall", f"-fsanitize={sanitize}",
                 "-fno-omit-frame-pointer"]
    else:
        flags = ["-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
                 "-Wall"]
        for extra in ("-march=native", "-fopenmp"):
            if _supports_flag(cxx, extra):
                flags.append(extra)
    # -lrt: shm_open/shm_unlink (the shm van transport) live in librt on
    # glibc < 2.34; on newer glibc the library is an empty stub, so
    # linking it unconditionally is safe and keeps dlopen from failing
    # with "undefined symbol: shm_open" on older hosts.
    cmd = [cxx, *flags, *srcs, "-o", lib_path, "-lrt"]
    if verbose:
        print("[byteps_tpu.core.build]", " ".join(cmd))
    subprocess.run(cmd, check=True)
    return lib_path


if __name__ == "__main__":
    san = ""
    if "--asan" in sys.argv:
        san = "address"
    elif "--tsan" in sys.argv:
        san = "thread"
    print(build(force="--force" in sys.argv, sanitize=san))
