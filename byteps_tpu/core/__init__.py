"""C++ core runtime: DCN KV transport, CPU-summation parameter server,
priority-credit scheduler, compression codecs. See csrc/ for the C++
sources, build.py for compilation, ffi.py for the ctypes bindings."""

from byteps_tpu.core.ffi import (  # noqa: F401
    Replica,
    Scheduler,
    Server,
    Worker,
    ensure_built,
)
