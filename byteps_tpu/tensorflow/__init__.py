"""byteps_tpu.tensorflow — TensorFlow framework plugin (Horovod-compatible).

Capability parity with the reference's byteps/tensorflow plugin (SURVEY.md
§2.5 and §3.5): ``init`` / ``shutdown`` / ``rank`` / ``size`` /
``local_rank`` / ``local_size``, ``push_pull`` (works eagerly and inside
``tf.function`` graphs), ``broadcast`` / ``broadcast_variables``,
``DistributedOptimizer`` (wraps ``apply_gradients``, and
``compute_gradients`` for tf.compat.v1 optimizers),
``DistributedGradientTape`` for TF2 custom training loops, and
``BroadcastGlobalVariablesHook``-equivalent callbacks (byteps_tpu.keras).

Transport: the byteps_tpu C++ core (TCP van → CPU-summation parameter
servers), the same path the torch plugin uses. The reference's custom op
kernels ("BytepsPushPull", byteps/tensorflow/ops.cc) become
``tf.numpy_function`` nodes whose eager body hands zero-copy numpy views
to the C core — no TF custom-op build step needed.

Single-process mode (no scheduler configured): all collective calls
degrade to local no-ops so scripts run unmodified, matching the
reference's non-distributed fallback.
"""

from __future__ import annotations

import threading
from typing import Any, Iterable, Optional

import numpy as np
import tensorflow as tf

from byteps_tpu.config import Config, get_config
from byteps_tpu.tensorflow.compression import Compression

__all__ = [
    "init", "shutdown", "initialized", "rank", "size", "local_rank",
    "local_size", "push_pull", "broadcast", "broadcast_variables",
    "DistributedOptimizer", "DistributedGradientTape", "Compression",
    "BroadcastGlobalVariablesHook",
]

_lock = threading.Lock()
_client = None            # core.ffi.Worker in distributed mode
_cfg: Optional[Config] = None
_initialized = False
_declared = {}            # name -> (tensor_id, nelem, dtype_name)
_noname_seq = 0


def init(config: Optional[Config] = None) -> None:
    """Initialise the plugin (reference: bps.init() → byteps_init)."""
    global _client, _cfg, _initialized
    with _lock:
        if _initialized:
            return
        _cfg = config or get_config(reload=True)
        if _cfg.distributed:
            from byteps_tpu.core import ffi as _ffi
            _client = _ffi.Worker.start(_cfg)
        _initialized = True


def shutdown() -> None:
    """Tear down (reference: byteps_shutdown)."""
    global _client, _initialized, _noname_seq
    with _lock:
        if _client is not None:
            _client.shutdown()
            _client = None
        _declared.clear()
        _noname_seq = 0
        _initialized = False


def initialized() -> bool:
    return _initialized


def _require_init() -> None:
    if not _initialized:
        raise RuntimeError("byteps_tpu.tensorflow.init() has not been "
                           "called")


def rank() -> int:
    """This worker process's rank in [0, size())."""
    _require_init()
    return _client.worker_rank() if _client is not None else 0


def size() -> int:
    """Number of worker processes (the gradient-averaging denominator)."""
    _require_init()
    return _client.num_workers() if _client is not None else 1


def local_rank() -> int:
    _require_init()
    return _cfg.local_rank


def local_size() -> int:
    _require_init()
    return _cfg.local_size


# --- tensor plumbing --------------------------------------------------------

def _auto_name() -> str:
    """Sequential fallback name (reference/Horovod: BytePSPushPull.noname.N).
    Correct when all ranks issue unnamed calls in lockstep order."""
    global _noname_seq
    name = f"byteps_tpu.tf.noname.{_noname_seq}"
    _noname_seq += 1
    return name


def _declare(name: str, nelem: int, np_dtype) -> int:
    dt = np.dtype(np_dtype).name
    cached = _declared.get(name)
    if cached is not None:
        tid, n0, d0 = cached
        if (n0, d0) != (nelem, dt):
            raise ValueError(f"tensor {name!r} re-declared with different "
                             f"shape/dtype ({n0},{d0}) vs ({nelem},{dt})")
        return tid
    tid = _client.declare(name, nelem, dt)
    _declared[name] = (tid, nelem, dt)
    return tid


def _push_pull_numpy(arr: np.ndarray, average: bool, name: str) -> np.ndarray:
    """Eager body of the push_pull op: hand a flat buffer to the C core,
    wait, return the summed buffer. Runs on the host — exactly where the
    reference's kernel enqueues into the core pipeline (ops.cc
    BytepsPushPullOp::ComputeAsync). The core sums IN PLACE, and on CPU
    ``tf.Tensor.numpy()`` / ``tf.numpy_function`` inputs can alias the
    tensor's own storage, so copy first — push_pull must not mutate its
    input."""
    flat = np.array(arr, copy=True).reshape(-1)
    tid = _declare(name, flat.size, flat.dtype)
    h = _client.push_pull(tid, flat, average=average,
                          async_mode=_cfg.enable_async)
    _client.wait(h)
    return flat.reshape(arr.shape)


def push_pull(tensor, average: bool = True, name: Optional[str] = None,
              compression=Compression.none):
    """Sum (or average) ``tensor`` across all workers; returns the result.
    Reference: byteps.tensorflow.push_pull (ops.py _push_pull). Works both
    eagerly and inside a ``tf.function``: in a traced graph the exchange
    becomes a ``tf.numpy_function`` node running the same eager body.

    ``tf.IndexedSlices`` (embedding gradients) are densified first, like
    the reference/Horovod.
    """
    _require_init()
    tensor = tf.convert_to_tensor(tensor)  # densifies tf.IndexedSlices too
    if _client is None:
        return tensor
    nm = name or _auto_name()
    wire, ctx = compression.compress(tensor)

    def _body(arr):
        return _push_pull_numpy(arr, average, nm)

    if tf.executing_eagerly():
        out = tf.convert_to_tensor(_body(wire.numpy()))
    else:
        out = tf.numpy_function(_body, [wire], Tout=wire.dtype,
                                name="BytepsPushPull")
        out.set_shape(wire.shape)
    return compression.decompress(out, ctx)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None):
    """Value broadcast from ``root_rank`` (reference: BytepsBroadcast op).
    Returns a tensor equal to root's value on every worker."""
    _require_init()
    tensor = tf.convert_to_tensor(tensor)
    if _client is None:
        return tensor
    nm = name or _auto_name()

    def _body(arr):
        # copy: the core writes root's value in place (see _push_pull_numpy)
        flat = np.array(arr, copy=True).reshape(-1)
        tid = _declare(nm, flat.size, flat.dtype)
        _client.wait(_client.broadcast(tid, flat, root_rank=root_rank))
        return flat.reshape(arr.shape)

    if tf.executing_eagerly():
        return tf.convert_to_tensor(_body(tensor.numpy()))
    out = tf.numpy_function(_body, [tensor], Tout=tensor.dtype,
                            name="BytepsBroadcast")
    out.set_shape(tensor.shape)
    return out


def broadcast_variables(variables: Iterable, root_rank: int = 0) -> None:
    """Assign every variable its ``root_rank`` value, in place (reference:
    broadcast_variables / BroadcastGlobalVariablesHook body). Use after
    building the model so all workers start from identical weights."""
    _require_init()
    if _client is None:
        return
    for i, v in enumerate(variables):
        # v.name alone is not unique (unnamed Variables all report
        # "Variable:0"), so key on position too — iteration order is the
        # lockstep contract, as in the reference's noname sequence.
        name = getattr(v, "name", None) or "var"
        v.assign(broadcast(v, root_rank=root_rank,
                           name=f"bcast.{i}.{name}"))


# --- gradient integration ---------------------------------------------------

def _var_key(v, i: int) -> str:
    """Wire key for a gradient: the variable's name when it has one (as in
    the reference/Horovod — keeps two wrapped optimizers in one process
    from colliding), with position for unnamed variables."""
    name = getattr(v, "path", None) or getattr(v, "name", None)
    return f"grad.{name}" if name else f"grad.pos.{i}"


def _push_pull_grads(grads, variables, compression):
    """push_pull each gradient (None entries pass through untouched)."""
    out = []
    for i, (g, v) in enumerate(zip(grads, variables)):
        if g is None:
            out.append(None)
            continue
        out.append(push_pull(g, average=True, name=_var_key(v, i),
                             compression=compression))
    return out


class DistributedGradientTape:
    """TF2 custom-training-loop integration (reference:
    byteps/tensorflow/__init__.py DistributedGradientTape): wraps a
    ``tf.GradientTape`` so ``gradient()`` returns push_pull-averaged
    gradients.

        with bps.DistributedGradientTape(tf.GradientTape()) as tape:
            loss = loss_fn(model(x))
        grads = tape.gradient(loss, model.trainable_variables)
    """

    def __init__(self, tape: tf.GradientTape,
                 compression=Compression.none):
        self._tape = tape
        self._compression = compression

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        return self._tape.__exit__(exc_type, exc, tb)

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        if size() <= 1:
            return grads
        flat = _push_pull_grads(tf.nest.flatten(grads),
                                tf.nest.flatten(sources),
                                self._compression)
        return tf.nest.pack_sequence_as(grads, flat)


def DistributedOptimizer(optimizer, compression=Compression.none,
                         backward_passes_per_step: int = 1):
    """Wrap a TF optimizer for data-parallel training (reference:
    byteps.tensorflow.DistributedOptimizer).

    - Keras (2/3) optimizers: ``apply_gradients`` (and Keras 3 ``apply``)
      push_pull-average the gradients before the update.
    - tf.compat.v1 optimizers: ``compute_gradients`` returns averaged
      gradients, matching the reference's TF1 wrap.

    Returns an object of a dynamically created subclass of ``optimizer``'s
    class, so isinstance checks and LR schedules keep working.
    """
    if backward_passes_per_step != 1:
        raise ValueError(
            "backward_passes_per_step > 1 is not supported by the TF "
            "plugin; accumulate gradients in the training loop instead")
    _require_init()

    base = optimizer.__class__
    is_v1 = isinstance(optimizer, tf.compat.v1.train.Optimizer)

    if is_v1:
        class _Wrapped(base):  # type: ignore[valid-type, misc]
            def compute_gradients(self, *args, **kwargs):
                gradvars = super().compute_gradients(*args, **kwargs)
                if size() <= 1:
                    return gradvars
                grads = _push_pull_grads([g for g, _ in gradvars],
                                         [v for _, v in gradvars],
                                         compression)
                return list(zip(grads, [v for _, v in gradvars]))
    else:
        class _Wrapped(base):  # type: ignore[valid-type, misc]
            # Keras 3's apply_gradients delegates to apply(); the flag
            # keeps the nested call from communicating a second time.
            _bps_in_flight = False

            def apply_gradients(self, grads_and_vars, *args, **kwargs):
                grads_and_vars = list(grads_and_vars)
                if size() > 1 and not self._bps_in_flight:
                    grads = _push_pull_grads(
                        [g for g, _ in grads_and_vars],
                        [v for _, v in grads_and_vars], compression)
                    grads_and_vars = list(
                        zip(grads, [v for _, v in grads_and_vars]))
                self._bps_in_flight = True
                try:
                    return super().apply_gradients(grads_and_vars, *args,
                                                   **kwargs)
                finally:
                    self._bps_in_flight = False

            def apply(self, grads, trainable_variables=None, **kwargs):
                if size() > 1 and not self._bps_in_flight:
                    grads = list(grads)
                    tvars = (list(trainable_variables)
                             if trainable_variables is not None else
                             list(getattr(self, "_trainable_variables",
                                          None) or [None] * len(grads)))
                    grads = _push_pull_grads(grads, tvars, compression)
                self._bps_in_flight = True
                try:
                    if trainable_variables is None:
                        return super().apply(grads, **kwargs)
                    return super().apply(grads, trainable_variables,
                                         **kwargs)
                finally:
                    self._bps_in_flight = False

    _Wrapped.__name__ = "Distributed" + base.__name__
    wrapped = _Wrapped.__new__(_Wrapped)
    wrapped.__dict__.update(optimizer.__dict__)
    return wrapped


def BroadcastGlobalVariablesHook(root_rank: int = 0):
    """TF1-compat session hook (reference: byteps.tensorflow
    BroadcastGlobalVariablesHook): broadcasts all global variables from
    ``root_rank`` right after session creation, so graph-mode
    ``tf.compat.v1`` training starts from identical weights. The
    broadcast ops are built in ``begin()`` (before graph finalisation)
    and run once in ``after_create_session``.
    """
    _require_init()

    class _Hook(tf.compat.v1.train.SessionRunHook):
        def __init__(self):
            self._bcast_op = None

        def begin(self):
            vs = tf.compat.v1.global_variables()
            self._bcast_op = tf.group(*[
                tf.compat.v1.assign(
                    v, broadcast(v, root_rank=root_rank,
                                 name=f"bcast.hook.{i}.{v.name}"))
                for i, v in enumerate(vs)
            ])

        def after_create_session(self, session, coord):
            session.run(self._bcast_op)

    return _Hook()
