"""Wire-level gradient compression for the TensorFlow plugin.

Capability parity: reference byteps/tensorflow/compression.py (SURVEY.md
§2.5) — the Horovod-compatible ``Compression`` namespace: ``none`` and
``fp16``, applied to each tensor before communication and undone after.
"""

from __future__ import annotations

import tensorflow as tf


class NoneCompressor:
    """No-op compression (reference: Compression.none)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor:
    """Cast to float16 for the wire, cast back after (reference:
    Compression.fp16). Halves DCN bytes; the server sums in fp16."""

    @staticmethod
    def compress(tensor):
        if tensor.dtype in (tf.float32, tf.float64):
            return tf.cast(tensor, tf.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None:
            return tf.cast(tensor, ctx)
        return tensor


class Compression:
    """Namespace of wire compressors (Horovod-compatible)."""

    none = NoneCompressor
    fp16 = FP16Compressor
