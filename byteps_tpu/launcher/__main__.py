import sys

from byteps_tpu.launcher.launch import main

if __name__ == "__main__":
    sys.exit(main())
