"""bpslaunch — multi-role process launcher.

Capability parity with the reference's ``launcher/launch.py`` (SURVEY.md
§2.6): one CLI, behavior switched on ``DMLC_ROLE``:

- ``scheduler`` / ``server`` → run the CPU parameter-server / scheduler
  loop (reference: exec ``python -c 'import byteps.server'``).
- ``worker`` → spawn worker process(es) running the user command with
  ``BYTEPS_LOCAL_RANK`` / ``BYTEPS_LOCAL_SIZE`` set, and reap them.

TPU-first differences from the reference:

- The reference spawns ONE PROCESS PER GPU because NCCL+CUDA want
  single-device processes. On TPU, one controller process drives all local
  chips through XLA, so the default is one worker process per host
  (``--workers-per-host 1``); the per-GPU fanout survives as
  ``--workers-per-host N`` for CPU-simulation topologies.
- ``--local N`` convenience mode brings up a full localhost fleet
  (scheduler + servers + N workers) in one command — the reference needs
  a shell script (tests/run_byteps_test.sh) for this.
- NUMA pinning: ``--numa`` prefixes workers with ``numactl --cpunodebind``
  round-robin, like the reference's numa wrapper.
"""

from __future__ import annotations

import argparse
import os
import shutil
import signal
import subprocess
import sys
from typing import Dict, List, Optional, Sequence


def _role_env(base: Dict[str, str], role: str, **extra: str) -> Dict[str, str]:
    env = dict(base)
    env["DMLC_ROLE"] = role
    env.update(extra)
    return env


def _numa_prefix(local_rank: int) -> List[str]:
    """Round-robin NUMA binding (reference: launch.py numactl wrapper)."""
    numactl = shutil.which("numactl")
    if not numactl:
        return []
    try:
        nodes = sorted(
            int(d[4:]) for d in os.listdir("/sys/devices/system/node")
            if d.startswith("node") and d[4:].isdigit())
    except OSError:
        return []
    if len(nodes) <= 1:
        return []
    node = nodes[local_rank % len(nodes)]
    return [numactl, f"--cpunodebind={node}", f"--membind={node}"]


def run_server_role(role: str) -> int:
    """Run the scheduler/server loop in-process; returns exit code."""
    os.environ["DMLC_ROLE"] = role
    from byteps_tpu.server import main as server_main
    server_main()
    return 0


def spawn_workers(command: Sequence[str], workers_per_host: int,
                  env: Dict[str, str], numa: bool = False
                  ) -> List[subprocess.Popen]:
    procs = []
    for i in range(workers_per_host):
        e = _role_env(env, "worker",
                      BYTEPS_LOCAL_RANK=str(i),
                      BYTEPS_LOCAL_SIZE=str(workers_per_host))
        prefix = _numa_prefix(i) if numa else []
        procs.append(subprocess.Popen(prefix + list(command), env=e))
    return procs


_TERM_GRACE_S = 10.0


def _describe_exit(code: Optional[int]) -> str:
    """Human attribution for a child's exit: signal name when killed,
    plain code otherwise — post-mortems need to know WHICH role died and
    HOW, not just that 'the fleet failed'."""
    if code is not None and code < 0:
        try:
            signame = signal.Signals(-code).name
        except ValueError:
            signame = f"signal {-code}"
        return f"signal {-code} ({signame})"
    return f"exit code {code}"


def _reap(procs: List[subprocess.Popen], names: Optional[List[str]] = None,
          respawn=None, supervise: int = 0, poll_hook=None,
          worker_death=None) -> int:
    """Wait for all children; on first failure kill the rest.

    Mirrors the reference launcher's fail-fast behavior: a dead worker
    must take the job down, not hang it. Survivors get SIGTERM, then
    SIGKILL after a grace period, so a child that traps SIGTERM (e.g. a
    checkpoint-on-term training script) cannot wedge the launcher.

    --supervise mode: ``respawn(name)`` (when given) returns a fresh
    Popen for a dead SERVER or SCHEDULER role — hot replacement via
    DMLC_RECOVER_RANK, crash-restart via DMLC_SCHED_RECOVER — and up
    to ``supervise`` such respawns replace the fail-fast for those
    children. Deaths past the budget fail fast as before.

    --elastic mode hooks (ISSUE 8): ``poll_hook(remaining)`` runs every
    loop tick and returns newly spawned children to track (the SIGHUP
    scale protocol); ``worker_death(name, code)`` decides a dead
    WORKER's fate — ``"shrink"`` keeps the fleet running (the scheduler
    retires the rank via the elastic shrink path), ``(new_name, proc)``
    additionally respawns a fresh joiner, ``None`` falls through to the
    fail-fast. With both hooks absent the pre-elastic behavior is
    unchanged: any worker death takes the job down.
    """
    import time

    names = names or [f"proc{i}" for i in range(len(procs))]
    rc = 0
    budget = supervise
    term_deadline = None
    try:
        remaining = dict(zip(names, procs))
        while remaining:
            if term_deadline is not None and time.monotonic() > term_deadline:
                for q in remaining.values():
                    q.kill()
                term_deadline = None
            if poll_hook is not None and term_deadline is None:
                for nname, np_ in (poll_hook(remaining) or {}).items():
                    procs.append(np_)
                    remaining[nname] = np_
            for name in list(remaining):
                p = remaining[name]
                try:
                    code = p.wait(timeout=0.2)
                except subprocess.TimeoutExpired:
                    continue
                del remaining[name]
                if code != 0:
                    # Failure attribution BEFORE any restart decision:
                    # which role/rank died, its pid, and how.
                    print(f"bpslaunch: {name} (pid {p.pid}) died with "
                          f"{_describe_exit(code)}", file=sys.stderr,
                          flush=True)
                    if name.startswith("replica") and term_deadline is None:
                        # Read replicas are expendable by design
                        # (ISSUE 16): the scheduler scrubs the dead one
                        # from the roster, readers fail over to the next
                        # endpoint, and the training fleet never
                        # notices. Never fail-fast the job for one.
                        print(f"bpslaunch: {name} was a read replica — "
                              "readers fail over; fleet continues",
                              file=sys.stderr, flush=True)
                        continue
                    if (respawn is not None and term_deadline is None
                            and (name.startswith("server")
                                 or name == "scheduler") and budget > 0):
                        budget -= 1
                        fresh = respawn(name)
                        if fresh is not None:
                            kind = ("crash-restart"
                                    if name == "scheduler"
                                    else "hot replacement")
                            print(f"bpslaunch: respawning {name} as "
                                  f"{kind} (pid {fresh.pid}, "
                                  f"{budget} respawn(s) left)",
                                  file=sys.stderr, flush=True)
                            procs.append(fresh)
                            remaining[name] = fresh
                            continue
                    if (worker_death is not None and term_deadline is None
                            and name.startswith("worker")):
                        verdict = worker_death(name, code)
                        if verdict == "shrink":
                            print(f"bpslaunch: elastic shrink — fleet "
                                  f"continues without {name}",
                                  file=sys.stderr, flush=True)
                            continue
                        if verdict is not None:
                            new_name, fresh = verdict
                            print(f"bpslaunch: respawning a fresh "
                                  f"elastic joiner {new_name} "
                                  f"(pid {fresh.pid}) to replace {name}",
                                  file=sys.stderr, flush=True)
                            procs.append(fresh)
                            remaining[new_name] = fresh
                            continue
                    rc = rc or code
                    if remaining and term_deadline is None:
                        for q in remaining.values():
                            q.terminate()
                        term_deadline = time.monotonic() + _TERM_GRACE_S
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGINT)
        deadline = time.monotonic() + _TERM_GRACE_S
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
        rc = 130
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return rc


def _has_sealed_checkpoint(ckpt_dir: str) -> bool:
    """True when the spool holds at least one shard directory with a
    sealed MANIFEST. Presence is all the launcher checks — rejecting a
    torn or checksum-invalid spill is the restore scan's job, and a
    restore attempt over nothing-valid fail-stops with the shard named
    rather than cold-starting."""
    try:
        return any(n.startswith("ckpt_v")
                   and os.path.exists(os.path.join(ckpt_dir, n, "MANIFEST"))
                   for n in os.listdir(ckpt_dir))
    except OSError:
        return False


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch_local_fleet(command: Sequence[str], num_workers: int,
                       num_servers: int, port: int, env: Dict[str, str],
                       numa: bool = False, supervise: int = 0,
                       elastic: bool = False, scale_file: str = "",
                       num_replicas: int = 0) -> int:
    """Bring up scheduler + servers + workers on 127.0.0.1 in one call
    (the reference needs tests/run_byteps_test.sh for this topology).

    port=0 picks a free port; because another process can grab it between
    probe and bind, the scheduler launch is retried on fresh ports.
    """
    import time

    base = dict(env)
    base.update({
        "DMLC_PS_ROOT_URI": base.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
        "DMLC_NUM_WORKER": str(num_workers),
        "DMLC_NUM_SERVER": str(num_servers),
        "BYTEPS_FORCE_DISTRIBUTED": "1",
    })
    if base.get("BYTEPS_MONITOR_ON", "").strip().lower() in (
            "1", "true", "yes", "on"):
        # Every role serves /metrics + /healthz on base_port + node_id
        # (byteps_tpu.monitor); print the map so the operator can point
        # `python -m byteps_tpu.monitor.top` (or curl) at the fleet.
        mport = int(base.get("BYTEPS_MONITOR_PORT", "9100") or 9100)
        from byteps_tpu.monitor.top import fleet_endpoints
        eps = fleet_endpoints("127.0.0.1", mport, num_workers, num_servers)
        print("bpslaunch: monitor endpoints: "
              + " ".join(f"{n}=http://{e}" for n, e in sorted(eps.items())),
              file=sys.stderr)
    server_cmd = [sys.executable, "-m", "byteps_tpu.server"]
    auto_port = port == 0
    for attempt in range(3):
        chosen = _free_port() if auto_port else port
        base["DMLC_PS_ROOT_PORT"] = str(chosen)
        sched = subprocess.Popen(server_cmd, env=_role_env(base, "scheduler"))
        # The scheduler binds immediately; if it lost the port race it dies
        # within this window and we retry on a fresh port.
        time.sleep(0.5)
        if sched.poll() is None or sched.returncode == 0:
            break
        if not auto_port or attempt == 2:
            print(f"bpslaunch: scheduler failed to start on port {chosen}",
                  file=sys.stderr)
            return sched.returncode or 1
    procs = [sched]
    names = ["scheduler"]
    for s in range(num_servers):
        # DMLC_WORKER_ID pins the server's RANK to its launch index
        # (the scheduler sorts registrations by preferred rank), so
        # --supervise can respawn "server s" with DMLC_RECOVER_RANK=s
        # and be certain it adopts the right shard.
        procs.append(
            subprocess.Popen(server_cmd,
                             env=_role_env(base, "server",
                                           DMLC_WORKER_ID=str(s))))
        names.append(f"server{s}")
    # Elastic scale protocol (ISSUE 8): SIGHUP makes the launcher read a
    # target worker count from the scale file — growth spawns fresh
    # JOINERS (DMLC_JOIN=1; the scheduler allocates never-reused ranks),
    # shrink touches the highest-index workers' retire files (each
    # worker's BYTEPS_RETIRE_FILE; training loops poll
    # ``byteps_tpu.core.ffi.leave_requested()`` and leave gracefully).
    import tempfile

    state = {"hup": False, "next_idx": num_workers}
    retire_dir = ""
    if elastic:
        base["BYTEPS_ELASTIC"] = "1"
        retire_dir = tempfile.mkdtemp(prefix="bps_retire_")
        if not scale_file:
            scale_file = os.path.join(retire_dir, "bps_scale")
        signal.signal(signal.SIGHUP,
                      lambda signum, frame: state.update(hup=True))
        print(f"bpslaunch: elastic fleet — write a target worker count "
              f"to {scale_file} and send SIGHUP to pid {os.getpid()} to "
              f"grow/shrink", file=sys.stderr, flush=True)

    def _spawn_worker(idx: int, join: bool) -> subprocess.Popen:
        extra = {"DMLC_WORKER_ID": str(idx),
                 "BYTEPS_LOCAL_RANK": "0",
                 "BYTEPS_LOCAL_SIZE": "1"}
        if retire_dir:
            extra["BYTEPS_RETIRE_FILE"] = os.path.join(
                retire_dir, f"retire.worker{idx}")
        if join:
            extra["DMLC_JOIN"] = "1"
        e = _role_env(base, "worker", **extra)
        prefix = _numa_prefix(idx) if numa else []
        return subprocess.Popen(prefix + list(command), env=e)

    # Versioned snapshot serving (ISSUE 16): read replicas shadow the
    # servers round-robin. Each gets a PINNED listen port so inference
    # readers have stable endpoints to fail over across; the combined
    # list is printed (and exported as BYTEPS_SNAP_ENDPOINTS to the
    # worker command, spawned below) in byteps_tpu.client.pull_snapshot
    # format. Spawn order doesn't matter for correctness — the scheduler
    # buffers replica registrations until fleet formation commits.
    if num_replicas > 0:
        snap_eps = []
        for r in range(num_replicas):
            rport = _free_port()
            procs.append(subprocess.Popen(
                server_cmd,
                env=_role_env(base, "replica",
                              BYTEPS_REPLICA_OF=str(r % max(num_servers, 1)),
                              BYTEPS_LISTEN_PORT=str(rport))))
            names.append(f"replica{r}")
            snap_eps.append(f"127.0.0.1:{rport}")
        base["BYTEPS_SNAP_ENDPOINTS"] = ",".join(snap_eps)
        print(f"bpslaunch: snapshot endpoints (read replicas): "
              f"{base['BYTEPS_SNAP_ENDPOINTS']}", file=sys.stderr,
              flush=True)
    for w in range(num_workers):
        procs.append(_spawn_worker(w, join=False))
        names.append(f"worker{w}")
    # Pid map for operators (and the recovery tests): supervision and
    # post-mortems need to know which pid is which role.
    for name, p in zip(names, procs):
        print(f"bpslaunch: spawned {name} pid={p.pid}", file=sys.stderr,
              flush=True)

    sched_respawns = {"count": 0}

    def _respawn_server(name: str) -> Optional[subprocess.Popen]:
        # Hot replacement: respawn ONLY the dead control-plane role.
        # A server comes back with DMLC_RECOVER_RANK so it adopts the
        # dead rank's id and key shard instead of joining formation; a
        # scheduler comes back with DMLC_SCHED_RECOVER so it rebuilds
        # its address book / rank allocator / tenant rosters from the
        # fleet's re-registrations (the port is pinned in base, so
        # parked nodes re-dial the same endpoint).
        if name == "scheduler":
            if (base.get("BYTEPS_SCHED_RECOVERY_TIMEOUT_MS", "0")
                    or "0").strip() in ("", "0"):
                print("bpslaunch: scheduler died but "
                      "BYTEPS_SCHED_RECOVERY_TIMEOUT_MS is unset/0 — "
                      "the fleet cannot re-register; failing fast",
                      file=sys.stderr, flush=True)
                return None
            # Capped backoff between scheduler respawns: the pinned
            # port may still be in TIME_WAIT, and a crash-looping
            # scheduler must not burn the whole budget in a second.
            delay = min(0.2 * (2 ** sched_respawns["count"]), 5.0)
            sched_respawns["count"] += 1
            time.sleep(delay)
            e = _role_env(base, "scheduler", DMLC_SCHED_RECOVER="1")
            return subprocess.Popen(server_cmd, env=e)
        rank = int(name[len("server"):])
        e = _role_env(base, "server", DMLC_RECOVER_RANK=str(rank))
        return subprocess.Popen(server_cmd, env=e)

    def _scale_hook(remaining):
        # Runs on every reap tick; acts only after a SIGHUP.
        if not state["hup"]:
            return {}
        state["hup"] = False
        try:
            with open(scale_file) as f:
                target = int(f.read().strip() or "0")
        except (OSError, ValueError) as exc:
            print(f"bpslaunch: SIGHUP but no usable scale file "
                  f"{scale_file}: {exc}", file=sys.stderr, flush=True)
            return {}
        live = sorted(n for n in remaining if n.startswith("worker"))
        new = {}
        if target > len(live):
            for _ in range(target - len(live)):
                idx = state["next_idx"]
                state["next_idx"] += 1
                p2 = _spawn_worker(idx, join=True)
                print(f"bpslaunch: elastic grow — spawned worker{idx} "
                      f"pid={p2.pid} as joiner", file=sys.stderr,
                      flush=True)
                new[f"worker{idx}"] = p2
        elif target < len(live) and target >= 1:
            for name in list(reversed(live))[:len(live) - target]:
                path = os.path.join(retire_dir, f"retire.{name}")
                with open(path, "w") as f:
                    f.write("retire\n")
                print(f"bpslaunch: elastic shrink — asked {name} to "
                      f"retire ({path})", file=sys.stderr, flush=True)
        return new

    worker_budget = {"left": supervise}

    def _worker_death(name: str, code: int):
        # The scheduler retires the dead rank via the elastic shrink
        # path either way; with --supervise budget left, additionally
        # replace the capacity with a fresh joiner (never the old rank —
        # worker ranks are allocated once and never reused).
        if worker_budget["left"] > 0:
            worker_budget["left"] -= 1
            idx = state["next_idx"]
            state["next_idx"] += 1
            return (f"worker{idx}", _spawn_worker(idx, join=True))
        return "shrink"

    return _reap(procs, names, respawn=_respawn_server if supervise else None,
                 supervise=supervise,
                 poll_hook=_scale_hook if elastic else None,
                 worker_death=_worker_death if elastic else None)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="bpslaunch",
        description="byteps_tpu multi-role launcher (role from DMLC_ROLE; "
                    "see docs/env.md)")
    p.add_argument("--local", type=int, metavar="N", default=0,
                   help="localhost fleet mode: launch scheduler + servers + "
                        "N workers on 127.0.0.1")
    p.add_argument("--num-servers", type=int, default=1,
                   help="servers for --local mode (default 1)")
    p.add_argument("--port", type=int, default=0,
                   help="scheduler port for --local mode (default: free port)")
    p.add_argument("--replicas", type=int, metavar="N", default=0,
                   help="--local mode: spawn N read-only snapshot "
                        "replicas (DMLC_ROLE=replica, docs/serving.md) "
                        "shadowing the servers round-robin; their pinned "
                        "reader endpoints are printed and exported to "
                        "workers as BYTEPS_SNAP_ENDPOINTS for "
                        "byteps_tpu.client.pull_snapshot. A dead replica "
                        "costs readers one failover and the fleet "
                        "nothing (it never fail-fasts the job)")
    p.add_argument("--workers-per-host", type=int,
                   default=int(os.environ.get("BYTEPS_LOCAL_SIZE", "1") or 1),
                   help="worker processes to spawn on this host (TPU default "
                        "1: one controller drives all local chips)")
    p.add_argument("--numa", action="store_true",
                   help="bind worker processes round-robin across NUMA nodes")
    p.add_argument("--monitor-port", type=int, metavar="BASE", default=0,
                   help="enable live monitoring (BYTEPS_MONITOR_ON=1): "
                        "every role serves /metrics + /healthz on "
                        "BASE + its node id; scrape with "
                        "`python -m byteps_tpu.monitor.top`")
    p.add_argument("--fusion-bytes", type=int, metavar="N", default=-1,
                   help="small-tensor fusion threshold for the whole "
                        "fleet (BYTEPS_FUSION_BYTES): partitions under N "
                        "raw bytes coalesce into multi-key wire frames; "
                        "0 disables fusion (default: inherit env, 65536)")
    p.add_argument("--wire-quant", action="store_true",
                   help="arm the block-quantized wire for the whole "
                        "fleet (BYTEPS_WIRE_QUANT=1): codec-less "
                        "float32 partitions ship as per-block int8 with "
                        "worker-side error feedback, ~3.8x fewer wire "
                        "bytes each way (docs/performance.md 'Quantized "
                        "wire'); tune with BYTEPS_WIRE_QUANT_BLOCK / "
                        "BYTEPS_WIRE_QUANT_MIN_BYTES")
    p.add_argument("--no-roundstats", action="store_true",
                   help="disable the default-on per-round introspection "
                        "layer (BYTEPS_ROUNDSTATS_ON=0): no per-round "
                        "stage summaries, no heartbeat-piggybacked fleet "
                        "round table, no live bottleneck attribution "
                        "(`python -m byteps_tpu.monitor.insight`); each "
                        "instrumentation site reduces to one relaxed "
                        "atomic load (docs/monitoring.md 'Round insight')")
    p.add_argument("--trace-dir", metavar="DIR", default="",
                   help="arm fleet-wide distributed tracing "
                        "(BYTEPS_TRACE_ON=1, BYTEPS_TRACE_DIR=DIR): "
                        "every role — scheduler, servers, workers — "
                        "leaves a clock-aligned per-rank dump in DIR at "
                        "shutdown; merge with `python -m "
                        "byteps_tpu.monitor.timeline merge --dir DIR` "
                        "(docs/timeline.md). Flight-recorder auto-dumps "
                        "land in the same directory")
    p.add_argument("--elastic", action="store_true",
                   help="arm elastic worker membership for the whole "
                        "fleet (BYTEPS_ELASTIC=1, docs/elasticity.md): "
                        "workers can join (DMLC_JOIN), leave "
                        "gracefully, and a dead worker shrinks the "
                        "fleet to N-1 (scheduler-coordinated rollback) "
                        "instead of fail-stopping. In --local mode, "
                        "SIGHUP + the scale file grow/shrink the fleet "
                        "at runtime, and a dead worker is retired via "
                        "the shrink path (with --supervise N, a fresh "
                        "joiner replaces the capacity)")
    p.add_argument("--tenant", type=int, metavar="ID", default=None,
                   help="register this job under tenant ID "
                        "(BYTEPS_TENANT_ID, docs/multitenancy.md): its "
                        "keys are (tenant, key)-namespaced server-side "
                        "and its traffic rides the weighted-fair engine "
                        "dispatch; unset keeps the single-tenant wire "
                        "byte for byte")
    p.add_argument("--tenant-weight", type=int, metavar="W", default=1,
                   help="this tenant's fair-share weight "
                        "(BYTEPS_TENANT_WEIGHT): backlogged tenants' "
                        "served bytes converge to the weight ratio")
    p.add_argument("--tenant-name", metavar="NAME", default="",
                   help="display name for /tenants and monitor.top "
                        "(BYTEPS_TENANT_NAME; never on the wire)")
    p.add_argument("--scale-file", metavar="PATH", default="",
                   help="--local --elastic mode: file holding the "
                        "target worker count, read on SIGHUP (default: "
                        "a temp path printed at startup)")
    p.add_argument("--supervise", type=int, metavar="N", default=0,
                   help="--local mode: per-child supervision — respawn a "
                        "dead SERVER role (up to N times total) as a hot "
                        "replacement with DMLC_RECOVER_RANK set, instead "
                        "of failing the whole fleet; the scheduler "
                        "coordinates the epoch pause + shard re-seed "
                        "(requires BYTEPS_RECOVERY_TIMEOUT_MS > 0, the "
                        "default). A dead SCHEDULER is respawned too "
                        "when BYTEPS_SCHED_RECOVERY_TIMEOUT_MS > 0: the "
                        "restart carries DMLC_SCHED_RECOVER=1 and "
                        "rebuilds control-plane state from the parked "
                        "fleet's re-registrations. Worker deaths still "
                        "fail fast (pair with --elastic or --restarts "
                        "for those)")
    p.add_argument("--ckpt-dir", metavar="DIR", default="",
                   help="arm durable checkpoints for the whole fleet "
                        "(BYTEPS_CKPT_DIR, docs/checkpoint.md): every "
                        "server spills each BYTEPS_CKPT_EVERY-th "
                        "committed snapshot version to DIR as CRC32C-"
                        "checksummed chunks sealed by a manifest, off "
                        "the training path. Pair with --restarts N for "
                        "full-fleet-loss recovery: a relaunch after a "
                        "failed run escalates to BYTEPS_CKPT_RESTORE=1 "
                        "automatically once DIR holds a sealed "
                        "checkpoint, so the fleet resumes from the last "
                        "durable cut instead of cold-starting")
    p.add_argument("--ckpt-every", type=int, metavar="N", default=0,
                   help="spill every Nth committed snapshot version "
                        "(BYTEPS_CKPT_EVERY; default inherit env, 1)")
    p.add_argument("--restore", action="store_true",
                   help="start the fleet in coordinated restore mode "
                        "(BYTEPS_CKPT_RESTORE=1): servers scan their "
                        "--ckpt-dir shards, the scheduler commits a "
                        "restore epoch at the minimum checksum-valid "
                        "version common to every shard, and workers "
                        "resume from the round after it — or the fleet "
                        "fail-stops with the missing shard named. "
                        "Requires --ckpt-dir (or BYTEPS_CKPT_DIR)")
    p.add_argument("--restarts", type=int, default=0,
                   help="--local mode: relaunch the whole fleet up to N "
                        "times after a failed run (elastic-ish recovery: "
                        "with --ckpt-dir the relaunch restores from the "
                        "last sealed checkpoint; otherwise pair the "
                        "training script with its own checkpoint/resume "
                        "so restarts continue from the last step)")
    p.add_argument("--restart-backoff", type=float, metavar="SECONDS",
                   default=1.0,
                   help="base delay before each --restarts relaunch, "
                        "doubled per consecutive failed attempt (capped "
                        "at 30 s): a crash-looping fleet must not hammer "
                        "ports/scheduler at full speed (default 1.0)")
    p.add_argument("--chaos", metavar="SPEC", default="",
                   help="arm the deterministic fault-injection layer for "
                        "the whole fleet: comma-separated knobs "
                        "drop=P,dup=P,delay-us=N,reset-every=N,seed=N,"
                        "ctrl=1 (sets BYTEPS_CHAOS_*; e.g. --chaos "
                        "drop=0.01,reset-every=1000,seed=42). ctrl=1 "
                        "extends injection to CONTROL-plane frames and "
                        "requires scheduler fail-over armed "
                        "(BYTEPS_SCHED_RECOVERY_TIMEOUT_MS > 0). "
                        "Requires the retry layer (BYTEPS_RETRY_MAX > "
                        "0, the default); see docs/troubleshooting.md")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="worker command, e.g. python train.py")
    args = p.parse_args(argv)
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if args.monitor_port:
        os.environ["BYTEPS_MONITOR_ON"] = "1"
        os.environ["BYTEPS_MONITOR_PORT"] = str(args.monitor_port)
    if args.trace_dir:
        os.environ["BYTEPS_TRACE_ON"] = "1"
        os.environ["BYTEPS_TRACE_DIR"] = args.trace_dir
        print(f"bpslaunch: fleet tracing on — per-rank dumps land in "
              f"{args.trace_dir}; merge with `python -m "
              f"byteps_tpu.monitor.timeline merge --dir "
              f"{args.trace_dir}`", file=sys.stderr)
    if args.fusion_bytes >= 0:
        os.environ["BYTEPS_FUSION_BYTES"] = str(args.fusion_bytes)
    if args.wire_quant:
        os.environ["BYTEPS_WIRE_QUANT"] = "1"
    if args.no_roundstats:
        os.environ["BYTEPS_ROUNDSTATS_ON"] = "0"
    if args.elastic:
        os.environ["BYTEPS_ELASTIC"] = "1"
    if args.ckpt_dir:
        os.environ["BYTEPS_CKPT_DIR"] = args.ckpt_dir
    if args.ckpt_every > 0:
        os.environ["BYTEPS_CKPT_EVERY"] = str(args.ckpt_every)
    if args.restore:
        if not os.environ.get("BYTEPS_CKPT_DIR", ""):
            p.error("--restore requires --ckpt-dir (or BYTEPS_CKPT_DIR)")
        os.environ["BYTEPS_CKPT_RESTORE"] = "1"
    if args.tenant is not None:
        # Multi-tenant PS (ISSUE 9): one launcher invocation = one job
        # = one tenant; every role it spawns carries the id, and
        # workers register the weight with the scheduler. Leaving
        # --tenant off keeps the single-tenant wire byte for byte.
        os.environ["BYTEPS_TENANT_ID"] = str(args.tenant)
        os.environ["BYTEPS_TENANT_WEIGHT"] = str(args.tenant_weight)
        if args.tenant_name:
            os.environ["BYTEPS_TENANT_NAME"] = args.tenant_name
    if args.chaos:
        chaos_envs = {"drop": "BYTEPS_CHAOS_DROP",
                      "dup": "BYTEPS_CHAOS_DUP",
                      "delay-us": "BYTEPS_CHAOS_DELAY_US",
                      "reset-every": "BYTEPS_CHAOS_RESET_EVERY",
                      "seed": "BYTEPS_CHAOS_SEED",
                      "ctrl": "BYTEPS_CHAOS_CTRL"}
        for item in args.chaos.split(","):
            key, sep, val = item.partition("=")
            key = key.strip().lower()
            if not sep or key not in chaos_envs:
                p.error(f"--chaos: unknown knob {item!r} (expected "
                        f"{'/'.join(sorted(chaos_envs))}=value)")
            os.environ[chaos_envs[key]] = val.strip()

    if args.local:
        if not command:
            p.error("--local requires a worker command")
        import time

        rc = launch_local_fleet(command, args.local, args.num_servers,
                                args.port, dict(os.environ), numa=args.numa,
                                supervise=args.supervise,
                                elastic=args.elastic,
                                scale_file=args.scale_file,
                                num_replicas=args.replicas)
        for attempt in range(args.restarts):
            if rc == 0:
                break
            # Capped exponential backoff between relaunches: a
            # crash-looping fleet (bad config, dead dependency) must not
            # hammer the scheduler port / cluster manager at full speed,
            # and TIME_WAIT sockets from the failed fleet get a chance
            # to clear.
            delay = min(args.restart_backoff * (2 ** attempt), 30.0)
            print(f"bpslaunch: fleet failed (exit {rc}); restart "
                  f"{attempt + 1}/{args.restarts} in {delay:.1f}s",
                  file=sys.stderr)
            if delay > 0:
                time.sleep(delay)
            # Durable-checkpoint escalation (ISSUE 18): a dead fleet
            # that was spilling checkpoints relaunches in restore mode,
            # so the restart resumes from the last sealed cut instead of
            # cold-starting from step 0 over the same spool.
            ckpt_dir = os.environ.get("BYTEPS_CKPT_DIR", "")
            if (ckpt_dir and _has_sealed_checkpoint(ckpt_dir)
                    and not os.environ.get("BYTEPS_CKPT_RESTORE")):
                os.environ["BYTEPS_CKPT_RESTORE"] = "1"
                print(f"bpslaunch: sealed checkpoint(s) found in "
                      f"{ckpt_dir} — escalating the relaunch to "
                      f"BYTEPS_CKPT_RESTORE=1 (resume from the last "
                      f"durable cut)", file=sys.stderr, flush=True)
            rc = launch_local_fleet(command, args.local, args.num_servers,
                                    args.port, dict(os.environ),
                                    numa=args.numa,
                                    supervise=args.supervise,
                                    elastic=args.elastic,
                                    scale_file=args.scale_file,
                                    num_replicas=args.replicas)
        return rc

    role = os.environ.get("DMLC_ROLE", "worker").lower()
    if role in ("scheduler", "server", "replica"):
        return run_server_role(role)
    if role != "worker":
        p.error(f"DMLC_ROLE must be scheduler|server|replica|worker, "
                f"got {role!r}")
    if not command:
        p.error("worker role requires a command")
    procs = spawn_workers(command, args.workers_per_host, dict(os.environ),
                          numa=args.numa)
    return _reap(procs, [f"worker/{i}" for i in range(len(procs))])


if __name__ == "__main__":
    sys.exit(main())
