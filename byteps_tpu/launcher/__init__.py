"""byteps_tpu.launcher — bpslaunch multi-role launcher.

Reference analogue: launcher/launch.py (`bpslaunch` entry point),
SURVEY.md §2.6.
"""

from byteps_tpu.launcher.launch import main  # noqa: F401
