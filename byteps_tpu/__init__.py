"""byteps_tpu — a TPU-native gradient-synchronization framework.

A brand-new, TPU-first implementation of the capability set of BytePS
(reference: ymjiang/byteps — see SURVEY.md): hierarchical two-level gradient
aggregation (intra-slice ICI collectives via XLA/shard_map + an inter-host
DCN key-value push/pull leg to CPU-only parameter servers), tensor
partitioning, priority-credit scheduling, pluggable gradient compression,
sync and async training modes, a Horovod-style user API, and a multi-role
launcher.

Layout (capability parity with the reference's layer map, SURVEY.md §1):

- ``byteps_tpu.config``     — env-var config system (docs/env.md parity).
- ``byteps_tpu.partition``  — tensor → partition slicing + key assignment.
- ``byteps_tpu.core``       — C++ runtime (DCN van, postoffice, PS server,
                              CPU reducer, priority scheduler, compression
                              codecs) + ctypes bindings (core/ffi.py).
- ``byteps_tpu.jax``        — the flagship JAX plugin (init/push_pull/
                              DistributedOptimizer/broadcast_parameters,
                              collective + PS modes, per-layer overlap,
                              sync/async/flax/haiku step builders).
- ``byteps_tpu.torch`` / ``.tensorflow`` / ``.keras`` / ``.mxnet`` —
                              Horovod-compatible framework plugins.
- ``byteps_tpu.parallel``   — mesh construction, hierarchical DP (+ int8
                              quantized), ring/Ulysses sequence parallel,
                              TP, GPipe PP, MoE EP, ZeRO sharding.
- ``byteps_tpu.ops``        — Pallas TPU kernels (flash attention fwd/bwd,
                              sliding window).
- ``byteps_tpu.models``     — flax model zoo (ResNet/VGG/BERT/GPT-2/LLaMA/
                              MoE) used by examples/benchmarks.
- ``byteps_tpu.utils``      — checkpoint/resume (orbax), trace timeline.
- ``byteps_tpu.callbacks``  — Keras-style callbacks for JAX loops.
- ``byteps_tpu.server``     — ``python -m byteps_tpu.server`` runs a CPU PS
                              or the scheduler (reference:
                              byteps/server/__init__.py).
- ``byteps_tpu.launcher``   — ``bpslaunch``-style multi-role launcher.
"""

__version__ = "0.1.0"

from byteps_tpu.config import Config, get_config  # noqa: F401
