"""byteps_tpu — a TPU-native gradient-synchronization framework.

A brand-new, TPU-first implementation of the capability set of BytePS
(reference: ymjiang/byteps — see SURVEY.md): hierarchical two-level gradient
aggregation (intra-slice ICI collectives via XLA/shard_map + an inter-host
DCN key-value push/pull leg to CPU-only parameter servers), tensor
partitioning, priority-credit scheduling, pluggable gradient compression,
sync and async training modes, a Horovod-style user API, and a multi-role
launcher.

Layout (capability parity with the reference's layer map, SURVEY.md §1):

- ``byteps_tpu.config``     — env-var config system (docs/env.md parity).
- ``byteps_tpu.topology``   — roles, ranks, mesh construction.
- ``byteps_tpu.partition``  — tensor → partition slicing + key assignment.
- ``byteps_tpu.core``       — C++ runtime (DCN van, PS server, CPU reducer,
                              priority scheduler) + ctypes bindings.
- ``byteps_tpu.jax``        — the JAX framework plugin (init/push_pull/
                              DistributedOptimizer/broadcast_parameters);
                              the equivalent of the reference's byteps/torch.
- ``byteps_tpu.parallel``   — mesh/sharding utilities: hierarchical DP,
                              ring-attention sequence parallelism, TP/PP/EP.
- ``byteps_tpu.ops``        — Pallas TPU kernels for hot ops.
- ``byteps_tpu.compression``— gradient compression plugin registry
                              (onebit/topk/randomk/dithering + error
                              feedback + momentum), JAX-native codecs.
- ``byteps_tpu.models``     — flax model zoo used by examples/benchmarks.
- ``byteps_tpu.server``     — ``import byteps_tpu.server`` runs a CPU PS
                              (reference: byteps/server/__init__.py).
- ``byteps_tpu.launcher``   — ``bpslaunch``-style multi-role launcher.
"""

__version__ = "0.1.0"

from byteps_tpu.config import Config, get_config  # noqa: F401
