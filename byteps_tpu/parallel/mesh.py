"""Device-mesh construction for the two-level (ICI × DCN) topology.

TPU-native replacement for the reference's topology discovery
(byteps/common/global.cc ``BytePSGlobal::Init``: rank/local_rank/size/
local_size + NCCL communicator setup, SURVEY.md §2.1). On TPU, the
"local" (fast) domain is the ICI-connected slice and the "inter-host"
(slow) domain is DCN between slices; we encode both as named mesh axes so
XLA emits ICI collectives for the inner axis and DCN collectives for the
outer one — the exact analogue of NCCL-then-ps-lite in the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named description of the data-parallel mesh.

    ``dcn`` is the slow/outer axis (inter-slice, parameter-server leg in PS
    mode); ``ici`` is the fast/inner axis (intra-slice reduce-scatter /
    all-gather). Either may be 1.
    """

    dcn: int
    ici: int
    dcn_axis: str = "dcn"
    ici_axis: str = "ici"

    @property
    def size(self) -> int:
        return self.dcn * self.ici


def build_mesh(
    spec: Optional[MeshSpec] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    *,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
) -> Mesh:
    """Build a 2-D (dcn, ici) mesh over the available devices.

    Default layout: one dcn group per process (so the outer axis crosses
    host/DCN boundaries exactly like the reference's inter-node PS stage),
    all local devices on the ici axis. On a single process this collapses
    to dcn=1 × ici=<local devices>.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if spec is None:
        n_proc = max(1, jax.process_count())
        if n % n_proc == 0 and n_proc > 1:
            spec = MeshSpec(dcn=n_proc, ici=n // n_proc,
                            dcn_axis=dcn_axis, ici_axis=ici_axis)
        else:
            spec = MeshSpec(dcn=1, ici=n, dcn_axis=dcn_axis, ici_axis=ici_axis)
    if spec.size != n:
        raise ValueError(
            f"MeshSpec {spec.dcn}x{spec.ici} != device count {n}")
    arr = np.asarray(devices).reshape(spec.dcn, spec.ici)
    return Mesh(arr, (spec.dcn_axis, spec.ici_axis))


_global_mesh: Optional[Mesh] = None


def set_global_mesh(mesh: Mesh) -> None:
    global _global_mesh
    _global_mesh = mesh


def global_mesh() -> Mesh:
    """The mesh installed by ``byteps_tpu.jax.init()``."""
    if _global_mesh is None:
        raise RuntimeError(
            "byteps_tpu mesh not initialised — call byteps_tpu.jax.init() "
            "first")
    return _global_mesh
