"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context scope beyond reference parity (the reference never touches
model internals — SURVEY.md §5 "Long-context / sequence parallelism:
absent"); this module is the TPU-native long-sequence answer the task
brief makes first-class.

Design (blockwise ring attention, Liu et al.'s RingAttention shape): the
sequence is sharded over a mesh axis (``sp``). Each device holds one
Q/K/V block; K/V blocks rotate around the ring via ``lax.ppermute`` while
each device accumulates attention of its local Q against every block with
an online (streaming) softmax — numerically identical to full attention,
memory O(S/n) per device. The ppermute for step i+1 is data-independent
of step i's matmuls, so XLA's latency-hiding scheduler overlaps the ICI
transfer with the block compute — the same comm/compute overlap the
reference engineered with its pipeline threads (core_loops.cc), here
falling out of the dataflow graph.

All functions are per-device code: call inside ``jax.shard_map`` over a
mesh with the named sequence axis. Layout [batch, seq, heads, head_dim];
block matmuls run on the MXU in the input dtype, accumulation in f32.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from byteps_tpu.jax._compat import axis_size as _axis_size


def _big_neg(dtype) -> float:
    return float(jnp.finfo(dtype).min) / 2


def _block_attn(q, k, v, m, l, o, q_pos, k_pos, causal, scale):
    """One blockwise attention update with streaming-softmax state.

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; m/l: [B, H, Sq]; o: [B, Sq, H, D].
    Everything but the matmul inputs is float32.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None, :, :], s, _big_neg(jnp.float32))
    m_new = jnp.maximum(m, s.max(axis=-1))
    # m_new is finite (>= _big_neg/1) so exp never sees inf-inf.
    p = jnp.exp(s - m_new[..., None])
    correction = jnp.exp(m - m_new)
    l_new = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis: str = "sp",
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention over a sequence sharded on mesh axis ``axis``.

    Per-device code (use under shard_map). ``q``/``k``/``v`` are the local
    sequence blocks, shape [batch, seq_local, heads, head_dim]; the global
    sequence length is seq_local * axis_size. Returns the local block of
    the attention output, same shape/dtype as ``q``.

    ``causal`` masks by *global* position, so the result equals full causal
    attention on the gathered sequence.
    """
    n = _axis_size(axis)
    my = lax.axis_index(axis)
    b, s_q, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)

    if n == 1:
        return _single_device_attention(q, k, v, causal=causal, scale=scale)

    m0 = jnp.full((b, h, s_q), _big_neg(jnp.float32), jnp.float32)
    l0 = jnp.zeros((b, h, s_q), jnp.float32)
    o0 = jnp.zeros((b, s_q, h, d), jnp.float32)
    q_pos = my * s_q + jnp.arange(s_q)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, i):
        kv, m, l, o = carry
        k_blk, v_blk = kv
        # The block now held originated on device (my - i) mod n.
        src = (my - i) % n
        k_pos = src * s_q + jnp.arange(k_blk.shape[1])
        # Launch the rotation first: it does not depend on this step's
        # matmuls, so the ICI permute overlaps the block compute.
        kv_next = jax.tree_util.tree_map(
            lambda x: lax.ppermute(x, axis, perm), kv)
        m, l, o = _block_attn(q, k_blk, v_blk, m, l, o,
                              q_pos, k_pos, causal, scale)
        return (kv_next, m, l, o), None

    # n-1 rotating steps in a scan, then the last block unrolled with no
    # trailing ppermute (its result would be discarded — one whole K/V
    # block of ICI traffic saved per layer per step).
    (kv_last, m, l, o), _ = lax.scan(
        step, ((k, v), m0, l0, o0), jnp.arange(n - 1))
    src = (my - (n - 1)) % n
    k_pos = src * s_q + jnp.arange(kv_last[0].shape[1])
    m, l, o = _block_attn(q, kv_last[0], kv_last[1], m, l, o,
                          q_pos, k_pos, causal, scale)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _single_device_attention(q, k, v, *, causal: bool, scale: float):
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.arange(s_q)[:, None] >= jnp.arange(s_k)[None, :]
        s = jnp.where(mask[None, None], s, _big_neg(jnp.float32))
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(p.dtype),
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def full_attention(q, k, v, *, causal: bool = False,
                   scale: Optional[float] = None):
    """Unsharded reference attention (testing / single-device fallback)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    return _single_device_attention(q, k, v, causal=causal, scale=scale)


@partial(jax.jit, static_argnums=(3, 4, 5, 6))
def _ring_sharded_impl(q, k, v, mesh, axis, causal, scale):
    from jax.sharding import PartitionSpec as P

    from byteps_tpu.jax._compat import shard_map as _shard_map

    spec = P(None, axis, None, None)
    run = _shard_map(
        lambda ql, kl, vl: ring_attention(ql, kl, vl, axis=axis,
                                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return run(q, k, v)


def ring_attention_sharded(q, k, v, mesh, *, axis: str = "sp",
                           causal: bool = False,
                           scale: Optional[float] = None):
    """Convenience wrapper: global [B, S, H, D] arrays in, jitted
    shard_map'd ring attention over ``mesh``'s ``axis`` out. The jit cache
    is keyed on (mesh, axis, causal, scale) — loops don't recompile."""
    return _ring_sharded_impl(q, k, v, mesh, axis, causal, scale)
