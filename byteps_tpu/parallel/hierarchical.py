"""Hierarchical two-level all-reduce — the heart of the framework.

Capability parity with the reference's core pipeline (SURVEY.md §3.3,
byteps/common/core_loops.cc): NCCL reduce-scatter intra-node → push/pull to
CPU parameter servers inter-node → NCCL broadcast/all-gather back. The
TPU-native mapping:

    REDUCE (NCCL reduce-scatter)  →  lax.psum_scatter over the ``ici`` axis
    PUSH/PULL (ps-lite over TCP)  →  ``dcn_reduce_fn``: either
                                     lax.psum over the ``dcn`` axis
                                     (XLA DCN collective, collective mode)
                                     or a host callback into the C++ KV
                                     client → CPU PS (PS mode)
    BROADCAST (NCCL all-gather)   →  lax.all_gather over the ``ici`` axis

Every function here is *per-device* code: call it inside ``jax.shard_map``
over a mesh with the named axes. Shapes are static; padding is applied so
reduce-scatter tiles evenly — both required for XLA to schedule the
collectives on ICI without host round-trips.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from byteps_tpu.jax._compat import axis_size as _compat_axis_size

ReduceFn = Callable[[jax.Array], jax.Array]


def _axis_size(axis: Optional[str]) -> int:
    return _compat_axis_size(axis) if axis else 1


def hierarchical_all_reduce(
    x: jax.Array,
    *,
    ici_axis: Optional[str] = "ici",
    dcn_axis: Optional[str] = "dcn",
    average: bool = True,
    dcn_reduce_fn: Optional[ReduceFn] = None,
) -> jax.Array:
    """Two-level all-reduce of one array (per-device code under shard_map).

    Stage 1 reduce-scatters over the fast ``ici`` axis so each chip owns
    1/ici_size of the gradient; stage 2 reduces those shards over the slow
    ``dcn`` axis (or hands them to ``dcn_reduce_fn`` — the PS hook); stage 3
    all-gathers the result back over ``ici``. With 1/N-sized shards on the
    slow fabric this is bandwidth-optimal, exactly the reference's rationale
    (docs/rationale.md) transplanted to ICI/DCN.
    """
    ici = ici_axis if ici_axis and _axis_size(ici_axis) > 1 else None
    dcn = dcn_axis if dcn_axis and _axis_size(dcn_axis) > 1 else None
    denom = _axis_size(ici) * _axis_size(dcn)

    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    n = flat.shape[0]

    if ici is None:
        # Single-chip slice: only the slow-level reduction applies.
        if dcn is not None:
            flat = dcn_reduce_fn(flat) if dcn_reduce_fn else lax.psum(flat, dcn)
        if average and denom > 1:
            flat = flat / denom
        return flat.reshape(orig_shape).astype(orig_dtype)

    ici_size = _axis_size(ici)
    pad = (-n) % ici_size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])

    shard = lax.psum_scatter(flat, ici, scatter_dimension=0, tiled=True)
    if dcn is not None:
        shard = dcn_reduce_fn(shard) if dcn_reduce_fn else lax.psum(shard, dcn)
    if average and denom > 1:
        shard = shard / denom
    out = lax.all_gather(shard, ici, axis=0, tiled=True)
    if pad:
        out = out[:n]
    return out.reshape(orig_shape).astype(orig_dtype)


def tree_all_reduce(
    tree,
    *,
    ici_axis: Optional[str] = "ici",
    dcn_axis: Optional[str] = "dcn",
    average: bool = True,
    dcn_reduce_fn: Optional[ReduceFn] = None,
    fuse: bool = True,
) -> "jax.tree_util.PyTreeDef":
    """All-reduce a pytree of arrays (per-device code under shard_map).

    With ``fuse=True`` all leaves are flattened into one contiguous bf16/f32
    buffer first (reference analogue: tensor fusion, and the reason BytePS
    partitions at ~4 MB — big transfers saturate the fabric; SURVEY.md §6
    "saturates 100 Gbps with ≥4 MB partitions"). One fused reduce-scatter /
    all-gather keeps ICI busy with a single large transfer and lets XLA
    overlap it with whatever compute remains.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    # Axis sizes are static at trace time: when neither level has >1
    # participant the all-reduce is the identity, and the fused
    # concat/slice round-trip would be pure single-chip HBM tax
    # (~200 MB of extra reads+writes per step on ResNet-50).
    if _axis_size(ici_axis) * _axis_size(dcn_axis) == 1:
        return tree
    if not fuse:
        red = [
            hierarchical_all_reduce(
                g, ici_axis=ici_axis, dcn_axis=dcn_axis, average=average,
                dcn_reduce_fn=dcn_reduce_fn)
            for g in leaves
        ]
        return jax.tree_util.tree_unflatten(treedef, red)

    # Fused path: one flat buffer in the widest participating dtype.
    acc_dtype = jnp.result_type(*[l.dtype for l in leaves])
    sizes = [l.size for l in leaves]
    flat = jnp.concatenate([l.reshape(-1).astype(acc_dtype) for l in leaves])
    flat = hierarchical_all_reduce(
        flat, ici_axis=ici_axis, dcn_axis=dcn_axis, average=average,
        dcn_reduce_fn=dcn_reduce_fn)
    out, off = [], 0
    for leaf, sz in zip(leaves, sizes):
        out.append(flat[off:off + sz].reshape(leaf.shape).astype(leaf.dtype))
        off += sz
    return jax.tree_util.tree_unflatten(treedef, out)


def hierarchical_broadcast(
    x: jax.Array,
    *,
    root: int = 0,
    ici_axis: Optional[str] = "ici",
    dcn_axis: Optional[str] = "dcn",
) -> jax.Array:
    """Broadcast ``x`` from the device with linearised index ``root``.

    Reference analogue: ``broadcast_parameters`` (SURVEY.md §3.4) — root's
    values pushed, everyone pulls the same buffer. Implemented as a masked
    psum (zero everywhere but root), which XLA lowers to an efficient
    broadcast over ICI+DCN.
    """
    ici = ici_axis if ici_axis and _axis_size(ici_axis) > 1 else None
    dcn = dcn_axis if dcn_axis and _axis_size(dcn_axis) > 1 else None
    idx = jnp.int32(0)
    scale = 1
    if ici is not None:
        idx = idx + lax.axis_index(ici)
        scale = _axis_size(ici)
    if dcn is not None:
        idx = idx + lax.axis_index(dcn) * scale
    mask = (idx == root).astype(x.dtype)
    y = x * mask
    if ici is not None:
        y = lax.psum(y, ici)
    if dcn is not None:
        y = lax.psum(y, dcn)
    return y


def tree_broadcast(tree, *, root: int = 0,
                   ici_axis: Optional[str] = "ici",
                   dcn_axis: Optional[str] = "dcn"):
    """Broadcast a pytree from ``root`` (per-device code under shard_map)."""
    return jax.tree_util.tree_map(
        lambda x: hierarchical_broadcast(
            x, root=root, ici_axis=ici_axis, dcn_axis=dcn_axis),
        tree)


def _blockwise_quantize(x: jax.Array, block: int):
    """int8-quantize with one f32 scale per ``block`` values (x is padded
    to a block multiple by the caller). Returns (q[int8], scales[f32])."""
    b = x.reshape(-1, block).astype(jnp.float32)
    scale = jnp.max(jnp.abs(b), axis=1, keepdims=True) / 127.0
    safe = jnp.where(scale == 0.0, 1.0, scale)
    q = jnp.clip(jnp.round(b / safe), -127, 127).astype(jnp.int8)
    return q, scale


def _blockwise_dequantize(q: jax.Array, scale: jax.Array) -> jax.Array:
    return (q.astype(jnp.float32) * scale).reshape(-1)


def _quantized_reduce_scatter(flat: jax.Array, axis: str, block: int
                              ) -> jax.Array:
    """int8 reduce-scatter over ``axis``: quantize per destination chunk,
    all-to-all the int8 chunks + per-block f32 scales, sum dequantized
    locally. ``flat`` length must be divisible by (axis_size * block).
    Returns this device's 1/k shard of the sum in f32."""
    k = _axis_size(axis)
    chunk = flat.shape[0] // k
    q, scale = _blockwise_quantize(flat, block)           # [nb, block]
    q = q.reshape(k, chunk // block, block)
    scale = scale.reshape(k, chunk // block, 1)
    q_recv = lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                            tiled=False)
    s_recv = lax.all_to_all(scale, axis, split_axis=0, concat_axis=0,
                            tiled=False)
    return jnp.sum(q_recv.astype(jnp.float32) * s_recv, axis=0).reshape(-1)


def _quantized_all_gather(shard: jax.Array, axis: str, block: int
                          ) -> jax.Array:
    """int8 all-gather over ``axis``: each device ships its quantized
    shard + scales; everyone dequantizes the concatenation."""
    q, s = _blockwise_quantize(shard, block)
    q_all = lax.all_gather(q, axis, axis=0, tiled=True)
    s_all = lax.all_gather(s, axis, axis=0, tiled=True)
    return _blockwise_dequantize(q_all, s_all)


def quantized_all_reduce(
    x: jax.Array,
    *,
    ici_axis: Optional[str] = "ici",
    dcn_axis: Optional[str] = "dcn",
    average: bool = True,
    block: int = 256,
    quantize_dcn: bool = False,
) -> jax.Array:
    """Hierarchical all-reduce with int8 blockwise-quantized transport
    (EQuARX-style, PAPERS.md: arXiv 2506.17615): ~4x the effective
    bandwidth of f32 (2x bf16) at ~1e-2 relative error per stage.

    Per-device code under shard_map. Each quantized level runs the same
    scheme: reduce-scatter becomes an all-to-all of int8 chunks +
    per-block f32 scales with local f32 summation, and the return
    all-gather ships int8 too.

    ``quantize_dcn=False`` (default) keeps the cross-slice stage exact
    (f32 psum) — double quantization compounds error, and in PS mode the
    DCN bytes are the C-core codec layer's job. ``quantize_dcn=True``
    applies the same int8 scheme to the dcn axis: in pure collective
    mode the DCN is the *slow* fabric, so that is where the 4x matters
    most; each shard crosses DCN as int8 both ways. Pair with error
    feedback at the optimizer level if the noise matters.
    """
    ici = ici_axis if ici_axis and _axis_size(ici_axis) > 1 else None
    dcn = dcn_axis if dcn_axis and _axis_size(dcn_axis) > 1 else None
    denom = _axis_size(ici) * _axis_size(dcn)

    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    n = flat.shape[0]

    if ici is None and dcn is None:
        return x
    if ici is None:
        # Single-chip slices: the dcn axis is the only level. With
        # quantize_dcn it becomes the (sole) quantized level — fall
        # through to the generic stages with dcn playing ici's role.
        if quantize_dcn:
            ici, dcn = dcn, None
        else:
            flat = lax.psum(flat, dcn)
            if average and denom > 1:
                flat = flat / denom
            return flat.reshape(orig_shape).astype(orig_dtype)

    k = _axis_size(ici)
    kd = _axis_size(dcn) if dcn else 1
    # Pad so the ici shard also tiles (dcn_size * block) when the dcn
    # level is quantized too.
    pad = (-n) % (k * kd * block if (dcn and quantize_dcn) else k * block)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])

    # Stage 1: int8 reduce-scatter over the fast axis.
    shard = _quantized_reduce_scatter(flat, ici, block)

    # Stage 2: cross-slice reduction — exact psum, or the same int8
    # scheme when the slow fabric's bytes dominate.
    if dcn is not None:
        if quantize_dcn:
            dshard = _quantized_reduce_scatter(shard, dcn, block)
            if average:
                dshard = dshard / denom
            shard = _quantized_all_gather(dshard, dcn, block)
        else:
            shard = lax.psum(shard, dcn)
            if average:
                shard = shard / denom
    elif average and denom > 1:
        shard = shard / denom

    # Stage 3: int8 all-gather back over the fast axis.
    out = _quantized_all_gather(shard, ici, block)
    if pad:
        out = out[:n]
    return out.reshape(orig_shape).astype(orig_dtype)


def tree_quantized_all_reduce(
    tree,
    *,
    ici_axis: Optional[str] = "ici",
    dcn_axis: Optional[str] = "dcn",
    average: bool = True,
    block: int = 256,
    quantize_dcn: bool = False,
):
    """Fused pytree variant of quantized_all_reduce: one flat f32 buffer,
    one quantized collective pair (tensor fusion, as tree_all_reduce)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    if _axis_size(ici_axis) * _axis_size(dcn_axis) == 1:
        return tree  # identity on a 1x1 mesh — skip the quantize round-trip
    sizes = [l.size for l in leaves]
    flat = jnp.concatenate(
        [l.reshape(-1).astype(jnp.float32) for l in leaves])
    flat = quantized_all_reduce(flat, ici_axis=ici_axis, dcn_axis=dcn_axis,
                                average=average, block=block,
                                quantize_dcn=quantize_dcn)
    out, off = [], 0
    for leaf, sz in zip(leaves, sizes):
        out.append(flat[off:off + sz].reshape(leaf.shape)
                   .astype(leaf.dtype))
        off += sz
    return jax.tree_util.tree_unflatten(treedef, out)
