"""Mixture-of-Experts FFN with expert parallelism (all-to-all dispatch).

Beyond-reference scope (SURVEY.md §2.7: EP absent from the reference);
opens the expert-parallel mesh axis the task brief asks for. GShard-shaped
design: top-1 gating with a capacity limit, one-hot dispatch/combine
einsums (MXU-friendly — no gathers/scatters in the hot path), and when an
``ep_axis`` is given the dispatched [experts, capacity, d] blocks ride two
``lax.all_to_all``s so each device runs only its local experts over the
full (global) token set.

Per-device code under ``shard_map`` when ``ep_axis`` is set; plain dense
computation otherwise.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from byteps_tpu.jax._compat import axis_size as _axis_size


def moe_dispatch(gate_logits: jax.Array, capacity: int,
                 _legacy_capacity: Optional[int] = None):
    """Top-1 dispatch/combine tensors.

    gate_logits: [T, E]. Returns (dispatch [T, E, C] one-hot,
    combine [T, E, C] gate-weighted, aux_loss scalar). Tokens beyond an
    expert's capacity are dropped (their combine weights are zero) — the
    standard capacity-factor contract.

    Accepts the pre-0.2 POSITIONAL 3-arg form ``moe_dispatch(x,
    gate_logits, capacity)`` (the token tensor was never used by the
    dispatch math) with a DeprecationWarning; remove the leading ``x``
    argument. Legacy calls that passed any of those args by keyword are
    not shimmed — they fail with Python's own "multiple values"
    TypeError at the call site.
    """
    if _legacy_capacity is not None:
        import warnings
        warnings.warn(
            "moe_dispatch(x, gate_logits, capacity) is deprecated; the "
            "leading token tensor was dropped — call "
            "moe_dispatch(gate_logits, capacity)",
            DeprecationWarning, stacklevel=2)
        gate_logits, capacity = capacity, _legacy_capacity
    import operator
    try:
        capacity = operator.index(capacity)  # any int-like, incl. 0-d jnp int
    except TypeError:
        # Catches any call where capacity ends up a tensor (e.g. a legacy
        # positional call that slipped the gate logits into this slot)
        # before it turns into a confusing deep-in-JAX error.
        raise TypeError(
            "moe_dispatch capacity must be a static int; got "
            f"{type(capacity).__name__}. Note the signature changed from "
            "moe_dispatch(x, gate_logits, capacity) to "
            "moe_dispatch(gate_logits, capacity) — drop the leading token "
            "tensor.") from None
    t, e = gate_logits.shape
    gates = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(gates, axis=-1)                    # [T]
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)  # [T, E]
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0        # [T, E]
    keep = (pos >= 0) & (pos < capacity)
    slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                          dtype=jnp.float32)               # [T, E, C]
    dispatch = slot * keep[..., None]
    gate_val = (gates * onehot).sum(-1, keepdims=True)     # [T, 1]
    combine = dispatch * gate_val[..., None]
    # load-balancing auxiliary loss (Shazeer et al.): mean_gate · frac
    density = onehot.mean(axis=0)
    density_proxy = gates.mean(axis=0)
    aux = (density * density_proxy).sum() * (e ** 2) / e
    return dispatch, combine, aux


def moe_dispatch_top2(gate_logits: jax.Array, capacity: int):
    """Top-2 dispatch/combine tensors (GShard's original gating).

    gate_logits: [T, E]. Each token routes to its best TWO experts with
    combine weights renormalised over the CHOSEN pair (before capacity
    masking, as in GShard: a dropped second choice forfeits its share
    rather than re-inflating the first); second choices queue behind all
    first choices (GShard's position offset), so under capacity pressure
    first choices win slots. Returns
    (dispatch [T, E, C], combine [T, E, C], aux_loss).
    """
    t, e = gate_logits.shape
    gates = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    top_v, top_i = lax.top_k(gates, 2)                      # [T, 2]
    norm = top_v / jnp.maximum(top_v.sum(-1, keepdims=True), 1e-9)

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    fill = jnp.zeros((e,), jnp.float32)  # slots taken by earlier choices
    for c in range(2):
        onehot = jax.nn.one_hot(top_i[:, c], e, dtype=jnp.float32)
        pos = (jnp.cumsum(onehot, axis=0) - 1.0 + fill[None, :]) * onehot
        keep = onehot.astype(bool) & (pos < capacity)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                              dtype=jnp.float32)
        d_c = slot * keep[..., None]
        dispatch = dispatch + d_c
        combine = combine + d_c * norm[:, c][:, None, None]
        fill = fill + onehot.sum(axis=0)

    # load balancing on FIRST choices (GShard): fraction routed x mean gate
    first = jax.nn.one_hot(top_i[:, 0], e, dtype=jnp.float32)
    aux = (first.mean(0) * gates.mean(0)).sum() * (e ** 2) / e
    return dispatch, combine, aux


def moe_ffn(
    x: jax.Array,
    gate_w: jax.Array,
    w1: jax.Array,
    w2: jax.Array,
    *,
    capacity_factor: float = 1.25,
    ep_axis: Optional[str] = None,
    top_k: int = 1,
):
    """Top-1 (Switch) or top-2 (GShard) MoE feed-forward.

    x: [T, D] (local tokens); gate_w: [D, E]; w1: [E, D, H]; w2: [E, H, D].
    With ``ep_axis`` (size n, per-device code): E must be divisible by n;
    each device holds ALL expert weights but computes only its E/n local
    experts over the globally dispatched slots — pair with a sharded
    weight layout in real deployments. ``top_k=2`` routes each token to
    its two best experts (combine weights renormalised over the pair;
    size the capacity_factor ~2x accordingly). Returns ([T, D], aux_loss).
    """
    t, d = x.shape
    e = gate_w.shape[1]
    logits = x @ gate_w
    n = _axis_size(ep_axis) if ep_axis else 1
    # Per-DEVICE capacity (GShard): each device dispatches at most
    # cf·t_local/e slots per expert, keeping per-device slot volume at 1/n
    # of the dense problem (imbalance beyond cf is dropped, by design).
    capacity = max(1, int(capacity_factor * t / e))

    if top_k == 1:
        dispatch, combine, aux = moe_dispatch(logits, capacity)
    elif top_k == 2:
        dispatch, combine, aux = moe_dispatch_top2(logits, capacity)
    else:
        raise ValueError(f"top_k must be 1 or 2, got {top_k}")
    # [T, E, C] x [T, D] -> [E, C, D]
    slots = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))

    if ep_axis is None:
        h = jnp.einsum("ecd,edh->ech", slots, w1.astype(jnp.float32))
        h = jax.nn.gelu(h)
        out = jnp.einsum("ech,ehd->ecd", h, w2.astype(jnp.float32))
    else:
        if e % n != 0:
            raise ValueError(f"experts ({e}) must divide by '{ep_axis}' "
                             f"axis size ({n})")
        el = e // n
        me = lax.axis_index(ep_axis)
        # send each expert block to its owner; receive all devices' slots
        # for MY experts, stacked on the capacity-ish axis
        recv = lax.all_to_all(slots, ep_axis, split_axis=0, concat_axis=1,
                              tiled=True)                  # [El, n*C, D]
        w1_l = lax.dynamic_slice_in_dim(w1, me * el, el, 0)
        w2_l = lax.dynamic_slice_in_dim(w2, me * el, el, 0)
        h = jnp.einsum("ecd,edh->ech", recv, w1_l.astype(jnp.float32))
        h = jax.nn.gelu(h)
        out_l = jnp.einsum("ech,ehd->ecd", h, w2_l.astype(jnp.float32))
        # route results back to the tokens' home devices
        out = lax.all_to_all(out_l, ep_axis, split_axis=1, concat_axis=0,
                             tiled=True)                   # [E, C, D]

    y = jnp.einsum("tec,ecd->td", combine, out)
    if ep_axis is not None:
        aux = lax.pmean(aux, ep_axis)
    return y.astype(x.dtype), aux
