"""Ulysses-style sequence parallelism: all-to-all head/sequence resharding.

Long-context scope beyond reference parity (SURVEY.md §5 notes the
reference has no sequence parallelism); companion to
``byteps_tpu.parallel.ring_attention``.

The DeepSpeed-Ulysses shape: activations arrive sequence-sharded
[B, S/n, H, D]. One ``lax.all_to_all`` over the sequence axis reshards to
head-sharded [B, S, H/n, D] — each device then computes *exact* attention
over the full sequence for its head group (any attention kernel works,
including the Pallas flash kernel) — and a second all-to-all restores
sequence sharding. Communication is two all-to-alls of the activations
(O(B·S·H·D/n) per device) instead of ring attention's n-step K/V rotation;
on an all-to-all-rich ICI fabric this is often the cheaper long-context
schedule when heads divide evenly.

Per-device code: call inside ``jax.shard_map``.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
from jax import lax

from byteps_tpu.jax._compat import axis_size as _axis_size

from byteps_tpu.parallel.ring_attention import full_attention

AttnFn = Callable[..., jax.Array]


def _seq_to_heads(x: jax.Array, axis: str) -> jax.Array:
    # [B, S/n, H, D] -> [B, S, H/n, D]
    return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)


def _heads_to_seq(x: jax.Array, axis: str) -> jax.Array:
    # [B, S, H/n, D] -> [B, S/n, H, D]
    return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis: str = "sp",
    causal: bool = False,
    scale: Optional[float] = None,
    attn_fn: Optional[AttnFn] = None,
) -> jax.Array:
    """Exact attention over a sequence sharded on mesh axis ``axis`` via
    head/sequence all-to-all resharding.

    ``q``/``k``/``v``: local blocks [batch, seq_local, heads, head_dim];
    ``heads`` must be divisible by the axis size. ``attn_fn`` replaces the
    inner full-sequence attention (signature: (q, k, v, *, causal, scale));
    defaults to the exact softmax attention.
    """
    n = _axis_size(axis)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(
            f"ulysses_attention needs heads ({h}) divisible by the "
            f"'{axis}' axis size ({n}); use ring_attention otherwise")
    inner = attn_fn or full_attention
    if n == 1:
        return inner(q, k, v, causal=causal, scale=scale)

    qh = _seq_to_heads(q, axis)
    kh = _seq_to_heads(k, axis)
    vh = _seq_to_heads(v, axis)
    out = inner(qh, kh, vh, causal=causal, scale=scale)
    return _heads_to_seq(out, axis)


@partial(jax.jit, static_argnums=(3, 4, 5, 6, 7))
def _ulysses_sharded_impl(q, k, v, mesh, axis, causal, scale, attn_fn):
    from jax.sharding import PartitionSpec as P

    from byteps_tpu.jax._compat import shard_map as _shard_map

    spec = P(None, axis, None, None)
    run = _shard_map(
        lambda ql, kl, vl: ulysses_attention(ql, kl, vl, axis=axis,
                                             causal=causal, scale=scale,
                                             attn_fn=attn_fn),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return run(q, k, v)


def ulysses_attention_sharded(q, k, v, mesh, *, axis: str = "sp",
                              causal: bool = False,
                              scale: Optional[float] = None,
                              attn_fn: Optional[AttnFn] = None):
    """Convenience wrapper: global [B, S, H, D] arrays in, jitted
    shard_map'd Ulysses attention over ``mesh``'s ``axis`` out. The jit
    cache is keyed on (mesh, axis, causal, scale, attn_fn) — loops don't
    recompile (pass a stable ``attn_fn``, not a fresh lambda per call)."""
    return _ulysses_sharded_impl(q, k, v, mesh, axis, causal, scale,
                                 attn_fn)
