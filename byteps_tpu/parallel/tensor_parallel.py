"""Tensor parallelism: Megatron-style column/row-parallel layers.

Beyond-reference scope (SURVEY.md §2.7: BytePS has no TP), added because
the TPU design keeps every mesh axis first-class (§7 "leave the mesh-axis
door open"). The layout is the standard pairing:

    y = f(x @ A) @ B,   A column-sharded, B row-sharded over axis 'tp'
    -> one psum at the pair's output; the activation between A and B
       stays sharded (its heads/hidden slice), never materialised full.

Everything here is *per-device* code for use under ``jax.shard_map`` with
a mesh that has the given axis; the weight tensors passed in are the
LOCAL shards. XLA turns the single ``psum`` per pair into one fused ICI
all-reduce — the whole point of the column-then-row ordering.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from byteps_tpu.jax._compat import axis_size as _axis_size


def column_parallel(x: jax.Array, w_shard: jax.Array,
                    b_shard: Optional[jax.Array] = None) -> jax.Array:
    """Local half of a column-parallel matmul: returns THIS device's slice
    of the output features. No communication (inputs are replicated)."""
    y = x @ w_shard
    if b_shard is not None:
        y = y + b_shard
    return y


def row_parallel(x_shard: jax.Array, w_shard: jax.Array,
                 axis: str = "tp",
                 bias: Optional[jax.Array] = None) -> jax.Array:
    """Row-parallel matmul closing a column-parallel pair: each device
    contributes a partial product over its input slice; one psum over
    ``axis`` produces the full output on every device. ``bias`` is the
    full (unsharded) bias, added after the reduction."""
    y = lax.psum(x_shard @ w_shard, axis)
    if bias is not None:
        y = y + bias
    return y


def tp_mlp(x: jax.Array, w_in_shard: jax.Array, w_out_shard: jax.Array,
           *, axis: str = "tp",
           activation: Callable[[jax.Array], jax.Array] = jax.nn.gelu,
           b_in_shard: Optional[jax.Array] = None,
           b_out: Optional[jax.Array] = None) -> jax.Array:
    """The canonical TP transformer MLP: column-parallel in-projection,
    activation on the local hidden slice, row-parallel out-projection,
    one all-reduce total."""
    h = activation(column_parallel(x, w_in_shard, b_in_shard))
    return row_parallel(h, w_out_shard, axis, bias=b_out)


def tp_attention(x: jax.Array, wq_shard: jax.Array, wk_shard: jax.Array,
                 wv_shard: jax.Array, wo_shard: jax.Array,
                 *, axis: str = "tp", num_local_heads: int,
                 causal: bool = False,
                 attn_fn: Optional[Callable] = None) -> jax.Array:
    """Head-parallel self-attention: each device owns ``num_local_heads``
    heads end to end (q/k/v column-sharded by head, output row-sharded),
    one psum at the output projection.

    ``x``: [batch, seq, d_model] replicated; w*_shard: [d_model,
    local_heads*head_dim] (wo_shard transposed: [local_heads*head_dim,
    d_model]). ``attn_fn`` defaults to exact softmax attention
    (byteps_tpu.parallel.full_attention); pass the Pallas flash kernel
    for long sequences.
    """
    from byteps_tpu.parallel.ring_attention import full_attention

    b, s, _ = x.shape
    q = (x @ wq_shard).reshape(b, s, num_local_heads, -1)
    k = (x @ wk_shard).reshape(b, s, num_local_heads, -1)
    v = (x @ wv_shard).reshape(b, s, num_local_heads, -1)
    inner = attn_fn or full_attention
    out = inner(q, k, v, causal=causal)
    out = out.reshape(b, s, -1)
    return row_parallel(out, wo_shard, axis)


def shard_columns(w: jax.Array, axis: str = "tp") -> jax.Array:
    """Per-device code: slice the LAST dim of a replicated weight into
    this device's column shard (convenience for loading unsharded
    checkpoints under shard_map)."""
    n = _axis_size(axis)
    i = lax.axis_index(axis)
    cols = w.shape[-1] // n
    return lax.dynamic_slice_in_dim(w, i * cols, cols, axis=w.ndim - 1)


def shard_rows(w: jax.Array, axis: str = "tp") -> jax.Array:
    """Per-device code: slice the FIRST dim into this device's row shard."""
    n = _axis_size(axis)
    i = lax.axis_index(axis)
    rows = w.shape[0] // n
    return lax.dynamic_slice_in_dim(w, i * rows, rows, axis=0)
