"""ZeRO-style sharded optimizer state over the data-parallel mesh.

Beyond-reference scope (SURVEY.md §2.7: BytePS has no optimizer sharding —
its servers hold aggregation buffers only). TPU-first shape: flatten the
parameter pytree into ONE fused buffer, reduce-scatter gradients so each
device owns 1/n of them (ZeRO-2 communication: same bytes as an
all-reduce's first half), update ONLY the owned shard with optimizer
state allocated for that shard alone (ZeRO-1 memory: optimizer state
divided by the axis size), then all-gather the updated parameters.

Exactness: elementwise optimizers (SGD/momentum/Adam/AdamW/...) act
per-parameter, so for float32 parameters the sharded update is
bit-identical to the unsharded one — verified against the dense step in
tests. Lower-precision params follow the master-weights recipe instead:
the flat buffer, optimizer state, and update run in float32 and params
are cast back afterwards (more accurate than a bf16-state dense step,
not bitwise equal to it; the all-gather also ships f32). Optimizers that
couple elements across the tree (e.g. global-norm clipping) need the
coupling computed globally first; compose with
``optax.clip_by_global_norm`` OUTSIDE this step or psum the norm
yourself.

Per-device code for use under ``jax.shard_map`` over axis ``axis``.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from byteps_tpu.jax._compat import axis_size as _axis_size


def _flatten(tree) -> Tuple[jax.Array, list, list, "jax.tree_util.PyTreeDef"]:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    flat = jnp.concatenate([l.reshape(-1).astype(jnp.float32)
                            for l in leaves])
    shapes = [l.shape for l in leaves]
    dtypes = [l.dtype for l in leaves]
    return flat, shapes, dtypes, treedef


def _unflatten(flat, shapes, dtypes, treedef):
    out, off = [], 0
    for shape, dtype in zip(shapes, dtypes):
        n = 1
        for s in shape:
            n *= s
        out.append(flat[off:off + n].reshape(shape).astype(dtype))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def zero_init(params, optimizer: optax.GradientTransformation,
              axis: str = "ici"):
    """Per-device code: initialise THIS device's optimizer-state shard
    (state over the f32 flat shard; padding is recomputed by
    ``zero_apply``)."""
    n = _axis_size(axis)
    idx = lax.axis_index(axis)
    flat, _, _, _ = _flatten(params)
    pad = (-flat.shape[0]) % n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    shard_len = flat.shape[0] // n
    my = lax.dynamic_slice_in_dim(flat, idx * shard_len, shard_len)
    return optimizer.init(my)


def zero_apply(params, grads, opt_state_shard,
               optimizer: optax.GradientTransformation,
               *, axis: str = "ici", average: bool = True):
    """Per-device code: one sharded-optimizer update.

    ``grads`` are this device's LOCAL gradients (pre-reduction); the
    reduce-scatter of the fused gradient buffer is the communication
    equivalent of the all-reduce's first half. Returns
    ``(new_params, new_opt_state_shard)``.
    """
    n = _axis_size(axis)
    idx = lax.axis_index(axis)
    flat_p, shapes, dtypes, treedef = _flatten(params)
    flat_g, _, _, _ = _flatten(grads)
    pad = (-flat_p.shape[0]) % n
    if pad:
        z = jnp.zeros((pad,), jnp.float32)
        flat_p = jnp.concatenate([flat_p, z])
        flat_g = jnp.concatenate([flat_g, z])
    shard_len = flat_p.shape[0] // n

    g_shard = lax.psum_scatter(flat_g, axis, scatter_dimension=0,
                               tiled=True)
    if average:
        g_shard = g_shard / n
    p_shard = lax.dynamic_slice_in_dim(flat_p, idx * shard_len, shard_len)
    updates, opt_state_shard = optimizer.update(g_shard, opt_state_shard,
                                                p_shard)
    p_shard = optax.apply_updates(p_shard, updates)
    flat_new = lax.all_gather(p_shard, axis, axis=0, tiled=True)
    if pad:
        flat_new = flat_new[:-pad]
    return _unflatten(flat_new, shapes, dtypes, treedef), opt_state_shard


def make_zero_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh=None,
    *,
    axis: Optional[str] = None,
    donate: bool = True,
):
    """Build a jitted DP step with ZeRO-sharded optimizer state.

    ``step(params, opt_state_shard, batch) ->
    (params, opt_state_shard, loss)`` — same contract as
    ``make_train_step`` but ``opt_state_shard`` comes from
    ``zero_init_sharded`` and is 1/axis_size the size. The batch is
    sharded over ALL mesh axes; optimizer state shards over ``axis``
    (default: the innermost/ici axis).
    """
    from functools import partial

    from jax.sharding import PartitionSpec as P

    import byteps_tpu.jax as bps
    from byteps_tpu.jax._compat import shard_map as _shard_map

    mesh = mesh or bps.mesh()
    cfg = bps._st().config
    if cfg.use_ps:
        raise NotImplementedError(
            "make_zero_train_step covers collective mode only; in PS mode "
            "the cross-host reduction rides the DCN leg, which this step "
            "does not drive — use make_train_step, or shard manually with "
            "zero_apply inside your own step")
    batch_axes = tuple(a for a in (cfg.dcn_axis, cfg.ici_axis)
                       if a in mesh.axis_names)
    if not batch_axes:
        raise ValueError(
            f"mesh axes {mesh.axis_names} do not include the configured "
            f"dcn/ici axes ({cfg.dcn_axis!r}, {cfg.ici_axis!r}); build the "
            "mesh with byteps_tpu.parallel.mesh.build_mesh or init with "
            "matching axis names")
    shard_axis = axis or batch_axes[-1]
    other_axes = tuple(a for a in batch_axes if a != shard_axis)

    @partial(_shard_map, mesh=mesh,
             in_specs=(P(), P(shard_axis), P(batch_axes)),
             out_specs=(P(), P(shard_axis), P()),
             check_vma=False)
    def _step(params, opt_state_shard, batch):
        opt_state_shard = jax.tree_util.tree_map(
            lambda x: x[0], opt_state_shard)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        # fold the non-sharding axes in first (plain mean), then the
        # sharded axis via the fused reduce-scatter inside zero_apply
        for ax in other_axes:
            grads = jax.tree_util.tree_map(
                lambda g, a=ax: lax.pmean(g, a), grads)
            loss = lax.pmean(loss, ax)
        params, opt_state_shard = zero_apply(
            params, grads, opt_state_shard, optimizer, axis=shard_axis)
        loss = lax.pmean(loss, shard_axis)
        return params, jax.tree_util.tree_map(
            lambda x: x[None], opt_state_shard), loss

    jit_kwargs = {"donate_argnums": (0, 1)} if donate else {}
    return jax.jit(_step, **jit_kwargs)


def zero_init_sharded(params, optimizer: optax.GradientTransformation,
                      mesh=None, *, axis: Optional[str] = None):
    """Host-level: build the sharded optimizer state for
    ``make_zero_train_step`` (stacked over the shard axis)."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    import byteps_tpu.jax as bps
    from byteps_tpu.jax._compat import shard_map as _shard_map

    mesh = mesh or bps.mesh()
    cfg = bps._st().config
    batch_axes = tuple(a for a in (cfg.dcn_axis, cfg.ici_axis)
                       if a in mesh.axis_names)
    if not batch_axes:
        raise ValueError(
            f"mesh axes {mesh.axis_names} do not include the configured "
            f"dcn/ici axes ({cfg.dcn_axis!r}, {cfg.ici_axis!r})")
    shard_axis = axis or batch_axes[-1]

    @jax.jit
    @partial(_shard_map, mesh=mesh, in_specs=(P(),),
             out_specs=P(shard_axis), check_vma=False)
    def _init(p):
        state = zero_init(p, optimizer, axis=shard_axis)
        return jax.tree_util.tree_map(lambda x: x[None], state)

    return _init(params)
