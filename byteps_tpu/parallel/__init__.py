"""Mesh/sharding utilities: hierarchical DP, sequence/context parallelism,
tensor parallelism, pipeline parallelism, expert parallelism.

The reference (SURVEY.md §2.7) ships data parallelism (sync + async) with
hierarchical two-level reduction. This package provides that as the core
(``hierarchical``) and adds the TPU-first mesh-axis generalizations the
task requires (ring attention SP, TP, PP, EP) as first-class citizens.
"""

from byteps_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    build_mesh,
    global_mesh,
    set_global_mesh,
)
from byteps_tpu.parallel.hierarchical import (  # noqa: F401
    hierarchical_all_reduce,
    hierarchical_broadcast,
    tree_all_reduce,
    tree_broadcast,
)
from byteps_tpu.parallel.ring_attention import (  # noqa: F401
    full_attention,
    ring_attention,
    ring_attention_sharded,
)
from byteps_tpu.parallel.ulysses import (  # noqa: F401
    ulysses_attention,
    ulysses_attention_sharded,
)
from byteps_tpu.parallel.moe import moe_dispatch, moe_dispatch_top2, moe_ffn  # noqa: F401
from byteps_tpu.parallel.hierarchical import (  # noqa: F401
    quantized_all_reduce,
)
from byteps_tpu.parallel.pipeline import (gpipe, pipeline_1f1b,
                                           stage_params)  # noqa: F401
from byteps_tpu.parallel.zero import (  # noqa: F401
    make_zero_train_step,
    zero_apply,
    zero_init,
    zero_init_sharded,
)
from byteps_tpu.parallel.tensor_parallel import (  # noqa: F401
    column_parallel,
    row_parallel,
    shard_columns,
    shard_rows,
    tp_attention,
    tp_mlp,
)
