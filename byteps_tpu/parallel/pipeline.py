"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

Beyond-reference scope (SURVEY.md §2.7: BytePS has no PP), same rationale
as tensor_parallel.py — every mesh axis is first-class. TPU-first shape:
the schedule is a single ``lax.fori_loop`` of identical SPMD ticks, with
stage-to-stage transfer as a ring ``ppermute`` (one ICI hop), so XLA sees
a static program: no per-stage host control flow, no dynamic shapes.
Backward works through ``jax.grad`` (the transpose of ppermute is the
reverse ppermute), giving full GPipe training semantics: all microbatch
gradients accumulate before any optimizer step.

Per-device code for use under ``jax.shard_map``: each device owns ONE
stage's parameters and processes every microbatch in turn; with M
microbatches and N stages the loop runs M + N - 1 ticks, the classic
GPipe bubble fraction (N-1)/(M+N-1).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def gpipe(
    stage_fn: Callable,
    params_local,
    microbatches: jax.Array,
    *,
    axis: str = "pp",
) -> jax.Array:
    """Run ``microbatches`` through the N-stage pipeline.

    - ``stage_fn(params_local, x) -> y``: this device's stage; activations
      ``x``/``y`` must share one shape across stages (the usual
      transformer-block contract).
    - ``params_local``: THIS device's stage parameters (e.g. produced by
      slicing a stacked [N, ...] tree with ``lax.index_in_dim`` on
      ``lax.axis_index(axis)``).
    - ``microbatches``: [M, ...] replicated input; M >= 1.

    Returns [M, ...] final-stage outputs, replicated to every device (one
    all-gather-free ppermute ring closes the loop: the last stage feeds
    device 0's carry, which is where outputs are read off).
    """
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    m = microbatches.shape[0]
    act_shape = microbatches.shape[1:]
    perm = [(i, (i + 1) % n) for i in range(n)]

    outputs0 = jnp.zeros((m,) + act_shape, microbatches.dtype)
    carry0 = jnp.zeros(act_shape, microbatches.dtype)

    def tick(t, state):
        carry, outputs = state
        # Stage 0 ingests microbatch t (while available); other stages
        # consume what the ring delivered last tick.
        feed_idx = jnp.clip(t, 0, m - 1)
        first_in = lax.dynamic_index_in_dim(microbatches, feed_idx, 0,
                                            keepdims=False)
        x = jnp.where(idx == 0, first_in, carry)
        y = stage_fn(params_local, x)
        # Microbatch id at this device this tick; valid while 0 <= id < m.
        mb = t - idx
        valid = jnp.logical_and(mb >= 0, mb < m)
        y = jnp.where(valid, y, jnp.zeros_like(y))
        # Ring transfer: stage d -> d+1; the last stage's wrap-around to
        # device 0 carries the FINISHED microbatch, captured below.
        moved = lax.ppermute(y, axis, perm)
        # Device 0 received the last stage's output for microbatch t-(n-1).
        done_mb = t - (n - 1)
        take = jnp.logical_and(idx == 0,
                               jnp.logical_and(done_mb >= 0, done_mb < m))
        slot = jnp.clip(done_mb, 0, m - 1)
        updated = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(take, moved,
                               lax.dynamic_index_in_dim(
                                   outputs, slot, 0, keepdims=False)),
            slot, 0)
        return moved, updated

    _, outputs = lax.fori_loop(0, m + n - 1, tick, (carry0, outputs0))
    # Outputs accumulated on device 0's copy; replicate via psum of the
    # masked buffer (every other device holds zeros there).
    outputs = jnp.where(idx == 0, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, axis)


def stage_params(stacked, axis: str = "pp"):
    """Per-device code: pick this device's stage slice from a pytree whose
    leaves are stacked [num_stages, ...]."""
    i = lax.axis_index(axis)
    return jax.tree_util.tree_map(
        lambda w: lax.dynamic_index_in_dim(w, i, 0, keepdims=False),
        stacked)
