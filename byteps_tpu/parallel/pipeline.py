"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

Beyond-reference scope (SURVEY.md §2.7: BytePS has no PP), same rationale
as tensor_parallel.py — every mesh axis is first-class. TPU-first shape:
the schedule is a single ``lax.fori_loop`` of identical SPMD ticks, with
stage-to-stage transfer as a ring ``ppermute`` (one ICI hop), so XLA sees
a static program: no per-stage host control flow, no dynamic shapes.
Backward works through ``jax.grad`` (the transpose of ppermute is the
reverse ppermute), giving full GPipe training semantics: all microbatch
gradients accumulate before any optimizer step.

Per-device code for use under ``jax.shard_map``: each device owns ONE
stage's parameters and processes every microbatch in turn; with M
microbatches and N stages the loop runs M + N - 1 ticks, the classic
GPipe bubble fraction (N-1)/(M+N-1).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from byteps_tpu.jax._compat import axis_size as _axis_size


def gpipe(
    stage_fn: Callable,
    params_local,
    microbatches: jax.Array,
    *,
    axis: str = "pp",
) -> jax.Array:
    """Run ``microbatches`` through the N-stage pipeline.

    - ``stage_fn(params_local, x) -> y``: this device's stage; activations
      ``x``/``y`` must share one shape across stages (the usual
      transformer-block contract).
    - ``params_local``: THIS device's stage parameters (e.g. produced by
      slicing a stacked [N, ...] tree with ``lax.index_in_dim`` on
      ``lax.axis_index(axis)``).
    - ``microbatches``: [M, ...] replicated input; M >= 1.

    Returns [M, ...] final-stage outputs, replicated to every device (one
    all-gather-free ppermute ring closes the loop: the last stage feeds
    device 0's carry, which is where outputs are read off).
    """
    n = _axis_size(axis)
    idx = lax.axis_index(axis)
    m = microbatches.shape[0]
    act_shape = microbatches.shape[1:]
    perm = [(i, (i + 1) % n) for i in range(n)]

    outputs0 = jnp.zeros((m,) + act_shape, microbatches.dtype)
    carry0 = jnp.zeros(act_shape, microbatches.dtype)

    def tick(t, state):
        carry, outputs = state
        # Stage 0 ingests microbatch t (while available); other stages
        # consume what the ring delivered last tick.
        feed_idx = jnp.clip(t, 0, m - 1)
        first_in = lax.dynamic_index_in_dim(microbatches, feed_idx, 0,
                                            keepdims=False)
        x = jnp.where(idx == 0, first_in, carry)
        y = stage_fn(params_local, x)
        # Microbatch id at this device this tick; valid while 0 <= id < m.
        mb = t - idx
        valid = jnp.logical_and(mb >= 0, mb < m)
        y = jnp.where(valid, y, jnp.zeros_like(y))
        # Ring transfer: stage d -> d+1; the last stage's wrap-around to
        # device 0 carries the FINISHED microbatch, captured below.
        moved = lax.ppermute(y, axis, perm)
        # Device 0 received the last stage's output for microbatch t-(n-1).
        done_mb = t - (n - 1)
        take = jnp.logical_and(idx == 0,
                               jnp.logical_and(done_mb >= 0, done_mb < m))
        slot = jnp.clip(done_mb, 0, m - 1)
        updated = lax.dynamic_update_index_in_dim(
            outputs, jnp.where(take, moved,
                               lax.dynamic_index_in_dim(
                                   outputs, slot, 0, keepdims=False)),
            slot, 0)
        return moved, updated

    _, outputs = lax.fori_loop(0, m + n - 1, tick, (carry0, outputs0))
    # Outputs accumulated on device 0's copy; replicate via psum of the
    # masked buffer (every other device holds zeros there).
    outputs = jnp.where(idx == 0, outputs, jnp.zeros_like(outputs))
    return lax.psum(outputs, axis)


def pipeline_1f1b(
    stage_fn: Callable,
    loss_fn: Callable,
    params_local,
    microbatches: jax.Array,
    targets: jax.Array,
    *,
    axis: str = "pp",
):
    """One-forward-one-backward (1F1B) pipeline training schedule.

    GPipe above differentiates through the whole M-tick loop, so every
    microbatch's activations stay live until the backward pass — O(M)
    activation memory per device. 1F1B starts each microbatch's backward
    as soon as the last stage finishes its forward: fwd(m) runs on
    device d at tick ``m + d`` (GPipe timing), bwd(m) at tick
    ``m + 2(N-1) - d``, so a stored input lives at most ``2(N-1-d)``
    ticks and the residual buffer is a fixed ``2N`` slots — **O(N)
    activation memory, independent of M**. Activations are recomputed
    from the stored stage INPUT during the backward tick (per-stage
    remat), the standard 1F1B memory/compute trade. Both ring transfers
    (activations +1, gradients -1) run unconditionally every tick, so
    XLA sees one static SPMD program of ``M + 2N - 2`` identical ticks.

    - ``stage_fn(params_local, x) -> y`` — as in :func:`gpipe`.
    - ``loss_fn(y, target) -> scalar`` — applied to the LAST stage's
      output per microbatch; the mean over microbatches is returned.
    - ``microbatches``: [M, ...] replicated input, ``targets``: [M, ...]
      replicated per-microbatch targets.

    Returns ``(loss, grads_local)``: the mean loss (replicated) and THIS
    device's stage-parameter gradients (of the mean loss) — apply your
    optimizer per stage locally; no jax.grad around this is needed.
    """
    n = _axis_size(axis)
    idx = lax.axis_index(axis)
    m = microbatches.shape[0]
    act_shape = microbatches.shape[1:]
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    bwd_perm = [(i, (i - 1) % n) for i in range(n)]
    r_slots = 2 * n  # max live stored inputs per device is 2(N-1)+1 < 2N

    fwd_carry0 = jnp.zeros(act_shape, microbatches.dtype)
    bwd_carry0 = jnp.zeros(act_shape, jnp.float32)
    resid0 = jnp.zeros((r_slots,) + act_shape, microbatches.dtype)
    grads0 = jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params_local)
    loss0 = jnp.float32(0.0)

    def tick(t, state):
        fwd_carry, bwd_carry, resid, grads, loss_acc = state

        # --- forward half: same timing as GPipe -------------------------
        mb_f = t - idx
        f_valid = jnp.logical_and(mb_f >= 0, mb_f < m)
        feed = lax.dynamic_index_in_dim(microbatches,
                                        jnp.clip(mb_f, 0, m - 1), 0,
                                        keepdims=False)
        x = jnp.where(idx == 0, feed, fwd_carry)
        y = stage_fn(params_local, x)
        y = jnp.where(f_valid, y, jnp.zeros_like(y))
        slot_f = jnp.clip(mb_f, 0, None) % r_slots
        old = lax.dynamic_index_in_dim(resid, slot_f, 0, keepdims=False)
        resid = lax.dynamic_update_index_in_dim(
            resid, jnp.where(f_valid, x, old), slot_f, 0)
        fwd_next = lax.ppermute(y, axis, fwd_perm)

        # --- backward half: bwd(m, d) at tick m + 2(N-1) - d ------------
        mb_b = t - 2 * (n - 1) + idx
        b_valid = jnp.logical_and(mb_b >= 0, mb_b < m)
        slot_b = jnp.clip(mb_b, 0, None) % r_slots
        x_saved = lax.dynamic_index_in_dim(resid, slot_b, 0,
                                           keepdims=False)
        y_b, vjp_fn = jax.vjp(stage_fn, params_local, x_saved)
        tgt = lax.dynamic_index_in_dim(targets,
                                       jnp.clip(mb_b, 0, m - 1), 0,
                                       keepdims=False)
        # Last stage seeds the gradient chain from the per-microbatch
        # loss; inner stages consume what the -1 ring delivered (bwd of
        # the next stage ran exactly one tick earlier — no buffering).
        loss_m, seed = jax.value_and_grad(
            lambda yy: loss_fn(yy, tgt) / m)(y_b)
        g_in = jnp.where(idx == n - 1, seed,
                         bwd_carry.astype(seed.dtype))
        g_in = jnp.where(b_valid, g_in, jnp.zeros_like(g_in))
        dp, dx = vjp_fn(g_in)
        grads = jax.tree_util.tree_map(
            lambda a, d: a + d.astype(jnp.float32), grads, dp)
        loss_acc = loss_acc + jnp.where(
            jnp.logical_and(b_valid, idx == n - 1), loss_m, 0.0)
        bwd_next = lax.ppermute(dx.astype(jnp.float32), axis, bwd_perm)

        return fwd_next, bwd_next, resid, grads, loss_acc

    _, _, _, grads, loss_acc = lax.fori_loop(
        0, m + 2 * n - 2, tick,
        (fwd_carry0, bwd_carry0, resid0, grads0, loss0))
    # loss_m was already divided by m; psum replicates the last stage's
    # accumulated mean to every device (others hold zero).
    return lax.psum(loss_acc, axis), grads


def stage_params(stacked, axis: str = "pp"):
    """Per-device code: pick this device's stage slice from a pytree whose
    leaves are stacked [num_stages, ...]."""
    i = lax.axis_index(axis)
    return jax.tree_util.tree_map(
        lambda w: lax.dynamic_index_in_dim(w, i, 0, keepdims=False),
        stacked)
