"""Horovod/Keras-compatible training callbacks for JAX training loops.

Capability parity: the reference's byteps/keras plugin (SURVEY.md §2.5):
``BroadcastGlobalVariablesCallback``, ``MetricAverageCallback``,
``LearningRateWarmupCallback`` — the same names and semantics, adapted to
functional JAX loops. A loop drives them through the small ``CallbackList``
protocol (on_train_begin / on_epoch_end / on_batch_end), or uses the optax
schedule builders directly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import numpy as np

import byteps_tpu.jax as bps


class Callback:
    """Protocol: a training loop calls these around its epochs/batches.
    ``state`` is the loop's mutable dict (params, opt_state, metrics...)."""

    def on_train_begin(self, state: Dict[str, Any]) -> None: ...
    def on_epoch_begin(self, epoch: int, state: Dict[str, Any]) -> None: ...
    def on_epoch_end(self, epoch: int, state: Dict[str, Any]) -> None: ...
    def on_batch_end(self, batch: int, state: Dict[str, Any]) -> None: ...


class CallbackList(Callback):
    def __init__(self, callbacks: List[Callback]):
        self._cbs = list(callbacks)

    def on_train_begin(self, state):
        for cb in self._cbs:
            cb.on_train_begin(state)

    def on_epoch_begin(self, epoch, state):
        for cb in self._cbs:
            cb.on_epoch_begin(epoch, state)

    def on_epoch_end(self, epoch, state):
        for cb in self._cbs:
            cb.on_epoch_end(epoch, state)

    def on_batch_end(self, batch, state):
        for cb in self._cbs:
            cb.on_batch_end(batch, state)


class BroadcastGlobalVariablesCallback(Callback):
    """Sync ``state['params']`` (and opt_state if present) from root at
    train begin — the reference's init-time weight sync as a callback."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, state):
        for key in ("params", "batch_stats"):
            if state.get(key) is not None:
                state[key] = bps.broadcast_parameters(
                    state[key], root_rank=self.root_rank)
        if state.get("opt_state") is not None:
            # optimizer state may hold non-array leaves (schedules, step
            # counters) — broadcast_optimizer_state skips those
            state["opt_state"] = bps.broadcast_optimizer_state(
                state["opt_state"], root_rank=self.root_rank)


class MetricAverageCallback(Callback):
    """Average ``state['metrics']`` across all workers at epoch end
    (reference: keras MetricAverageCallback)."""

    def on_epoch_end(self, epoch, state):
        metrics = state.get("metrics")
        if not metrics:
            return
        keys = sorted(metrics)
        vals = np.asarray([float(metrics[k]) for k in keys], np.float32)
        st = bps._st()
        if st.ps_client is not None:
            from byteps_tpu.jax.ps import ps_push_pull
            out = ps_push_pull(vals, average=True, prefix="metric_avg")
            vals = np.asarray(out)
        # Single-controller collective mode: metrics from a shard_map'd
        # step are already globally reduced (pmean in the step), so this
        # is the identity there — matching Horovod semantics where each
        # process holds a local value.
        state["metrics"] = {k: float(v) for k, v in zip(keys, vals)}


class LearningRateWarmupCallback(Callback):
    """Horovod-style LR warmup: scale from ``initial_lr`` to
    ``initial_lr * multiplier`` over ``warmup_epochs``. The loop reads
    ``state['lr']`` each step (or use ``warmup_schedule`` with optax)."""

    def __init__(self, initial_lr: float, multiplier: float,
                 warmup_epochs: int = 5, steps_per_epoch: int = 1,
                 verbose: bool = False):
        self.initial_lr = initial_lr
        self.multiplier = multiplier
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose
        self._batches = 0

    def _lr(self) -> float:
        total = max(1, self.warmup_epochs * self.steps_per_epoch)
        frac = min(1.0, self._batches / total)
        return self.initial_lr * (1.0 + frac * (self.multiplier - 1.0))

    def on_train_begin(self, state):
        state["lr"] = self._lr()

    def on_batch_end(self, batch, state):
        self._batches += 1
        state["lr"] = self._lr()
        if self.verbose and self._batches % self.steps_per_epoch == 0:
            print(f"warmup lr -> {state['lr']:.6f}")


class MonitorCallback(Callback):
    """Publish per-step training telemetry to the live monitor endpoint
    (byteps_tpu.monitor, docs/monitoring.md): step counter, per-step
    throughput, wire-byte deltas, queue depth, and credit occupancy.
    The numbers ride the same ``/metrics`` page as the C core's
    per-stage counters, so one scrape correlates training progress with
    communication health.

    The loop provides ``state['batch_size']`` (items per global step)
    for items/sec; without it only step timing and wire bytes are
    published. A summary dict also lands in ``state['monitor']`` each
    batch for in-loop consumers (loggers, progress bars)."""

    def __init__(self, batch_size: Optional[int] = None):
        self.batch_size = batch_size
        self._last_t: Optional[float] = None
        self._last_wire = (0, 0)
        self._steps = 0

    @staticmethod
    def _wire_bytes() -> tuple:
        try:
            import byteps_tpu.core.ffi as ffi
            if ffi._lib is None:
                # Collective mode: no C core loaded — don't trigger a
                # build just to report zero wire bytes.
                return (0, 0)
            van = ffi.metrics_snapshot().get("van", {})
            return (int(van.get("sent_bytes", 0)),
                    int(van.get("recv_bytes", 0)))
        except Exception:
            return (0, 0)

    def on_train_begin(self, state):
        import time
        self._last_t = time.perf_counter()
        self._last_wire = self._wire_bytes()

    def on_batch_end(self, batch, state):
        import time

        from byteps_tpu.monitor import inc_counter, set_gauge

        now = time.perf_counter()
        dt = now - (self._last_t or now)
        self._last_t = now
        self._steps += 1
        sent, recv = self._wire_bytes()
        d_sent = sent - self._last_wire[0]
        d_recv = recv - self._last_wire[1]
        self._last_wire = (sent, recv)

        inc_counter("bps_train_steps_total")
        set_gauge("bps_step_seconds", dt)
        set_gauge("bps_step_wire_sent_bytes", d_sent)
        set_gauge("bps_step_wire_recv_bytes", d_recv)
        report = {"step": self._steps, "step_seconds": dt,
                  "wire_sent_bytes": d_sent, "wire_recv_bytes": d_recv}
        batch_size = self.batch_size or state.get("batch_size")
        if batch_size and dt > 0:
            ips = batch_size / dt
            set_gauge("bps_examples_per_sec", ips)
            report["examples_per_sec"] = ips
        try:
            import byteps_tpu.core.ffi as ffi
            if ffi._lib is not None:
                q = ffi.metrics_snapshot().get("queue", {})
                report["queue_pending"] = int(q.get("pending", 0))
                report["credit_inflight_bytes"] = int(
                    q.get("inflight_bytes", 0))
        except Exception:
            pass
        state["monitor"] = report


def warmup_schedule(base_lr: float, multiplier: Optional[float] = None,
                    warmup_steps: int = 1000):
    """optax learning-rate schedule: linear warmup from ``base_lr`` to
    ``base_lr * multiplier`` (default: the device count — Horovod's
    'scale lr by workers' recipe), constant after."""
    import jax.numpy as jnp

    def schedule(step):
        mult = multiplier if multiplier is not None else float(
            bps.device_count() if bps.initialized() else jax.device_count())
        frac = jnp.minimum(1.0, step / max(1, warmup_steps))
        return base_lr * (1.0 + frac * (mult - 1.0))

    return schedule
