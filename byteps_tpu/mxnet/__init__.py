"""byteps_tpu.mxnet — MXNet framework plugin (Horovod-compatible API).

Capability parity target: reference byteps/mxnet (SURVEY.md §2.5): ``init``
/ ``rank`` / ``size``, ``byteps_push_pull(NDArray)``,
``DistributedTrainer`` (a ``gluon.Trainer`` whose ``_allreduce_grads``
push_pulls through the PS core), ``broadcast_parameters``.

MXNet is not installed in this environment (it is long past end-of-life
and absent from the image), so this module gates on import: the API is
implemented against MXNet's stable NDArray/gluon surface and raises a
clear ImportError when mxnet is missing rather than failing obscurely.
The transport underneath is byteps_tpu's C++ PS core, shared with the
torch/tensorflow plugins.

The plugin logic is still executed by CI: tests/test_ps_core.py runs
this module over a real localhost PS fleet with only the mxnet package
itself emulated by the API-faithful stub in tests/mxnet_stub.py.
"""

from __future__ import annotations

from typing import Optional

try:
    import mxnet as mx
    from mxnet import gluon
except ImportError as _e:  # pragma: no cover - environment-dependent
    raise ImportError(
        "byteps_tpu.mxnet requires the 'mxnet' package, which is not "
        "installed in this environment. The JAX (byteps_tpu.jax), PyTorch "
        "(byteps_tpu.torch), TensorFlow (byteps_tpu.tensorflow) and Keras "
        "(byteps_tpu.keras) plugins provide the same Horovod-compatible "
        "API surface.") from _e

import numpy as np

from byteps_tpu.config import Config, get_config

__all__ = [
    "init", "shutdown", "rank", "size", "local_rank", "local_size",
    "byteps_push_pull", "broadcast_parameters", "DistributedTrainer",
]

_client = None
_cfg: Optional[Config] = None
_declared = {}            # name -> (tensor_id, nelem, dtype_name)
_noname_seq = 0


def init(config: Optional[Config] = None) -> None:
    """Initialise the plugin (reference: byteps.mxnet.init)."""
    global _client, _cfg
    if _cfg is not None:
        return
    _cfg = config or get_config(reload=True)
    if _cfg.distributed:
        from byteps_tpu.core import ffi as _ffi
        _client = _ffi.Worker.start(_cfg)


def shutdown() -> None:
    global _client, _cfg, _noname_seq
    if _client is not None:
        _client.shutdown()
        _client = None
    _declared.clear()
    _noname_seq = 0
    _cfg = None


def rank() -> int:
    return _client.worker_rank() if _client is not None else 0


def size() -> int:
    return _client.num_workers() if _client is not None else 1


def local_rank() -> int:
    return _cfg.local_rank if _cfg else 0


def local_size() -> int:
    return _cfg.local_size if _cfg else 1


def _declare(name: str, nelem: int, dtype) -> int:
    dt = np.dtype(dtype).name
    cached = _declared.get(name)
    if cached is not None:
        tid, n0, d0 = cached
        if (n0, d0) != (nelem, dt):
            raise ValueError(f"tensor {name!r} re-declared with different "
                             f"shape/dtype ({n0},{d0}) vs ({nelem},{dt})")
        return tid
    tid = _client.declare(name, nelem, dt)
    _declared[name] = (tid, nelem, dt)
    return tid


def _auto_name() -> str:
    """Per-call sequential fallback name (reference/Horovod:
    push_pull.noname.N) — correct when all ranks issue unnamed calls in
    lockstep order. Never keyed on id(): CPython reuses object ids, which
    would resurrect a stale declaration."""
    global _noname_seq
    name = f"byteps.mx.noname.{_noname_seq}"
    _noname_seq += 1
    return name


def byteps_push_pull(tensor, version: int = 0, priority: int = 0,
                     name: Optional[str] = None,
                     is_average: bool = True) -> None:
    """In-place sum (or average) of an NDArray across workers (reference:
    byteps.mxnet.byteps_push_pull → MXEnginePushAsync + EnqueueTensor).
    Synchronous here: MXNet's async engine is not in the loop, the PS
    pipeline itself provides the overlap."""
    if _client is None:
        return
    arr = tensor.asnumpy().reshape(-1)
    tid = _declare(name or _auto_name(), arr.size, arr.dtype)
    _client.wait(_client.push_pull(tid, arr, average=is_average))
    tensor[:] = mx.nd.array(arr.reshape(tensor.shape), dtype=arr.dtype)


def broadcast_parameters(params, root_rank: int = 0) -> None:
    """Sync a gluon ParameterDict (or dict of NDArrays) from root
    (reference: byteps.mxnet.broadcast_parameters)."""
    if _client is None:
        return
    if hasattr(params, "items"):
        named = sorted(params.items())
    else:
        named = sorted(enumerate(params))
    for name, p in named:
        nd = p.data() if hasattr(p, "data") else p
        arr = nd.asnumpy().reshape(-1)
        tid = _declare(f"bcast.{name}", arr.size, arr.dtype)
        _client.wait(_client.broadcast(tid, arr, root_rank=root_rank))
        nd[:] = mx.nd.array(arr.reshape(nd.shape), dtype=arr.dtype)


class DistributedTrainer(gluon.Trainer):
    """gluon.Trainer whose gradient reduction goes through the PS core
    (reference: byteps.mxnet.DistributedTrainer overriding
    _allreduce_grads; LR is rescaled so the server-side sum plus local
    scale equals a true average)."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 root_rank: int = 0):
        super().__init__(params, optimizer, optimizer_params,
                         kvstore=None)
        self._bps_root = root_rank
        self._scale /= size()

    def _allreduce_grads(self) -> None:
        if size() <= 1:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                for grad in param.list_grad():
                    byteps_push_pull(grad, priority=-i,
                                     name=f"grad.{i}.{param.name}",
                                     is_average=False)
