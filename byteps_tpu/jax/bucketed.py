"""Bucketed multi-program overlap for PS-mode training — no host callbacks.

SURVEY.md §7 hard part #1 names three designs for recovering the
reference's hook-style push streaming (byteps/torch/__init__.py
_make_hook) in JAX: custom_vjp taps (``overlap.py`` — needs
``io_callback``, which tunneled/remote PJRT plugins reject), donated
double-buffers, or **multi-program stepping**. This module is the third:

* The parameter tree is split into K contiguous, byte-balanced
  **buckets** (model order; processed in reverse = backward order, the
  order autograd hooks would fire in).
* ``multi_program=True`` compiles one gradient program per bucket —
  program b computes ``grad(loss, bucket_b)`` only (XLA prunes the rest
  of the backward cone). All K programs are dispatched up front; the
  device runs them back-to-back while the host walks the completed ones.
  The D2H + PS push of bucket b therefore overlaps the backward compute
  of buckets b+1..K — the verbatim overlap contract of the reference's
  per-parameter hooks, with programs playing hooks. The price is
  recomputation (K forwards + progressively deeper partial backwards);
  on hosts where the device↔host boundary dominates the step (tunneled
  PJRT: ~5–50 MB/s, measured) that price is noise, and this is the only
  overlap design that works at all without host callbacks.
* ``multi_program=False`` compiles ONE gradient program (no recompute)
  and recovers the boundary-leg pipeline only: the D2H of bucket b
  overlaps the network round of buckets < b and the H2D of buckets
  already pulled. On boundary-dominated hosts this captures most of the
  win at zero compute overhead.

Either way the three host-boundary legs — D2H, DCN push/pull, H2D — run
as a bucket pipeline instead of tree-serial phases: steady-state step
time approaches max(leg) + compute instead of sum(legs) + compute.
Completed buckets start their (async-dispatch) H2D upload immediately,
while later buckets are still crossing D2H or the wire.

Semantics match ``training.py``'s PS step exactly: local chips are
reduced inside jit over the process-local mesh (pmean/psum), the C++
core handles the DCN leg (partitioning, priority-credit scheduling,
C codecs via ``compression_config``, CPU summation), and with
``average=True`` the result is the global mean for a homogeneous fleet.
``make_overlapped_train_step`` uses this builder automatically wherever
``io_callback`` is unavailable.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

import byteps_tpu.jax as bps
from byteps_tpu.jax._compat import shard_map as _shard_map
from byteps_tpu.jax.ps import _wait_all, _writable


def partition_buckets(sizes: Sequence[int], n_buckets: int) -> List[List[int]]:
    """Split leaf indices into <=n_buckets contiguous groups balanced by
    byte size (greedy: close each bucket once it reaches the ideal
    share). Contiguity preserves model order, so reversed(buckets) is
    backward order — the order the reference's hooks fire in."""
    n_buckets = max(1, min(n_buckets, len(sizes)))
    total = sum(sizes) or 1
    ideal = total / n_buckets
    buckets: List[List[int]] = [[]]
    acc = 0
    for i, s in enumerate(sizes):
        remaining_leaves = len(sizes) - i
        remaining_buckets = n_buckets - len(buckets) + 1
        if (buckets[-1] and acc + s / 2 > ideal * len(buckets)
                and remaining_buckets > 1
                and remaining_leaves >= remaining_buckets):
            buckets.append([])
        buckets[-1].append(i)
        acc += s
    return buckets


class _BucketPipeline:
    """Host-side leg pipeline over one step's buckets.

    Tracks per-bucket staged host buffers + C-core handles; uploads a
    bucket (async device_put) the moment its pulls complete, so H2D of
    bucket j rides under the D2H/network of buckets processed later.
    All error paths settle EVERY outstanding handle before raising —
    bailing early would free staging buffers that live-server partitions
    still write into (the Wait/Poll settle invariant, kept one layer up).
    """

    def __init__(self, client):
        self.client = client
        # bucket_idx -> list of (handle, staged_array, leaf_idx)
        self.pending: dict = {}
        self.uploaded: dict = {}

    def push_bucket(self, b: int, tids, host_arrays, leaf_idx, average):
        # Register the bucket BEFORE the first enqueue: if push_pull
        # raises mid-bucket, the already-staged handles are visible to
        # settle_all() on the step's error path.
        staged: list = []
        self.pending[b] = staged
        for tid, arr, li in zip(tids, host_arrays, leaf_idx):
            arr = _writable(np.asarray(arr))
            h = self.client.push_pull(tid, arr.reshape(-1),
                                      average=average)
            staged.append((h, arr, li))

    def sweep(self):
        """Non-blocking: upload any bucket whose pulls have all landed.
        poll() raises on a failed handle — the caller's error path
        settles everything else via settle_all()."""
        done = [b for b, staged in self.pending.items()
                if all(self.client.poll(h) for h, _, _ in staged)]
        for b in done:
            self._upload(b)

    def _upload(self, b: int):
        staged = self.pending.pop(b)
        # ONE batched async device_put per bucket: dispatch returns
        # immediately, the runtime overlaps the transfer with whatever
        # the device/host do next.
        devs = jax.device_put([arr for _, arr, _ in staged])
        for d, (_, _, li) in zip(devs, staged):
            self.uploaded[li] = d

    def _settle_pending(self):
        """Wait out EVERY pending handle (never bail early — a freed
        staging buffer with a live-server partition in flight is a
        use-after-free); return the first error, leaving ``pending``
        intact for the caller to consume or clear."""
        err = None
        for staged in self.pending.values():
            try:
                _wait_all(self.client, staged)
            except Exception as e:  # noqa: BLE001 — settle every bucket
                if err is None:
                    err = e
        return err

    def finish(self) -> dict:
        """Wait out every remaining bucket, upload, and return
        {leaf_idx: device_array}."""
        err = self._settle_pending()
        if err is not None:
            self.pending.clear()
            self.uploaded = {}
            raise err
        for b in sorted(self.pending):
            self._upload(b)
        self.pending.clear()
        out, self.uploaded = self.uploaded, {}
        return out

    def settle_all(self) -> None:
        """Quiet settle for error paths: waits everything out, swallows
        settle-time errors (the caller re-raises the original)."""
        self._settle_pending()
        self.pending.clear()
        self.uploaded = {}


def make_bucketed_overlap_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    *,
    n_buckets: Optional[int] = None,
    multi_program: Optional[bool] = None,
    average: bool = True,
    wire_dtype: str = "float32",
    compression_config: Optional[str] = None,
    donate: bool = True,
    prefix: str = "bgrad",
):
    """Build ``step(params, opt_state, batch) -> (params, opt_state, loss)``
    with bucketed-overlap PS communication (see module docstring).

    ``loss_fn(params, batch) -> scalar``; ``batch`` leaves carry this
    worker's batch on the leading axis (sharded over the process-local
    mesh). ``n_buckets`` defaults to ``BYTEPS_OVERLAP_BUCKETS`` (4).
    ``multi_program`` defaults to ``BYTEPS_BUCKET_PROGRAMS`` ∈
    {``multi``, ``single``} (multi): per-bucket gradient programs give
    true compute/comm overlap at a recompute cost; ``single`` gives
    boundary-leg pipelining only. ``wire_dtype="bfloat16"`` casts the
    wire inside jit (half the boundary bytes; the apply casts back).
    ``compression_config`` is the C-core codec string applied per leaf
    on the DCN leg (e.g. ``"type=onebit;ef=vanilla"``).
    """
    st = bps._st()
    client = st.ps_client
    if client is None:
        raise RuntimeError(
            "make_bucketed_overlap_step needs PS mode (init with "
            "DMLC_NUM_SERVER>0 / BYTEPS_PS_MODE=ps)")
    if wire_dtype not in ("float32", "bfloat16"):
        raise ValueError(
            f"wire_dtype must be float32|bfloat16, got {wire_dtype!r}")
    if n_buckets is None:
        n_buckets = int(os.environ.get("BYTEPS_OVERLAP_BUCKETS", "4"))
    if n_buckets < 1:
        raise ValueError("n_buckets must be >= 1")
    if multi_program is None:
        multi_program = os.environ.get(
            "BYTEPS_BUCKET_PROGRAMS", "multi").lower() != "single"
    mesh = st.mesh
    cfg = st.config
    axes = tuple(a for a in (cfg.dcn_axis, cfg.ici_axis)
                 if a in mesh.axis_names)
    wire = jnp.bfloat16 if wire_dtype == "bfloat16" else None

    # Filled lazily at the first step (needs the concrete param tree).
    built: dict = {}

    def _build(params):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        sizes = [int(np.size(l)) * jnp.dtype(l.dtype).itemsize
                 for l in leaves]
        buckets = partition_buckets(sizes, n_buckets)
        # Declare in MODEL order: declaration order is PS priority, and
        # front-of-model pulls are needed first by the next forward.
        tids = [client.declare(
                    f"{prefix}_{i}", int(np.size(l)),
                    wire_dtype if wire is not None
                    else jnp.dtype(l.dtype).name,
                    compression=compression_config)
                for i, l in enumerate(leaves)]
        shapes = [jnp.shape(l) for l in leaves]
        dtypes = [jnp.dtype(l.dtype) for l in leaves]

        def cast_wire(g):
            return g.astype(wire) if wire is not None else g

        def merged_loss(bucket_vals, other_vals, batch, idx, other_idx):
            full: List = [None] * len(leaves)
            for v, i in zip(bucket_vals, idx):
                full[i] = v
            for v, i in zip(other_vals, other_idx):
                full[i] = v
            return loss_fn(jax.tree_util.tree_unflatten(treedef, full),
                           batch)

        def reduce_local(loss, grads):
            red = lax.pmean if average else lax.psum
            for ax in axes:
                grads = jax.tree_util.tree_map(
                    lambda g, a=ax: red(g, a), grads)
                loss = lax.pmean(loss, ax)
            return loss, jax.tree_util.tree_map(cast_wire, grads)

        if multi_program:
            programs = []
            for idx in buckets:
                other_idx = [i for i in range(len(leaves))
                             if i not in set(idx)]

                def grad_b(params_, batch, idx=tuple(idx),
                           other_idx=tuple(other_idx)):
                    ls = jax.tree_util.tree_flatten(params_)[0]
                    bucket_vals = [ls[i] for i in idx]
                    other_vals = [ls[i] for i in other_idx]
                    loss, g = jax.value_and_grad(merged_loss)(
                        bucket_vals, other_vals, batch, idx, other_idx)
                    return reduce_local(loss, g)

                programs.append(jax.jit(partial(
                    _shard_map, mesh=mesh, in_specs=(P(), P(axes)),
                    out_specs=(P(), P()), check_vma=False)(grad_b)))
            built["programs"] = programs
        else:
            @jax.jit
            @partial(_shard_map, mesh=mesh, in_specs=(P(), P(axes)),
                     out_specs=(P(), P()), check_vma=False)
            def grad_all(params_, batch):
                loss, grads = jax.value_and_grad(loss_fn)(params_, batch)
                return reduce_local(loss, grads)

            built["grad_all"] = grad_all

        def apply_fn(params_, opt_state, flat_grads):
            gl = [g.reshape(s).astype(d)
                  for g, s, d in zip(flat_grads, shapes, dtypes)]
            grads = jax.tree_util.tree_unflatten(treedef, gl)
            updates, opt_state = optimizer.update(grads, opt_state,
                                                  params_)
            return optax.apply_updates(params_, updates), opt_state

        # Gradient buffers (argnum 2) are fresh per step — always donate
        # them; params/opt_state donation is the caller's choice.
        built["apply"] = jax.jit(
            apply_fn, donate_argnums=(0, 1, 2) if donate else (2,))
        built["buckets"] = buckets
        built["tids"] = tids
        built["treedef"] = treedef
        built["n_leaves"] = len(leaves)

    def step(params, opt_state, batch):
        if not built:
            _build(params)
        buckets = built["buckets"]
        tids = built["tids"]
        order = list(reversed(range(len(buckets))))  # backward order
        pipe = _BucketPipeline(client)
        try:
            if multi_program:
                # Dispatch EVERY program now (async): the device
                # pipelines them back-to-back while the host walks
                # completed buckets.
                outs = [built["programs"][b](params, batch) for b in order]
                loss = outs[0][0]
                for (_, grads_b), b in zip(outs, order):
                    # Blocks only until program b's outputs are ready —
                    # later programs keep computing while this bucket
                    # crosses D2H and the wire.
                    host = jax.device_get(list(grads_b))
                    pipe.push_bucket(b, [tids[i] for i in buckets[b]],
                                     host, buckets[b], average)
                    pipe.sweep()
            else:
                loss, grads = built["grad_all"](params, batch)
                flat = jax.tree_util.tree_flatten(grads)[0]
                for b in order:
                    host = jax.device_get([flat[i] for i in buckets[b]])
                    pipe.push_bucket(b, [tids[i] for i in buckets[b]],
                                     host, buckets[b], average)
                    pipe.sweep()
            by_leaf = pipe.finish()
        except Exception:
            # Settle-before-raise, one level up from every fault site
            # (enqueue, poll, device transfer): no staging buffer is
            # freed while a live-server partition can still write it.
            pipe.settle_all()
            raise
        flat_grads = [by_leaf[i] for i in range(built["n_leaves"])]
        params, opt_state = built["apply"](params, opt_state, flat_grads)
        return params, opt_state, loss

    return step
