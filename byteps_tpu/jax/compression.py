"""Wire-level gradient compression for the JAX plugin.

Capability parity with the reference's byteps/torch/compression.py
(SURVEY.md §2.5): a small, Horovod-compatible `Compression` namespace whose
members are applied to gradients before the communication stage and undone
after. This is distinct from the server-side compressor plugin framework
(byteps/common/compressor/ → byteps_tpu.compression): these casts happen
*inside jit*, so XLA fuses them into the reduce-scatter for free — the
TPU-native way to halve ICI/DCN bytes.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Compressor:
    """A (compress, decompress) pair applied around push_pull."""

    name: str
    compress: Callable[[jax.Array], jax.Array]
    decompress: Callable[[jax.Array, jnp.dtype], jax.Array]


def _identity(x):
    return x


def _restore(x, dtype):
    return x.astype(dtype)


class Compression:
    """Namespace of wire compressors (reference: Compression.none/fp16)."""

    none = Compressor("none", _identity, lambda x, d: x)
    fp16 = Compressor("fp16", lambda x: x.astype(jnp.float16), _restore)
    # bfloat16 is the TPU-native half type: same exponent range as f32, so
    # gradient casts need no loss scaling — preferred over fp16 on TPU.
    bf16 = Compressor("bf16", lambda x: x.astype(jnp.bfloat16), _restore)
    # int8: EQuARX-style blockwise-quantized collective transport (the
    # whole reduce path changes, not just a cast) — push_pull dispatches
    # to parallel.hierarchical.quantized_all_reduce when it sees this.
    # Plain int8 quantizes the fast (ici) level only; int8_dcn applies
    # the same scheme to the slow cross-slice fabric too, where the 4x
    # bandwidth saving matters most in pure collective mode.
    int8 = Compressor("int8_quant", _identity, lambda x, d: x.astype(d))
    int8_dcn = Compressor("int8_quant_dcn", _identity,
                          lambda x, d: x.astype(d))
