"""JAX ↔ C++ parameter-server bridge (PS mode).

This is the DCN leg of the hierarchy (SURVEY.md §3.3): gradients leave the
chips ici-reduced (XLA collectives inside the jitted step), cross the host
boundary once, and the C++ core partitions / compresses / priority-schedules
/ pushes them over TCP to the CPU-summation servers, pulling the aggregate
back into the same buffers. One BytePS worker per controller process; the
reduction denominator factorises as (local chips via pmean) x (worker
hosts via PS average).

Reference analogues: byteps/torch/ops.py (push_pull on framework tensors)
and the COPYD2H → PUSH → PULL → COPYH2D pipeline stages.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

import byteps_tpu.jax as bps


def ps_push_pull(tree, average: bool = True, prefix: str = "grad",
                 async_mode: Optional[bool] = None):
    """Sum (or average) a pytree across worker hosts via the CPU PS fleet.

    Host-level call (use on the outputs of a jitted step). All leaves are
    enqueued before any wait, so partitions from every tensor pipeline
    through the priority-scheduled push queue together — large trees
    overlap compression, network, and summation across partitions exactly
    like the reference's per-partition scheduling.
    """
    st = bps._st()
    client = st.ps_client
    if client is None:
        raise RuntimeError(
            "PS mode is not active (init with BYTEPS_PS_MODE=ps / "
            "DMLC_NUM_SERVER>0)")
    if async_mode is None:
        async_mode = st.config.enable_async
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    staged = []
    for i, leaf in enumerate(leaves):
        arr = np.ascontiguousarray(np.asarray(leaf))
        tid = client.declare(f"{prefix}_{i}", arr.size, arr.dtype)
        h = client.push_pull(tid, arr, average=average,
                             async_mode=async_mode)
        staged.append((h, arr, leaf))
    out = []
    for h, arr, leaf in staged:
        client.wait(h)
        out.append(jnp.asarray(arr).reshape(leaf.shape).astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, out)


def ps_broadcast(tree, root_rank: int = 0, prefix: str = "param"):
    """Init-time weight sync across worker hosts through the servers
    (reference: broadcast_parameters, SURVEY.md §3.4)."""
    st = bps._st()
    client = st.ps_client
    if client is None:
        raise RuntimeError("PS mode is not active")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    staged = []
    for i, leaf in enumerate(leaves):
        arr = np.ascontiguousarray(np.asarray(leaf))
        tid = client.declare(f"{prefix}_{i}", arr.size, arr.dtype)
        h = client.broadcast(tid, arr, root_rank=root_rank)
        staged.append((h, arr, leaf))
    out = []
    for h, arr, leaf in staged:
        client.wait(h)
        out.append(jnp.asarray(arr).reshape(leaf.shape).astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, out)


def ps_barrier() -> None:
    """Fleet-wide worker barrier through the scheduler."""
    st = bps._st()
    if st.ps_client is None:
        raise RuntimeError("PS mode is not active")
    st.ps_client.barrier()
