"""JAX ↔ C++ parameter-server bridge (PS mode).

This is the DCN leg of the hierarchy (SURVEY.md §3.3): gradients leave the
chips ici-reduced (XLA collectives inside the jitted step), cross the host
boundary once, and the C++ core partitions / compresses / priority-schedules
/ pushes them over TCP to the CPU-summation servers, pulling the aggregate
back into the same buffers. One BytePS worker per controller process; the
reduction denominator factorises as (local chips via pmean) x (worker
hosts via PS average).

Reference analogues: byteps/torch/ops.py (push_pull on framework tensors)
and the COPYD2H → PUSH → PULL → COPYH2D pipeline stages.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

import byteps_tpu.jax as bps

# --- ordered bridge execution ----------------------------------------------
# Wire keys are (declaration-order id << 16 | partition) — worker.cc's
# Declare assigns ids by LOCAL declaration order, so every worker must
# declare tensors in the same order or the servers sum unrelated tensors
# under one key. A single FIFO bridge thread gives that order a single
# authority: every host-boundary PS op (sync or async) executes on it in
# submission order, and submissions happen in the caller's program order.
_pool = None
_pool_lock = threading.Lock()
_POOL_PREFIX = "bps_bridge"


def _ensure_pool():
    global _pool
    with _pool_lock:
        if _pool is None:
            import concurrent.futures
            _pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=_POOL_PREFIX)
        return _pool


def _on_pool_thread() -> bool:
    return threading.current_thread().name.startswith(_POOL_PREFIX)


def _run_ordered(fn, *args, **kwargs):
    """Execute fn on the bridge thread and wait. Re-entrant: a call that is
    already ON the bridge thread (an async op's PS leg) runs inline — a
    submit-and-wait there would deadlock the single-worker FIFO."""
    if _on_pool_thread():
        return fn(*args, **kwargs)
    return _ensure_pool().submit(fn, *args, **kwargs).result()


def submit_ordered(fn, *args, **kwargs):
    """Queue fn on the bridge thread and return the Future (the async
    handle path). Caller must not already be on the bridge thread."""
    assert not _on_pool_thread(), "async submit from the bridge thread"
    return _ensure_pool().submit(fn, *args, **kwargs)


def drain_bridge() -> None:
    """Settle every queued bridge op and retire the pool (shutdown path:
    the C++ client must not be torn down under an in-flight async op)."""
    global _pool
    with _pool_lock:
        p, _pool = _pool, None
    if p is not None:
        p.shutdown(wait=True)

# (prefix, n_leaves) -> list of tensor ids. Declares are per-tensor-
# lifetime, not per-step: each declare is a ctypes call into the C core's
# locked registry (and, on first sight, a blocking INIT_KEY round trip to
# every owning server) — pure per-step overhead once the tree shape is
# fixed. Cleared by bps.init()/shutdown() via reset_declare_cache().
_tid_cache: dict = {}
# Steps that declared at least one NEW tensor (test hook: after warm-up
# this must stop growing — one registration per tensor lifetime).
declare_steps: int = 0


def reset_declare_cache() -> None:
    _tid_cache.clear()


def _writable(arr: np.ndarray) -> np.ndarray:
    """The C core pushes FROM and pulls INTO this buffer in place. On CPU
    backends ``device_get`` returns a read-only zero-copy view of the jax
    buffer — writing through it would mutate the (immutable) source array,
    so un-alias exactly when the runtime says the buffer isn't ours."""
    arr = np.ascontiguousarray(arr)
    if not arr.flags.writeable:
        arr = np.array(arr)
    return arr


def _as_arrays(leaves):
    """Normalise pytree leaves: Python scalars (ints/floats in opt state
    trees) become 0-d numpy arrays so size/dtype/shape queries work."""
    return [l if hasattr(l, "dtype") and hasattr(l, "size")
            else np.asarray(l) for l in leaves]


def _wait_all(client, staged):
    """Settle EVERY staged handle before surfacing a failure. client.wait
    raises on the first failed handle; bailing out of the loop there would
    free the numpy staging buffers of the not-yet-waited handles while
    live-server partitions are still in flight — the C core's pull
    callbacks would then memcpy into freed memory (the same use-after-free
    the Wait/Poll settle semantics in worker.cc prevent one layer down).
    Collect errors, wait everything, then re-raise the first."""
    first_err = None
    for h, _, _ in staged:
        try:
            client.wait(h)
        except Exception as e:  # noqa: BLE001 — must settle all handles
            if first_err is None:
                first_err = e
    if first_err is not None:
        raise first_err


def _codec_active(st) -> bool:
    """A fleet-default codec (BYTEPS_COMPRESSOR) is configured. Mirrors
    the C core's rule: ANY non-empty config makes declares codec-bearing
    (and the codecs are float32-domain — worker.cc guards the declare)."""
    import os
    return bool(getattr(st.config, "compressor", "")
                or os.environ.get("BYTEPS_COMPRESSOR", ""))


def _wire_plan(leaves, codec: bool):
    """Per-leaf (declare dtype, compression override) so half-precision
    wire and lossy codecs compose instead of fail-stopping:

    - float32 + codec: inherit the default codec (None).
    - bfloat16/float16 + codec: declare FLOAT32 and upcast the staged
      host buffer — the in-jit half cast still halves the dominant
      device<->host boundary both ways; the C codec (e.g. onebit, 32x)
      takes the DCN leg from there.
    - non-float leaves (int step counters in optimizer trees): declare
      with compression="" — quantising integers is meaningless and the
      core would reject them.
    """
    plan = []
    for leaf in leaves:
        name = np.dtype(leaf.dtype).name
        if not codec:
            plan.append((name, None))
        elif name == "float32":
            plan.append((name, None))
        elif name in ("bfloat16", "float16"):
            plan.append(("float32", None))
        else:
            plan.append((name, ""))
    return plan


def _tids(client, prefix: str, leaves, plan):
    global declare_steps
    # Shape/dtype signature in the key: a same-named tree with different
    # leaf sizes must re-declare (the C core rejects size changes).
    sig = tuple((int(l.size), str(l.dtype)) for l in leaves)
    key = (prefix, sig, tuple(p[0] for p in plan))
    tids = _tid_cache.get(key)
    if tids is None:
        declare_steps += 1
        # The shape signature goes INTO the wire name: two different-shaped
        # trees under the same prefix (e.g. two unnamed push_pull call
        # sites) must land on distinct server tensors — re-declaring a
        # name with a new size is a deliberate fatal in the C core. The
        # digest is content-derived, so it is identical on every worker
        # (python's hash() is salted per process and would NOT be).
        import zlib
        shape_key = zlib.crc32(repr(key).encode())
        tids = [
            client.declare(f"{prefix}_{shape_key:08x}_{i}", int(leaf.size),
                           wire_dtype, compression=comp)
            for i, (leaf, (wire_dtype, comp)) in enumerate(zip(leaves,
                                                               plan))
        ]
        _tid_cache[key] = tids
    return tids


def ps_push_pull(tree, average: bool = True, prefix: str = "grad",
                 async_mode: Optional[bool] = None):
    """Sum (or average) a pytree across worker hosts via the CPU PS fleet.

    Host-level call (use on the outputs of a jitted step). All leaves are
    enqueued before any wait, so partitions from every tensor pipeline
    through the priority-scheduled push queue together — large trees
    overlap compression, network, and summation across partitions exactly
    like the reference's per-partition scheduling.

    Host-boundary discipline (reference: shared_memory.cc + ps-lite
    zero-copy SArray, SURVEY.md §7 hard part #2): ONE batched D2H
    transfer for the whole tree (``jax.device_get`` — the runtime
    overlaps per-leaf transfers), the resulting host buffers are handed
    to the C core zero-copy (pushed from and pulled back into in place),
    and tensor declares are cached for the tree's lifetime instead of
    re-registering every step. Executes on the FIFO bridge thread so
    declares keep a fleet-consistent order against async ops.
    """
    return _run_ordered(_ps_push_pull_impl, tree, average, prefix,
                        async_mode)


def _ps_push_pull_impl(tree, average, prefix, async_mode):
    st = bps._st()
    client = st.ps_client
    if client is None:
        raise RuntimeError(
            "PS mode is not active (init with BYTEPS_PS_MODE=ps / "
            "DMLC_NUM_SERVER>0)")
    if async_mode is None:
        async_mode = st.config.enable_async
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    leaves = _as_arrays(leaves)
    plan = _wire_plan(leaves, _codec_active(st))
    tids = _tids(client, prefix, leaves, plan)
    # One batched D2H for the whole tree; each result is a fresh
    # contiguous writable host buffer that serves as both push source and
    # pull destination (no second host-side copy).
    host = jax.device_get(leaves)
    staged = []
    for tid, arr, leaf, (wire_dtype, _) in zip(tids, host, leaves, plan):
        arr = _writable(arr)
        if arr.dtype != np.dtype(wire_dtype):
            arr = arr.astype(wire_dtype)  # half-wire + codec: f32 DCN leg
        h = client.push_pull(tid, arr, average=average,
                             async_mode=async_mode)
        staged.append((h, arr, leaf))
    _wait_all(client, staged)
    # ONE batched H2D for the whole tree (mirror of the batched
    # device_get above): per-leaf jnp.asarray would pay the host-boundary
    # dispatch latency once PER LEAF — measured ~0.1-0.26 s each on
    # tunneled PJRT, i.e. tens of seconds per step for transformer-sized
    # trees. jax.device_put on the list lets the runtime overlap them.
    # Downcast upcast-staged leaves on host first so the upload leg pays
    # half-precision bytes too (the device-side astype is then a no-op).
    devs = jax.device_put(
        [arr if arr.dtype == getattr(leaf, "dtype", arr.dtype)
         else arr.astype(leaf.dtype) for _, arr, leaf in staged])
    out = [d.reshape(leaf.shape).astype(leaf.dtype)
           for d, (_, _, leaf) in zip(devs, staged)]
    return jax.tree_util.tree_unflatten(treedef, out)


def ps_broadcast(tree, root_rank: int = 0, prefix: str = "param"):
    """Init-time weight sync across worker hosts through the servers
    (reference: broadcast_parameters, SURVEY.md §3.4). Bridge-thread
    ordered like ps_push_pull."""
    return _run_ordered(_ps_broadcast_impl, tree, root_rank, prefix)


def _ps_broadcast_impl(tree, root_rank, prefix):
    st = bps._st()
    client = st.ps_client
    if client is None:
        raise RuntimeError("PS mode is not active")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    leaves = _as_arrays(leaves)
    plan = _wire_plan(leaves, _codec_active(st))
    tids = _tids(client, prefix, leaves, plan)
    host = jax.device_get(leaves)
    staged = []
    for tid, arr, leaf, (wire_dtype, _) in zip(tids, host, leaves, plan):
        arr = _writable(arr)
        if arr.dtype != np.dtype(wire_dtype):
            arr = arr.astype(wire_dtype)
        h = client.broadcast(tid, arr, root_rank=root_rank)
        staged.append((h, arr, leaf))
    _wait_all(client, staged)
    devs = jax.device_put(
        [arr if arr.dtype == getattr(leaf, "dtype", arr.dtype)
         else arr.astype(leaf.dtype)
         for _, arr, leaf in staged])  # one batched H2D
    out = [d.reshape(leaf.shape).astype(leaf.dtype)
           for d, (_, _, leaf) in zip(devs, staged)]
    return jax.tree_util.tree_unflatten(treedef, out)


def ps_barrier() -> None:
    """Fleet-wide worker barrier through the scheduler."""
    st = bps._st()
    if st.ps_client is None:
        raise RuntimeError("PS mode is not active")
    st.ps_client.barrier()
