"""Per-layer compute/communication overlap for PS-mode JAX training.

SURVEY.md §7 "hard part #1": the reference's torch plugin registers
per-parameter autograd hooks so each gradient starts its push the moment
backward produces it (byteps/torch/__init__.py _make_hook) — communication
overlaps the *rest of backward*. JAX has no hooks: gradients normally
leave ``value_and_grad`` all at once, so PS-mode pushes can only start
after the whole backward finishes.

This module recovers hook-style streaming inside the jitted program:
every parameter leaf is wrapped in a ``custom_vjp`` identity *tap* whose
backward rule fires a ``jax.experimental.io_callback``. When XLA's
backward pass materialises that parameter's gradient, the callback hands
it straight to the C++ KV worker's priority-credit push queue — while the
device continues with the remaining backward compute. After the step's
dispatch completes, the host waits on the per-tensor handles (pulls) and
applies the optimizer update.

Multi-chip controllers are first-class: the tapped loss runs under
``shard_map`` over the process-local (dcn, ici) mesh, and each tap's
backward rule reduce-scatters the gradient over ALL local mesh axes
inside jit (``lax.psum_scatter`` — the reference's NCCL intra-node
reduce-scatter stage) before any host transfer. Each chip's callback
hands the host only its 1/k shard of the locally-summed gradient, so the
host↔DCN leg carries exactly one gradient's worth of bytes per step
regardless of local chip count — the reference's two-level pipeline
(SURVEY.md §3.3) with XLA playing NCCL. Shards are declared as separate
PS keys (``{name}.{j}``), preserving declaration-order priority
(front-of-model first) at shard granularity.

Priorities follow parameter declaration order (flattened tree order =
front-of-model first for standard model pytrees), so early layers' pulls
complete first — exactly the reference's scheduling rationale.

Options: ``wire_dtype`` compresses the device->host transfer inside jit
(bf16 2x / int8+scales ~4x, re-expanded to f32 before the PS push);
``backward_passes_per_step`` accumulates K backward passes host-side and
communicates once (the reference's gradient-accumulation contract).
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.experimental import io_callback
from jax.sharding import PartitionSpec as P

import byteps_tpu.jax as bps
from byteps_tpu.jax._compat import shard_map as _shard_map


def _effects_barrier() -> None:
    """``jax.effects_barrier`` guarded for jax versions without it — one
    shim for every call site, so a version that drops the API degrades
    to the cv-wait in ``collect`` instead of crashing each step."""
    if hasattr(jax, "effects_barrier"):
        jax.effects_barrier()


def io_callback_supported(backend: Optional[str] = None) -> bool:
    """True iff the backend can run ``io_callback`` inside jit.

    The overlap taps need host callbacks; most PJRT plugins support them
    (CPU, standard TPU), but tunneled/remote plugins may not (observed:
    "UNIMPLEMENTED: ... does not support host send/recv callbacks").
    Probed once per backend and cached.
    """
    key = backend or jax.default_backend()
    cached = _IO_CB_SUPPORT.get(key)
    if cached is not None:
        return cached
    seen = []

    @jax.jit
    def probe(x):
        io_callback(lambda v: seen.append(v), None, x, ordered=False)
        return x + 1

    try:
        probe(jnp.int32(1)).block_until_ready()
        _effects_barrier()
        ok = True
    except jax.errors.JaxRuntimeError:
        # Only the runtime's own verdict ("UNIMPLEMENTED: ... host
        # send/recv callbacks" and kin) means the backend lacks
        # callbacks. Anything else (transient tracing/API errors) must
        # propagate rather than permanently caching ok=False and
        # silently downgrading every overlapped step to the fallback.
        ok = False
    _IO_CB_SUPPORT[key] = ok
    return ok


_IO_CB_SUPPORT: Dict[str, bool] = {}


class _TapState:
    """Declared shard tensors + in-flight handles for one step builder."""

    def __init__(self, client, prefix: str, average: bool,
                 compression_config: Optional[str], n_shards: int,
                 wire_dtype: str = "float32", wire_block: int = 256,
                 backward_passes_per_step: int = 1):
        self.client = client
        self.prefix = prefix
        self.average = average
        self.compression_config = compression_config
        self.n_shards = n_shards
        self.wire_dtype = wire_dtype
        self.wire_block = wire_block
        self.bpps = backward_passes_per_step
        self.acc: Dict[Tuple[int, int], np.ndarray] = {}
        self.acc_count: Dict[Tuple[int, int], int] = {}
        # (leaf_idx, shard_idx) -> declared tensor id / in-flight handle
        self.tids: Dict[Tuple[int, int], int] = {}
        self.shard_elems: Dict[int, int] = {}
        self.blocks: Dict[int, int] = {}
        self.cv = threading.Condition()
        self.inflight: Dict[Tuple[int, int], Tuple[int, np.ndarray]] = {}

    def pad_unit(self, idx: int) -> int:
        """Leaf ``idx``'s flat gradient is padded to this multiple before
        scattering (int8 wire additionally needs block-tiled shards).
        The quantization block shrinks with the leaf so a 3-element bias
        is not padded out to k*256 elements of PS traffic."""
        return self.n_shards * self.blocks[idx]

    def declare_all(self, leaves) -> None:
        k = self.n_shards
        for i, leaf in enumerate(leaves):
            n = int(np.size(leaf))
            if self.wire_dtype == "int8":
                self.blocks[i] = min(self.wire_block, max(1, -(-n // k)))
            else:
                self.blocks[i] = 1
            unit = self.pad_unit(i)
            padded = -(-n // unit) * unit
            self.shard_elems[i] = padded // k
            # Quantized/cast wires always land as f32 on the host (the C
            # codecs and summation operate on f32).
            dt = (np.dtype(leaf.dtype).name
                  if self.wire_dtype == "float32" else "float32")
            for j in range(k):
                self.tids[(i, j)] = self.client.declare(
                    f"{self.prefix}_{i}.{j}", self.shard_elems[i], dt,
                    compression=self.compression_config)

    def push_shard(self, idx: int, j, g: np.ndarray,
                   scales: Optional[np.ndarray] = None) -> None:
        # io_callback may hand a read-only view; the C core sums in place,
        # so stage through a writable copy that also serves as the pull
        # destination.
        j = int(j)
        if scales is not None:
            # int8 wire: dequantize blockwise on the host (cheap
            # vectorised numpy), push f32.
            arr = (np.asarray(g, np.float32).reshape(-1, self.blocks[idx])
                   * np.asarray(scales, np.float32).reshape(-1, 1)
                   ).reshape(-1)
        else:
            arr = np.array(g, dtype=np.float32 if self.wire_dtype != "float32"
                           else None, copy=True).reshape(-1)
        if self.bpps > 1:
            # Gradient accumulation (reference: DistributedOptimizer
            # backward_passes_per_step): sum K backward passes host-side,
            # communicate once on the K-th. Division by K is the
            # caller's, exactly as in the reference. Under the lock:
            # unordered io_callbacks for the same key can run on
            # different host threads (a straggler from microbatch m
            # racing m+1), and an unguarded read-modify-write here would
            # lose a gradient or an acc_count increment.
            key = (idx, j)
            with self.cv:
                acc = self.acc.get(key)
                self.acc[key] = arr if acc is None else acc + arr
                self.acc_count[key] = self.acc_count.get(key, 0) + 1
                if self.acc_count[key] < self.bpps:
                    return
                arr = self.acc.pop(key)
                self.acc_count[key] = 0
        h = self.client.push_pull(self.tids[(idx, j)], arr,
                                  average=self.average)
        with self.cv:
            self.inflight[(idx, j)] = (h, arr)
            self.cv.notify_all()

    def reset_window(self) -> None:
        """Drop any partial accumulation/in-flight state. Called at the
        start of each accumulation window: if a previous step crashed
        mid-backward (device error after some taps fired), leftover
        acc/acc_count entries would silently mix microbatches from
        different windows on the next retry — bound the damage to the
        failed window instead. The effects barrier first flushes any
        still-queued io_callbacks from the crashed step, so a straggler
        cannot re-pollute the fresh window right after the clear."""
        try:
            _effects_barrier()
        except Exception:
            pass  # a dead backend can raise here; clearing still helps
        with self.cv:
            self.acc.clear()
            self.acc_count.clear()
            self.inflight.clear()

    def _pop(self, key: Tuple[int, int], timeout: float):
        """Wait until the tap callback for ``key`` has fired, then take
        its handle. Callbacks are unordered and — on tunneled/remote PJRT
        platforms — may land after block_until_ready returns, so a plain
        dict pop would race; waiting on the condition variable makes
        collect robust no matter when the runtime runs the callback."""
        with self.cv:
            if not self.cv.wait_for(lambda: key in self.inflight, timeout):
                raise RuntimeError(
                    f"gradient tap {key} never fired within {timeout}s "
                    "(io_callback lost or step crashed mid-backward)")
            return self.inflight.pop(key)

    def collect(self, leaves, timeout: Optional[float] = None):
        if timeout is None:
            # A big model's first step (slow compile) plus a cold fleet can
            # exceed any fixed bound — configurable, generous default.
            import os
            timeout = float(os.environ.get("BYTEPS_TAP_TIMEOUT_S", "600"))
        out = []
        for i, leaf in enumerate(leaves):
            shards = []
            for j in range(self.n_shards):
                h, arr = self._pop((i, j), timeout)
                self.client.wait(h)
                shards.append(arr)
            flat = shards[0] if self.n_shards == 1 else np.concatenate(shards)
            out.append(flat[:int(np.size(leaf))].reshape(np.shape(leaf))
                       .astype(leaf.dtype))
        return out


def _make_tap(state: _TapState, idx: int, axes: Tuple[str, ...], k: int):
    @jax.custom_vjp
    def tap(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        # Fires mid-backward per device: reduce-scatter this gradient over
        # the local chips inside jit (ICI collective), then enqueue each
        # chip's 1/k shard push while the device keeps differentiating
        # earlier layers. With average=True the local level contributes the
        # local mean and the PS level averages over workers — the global
        # mean for a homogeneous fleet (same split as the non-overlapped
        # PS step in training.py).
        flat = g.reshape(-1)
        pad = (-flat.shape[0]) % state.pad_unit(idx)
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        if k > 1:
            shard = lax.psum_scatter(flat, axes, scatter_dimension=0,
                                     tiled=True)
            if state.average:
                shard = shard / k
            j = lax.axis_index(axes)
        else:
            shard = flat
            j = jnp.int32(0)
        # On-device wire compression (SURVEY.md §7 step 5): the D2H
        # transfer is the host boundary's scarce resource on real chips —
        # cast (bf16, 2x) or blockwise-quantize (int8 + per-block scales,
        # ~4x) INSIDE jit so fewer bytes cross it. The host re-expands to
        # f32 before the PS push; DCN-leg compression stays the C codec's
        # job. The quantization loss here is per-step (not error-fed).
        if state.wire_dtype == "int8":
            from byteps_tpu.parallel.hierarchical import _blockwise_quantize
            q, scales = _blockwise_quantize(shard, state.blocks[idx])
            io_callback(
                lambda jj, qq, ss: state.push_shard(idx, jj, qq, ss),
                None, j, q, scales, ordered=False)
        elif state.wire_dtype == "bfloat16":
            io_callback(lambda jj, arr: state.push_shard(idx, jj, arr),
                        None, j, shard.astype(jnp.bfloat16),
                        ordered=False)
        else:
            io_callback(lambda jj, arr: state.push_shard(idx, jj, arr),
                        None, j, shard, ordered=False)
        return (g,)

    tap.defvjp(fwd, bwd)
    return tap


def make_overlapped_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    *,
    average: bool = True,
    compression_config: Optional[str] = None,
    wire_dtype: str = "float32",
    wire_block: int = 256,
    backward_passes_per_step: int = 1,
    prefix: str = "ograd",
):
    """Build ``step(params, opt_state, batch) -> (params, opt_state, loss)``
    with hook-style push streaming (see module docstring).

    ``loss_fn(params, batch) -> scalar``. ``batch`` leaves carry this
    worker's batch on the leading axis; it is sharded over the local mesh
    axes (single-chip meshes included). ``compression_config`` is the
    C-core codec string (e.g. ``"type=onebit;ef=vanilla"``) applied per
    shard tensor on the DCN leg. ``wire_dtype`` compresses the
    device->host transfer inside jit: ``"bfloat16"`` (2x, ~1e-3 error)
    or ``"int8"`` (blockwise-quantized, ~4x, ~1e-2 error, not
    error-fed); the host re-expands to f32 before the PS push.
    ``wire_block`` caps the int8 scale-block size (it shrinks
    automatically for small leaves so padding stays proportional).
    ``backward_passes_per_step=K`` accumulates K backward passes
    host-side and communicates once on the K-th (the reference's
    gradient-accumulation contract; divide by K in your optimizer) —
    non-final calls return the params/opt_state unchanged. The
    returned loss is this worker's local loss (mean over its chips).
    """
    st = bps._st()
    client = st.ps_client
    if client is None:
        raise RuntimeError(
            "make_overlapped_train_step needs PS mode (init with "
            "DMLC_NUM_SERVER>0 / BYTEPS_PS_MODE=ps)")
    if not io_callback_supported():
        # No host callbacks on this backend (tunneled/remote PJRT
        # plugins; standard TPU and CPU both support them): the in-jit
        # taps cannot fire. Fall back to bucketed multi-program stepping
        # (SURVEY §7 hard part #1's io_callback-free overlap design):
        # per-bucket gradient programs whose D2H + PS push overlap the
        # backward compute of later buckets, plus a bucket pipeline over
        # the D2H / DCN / H2D legs — real overlap, not the plain step.
        import warnings
        from byteps_tpu.jax.bucketed import make_bucketed_overlap_step
        warnings.warn(
            f"backend {jax.default_backend()!r} does not support "
            "io_callback inside jit; make_overlapped_train_step uses "
            "bucketed multi-program overlap instead of per-parameter "
            "taps (set BYTEPS_OVERLAP_BUCKETS / BYTEPS_BUCKET_PROGRAMS "
            "to tune)", stacklevel=2)
        if backward_passes_per_step != 1:
            # The fallback cannot reproduce the accumulate-K contract
            # (callers scaled their optimizer for it) — failing beats
            # silently applying K-times-too-small updates every pass.
            raise NotImplementedError(
                "backward_passes_per_step > 1 requires the overlap taps, "
                "which this backend cannot run (no io_callback); "
                "accumulate microbatches in your own loop or use a "
                "callback-capable backend")
        if wire_dtype == "int8":
            raise NotImplementedError(
                "wire_dtype='int8' (blockwise scales) requires the "
                "overlap taps; use 'bfloat16' on this backend")
        return make_bucketed_overlap_step(
            loss_fn, optimizer, average=average, wire_dtype=wire_dtype,
            compression_config=compression_config, donate=False,
            prefix=prefix)
    if (jax.default_backend() == "cpu"
            and jax.local_device_count() == 1):
        # Verified deadlock on this configuration: io_callback_impl
        # device_puts the tap's operands onto the single-threaded XLA:CPU
        # client while the training program occupies that same pool, so
        # materialising the gradient inside the callback waits forever
        # under load (one device == one async worker thread). Two or more
        # host devices widen the pool and the hang disappears.
        import warnings
        warnings.warn(
            "overlapped PS training on a single-device CPU backend can "
            "deadlock in XLA's callback machinery under load; set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=2 (or more) "
            "for CPU runs", stacklevel=2)
    if wire_dtype not in ("float32", "bfloat16", "int8"):
        raise ValueError(
            f"wire_dtype must be float32|bfloat16|int8, got {wire_dtype!r}")
    if backward_passes_per_step < 1:
        raise ValueError("backward_passes_per_step must be >= 1")
    mesh = st.mesh
    axes = tuple(mesh.axis_names)
    k = mesh.size

    state = _TapState(client, prefix, average, compression_config, k,
                      wire_dtype=wire_dtype, wire_block=wire_block,
                      backward_passes_per_step=backward_passes_per_step)
    taps: Dict[int, Callable] = {}

    def tapped_loss(params, batch):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        tapped = [taps[i](leaf) for i, leaf in enumerate(leaves)]
        return loss_fn(jax.tree_util.tree_unflatten(treedef, tapped), batch)

    @jax.jit
    @partial(_shard_map, mesh=mesh, in_specs=(P(), P(axes)),
             out_specs=P(), check_vma=False)
    def grad_device(params, batch):
        # Gradients never leave the program whole: they reach the host
        # only through the taps' reduce-scattered shards.
        loss = jax.value_and_grad(tapped_loss)(params, batch)[0]
        for ax in axes:
            loss = lax.pmean(loss, ax)
        return loss

    def apply_fn(params, opt_state, grads):
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    # Gradient buffers are fresh per step — donating them lets XLA write
    # the updates in place instead of allocating a second tree.
    apply_jit = jax.jit(apply_fn, donate_argnums=(2,))

    micro = [0]

    def step(params, opt_state, batch):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        if not taps:
            state.declare_all(leaves)
            for i in range(len(leaves)):
                taps[i] = _make_tap(state, i, axes, k)
        if micro[0] % backward_passes_per_step == 0:
            # window start: discard any state a crashed step left behind
            state.reset_window()
        try:
            loss = grad_device(params, batch)
            # Pushes already overlapped the backward pass; the effects
            # barrier flushes any unordered callbacks the runtime hasn't
            # yet run, and collect's cv-wait covers runtimes where even
            # that is lazy.
            loss.block_until_ready()
            _effects_barrier()
            micro[0] += 1
            if micro[0] % backward_passes_per_step:
                # accumulation pass: gradients summed host-side, nothing
                # on the wire yet, parameters unchanged
                return params, opt_state, loss
            # ONE batched H2D for the whole collected tree: passing the
            # numpy leaves straight to apply_jit would transfer each
            # leaf individually at dispatch (measured 0.1-0.26 s PER
            # LEAF on tunneled PJRT) — the same per-leaf pattern the
            # ps.py bridge batches away.
            grads = jax.tree_util.tree_unflatten(
                treedef, jax.device_put(state.collect(leaves)))
            params, opt_state = apply_jit(params, opt_state, grads)
            return params, opt_state, loss
        except Exception:
            # A crash mid-window (some taps fired, counter not advanced)
            # would double-count the failed pass on retry; roll back to
            # the window boundary so the next call resets cleanly.
            micro[0] -= micro[0] % backward_passes_per_step
            raise

    return step
