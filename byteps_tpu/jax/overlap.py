"""Per-layer compute/communication overlap for PS-mode JAX training.

SURVEY.md §7 "hard part #1": the reference's torch plugin registers
per-parameter autograd hooks so each gradient starts its push the moment
backward produces it (byteps/torch/__init__.py _make_hook) — communication
overlaps the *rest of backward*. JAX has no hooks: gradients normally
leave ``value_and_grad`` all at once, so PS-mode pushes can only start
after the whole backward finishes.

This module recovers hook-style streaming inside the jitted program:
every parameter leaf is wrapped in a ``custom_vjp`` identity *tap* whose
backward rule fires a ``jax.experimental.io_callback``. When XLA's
backward pass materialises that parameter's gradient, the callback hands
it straight to the C++ KV worker's priority-credit push queue — while the
device continues with the remaining backward compute. After the step's
dispatch completes, the host waits on the per-tensor handles (pulls) and
applies the optimizer update.

Priorities follow parameter declaration order (flattened tree order =
front-of-model first for standard model pytrees), so early layers' pulls
complete first — exactly the reference's scheduling rationale.

Topology contract: one JAX process per accelerator (the reference's
process-per-GPU layout). The local mesh must be a single device; use the
regular ``make_train_step`` when one controller drives several chips.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.experimental import io_callback

import byteps_tpu.jax as bps


class _TapState:
    """Declared tensors + in-flight handles for one step builder."""

    def __init__(self, client, prefix: str, average: bool,
                 compression_config: Optional[str]):
        self.client = client
        self.prefix = prefix
        self.average = average
        self.compression_config = compression_config
        self.tids: Dict[int, int] = {}
        self.lock = threading.Lock()
        self.inflight: Dict[int, Tuple[int, np.ndarray]] = {}

    def declare_all(self, leaves) -> None:
        for i, leaf in enumerate(leaves):
            self.tids[i] = self.client.declare(
                f"{self.prefix}_{i}", int(np.size(leaf)),
                np.dtype(leaf.dtype).name,
                compression=self.compression_config)

    def push(self, idx: int, g: np.ndarray) -> None:
        # io_callback may hand a read-only view; the C core sums in place,
        # so stage through a writable copy that also serves as the pull
        # destination.
        arr = np.array(g, copy=True).reshape(-1)
        h = self.client.push_pull(self.tids[idx], arr,
                                  average=self.average)
        with self.lock:
            self.inflight[idx] = (h, arr)

    def collect(self, leaves):
        out = []
        for i, leaf in enumerate(leaves):
            with self.lock:
                h, arr = self.inflight.pop(i)
            self.client.wait(h)
            out.append(arr.reshape(leaf.shape).astype(leaf.dtype))
        return out


def _make_tap(state: _TapState, idx: int):
    @jax.custom_vjp
    def tap(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        # Fires mid-backward on the host: enqueue this tensor's push while
        # the device keeps differentiating earlier layers.
        io_callback(lambda arr: state.push(idx, arr), None, g,
                    ordered=False)
        return (g,)

    tap.defvjp(fwd, bwd)
    return tap


def make_overlapped_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    *,
    average: bool = True,
    compression_config: Optional[str] = None,
    prefix: str = "ograd",
):
    """Build ``step(params, opt_state, batch) -> (params, opt_state, loss)``
    with hook-style push streaming (see module docstring).

    ``loss_fn(params, batch) -> scalar``. ``compression_config`` is the
    C-core codec string (e.g. ``"type=onebit;ef=vanilla"``) applied per
    tensor on the DCN leg. The returned loss is this worker's local loss.
    """
    st = bps._st()
    client = st.ps_client
    if client is None:
        raise RuntimeError(
            "make_overlapped_train_step needs PS mode (init with "
            "DMLC_NUM_SERVER>0 / BYTEPS_PS_MODE=ps)")
    if st.mesh is not None and st.mesh.size != 1:
        raise ValueError(
            "overlapped steps drive one accelerator per process "
            f"(local mesh has {st.mesh.size} devices); use "
            "make_train_step for multi-chip controllers")

    state = _TapState(client, prefix, average, compression_config)
    taps: Dict[int, Callable] = {}

    def tapped_loss(params, batch):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        tapped = [taps[i](leaf) for i, leaf in enumerate(leaves)]
        return loss_fn(jax.tree_util.tree_unflatten(treedef, tapped), batch)

    grad_jit = jax.jit(lambda p, b: jax.value_and_grad(tapped_loss)(p, b)[0])

    def apply_fn(params, opt_state, grads):
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    apply_jit = jax.jit(apply_fn)

    def step(params, opt_state, batch):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        if not taps:
            state.declare_all(leaves)
            for i in range(len(leaves)):
                taps[i] = _make_tap(state, i)
        loss = grad_jit(params, batch)
        # Block for the device (all taps have fired by completion); pushes
        # already overlapped the backward pass.
        loss.block_until_ready()
        grads = jax.tree_util.tree_unflatten(treedef,
                                             state.collect(leaves))
        params, opt_state = apply_jit(params, opt_state, grads)
        return params, opt_state, loss

    return step
