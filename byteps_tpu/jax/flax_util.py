"""Flax integration: data-parallel train step for models with mutable state.

The reference's DistributedOptimizer wraps any torch model incl. BatchNorm
models (ResNet-50 is its flagship benchmark). The flax equivalent needs the
mutable ``batch_stats`` collection threaded through the step; this helper
builds the canonical jitted shard_map'd step: per-device forward/backward,
push_pull on gradients, cross-replica averaging of batch statistics
(sync-BN-style), optimizer update.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import byteps_tpu.jax as bps
from byteps_tpu.jax._compat import shard_map as _shard_map
from byteps_tpu.jax.compression import Compression, Compressor


def cross_entropy_loss(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))


def make_flax_train_step(
    apply_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    *,
    loss_fn: Callable = cross_entropy_loss,
    average: bool = True,
    compression: Compressor = Compression.none,
    donate: bool = True,
    has_batch_stats: bool = True,
):
    """Build ``step(params, batch_stats, opt_state, (x, y)) ->
    (params, batch_stats, opt_state, loss)`` for a flax model.

    ``apply_fn`` is ``model.apply``. Batch leaves are sharded over the
    (dcn, ici) axes; params/opt_state replicated. Gradients are push_pull'd
    (hierarchical two-level all-reduce); batch_stats are pmean'd across
    replicas each step (synchronous statistics).
    """
    mesh = mesh or bps.mesh()
    cfg = bps._st().config
    axes = tuple(a for a in (cfg.dcn_axis, cfg.ici_axis)
                 if a in mesh.axis_names)

    @partial(_shard_map, mesh=mesh,
             in_specs=(P(), P(), P(), P(axes)),
             out_specs=(P(), P(), P(), P()),
             check_vma=False)
    def _step(params, batch_stats, opt_state, batch):
        x, y = batch

        def compute_loss(p):
            variables = {"params": p}
            if has_batch_stats:
                variables["batch_stats"] = batch_stats
                logits, new_state = apply_fn(
                    variables, x, train=True, mutable=["batch_stats"])
                return loss_fn(logits, y), new_state["batch_stats"]
            logits = apply_fn(variables, x, train=True)
            return loss_fn(logits, y), batch_stats

        (loss, new_stats), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(params)
        grads = bps.push_pull(grads, average=average, compression=compression)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        for ax in axes:
            loss = lax.pmean(loss, ax)
            new_stats = jax.tree_util.tree_map(
                lambda s, a=ax: lax.pmean(s, a), new_stats)
        return params, new_stats, opt_state, loss

    jit_kwargs = {"donate_argnums": (0, 1, 2)} if donate else {}
    return jax.jit(_step, **jit_kwargs)
