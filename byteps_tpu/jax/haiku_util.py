"""dm-haiku integration: data-parallel train step for transformed functions.

Same contract as the reference's per-framework plugins (SURVEY.md §2.5 —
it shipped adapters for every framework its users trained with): a haiku
``hk.transform`` / ``hk.transform_with_state`` pair gets the canonical
jitted shard_map'd step — per-device forward/backward, hierarchical
push_pull on gradients, pmean'd haiku state (sync-BN-style), optimizer
update — matching ``make_flax_train_step`` for flax.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import byteps_tpu.jax as bps
from byteps_tpu.jax._compat import axis_size as _axis_size
from byteps_tpu.jax._compat import shard_map as _shard_map
from byteps_tpu.jax.compression import Compression, Compressor


def make_haiku_train_step(
    loss_apply: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    *,
    average: bool = True,
    compression: Compressor = Compression.none,
    donate: bool = True,
    with_state: bool = False,
    rng: bool = False,
):
    """Build a DP step for a haiku-transformed loss.

    - ``with_state=False``: ``loss_apply = hk.transform(f).apply`` where
      ``f(batch) -> scalar loss``; step signature
      ``step(params, opt_state, key, batch) -> (params, opt_state, loss)``
      (``key=None`` when ``rng=False`` — haiku's without_apply_rng).
    - ``with_state=True``: ``loss_apply = hk.transform_with_state(f).apply``
      returning ``(loss, new_hk_state)``; step signature
      ``step(params, hk_state, opt_state, key, batch) ->
      (params, hk_state, opt_state, loss)``; state is pmean'd across
      replicas each step like flax batch_stats.

    Batch leaves are sharded over the (dcn, ici) axes; params/state/
    opt_state replicated. Per-device RNG: the key is folded with the
    device's linear mesh index so dropout differs across replicas.
    """
    mesh = mesh or bps.mesh()
    cfg = bps._st().config
    axes = tuple(a for a in (cfg.dcn_axis, cfg.ici_axis)
                 if a in mesh.axis_names)

    def _device_key(key):
        if key is None:
            return None
        idx = 0
        for ax in axes:
            idx = idx * _axis_size(ax) + lax.axis_index(ax)
        return jax.random.fold_in(key, idx)

    def _sync(loss, grads):
        grads = bps.push_pull(grads, average=average,
                              compression=compression)
        for ax in axes:
            loss = lax.pmean(loss, ax)
        return loss, grads

    if with_state:
        @partial(_shard_map, mesh=mesh,
                 in_specs=(P(), P(), P(), P(), P(axes)),
                 out_specs=(P(), P(), P(), P()),
                 check_vma=False)
        def _step(params, hk_state, opt_state, key, batch):
            def compute_loss(p):
                loss, new_state = loss_apply(p, hk_state, _device_key(key),
                                             batch)
                return loss, new_state

            (loss, new_state), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(params)
            loss, grads = _sync(loss, grads)
            for ax in axes:
                new_state = jax.tree_util.tree_map(
                    lambda s, a=ax: lax.pmean(s, a), new_state)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, new_state, opt_state, loss

        jit_kwargs = {"donate_argnums": (0, 1, 2)} if donate else {}
        jitted = jax.jit(_step, **jit_kwargs)

        def step(params, hk_state, opt_state, key, batch):
            return jitted(params, hk_state, opt_state, key, batch)

        return step

    @partial(_shard_map, mesh=mesh,
             in_specs=(P(), P(), P(), P(axes)),
             out_specs=(P(), P(), P()),
             check_vma=False)
    def _step(params, opt_state, key, batch):
        def compute_loss(p):
            if rng:
                return loss_apply(p, _device_key(key), batch)
            return loss_apply(p, None, batch)

        loss, grads = jax.value_and_grad(compute_loss)(params)
        loss, grads = _sync(loss, grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    jit_kwargs = {"donate_argnums": (0, 1)} if donate else {}
    return jax.jit(_step, **jit_kwargs)
