"""byteps_tpu.jax — the JAX framework plugin (the adapter boundary).

Capability parity with the reference's framework plugins (SURVEY.md §2.5,
byteps/torch/__init__.py + ops.py): ``init``, ``rank/size/local_rank/
local_size``, ``push_pull`` (+ ``_async``/``poll``/``synchronize``),
``declare_tensor``, ``DistributedOptimizer``, ``broadcast_parameters``.

TPU-first semantics:

- ``push_pull`` is *per-device* code when called inside ``jax.shard_map``
  (the hot path — XLA fuses the hierarchical ICI reduce-scatter/all-gather
  into the step program), and auto-wraps itself in a jitted shard_map when
  called on stacked per-replica arrays outside jit.
- Async handles map onto JAX's asynchronous dispatch: ``push_pull_async``
  returns immediately with arrays whose computation is in flight;
  ``synchronize`` blocks on them (reference: HandleManager + poll/
  synchronize, byteps/torch/handle_manager.cc — on TPU the runtime already
  gives us the async handle table for free).
- ``DistributedOptimizer`` is an optax gradient-transformation wrapper: the
  idiomatic JAX counterpart of wrapping ``optimizer.step()``.
"""

from __future__ import annotations

import dataclasses
import threading
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from byteps_tpu.config import Config, get_config
from byteps_tpu.jax.compression import Compression, Compressor
from byteps_tpu.parallel import hierarchical as _h
from byteps_tpu.parallel.mesh import build_mesh, set_global_mesh
from byteps_tpu.partition import TensorRegistry

from byteps_tpu.jax._compat import axis_size as _axis_size
from byteps_tpu.jax._compat import shard_map as _shard_map

__all__ = [
    "init", "shutdown", "initialized", "rank", "size", "device_count",
    "local_rank", "local_size", "push_pull", "push_pull_async", "poll", "synchronize",
    "declare_tensor", "broadcast_parameters", "broadcast_optimizer_state",
    "DistributedOptimizer", "Compression", "mesh",
]


@dataclasses.dataclass
class _State:
    config: Config
    mesh: Mesh
    registry: TensorRegistry
    ps_client: Any = None  # C++ KV client (PS mode), wired in core.ffi


_state: Optional[_State] = None
_lock = threading.Lock()


def init(mesh: Optional[Mesh] = None, config: Optional[Config] = None) -> None:
    """Initialise byteps_tpu (reference: bps.init() → byteps_init,
    SURVEY.md §3.2). Builds/installs the (dcn, ici) device mesh, the tensor
    registry, and — in PS mode — the C++ KV client connection to the
    scheduler."""
    global _state
    # Drain BEFORE touching any global state (and before the C core is
    # re-initialised below): a stale async op from a previous session must
    # fully settle against the OLD client, not straddle the re-init.
    from byteps_tpu.jax import ps as _ps_drain
    _ps_drain.drain_bridge()
    with _lock:
        cfg = config or get_config(reload=True)
        if mesh is None:
            mesh = build_mesh(dcn_axis=cfg.dcn_axis, ici_axis=cfg.ici_axis)
        set_global_mesh(mesh)
        registry = TensorRegistry(cfg.partition_bytes,
                                  max(1, cfg.num_server))
        ps_client = None
        if cfg.use_ps:
            try:
                from byteps_tpu.core import ffi as _ffi
            except ImportError as e:
                raise RuntimeError(
                    "PS mode requested (BYTEPS_PS_MODE=ps / DMLC_NUM_SERVER>0"
                    " / BYTEPS_FORCE_DISTRIBUTED=1) but the byteps_tpu C++ "
                    "core is not built. Build it with "
                    "`python -m byteps_tpu.core.build`, or set "
                    "BYTEPS_PS_MODE=collective to use pure XLA collectives."
                ) from e
            ps_client = _ffi.Worker.start(cfg)
        from byteps_tpu.jax import ps as _ps
        _ps.reset_declare_cache()
        _global_run_cache.clear()
        _state = _State(cfg, mesh, registry, ps_client)


def shutdown() -> None:
    """Tear down (reference: byteps_shutdown)."""
    global _state
    from byteps_tpu.jax import ps as _ps
    # Settle in-flight async bridge ops BEFORE taking the lock or touching
    # the C++ client: a pending push_pull_async still holds staged host
    # buffers the core pulls into, and must complete against a live fleet.
    _ps.drain_bridge()
    with _lock:
        if _state is not None and _state.ps_client is not None:
            _state.ps_client.shutdown()
        _ps.reset_declare_cache()
        _global_run_cache.clear()
        _state = None


def initialized() -> bool:
    return _state is not None


def _st() -> _State:
    if _state is None:
        raise RuntimeError("byteps_tpu.jax.init() has not been called")
    return _state


def mesh() -> Mesh:
    return _st().mesh


# --- topology queries (reference: BytePSBasics, byteps/common/__init__.py) --
#
# Horovod-contract note: in the reference, one process drives one GPU, so
# rank/size are simultaneously the process index and the chip index. Under
# single-controller JAX one process drives all its local chips, so the two
# notions split. We keep the Horovod invariant rank() ∈ [0, size()) at the
# *process* level — the level at which users shard input data — and expose
# the chip count separately as device_count() (the gradient-averaging
# denominator, applied internally by push_pull).

def rank() -> int:
    """Index of this controller process in [0, size()).

    PS mode: the fleet-wide worker rank (DMLC_WORKER_ID order) — each
    launcher-spawned worker is its own JAX process, so
    ``jax.process_index()`` would be 0 everywhere and data sharding by
    rank would silently train identical shards. Collective /
    multi-controller mode: ``jax.process_index()``.
    """
    st = _st()
    if st.ps_client is not None:
        return st.ps_client.worker_rank()
    return jax.process_index()


def size() -> int:
    """Number of controller processes (use with rank() for data sharding).

    PS mode: the fleet's worker count; otherwise ``jax.process_count()``
    (see rank()).
    """
    st = _st()
    if st.ps_client is not None:
        return st.ps_client.num_workers()
    return jax.process_count()


def device_count() -> int:
    """Total participating chips — the reduction denominator."""
    return _st().mesh.size


def local_rank() -> int:
    """This process's index among processes on the same host."""
    return _st().config.local_rank


def local_size() -> int:
    """Number of chips driven by this process."""
    _st()
    return jax.local_device_count()


# --- push_pull -------------------------------------------------------------

def _axes():
    st = _st()
    names = st.mesh.axis_names
    ici = st.config.ici_axis if st.config.ici_axis in names else None
    dcn = st.config.dcn_axis if st.config.dcn_axis in names else None
    return ici, dcn


# In-jit push_pull always reduces via XLA collectives over the mesh axes.
# In PS mode the mesh is process-local (one BytePS worker per controller
# process), so those collectives cover exactly the local chips; the
# cross-host DCN level runs at the host boundary through the C++ KV client
# (byteps_tpu.jax.ps.ps_push_pull / _make_ps_train_step).


def _inside_spmd(axis: Optional[str]) -> bool:
    if axis is None:
        return False
    try:
        _axis_size(axis)
        return True
    except Exception:  # unbound axis name outside shard_map
        return False


def push_pull(tree, average: bool = True, name: Optional[str] = None,
              compression: Compressor = Compression.none):
    """Sum (or average) a pytree of gradients across all chips.

    Inside ``shard_map`` this is the hot path: hierarchical two-level
    all-reduce (SURVEY.md §3.3's REDUCE→PUSH/PULL→BROADCAST pipeline as one
    fused XLA program). Outside, arrays must carry a leading replica axis of
    length ``device_count()`` — this process's mesh size — (stacked
    per-chip values) and the same collective runs under a jitted shard_map;
    in PS mode the result then crosses the host boundary once more through
    the C++ KV client, so the reduction is global across worker processes
    (Horovod semantics), not just across this host's chips. ``name`` keys
    the PS registry for that leg; unnamed calls share a shape-keyed name
    and must be issued in the same order on every worker.
    """
    ici, dcn = _axes()
    if _inside_spmd(ici) or _inside_spmd(dcn):
        return _per_device_push_pull(tree, average, compression)
    return _global_push_pull(tree, average, compression, name)


def _per_device_push_pull(tree, average, compression):
    ici, dcn = _axes()
    if compression.name in ("int8_quant", "int8_quant_dcn"):
        # quantization replaces the transport itself (all-to-all of int8
        # chunks + scales), not a pre-cast; see hierarchical.py
        return _h.tree_quantized_all_reduce(
            tree, ici_axis=ici, dcn_axis=dcn, average=average,
            quantize_dcn=compression.name == "int8_quant_dcn")
    orig_dtypes = jax.tree_util.tree_map(lambda x: x.dtype, tree)
    tree = jax.tree_util.tree_map(compression.compress, tree)
    red = _h.tree_all_reduce(
        tree, ici_axis=ici, dcn_axis=dcn, average=average)
    return jax.tree_util.tree_map(
        lambda x, d: compression.decompress(x, d), red, orig_dtypes)


# (mesh, mesh_axes, average, compression) -> jitted host-level reducer.
# Without this cache every host-level push_pull would build a FRESH
# closure, and jax.jit's cache (keyed on function identity) would retrace
# and recompile per call — seconds per step for a per-step API. Cleared by
# init()/shutdown() (a new mesh keys differently anyway).
_global_run_cache: dict = {}


def _global_run(mesh, mesh_axes, average, compression):
    key = (mesh, mesh_axes, average, compression)
    run = _global_run_cache.get(key)
    if run is None:
        @partial(jax.jit)
        @partial(_shard_map, mesh=mesh, in_specs=P(mesh_axes),
                 out_specs=P(), check_vma=False)
        def run(stacked):
            local = jax.tree_util.tree_map(lambda x: x[0], stacked)
            return _per_device_push_pull(local, average, compression)

        _global_run_cache[key] = run
    return run


def _global_push_pull(tree, average, compression, name=None):
    st = _st()
    n = st.mesh.size
    ici, dcn = _axes()
    mesh_axes = tuple(a for a in (dcn, ici) if a)

    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return tree
    for leaf in leaves:
        if leaf.ndim == 0 or leaf.shape[0] != n:
            raise ValueError(
                "push_pull outside shard_map expects arrays stacked over a "
                "leading replica axis of length device_count()="
                f"{n} (this process's mesh size); got shape "
                f"{leaf.shape}. Inside a shard_map'd step, call push_pull "
                "on the per-device gradients directly.")

    out = _global_run(st.mesh, mesh_axes, average, compression)(tree)
    if st.ps_client is not None:
        # Cross-worker DCN leg: the in-jit collective covered only this
        # process's chips (the mesh is process-local in PS mode), so a
        # host-level push_pull must still cross the PS fleet to keep
        # Horovod-global semantics. The denominator factorises: local
        # pmean over n chips, then PS average over equal workers.
        from byteps_tpu.jax import ps as _ps
        out = _ps.ps_push_pull(out, average=average,
                               prefix=name or "push_pull")
    return out


# --- async handle surface (reference: handle_manager.cc + ops.py) ----------

@dataclasses.dataclass
class Handle:
    """An in-flight push_pull. In collective mode JAX's async dispatch IS
    the handle table (``value`` holds not-yet-ready arrays); in PS mode
    ``value`` is a Future for the host-side DCN round trip running on the
    bridge thread."""

    value: Any


def push_pull_async(tree, average: bool = True, name: Optional[str] = None,
                    compression: Compressor = Compression.none) -> Handle:
    """Non-blocking push_pull (reference: push_pull_async + handle table).

    Collective mode: XLA's async dispatch means the jitted collective is
    already in flight when this returns. PS mode: the host-level DCN leg
    (device_get → C++ push/pull → device_put) runs on the ordered bridge
    thread (byteps_tpu.jax.ps) so this call returns immediately, the
    fleet round trip overlaps with the caller's other host work, and
    declares stay in fleet-consistent order against synchronous calls;
    ``synchronize`` joins it.
    """
    st = _st()
    ici, dcn = _axes()
    inside = _inside_spmd(ici) or _inside_spmd(dcn)
    if st.ps_client is not None and not inside:
        from byteps_tpu.jax import ps as _ps
        fut = _ps.submit_ordered(
            _global_push_pull, tree, average, compression, name)
        return Handle(fut)
    return Handle(push_pull(tree, average=average, name=name,
                            compression=compression))


def _is_future(v) -> bool:
    return hasattr(v, "done") and hasattr(v, "result")


def poll(handle: Handle) -> bool:
    """True iff the result is materialised (reference: byteps_torch_poll)."""
    value = handle.value
    if _is_future(value):
        if not value.done():
            return False
        # The bridge op ends with a non-blocking device_put; "done" means
        # the fleet round trip finished, not that the H2D transfers have
        # landed — hold poll() to the same is_ready bar as the
        # collective branch.
        value = value.result()
    leaves = jax.tree_util.tree_leaves(value)
    return all(l.is_ready() for l in leaves if hasattr(l, "is_ready"))


def synchronize(handle: Handle):
    """Block until the result is ready and return it."""
    if _is_future(handle.value):
        return jax.block_until_ready(handle.value.result())
    return jax.block_until_ready(handle.value)


# --- declare / broadcast ----------------------------------------------------

def declare_tensor(name: str, shape, dtype) -> None:
    """Pre-register a tensor (reference: byteps_declare_tensor). Establishes
    declaration-order priority and the partition/key table used by the PS
    path and the trace timeline."""
    _st().registry.declare(name, tuple(shape), jnp.dtype(dtype).name)


def broadcast_parameters(tree, root_rank: int = 0,
                         name: Optional[str] = None):
    """Replicate ``tree`` from ``root_rank``'s copy to all chips (reference:
    broadcast_parameters, SURVEY.md §3.4).

    Inside shard_map: a masked-psum broadcast over both axes. Outside, with
    single-controller JAX, this host's chips are already logically
    replicated, so locally it devolves to installing a fully-replicated
    sharding; in PS mode the tree additionally round-trips through the
    servers so every worker process ends up holding ``root_rank``'s values
    (the reference's init-time weight sync, SURVEY.md §3.4). ``name`` keys
    the PS registry for that leg — distinct same-shaped trees broadcast
    from different call sites should pass distinct names (unnamed calls
    share a shape-keyed name and must be issued in the same order on
    every worker).
    """
    ici, dcn = _axes()
    if _inside_spmd(ici) or _inside_spmd(dcn):
        return _h.tree_broadcast(tree, root=root_rank,
                                 ici_axis=ici, dcn_axis=dcn)
    st = _st()
    if st.ps_client is not None:
        from byteps_tpu.jax import ps as _ps
        tree = _ps.ps_broadcast(tree, root_rank=root_rank,
                                prefix=name or "param")
    repl = jax.sharding.NamedSharding(st.mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, repl), tree)


def broadcast_optimizer_state(opt_state, root_rank: int = 0,
                              name: str = "opt_state"):
    """Replicate optimizer state from ``root_rank`` (reference:
    broadcast_optimizer_state). optax states are pytrees of arrays;
    non-array leaves (python scalars, schedule callables) pass through
    untouched. All array leaves go through ONE broadcast_parameters call
    (one batched host round trip in PS mode, not one per leaf); pass a
    distinct ``name`` when broadcasting several optimizer states."""
    leaves, treedef = jax.tree_util.tree_flatten(opt_state)
    arr_idx = [i for i, l in enumerate(leaves) if hasattr(l, "dtype")]
    if arr_idx:
        synced = broadcast_parameters([leaves[i] for i in arr_idx],
                                      root_rank=root_rank, name=name)
        for i, v in zip(arr_idx, synced):
            leaves[i] = v
    return jax.tree_util.tree_unflatten(treedef, leaves)


# --- DistributedOptimizer ---------------------------------------------------

def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    *,
    average: bool = True,
    compression: Compressor = Compression.none,
    backward_passes_per_step: int = 1,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so updates are push_pull'd before applying.

    Reference: byteps/torch DistributedOptimizer (SURVEY.md §2.5) — which
    hooks autograd to overlap communication with backward compute. In JAX
    the overlap is XLA's job: call ``update`` inside your shard_map'd jitted
    train step and the fused reduce-scatter/all-gather is scheduled by the
    compiler alongside remaining compute.

    ``backward_passes_per_step`` > 1 reproduces the reference's gradient
    accumulation contract: grads are accumulated locally that many times and
    communicated once (use with ``optax.MultiSteps`` or lax.scan'd
    microbatching; the division by the accumulation count is the caller's,
    exactly as in the reference).
    """
    if backward_passes_per_step < 1:
        raise ValueError("backward_passes_per_step must be >= 1")

    def init_fn(params):
        return optimizer.init(params)

    def update_fn(updates, state, params=None, **extra):
        updates = push_pull(updates, average=average, compression=compression)
        return optimizer.update(updates, state, params, **extra)

    return optax.GradientTransformation(init_fn, update_fn)
