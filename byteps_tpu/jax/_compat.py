"""JAX version compatibility shims.

All in-tree code (library, tests, examples) that touches an API renamed
or added across the supported jax range goes through this module instead
of jax directly:

- ``shard_map``: top-level on jax >= 0.8, ``jax.experimental.shard_map``
  before; the replication-check kwarg renamed check_rep -> check_vma.
- ``axis_size``: ``jax.lax.axis_size`` exists only on newer jax; older
  versions spell it ``lax.psum(1, axis)`` (statically evaluated, so it
  is a Python int inside shard_map either way, and raises NameError on
  an unbound axis exactly like the real one).
"""

from __future__ import annotations

import inspect

from jax import lax as _lax

try:  # jax >= 0.8 exports shard_map at top level
    from jax import shard_map as _raw_shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _raw_shard_map

# The replication-check kwarg was renamed check_rep -> check_vma across jax
# versions; detect which one this jax accepts.
_params = inspect.signature(_raw_shard_map).parameters
if "check_vma" in _params:
    _CHECK_KW = "check_vma"
elif "check_rep" in _params:  # pragma: no cover - older jax
    _CHECK_KW = "check_rep"
else:  # pragma: no cover
    _CHECK_KW = None


def shard_map(f=None, /, *, mesh, in_specs, out_specs, check_vma=True):
    """jax.shard_map with the replication-check kwarg name normalised."""
    kwargs = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    if _CHECK_KW is not None:
        kwargs[_CHECK_KW] = check_vma
    if f is None:
        return lambda g: _raw_shard_map(g, **kwargs)
    return _raw_shard_map(f, **kwargs)


def axis_size(axis_name):
    """``jax.lax.axis_size`` with a pre-axis_size-API fallback.

    Inside ``shard_map``/``pmap`` both forms return the mapped axis size
    as a Python int (``psum`` of a concrete constant is evaluated
    statically); outside, both raise ``NameError`` for the unbound axis
    name — callers that probe for "am I inside spmd?" rely on that.
    """
    if hasattr(_lax, "axis_size"):
        return _lax.axis_size(axis_name)
    return _lax.psum(1, axis_name)
