"""JAX version compatibility shims."""

from __future__ import annotations

import inspect

try:  # jax >= 0.8 exports shard_map at top level
    from jax import shard_map as _raw_shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _raw_shard_map

# The replication-check kwarg was renamed check_rep -> check_vma across jax
# versions; detect which one this jax accepts.
_params = inspect.signature(_raw_shard_map).parameters
if "check_vma" in _params:
    _CHECK_KW = "check_vma"
elif "check_rep" in _params:  # pragma: no cover - older jax
    _CHECK_KW = "check_rep"
else:  # pragma: no cover
    _CHECK_KW = None


def shard_map(f=None, /, *, mesh, in_specs, out_specs, check_vma=True):
    """jax.shard_map with the replication-check kwarg name normalised."""
    kwargs = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    if _CHECK_KW is not None:
        kwargs[_CHECK_KW] = check_vma
    if f is None:
        return lambda g: _raw_shard_map(g, **kwargs)
    return _raw_shard_map(f, **kwargs)
