"""Canonical data-parallel training step builder.

The reference's end-user contract (SURVEY.md §3.3): wrap your optimizer,
call ``loss.backward()``; gradients are push_pull'd behind the scenes and
``step()`` applies the synchronized update. The JAX-native equivalent is a
*jitted, shard_map'd step function*: gradients come out of ``value_and_grad``
per-device, ``push_pull`` fuses the hierarchical reduction into the same XLA
program, and the optimizer update runs replicated. XLA overlaps the ICI
collectives with remaining backward compute — the compiler plays the role of
the reference's priority-scheduled background pipeline threads.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

import byteps_tpu.jax as bps
from byteps_tpu.jax.compression import Compression, Compressor

from byteps_tpu.jax._compat import shard_map as _shard_map


def make_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    *,
    average: bool = True,
    compression: Compressor = Compression.none,
    donate: bool = True,
    ps_prefix: str = "grad",
):
    """Build ``step(params, opt_state, batch) -> (params, opt_state, loss)``.

    ``loss_fn(params, batch) -> scalar``. ``batch`` is a pytree whose leaves
    carry the global batch on their leading axis; it is sharded over the
    (dcn, ici) mesh axes. Params/opt_state are replicated. The returned step
    is jitted with donated params/opt_state (in-place buffer reuse in HBM).

    ``ps_prefix`` names this step's gradient tensors in the PS registry
    (PS mode only). Wire names carry the tree's shape/dtype signature, so
    two step builders may share a prefix; distinct prefixes still help
    trace readability.
    """
    mesh = mesh or bps.mesh()
    cfg = bps._st().config
    axes = tuple(a for a in (cfg.dcn_axis, cfg.ici_axis)
                 if a in mesh.axis_names)

    if cfg.use_ps:
        return _make_ps_train_step(loss_fn, optimizer, mesh, axes, average,
                                   compression, donate, ps_prefix)

    @partial(_shard_map, mesh=mesh,
             in_specs=(P(), P(), P(axes)),
             out_specs=(P(), P(), P()),
             check_vma=False)
    def _step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        grads = bps.push_pull(grads, average=average, compression=compression)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        for ax in axes:
            loss = lax.pmean(loss, ax)
        return params, opt_state, loss

    jit_kwargs = {"donate_argnums": (0, 1)} if donate else {}
    return jax.jit(_step, **jit_kwargs)


def _make_ps_train_step(loss_fn, optimizer, mesh, axes, average, compression,
                        donate, prefix="grad"):
    """PS-mode step: local-chip level inside jit, cross-host DCN level
    through the C++ KV client to the CPU parameter servers (SURVEY.md
    §3.3's two-level pipeline with XLA playing NCCL and the core playing
    ps-lite).

    In PS mode the mesh is process-local (one BytePS worker per controller
    process), so the in-jit reduction covers exactly this host's chips.
    Semantics match the collective path: average=True gives the global mean
    (local pmean, then PS average over equal-sized workers); average=False
    gives the global sum (local psum, then PS sum). Wire compression is
    applied inside jit before the host transfer (XLA fuses the cast) and
    undone after the pull.
    """
    from byteps_tpu.jax.ps import ps_push_pull

    if compression.name in ("int8_quant", "int8_quant_dcn"):
        # int8_quant replaces the *collective transport* (all-to-all of
        # int8 chunks + scales); in PS mode its compress fn is an identity,
        # so the DCN leg would silently ship uncompressed f32. The PS wire
        # has its own codec framework — point the user there.
        raise ValueError(
            f"Compression {compression.name!r} (int8 quantized transport) "
            "only applies to collective mode. In PS mode use the C-core "
            "codec instead: declare tensors with a compressor config "
            "string (e.g. BYTEPS_COMPRESSOR=onebit or type=dithering;k=4), "
            "or use Compression.bf16/fp16 for an in-jit wire cast.")

    @jax.jit
    @partial(_shard_map, mesh=mesh, in_specs=(P(), P(axes)),
             out_specs=(P(), P()), check_vma=False)
    def grad_step(params, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        reduce = lax.pmean if average else lax.psum
        for ax in axes:
            grads = jax.tree_util.tree_map(
                lambda g, a=ax: reduce(g, a), grads)
            loss = lax.pmean(loss, ax)
        grads = jax.tree_util.tree_map(compression.compress, grads)
        return loss, grads

    def apply_step(params, opt_state, grads):
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    apply_jit = jax.jit(apply_step,
                        donate_argnums=(0, 1) if donate else ())

    def step(params, opt_state, batch):
        loss, grads = grad_step(params, batch)
        dtypes = jax.tree_util.tree_map(lambda p: p.dtype, params)
        grads = ps_push_pull(grads, average=average, prefix=prefix)
        grads = jax.tree_util.tree_map(
            lambda g, d: compression.decompress(g, d), grads, dtypes)
        params, opt_state = apply_jit(params, opt_state, grads)
        return params, opt_state, loss

    return step


def make_async_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    params,
    *,
    prefix: str = "aparam",
):
    """Asynchronous PS training (reference: BYTEPS_ENABLE_ASYNC,
    server.cc async path): the SERVER holds the parameters as a
    server-resident accumulator; each worker, at its own pace and with no
    per-round barrier, computes a local update and pushes the DELTA, then
    pulls whatever the parameters currently are — stale gradients by
    design.

    ``params`` is the initial pytree; call on every worker with identical
    values BEFORE training (it seeds the server copy via ps_broadcast from
    rank 0). Returns ``step(params, opt_state, batch) ->
    (params, opt_state, loss)`` where the returned params are the freshly
    pulled server state.
    """
    from byteps_tpu.jax.ps import ps_broadcast

    st = bps._st()
    client = st.ps_client
    if client is None:
        raise RuntimeError(
            "make_async_train_step needs PS mode (DMLC_NUM_SERVER>0)")

    # Seed: rank 0's initial params become the server-resident copy —
    # CMD_BCAST_PUSH initialises the async accumulator for THE SAME wire
    # keys the step pushes deltas to, and everyone starts from the same
    # values.
    params = ps_broadcast(params, root_rank=0, prefix=prefix)

    @jax.jit
    def local_update(p, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(p, batch)
        updates, opt_state = optimizer.update(grads, opt_state, p)
        return updates, opt_state, loss

    leaves0, treedef = jax.tree_util.tree_flatten(params)
    # Wire keys MUST be the ones ps_broadcast seeded: _tids derives the
    # same `{prefix}_{crc32:08x}_{i}` names (and hits its cache, since
    # the broadcast above registered this exact tree). Declaring bare
    # `{prefix}_{i}` here instead would push the deltas to fresh,
    # never-initialised server keys — the first delta would silently
    # BECOME the parameters instead of updating them.
    from byteps_tpu.jax.ps import (_as_arrays, _codec_active, _tids,
                                   _wait_all, _wire_plan, _writable)

    plan_leaves = _as_arrays(leaves0)
    tids = _tids(client, prefix, plan_leaves,
                 _wire_plan(plan_leaves, _codec_active(st)))

    def step(params, opt_state, batch):
        updates, opt_state, loss = local_update(params, opt_state, batch)
        up_leaves = jax.tree_util.tree_flatten(updates)[0]
        # ONE batched D2H for the whole delta tree (per-leaf np.asarray
        # pays the host-boundary dispatch latency once per leaf).
        host = jax.device_get(up_leaves)
        staged = []
        for tid, arr in zip(tids, host):
            arr = _writable(arr)
            h = client.push_pull(tid, arr, average=False, async_mode=True)
            staged.append((h, arr, None))
        _wait_all(client, staged)  # settle every handle before surfacing
        # ONE batched H2D for the pulled server state (mirror of ps.py).
        devs = jax.device_put([arr for _, arr, _ in staged])
        fresh = [d.reshape(leaf.shape).astype(leaf.dtype)
                 for d, leaf in zip(devs, leaves0)]
        return (jax.tree_util.tree_unflatten(treedef, fresh), opt_state,
                loss)

    return params, step


def replicate(tree, mesh: Optional[Mesh] = None):
    """Place a host pytree replicated on every device of the mesh."""
    mesh = mesh or bps.mesh()
    sharding = jax.sharding.NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


def shard_batch(batch, mesh: Optional[Mesh] = None):
    """Shard a host batch over the data-parallel mesh axes (leading dim).

    Single-controller: ``batch`` carries the GLOBAL batch and is laid out
    over the mesh. Multi-controller (``jax.distributed`` across hosts —
    the collective-mode analogue of the reference's one-process-per-GPU
    fleets): ``batch`` carries THIS PROCESS's shard (the Horovod
    contract — shard your input by ``rank()``), and the shards are
    assembled into one global array spanning all hosts.
    """
    mesh = mesh or bps.mesh()
    cfg = bps._st().config
    axes = tuple(a for a in (cfg.dcn_axis, cfg.ici_axis)
                 if a in mesh.axis_names)
    sharding = jax.sharding.NamedSharding(mesh, P(axes))
    if jax.process_count() > 1:
        import numpy as np
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(
                sharding, np.asarray(x)), batch)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), batch)
