"""byteps_tpu.keras — Keras framework plugin (Horovod-compatible API).

Capability parity: reference byteps/keras/__init__.py (SURVEY.md §2.5):
``init`` / ``rank`` / ``size`` etc. re-exported from the TensorFlow
plugin, ``DistributedOptimizer`` usable directly in ``model.compile``,
``broadcast_global_variables``, and the callback set in
``byteps_tpu.keras.callbacks``.

    import byteps_tpu.keras as bps
    bps.init()
    model.compile(optimizer=bps.DistributedOptimizer(keras.optimizers.SGD(
        learning_rate=0.01 * bps.size())), loss=..., metrics=[...])
    model.fit(dataset,
              callbacks=[bps.callbacks.BroadcastGlobalVariablesCallback(0),
                         bps.callbacks.MetricAverageCallback()])
"""

from __future__ import annotations

from byteps_tpu.tensorflow import (  # noqa: F401
    Compression,
    DistributedOptimizer,
    broadcast,
    broadcast_variables,
    init,
    initialized,
    local_rank,
    local_size,
    push_pull,
    rank,
    shutdown,
    size,
)

from byteps_tpu.keras import callbacks  # noqa: F401  (after bps exports)

__all__ = [
    "init", "shutdown", "initialized", "rank", "size", "local_rank",
    "local_size", "push_pull", "broadcast", "broadcast_variables",
    "broadcast_global_variables", "DistributedOptimizer", "Compression",
    "callbacks",
]


def broadcast_global_variables(root_rank: int = 0) -> None:
    """Broadcast every TF global variable from ``root_rank`` (reference:
    keras broadcast_global_variables — TF1-session flavour). With TF2
    eager there is no global collection; prefer
    ``broadcast_variables(model.variables)`` or the
    BroadcastGlobalVariablesCallback."""
    import tensorflow as tf

    v1_vars = tf.compat.v1.global_variables()
    if not v1_vars:
        raise RuntimeError(
            "no tf.compat.v1 global variables exist (TF2 eager mode); "
            "use broadcast_variables(model.variables, root_rank) or the "
            "BroadcastGlobalVariablesCallback instead")
    broadcast_variables(v1_vars, root_rank=root_rank)
