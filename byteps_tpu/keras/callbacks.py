"""Keras callbacks for byteps_tpu (Horovod-compatible names/semantics).

Capability parity: reference byteps/keras/callbacks.py +
byteps/tensorflow/keras/callbacks.py (SURVEY.md §2.5):
``BroadcastGlobalVariablesCallback``, ``MetricAverageCallback``,
``LearningRateWarmupCallback``, ``LearningRateScheduleCallback`` — real
``keras.callbacks.Callback`` subclasses that plug into ``model.fit``.
"""

from __future__ import annotations

from typing import Callable, Optional

import tensorflow as tf

import byteps_tpu.tensorflow as bps

_KerasCallback = tf.keras.callbacks.Callback


class BroadcastGlobalVariablesCallback(_KerasCallback):
    """Broadcast all model/optimizer variables from ``root_rank`` at the
    start of training so every worker begins from identical state
    (reference: keras BroadcastGlobalVariablesCallback)."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self._done = False

    def on_train_begin(self, logs=None):
        if self._done or bps.size() <= 1:
            return
        model_vars = list(getattr(self.model, "variables", []) or [])
        opt = getattr(self.model, "optimizer", None)
        opt_vars = list(getattr(opt, "variables", []) or []) if opt else []
        seen = set()
        to_sync = []
        for v in model_vars + opt_vars:
            if id(v) not in seen and hasattr(v, "assign"):
                seen.add(id(v))
                to_sync.append(v)
        bps.broadcast_variables(to_sync, root_rank=self.root_rank)
        self._done = True


class MetricAverageCallback(_KerasCallback):
    """Average epoch metrics over all workers before other callbacks
    (checkpointing, early stopping, logging) read them (reference: keras
    MetricAverageCallback). Place it before those callbacks in the list."""

    def on_epoch_end(self, epoch, logs=None):
        if not logs or bps.size() <= 1:
            return
        for k in sorted(logs):
            v = logs[k]
            if isinstance(v, (int, float)):
                logs[k] = float(bps.push_pull(
                    tf.constant(float(v)), average=True,
                    name=f"metric.{k}").numpy())


class LearningRateScheduleCallback(_KerasCallback):
    """Multiply the optimizer LR by ``multiplier`` (a constant or a
    function of epoch) within [start_epoch, end_epoch) (reference: keras
    LearningRateScheduleCallback)."""

    def __init__(self, initial_lr: float,
                 multiplier,
                 start_epoch: int = 0,
                 end_epoch: Optional[int] = None,
                 staircase: bool = True,
                 steps_per_epoch: Optional[int] = None):
        super().__init__()
        self.initial_lr = float(initial_lr)
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self._current_epoch = 0
        if callable(multiplier):
            self._mult: Callable[[float], float] = multiplier
            self._constant = None
        else:
            self._constant = float(multiplier)
            self._mult = lambda epoch: self._constant

    def _in_window(self, epoch: float) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def _set_lr(self, epoch: float) -> None:
        if not self._in_window(epoch):
            return
        lr = self.initial_lr * self._mult(epoch)
        opt = self.model.optimizer
        # Keras 3: .learning_rate variable; Keras 2 legacy: .lr
        target = getattr(opt, "learning_rate", None)
        if target is None:
            target = getattr(opt, "lr")
        if hasattr(target, "assign"):
            target.assign(lr)
        else:
            opt.learning_rate = lr

    def on_epoch_begin(self, epoch, logs=None):
        self._current_epoch = epoch
        if self.staircase:
            self._set_lr(epoch)

    def on_batch_begin(self, batch, logs=None):
        if not self.staircase and self.steps_per_epoch:
            self._set_lr(self._current_epoch +
                         batch / float(self.steps_per_epoch))


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Horovod's gradual LR warmup (reference: keras
    LearningRateWarmupCallback): ramp from ``initial_lr`` to
    ``initial_lr * multiplier`` (default: worker count, the linear-scaling
    rule) over ``warmup_epochs`` epochs, smoothly per batch."""

    def __init__(self, initial_lr: float,
                 multiplier: Optional[float] = None,
                 warmup_epochs: int = 5,
                 steps_per_epoch: Optional[int] = None,
                 verbose: bool = False):
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose
        mult = float(multiplier if multiplier is not None else bps.size())

        def warmup_mult(epoch: float) -> float:
            frac = min(1.0, (epoch + 1.0) / max(1, self.warmup_epochs))
            return 1.0 + frac * (mult - 1.0)

        # Without steps_per_epoch a non-staircase schedule has no per-batch
        # clock and would silently never adjust the LR — fall back to
        # per-epoch (staircase) warmup so the ramp still happens.
        super().__init__(initial_lr, warmup_mult, start_epoch=0,
                         end_epoch=warmup_epochs,
                         staircase=steps_per_epoch is None,
                         steps_per_epoch=steps_per_epoch)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose and epoch == self.warmup_epochs - 1:
            print(f"warmup complete: lr -> "
                  f"{float(tf.keras.backend.get_value(self.model.optimizer.learning_rate)):.6g}")
