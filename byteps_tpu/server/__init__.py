"""Parameter-server / scheduler process entry.

Capability parity: reference byteps/server/__init__.py (SURVEY.md §2.3) —
there, ``import byteps.server`` blocks in the server loop as an import
side-effect. We keep the same capability behind an explicit entry point
(``python -m byteps_tpu.server``; role from DMLC_ROLE) — import
side-effects that block are hostile to tooling, so main() is a function.
"""

from __future__ import annotations

import os
import sys


def main() -> None:
    role = os.environ.get("DMLC_ROLE", "server").lower()
    recover_rank = os.environ.get("DMLC_RECOVER_RANK", "")
    if recover_rank and role == "server":
        # Hot replacement (ISSUE 4): this incarnation adopts a dead
        # server rank's id + key shard. Start() registers with the
        # recovery marker; the scheduler answers with a direct ADDRBOOK
        # and broadcasts the epoch RESUME, and the workers re-seed us.
        print(f"byteps_tpu.server: starting as hot replacement for "
              f"server rank {recover_rank}", file=sys.stderr, flush=True)
    sched_recover = os.environ.get("DMLC_SCHED_RECOVER", "")
    if sched_recover and role == "scheduler":
        # Scheduler fail-over (ISSUE 15): this incarnation is a
        # crash-restart of the control plane. Start() listens on the
        # same pinned port and rebuilds the address book / rank
        # allocator / tenant rosters from the parked fleet's
        # CMD_REREGISTER quorum instead of running fleet formation; a
        # failed rebuild (conflict / window expiry) aborts nonzero so
        # the supervisor can attribute the death.
        print("byteps_tpu.server: starting as scheduler crash-restart "
              "(DMLC_SCHED_RECOVER) — waiting for the fleet's "
              "re-registration quorum", file=sys.stderr, flush=True)
    if (os.environ.get("BYTEPS_CKPT_RESTORE", "")
            and role in ("scheduler", "server")):
        # Durable restore (ISSUE 18): this fleet resumes from disk. A
        # server scans its BYTEPS_CKPT_DIR shard for the newest
        # checksum-valid version and registers it; the scheduler commits
        # a restore epoch at the minimum version common to EVERY shard —
        # or refuses to start if any shard has nothing valid (a named
        # fail-stop, never a silent cold start).
        print(f"byteps_tpu.server: {role} starting in checkpoint-restore "
              f"mode (BYTEPS_CKPT_RESTORE, dir "
              f"{os.environ.get('BYTEPS_CKPT_DIR', '?')})",
              file=sys.stderr, flush=True)
    replica_of = os.environ.get("BYTEPS_REPLICA_OF", "")
    if role == "replica":
        # Versioned snapshot serving (ISSUE 16): a read-only replica.
        # Registers with the scheduler for a fresh elastic rank, shadows
        # server rank BYTEPS_REPLICA_OF via the snapshot delta protocol,
        # and serves CMD_SNAP_PULL reads. Its death costs readers one
        # failover and the training fleet nothing.
        print(f"byteps_tpu.server: starting as read replica of server "
              f"rank {replica_of or 0} (snapshot serving)",
              file=sys.stderr, flush=True)
    from byteps_tpu.core import Replica, Scheduler, Server
    if role == "scheduler":
        node = Scheduler.start()
    elif role == "server":
        node = Server.start()
    elif role == "replica":
        node = Replica.start()
    else:
        raise SystemExit(
            f"DMLC_ROLE must be scheduler|server|replica, got {role!r}")
    # BYTEPS_MONITOR_ON=1 gave this node a /metrics + /healthz endpoint
    # (byteps_tpu.monitor, started inside Node.start); announce it so
    # operators and monitor.top know where to scrape this role.
    if node._monitor is not None:
        print(f"byteps_tpu.server: {role} monitor endpoint on "
              f":{node._monitor.port} (/metrics, /healthz)",
              file=sys.stderr, flush=True)
    # Start() returns once the topology is up; shutdown() blocks until the
    # scheduler broadcasts fleet shutdown (worker goodbyes all received).
    node.shutdown()
    if recover_rank and role == "server":
        # A replacement incarnation ran a recovery: none of the
        # automatic flight-dump triggers (EPOCH_PAUSE/RESUME land on the
        # OTHER ranks) fire here, so leave the re-seed trail — parked
        # ops, RESEEDs, grace events — at clean exit (ISSUE 5).
        try:
            node.dump_flight()
        except Exception:
            pass
    # A FAILURE-triggered shutdown (dead-node broadcast / lost scheduler
    # connection) exits nonzero so a supervisor can tell crash from
    # completion. The scheduler itself stays 0 — detecting and
    # broadcasting a failure IS its job done correctly (and the restart
    # loop keys off the workers' exit codes).
    if role == "server" and node.failure_shutdown():
        print("byteps_tpu.server: failure shutdown (a node died); "
              "exiting nonzero", file=sys.stderr, flush=True)
        raise SystemExit(2)
