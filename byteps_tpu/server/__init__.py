"""Parameter-server / scheduler process entry.

Capability parity: reference byteps/server/__init__.py (SURVEY.md §2.3) —
there, ``import byteps.server`` blocks in the server loop as an import
side-effect. We keep the same capability behind an explicit entry point
(``python -m byteps_tpu.server``; role from DMLC_ROLE) — import
side-effects that block are hostile to tooling, so main() is a function.
"""

from __future__ import annotations

import os


def main() -> None:
    role = os.environ.get("DMLC_ROLE", "server").lower()
    from byteps_tpu.core import Scheduler, Server
    if role == "scheduler":
        node = Scheduler.start()
    elif role == "server":
        node = Server.start()
    else:
        raise SystemExit(f"DMLC_ROLE must be scheduler|server, got {role!r}")
    # Start() returns once the topology is up; shutdown() blocks until the
    # scheduler broadcasts fleet shutdown (worker goodbyes all received).
    node.shutdown()
