from byteps_tpu.server import main

if __name__ == "__main__":
    main()
