"""TPU kernel library (Pallas) for the hot ops.

The reference's native performance layer is C++/NCCL (SURVEY.md §2.1); on
TPU the equivalent "hand-tuned hot path" lives in Pallas kernels that feed
the MXU and keep working sets in VMEM.
"""

from byteps_tpu.ops.flash_attention import flash_attention  # noqa: F401
