"""Pallas TPU flash attention (forward kernel + training VJP).

Blockwise attention with online softmax: Q blocks in VMEM, the kernel
streams K/V blocks and keeps only O(block) state — never materialising the
[S, S] score matrix in HBM. Block matmuls hit the MXU at the (128, 128)
tile shape; masking (causal / key padding) is computed on the VPU with
broadcasted iota. Per /opt/skills/guides/pallas_guide.md patterns: grid
iterates (batch*heads, q_block, k_block) with the k_block dimension
innermost so VMEM scratch carries the running (m, l, acc) across K steps.

Layout contract matches byteps_tpu.parallel attention: [batch, seq, heads,
head_dim]; any dtype (bf16 hot path), f32 accumulation.

The backward pass is a pair of Pallas kernels (dQ, and dK/dV) doing the
standard flash-attention blockwise recompute from the forward's saved
(q, k, v, o, logsumexp) — O(seq) memory end to end, measured ~1.4x the
XLA-recompute VJP at seq 4k on v5e and the only way 32k-token training
fits HBM. Off-TPU the kernels run in interpret mode, so tests exercise
the real kernel code paths on CPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard for safety
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
               *, scale: float, causal: bool, block_q: int, block_k: int,
               seq_k: int, window: Optional[int] = None,
               nk_total: Optional[int] = None):
    # lse_ref is None for inference-only calls (no residual output).
    # nk_total set => restricted-window grid: the third grid dim walks only
    # the ~window/block_k live k blocks per q block (see _window_kv_index).
    """One (bh, qi, ki) grid step of blockwise attention."""
    ki = pl.program_id(2)
    qi = pl.program_id(1)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    if nk_total is None:
        k_start = ki * block_k
    else:
        # real (unclamped) k block this step serves; duplicates from the
        # index-map clamp are skipped via the k_idx bound below
        k_idx = _window_start_block(q_start, window, block_k) + ki
        k_start = k_idx * block_k

    def _compute():
        q = q_ref[0]                       # [block_q, d]
        k = k_ref[0]                       # [block_k, d]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]

        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < seq_k               # key padding
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, q_pos >= k_pos)
            if window is not None:
                # sliding window: attend to the last `window` positions
                mask = jnp.logical_and(mask, q_pos - k_pos < window)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, 0:1]             # [bq, 1]
        m_cur = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_cur)
        corr = jnp.exp(m_prev - m_cur)     # [bq, 1]
        # m/l live in 128-lane scratch rows (VMEM tiling); lane 0 is the
        # value, writes broadcast across lanes.
        l_new = l_ref[:, 0:1] * corr + p.sum(axis=-1, keepdims=True)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
        m_ref[:] = jnp.broadcast_to(m_cur, m_ref.shape)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv

    if causal:
        # k_start/q_start are traced (program_id); predicate at runtime.
        live = k_start <= q_start + block_q - 1
        if window is not None:
            # skip blocks entirely left of every query's window
            live = jnp.logical_and(
                live, k_start + block_k - 1 >= q_start - (window - 1))
        if nk_total is not None:
            live = jnp.logical_and(live, k_start < nk_total * block_k)

        @pl.when(live)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finish():
        # Fully-masked rows (query padding) have l == 0; guard the divide.
        l = l_ref[:, 0:1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        # logsumexp per row (scaled-score space) for the backward pass;
        # +LARGE for empty rows so exp(s - lse) underflows to exactly 0.
        if lse_ref is not None:
            lse = jnp.where(l == 0.0, _NEG_INF * -1.0,
                            m_ref[:, 0:1] + jnp.log(safe_l))
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _window_start_block(q_start, window, block_k):
    """First k block that can be inside [q_start - window + 1, ...]."""
    return jnp.maximum((q_start - (window - 1)) // block_k, 0)


def _window_live_blocks(window: int, block_q: int, block_k: int,
                        nk: int) -> int:
    """Static count of k blocks a q block can touch under the window."""
    span = window + block_q - 1
    return min(nk, span // block_k + 2)


def _pad_to(x, multiple: int, axis: int):
    n = x.shape[axis]
    pad = (-n) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """Flash attention over [batch, seq, heads, head_dim] arrays.

    Default blocks (512, 1024) measured fastest on v5e at seq 2k-8k
    (~1.6x over XLA's fused attention; 128x128 was slower than XLA).
    Blocks clamp to the sequence length for short inputs.

    ``window`` (requires ``causal``) restricts each query to the last
    ``window`` positions — Mistral-style sliding-window attention; blocks
    left of every query's window are skipped entirely, so compute scales
    with ``seq * window`` instead of ``seq^2 / 2``.

    Exact softmax attention, O(seq) memory. ``interpret=None`` auto-selects
    interpret mode off-TPU (tests run the same kernel on CPU). Drop-in for
    ``byteps_tpu.parallel.full_attention``, including as the inner kernel
    of ``ulysses_attention(attn_fn=...)``.
    """
    if window is not None and not causal:
        raise ValueError("window requires causal=True (sliding-window "
                         "attention is a causal scheme)")
    return _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k,
                           interpret, window=window)


def _to_bhsd(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _from_bhsd(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k, interpret,
                    return_lse: bool = False,
                    window: Optional[int] = None):
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    bq = min(block_q, max(s_q, 8))
    bk = min(block_k, max(s_k, 8))

    qq = _pad_to(_to_bhsd(q), bq, axis=1)
    kk = _pad_to(_to_bhsd(k), bk, axis=1)
    vv = _pad_to(_to_bhsd(v), bk, axis=1)
    sq_p, sk_p = qq.shape[1], kk.shape[1]

    nk = sk_p // bk
    if window is not None:
        # visit only the live k blocks per q block: grid work (and the
        # BlockSpec K/V prefetches) scale with seq*window, not seq^2
        nkg = _window_live_blocks(window, bq, bk, nk)

        def kv_index(bh, qi, ki):
            return (bh,
                    jnp.clip(_window_start_block(qi * bq, window, bk) + ki,
                             0, nk - 1), 0)
    else:
        nkg = nk

        def kv_index(bh, qi, ki):
            return (bh, ki, 0)

    grid = (b * h, sq_p // bq, nkg)
    scratch = [
        _VMEM((bq, 128), jnp.float32),  # m (value in lane 0)
        _VMEM((bq, 128), jnp.float32),  # l (value in lane 0)
        _VMEM((bq, d), jnp.float32),    # acc
    ]
    vmem = pl.BlockSpec
    in_specs = [
        vmem((1, bq, d), lambda bh, qi, ki: (bh, qi, 0),
             memory_space=_VMEM),
        vmem((1, bk, d), kv_index, memory_space=_VMEM),
        vmem((1, bk, d), kv_index, memory_space=_VMEM),
    ]
    o_spec = vmem((1, bq, d), lambda bh, qi, ki: (bh, qi, 0),
                  memory_space=_VMEM)
    o_shape = jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype)
    common = dict(scale=scale, causal=causal, block_q=bq, block_k=bk,
                  seq_k=s_k, window=window,
                  nk_total=nk if window is not None else None)
    if return_lse:
        out, lse = pl.pallas_call(
            functools.partial(_fa_kernel, **common),
            grid=grid,
            in_specs=in_specs,
            out_specs=[
                o_spec,
                # lane dim 8 (not 128): the smallest layout-legal tile —
                # the kernels only read one value per row
                vmem((1, bq, 8), lambda bh, qi, ki: (bh, qi, 0),
                     memory_space=_VMEM),
            ],
            out_shape=[
                o_shape,
                jax.ShapeDtypeStruct((b * h, sq_p, 8), jnp.float32),
            ],
            scratch_shapes=scratch,
            interpret=interpret,
        )(qq, kk, vv)
        return _from_bhsd(out[:, :s_q], b, h), lse  # padded [bh, sq_p, 8]

    def _kernel_nolse(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref):
        _fa_kernel(q_ref, k_ref, v_ref, o_ref, None, m_ref, l_ref,
                   acc_ref, **common)

    out = pl.pallas_call(
        _kernel_nolse,
        grid=grid,
        in_specs=in_specs,
        out_specs=o_spec,
        out_shape=o_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(qq, kk, vv)
    return _from_bhsd(out[:, :s_q], b, h)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               window):
    out, lse = _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k,
                               interpret, return_lse=True, window=window)
    return out, (q, k, v, out, lse)


# Backward blocks are fixed smaller than the forward's: the bwd kernels
# hold more live [bq, bk] f32 temporaries (p, dp, ds) in VMEM.
_BWD_BQ = 256
_BWD_BK = 512


def _bwd_mask(q_start, k_start, bq, bk, seq_q, seq_k, causal, window):
    q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = jnp.logical_and(q_pos < seq_q, k_pos < seq_k)
    if causal:
        mask = jnp.logical_and(mask, q_pos >= k_pos)
        if window is not None:
            mask = jnp.logical_and(mask, q_pos - k_pos < window)
    return mask


def _bwd_live(q_start, k_start, bq, bk, causal, window):
    """Block-level skip predicate shared by both backward kernels."""
    if not causal:
        return None
    live = q_start + bq - 1 >= k_start
    if window is not None:
        live = jnp.logical_and(live,
                               k_start + bk - 1 >= q_start - (window - 1))
    return live


def _bwd_recompute(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
                   q_start, k_start, *, scale, causal, block_q, block_k,
                   seq_q, seq_k, window=None):
    """Shared dq/dkv block recompute: returns (p, ds, do_f32). The one
    place the score/probability/ds math lives, so the two backward
    kernels cannot silently diverge."""
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, 0:1]
    dd = dd_ref[0][:, 0:1]
    sc = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    mask = _bwd_mask(q_start, k_start, block_q, block_k, seq_q, seq_k,
                     causal, window)
    p = jnp.where(mask, jnp.exp(sc - lse), 0.0)
    dp = jax.lax.dot_general(
        do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - dd) * scale
    return p, ds, do


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, dq_ref,
                      dq_acc, *, scale, causal, block_q, block_k,
                      seq_q, seq_k, window=None):
    """dQ = scale * sum_k [p * (dO V^T - D)] K; grid (bh, qi, ki)."""
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = qi * block_q
    k_start = ki * block_k

    def _compute():
        _, ds, _ = _bwd_recompute(
            q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, q_start, k_start,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            seq_q=seq_q, seq_k=seq_k, window=window)
        k = k_ref[0]
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = _bwd_live(q_start, k_start, block_q, block_k, causal, window)
    if live is None:
        _compute()
    else:
        @pl.when(live)
        def _():
            _compute()

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref,
                       dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                       block_q, block_k, seq_q, seq_k, window=None):
    """dK = scale * sum_q ds^T Q;  dV = sum_q p^T dO; grid (bh, ki, qi)."""
    ki, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = ki * block_k

    def _compute():
        p, ds, do = _bwd_recompute(
            q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, q_start, k_start,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            seq_q=seq_q, seq_k=seq_k, window=window)
        q = q_ref[0]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = _bwd_live(q_start, k_start, block_q, block_k, causal, window)
    if live is None:
        _compute()
    else:
        @pl.when(live)
        def _():
            _compute()

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(causal, scale, block_q, block_k, interpret, window, res,
               g):
    """Pallas backward: blockwise recompute from (q, k, v, o, lse) — the
    standard flash-attention backward, O(seq) memory like the forward."""
    q, k, v, out, lse = res
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    bq = min(_BWD_BQ, max(s_q, 8))
    bk = min(_BWD_BK, max(s_k, 8))

    qq = _pad_to(_to_bhsd(q), bq, axis=1)
    kk = _pad_to(_to_bhsd(k), bk, axis=1)
    vv = _pad_to(_to_bhsd(v), bk, axis=1)
    dd_o = _pad_to(_to_bhsd(g.astype(q.dtype)), bq, axis=1)
    sq_p, sk_p = qq.shape[1], kk.shape[1]

    # D_i = rowsum(dO * O), f32, one value per row in the 8-lane tile.
    dvec = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                   axis=-1)                                  # [b, s, h]
    dvec = dvec.transpose(0, 2, 1).reshape(b * h, s_q)
    dd = jnp.broadcast_to(_pad_to(dvec, bq, axis=1)[:, :, None],
                          (b * h, sq_p, 8))

    # the forward's lse is padded with the FORWARD's bq; re-pad for bwd
    lse = _pad_to(lse[:, :s_q], bq, axis=1)

    vmem = pl.BlockSpec
    kw = dict(scale=scale, causal=causal, block_q=bq, block_k=bk,
              seq_q=s_q, seq_k=s_k, window=window)

    dq = pl.pallas_call(
        functools.partial(_fa_bwd_dq_kernel, **kw),
        grid=(b * h, sq_p // bq, sk_p // bk),
        in_specs=[
            vmem((1, bq, d), lambda bh, qi, ki: (bh, qi, 0),
                 memory_space=_VMEM),
            vmem((1, bk, d), lambda bh, qi, ki: (bh, ki, 0),
                 memory_space=_VMEM),
            vmem((1, bk, d), lambda bh, qi, ki: (bh, ki, 0),
                 memory_space=_VMEM),
            vmem((1, bq, d), lambda bh, qi, ki: (bh, qi, 0),
                 memory_space=_VMEM),
            vmem((1, bq, 8), lambda bh, qi, ki: (bh, qi, 0),
                 memory_space=_VMEM),
            vmem((1, bq, 8), lambda bh, qi, ki: (bh, qi, 0),
                 memory_space=_VMEM),
        ],
        out_specs=vmem((1, bq, d), lambda bh, qi, ki: (bh, qi, 0),
                       memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
        scratch_shapes=[_VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qq, kk, vv, dd_o, lse, dd)

    dk, dv = pl.pallas_call(
        functools.partial(_fa_bwd_dkv_kernel, **kw),
        grid=(b * h, sk_p // bk, sq_p // bq),
        in_specs=[
            vmem((1, bq, d), lambda bh, ki, qi: (bh, qi, 0),
                 memory_space=_VMEM),
            vmem((1, bk, d), lambda bh, ki, qi: (bh, ki, 0),
                 memory_space=_VMEM),
            vmem((1, bk, d), lambda bh, ki, qi: (bh, ki, 0),
                 memory_space=_VMEM),
            vmem((1, bq, d), lambda bh, ki, qi: (bh, qi, 0),
                 memory_space=_VMEM),
            vmem((1, bq, 8), lambda bh, ki, qi: (bh, qi, 0),
                 memory_space=_VMEM),
            vmem((1, bq, 8), lambda bh, ki, qi: (bh, qi, 0),
                 memory_space=_VMEM),
        ],
        out_specs=[
            vmem((1, bk, d), lambda bh, ki, qi: (bh, ki, 0),
                 memory_space=_VMEM),
            vmem((1, bk, d), lambda bh, ki, qi: (bh, ki, 0),
                 memory_space=_VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk_p, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk_p, d), v.dtype),
        ],
        scratch_shapes=[_VMEM((bk, d), jnp.float32),
                        _VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(qq, kk, vv, dd_o, lse, dd)

    dq = _from_bhsd(dq[:, :s_q], b, h)
    dk = _from_bhsd(dk[:, :s_k], b, h)
    dv = _from_bhsd(dv[:, :s_k], b, h)
    return dq, dk, dv


flash_attention.defvjp(_flash_fwd, _flash_bwd)
