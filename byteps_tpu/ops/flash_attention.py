"""Pallas TPU flash attention (forward kernel + training VJP).

Blockwise attention with online softmax: Q blocks in VMEM, the kernel
streams K/V blocks and keeps only O(block) state — never materialising the
[S, S] score matrix in HBM. Block matmuls hit the MXU at the (128, 128)
tile shape; masking (causal / key padding) is computed on the VPU with
broadcasted iota. Per /opt/skills/guides/pallas_guide.md patterns: grid
iterates (batch*heads, q_block, k_block) with the k_block dimension
innermost so VMEM scratch carries the running (m, l, acc) across K steps.

Layout contract matches byteps_tpu.parallel attention: [batch, seq, heads,
head_dim]; any dtype (bf16 hot path), f32 accumulation.

The backward pass is a custom VJP that recomputes attention with the
XLA reference implementation (exact same math, compiler-fused); a Pallas
backward kernel is a later optimisation, the VJP boundary already makes
the forward kernel trainable. Off-TPU the kernel runs in interpret mode,
so tests exercise the real kernel code path on CPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard for safety
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
               scale: float, causal: bool, block_q: int, block_k: int,
               seq_k: int):
    """One (bh, qi, ki) grid step of blockwise attention."""
    ki = pl.program_id(2)
    qi = pl.program_id(1)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k

    def _compute():
        q = q_ref[0]                       # [block_q, d]
        k = k_ref[0]                       # [block_k, d]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]

        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < seq_k               # key padding
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, 0:1]             # [bq, 1]
        m_cur = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_cur)
        corr = jnp.exp(m_prev - m_cur)     # [bq, 1]
        # m/l live in 128-lane scratch rows (VMEM tiling); lane 0 is the
        # value, writes broadcast across lanes.
        l_new = l_ref[:, 0:1] * corr + p.sum(axis=-1, keepdims=True)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)
        m_ref[:] = jnp.broadcast_to(m_cur, m_ref.shape)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv

    if causal:
        # k_start/q_start are traced (program_id); predicate at runtime.
        @pl.when(k_start <= q_start + block_q - 1)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finish():
        # Fully-masked rows (query padding) have l == 0; guard the divide.
        l = l_ref[:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _pad_to(x, multiple: int, axis: int):
    n = x.shape[axis]
    pad = (-n) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention over [batch, seq, heads, head_dim] arrays.

    Default blocks (512, 1024) measured fastest on v5e at seq 2k-8k
    (~1.6x over XLA's fused attention; 128x128 was slower than XLA).
    Blocks clamp to the sequence length for short inputs.

    Exact softmax attention, O(seq) memory. ``interpret=None`` auto-selects
    interpret mode off-TPU (tests run the same kernel on CPU). Drop-in for
    ``byteps_tpu.parallel.full_attention``, including as the inner kernel
    of ``ulysses_attention(attn_fn=...)``.
    """
    return _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k,
                           interpret)


def _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k, interpret):
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    bq = min(block_q, max(s_q, 8))
    bk = min(block_k, max(s_k, 8))

    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    qq = _pad_to(to_bhsd(q), bq, axis=1)
    kk = _pad_to(to_bhsd(k), bk, axis=1)
    vv = _pad_to(to_bhsd(v), bk, axis=1)
    sq_p, sk_p = qq.shape[1], kk.shape[1]

    grid = (b * h, sq_p // bq, sk_p // bk)
    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        seq_k=s_k)
    scratch = [
        _VMEM((bq, 128), jnp.float32),  # m (value in lane 0)
        _VMEM((bq, 128), jnp.float32),  # l (value in lane 0)
        _VMEM((bq, d), jnp.float32),    # acc
    ]
    vmem = pl.BlockSpec
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            vmem((1, bq, d), lambda bh, qi, ki: (bh, qi, 0),
                 memory_space=_VMEM),
            vmem((1, bk, d), lambda bh, qi, ki: (bh, ki, 0),
                 memory_space=_VMEM),
            vmem((1, bk, d), lambda bh, qi, ki: (bh, ki, 0),
                 memory_space=_VMEM),
        ],
        out_specs=vmem((1, bq, d), lambda bh, qi, ki: (bh, qi, 0),
                       memory_space=_VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
        scratch_shapes=scratch,
        interpret=interpret,
    )(qq, kk, vv)
    out = out[:, :s_q].reshape(b, h, s_q, d).transpose(0, 2, 1, 3)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_fwd_impl(q, k, v, causal, scale, block_q, block_k,
                          interpret)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    """Backward via XLA recompute of the exact same attention math."""
    from byteps_tpu.parallel.ring_attention import full_attention

    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: full_attention(q_, k_, v_, causal=causal,
                                          scale=scale), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)
