"""One-command incident reports from the fleet event journal (ISSUE 20).

The scheduler's journal (``/events`` on its monitor endpoint, or the
``bps_events_summary`` probe) already holds everything a post-mortem
opens with: the clock-aligned fleet timeline of lifecycle events
(pauses, deaths, recoveries, scheduler fail-over, checkpoint seals,
CRC quarantines, ...), plus bounded history rings sampled from every
registered gauge. This module turns one journal snapshot — live-scraped
or saved to a file — into a readable report, and stitches in the
flight-recorder dumps (ISSUE 5) each crisis left behind, matched by
role/node and overlapped against the same scheduler timebase.

Usage::

    python -m byteps_tpu.monitor.incident --url http://host:9100
    python -m byteps_tpu.monitor.incident --file events.json \
        --dir traces/ --window-s 120
    python -m byteps_tpu.monitor.incident --file events.json --json

``--window-s N`` keeps the LAST N seconds of the timeline (measured
back from its newest event); ``--since-us`` / ``--until-us`` pin an
explicit aligned-timestamp window instead. The same functions are
importable for tests and tooling: ``load_events`` / ``stitch_flights``
/ ``build_report`` / ``render_report``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request
from typing import List, Optional

from byteps_tpu.monitor import timeline as _timeline

# Event types whose presence makes a window an "incident" rather than
# routine churn — the report leads with these (csrc/events.h catalog).
_SEVERE = {
    "epoch_pause", "fleet_pause", "death", "sched_park", "shutdown",
    "crc_quarantine", "crc_failstop", "ckpt_restore", "replica_lag",
    "tenant_starved",
}

# ...and the ones that close an episode the severe set opened.
_RESOLVING = {
    "epoch_resume", "fleet_resume", "server_recover",
    "sched_recovery_commit", "join",
}


def load_events(url: Optional[str] = None,
                file: Optional[str] = None,
                timeout: float = 5.0) -> dict:
    """One journal snapshot: scrape ``<url>/events`` or read a saved
    JSON file; with neither, probe the in-process journal (the FFI
    path — useful from tests and notebooks living inside a rank)."""
    if url:
        full = url.rstrip("/") + "/events"
        with urllib.request.urlopen(full, timeout=timeout) as r:
            return json.loads(r.read().decode())
    if file:
        with open(file) as f:
            return json.load(f)
    from byteps_tpu.core.ffi import events_summary
    return events_summary()


def _window(journal: dict, since_us: Optional[int],
            until_us: Optional[int],
            window_s: Optional[float]) -> tuple:
    """Resolve the report's [since, until] aligned-timestamp window.
    The scheduler's own events are already on its timebase; a rank's
    local ring (no ingest) serves as the timeline fallback so the
    command still works pointed at a worker."""
    evs = journal.get("timeline") or journal.get("events") or []
    ts = [e["ts_us"] for e in evs if "ts_us" in e]
    lo = min(ts) if ts else 0
    hi = max(ts) if ts else 0
    if window_s is not None:
        lo = hi - int(window_s * 1e6)
    if since_us is not None:
        lo = since_us
    if until_us is not None:
        hi = until_us
    return lo, hi


def stitch_flights(trace_dir: str,
                   pattern: str = "flight_*.json") -> List[dict]:
    """Summarise every flight-recorder dump under ``trace_dir`` on the
    scheduler timebase: who dumped (role/node/incarnation), why
    (``meta.reason`` — the FlightDumpAuto trigger), and the aligned
    time span its ring covers, so the report can point each journal
    event at the dump that holds its microscale evidence."""
    out = []
    for d in _timeline.gather(trace_dir, pattern):
        meta = d.get("meta", {})
        offset = int(meta.get("clock_offset_us", 0) or 0)
        ts = [e["ts"] + offset for e in d.get("traceEvents", [])
              if "ts" in e]
        out.append({
            "path": meta.get("path", ""),
            "role": meta.get("role", -1),
            "node_id": meta.get("node_id", -1),
            "incarnation": _timeline._incarnation(meta),
            "label": _timeline._rank_label(meta),
            "reason": meta.get("reason", ""),
            "events": len(ts),
            "dropped": meta.get("dropped", 0),
            "first_ts_us": min(ts) if ts else -1,
            "last_ts_us": max(ts) if ts else -1,
        })
    out.sort(key=lambda f: (f["first_ts_us"], f["node_id"]))
    return out


def build_report(journal: dict,
                 flights: Optional[List[dict]] = None,
                 since_us: Optional[int] = None,
                 until_us: Optional[int] = None,
                 window_s: Optional[float] = None) -> dict:
    """Assemble the incident document: the in-window slice of the
    fleet timeline (falling back to the local ring off-scheduler),
    in-window metric history, per-type counts, and — when flight dumps
    were stitched in — each dump matched against the window."""
    lo, hi = _window(journal, since_us, until_us, window_s)
    evs = journal.get("timeline") or journal.get("events") or []
    inwin = [e for e in evs if lo <= e.get("ts_us", 0) <= hi]
    counts: dict = {}
    for e in inwin:
        counts[e.get("name", "?")] = counts.get(e.get("name", "?"), 0) + 1
    history = {}
    for name, samples in (journal.get("history") or {}).items():
        kept = [s for s in samples if lo <= s[0] <= hi]
        if kept:
            history[name] = {
                "samples": len(kept),
                "first": kept[0][1], "last": kept[-1][1],
                "min": min(s[1] for s in kept),
                "max": max(s[1] for s in kept),
            }
    matched = []
    for fl in flights or []:
        fl = dict(fl)
        # A dump "covers" the window when its ring span overlaps it —
        # empty dumps (or never-aligned rings) are kept but flagged, so
        # a rank that died before its clock exchange still shows up.
        fl["in_window"] = (fl["events"] > 0 and fl["last_ts_us"] >= lo
                           and fl["first_ts_us"] <= hi)
        matched.append(fl)
    return {
        "source": {
            "role": journal.get("role", -1),
            "node_id": journal.get("node_id", -1),
            "on": journal.get("on", False),
            "scheduler": bool(journal.get("timeline")),
            "emitted_total": journal.get("emitted_total", 0),
            "ingested_total": journal.get("ingested_total", 0),
            "dropped": journal.get("dropped", 0),
            "timeline_dropped": journal.get("timeline_dropped", 0),
        },
        "window_us": [lo, hi],
        "events": inwin,
        "counts": counts,
        "severe": sorted(k for k in counts if k in _SEVERE),
        "resolved": sorted(k for k in counts if k in _RESOLVING),
        "history": history,
        "flights": matched,
    }


_ROLE = {0: "sched", 1: "server", 2: "worker"}


def _fmt_ev(e: dict, t0: int) -> str:
    dt = (e.get("ts_us", 0) - t0) / 1e6
    who = f"{_ROLE.get(e.get('role', -1), '?')}/n{e.get('node', -1)}"
    args = ",".join(str(e.get(k, 0)) for k in ("a0", "a1", "a2"))
    return (f"  +{dt:10.3f}s  {e.get('name', '?'):<22} {who:<12} "
            f"args=[{args}]")


def render_report(report: dict, file=None) -> None:
    """Human-readable post-mortem: verdict line, ordered timeline,
    metric history extremes, and the flight dumps to open next."""
    out = file or sys.stdout
    src = report["source"]
    lo, hi = report["window_us"]
    span = max(0, hi - lo) / 1e6
    where = "scheduler journal" if src["scheduler"] else (
        f"local ring ({_ROLE.get(src['role'], '?')}/n{src['node_id']})")
    print(f"incident report — {where}, {len(report['events'])} "
          f"event(s) over {span:.1f}s", file=out)
    if report["severe"]:
        closing = (f"; resolved by: {', '.join(report['resolved'])}"
                   if report["resolved"] else "; NOT resolved in window")
        print(f"  severe: {', '.join(report['severe'])}{closing}",
              file=out)
    elif report["events"]:
        print("  no severe lifecycle events in window (routine churn)",
              file=out)
    else:
        print("  journal empty in window — widen it (--window-s) or "
              "point --url/--file at the scheduler", file=out)
    lost = src["dropped"] + src["timeline_dropped"]
    if lost:
        print(f"  WARNING: {lost} event(s) dropped before this "
              "snapshot (raise BYTEPS_EVENTS_RING)", file=out)
    print("timeline (scheduler timebase):", file=out)
    for e in report["events"]:
        print(_fmt_ev(e, lo), file=out)
    if report["history"]:
        print("metric history (in-window):", file=out)
        for name, h in sorted(report["history"].items()):
            print(f"  {name:<34} first={h['first']} last={h['last']} "
                  f"min={h['min']} max={h['max']} "
                  f"({h['samples']} samples)", file=out)
    if report["flights"]:
        print("flight-recorder dumps:", file=out)
        for fl in report["flights"]:
            flag = "in-window" if fl["in_window"] else "outside window"
            why = f" reason={fl['reason']}" if fl["reason"] else ""
            print(f"  {fl['label']}: {fl['path']} "
                  f"({fl['events']} events, {flag}){why}", file=out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m byteps_tpu.monitor.incident",
        description="render a post-mortem report from the fleet event "
                    "journal (docs/monitoring.md, "
                    "docs/troubleshooting.md)")
    p.add_argument("--url", default="",
                   help="monitor endpoint base URL (scrapes <url>/"
                        "events); point it at the SCHEDULER for the "
                        "fleet timeline")
    p.add_argument("--file", default="",
                   help="saved /events (or bps_events_summary) JSON")
    p.add_argument("--dir", default=os.environ.get("BYTEPS_TRACE_DIR")
                   or os.environ.get("BPS_TRACE_OUT") or "",
                   help="trace directory to stitch flight dumps from "
                        "(default: BYTEPS_TRACE_DIR; '' = skip)")
    p.add_argument("--window-s", type=float, default=None,
                   help="keep only the last N seconds of the timeline")
    p.add_argument("--since-us", type=int, default=None,
                   help="window start (aligned us)")
    p.add_argument("--until-us", type=int, default=None,
                   help="window end (aligned us)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report (one JSON line)")
    args = p.parse_args(argv)

    try:
        journal = load_events(url=args.url or None,
                              file=args.file or None)
    except Exception as e:
        print(f"cannot load journal: {e}", file=sys.stderr)
        return 1
    flights = stitch_flights(args.dir) if args.dir else []
    report = build_report(journal, flights, since_us=args.since_us,
                          until_us=args.until_us,
                          window_s=args.window_s)
    if args.json:
        print(json.dumps(report))
    else:
        render_report(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
