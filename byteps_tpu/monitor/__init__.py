"""byteps_tpu.monitor — live metrics, health, and straggler detection.

New scope (no reference equivalent): the reference's only runtime
observability is the post-hoc Chrome-trace timeline (``BYTEPS_TRACE_*``,
docs/timeline.md). This package is the *live* counterpart — the signal
you need while the job runs to tune partition size, credits, and
compression, and to spot sick nodes before they stall the fleet:

- ``metrics``  — snapshot of the C core's lock-free metric registry
  (per-stage counters/gauges/latency histograms + van wire bytes, async
  staleness, queue occupancy, scheduler heartbeat ages) plus a small
  Python-side registry for step-level metrics, and Prometheus text
  exposition over both.
- ``http``     — per-role background HTTP endpoint (``/metrics``,
  ``/healthz``), started automatically by every node when
  ``BYTEPS_MONITOR_ON=1`` on ``BYTEPS_MONITOR_PORT + node_id``.
- ``top``      — ``python -m byteps_tpu.monitor.top``: scrape every role
  endpoint, compute per-worker push-latency skew, flag stragglers and
  dead/stale heartbeats.
- ``insight``  — ``python -m byteps_tpu.monitor.insight``: live
  per-round bottleneck attribution from the scheduler's fleet round
  table (``/rounds``): names the dominant stage, classifies the fleet
  state (wire-bound / sum-bound / straggler-skewed / retry-degraded /
  healthy), flags EWMA regressions, and emits advisory tuning hints.

See docs/monitoring.md for the endpoint layout, metric catalog, and
straggler thresholds.
"""

from byteps_tpu.monitor.metrics import (  # noqa: F401
    inc_counter,
    observe_histo,
    parse_prometheus,
    prometheus_text,
    set_gauge,
    snapshot,
)
from byteps_tpu.monitor.http import (  # noqa: F401
    MonitorServer,
    maybe_start_monitor,
)
