"""Fleet scraper + straggler/health detector.

``python -m byteps_tpu.monitor.top`` polls every role's monitor endpoint
(derived from the topology env — DMLC_NUM_WORKER / DMLC_NUM_SERVER /
BYTEPS_MONITOR_PORT — or given explicitly with ``--endpoints``) and
reports, per worker: push throughput, wire bytes, queue occupancy, and
mean push latency; fleet-wide: heartbeat freshness and dead nodes.

Straggler rule (docs/monitoring.md): a worker is flagged when its mean
push latency exceeds ``BYTEPS_STRAGGLER_FACTOR`` (default 2.0) times the
fleet's LOW-median of worker means, and is above an absolute 1 ms floor.
The low-median (lower of the two middle values) keeps the baseline
anchored to the healthy majority even in 2-worker fleets, where a plain
median would average the straggler in. Heartbeat health comes from the
scheduler endpoint: an age past PS_HEARTBEAT_TIMEOUT is stale; ids in
``bps_dead_nodes`` are already declared dead.

The launcher and later fault-tolerance PRs consume the same ``analyze``
output programmatically.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

DEFAULT_TIMEOUT_S = 2.0


def fleet_endpoints(host: str, base_port: int, num_workers: int,
                    num_servers: int, num_replicas: int = 0
                    ) -> Dict[str, str]:
    """role-name -> host:port for every node, from the postoffice id
    layout (scheduler 0, servers 1..S, workers S+1..S+W). Read replicas
    (ISSUE 16) draw fresh ranks from the same elastic allocator workers
    use, so a fleet launched with replicas has them at S+W+1..S+W+R —
    valid as long as no elastic joins raced the replica registrations
    (use --endpoints for exotic topologies)."""
    eps = {"scheduler": f"{host}:{base_port}"}
    for s in range(num_servers):
        eps[f"server{s}"] = f"{host}:{base_port + 1 + s}"
    for w in range(num_workers):
        eps[f"worker{w}"] = f"{host}:{base_port + 1 + num_servers + w}"
    for r in range(num_replicas):
        eps[f"replica{r}"] = (
            f"{host}:{base_port + 1 + num_servers + num_workers + r}")
    return eps


def scrape(endpoint: str, timeout: float = DEFAULT_TIMEOUT_S
           ) -> Optional[dict]:
    """Fetch + parse one endpoint's /metrics; None when unreachable."""
    from byteps_tpu.monitor.metrics import parse_prometheus
    try:
        with urllib.request.urlopen(f"http://{endpoint}/metrics",
                                    timeout=timeout) as r:
            return parse_prometheus(r.read().decode())
    except (OSError, ValueError):
        return None


def scrape_events(endpoint: str, since_us: int = 0, limit: int = 10,
                  timeout: float = DEFAULT_TIMEOUT_S) -> List[dict]:
    """Tail the fleet event journal (ISSUE 20) from one endpoint's
    /events — newest `limit` timeline entries newer than `since_us`
    (aligned us), so repeated polls render a scrolling ticker instead
    of reprinting history. [] when unreachable or journal off."""
    try:
        with urllib.request.urlopen(f"http://{endpoint}/events",
                                    timeout=timeout) as r:
            doc = json.loads(r.read().decode())
    except (OSError, ValueError):
        return []
    evs = doc.get("timeline") or doc.get("events") or []
    fresh = [e for e in evs if e.get("ts_us", 0) > since_us]
    return fresh[-limit:]


def _sample(metrics: dict, name: str, default: float = 0.0) -> float:
    series = metrics.get(name)
    if not series:
        return default
    return next(iter(series.values()))


def _rank_key(name: str):
    """Numeric rank ordering for role rows: worker10 sorts after
    worker2, not between worker1 and worker2 (stable scripting order
    for --json consumers and the tenant fleet tests)."""
    head = name.rstrip("0123456789")
    tail = name[len(head):]
    return (head, int(tail) if tail else -1)


def _tenant_rows(scrapes: Dict[str, Optional[dict]],
                 starve_ms: float = 2000.0) -> Dict[str, dict]:
    """Per-tenant accounting aggregated across the SERVER scrapes
    (bps_tenant_* labeled series, ISSUE 9) plus worker count / weight
    from any role that carries the address-book roster gauges. A
    tenant is STARVED when any server reports queued work unserved for
    longer than BYTEPS_TENANT_STARVE_MS."""
    rows: Dict[str, dict] = {}

    def row(tid: str) -> dict:
        return rows.setdefault(tid, {
            "push_bytes": 0, "reply_bytes": 0, "ops": 0,
            "queue_depth": 0, "dispatched": 0, "starve_us": 0,
            "workers": 0, "weight": 0, "starved": False,
        })

    for name, m in scrapes.items():
        if m is None:
            continue
        is_server = name.startswith("server")
        for metric, field in (("bps_tenant_push_bytes_total",
                               "push_bytes"),
                              ("bps_tenant_reply_bytes_total",
                               "reply_bytes"),
                              ("bps_tenant_ops_total", "ops"),
                              ("bps_tenant_queue_depth", "queue_depth"),
                              ("bps_tenant_dispatched_total",
                               "dispatched"),
                              ("bps_tenant_starve_us", "starve_us")):
            if not is_server:
                continue  # engine accounting lives on servers
            for labels, v in (m.get(metric) or {}).items():
                tid = dict(labels).get("tenant")
                if tid is None:
                    continue
                r = row(tid)
                if field == "starve_us":
                    r[field] = max(r[field], int(v))
                else:
                    r[field] += int(v)
        for metric, field in (("bps_tenant_workers", "workers"),
                              ("bps_tenant_weight", "weight")):
            for labels, v in (m.get(metric) or {}).items():
                tid = dict(labels).get("tenant")
                if tid is not None:
                    row(tid)[field] = max(row(tid)[field], int(v))
    for r in rows.values():
        r["starved"] = (r["queue_depth"] > 0
                        and r["starve_us"] / 1000.0 > starve_ms)
    return rows


def _replica_rows(scrapes: Dict[str, Optional[dict]],
                  lag_rounds: int = 8) -> Dict[str, dict]:
    """Per-replica snapshot-serving state (ISSUE 16): the committed
    snapshot version this replica serves, how many rounds it trails its
    primary (bps_replica_lag_rounds, stamped from every delta reply),
    and its read traffic. A replica is REPLICA-LAGGING when the lag
    exceeds BYTEPS_REPLICA_LAG_ROUNDS — readers pinned there see stale
    (but still never-torn) cuts."""
    rows: Dict[str, dict] = {}
    for name, m in scrapes.items():
        if not name.startswith("replica") or m is None:
            continue
        lag = int(_sample(m, "bps_replica_lag_rounds"))
        rows[name] = {
            "snapshot_version": int(_sample(m, "bps_snapshot_version",
                                            -1)),
            "lag_rounds": lag,
            "snap_pulls": int(_sample(m, "bps_snap_pulls_total")),
            "lagging": lag > lag_rounds,
        }
    return rows


def _ckpt_rows(scrapes: Dict[str, Optional[dict]],
               lag_warn: int = 8) -> Dict[str, dict]:
    """Per-server durable-checkpoint state (ISSUE 18): the newest sealed
    spill version, how many committed snapshot versions the writer
    trails the training watermark, and spill traffic. A server is
    CKPT-LAGGING when the lag exceeds BYTEPS_CKPT_LAG_WARN — the disk is
    not keeping up, and a full-fleet loss right now costs that many
    rounds of progress. Servers without the writer armed (no
    bps_ckpt_version series) are omitted, so checkpoint-less fleets get
    an unchanged report."""
    rows: Dict[str, dict] = {}
    for name, m in scrapes.items():
        if not name.startswith("server") or m is None:
            continue
        if "bps_ckpt_version" not in m:
            continue
        lag = int(_sample(m, "bps_ckpt_lag_rounds"))
        rows[name] = {
            "ckpt_version": int(_sample(m, "bps_ckpt_version", -1)),
            "lag_rounds": lag,
            "spills": int(_sample(m, "bps_ckpt_spills_total")),
            "failures": int(_sample(m, "bps_ckpt_failures_total")),
            "spill_ms": int(_sample(m, "bps_ckpt_spill_ms")),
            "lagging": lag > lag_warn,
        }
    return rows


def analyze(scrapes: Dict[str, Optional[dict]],
            straggler_factor: float = 2.0,
            heartbeat_timeout_s: float = 30.0) -> dict:
    """Turn per-role scrapes into a health report. ``scrapes`` maps role
    names (workerN / serverN / scheduler) to parsed metrics (None =
    endpoint unreachable)."""
    workers: Dict[str, dict] = {}
    for name, m in scrapes.items():
        if not name.startswith("worker") or m is None:
            continue
        count = _sample(m, "bps_push_us_count")
        workers[name] = {
            "push_mean_us": (_sample(m, "bps_push_us_sum") / count
                             if count else 0.0),
            "push_count": int(count),
            "push_bytes": int(_sample(m, "bps_push_bytes_total")),
            "pull_bytes": int(_sample(m, "bps_pull_bytes_total")),
            "queue_pending": int(_sample(m, "bps_queue_pending")),
            "inflight_bytes": int(_sample(m, "bps_queue_inflight_bytes")),
            "credit_budget_bytes": int(
                _sample(m, "bps_queue_credit_budget_bytes")),
            # Transient-fault telemetry: nonzero means this worker is
            # absorbing faults in-band (resends / re-dialled server
            # connections) — the flag to investigate a link or peer
            # BEFORE the node goes dead.
            "retries": int(_sample(m, "bps_retries_total")),
            "reconnects": int(_sample(m, "bps_reconnects_total")),
            # Wire integrity (ISSUE 19): receive-side frame accounting.
            # gaps/dups come from the per-connection seq cursor; CRC
            # fails are frames dropped on a checksum mismatch;
            # quarantines are flaky-link force-re-dials; corrupting is
            # the persistently-corrupting-link flag that precedes the
            # named fail-stop.
            "seq_gaps": int(_sample(m, "bps_seq_gaps_total")),
            "seq_dups": int(_sample(m, "bps_seq_dups_total")),
            "crc_fails": int(_sample(m, "bps_crc_fail_total")),
            "crc_quarantines": int(
                _sample(m, "bps_crc_quarantine_total")),
            "corrupting": bool(_sample(m, "bps_link_corrupting")),
            # Hot-replacement telemetry: server recoveries this worker
            # re-seeded, and whether one is in progress right now.
            "recoveries": int(_sample(m, "bps_recoveries_total")),
            "recovering": bool(_sample(m, "bps_recovering")),
            # Scheduler fail-over (ISSUE 15): 1 while this worker is
            # PARKED on a lost scheduler (data plane still draining,
            # control plane frozen, re-dialling the endpoint).
            "sched_lost": bool(_sample(m, "bps_sched_lost")),
            "sched_recoveries": int(
                _sample(m, "bps_sched_recoveries_total")),
            # Trace health (ISSUE 5): drop-oldest overwrites in the main
            # trace ring mean the timeline is missing events — raise
            # BYTEPS_TRACE_RING_EVENTS or narrow the step window.
            "trace_dropped": int(_sample(m, "bps_trace_dropped_total")),
            "flight_dumps": int(_sample(m, "bps_flight_dumps_total")),
            # Quantized wire (ISSUE 6): encoded bytes that crossed the
            # wire and raw-minus-encoded savings, both legs. The
            # compression ratio column is (wire + saved) / wire.
            "quant_wire_bytes": int(
                _sample(m, "bps_quant_bytes_on_wire_total")),
            "quant_saved_bytes": int(
                _sample(m, "bps_quant_bytes_saved_total")),
        }
        qw = workers[name]["quant_wire_bytes"]
        qs = workers[name]["quant_saved_bytes"]
        workers[name]["quant_ratio"] = (
            round((qw + qs) / qw, 2) if qw > 0 else 1.0)
        # Last completed round's stage breakdown (ISSUE 7): the
        # BOTTLENECK column + fleet-state header come from these
        # gauges through the same insight classifier the /rounds
        # watcher uses.
        rec = {
            "round": int(_sample(m, "bps_round_last", -1)),
            "parts": int(_sample(m, "bps_round_parts")),
            "queue_us": _sample(m, "bps_round_queue_us"),
            "comp_us": _sample(m, "bps_round_comp_us"),
            "push_us": _sample(m, "bps_round_push_us"),
            "sum_us": _sample(m, "bps_round_sum_us"),
            "wire_ack_us": _sample(m, "bps_round_wire_ack_us"),
            "pull_us": _sample(m, "bps_round_pull_us"),
            "dec_us": _sample(m, "bps_round_dec_us"),
            "wire_bytes": int(_sample(m, "bps_round_wire_bytes")),
            "wire_msgs": int(_sample(m, "bps_round_wire_msgs")),
            "retries": int(_sample(m, "bps_round_retries")),
            "parked": int(_sample(m, "bps_round_parked")),
        }
        workers[name]["round"] = rec
        if rec["round"] >= 0:
            from byteps_tpu.monitor import insight
            stage, share = insight.dominant_stage(rec)
            workers[name]["bottleneck"] = stage
            workers[name]["bottleneck_share"] = round(share, 2)
        else:
            workers[name]["bottleneck"] = "-"
            workers[name]["bottleneck_share"] = 0.0

    # A worker actively riding the retry layer is flagged separately
    # from stragglers: its latency may still look healthy while its
    # connection quality is not.
    retrying = sorted((n for n, w in workers.items()
                       if w["retries"] > 0 or w["reconnects"] > 0),
                      key=_rank_key)
    corrupting = sorted((n for n, w in workers.items()
                         if w["corrupting"]), key=_rank_key)
    trace_dropping = sorted((n for n, w in workers.items()
                             if w["trace_dropped"] > 0),
                            key=_rank_key)

    stragglers: List[str] = []
    active = {n: w["push_mean_us"] for n, w in workers.items()
              if w["push_count"] > 0}
    baseline_us = statistics.median_low(list(active.values())) \
        if active else 0.0
    for name, mean_us in active.items():
        if mean_us >= 1000.0 and mean_us > straggler_factor * baseline_us:
            stragglers.append(name)

    stale_nodes: List[int] = []
    dead_nodes: List[int] = []
    epoch = 0
    recovering = any(w.get("recovering") for w in workers.values())
    recoveries = 0
    fleet_workers = 0
    resizing = False
    joins = leaves = 0
    # Scheduler fail-over (ISSUE 15): the fleet counts as
    # SCHED-RECOVERING when any node is parked on a lost scheduler OR
    # a restarted scheduler is still collecting its quorum.
    sched_recovering = any(w.get("sched_lost") for w in workers.values())
    sched_recoveries = 0
    sched_rereg = sched_rereg_expected = 0
    sched = scrapes.get("scheduler")
    if sched:
        for labels in sched.get("bps_node_dead", {}):
            dead_nodes.append(int(dict(labels)["node"]))
        for labels, age_ms in sched.get("bps_heartbeat_age_ms",
                                        {}).items():
            if age_ms > heartbeat_timeout_s * 1000.0:
                stale_nodes.append(int(dict(labels)["node"]))
        # Recovery state is authoritative at the scheduler: the
        # membership epoch climbs once per hot replacement, and
        # bps_recovering is 1 while the fleet is paused for one.
        epoch = int(_sample(sched, "bps_membership_epoch"))
        recovering = recovering or bool(_sample(sched, "bps_recovering"))
        recoveries = int(_sample(sched, "bps_recoveries_total"))
        # Elastic worker membership (ISSUE 8): the LIVE fleet size and
        # whether a join/leave/shrink is committing right now.
        fleet_workers = int(_sample(sched, "bps_fleet_workers"))
        resizing = bool(_sample(sched, "bps_fleet_resizing"))
        joins = int(_sample(sched, "bps_worker_joins_total"))
        leaves = int(_sample(sched, "bps_worker_leaves_total"))
        sched_recovering = sched_recovering or bool(
            _sample(sched, "bps_sched_recovering"))
        sched_recoveries = int(
            _sample(sched, "bps_sched_recoveries_total"))
        sched_rereg = int(_sample(sched, "bps_sched_rereg"))
        sched_rereg_expected = int(
            _sample(sched, "bps_sched_rereg_expected"))

    # Fleet state (ISSUE 7): classify the workers' last-round records
    # with the same rules the /rounds watcher applies.
    fleet_state = "idle"
    fleet_bottleneck = "-"
    round_recs = {n: w["round"] for n, w in workers.items()
                  if w.get("round", {}).get("round", -1) >= 0}
    if round_recs:
        from byteps_tpu.monitor import insight
        rep = insight.classify(round_recs,
                               straggler_factor=straggler_factor,
                               resizing=resizing,
                               crc_fails=sum(w["crc_fails"]
                                             for w in workers.values()))
        fleet_state = rep["state"]
        fleet_bottleneck = rep["dominant"]
    elif resizing:
        fleet_state = "resizing"

    import os as _os
    tenants = _tenant_rows(
        scrapes,
        starve_ms=float(_os.environ.get("BYTEPS_TENANT_STARVE_MS",
                                        "2000") or 2000))
    replicas = _replica_rows(
        scrapes,
        lag_rounds=int(_os.environ.get("BYTEPS_REPLICA_LAG_ROUNDS",
                                       "8") or 8))
    ckpt = _ckpt_rows(
        scrapes,
        lag_warn=int(_os.environ.get("BYTEPS_CKPT_LAG_WARN", "8") or 8))

    return {
        "workers": workers,
        # Snapshot-serving replicas (ISSUE 16; docs/serving.md).
        "replicas": replicas,
        "lagging_replicas": sorted(
            (n for n, r in replicas.items() if r["lagging"]),
            key=_rank_key),
        # Durable checkpoints (ISSUE 18; docs/checkpoint.md).
        "ckpt": ckpt,
        "lagging_ckpt": sorted(
            (n for n, r in ckpt.items() if r["lagging"]),
            key=_rank_key),
        # Multi-tenant rows (ISSUE 9; docs/multitenancy.md).
        "tenants": tenants,
        "starved_tenants": sorted(
            (t for t, r in tenants.items() if r["starved"]), key=int),
        "baseline_push_us": baseline_us,
        "stragglers": sorted(stragglers, key=_rank_key),
        "retrying": retrying,
        # Wire integrity (ISSUE 19): workers observing a persistently
        # corrupting link (bps_link_corrupting set — the named
        # fail-stop is imminent or already under way).
        "corrupting": corrupting,
        "trace_dropping": trace_dropping,
        "stale_nodes": sorted(stale_nodes),
        "dead_nodes": sorted(dead_nodes),
        "unreachable": sorted((n for n, m in scrapes.items()
                               if m is None), key=_rank_key),
        # Hot-replacement fleet state (docs/monitoring.md "Recovery").
        "epoch": epoch,
        "recovering": recovering,
        "recoveries": recoveries,
        # Elastic membership (ISSUE 8; docs/elasticity.md).
        "fleet_workers": fleet_workers,
        "resizing": resizing,
        "joins": joins,
        "leaves": leaves,
        # Scheduler fail-over (ISSUE 15; docs/troubleshooting.md).
        "sched_recovering": sched_recovering,
        "sched_recoveries": sched_recoveries,
        "sched_reregistered": sched_rereg,
        "sched_expected": sched_rereg_expected,
        # Per-round insight (docs/monitoring.md "Round insight").
        "fleet_state": fleet_state,
        "fleet_bottleneck": fleet_bottleneck,
    }


def _print_report(report: dict, as_json: bool) -> None:
    if as_json:
        print(json.dumps(report))
        return
    print(f"{'worker':<10} {'push/s':>8} {'push MB':>9} {'pull MB':>9} "
          f"{'q-ratio':>7} {'mean push':>10} {'queue':>6} {'credit':>14} "
          f"{'rtry':>5} {'reconn':>6} {'gap/dup':>8} {'crc':>5} "
          f"{'BOTTLENECK':>14} flags")
    if report.get("fleet_workers"):
        extra = ""
        if report.get("joins") or report.get("leaves"):
            extra = (f"; {report.get('joins', 0)} join(s), "
                     f"{report.get('leaves', 0)} leave(s)")
        print(f"fleet: {report['fleet_workers']} worker(s)"
              + (" — RESIZING (membership change committing)"
                 if report.get("resizing") else "") + extra)
    if report.get("sched_recovering"):
        print(f"fleet: SCHED-RECOVERING (scheduler lost/restarting; "
              f"{report.get('sched_reregistered', 0)}/"
              f"{report.get('sched_expected', 0)} node(s) "
              "re-registered; data plane draining against the last "
              "committed address book)")
    if report.get("recovering"):
        print(f"fleet: RECOVERING (membership epoch {report['epoch']}; "
              "a server rank is being hot-replaced)")
    elif report.get("epoch"):
        print(f"fleet: epoch {report['epoch']} "
              f"({report.get('recoveries', 0)} recovery(ies) completed)")
    if report.get("fleet_state", "idle") != "idle":
        print(f"fleet: {report['fleet_state'].upper()} "
              f"(round bottleneck: {report['fleet_bottleneck']}; "
              "details: python -m byteps_tpu.monitor.insight)")
    tenants = report.get("tenants") or {}
    # Tenant rows only when some job actually registered a tenant (a
    # legacy fleet's single implicit tenant 0 row would be noise).
    if any(t != "0" for t in tenants):
        print(f"{'tenant':<10} {'weight':>6} {'workers':>7} "
              f"{'push MB':>9} {'reply MB':>9} {'ops':>8} {'queued':>6} "
              f"{'served MB':>9} flags")
        for tid in sorted(tenants, key=int):
            r = tenants[tid]
            flags = "STARVED" if r["starved"] else ""
            print(f"t{tid:<9} {r['weight']:>6} {r['workers']:>7} "
                  f"{r['push_bytes'] / 1e6:>9.2f} "
                  f"{r['reply_bytes'] / 1e6:>9.2f} {r['ops']:>8} "
                  f"{r['queue_depth']:>6} "
                  f"{r['dispatched'] / 1e6:>9.2f} {flags}")
    for name in sorted(report["workers"], key=_rank_key):
        w = report["workers"][name]
        flags = []
        if name in report["stragglers"]:
            flags.append("STRAGGLER")
        if name in report.get("retrying", []):
            flags.append("RETRYING")
        if name in report.get("trace_dropping", []):
            flags.append("TRACE-DROPPING")
        if w.get("recovering"):
            flags.append("RECOVERING")
        elif w.get("recoveries"):
            flags.append(f"RECOVERED×{w['recoveries']}")
        if w.get("corrupting"):
            flags.append("CORRUPTING")
        credit = (f"{w['inflight_bytes'] >> 10}/"
                  f"{w['credit_budget_bytes'] >> 10}K")
        qratio = (f"{w['quant_ratio']:.1f}x"
                  if w.get("quant_wire_bytes") else "-")
        gapdup = f"{w.get('seq_gaps', 0)}/{w.get('seq_dups', 0)}"
        bneck = w.get("bottleneck", "-")
        if bneck != "-":
            bneck = f"{bneck}({w.get('bottleneck_share', 0) * 100:.0f}%)"
        print(f"{name:<10} {w['push_count']:>8} "
              f"{w['push_bytes'] / 1e6:>9.2f} {w['pull_bytes'] / 1e6:>9.2f} "
              f"{qratio:>7} "
              f"{w['push_mean_us'] / 1e3:>8.2f}ms {w['queue_pending']:>6} "
              f"{credit:>14} {w.get('retries', 0):>5} "
              f"{w.get('reconnects', 0):>6} {gapdup:>8} "
              f"{w.get('crc_fails', 0):>5} {bneck:>14} "
              f"{' '.join(flags)}")
    replicas = report.get("replicas") or {}
    if replicas:
        print(f"{'replica':<10} {'snap-ver':>9} {'lag':>5} "
              f"{'snap pulls':>10} flags")
        for name in sorted(replicas, key=_rank_key):
            r = replicas[name]
            flags = "REPLICA-LAGGING" if r["lagging"] else ""
            print(f"{name:<10} {r['snapshot_version']:>9} "
                  f"{r['lag_rounds']:>5} {r['snap_pulls']:>10} {flags}")
    ckpt = report.get("ckpt") or {}
    if ckpt:
        print(f"{'server':<10} {'ckpt-ver':>9} {'lag':>5} {'spills':>7} "
              f"{'fail':>5} {'spill ms':>8} flags")
        for name in sorted(ckpt, key=_rank_key):
            r = ckpt[name]
            flags = "CKPT-LAGGING" if r["lagging"] else ""
            print(f"{name:<10} {r['ckpt_version']:>9} "
                  f"{r['lag_rounds']:>5} {r['spills']:>7} "
                  f"{r['failures']:>5} {r['spill_ms']:>8} {flags}")
    for kind in ("retrying", "corrupting", "stale_nodes", "dead_nodes",
                 "unreachable", "starved_tenants", "lagging_replicas",
                 "lagging_ckpt"):
        if report.get(kind):
            print(f"{kind}: {report[kind]}")
    # Journal ticker (ISSUE 20): fresh fleet lifecycle events since the
    # last poll, on the scheduler timebase. A pause that never resumes,
    # a death, a quarantine — they land here the poll after they
    # happen, without waiting for a gauge to move.
    roles = {0: "sched", 1: "server", 2: "worker"}
    for e in report.get("events") or []:
        who = (f"{roles.get(e.get('role', -1), '?')}"
               f"/n{e.get('node', -1)}")
        args = ",".join(str(e.get(k, 0)) for k in ("a0", "a1", "a2"))
        print(f"event: {e.get('ts_us', 0) / 1e6:>12.3f}s "
              f"{e.get('name', '?'):<22} {who:<12} args=[{args}]")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m byteps_tpu.monitor.top",
        description="scrape the fleet's monitor endpoints; flag "
                    "stragglers and dead/stale nodes (docs/monitoring.md)")
    p.add_argument("--host", default=os.environ.get("DMLC_PS_ROOT_URI",
                                                    "127.0.0.1"))
    p.add_argument("--base-port", type=int,
                   default=int(os.environ.get("BYTEPS_MONITOR_PORT",
                                              "9100")))
    p.add_argument("--num-workers", type=int,
                   default=int(os.environ.get("DMLC_NUM_WORKER", "1")))
    p.add_argument("--num-servers", type=int,
                   default=int(os.environ.get("DMLC_NUM_SERVER", "1")))
    p.add_argument("--num-replicas", type=int,
                   default=int(os.environ.get("BYTEPS_NUM_REPLICAS", "0")
                               or 0),
                   help="read replicas to scrape (ranks after the "
                        "workers; docs/serving.md)")
    p.add_argument("--endpoints", nargs="*", metavar="NAME=HOST:PORT",
                   help="explicit endpoints (e.g. worker0=10.0.0.5:9104); "
                        "overrides the derived topology")
    p.add_argument("--straggler-factor", type=float,
                   default=float(os.environ.get("BYTEPS_STRAGGLER_FACTOR",
                                                "2.0")))
    p.add_argument("--heartbeat-timeout", type=float,
                   default=float(os.environ.get("PS_HEARTBEAT_TIMEOUT",
                                                "30")))
    p.add_argument("--watch", type=float, metavar="SECONDS", default=0,
                   help="re-scrape every N seconds until interrupted")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report (one JSON line per poll)")
    args = p.parse_args(argv)

    if args.endpoints:
        eps = dict(e.split("=", 1) for e in args.endpoints)
    else:
        eps = fleet_endpoints(args.host, args.base_port, args.num_workers,
                              args.num_servers, args.num_replicas)
    last_ev_us = 0
    while True:
        report = analyze({name: scrape(ep) for name, ep in eps.items()},
                         straggler_factor=args.straggler_factor,
                         heartbeat_timeout_s=args.heartbeat_timeout)
        if "scheduler" in eps:
            fresh = scrape_events(eps["scheduler"], since_us=last_ev_us)
            if fresh:
                last_ev_us = max(e.get("ts_us", 0) for e in fresh)
            report["events"] = fresh
        _print_report(report, args.json)
        if not args.watch:
            return 1 if (report["stragglers"] or report["dead_nodes"]
                         or report["stale_nodes"]) else 0
        time.sleep(args.watch)


if __name__ == "__main__":
    sys.exit(main())
