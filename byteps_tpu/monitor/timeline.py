"""Fleet timeline merge + critical-path analysis (ISSUE 5).

Every role dumps a per-rank Chrome-trace JSON (``bps_dump_trace``, or
automatically at shutdown with ``BYTEPS_TRACE_ON=1``) whose ``meta``
object carries the rank's identity and its clock offset vs the
scheduler (estimated from the heartbeat RTT exchange, min-RTT sample).
This module gathers those dumps, applies the offsets so every rank sits
on the scheduler's timebase, and emits ONE Perfetto/chrome://tracing
loadable trace in which a worker's push span flow-links (Chrome
``s``/``t``/``f`` events keyed on (sender, req_id)) to its server's sum
span and back to the ack — the cross-rank attribution the worker-only
timeline could not give ("server slow" vs "peer late" vs "wire
congested").

It also prints a per-step critical-path breakdown — worker-enqueue wait
vs wire+ack vs server-sum vs pull wait — and straggler attribution using
the same low-median rule as ``monitor.top``.

Usage::

    python -m byteps_tpu.monitor.timeline merge --dir traces/ \
        --out fleet.json            # merged trace + report
    python -m byteps_tpu.monitor.timeline report --dir traces/
    python -m byteps_tpu.monitor.timeline merge --dir traces/ \
        --glob 'flight_*.json' --out flight.json   # merged flight view

The same functions are importable for tests and tooling:
``load_dump`` / ``merge_dumps`` / ``critical_path``.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import re
import statistics
import sys
from typing import Dict, List, Optional, Tuple

_ROLE_NAMES = {0: "scheduler", 1: "server", 2: "worker"}

# Worker/server span names the critical-path report attributes.
# qencode/qdecode (ISSUE 7 satellite): the quantized wire's encode/EF
# fold and reply-leg dequant, previously invisible under "compress" /
# inside the pull span.
_WORKER_SPANS = ("compress", "qencode", "push", "pull", "qdecode")
_SERVER_SPANS = ("s_sum", "s_reply")


def load_dump(path: str) -> dict:
    """One per-rank dump: {"meta": {...}, "traceEvents": [...]}. Dumps
    from pre-ISSUE-5 cores (no meta) load with an empty meta."""
    with open(path) as f:
        d = json.load(f)
    d.setdefault("meta", {})
    d["meta"].setdefault("path", path)
    return d


def gather(trace_dir: str, pattern: str = "trace_*.json") -> List[dict]:
    paths = sorted(_glob.glob(os.path.join(trace_dir, pattern)))
    return [load_dump(p) for p in paths]


def _rank_label(meta: dict) -> str:
    role = _ROLE_NAMES.get(meta.get("role", -1), "rank")
    nid = meta.get("node_id", -1)
    if role == "worker" and meta.get("worker_rank", -1) >= 0:
        return f"worker {meta['worker_rank']} (node {nid})"
    if nid < 0:
        # Pre-topology dump (a rank that died before learning its id):
        # the pid is the only attribution; SetNode renames survivors'
        # files to role/node form, but the merge tolerates both.
        return f"{role} (pid {meta.get('pid', '?')})"
    return f"{role} (node {nid})"


def _incarnation(meta: dict) -> int:
    """Incarnation index from the dump filename: restart forensics
    (crash-restart, restore-relaunch) leave multiple dumps for one
    role/node — ``flight_rR_nN.json`` is the first life, and each
    relaunch probes to ``flight_rR_nN_i<k>.json`` rather than
    overwriting its predecessor's evidence."""
    m = re.search(r"_i(\d+)\.json$", meta.get("path", "") or "")
    return int(m.group(1)) if m else 0


# Synthetic process row for the journal overlay: far above any real
# node id (which stay < 100000 * incarnations in practice).
_EVENTS_PID = 10 ** 9


def journal_instants(journal: dict) -> List[dict]:
    """Fleet event journal (ISSUE 20) -> Perfetto instant events, one
    per timeline entry, already on the scheduler timebase (the journal
    aligns at ingest). They ride a dedicated "fleet events" process
    row so pauses, deaths, and recovery commits sit visually above the
    per-rank spans they explain."""
    out = []
    for e in journal.get("timeline") or journal.get("events") or []:
        out.append({
            "name": e.get("name", "event"),
            "ph": "i", "s": "g",  # global scope: full-height marker
            "pid": _EVENTS_PID, "tid": e.get("node", -1),
            "ts": e.get("ts_us", 0),
            "args": {"node": e.get("node", -1),
                     "role": e.get("role", -1),
                     "a0": e.get("a0", 0), "a1": e.get("a1", 0),
                     "a2": e.get("a2", 0)},
        })
    return out


def merge_dumps(dumps: List[dict],
                out_path: Optional[str] = None,
                journal: Optional[dict] = None) -> dict:
    """Merge per-rank dumps into one fleet trace.

    Clock alignment: each rank's events are shifted by its
    ``meta.clock_offset_us`` so all timestamps sit on the scheduler's
    timebase (offset is defined as t_scheduler ~= t_local + offset).
    Each rank becomes its own process row (pid = node id) with a
    ``process_name`` metadata record, so Perfetto shows one labelled
    track group per rank. Events are emitted in timestamp order.

    Incarnations: when several dumps share one (role, node id) — a
    crashed first life plus its restarted successor(s), distinguished
    by the ``_i<k>`` filename suffix — each life gets its OWN labelled
    row ("life k") instead of interleaving pre-crash and post-restart
    events on one track.
    """
    events: List[dict] = []
    ranks = []
    lives: Dict[Tuple[int, int], int] = {}
    for d in dumps:
        key = (d.get("meta", {}).get("role", -1),
               d.get("meta", {}).get("node_id", -1))
        if key[1] >= 0:
            lives[key] = lives.get(key, 0) + 1
    for d in dumps:
        meta = d.get("meta", {})
        nid = meta.get("node_id", -1)
        inc = _incarnation(meta)
        # A rank that never learned its id (pre-topology dump) still
        # gets a distinct row: fall back to a synthetic negative pid.
        pid = nid if nid >= 0 else -(len(ranks) + 1)
        label = _rank_label(meta)
        if nid >= 0 and lives.get((meta.get("role", -1), nid), 0) > 1:
            # Distinct row per incarnation (node ids are small; the
            # 100000 stride cannot collide with a real node id).
            pid = nid + 100000 * inc
            label = f"{label} [life {inc + 1}]"
        offset = int(meta.get("clock_offset_us", 0) or 0)
        ranks.append({"pid": pid, "label": label,
                      "offset_us": offset,
                      "rtt_us": meta.get("clock_rtt_us", -1),
                      "dropped": meta.get("dropped", 0),
                      "incarnation": inc,
                      "role": meta.get("role", -1)})
        for e in d.get("traceEvents", []):
            if "ts" not in e:
                continue
            e2 = dict(e)
            e2["pid"] = pid
            e2["ts"] = e["ts"] + offset
            events.append(e2)
    overlay = journal_instants(journal) if journal else []
    events += overlay
    events.sort(key=lambda e: e["ts"])
    merged_events: List[dict] = [
        {"name": "process_name", "ph": "M", "pid": r["pid"],
         "args": {"name": r["label"]}} for r in ranks]
    if overlay:
        merged_events.append({"name": "process_name", "ph": "M",
                              "pid": _EVENTS_PID,
                              "args": {"name": "fleet events"}})
    merged_events += events
    merged = {"traceEvents": merged_events,
              "meta": {"ranks": ranks, "events": len(events),
                       "journal_events": len(overlay)}}
    if out_path:
        with open(out_path, "w") as f:
            json.dump(merged, f)
    return merged


def check_flows(merged: dict) -> dict:
    """Flow-event health of a merged trace: per flow id, the set of
    phases present. A healthy chain has its "s" start matched by an "f"
    end (steps "t" optional); unbalanced ids usually mean a rank's ring
    dropped events or a rank's dump is missing from the merge."""
    flows: Dict[Tuple[str, int], set] = {}
    for e in merged.get("traceEvents", []):
        if e.get("ph") in ("s", "t", "f") and "id" in e:
            flows.setdefault((e.get("name", ""), e["id"]),
                             set()).add(e["ph"])
    balanced = sum(1 for phs in flows.values()
                   if "s" in phs and "f" in phs)
    return {"flows": len(flows), "balanced": balanced,
            "unbalanced": len(flows) - balanced}


def _span_index(dumps: List[dict]) -> Tuple[list, list, dict]:
    """(worker_spans, server_spans, enqueue_index) from raw (unshifted)
    dumps — durations are offset-invariant, so the report reads the
    per-rank dumps directly. enqueue_index: (pid, key, round) -> ts."""
    wspans, sspans = [], []
    enq: Dict[Tuple[int, int, int], int] = {}
    for d in dumps:
        meta = d.get("meta", {})
        nid = meta.get("node_id", -1)
        role = meta.get("role", -1)
        for e in d.get("traceEvents", []):
            args = e.get("args", {})
            rec = {"pid": nid, "role": role, "name": e.get("name"),
                   "ts": e.get("ts", 0), "dur": e.get("dur", 0),
                   "key": args.get("key"), "peer": args.get("peer", -1),
                   "req": args.get("req", -1),
                   "round": args.get("round", -1),
                   # Byte labels on data-carrying spans (quantized
                   # wire): what crossed the wire vs the decoded size.
                   "wire_bytes": args.get("wire_bytes", 0),
                   "raw_bytes": args.get("raw_bytes", 0),
                   "label": _rank_label(meta)}
            if e.get("ph") == "X":
                if role == 2 and e.get("name") in _WORKER_SPANS:
                    wspans.append(rec)
                elif role == 1 and e.get("name") in _SERVER_SPANS:
                    sspans.append(rec)
            elif e.get("ph") == "i" and e.get("name") == "enqueue":
                enq[(nid, args.get("key"), args.get("round", -1))] = \
                    e.get("ts", 0)
    return wspans, sspans, enq


def critical_path(dumps: List[dict],
                  straggler_factor: float = 2.0) -> dict:
    """Per-stage totals and straggler attribution.

    Stages (all microsecond sums):
      - queue:      enqueue instant -> push-span start (scheduled-queue
                    wait: credit admission + priority)
      - compress:   codec encode spans
      - push:       push issue -> server ack (includes wire + server)
      - server_sum: the owning server's decompress+sum spans
      - wire_ack:   push minus its matched server_sum — wire transit,
                    server queueing, and the ack's return leg
      - pull:       pull issue -> response (includes waiting for PEERS'
                    pushes — the straggler signal)
      - server_reply: the server's reply-serve spans

    Matching uses (worker node id, req_id) — the same pair the flow
    events stitch on; server spans carry it as (peer, req).
    Per-step rows group by the round number each span carries.
    """
    wspans, sspans, enq = _span_index(dumps)
    ssum_by_req: Dict[Tuple[int, int, int], int] = {}
    for s in sspans:
        if s["name"] == "s_sum":
            k = (s["peer"], s["req"], s["key"])
            ssum_by_req[k] = ssum_by_req.get(k, 0) + s["dur"]

    per_worker: Dict[str, dict] = {}
    per_round: Dict[int, dict] = {}

    def stage_add(bucket: dict, stage: str, us: float) -> None:
        bucket[stage] = bucket.get(stage, 0.0) + us

    for w in wspans:
        wb = per_worker.setdefault(
            w["label"], {"push_count": 0, "stages": {},
                         "push_wire_bytes": 0, "push_raw_bytes": 0})
        rb = per_round.setdefault(w["round"], {})
        stage_add(wb["stages"], w["name"], w["dur"])
        stage_add(rb, w["name"], w["dur"])
        if w["name"] == "push":
            wb["push_count"] += 1
            # Quantized-vs-raw freight: a push span whose wire bytes
            # undercut its raw bytes shipped the int8 encoding.
            if w.get("raw_bytes", 0) > 0:
                wb["push_wire_bytes"] += w.get("wire_bytes", 0)
                wb["push_raw_bytes"] += w["raw_bytes"]
            q = enq.get((w["pid"], w["key"], w["round"]))
            if q is not None and w["ts"] >= q:
                stage_add(wb["stages"], "queue", w["ts"] - q)
                stage_add(rb, "queue", w["ts"] - q)
            ssum = ssum_by_req.get((w["pid"], w["req"], w["key"]))
            if ssum is not None:
                stage_add(wb["stages"], "server_sum", ssum)
                stage_add(wb["stages"], "wire_ack",
                          max(0, w["dur"] - ssum))
                stage_add(rb, "server_sum", ssum)
                stage_add(rb, "wire_ack", max(0, w["dur"] - ssum))

    per_server: Dict[str, dict] = {}
    for s in sspans:
        sb = per_server.setdefault(s["label"], {})
        stage_add(sb, s["name"], s["dur"])

    # Straggler rule: monitor.top's — mean push latency above
    # straggler_factor x the fleet low-median, with a 1 ms floor.
    means = {}
    for name, wb in per_worker.items():
        if wb["push_count"]:
            means[name] = wb["stages"].get("push", 0) / wb["push_count"]
    baseline = statistics.median_low(list(means.values())) if means else 0
    stragglers = sorted(
        n for n, m in means.items()
        if m >= 1000.0 and m > straggler_factor * baseline)

    fleet: Dict[str, float] = {}
    for wb in per_worker.values():
        for stage, us in wb["stages"].items():
            fleet[stage] = fleet.get(stage, 0.0) + us
    return {
        "per_worker": per_worker,
        "per_server": per_server,
        "per_round": {k: v for k, v in sorted(per_round.items())
                      if k >= 0},
        "fleet_stages_us": fleet,
        "push_mean_us": means,
        "baseline_push_us": baseline,
        "stragglers": stragglers,
    }


def _fmt_us(us: float) -> str:
    return f"{us / 1e3:.2f}ms" if us >= 1000 else f"{us:.0f}us"


def print_report(report: dict, flow_stats: Optional[dict] = None,
                 file=None) -> None:
    out = file or sys.stdout
    fleet = report["fleet_stages_us"]
    order = ("queue", "compress", "qencode", "push", "wire_ack",
             "server_sum", "pull", "qdecode")
    print("fleet critical-path totals (worker-observed):", file=out)
    for stage in order:
        if stage in fleet:
            print(f"  {stage:<11} {_fmt_us(fleet[stage])}", file=out)
    for name, wb in sorted(report["per_worker"].items()):
        mean = report["push_mean_us"].get(name, 0.0)
        flag = " STRAGGLER" if name in report["stragglers"] else ""
        stages = " ".join(f"{s}={_fmt_us(u)}"
                          for s, u in sorted(wb["stages"].items()))
        quant = ""
        if wb.get("push_raw_bytes", 0) > 0:
            wire = wb.get("push_wire_bytes", 0)
            raw = wb["push_raw_bytes"]
            kind = "quantized" if wire < raw else "raw"
            quant = (f" push_bytes={wire >> 10}K/{raw >> 10}K"
                     f" ({kind})")
        print(f"  {name}: pushes={wb['push_count']} "
              f"mean_push={_fmt_us(mean)} {stages}{quant}{flag}",
              file=out)
    for name, sb in sorted(report["per_server"].items()):
        stages = " ".join(f"{s}={_fmt_us(u)}"
                          for s, u in sorted(sb.items()))
        print(f"  {name}: {stages}", file=out)
    if report["stragglers"]:
        print(f"stragglers: {report['stragglers']} "
              f"(baseline {_fmt_us(report['baseline_push_us'])})",
              file=out)
    if flow_stats:
        print(f"flows: {flow_stats['flows']} "
              f"({flow_stats['balanced']} balanced, "
              f"{flow_stats['unbalanced']} unbalanced)", file=out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m byteps_tpu.monitor.timeline",
        description="merge per-rank trace dumps into one clock-aligned "
                    "fleet timeline + critical-path report "
                    "(docs/timeline.md)")
    p.add_argument("cmd", choices=["merge", "report"],
                   help="merge: write the fleet trace (+report); "
                        "report: analysis only")
    p.add_argument("--dir", default=os.environ.get("BYTEPS_TRACE_DIR")
                   or os.environ.get("BPS_TRACE_OUT") or "./traces",
                   help="directory holding the per-rank dumps "
                        "(default: BYTEPS_TRACE_DIR)")
    p.add_argument("--glob", default="trace_*.json",
                   help="dump filename pattern (use 'flight_*.json' to "
                        "merge flight-recorder dumps)")
    p.add_argument("--out", default="",
                   help="merged trace output path (merge mode; default "
                        "<dir>/fleet.json)")
    p.add_argument("--events", default="", metavar="JOURNAL",
                   help="overlay the fleet event journal (a saved "
                        "/events JSON, e.g. from monitor.incident) as "
                        "Perfetto instant markers on a 'fleet events' "
                        "row (merge mode)")
    p.add_argument("--straggler-factor", type=float,
                   default=float(os.environ.get("BYTEPS_STRAGGLER_FACTOR",
                                                "2.0")))
    p.add_argument("--json", action="store_true",
                   help="machine-readable report (one JSON line)")
    args = p.parse_args(argv)

    dumps = gather(args.dir, args.glob)
    if not dumps:
        print(f"no dumps matching {args.glob!r} under {args.dir!r} — "
              "run with BYTEPS_TRACE_ON=1 (every role auto-dumps at "
              "shutdown) or call bps_dump_trace", file=sys.stderr)
        return 1
    flow_stats = None
    if args.cmd == "merge":
        out = args.out or os.path.join(args.dir, "fleet.json")
        journal = None
        if args.events:
            with open(args.events) as f:
                journal = json.load(f)
        merged = merge_dumps(dumps, out_path=out, journal=journal)
        flow_stats = check_flows(merged)
        print(f"merged {len(dumps)} rank dump(s), "
              f"{merged['meta']['events']} events -> {out}",
              file=sys.stderr)
    report = critical_path(dumps, straggler_factor=args.straggler_factor)
    if args.json:
        report["flow_stats"] = flow_stats
        print(json.dumps(report))
    else:
        print_report(report, flow_stats)
    return 0


if __name__ == "__main__":
    sys.exit(main())
