"""Online per-round bottleneck attribution + fleet-state classification
(ISSUE 7).

The C core keeps a per-round summary ring on every rank (csrc/
roundstats.h): per-stage wall time, wire bytes/frames, retries, parked
ops. Workers piggyback completed rounds on their heartbeats; the
scheduler folds them into per-rank EWMA baselines and a fleet round
table, served raw at the monitor endpoint's ``/rounds`` path
(``bps_round_summary``). This module is the judgment layer on top:

- ``dominant_stage``   — which stage bound a round record;
- ``classify``         — the fleet state: ``wire-bound`` /
  ``sum-bound`` / ``straggler-skewed`` / ``retry-degraded`` /
  ``healthy``;
- ``regressions``      — ranks whose latest round wall blew past their
  EWMA baseline;
- ``hints``            — *advisory* tuning hints naming the knob (e.g.
  "wire msgs dominate -> raise BYTEPS_FUSION_BYTES"). Hints only, no
  actuation: this PR is the sensor; the closed-loop controller
  (ROADMAP item 3) consumes the same classification as its input.

``python -m byteps_tpu.monitor.insight --watch`` scrapes the
scheduler's ``/rounds`` endpoint and prints a live scrolling per-round
report; ``monitor.top`` reuses ``classify``/``dominant_stage`` for its
BOTTLENECK column and fleet-state header.

Stage taxonomy (docs/monitoring.md "Round insight"): ``queue``
(scheduled-queue wait), ``compress`` (codec + qencode), ``wire_ack``
(push wall minus the server's ack-reported sum time: wire transit,
server queueing, ack return), ``server_sum`` (decode+sum on the
server), ``pull_wait`` (pull issue -> response; includes waiting for
PEERS' pushes — the straggler signal), ``decode`` (decompress +
qdecode).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
import urllib.request
from typing import Dict, Iterable, List, Optional, Tuple

# Attribution stages, in report order. Keys into a breakdown dict.
STAGES = ("queue", "compress", "wire_ack", "server_sum", "pull_wait",
          "decode")

# Stages the fleet-state dominance rule considers: the ACTIVE stages —
# time something was being computed or carried. The two WAIT stages are
# deliberately excluded from dominance:
#  - pull_wait is mostly the echo of PEERS' bottlenecks (a pull waits
#    for every other rank's push to land), so in a symmetric
#    wire-bound fleet it mirrors wire_ack and would split the dominant
#    share in half; skew in it is caught by the straggler rule;
#  - queue wait is the echo of DOWNSTREAM serialization, quadratically:
#    with a backlog of N tasks the k-th waits k x the per-task send
#    time, so the queue total is ~N/2 x the wire total for ANY
#    wire-gated round — dominance over it would classify every
#    backlogged round "queue-bound" regardless of what actually gates
#    the drain rate.
# Both stay in the per-rank breakdown, the BOTTLENECK column, and the
# hints (where "mostly waiting" is exactly the informative reading).
ATTRIB_STAGES = ("compress", "wire_ack", "server_sum", "decode")

# A stage must own at least this share of the round wall before the
# fleet is declared BOUND on it; below, no single stage gates the round
# and the state is healthy.
DOMINANCE_SHARE = 0.4

# Straggler rule: same shape as monitor.top's — a rank whose mean
# per-partition push wall exceeds factor x the fleet low-median, above
# an absolute floor that keeps loopback microsecond noise quiet.
PUSH_FLOOR_US = 1000.0

# Regression rule: latest round wall vs the rank's EWMA baseline, only
# once the baseline has seen enough rounds to mean something.
REGRESS_FACTOR = 1.5
REGRESS_MIN_UPDATES = 3

FLEET_STATES = ("healthy", "wire-bound", "sum-bound", "straggler-skewed",
                "retry-degraded", "corruption-degraded", "resizing")


def stage_breakdown(rec: dict) -> Dict[str, float]:
    """Per-stage microseconds from one round record (the JSON shape
    ``bps_round_summary`` emits). ``wire_ack`` is derived when absent:
    push wall minus the server-reported sum time."""
    push = float(rec.get("push_us", 0))
    sum_us = float(rec.get("sum_us", 0))
    wire_ack = float(rec.get("wire_ack_us", max(0.0, push - sum_us)))
    return {
        "queue": float(rec.get("queue_us", 0)),
        "compress": float(rec.get("comp_us", 0)),
        "wire_ack": wire_ack,
        "server_sum": min(sum_us, push) if push else sum_us,
        "pull_wait": float(rec.get("pull_us", 0)),
        "decode": float(rec.get("dec_us", 0)),
    }


def round_wall_us(rec: dict) -> float:
    return sum(stage_breakdown(rec).values())


def dominant_stage(rec: dict) -> Tuple[str, float]:
    """(stage, share-of-wall) for the stage that bound this record;
    ("idle", 0.0) for an empty record."""
    bd = stage_breakdown(rec)
    wall = sum(bd.values())
    if wall <= 0:
        return "idle", 0.0
    stage = max(STAGES, key=lambda s: bd[s])
    return stage, bd[stage] / wall


def merge_recs(recs: Iterable[dict]) -> dict:
    """Elementwise sum of round records — the fleet-wide view of one
    round (or of each rank's latest round). ``round`` keeps the max,
    not the sum (it is an identity, not a quantity)."""
    recs = [r for r in recs if r]
    out: Dict[str, float] = {}
    for rec in recs:
        for k, v in rec.items():
            if isinstance(v, (int, float)):
                out[k] = out.get(k, 0) + v
    if recs and "round" in out:
        out["round"] = max(int(r.get("round", -1)) for r in recs)
    return out


def classify(workers: Dict[str, dict], straggler_factor: float = 2.0,
             retry_threshold: int = 1,
             dominance: float = DOMINANCE_SHARE,
             resizing: bool = False,
             tenants: Optional[Dict[str, int]] = None,
             crc_fails: int = 0) -> dict:
    """Fleet state from per-worker round records (one record per
    worker — normally each rank's latest completed round).

    Precedence: a membership epoch change in flight (``resizing``)
    first — a round spanning a join/leave/shrink legitimately stalls
    some ranks behind the commit and would otherwise read as
    straggler-skewed — then wire corruption (``corruption-degraded``,
    driven by the caller-scraped ``crc_fails`` total: CRC-failed frames
    CAUSE the resends, so naming the corruption outranks the generic
    retry state), then faults (``retry-degraded``), then skew
    (``straggler-skewed``), then stage dominance (``wire-bound`` /
    ``sum-bound``); anything else is ``healthy``. Skew outranks
    dominance because a paced straggler ALSO inflates wire shares —
    the skew is the actionable signal there, not the stage.
    """
    workers = {k: v for k, v in workers.items() if v}
    fleet = merge_recs(list(workers.values())) if workers else {}
    bd = stage_breakdown(fleet) if fleet else {}
    attrib_wall = sum(bd.get(s, 0.0) for s in ATTRIB_STAGES)
    if attrib_wall > 0:
        dom = max(ATTRIB_STAGES, key=lambda s: bd[s])
        share = bd[dom] / attrib_wall
    else:
        dom, share = "idle", 0.0
    retries = int(fleet.get("retries", 0))

    # Per-rank mean per-partition push wall (monitor.top's metric).
    push_means = {}
    for name, rec in workers.items():
        parts = int(rec.get("parts", 0))
        if parts > 0:
            push_means[name] = float(rec.get("push_us", 0)) / parts
    baseline = (statistics.median_low(list(push_means.values()))
                if push_means else 0.0)
    stragglers = sorted(
        n for n, m in push_means.items()
        if m >= PUSH_FLOOR_US and m > straggler_factor * baseline)

    if resizing:
        state = "resizing"
    elif crc_fails > 0:
        state = "corruption-degraded"
    elif retries >= retry_threshold:
        state = "retry-degraded"
    elif stragglers:
        state = "straggler-skewed"
    elif dom == "wire_ack" and share >= dominance:
        state = "wire-bound"
    elif dom == "server_sum" and share >= dominance:
        state = "sum-bound"
    else:
        state = "healthy"

    # Noisy-neighbor attribution (ISSUE 9): when the fleet spans more
    # than one tenant, split the round wall by tenant so a bound/skewed
    # state can NAME the job that owns most of it — the multi-tenant
    # "which neighbor is noisy" question monitor.top and the hints
    # surface.
    tenant_walls: Dict[str, float] = {}
    if tenants and len(set(tenants.values())) > 1:
        for name, rec in workers.items():
            t = str(tenants.get(name, 0))
            tenant_walls[t] = tenant_walls.get(t, 0.0) + round_wall_us(rec)
    total_wall = sum(tenant_walls.values())
    noisy = None
    if total_wall > 0:
        top = max(tenant_walls, key=lambda t: tenant_walls[t])
        if tenant_walls[top] / total_wall >= 0.6:
            noisy = top
    return {
        "state": state,
        "dominant": dom,
        "dominant_share": round(share, 3),
        "fleet": fleet,
        "stragglers": stragglers,
        "baseline_push_us": baseline,
        "retries": retries,
        "tenant_walls": {t: round(v, 1) for t, v in tenant_walls.items()},
        "noisy_tenant": noisy,
    }


def regressions(fleet: Dict[str, dict],
                factor: float = REGRESS_FACTOR) -> List[str]:
    """Ranks whose latest round wall exceeds factor x their EWMA
    baseline (``fleet`` is the scheduler snapshot's per-rank section:
    {node: {"last": rec, "ewma_wall_us": x, "updates": n}})."""
    out = []
    for node, st in fleet.items():
        if int(st.get("updates", 0)) < REGRESS_MIN_UPDATES:
            continue
        ewma = float(st.get("ewma_wall_us", 0.0))
        if ewma > 0 and round_wall_us(st.get("last", {})) > factor * ewma:
            out.append(node)
    return sorted(out)


def hints(state: str, fleet_rec: dict) -> List[str]:
    """Advisory tuning hints naming the knob. NEVER actuated here —
    the observability layer stays a sensor (docs/monitoring.md)."""
    out: List[str] = []
    parts = max(1, int(fleet_rec.get("parts", 0)))
    msgs_per_part = float(fleet_rec.get("wire_msgs", 0)) / parts
    fused = int(fleet_rec.get("fused_frames", 0))
    bd = stage_breakdown(fleet_rec)
    wall = sum(bd.values()) or 1.0
    if state == "wire-bound":
        if msgs_per_part > 1.5 and fused == 0:
            out.append(
                "wire_msgs dominate (%.1f frames/partition, none fused)"
                " -> raise BYTEPS_FUSION_BYTES so small tensors coalesce"
                % msgs_per_part)
        else:
            out.append(
                "wire transit bounds the round -> raise "
                "BYTEPS_VAN_STREAMS (per-stream cwnd cap) and check "
                "BYTEPS_SOCKET_BUF >= the link BDP")
    elif state == "sum-bound":
        out.append(
            "server summation bounds the round -> raise "
            "BYTEPS_SERVER_ENGINE_THREAD or add server ranks "
            "(DMLC_NUM_SERVER)")
    elif state == "straggler-skewed":
        out.append(
            "one rank's push wall gates the fleet -> inspect that "
            "host's NIC/pacing/CPU before touching fleet-wide knobs")
    elif state == "retry-degraded":
        out.append(
            "resends are burning round time -> inspect link loss; if "
            "rounds are healthy-but-slow, raise BYTEPS_RETRY_TIMEOUT_MS "
            "so the timer stops re-sending live requests")
    elif state == "corruption-degraded":
        out.append(
            "frames are failing CRC32C verification (bps_crc_fail_total "
            "climbing) -> the wire is corrupting data, not just losing "
            "it; check NICs/cables on the flagged link, arm "
            "BYTEPS_WIRE_CRC_QUARANTINE to force re-dials, and expect a "
            "named fail-stop if the corruption survives fresh sockets")
    elif state == "resizing":
        out.append(
            "a worker membership epoch change is committing -> "
            "transient; re-check once bps_fleet_resizing drops to 0 "
            "(stuck past BYTEPS_ELASTIC_TIMEOUT_MS would fail-stop)")
    if bd["queue"] / wall >= DOMINANCE_SHARE:
        out.append(
            "scheduled-queue wait dominates the wall -> raise "
            "BYTEPS_SCHEDULING_CREDIT if credit-limited; otherwise the "
            "queue is draining at the bound stage's rate (fix that "
            "first)")
    if bd["compress"] / wall >= DOMINANCE_SHARE:
        out.append(
            "encode cost dominates -> larger BYTEPS_WIRE_QUANT_BLOCK "
            "(fewer scales) or drop the codec on small keys "
            "(BYTEPS_WIRE_QUANT_MIN_BYTES)")
    if int(fleet_rec.get("parked", 0)) > parts:
        out.append(
            "server parks exceed partitions -> deep pipelining is "
            "outrunning slot recycling; fewer in-flight rounds or more "
            "servers")
    return out


def window_recs(summary: dict, window: int) -> Dict[str, dict]:
    """Per-worker records merged over each worker's last ``window``
    completed rounds in the scheduler's ``fleet_rounds`` table. A
    single round's record is pacing-sensitive (one scheduler hiccup on
    a loaded box flips its ratios); summing a small completed-round
    window classifies on the same share arithmetic but over a stable
    base — the deflake contract for the straggler fleet test. Falls
    back to each rank's ``last`` record when the table is empty or
    ``window`` <= 1."""
    fleet = summary.get("fleet", {}) or {}
    last = {node: st.get("last", {}) for node, st in fleet.items()
            if st.get("role") == 2}
    table = summary.get("fleet_rounds", {}) or {}
    if window <= 1 or not table:
        return last
    by_node: Dict[str, List[dict]] = {}
    for rnd in sorted(table, key=int, reverse=True):
        for node, rec in table[rnd].items():
            if node not in last:
                continue  # non-worker rank
            recs = by_node.setdefault(node, [])
            if len(recs) < window:
                recs.append(rec)
    return {node: merge_recs(recs) for node, recs in by_node.items()} \
        or last


def analyze(summary: dict, straggler_factor: float = 2.0,
            regress_factor: float = REGRESS_FACTOR,
            window: int = 1) -> dict:
    """Full report from one ``bps_round_summary`` snapshot (normally the
    SCHEDULER's, whose ``fleet`` section holds every rank's summaries).
    Falls back to the local ring when no fleet data is present.
    ``window`` > 1 classifies over each worker's last N completed
    rounds instead of a single pacing-sensitive one (see window_recs)."""
    fleet = summary.get("fleet", {}) or {}
    workers = window_recs(summary, window)
    local_only = False
    if not workers:
        last = summary.get("last")
        workers = {str(summary.get("node_id", -1)): last} if last else {}
        local_only = True
    tenants = {node: int(st.get("tenant", 0))
               for node, st in fleet.items() if st.get("role") == 2}
    rep = classify(workers, straggler_factor=straggler_factor,
                   resizing=bool(summary.get("resizing", 0)),
                   tenants=tenants)
    rep["regressions"] = regressions(
        {n: st for n, st in fleet.items() if st.get("role") == 2},
        factor=regress_factor)
    rep["hints"] = hints(rep["state"], rep["fleet"])
    # Noisy-neighbor hint (ISSUE 9): name the tenant, not just the
    # stage — on a shared fleet the actionable knob is that job's
    # BYTEPS_TENANT_WEIGHT (or its own pacing), not a fleet-wide one.
    if rep.get("noisy_tenant") is not None:
        walls = rep.get("tenant_walls", {})
        total = sum(walls.values()) or 1.0
        share = walls.get(rep["noisy_tenant"], 0.0) / total
        rep["hints"].append(
            "tenant %s owns %.0f%% of the fleet round wall -> the "
            "noisy neighbor; rebalance BYTEPS_TENANT_WEIGHT or pace "
            "that job before touching fleet-wide knobs"
            % (rep["noisy_tenant"], share * 100))
    rep["local_only"] = local_only
    rep["workers"] = workers
    rep["rounds_seen"] = sorted(
        int(r) for r in summary.get("fleet_rounds", {}))
    return rep


# --- live CLI ---------------------------------------------------------------

def _fmt_us(us: float) -> str:
    return f"{us / 1e3:.2f}ms" if us >= 1000 else f"{us:.0f}us"


# Classification -> journal code (EV_INSIGHT a0; the catalog lives in
# csrc/events.h and docs/monitoring.md "Event catalog"). Stable wire
# values: append, never renumber.
STATE_CODES = {
    "healthy": 0, "wire-bound": 1, "sum-bound": 2,
    "straggler-skewed": 3, "retry-degraded": 4,
    "corruption-degraded": 5, "resizing": 6, "idle": 7,
}


def journal_state(endpoint: str, state: str, prev_state: str,
                  timeout: float = 2.0) -> bool:
    """Journal a classification FLIP onto the fleet event timeline
    (POST /events, type=insight, a0=new code, a1=old code) so a
    performance regression lands next to the lifecycle events that
    explain it in `monitor.incident`. Edge-triggered by the caller —
    posting every poll would bury the timeline. Best-effort: False
    (and no raise) when the endpoint is unreachable."""
    body = json.dumps({
        "type": "insight",
        "a0": STATE_CODES.get(state, -1),
        "a1": STATE_CODES.get(prev_state, -1),
    }).encode()
    req = urllib.request.Request(
        f"http://{endpoint}/events", data=body,
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status == 200
    except (OSError, ValueError):
        return False


def scrape_rounds(endpoint: str, timeout: float = 2.0) -> Optional[dict]:
    """Fetch one /rounds snapshot; None when unreachable."""
    try:
        with urllib.request.urlopen(f"http://{endpoint}/rounds",
                                    timeout=timeout) as r:
            return json.loads(r.read().decode())
    except (OSError, ValueError):
        return None


def print_round_line(round_no: int, recs: Dict[str, dict],
                     file=None) -> None:
    """One scrolling line per fleet round: wall, bottleneck, state."""
    out = file or sys.stdout
    fleet = merge_recs(list(recs.values()))
    dom, share = dominant_stage(fleet)
    rep = classify(recs)
    print(f"round {round_no:>6}  wall {_fmt_us(round_wall_us(fleet)):>9}  "
          f"bottleneck {dom}({share * 100:.0f}%)  "
          f"state {rep['state'].upper()}  "
          f"wire {int(fleet.get('wire_bytes', 0)) >> 10}K/"
          f"{int(fleet.get('wire_msgs', 0))}msg"
          + (f"  retries {int(fleet.get('retries', 0))}"
             if fleet.get("retries") else ""), file=out,
          flush=True)  # watch mode is tail/pipe-friendly


def print_report(rep: dict, file=None) -> None:
    out = file or sys.stdout
    print(f"fleet state: {rep['state'].upper()} "
          f"(bottleneck {rep['dominant']} "
          f"{rep['dominant_share'] * 100:.0f}% of round wall"
          + (", local ring only — scrape the scheduler for fleet view"
             if rep.get("local_only") else "") + ")", file=out)
    bd = stage_breakdown(rep["fleet"])
    print("  " + "  ".join(f"{s}={_fmt_us(bd[s])}" for s in STAGES),
          file=out)
    if rep["stragglers"]:
        print(f"  stragglers: {rep['stragglers']} "
              f"(baseline push {_fmt_us(rep['baseline_push_us'])}/part)",
              file=out)
    if rep["regressions"]:
        print(f"  regressions vs EWMA baseline: {rep['regressions']}",
              file=out)
    for h in rep["hints"]:
        print(f"  hint: {h}", file=out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m byteps_tpu.monitor.insight",
        description="live per-round bottleneck attribution from the "
                    "scheduler's fleet round table "
                    "(docs/monitoring.md 'Round insight')")
    p.add_argument("--endpoint", default="",
                   help="scheduler monitor endpoint host:port (default: "
                        "DMLC_PS_ROOT_URI:BYTEPS_MONITOR_PORT — the "
                        "scheduler is node 0, so the base port IS its "
                        "port)")
    p.add_argument("--watch", type=float, metavar="SECONDS", default=0,
                   help="poll every N seconds, printing one line per "
                        "newly completed fleet round")
    p.add_argument("--straggler-factor", type=float,
                   default=float(os.environ.get("BYTEPS_STRAGGLER_FACTOR",
                                                "2.0")))
    p.add_argument("--json", action="store_true",
                   help="machine-readable report (one JSON object per "
                        "poll)")
    p.add_argument("--window", type=int, default=1,
                   help="classify over each worker's last N completed "
                        "rounds instead of only the latest (stable "
                        "under scheduler-noise; default 1)")
    args = p.parse_args(argv)

    endpoint = args.endpoint or "%s:%s" % (
        os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"),
        os.environ.get("BYTEPS_MONITOR_PORT", "9100"))
    last_printed = -1
    last_state = None
    while True:
        summary = scrape_rounds(endpoint)
        if summary is None:
            print(f"endpoint {endpoint} unreachable — is the scheduler "
                  "running with BYTEPS_MONITOR_ON=1?", file=sys.stderr)
            if not args.watch:
                return 1
            time.sleep(args.watch)
            continue
        rep = analyze(summary, straggler_factor=args.straggler_factor,
                      window=args.window)
        # Journal flips only (ISSUE 20): the first poll seeds the edge
        # detector without posting, so attaching insight to a long-
        # degraded fleet doesn't misreport the attach as a transition.
        if last_state is not None and rep["state"] != last_state:
            journal_state(endpoint, rep["state"], last_state)
        last_state = rep["state"]
        if args.json:
            rep2 = dict(rep)
            print(json.dumps(rep2))
        elif args.watch:
            table = summary.get("fleet_rounds", {})
            for rnd in sorted(int(r) for r in table):
                if rnd > last_printed:
                    print_round_line(rnd, table[str(rnd)])
                    last_printed = rnd
        else:
            print_report(rep)
        if not args.watch:
            return 0 if rep["state"] == "healthy" else 2
        time.sleep(args.watch)


if __name__ == "__main__":
    sys.exit(main())
