"""Background-thread HTTP endpoint serving /metrics and /healthz.

Every role (worker, server, scheduler) starts one automatically when
``BYTEPS_MONITOR_ON=1``; the port is ``BYTEPS_MONITOR_PORT + node_id``
(scheduler 0, servers 1..S, workers S+1..S+W — postoffice.h id layout),
so one env var covers a co-located fleet and ``monitor.top`` can derive
every endpoint from the topology alone.

The endpoint must never take the job down: bind failures log a warning
and disable monitoring for this process; request handling errors return
500 to the scraper and nothing to the training loop.
"""

from __future__ import annotations

import http.server
import json
import threading
import time
from typing import Optional

from byteps_tpu.monitor import metrics as _metrics


class _Handler(http.server.BaseHTTPRequestHandler):
    server_version = "byteps-monitor/1.0"

    def do_GET(self):  # noqa: N802 (http.server API)
        try:
            if self.path.split("?")[0] == "/metrics":
                body = _metrics.prometheus_text().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                code = 200
            elif self.path.split("?")[0] == "/rounds":
                # Per-round introspection snapshot (ISSUE 7): this
                # rank's round ring; on the scheduler, also the fleet
                # round table + per-rank EWMA baselines ingested from
                # heartbeat summaries. `python -m
                # byteps_tpu.monitor.insight --watch` polls this.
                # Elastic membership context (ISSUE 8) rides along so
                # the insight classifier can call an epoch-change round
                # `resizing` instead of misreading it as skew.
                from byteps_tpu.core.ffi import round_summary
                doc = round_summary()
                gauges = _metrics.snapshot().get("gauges", {})
                doc["epoch"] = int(gauges.get("bps_membership_epoch", 0))
                doc["resizing"] = int(gauges.get("bps_fleet_resizing", 0))
                doc["fleet_workers"] = int(
                    gauges.get("bps_fleet_workers", 0))
                body = json.dumps(doc).encode()
                ctype = "application/json"
                code = 200
            elif self.path.split("?")[0] == "/tenants":
                # Multi-tenant snapshot (ISSUE 9): this role's tenant
                # identity, per-tenant accounting (servers: bytes /
                # ops / engine queue depth / DRR dispatch), and the
                # address-book roster. `starved` applies the
                # BYTEPS_TENANT_STARVE_MS threshold (default 2000) to
                # the raw starvation age the C side reports.
                import os as _os

                from byteps_tpu.core.ffi import tenant_summary
                doc = tenant_summary()
                starve_ms = float(
                    _os.environ.get("BYTEPS_TENANT_STARVE_MS", "2000")
                    or 2000)
                for st in (doc.get("stats", {}) or {}).values():
                    st["starved"] = (
                        st.get("starve_us", 0) / 1000.0 > starve_ms)
                body = json.dumps(doc).encode()
                ctype = "application/json"
                code = 200
            elif self.path.split("?")[0] == "/events":
                # Fleet event journal (ISSUE 20): this rank's local
                # lifecycle-event ring; on the scheduler, also the
                # clock-aligned fleet timeline and per-gauge history
                # rings. `python -m byteps_tpu.monitor.incident` reads
                # this to render a post-mortem; monitor.top's ticker
                # tails it.
                from byteps_tpu.core.ffi import events_summary
                body = json.dumps(events_summary()).encode()
                ctype = "application/json"
                code = 200
            elif self.path.split("?")[0] == "/healthz":
                snap = _metrics.snapshot()
                dead = snap.get("dead_nodes", [])
                node = snap.get("node", {})
                counters = snap.get("counters", {})
                gauges = snap.get("gauges", {})
                # RECOVERING covers both flavours: a server rank being
                # hot-replaced (bps_recovering), a restarted scheduler
                # collecting its re-registration quorum
                # (bps_sched_recovering), and a node parked on a lost
                # scheduler (bps_sched_lost).
                recovering = bool(gauges.get("bps_recovering", 0)
                                  or gauges.get("bps_sched_recovering", 0)
                                  or gauges.get("bps_sched_lost", 0))
                healthy = bool(node.get("inited")) and not dead
                # Fleet state: RECOVERING while a server rank is being
                # hot-replaced (healthy-but-paused, NOT degraded — the
                # scheduler is coordinating; 200 so orchestrators don't
                # kill a fleet that is saving itself).
                state = ("RECOVERING" if recovering
                         else "OK" if healthy else "DEGRADED")
                body = {
                    "status": "ok" if healthy else "degraded",
                    "state": state,
                    "inited": bool(node.get("inited")),
                    "role": node.get("role"),
                    "node_id": node.get("id"),
                    "dead_nodes": dead,
                    # Transient-fault telemetry (docs/troubleshooting.md
                    # failure model): a climbing retry/reconnect rate is
                    # the early-warning signal BEFORE a node goes dead.
                    "retries": int(counters.get("bps_retries_total", 0)),
                    "reconnects": int(
                        counters.get("bps_reconnects_total", 0)),
                    # Wire integrity (ISSUE 19): sequence-cursor frame
                    # accounting (gaps = frames lost between stamping
                    # and this receiver, dups = duplicate deliveries)
                    # plus the CRC data plane — failed verifications,
                    # quarantine trips (flaky-link force-re-dials), and
                    # the persistently-corrupting-link flag that
                    # precedes the named fail-stop.
                    "seq_gaps": int(
                        counters.get("bps_seq_gaps_total", 0)),
                    "seq_dups": int(
                        counters.get("bps_seq_dups_total", 0)),
                    "crc_fails": int(
                        counters.get("bps_crc_fail_total", 0)),
                    "crc_quarantines": int(
                        counters.get("bps_crc_quarantine_total", 0)),
                    "corrupting": bool(
                        gauges.get("bps_link_corrupting", 0)),
                    # Hot-replacement telemetry: completed recoveries and
                    # the fleet membership epoch (bumped per recovery).
                    "recoveries": int(
                        counters.get("bps_recoveries_total", 0)),
                    "epoch": int(gauges.get("bps_membership_epoch", 0)),
                    # Elastic membership (ISSUE 8): LIVE worker count
                    # (the node section tracks joins/leaves/shrinks)
                    # plus the scheduler's change-in-flight flag.
                    "workers": int(node.get("num_workers", 0)),
                    "resizing": bool(
                        gauges.get("bps_fleet_resizing", 0)),
                    "joins": int(
                        counters.get("bps_worker_joins_total", 0)),
                    "leaves": int(
                        counters.get("bps_worker_leaves_total", 0)),
                    # Scheduler fail-over (ISSUE 15): parked flag on
                    # every role; the restarted scheduler additionally
                    # reports its re-registration progress so an
                    # operator can see `reregistered/expected` converge
                    # toward quorum during the outage.
                    "sched_lost": bool(gauges.get("bps_sched_lost", 0)),
                    "sched_recovering": bool(
                        gauges.get("bps_sched_recovering", 0)),
                    "sched_recoveries": int(
                        counters.get("bps_sched_recoveries_total", 0)),
                    "reregistered": int(
                        gauges.get("bps_sched_rereg", 0)),
                    "expected": int(
                        gauges.get("bps_sched_rereg_expected", 0)),
                    # Versioned snapshot serving (ISSUE 16): the
                    # committed cut this node serves, its lag behind
                    # the primary (replicas; 0 on a primary), and read
                    # traffic. -1 snapshot_version = nothing committed
                    # yet (or serving disabled).
                    "snapshot_version": int(
                        gauges.get("bps_snapshot_version", -1)),
                    "replica_lag_rounds": int(
                        gauges.get("bps_replica_lag_rounds", 0)),
                    "snap_pulls": int(
                        counters.get("bps_snap_pulls_total", 0)),
                    "uptime_s": round(
                        time.monotonic() - self.server.started_at, 3),
                }
                if "bps_ckpt_version" in gauges:
                    # Durable checkpoints (ISSUE 18): only present when
                    # the writer is armed (BYTEPS_CKPT_DIR) — an unarmed
                    # fleet's health document stays byte-identical to
                    # the pre-checkpoint one. lag_rounds is the distance
                    # between the newest committed snapshot and the
                    # newest sealed spill: a climbing lag means the disk
                    # can't keep up and a crash now loses that many
                    # rounds.
                    body.update({
                        "ckpt_version": int(
                            gauges.get("bps_ckpt_version", -1)),
                        "ckpt_lag_rounds": int(
                            gauges.get("bps_ckpt_lag_rounds", 0)),
                        "ckpt_spills": int(
                            counters.get("bps_ckpt_spills_total", 0)),
                        "ckpt_failures": int(
                            counters.get("bps_ckpt_failures_total", 0)),
                        "ckpt_spill_ms": int(
                            gauges.get("bps_ckpt_spill_ms", 0)),
                    })
                body = json.dumps(body).encode()
                ctype = "application/json"
                code = 200 if healthy else 503
            else:
                body, ctype, code = b"not found\n", "text/plain", 404
        except Exception as e:  # scrape must not kill the job
            body = f"snapshot failed: {e}\n".encode()
            ctype, code = "text/plain", 500
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802 (http.server API)
        # POST /events: journal one event from outside the C hot paths
        # (insight posts its classification flips here so regressions
        # land on the same incident timeline as the lifecycle events
        # they explain). Body: {"type": name-or-code, "a0","a1","a2"}.
        try:
            if self.path.split("?")[0] != "/events":
                body, code = b"not found\n", 404
            else:
                n = int(self.headers.get("Content-Length", 0) or 0)
                doc = json.loads(self.rfile.read(n).decode() or "{}")
                from byteps_tpu.core.ffi import events_emit
                events_emit(doc["type"], int(doc.get("a0", 0)),
                            int(doc.get("a1", 0)), int(doc.get("a2", 0)))
                body, code = b"ok\n", 200
        except Exception as e:  # a bad post must not kill the job
            body, code = f"event rejected: {e}\n".encode(), 400
        self.send_response(code)
        self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr noise
        pass


class MonitorServer:
    """ThreadingHTTPServer on a daemon thread; stop() joins it."""

    def __init__(self, port: int, host: str = "0.0.0.0"):
        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                     _Handler)
        self._httpd.daemon_threads = True
        self._httpd.started_at = time.monotonic()
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="bps-monitor",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def maybe_start_monitor(node_id: int) -> Optional[MonitorServer]:
    """Start the endpoint for this node iff BYTEPS_MONITOR_ON; returns
    None (monitoring off or port taken) otherwise. Never raises — the
    monitor is best-effort by contract."""
    import logging

    from byteps_tpu.config import load_config

    try:
        cfg = load_config()
        if not cfg.monitor_on:
            return None
        srv = MonitorServer(cfg.monitor_port + node_id)
        logging.getLogger("byteps_tpu.monitor").info(
            "monitor endpoint on :%d (/metrics, /healthz, /events)",
            srv.port)
        return srv
    except Exception as e:
        logging.getLogger("byteps_tpu.monitor").warning(
            "monitor endpoint disabled: %s", e)
        return None
