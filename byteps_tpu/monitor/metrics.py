"""Metric snapshot + Prometheus text exposition.

Two registries feed one exposition:

- the C core's lock-free registry (per-stage counters / gauges /
  fixed-bucket latency histograms, instrumented in worker.cc, server.cc,
  van.cc — see csrc/metrics.h), read in one call via
  ``bps_metrics_snapshot`` together with the live node state that used
  to be three ad-hoc C APIs (van wire bytes, async staleness, scheduler
  dead nodes) and the scheduled-queue occupancy;
- a small Python-side registry (``set_gauge`` / ``inc_counter`` /
  ``observe_histo``) for step-level metrics recorded by training
  callbacks — kept in Python so float values (examples/sec) survive and
  so the monitor endpoint still serves when the C core is idle.

Exposition follows the Prometheus text format (v0.0.4): counters end in
``_total``, histograms expose cumulative ``_bucket{le=...}`` series plus
``_sum``/``_count``. Durations are microseconds, carried in the metric
name (``*_us``) rather than rescaled — operators grep the same unit the
timeline shows.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

_ROLE_NAMES = {0: "scheduler", 1: "server", 2: "worker", 3: "replica"}

_py_lock = threading.Lock()
_py_counters: Dict[str, float] = {}
_py_gauges: Dict[str, float] = {}
_py_histos: Dict[str, Dict[str, float]] = {}  # name -> {sum, count}


def inc_counter(name: str, delta: float = 1.0) -> None:
    with _py_lock:
        _py_counters[name] = _py_counters.get(name, 0.0) + delta


def set_gauge(name: str, value: float) -> None:
    with _py_lock:
        _py_gauges[name] = float(value)


def observe_histo(name: str, value: float) -> None:
    """Python-side sum/count observation (no buckets — bucketed latency
    histograms live in the C registry; use ffi.metrics_observe for
    those)."""
    with _py_lock:
        h = _py_histos.setdefault(name, {"sum": 0.0, "count": 0.0})
        h["sum"] += float(value)
        h["count"] += 1.0


def snapshot() -> dict:
    """Combined telemetry snapshot: the C core's registry + node state,
    with the Python-side registry merged under ``py_counters`` /
    ``py_gauges`` / ``py_histograms``."""
    from byteps_tpu.core.ffi import metrics_snapshot
    snap = metrics_snapshot()
    with _py_lock:
        snap["py_counters"] = dict(_py_counters)
        snap["py_gauges"] = dict(_py_gauges)
        snap["py_histograms"] = {k: dict(v) for k, v in _py_histos.items()}
    return snap


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def prometheus_text(snap: Optional[dict] = None) -> str:
    """Render a snapshot dict as Prometheus text exposition."""
    if snap is None:
        snap = snapshot()
    lines: List[str] = []

    def scalar(name: str, kind: str, value, labels: str = "") -> None:
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name}{labels} {_fmt(value)}")

    node = snap.get("node", {})
    role = _ROLE_NAMES.get(node.get("role", -1), "none")
    scalar("bps_up", "gauge", 1 if node.get("inited") else 0,
           f'{{role="{role}",node_id="{node.get("id", -1)}"}}')

    for name, v in sorted(snap.get("counters", {}).items()):
        scalar(name, "counter", v)
    for name, v in sorted(snap.get("gauges", {}).items()):
        scalar(name, "gauge", v)
    for name, h in sorted(snap.get("histograms", {}).items()):
        lines.append(f"# TYPE {name} histogram")
        cum = 0
        for bound, count in zip(h["bounds_us"], h["buckets"]):
            cum += count
            lines.append(f'{name}_bucket{{le="{bound}"}} {cum}')
        cum += h["buckets"][-1]
        lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{name}_sum {h['sum']}")
        lines.append(f"{name}_count {h['count']}")

    van = snap.get("van", {})
    scalar("bps_van_sent_bytes_total", "counter", van.get("sent_bytes", 0))
    scalar("bps_van_recv_bytes_total", "counter", van.get("recv_bytes", 0))

    stale = snap.get("staleness", {})
    scalar("bps_async_staleness_mean", "gauge", stale.get("mean", 0))
    scalar("bps_async_staleness_max", "gauge", stale.get("max", 0))
    scalar("bps_async_staleness_samples", "gauge", stale.get("samples", 0))

    queue = snap.get("queue", {})
    scalar("bps_queue_pending", "gauge", queue.get("pending", 0))
    scalar("bps_queue_inflight_bytes", "gauge",
           queue.get("inflight_bytes", 0))
    scalar("bps_queue_credit_budget_bytes", "gauge",
           queue.get("credit_budget_bytes", 0))

    # Multi-tenant series (ISSUE 9): one labeled sample per tenant from
    # the accounting registry + the address-book roster (scheduler).
    tenants = snap.get("tenants", {}) or {}
    stats = tenants.get("stats", {}) or {}
    if stats:
        for metric, kind in (("bps_tenant_push_bytes_total", "counter"),
                             ("bps_tenant_reply_bytes_total", "counter"),
                             ("bps_tenant_ops_total", "counter"),
                             ("bps_tenant_sum_us_total", "counter"),
                             ("bps_tenant_dispatched_total", "counter"),
                             ("bps_tenant_queue_depth", "gauge"),
                             ("bps_tenant_starve_us", "gauge")):
            field = metric.replace("bps_tenant_", "").replace("_total",
                                                              "")
            field = {"push_bytes": "push_bytes",
                     "reply_bytes": "reply_bytes", "ops": "ops",
                     "sum_us": "sum_us", "dispatched": "dispatched",
                     "queue_depth": "queue_depth",
                     "starve_us": "starve_us"}[field]
            lines.append(f"# TYPE {metric} {kind}")
            for tid in sorted(stats, key=int):
                lines.append(
                    f'{metric}{{tenant="{tid}"}} '
                    f'{_fmt(stats[tid].get(field, 0))}')
    roster = tenants.get("roster", {}) or {}
    if roster:
        for metric, field in (("bps_tenant_workers", "workers"),
                              ("bps_tenant_weight", "weight")):
            lines.append(f"# TYPE {metric} gauge")
            for tid in sorted(roster, key=int):
                lines.append(f'{metric}{{tenant="{tid}"}} '
                             f'{_fmt(roster[tid].get(field, 0))}')

    ages = snap.get("heartbeat_age_ms", {})
    if ages:
        lines.append("# TYPE bps_heartbeat_age_ms gauge")
        for nid, age in sorted(ages.items(), key=lambda kv: int(kv[0])):
            lines.append(f'bps_heartbeat_age_ms{{node="{nid}"}} {_fmt(age)}')
    dead = snap.get("dead_nodes", [])
    scalar("bps_dead_nodes", "gauge", len(dead))
    if dead:
        lines.append("# TYPE bps_node_dead gauge")
        for nid in dead:
            lines.append(f'bps_node_dead{{node="{nid}"}} 1')

    for name, v in sorted(snap.get("py_counters", {}).items()):
        scalar(name, "counter", v)
    for name, v in sorted(snap.get("py_gauges", {}).items()):
        scalar(name, "gauge", v)
    for name, h in sorted(snap.get("py_histograms", {}).items()):
        scalar(f"{name}_sum", "gauge", h["sum"])
        scalar(f"{name}_count", "gauge", h["count"])

    return "\n".join(lines) + "\n"


def parse_prometheus(text: str
                     ) -> Dict[str, Dict[Tuple[Tuple[str, str], ...], float]]:
    """Parse Prometheus text exposition into
    ``{metric: {((label, value), ...): sample}}`` (empty tuple for
    unlabelled samples). Strict about line shape — the monitor tests use
    this as the 'Prometheus-parseable' oracle; a malformed line raises."""
    out: Dict[str, Dict[Tuple[Tuple[str, str], ...], float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            raise ValueError(f"malformed exposition line: {line!r}")
        value = float(value_part)  # raises on garbage
        labels: Tuple[Tuple[str, str], ...] = ()
        name = name_part
        if "{" in name_part:
            if not name_part.endswith("}"):
                raise ValueError(f"malformed labels: {line!r}")
            name, _, lbl = name_part[:-1].partition("{")
            pairs = []
            for item in lbl.split(","):
                k, _, v = item.partition("=")
                if not (v.startswith('"') and v.endswith('"')):
                    raise ValueError(f"malformed label value: {line!r}")
                pairs.append((k, v[1:-1]))
            labels = tuple(pairs)
        if not name or not name[0].isalpha():
            raise ValueError(f"malformed metric name: {line!r}")
        out.setdefault(name, {})[labels] = value
    return out
