"""byteps_tpu.torch — PyTorch framework plugin (Horovod-compatible API).

Capability parity with the reference's byteps/torch plugin (SURVEY.md §2.5
and §3.3): ``init`` / ``shutdown`` / ``rank`` / ``size`` / ``local_rank`` /
``local_size``, ``push_pull`` (+ ``_async`` / ``_inplace`` variants),
``poll`` / ``synchronize`` / ``declare``, ``DistributedOptimizer`` with
per-parameter gradient hooks (communication overlaps the remaining
backward compute, reference: byteps/torch/__init__.py _make_hook),
``broadcast_parameters`` and ``broadcast_optimizer_state``.

Transport: the byteps_tpu C++ core (TCP van → CPU-summation parameter
servers). CPU torch tensors share memory with numpy views, so the C side
reads and writes the tensor's own buffer — the same zero-copy contract the
reference gets from ZPush/ZPull over shared memory (byteps/torch/ops.cc
DoPushPull → EnqueueTensor).

Single-process mode (no scheduler configured): all collective calls degrade
to local no-ops so scripts run unmodified, matching the reference's
non-distributed fallback.
"""

from __future__ import annotations

import io
import threading
from typing import Any, Iterator, Optional, Tuple

import numpy as np
import torch

from byteps_tpu.config import Config, get_config
from byteps_tpu.torch.compression import Compression

__all__ = [
    "init", "shutdown", "initialized", "rank", "size", "local_rank",
    "local_size", "declare", "push_pull", "push_pull_async",
    "push_pull_inplace_", "push_pull_async_inplace_", "poll", "synchronize",
    "broadcast_parameters", "broadcast_optimizer_state",
    "DistributedOptimizer", "Compression",
]

_lock = threading.Lock()
_client = None            # core.ffi.Worker in distributed mode
_cfg: Optional[Config] = None
_initialized = False
_declared = {}            # name -> (tensor_id, nelem, dtype_name)

# torch dtype -> numpy dtype accepted by the C core reducer.
_TORCH_TO_NP = {
    torch.float32: np.float32,
    torch.float64: np.float64,
    torch.float16: np.float16,
    torch.int32: np.int32,
    torch.int64: np.int64,
    torch.uint8: np.uint8,
    torch.int8: np.int8,
}


def init(config: Optional[Config] = None) -> None:
    """Initialise the plugin (reference: bps.init() → byteps_init)."""
    global _client, _cfg, _initialized
    with _lock:
        if _initialized:
            return
        _cfg = config or get_config(reload=True)
        if _cfg.distributed:
            from byteps_tpu.core import ffi as _ffi
            _client = _ffi.Worker.start(_cfg)
        _initialized = True


def shutdown() -> None:
    """Tear down (reference: byteps_shutdown)."""
    global _client, _initialized, _noname_seq
    with _lock:
        if _client is not None:
            _client.shutdown()
            _client = None
        _declared.clear()
        _noname_seq = 0
        _initialized = False


def initialized() -> bool:
    return _initialized


def _require_init() -> None:
    if not _initialized:
        raise RuntimeError("byteps_tpu.torch.init() has not been called")


def rank() -> int:
    """This worker process's rank in [0, size())."""
    _require_init()
    return _client.worker_rank() if _client is not None else 0


def size() -> int:
    """Number of worker processes (the gradient-averaging denominator)."""
    _require_init()
    return _client.num_workers() if _client is not None else 1


def local_rank() -> int:
    _require_init()
    return _cfg.local_rank


def local_size() -> int:
    _require_init()
    return _cfg.local_size


# --- tensor plumbing --------------------------------------------------------

def _np_view(tensor: torch.Tensor) -> np.ndarray:
    """Zero-copy flat numpy view over a contiguous CPU tensor's storage."""
    if tensor.device.type != "cpu":
        raise ValueError(
            "byteps_tpu.torch drives CPU tensors; move to CPU first "
            f"(got device {tensor.device})")
    if tensor.dtype not in _TORCH_TO_NP:
        raise ValueError(f"unsupported dtype {tensor.dtype}; cast to one of "
                         f"{sorted(str(k) for k in _TORCH_TO_NP)}")
    t = tensor.detach()
    if not t.is_contiguous():
        raise ValueError("in-place communication needs a contiguous tensor")
    return t.view(-1).numpy()


def declare(name: str, tensor: torch.Tensor,
            compression_config: Optional[str] = None) -> int:
    """Pre-register a tensor (reference: byteps_declare_tensor).
    Declaration order fixes the communication priority: earlier-declared
    tensors (front-of-model) are pushed first."""
    _require_init()
    if _client is None:
        return -1
    key = name
    cached = _declared.get(key)
    nelem = tensor.numel()
    dt = np.dtype(_TORCH_TO_NP[tensor.dtype]).name
    if cached is not None:
        tid, n0, d0 = cached
        if (n0, d0) != (nelem, dt):
            raise ValueError(f"tensor {name!r} re-declared with different "
                             f"shape/dtype ({n0},{d0}) vs ({nelem},{dt})")
        return tid
    tid = _client.declare(key, nelem, dt, compression=compression_config)
    _declared[key] = (tid, nelem, dt)
    return tid


class Handle:
    """An in-flight push_pull (reference: handle_manager.cc handles)."""

    __slots__ = ("_core", "_wire", "_out", "_ctx", "_compression", "_done")

    def __init__(self, core_handle, wire_tensor, out_tensor, ctx,
                 compression):
        self._core = core_handle
        self._wire = wire_tensor
        self._out = out_tensor
        self._ctx = ctx
        self._compression = compression
        self._done = core_handle is None

    def _finish(self) -> torch.Tensor:
        if not self._done:
            if self._core is not None and _client is not None:
                _client.wait(self._core)
            self._done = True
            result = self._compression.decompress(self._wire, self._ctx)
            if result.data_ptr() != self._out.data_ptr():
                self._out.copy_(result.view_as(self._out))
        return self._out


_noname_seq = 0


def _auto_name(tensor: torch.Tensor) -> str:
    """Per-call sequential fallback name (reference/Horovod:
    allreduce.noname.N). Correct because all ranks issue unnamed calls in
    lockstep order; for tensors communicated repeatedly (training loops),
    pass an explicit ``name`` so the key table stays bounded."""
    global _noname_seq
    name = f"byteps_tpu.noname.{_noname_seq}"
    _noname_seq += 1
    return name


def push_pull_async_inplace_(tensor: torch.Tensor, average: bool = True,
                             name: Optional[str] = None,
                             compression=Compression.none) -> Handle:
    """Start a push_pull that sums ``tensor`` across workers IN PLACE.
    Returns a Handle for poll/synchronize. The hot path for gradients."""
    _require_init()
    if _client is None:
        return Handle(None, tensor, tensor, None, Compression.none)
    nm = name or _auto_name(tensor)
    wire, ctx = compression.compress(tensor)
    if wire.data_ptr() == tensor.data_ptr():
        wire = tensor
    wire = wire.contiguous()
    tid = declare(nm, wire)
    arr = _np_view(wire)
    h = _client.push_pull(tid, arr, average=average,
                          async_mode=_cfg.enable_async)
    return Handle(h, wire, tensor, ctx, compression)


def push_pull_async(tensor: torch.Tensor, average: bool = True,
                    name: Optional[str] = None,
                    compression=Compression.none) -> Handle:
    """Like push_pull_async_inplace_ but leaves the input untouched and
    resolves to a fresh result tensor."""
    out = tensor.clone()
    return push_pull_async_inplace_(out, average=average,
                                    name=name or _auto_name(tensor),
                                    compression=compression)


def push_pull(tensor: torch.Tensor, average: bool = True,
              name: Optional[str] = None,
              compression=Compression.none) -> torch.Tensor:
    """Blocking sum (or average) across all workers; returns the result
    (input unchanged). Reference: byteps.torch.push_pull."""
    return synchronize(push_pull_async(tensor, average=average, name=name,
                                       compression=compression))


def push_pull_inplace_(tensor: torch.Tensor, average: bool = True,
                       name: Optional[str] = None,
                       compression=Compression.none) -> torch.Tensor:
    """Blocking in-place variant (reference: byteps.torch.push_pull_)."""
    return synchronize(push_pull_async_inplace_(
        tensor, average=average, name=name, compression=compression))


def poll(handle: Handle) -> bool:
    """True iff the handle's communication has completed (reference:
    byteps_torch_poll). Raises RuntimeError if the operation FAILED
    (dead peer) — poll never reports a failed handle as success."""
    if handle._done or handle._core is None or _client is None:
        return True
    return bool(_client.poll(handle._core))


def synchronize(handle: Handle) -> torch.Tensor:
    """Block until done; returns the reduced tensor."""
    return handle._finish()


# --- broadcast --------------------------------------------------------------

def _named_tensors(params: Any) -> Iterator[Tuple[str, torch.Tensor]]:
    if isinstance(params, dict):
        yield from sorted(params.items())
    else:
        for i, item in enumerate(params):
            if isinstance(item, tuple) and len(item) == 2:
                yield item
            else:
                yield (str(i), item)


def broadcast_parameters(params: Any, root_rank: int = 0) -> None:
    """Sync parameters from ``root_rank`` to all workers, in place
    (reference: broadcast_parameters, SURVEY.md §3.4). ``params`` is a
    state_dict or an iterable of (name, tensor) — e.g.
    ``model.named_parameters()``."""
    _require_init()
    if _client is None:
        return
    handles = []
    for name, t in _named_tensors(params):
        if t is None or not isinstance(t, torch.Tensor):
            continue
        if not t.is_contiguous():
            t.data = t.data.contiguous()
        tid = declare(f"bcast.{name}", t)
        arr = _np_view(t)
        handles.append(_client.broadcast(tid, arr, root_rank=root_rank))
    for h in handles:
        _client.wait(h)


def _pickle_bytes(obj: Any) -> bytes:
    buf = io.BytesIO()
    torch.save(obj, buf)
    return buf.getvalue()


def _broadcast_blob(name: str, payload: bytes, root_rank: int) -> bytes:
    """Broadcast an arbitrary byte string from root (length first, then a
    padded uint8 buffer) — used for non-tensor optimizer hyperparams, the
    equivalent of the reference's scalar-wrapping in
    broadcast_optimizer_state."""
    ln = torch.tensor([len(payload)], dtype=torch.int64)
    tid = _client.declare(f"blob_len.{name}", 1, "int64")
    arr = _np_view(ln)
    _client.wait(_client.broadcast(tid, arr, root_rank=root_rank))
    n = int(ln.item())
    buf = torch.zeros(n, dtype=torch.uint8)
    if _client.worker_rank() == root_rank:
        buf.copy_(torch.frombuffer(bytearray(payload), dtype=torch.uint8))
    tid2 = _client.declare(f"blob.{name}.{n}", n, "uint8")
    arr2 = _np_view(buf)
    _client.wait(_client.broadcast(tid2, arr2, root_rank=root_rank))
    return bytes(arr2.tobytes())


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0) -> None:
    """Sync optimizer state from ``root_rank`` (reference:
    broadcast_optimizer_state). Tensor state (momentum buffers, etc.) is
    broadcast in place; scalar state and param_group hyperparameters travel
    as a pickled blob."""
    _require_init()
    if _client is None:
        return
    # Materialize state on ranks that have not stepped yet (momentum
    # buffers etc. only exist after the first step): a zero-gradient step
    # creates them without changing parameters — the reference does the
    # same before broadcasting.
    if len(optimizer.state_dict()["state"]) == 0:
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad and p.grad is None:
                    p.grad = torch.zeros_like(p.data)
                elif p.grad is not None:
                    p.grad.zero_()
        optimizer.step()
    state = optimizer.state_dict()
    # Guard against per-rank state asymmetry (would otherwise deadlock in
    # wait): every rank must hold the same (param-id, key) tensor set.
    local_keys = sorted(
        (str(pid), str(k), tuple(v.shape))
        for pid in state["state"] for k, v in state["state"][pid].items()
        if isinstance(v, torch.Tensor) and v.numel() > 0)
    root_keys = torch.load(io.BytesIO(_broadcast_blob(
        "opt_keys", _pickle_bytes(local_keys), root_rank)),
        weights_only=False)
    if local_keys != root_keys:
        raise RuntimeError(
            "broadcast_optimizer_state: optimizer state keys differ from "
            f"root rank's ({len(local_keys)} local vs {len(root_keys)} "
            "root entries); step all ranks the same number of times "
            "before broadcasting")
    # 1) tensors in .state, in deterministic (param-id, key) order
    handles = []
    scalars = {}
    for pid in sorted(state["state"], key=str):
        for k in sorted(state["state"][pid], key=str):
            v = state["state"][pid][k]
            if isinstance(v, torch.Tensor) and v.numel() > 0:
                if not v.is_contiguous():
                    state["state"][pid][k] = v = v.contiguous()
                tid = declare(f"opt.{pid}.{k}", v)
                handles.append(_client.broadcast(tid, _np_view(v),
                                                 root_rank=root_rank))
            else:
                scalars[(str(pid), str(k))] = v
    for h in handles:
        _client.wait(h)
    # 2) scalars + param_groups via pickled blob from root
    blob = io.BytesIO()
    torch.save({"scalars": scalars, "param_groups": state["param_groups"]},
               blob)
    data = _broadcast_blob("optimizer_state", blob.getvalue(), root_rank)
    loaded = torch.load(io.BytesIO(data), weights_only=False)
    for (pid, k), v in loaded["scalars"].items():
        for real_pid in state["state"]:
            if str(real_pid) == pid:
                state["state"][real_pid][k] = v
    state["param_groups"] = loaded["param_groups"]
    optimizer.load_state_dict(state)


# --- DistributedOptimizer ---------------------------------------------------

class _DistributedOptimizer(torch.optim.Optimizer):
    """Wraps a torch optimizer: per-parameter hooks launch push_pull the
    moment each gradient is accumulated (overlapping communication with the
    rest of backward), and ``step()`` waits for all of them before applying
    updates. Reference: byteps/torch/__init__.py (_make_hook / step)."""

    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self._bpps = backward_passes_per_step
        self._handles = {}
        self._grad_accs = []
        self._passes = {}

        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = [(f"param.{i}.{j}", p)
                     for i, g in enumerate(self.param_groups)
                     for j, p in enumerate(g["params"])]
        if len({n for n, _ in named}) != len(named):
            raise ValueError("DistributedOptimizer needs unique parameter "
                             "names (pass model.named_parameters())")
        self._param_names = {p: n for n, p in named}

        if size() > 1:
            self._register_hooks()

    def _register_hooks(self) -> None:
        if not hasattr(torch.Tensor, "register_post_accumulate_grad_hook"):
            raise RuntimeError(
                "byteps_tpu.torch.DistributedOptimizer needs torch >= 2.1 "
                f"(register_post_accumulate_grad_hook); found "
                f"{torch.__version__}")
        for group in self.param_groups:
            for p in group["params"]:
                if p.requires_grad:
                    self._passes[p] = 0
                    p.register_post_accumulate_grad_hook(self._make_hook())

    def _make_hook(self):
        def hook(p: torch.Tensor) -> None:
            if p in self._handles:
                # The previous push_pull is still writing into p.grad;
                # accumulating now would race with the comm thread.
                raise RuntimeError(
                    "Gradient for a parameter was computed more than "
                    "backward_passes_per_step times without an optimizer "
                    "step; raise backward_passes_per_step for gradient "
                    "accumulation")
            self._passes[p] += 1
            if self._passes[p] < self._bpps:
                return
            self._passes[p] = 0
            name = f"grad.{self._param_names.get(p, id(p))}"
            if self._bpps > 1:
                p.grad.div_(self._bpps)
            self._handles[p] = push_pull_async_inplace_(
                p.grad, average=True, name=name,
                compression=self._compression)
        return hook

    def synchronize(self) -> None:
        """Wait for every in-flight gradient push_pull."""
        for p, h in list(self._handles.items()):
            synchronize(h)
        self._handles.clear()

    def step(self, closure=None):
        if size() > 1:
            # Parameters whose hook never fired this step (e.g. frozen
            # branches) simply have no handle; that matches the reference.
            self.synchronize()
        return super(self.__class__, self).step(closure)


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step: int = 1):
    """Wrap ``optimizer`` for data-parallel training (reference API:
    bps.DistributedOptimizer(optimizer, named_parameters=...,
    compression=..., backward_passes_per_step=...)).

    Returns an object of a dynamically created class inheriting from
    ``optimizer``'s class with communication-aware ``step`` — the same
    class-surgery contract as the reference, so isinstance checks and LR
    schedulers keep working.
    """
    if backward_passes_per_step < 1:
        raise ValueError("backward_passes_per_step must be >= 1")
    _require_init()
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step)
