"""Wire-level gradient compression for the torch plugin.

Capability parity: reference byteps/torch/compression.py (SURVEY.md §2.5) —
the Horovod-compatible ``Compression`` namespace: ``none`` and ``fp16``,
applied to each tensor before communication and undone after.
"""

from __future__ import annotations

import torch


class NoneCompressor:
    """No-op compression (reference: Compression.none)."""

    @staticmethod
    def compress(tensor: torch.Tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        return tensor


class FP16Compressor:
    """Cast to float16 for the wire, cast back after (reference:
    Compression.fp16). Halves DCN bytes; the server sums in fp16."""

    @staticmethod
    def compress(tensor: torch.Tensor):
        if tensor.dtype in (torch.float32, torch.float64):
            return tensor.half(), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor: torch.Tensor, ctx):
        if ctx is not None:
            return tensor.to(ctx)
        return tensor


class Compression:
    """Namespace of wire compressors (Horovod-compatible)."""

    none = NoneCompressor
    fp16 = FP16Compressor
